# Convenience targets; CI / the driver call the underlying commands directly.

.PHONY: test quick bench csrc clean lint shard-report plan-report tune-overlap ckpt-bench pod-report monitor profile-report elastic-drill fleet-drill postmortem-drill serve-drill tenancy-drill hub-drill serve-report memory-report trend-report

csrc:
	$(MAKE) -C tpu_dist/csrc

test:
	python -m pytest tests/ -x -q

# Static lint (TD0xx) + jaxpr audit (TD1xx) against the checked-in baseline;
# non-zero exit on any new violation (docs/analysis.md)
lint:
	python -m tpu_dist.analysis --format json

# Layer 3 — the static HLO sharding & collective audit: lower+compile
# every config family, parse the OPTIMIZED HLO (what GSPMD actually
# emitted), gate TD116/TD117 (incl. the injected bad-in_shardings probe
# that must be caught), and write the schema-pinned shard_report.json the
# --auto_shard planner reads (docs/shard_report.md):
#   make shard-report [OUT=shard_report.json]
shard-report:
	python -m tpu_dist.analysis shard --inject-reshard --out $(or $(OUT),shard_report.json)

# Layer 4 — the sharding planner: enumerate + price the config-family
# space (calibrated roofline over the HLO-verified wire bytes), refuse
# over-budget candidates through the typed HBM path, rank, verify the
# chosen plan against a fresh compile (TD118 — incl. the injected
# miscost probe that must be caught, exit 2 if the detector went dead),
# and write the schema-pinned plan_report.json the trainer's
# --auto_shard consumes (docs/planner.md):
#   make plan-report [OUT=plan_report.json]
plan-report:
	python -m tpu_dist.analysis plan --inject-miscost --out $(or $(OUT),plan_report.json)

# Layer 5 — the comm/compute overlap autotuner: compile every knob
# candidate per config family, require payload-byte identity while the
# HLO collective schedule actually moves (TD121 — incl. the injected
# payload-perturbed probe that must be caught, exit 2 if the detector
# went dead), and write the schema-pinned tune_report.json that
# `plan --tune-report` and the trainer's `--tune_report` consume
# (docs/analysis.md "Layer 5"):
#   make tune-overlap [OUT=tune_report.json]
tune-overlap:
	python -m tpu_dist.analysis tune-overlap --inject-payload --out $(or $(OUT),tune_report.json)

# The async-checkpoint cost proof: measure step-loop blocking per
# sharded save for the synchronous barrier path vs the
# snapshot-then-write background path on the same model, print the
# ratio (acceptance floor: >=5x less blocking), and keep the TD120
# injected-EIO probe honest — a probe that comes back clean is a dead
# detector: exit 2 (docs/checkpointing.md "The cost, measured"):
#   make ckpt-bench
ckpt-bench:
	python bench.py --ckpt sweep --config resnet18_cifar100_fp32 --batch_size 64 --warmup 1

# <5-min cross-component slice (see tests/conftest.py for the curated set)
quick:
	python -m pytest tests/ -m quick -q

bench:
	python bench.py

# Cross-host pod report over per-host --log_file histories:
#   make pod-report LOGS="run.jsonl run.jsonl.h1" [TRACE=pod_trace.json]
# (docs/observability.md — per-host goodput ledgers, skew attribution,
# and optionally one merged Perfetto timeline)
pod-report:
	python -m tpu_dist.obs pod $(LOGS) $(if $(TRACE),--trace-out $(TRACE))

# Device-time attribution of a jax.profiler capture:
#   make profile-report CAPTURE=prof_dir/capture_0_s12_anomaly [TOP=10]
# (docs/observability.md "Trace analytics" — per-category device seconds,
# collectives by kind, comm/compute overlap, top ops)
profile-report:
	python -m tpu_dist.obs xprof $(CAPTURE) $(if $(TOP),--top $(TOP))

# The elastic proof, locally: preempt an 8-device ZeRO-1 run at step k
# (deterministic sigterm fault), resume at 4 devices (checkpoint remapped
# onto the new dp extent), assert the continued loss trajectory matches
# the uninterrupted golden run (docs/resilience.md "Elastic training"):
#   make elastic-drill [WORKDIR=/tmp/elastic_drill]
elastic-drill:
	python -m tpu_dist.elastic.drill --workdir $(or $(WORKDIR),/tmp/elastic_drill)

# The scale-up + fleet proof, locally: preempt an 8-device run (census
# caps the relaunch at 4), return the chips (the probe grows it back to
# 8 with golden-tolerance loss parity), then a 2-run arbitration — the
# scheduler scrapes real OpenMetrics textfiles and moves chips from the
# stalled run to the compute-bound one through the live supervised
# launchers (docs/resilience.md "Scale-up & fleet scheduling"):
#   make fleet-drill [WORKDIR=/tmp/fleet_drill] [PHASE=all|grow|fleet]
fleet-drill:
	python -m tpu_dist.fleet.drill --workdir $(or $(WORKDIR),/tmp/fleet_drill) --phase $(or $(PHASE),all)

# The crash-forensics proof, locally: a real run deliberately wedged at
# a step (deterministic hang fault), the launcher watchdog detects the
# frozen heartbeat, SIGUSR1s the rank for an all-threads stack dump
# (naming the hang site), escalates SIGTERM->SIGKILL, and auto-assembles
# the postmortem bundle — whose decoded flight ring must end exactly at
# the wedged step (docs/observability.md "Crash forensics"):
#   make postmortem-drill [WORKDIR=/tmp/postmortem_drill]
postmortem-drill:
	python -m tpu_dist.obs.drill --workdir $(or $(WORKDIR),/tmp/postmortem_drill)

# The serving proof, locally: deterministic request-trace replay through
# the continuous-batching engine — checkpoint loaded through the elastic
# Remapper, zero post-warmup retraces (CompileWatcher), histogram
# sum==count invariants, and the `obs compare --slo` exit contract (an
# injected latency regression exits 1, an improvement exits 0)
# (docs/serving.md):
#   make serve-drill [WORKDIR=/tmp/serve_drill]
serve-drill:
	python -m tpu_dist.serve drill --workdir $(or $(WORKDIR),/tmp/serve_drill)

# The co-scheduling proof, locally: one scheduler arbitrates a real
# training run and a supervised serving replica on the same chip budget
# through a deterministic diurnal cycle — a traffic spike breaches the
# serving SLO, training is preempted within the bounded tick count
# (SIGTERM -> emergency save -> exit 75 -> elastic relaunch on fewer
# chips, golden-loss parity), availability recovers, and off-peak the
# trainer reclaims the chips; the replica phase SIGKILLs the serving
# process and proves crash detection, postmortem bundling, and a
# bit-exact relaunch; chip-second conservation is audited exactly
# (docs/resilience.md "Multi-tenant pod"):
#   make tenancy-drill [WORKDIR=/tmp/tenancy_drill] [PHASE=all|policy|cycle|replica]
tenancy-drill:
	python -m tpu_dist.fleet.tenancy_drill --workdir $(or $(WORKDIR),/tmp/tenancy_drill) --phase $(or $(PHASE),all)

# The pod telemetry plane proof (docs/observability.md "Pod telemetry
# hub"): the diurnal replay arbitrated off ONE TelemetryHub fan-in
# (federated page round-trips with per-run labels + pod rollups), then
# the real-trainer cycle asserting the full causal chain — one
# decision_id spanning scheduler ledger -> allocation file/relaunch
# env -> resume record -> donor flight ring -> hub exposition, with
# the serve-preempt gap charged to preempt_for_serve_s and the goodput
# bucket partition exact:
#   make hub-drill [WORKDIR=/tmp/hub_drill]
hub-drill:
	python -m tpu_dist.fleet.tenancy_drill --workdir $(or $(WORKDIR),/tmp/hub_drill) --phase hub

# Offline serving SLO report over a run's serve records:
#   make serve-report LOG=serve.jsonl
# (docs/serving.md — per-window requests/s, latency p50/p99 bounds,
# availability, occupancy, fired SLO alerts)
serve-report:
	python -m tpu_dist.serve report $(LOG)

# Offline HBM report over a run's memory records + mem.* gauge series:
#   make memory-report LOG=run.jsonl
# (docs/observability.md "HBM ledger & OOM forensics" — the per-leaf
# static ledger, the memory_analysis waterfall, the census/allocator
# reconciliation, OOM events, and the peak-HBM compare-gate scalar)
memory-report:
	python -m tpu_dist.obs memory $(LOG)

# The longitudinal-archive proof (docs/observability.md "Longitudinal
# archive & trend gating"): rebuild the trend archive from the repo's
# committed bench/multichip artifacts (must match the seeded
# tools/bench_archive.jsonl record-for-record), gate the last-good
# capture against its own rolling MAD band (exit 0 — a sane history
# admits itself), render the trend + changepoint blame report, and run
# the TD124 inject-regression self-test: a just-outside-band injection
# must be CAUGHT per band, an improvement must pass, and the synthetic
# changepoint must be localized — a dead detector exits 2:
#   make trend-report [OUT=/tmp/trend_archive.jsonl]
trend-report:
	python -m tpu_dist.obs archive ingest BENCH_r01.json BENCH_r02.json BENCH_r03.json BENCH_r04.json BENCH_r05.json MULTICHIP_r01.json MULTICHIP_r02.json MULTICHIP_r03.json MULTICHIP_r04.json MULTICHIP_r05.json LAST_GOOD_BENCH.json --archive $(or $(OUT),/tmp/trend_archive.jsonl)
	python -m tpu_dist.obs compare --against-archive $(or $(OUT),/tmp/trend_archive.jsonl) --bench LAST_GOOD_BENCH.json
	python -m tpu_dist.obs trend $(or $(OUT),/tmp/trend_archive.jsonl) --blame
	python -m tpu_dist.obs trend $(or $(OUT),/tmp/trend_archive.jsonl) --inject-regression

# Follow a LIVE run from another terminal:
#   make monitor LOG=run.jsonl [HB=hb.json]
# (docs/observability.md "obs tail" — rolling epoch table, live alert/
# anomaly/straggler lines, heartbeat staleness)
monitor:
	python -m tpu_dist.obs tail $(LOG) $(if $(HB),--heartbeat $(HB))

clean:
	$(MAKE) -C tpu_dist/csrc clean
	find . -name __pycache__ -type d -exec rm -rf {} +
