# Convenience targets; CI / the driver call the underlying commands directly.

.PHONY: test bench csrc clean

csrc:
	$(MAKE) -C tpu_dist/csrc

test:
	python -m pytest tests/ -x -q

bench:
	python bench.py

clean:
	$(MAKE) -C tpu_dist/csrc clean
	find . -name __pycache__ -type d -exec rm -rf {} +
