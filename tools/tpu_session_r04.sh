#!/bin/bash
# Round-4 TPU capture session: run ONCE when the tunnel recovers, in
# decreasing order of VERDICT value. One TPU process at a time (each
# bench/python run takes the machine lock; bench also waits --lock_wait).
# Usage: bash tools/tpu_session_r04.sh [outdir]   (default /tmp/tpu_r04)
cd /root/repo || exit 2
OUT=${1:-/tmp/tpu_r04}
mkdir -p "$OUT"
log() { echo "$(date -u +%F_%T) $*" | tee -a "$OUT/session.log"; }

# 0. single bounded probe — bail early if still wedged
timeout -k 10 300 python - <<'PY' || { log "probe FAILED - tunnel still wedged"; exit 3; }
from tpu_dist.comm import tpu_lock
tpu_lock.guard_or_exit("r04_probe")
import jax
d = jax.devices()
assert d and d[0].platform != "cpu", d
print("ALIVE", d, flush=True)
PY
log "tunnel alive"

# 1. driver-contract default line (also exercises the compile cache)
timeout -k 10 1200 python bench.py > "$OUT/BENCH_DEFAULT.json" 2>"$OUT/bench_default.err"
log "default bench rc=$? $(cat "$OUT/BENCH_DEFAULT.json" 2>/dev/null | head -c 300)"

# 2. flash long-seq crossover (this round's kernel showcase), plus a
#    causal row (the above-diagonal tile skip is measurable fwd+bwd)
timeout -k 10 2400 python bench.py --attn_all --steps 30 --warmup 5 \
  > "$OUT/ATTN_ALL.json" 2>"$OUT/attn.err"
log "attn_all rc=$?"
timeout -k 10 1200 python bench.py --attn 4096 --causal --steps 30 --warmup 5 \
  > "$OUT/ATTN_CAUSAL.json" 2>"$OUT/attn_causal.err"
log "attn_causal rc=$?"

# 3. ResNet-50 at b128 + s2d stem A/B (VERDICT #2)
for cfg in resnet50_imagenet resnet50_imagenet_s2d; do
  timeout -k 10 1800 python bench.py --config "$cfg" \
    > "$OUT/BENCH_$cfg.json" 2>"$OUT/$cfg.err"
  log "$cfg rc=$? $(cat "$OUT/BENCH_$cfg.json" 2>/dev/null | head -c 300)"
done

# 4. ResNet-50 profile capture (VERDICT #2 anatomy)
timeout -k 10 1800 python bench.py --config resnet50_imagenet \
  --profile_dir "$OUT/rn50_profile" > "$OUT/BENCH_rn50_profiled.json" 2>"$OUT/prof.err"
log "rn50 profile rc=$?"

# 5. ViT-B/16 flash vs xla at 224px, then the 1024px long-context pair
for cfg in vit_b16_imagenet vit_b16_imagenet_flash vit_b16_1024px_flash vit_b16_1024px_xla; do
  timeout -k 10 1800 python bench.py --config "$cfg" \
    > "$OUT/BENCH_$cfg.json" 2>"$OUT/$cfg.err"
  log "$cfg rc=$? $(cat "$OUT/BENCH_$cfg.json" 2>/dev/null | head -c 300)"
done

# 6. remaining --all rows (ga4, fp32, fused) for BENCH_ALL_r04
timeout -k 10 3600 python bench.py --all > "$OUT/BENCH_ALL.json" 2>"$OUT/all.err"
log "all rc=$?"

# 7. discriminating convergence on real TPU (TPU_RUN_r04 exhibit):
#    20 epochs multifactor, scheduled LR, fused device-resident epoch path
timeout -k 10 2400 python -m tpu_dist.cli.train \
  --dataset synthetic_multifactor --model resnet18 --num_classes 16 \
  --batch_size 256 --epochs 20 --lr 0.4 --lr_milestones 10 15 --lr_gamma 0.1 \
  --synthetic_n 4096 --eval_every 5 --log_every 8 \
  --log_file "$OUT/TPU_RUN_r04.jsonl" > "$OUT/TPU_RUN_r04.log" 2>&1
log "convergence run rc=$? tail: $(tail -2 "$OUT/TPU_RUN_r04.log" | tr '\n' ' ')"
log "session complete"
