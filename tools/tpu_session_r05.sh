#!/bin/bash
# Round-5 TPU capture session: run ONCE when the tunnel recovers, in
# decreasing order of VERDICT-r4 value. One TPU process at a time (each
# bench/python run takes the machine lock; bench also waits --lock_wait).
# Usage: bash tools/tpu_session_r05.sh [outdir]   (default /root/repo/tpu_r05)
cd /root/repo || exit 2
OUT=${1:-/root/repo/tpu_r05}
mkdir -p "$OUT"
log() { echo "$(date -u +%F_%T) $*" | tee -a "$OUT/session.log"; }

# 0. single bounded probe — bail early if still wedged
timeout -k 10 300 python - <<'PY' || { log "probe FAILED - tunnel still wedged"; exit 3; }
from tpu_dist.comm import tpu_lock
tpu_lock.guard_or_exit("r05_probe")
import jax
d = jax.devices()
assert d and d[0].platform != "cpu", d
print("ALIVE", d, flush=True)
PY
log "tunnel alive"

# 1. driver-contract default line (also exercises the compile cache).
#    On success, refresh LAST_GOOD_BENCH.json so the stale-fallback path
#    serves this capture from now on.
timeout -k 10 1200 python bench.py > "$OUT/BENCH_DEFAULT.json" 2>"$OUT/bench_default.err"
rc=$?
log "default bench rc=$rc $(head -c 300 "$OUT/BENCH_DEFAULT.json" 2>/dev/null)"
if [ "$rc" -eq 0 ] && python - "$OUT/BENCH_DEFAULT.json" <<'PY'
import json, sys, datetime
line = open(sys.argv[1]).read().strip().splitlines()[-1]
d = json.loads(line)
ok = d.get("value") and not d.get("stale")
if ok:
    d["captured_round"] = 5
    d["captured_date"] = datetime.date.today().isoformat()
    d["hardware"] = "1x TPU v5e (axon tunnel)"
    open("LAST_GOOD_BENCH.json", "w").write(json.dumps(d) + "\n")
sys.exit(0 if ok else 1)
PY
then log "LAST_GOOD_BENCH.json refreshed from fresh capture"; fi

# 2. flash long-seq crossover (rounds-3/4 kernel showcase), plus a causal
#    row (the above-diagonal tile skip is measurable fwd+bwd)
timeout -k 10 2400 python bench.py --attn_all --steps 30 --warmup 5 \
  > "$OUT/ATTN_ALL.json" 2>"$OUT/attn.err"
log "attn_all rc=$?"
timeout -k 10 1200 python bench.py --attn 4096 --causal --steps 30 --warmup 5 \
  > "$OUT/ATTN_CAUSAL.json" 2>"$OUT/attn_causal.err"
log "attn_causal rc=$?"

# 3. ResNet-50 at b128 + s2d stem A/B (VERDICT-r4 #3 MFU work)
for cfg in resnet50_imagenet resnet50_imagenet_s2d; do
  timeout -k 10 1800 python bench.py --config "$cfg" \
    > "$OUT/BENCH_$cfg.json" 2>"$OUT/$cfg.err"
  log "$cfg rc=$? $(head -c 300 "$OUT/BENCH_$cfg.json" 2>/dev/null)"
done

# 4. ResNet-50 profile capture (MFU anatomy)
timeout -k 10 1800 python bench.py --config resnet50_imagenet \
  --profile_dir "$OUT/rn50_profile" > "$OUT/BENCH_rn50_profiled.json" 2>"$OUT/prof.err"
log "rn50 profile rc=$?"

# 5. ViT-B/16 flash vs xla at 224px, then the 1024px long-context pair
for cfg in vit_b16_imagenet vit_b16_imagenet_flash vit_b16_1024px_flash vit_b16_1024px_xla; do
  timeout -k 10 1800 python bench.py --config "$cfg" \
    > "$OUT/BENCH_$cfg.json" 2>"$OUT/$cfg.err"
  log "$cfg rc=$? $(head -c 300 "$OUT/BENCH_$cfg.json" 2>/dev/null)"
done

# 6. sharded-checkpoint path on real device arrays (VERDICT-r4 #6):
#    scale-1 save (one chip = one shard) then resume — exercises the real
#    manifest/commit path on TPU-resident arrays, not CPU emulation
timeout -k 10 1800 python -m tpu_dist.cli.train \
  --dataset synthetic --model resnet18 --num_classes 16 \
  --batch_size 256 --epochs 2 --lr 0.1 --synthetic_n 2048 \
  --ckpt_dir "$OUT/sharded_ckpt" --sharded_ckpt --save_every 1 \
  > "$OUT/SHARDED_CKPT_SAVE.log" 2>&1
log "sharded ckpt save rc=$? tail: $(tail -1 "$OUT/SHARDED_CKPT_SAVE.log")"
timeout -k 10 1800 python -m tpu_dist.cli.train \
  --dataset synthetic --model resnet18 --num_classes 16 \
  --batch_size 256 --epochs 3 --lr 0.1 --synthetic_n 2048 \
  --ckpt_dir "$OUT/sharded_ckpt" --sharded_ckpt --save_every 1 --resume \
  > "$OUT/SHARDED_CKPT_RESUME.log" 2>&1
log "sharded ckpt resume rc=$? tail: $(tail -1 "$OUT/SHARDED_CKPT_RESUME.log")"

# 7. remaining --all rows (ga4, fp32, fused) for BENCH_ALL_r05
timeout -k 10 3600 python bench.py --all > "$OUT/BENCH_ALL.json" 2>"$OUT/all.err"
log "all rc=$?"

# 8. discriminating convergence on real TPU (TPU_RUN_r05 exhibit):
#    20 epochs multifactor, scheduled LR, fused device-resident epoch path
timeout -k 10 2400 python -m tpu_dist.cli.train \
  --dataset synthetic_multifactor --model resnet18 --num_classes 16 \
  --batch_size 256 --epochs 20 --lr 0.4 --lr_milestones 10 15 --lr_gamma 0.1 \
  --synthetic_n 4096 --eval_every 5 --log_every 8 \
  --log_file "$OUT/TPU_RUN_r05.jsonl" > "$OUT/TPU_RUN_r05.log" 2>&1
log "convergence run rc=$? tail: $(tail -2 "$OUT/TPU_RUN_r05.log" | tr '\n' ' ')"
log "session complete"
