#!/bin/bash
# Axon-tunnel recovery watcher (round-1/2 lesson: the tunnel can wedge for
# hours; probe it with SINGLE bounded attempts, never concurrently).
# On recovery: capture the driver-contract benchmark once, then exit so the
# operator owns the (healthy) tunnel again. Mutual exclusion with any other
# TPU-touching process comes from tpu_dist.comm.tpu_lock inside the probe.
cd /root/repo || exit 2
N=${1:-120}
OUT=${2:-/tmp/BENCH_EARLY_r03.json}
for i in $(seq 1 "$N"); do
  ts=$(date -u +%F_%H:%M:%S)
  timeout -k 10 300 python - <<'EOF'
from tpu_dist.comm import tpu_lock
tpu_lock.guard_or_exit("tpu_watch")
import jax
d = jax.devices()
assert d and d[0].platform != "cpu", d
print("ALIVE", d, flush=True)
EOF
  rc=$?
  echo "$ts attempt $i rc=$rc" >> /tmp/tpu_watch.log
  if [ "$rc" -eq 0 ]; then
    echo "$ts tunnel ALIVE - capturing default bench" >> /tmp/tpu_watch.log
    timeout -k 10 1200 python bench.py > "$OUT" 2>/tmp/bench_early.err
    echo "$ts bench rc=$? out=$(cat "$OUT")" >> /tmp/tpu_watch.log
    exit 0
  fi
  sleep 240
done
echo "$(date -u +%F_%H:%M:%S) exhausted $N attempts" >> /tmp/tpu_watch.log
exit 1
