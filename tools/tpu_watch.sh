#!/bin/bash
# Axon-tunnel recovery watcher (round-1/2 lesson: the tunnel can wedge for
# hours; probe it with SINGLE bounded attempts, never concurrently).
# On recovery: capture the driver-contract benchmark once, then exit so the
# operator owns the (healthy) tunnel again. Mutual exclusion with any other
# TPU-touching process comes from tpu_dist.comm.tpu_lock inside the probe;
# bench.py itself waits (--lock_wait) if it loses a race for the lock.
# ADVICE r3: a failed bench (lock lost, tunnel re-wedged) no longer consumes
# the recovery shot — the loop keeps probing until bench actually lands.
cd /root/repo || exit 2
N=${1:-120}
OUT=${2:-/tmp/BENCH_EARLY_r04.json}
for i in $(seq 1 "$N"); do
  ts=$(date -u +%F_%H:%M:%S)
  timeout -k 10 300 python - <<'EOF'
from tpu_dist.comm import tpu_lock
tpu_lock.guard_or_exit("tpu_watch")
import jax
d = jax.devices()
assert d and d[0].platform != "cpu", d
print("ALIVE", d, flush=True)
EOF
  rc=$?
  echo "$ts attempt $i rc=$rc" >> /tmp/tpu_watch.log
  if [ "$rc" -eq 0 ]; then
    echo "$ts tunnel ALIVE - capturing default bench" >> /tmp/tpu_watch.log
    timeout -k 10 1200 python bench.py > "$OUT".tmp 2>/tmp/bench_early.err
    brc=$?
    echo "$(date -u +%F_%H:%M:%S) bench rc=$brc out=$(cat "$OUT".tmp)" >> /tmp/tpu_watch.log
    if [ "$brc" -eq 0 ] && [ -s "$OUT".tmp ]; then
      mv "$OUT".tmp "$OUT"
      exit 0
    fi
    # bench failed (lock handoff lost, re-wedge, ...): fall through and
    # keep probing rather than exiting with no valid JSON captured
    rm -f "$OUT".tmp
  fi
  sleep 240
done
echo "$(date -u +%F_%H:%M:%S) exhausted $N attempts" >> /tmp/tpu_watch.log
exit 1
