#!/bin/bash
# Round-5 recovery watcher: probe the wedged axon tunnel with SINGLE bounded
# attempts (~4 min apart, lock-guarded), and on recovery run the FULL
# round-5 capture session (tools/tpu_session_r05.sh) — not just one bench —
# then exit.  Kill leftover watchers from prior rounds before starting
# (`pgrep -af tpu_watch`).
cd /root/repo || exit 2
N=${1:-160}
OUT=${2:-/root/repo/tpu_r05}
for i in $(seq 1 "$N"); do
  ts=$(date -u +%F_%H:%M:%S)
  timeout -k 10 300 python - <<'EOF'
from tpu_dist.comm import tpu_lock
tpu_lock.guard_or_exit("tpu_watch_r05")
import jax
d = jax.devices()
assert d and d[0].platform != "cpu", d
print("ALIVE", d, flush=True)
EOF
  rc=$?
  echo "$ts attempt $i rc=$rc" >> /tmp/tpu_watch_r05.log
  if [ "$rc" -eq 0 ]; then
    echo "$ts tunnel ALIVE - running full r05 capture session" >> /tmp/tpu_watch_r05.log
    bash tools/tpu_session_r05.sh "$OUT" >> /tmp/tpu_watch_r05.log 2>&1
    src=$?
    echo "$(date -u +%F_%H:%M:%S) session rc=$src" >> /tmp/tpu_watch_r05.log
    # session rc=3 means the tunnel died again before step 0 completed:
    # keep probing. Any other rc means the session ran; we're done.
    if [ "$src" -ne 3 ]; then exit 0; fi
  fi
  sleep 240
done
echo "$(date -u +%F_%H:%M:%S) exhausted $N attempts" >> /tmp/tpu_watch_r05.log
exit 1
