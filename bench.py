"""Benchmark harness: training throughput on TPU, one JSON line on stdout.

Default (driver contract): ResNet-18 / CIFAR-100 — the reference's headline
benchmark — printing
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.

Baseline (BASELINE.md): the reference's best row, DDP + apex on
4×RTX 2080 Ti: 14.5 s/epoch over CIFAR-100's 50,000 images ≈ 3,448 img/s
aggregate. ``vs_baseline`` = our aggregate images/sec ÷ that (>1 beats the
whole 4-GPU rig).

More configs (BASELINE.json's matrix) via ``--config``:

    python bench.py --config resnet18_cifar100      # default, bf16
    python bench.py --config resnet18_cifar100_fp32
    python bench.py --config resnet18_cifar100_ga4  # grad accumulation 4
    python bench.py --config resnet50_imagenet      # 224x224, bf16
    python bench.py --config vit_b16_imagenet       # transformer grads

Measures the steady-state compiled train step (warmup excluded), reference
hyperparameters (SGD+momentum+wd, SyncBN on for the conv nets).
"""

from __future__ import annotations

import argparse
import json
import time
from dataclasses import dataclass

import numpy as np

BASELINE_IMG_PER_SEC = 50_000 / 14.5  # DDP+apex, 4x2080Ti (README.md:77)
CIFAR_TRAIN = 50_000


def _capture_fingerprint() -> dict:
    """One fingerprint per bench PROCESS (hostname + random id), stamped
    with a monotonic capture time into every emitted record. Two records
    carrying the SAME fingerprint are the same physical capture: a
    later artifact re-emitting it byte-identically is a stale copy, not
    a fresh measurement — exactly the r03–r05 failure mode BENCH_NOTES
    documents, which ``obs compare --bench`` / ``obs summarize --bench``
    now flag as STALE instead of reporting as fresh."""
    import socket  # noqa: PLC0415
    import uuid  # noqa: PLC0415

    return {"host": socket.gethostname(), "bench_run_id": uuid.uuid4().hex[:12]}


_CAPTURE = _capture_fingerprint()

#: Every record this process emitted (``_stamped`` appends) — the
#: ``--archive`` self-ingest reads this at exit so the longitudinal
#: archive (``tpu_dist/obs/archive.py``) stays current without a
#: separate ingest step.
_EMITTED: list = []


def _stamped(rec: dict) -> dict:
    rec["capture"] = {**_CAPTURE, "mono_s": round(time.monotonic(), 3)}
    _EMITTED.append(rec)
    return rec


def _self_ingest(path: str, records=None) -> None:
    """Fold this invocation's records into the longitudinal archive.
    NEVER dies: a broken archive must not fail the bench that measured
    fine — the failure is counted to stderr instead (the archive's own
    loader counts torn/foreign lines the same way)."""
    import sys  # noqa: PLC0415

    recs = _EMITTED if records is None else records
    if not recs:
        return
    try:
        from tpu_dist.obs import archive as archive_lib  # noqa: PLC0415

        rep = archive_lib.ingest_records(recs, path, source_path="bench.py")
        print(
            f"bench: archived {rep['appended']} record(s) to {path}"
            + (f" ({rep['deduped']} already present)"
               if rep["deduped"] else ""),
            file=sys.stderr, flush=True,
        )
    except Exception as e:  # the never-dies contract: count, don't raise
        print(
            f"bench: archive self-ingest FAILED ({len(recs)} record(s) "
            f"NOT archived): {type(e).__name__}: {e}",
            file=sys.stderr, flush=True,
        )


def _costmodel():
    """The shared cost/MFU layer (``tpu_dist.obs.costmodel``) — ONE home
    for the chip-peak table, the ``cost_analysis()`` normalization, and
    ``memory_analysis()`` reading that this file used to keep private
    copies of. Imported lazily like every tpu_dist import here (argparse
    and the lock guard must run before any backend touch)."""
    from tpu_dist.obs import costmodel

    return costmodel


def _step_cost(compiled, loop_trips: int = 1) -> dict:
    """flops/bytes of one compiled step (see ``costmodel.step_cost`` for
    the scan-body ``loop_trips`` contract); all-None on failure."""
    return _costmodel().step_cost(compiled, loop_trips)


def _mfu(flops_per_step: float | None, step_seconds: float, n_devices: int) -> float | None:
    """Model FLOPs utilization: achieved FLOP/s over aggregate chip peak
    (None on unknown chips — CPU emulation above all)."""
    return _costmodel().mfu(flops_per_step, step_seconds, n_devices)


def _hbm_fields(compiled) -> dict:
    """XLA's own executable memory accounting, when the backend reports it:
    ``{"peak_hbm_bytes": ...}`` or empty."""
    ma = _costmodel().memory_analysis_bytes(compiled)
    return {"peak_hbm_bytes": ma["peak_bytes"]} if ma else {}


def _plan_fields(cost: dict, *, n_dev: int, step_s: float | None,
                 grad_compression: str = "none", bf16: bool = False,
                 grad_accum: int = 1, wire_bytes: int | None = None) -> dict:
    """The planner's view of THIS measured config, stamped next to the
    measurement (``analysis/planner.py``): the family label from the
    shared registry, the cost model's priced step time (calibrated gauges
    when a capture ran earlier in the process, the planner's uncalibrated
    defaults otherwise — ``plan.gauge_source`` says which), and the TD119
    ``planner_error_frac`` of that price against the measured step time —
    the same drift scalar the trainer logs after a profiled run, so
    ``obs compare --bench`` gates bench records with the identical
    metric. Empty on an unpriceable config (cost analysis failed)."""
    try:
        from tpu_dist.analysis import planner  # noqa: PLC0415
        from tpu_dist.obs import costmodel  # noqa: PLC0415

        gauges, source = planner.pricing_gauges()
        pred = costmodel.predicted_step_time(
            cost, wire_bytes=wire_bytes, n_devices=n_dev, gauges=gauges,
        )
        if not pred:
            return {}
        out = {
            "plan": {
                "family": planner.family_of(
                    grad_compression=grad_compression, bf16=bf16,
                    grad_accu_steps=grad_accum,
                ),
                "gauge_source": source,
            },
            "predicted_step_s": pred["predicted_step_s"],
        }
        err = costmodel.planner_error_frac(pred["predicted_step_s"], step_s)
        if err is not None:
            out["planner_error_frac"] = err
        return out
    except Exception as e:  # noqa: BLE001 — a bench must not die on a stamp
        import sys  # noqa: PLC0415

        print(f"bench: plan stamp unavailable: {e}", file=sys.stderr)
        return {}


def _wire_audit(fn, *args, trips: int = 1) -> dict | None:
    """Static wire-byte accounting of a compiled step/epoch's gradient
    collectives (the jaxpr-level TD104 model from ``tpu_dist.analysis``),
    normalized to ONE step via ``trips``. An abstract trace — valid on CPU
    emulation, where the --grad_compression sweep's throughput numbers are
    not. Returns None (with a stderr note — this is the sweep's headline
    metric, a silent drop would read as 'audit unavailable') on failure."""
    import sys

    try:
        from tpu_dist.analysis.jaxpr_audit import trace_counts

        w = trace_counts(fn, *args)["wire"]
        return {
            k: w[k] // trips
            for k in ("payload_bytes", "quantized_payload_bytes", "sideband_bytes")
        }
    except Exception as e:
        print(f"bench: wire-byte audit failed ({type(e).__name__}: "
              f"{(str(e).splitlines() or [''])[0][:160]})",
              file=sys.stderr, flush=True)
        return None


def _hlo_wire_audit(
    compiled, loop_trips: int = 1, per_step_div: int = 1
) -> int | None:
    """HLO-derived wire bytes of ONE step, from the optimized module the
    compiler actually emitted (the shardlint parser over
    ``Compiled.as_text()`` — tpu_dist/analysis/shardlint.py). Stamped
    beside the jaxpr ring model's ``wire_bytes_per_step`` so the two
    accountings ride every bench record together, and gated by ``obs
    compare --bench`` (higher = a compiled-comm regression: GSPMD grew a
    reshard the jaxpr can't see). ``loop_trips`` prices ``while``-body
    collectives at their trip count; ``per_step_div`` normalizes a
    whole-epoch scan program back to one step. The two are SEPARATE so a
    grad-accumulation step (trips=K, div=1) shows a collective that
    drifted INTO the accumulation loop as a Kx wire regression instead
    of hiding it. None (with a stderr note) on failure — CPU-valid, so
    this gates while the TPU tunnel is down."""
    import sys

    try:
        from tpu_dist.analysis.shardlint import parse_hlo_collectives

        ops = parse_hlo_collectives(compiled.as_text(), loop_trips=loop_trips)
        return sum(op.wire_bytes for op in ops) // per_step_div
    except Exception as e:
        print(f"bench: HLO wire-byte audit failed ({type(e).__name__}: "
              f"{(str(e).splitlines() or [''])[0][:160]})",
              file=sys.stderr, flush=True)
        return None


@dataclass(frozen=True)
class BenchConfig:
    name: str
    model: str
    image_size: int
    num_classes: int
    global_batch: int
    bf16: bool = True
    grad_accum: int = 1
    sync_bn: bool = True
    fused_epoch: bool = False  # device-resident data, one jit per epoch
    flash: bool = False        # Pallas tiled attention (transformer models)
    s2d: bool = False          # space-to-depth stem (ImageNet ResNet only)
    epoch_images: int = CIFAR_TRAIN  # for sec/epoch derivation


CONFIGS = {
    c.name: c
    for c in [
        BenchConfig("resnet18_cifar100", "resnet18", 32, 100, 256),
        BenchConfig("resnet18_cifar100_fp32", "resnet18", 32, 100, 256, bf16=False),
        BenchConfig("resnet18_cifar100_ga4", "resnet18", 32, 100, 256, grad_accum=4),
        BenchConfig("resnet18_cifar100_fused", "resnet18", 32, 100, 256, fused_epoch=True),
        # b128: the measured single-chip operating point (BENCH_NOTES r2
        # batch sweep: b64 2,430 img/s / MFU 0.296 vs b128 2,624 / 0.319)
        BenchConfig(
            "resnet50_imagenet", "resnet50_imagenet", 224, 1000, 128,
            epoch_images=1_281_167,
        ),
        # same model, space-to-depth stem (MXU-utilization rewrite of the
        # 7x7/2 C_in=3 conv; numerics-identical, nn/resnet.py::_stem_s2d)
        BenchConfig(
            "resnet50_imagenet_s2d", "resnet50_imagenet", 224, 1000, 128,
            s2d=True, epoch_images=1_281_167,
        ),
        BenchConfig(
            "vit_b16_imagenet", "vit_b16", 224, 1000, 64,
            sync_bn=False, epoch_images=1_281_167,
        ),
        BenchConfig(
            "vit_b16_imagenet_flash", "vit_b16", 224, 1000, 64,
            sync_bn=False, flash=True, epoch_images=1_281_167,
        ),
        # long-context showcase: 1024px -> S = 64^2+1 = 4097 tokens; the
        # full train step (not just the attention micro-bench) at a length
        # where the XLA path's score tensor is the memory bottleneck
        BenchConfig(
            "vit_b16_1024px_flash", "vit_b16", 1024, 1000, 8,
            sync_bn=False, flash=True, epoch_images=1_281_167,
        ),
        BenchConfig(
            "vit_b16_1024px_xla", "vit_b16", 1024, 1000, 8,
            sync_bn=False, epoch_images=1_281_167,
        ),
    ]
}


def run(cfg: BenchConfig, steps: int, warmup: int, n_devices: int | None = None,
        profile_dir: str | None = None, grad_compression: str = "none") -> dict:
    # goodput accounting opens with the bench itself: everything from here
    # to the record — model init, compile, warmup — is overhead the
    # measured loop amortizes, and goodput_frac = measured-loop seconds /
    # total wall is the CPU-valid time-accounting signal the trainer's
    # run ledger reports at scale (obs/goodput.py)
    t_bench0 = time.perf_counter()
    import jax
    import jax.numpy as jnp

    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.nn import resnet18, resnet34, resnet50
    from tpu_dist.nn.resnet import resnet50_imagenet
    from tpu_dist.nn.vit import vit_b16
    from tpu_dist.train.optim import SGD
    from tpu_dist.train.state import TrainState
    from tpu_dist.train.step import make_train_step

    models = {
        "resnet18": resnet18, "resnet34": resnet34, "resnet50": resnet50,
        "resnet50_imagenet": lambda num_classes: resnet50_imagenet(
            num_classes, s2d_stem=cfg.s2d
        ),
        "vit_b16": lambda num_classes: vit_b16(num_classes, cfg.image_size),
    }
    from tpu_dist.nn.attention import set_default_attention_impl

    # process-global: reset per run so --all mixes flash/xla configs safely
    set_default_attention_impl("flash" if cfg.flash else "xla")
    if n_devices is None:
        mesh = mesh_lib.data_parallel_mesh()
    else:
        mesh = mesh_lib.device_mesh(
            [n_devices], [mesh_lib.DATA_AXIS], jax.devices()[:n_devices]
        )
    n_dev = int(mesh.devices.size)
    batch = cfg.global_batch
    if batch % (n_dev * cfg.grad_accum):
        batch = n_dev * cfg.grad_accum * max(1, batch // (n_dev * cfg.grad_accum))

    model = models[cfg.model](num_classes=cfg.num_classes)
    optimizer = SGD(momentum=0.9, weight_decay=1e-4)
    params, bn_state = model.init(jax.random.PRNGKey(0))
    state = jax.device_put(
        TrainState.create(params, bn_state, optimizer), mesh_lib.replicated(mesh)
    )
    if grad_compression == "int8_ef":
        from tpu_dist.train.step import init_ef_state

        state = state._replace(ef=init_ef_state(params, mesh))
    if cfg.fused_epoch:
        return _run_fused(
            cfg, mesh, model, optimizer, state, n_dev, batch,
            grad_compression=grad_compression, t_bench0=t_bench0,
        )
    step = make_train_step(
        model.apply,
        optimizer,
        mesh,
        grad_accum_steps=cfg.grad_accum,
        sync_bn=cfg.sync_bn,
        compute_dtype=jnp.bfloat16 if cfg.bf16 else jnp.float32,
        grad_compression=grad_compression,
    )

    rng = np.random.default_rng(0)
    images = mesh_lib.shard_batch(
        mesh, rng.normal(size=(batch, cfg.image_size, cfg.image_size, 3)).astype(np.float32)
    )
    labels = mesh_lib.shard_batch(
        mesh, rng.integers(0, cfg.num_classes, batch).astype(np.int32)
    )

    wire = _wire_audit(step, state, images, labels, 0.1)

    # AOT-compile once: the same executable serves cost analysis (MFU
    # numerator), memory accounting, AND the measured loop — no double
    # compile.
    try:
        compiled = step.lower(state, images, labels, 0.1).compile()
        cost = _step_cost(compiled, loop_trips=cfg.grad_accum)
        hbm = _hbm_fields(compiled)
        hlo_wire = _hlo_wire_audit(compiled, loop_trips=cfg.grad_accum)
        call = compiled
    except Exception:
        cost, hbm, hlo_wire = (
            {"flops_per_step": None, "bytes_per_step": None}, {}, None,
        )
        call = step
    flops_per_step = cost["flops_per_step"]

    for _ in range(warmup):
        state, metrics = call(state, images, labels, 0.1)
    jax.block_until_ready(state.params)

    import contextlib

    from tpu_dist.obs.profile import StepTimer, trace

    prof = trace(profile_dir) if profile_dir else contextlib.nullcontext()
    with prof:
        # per-step laps WITHOUT a per-step sync (StepTimer discipline): the
        # device queue's backpressure paces the enqueues at the real step
        # rate in steady state, so the percentiles see stalls/jitter while
        # the hot loop stays sync-free; only the final block is exact.
        timer = StepTimer(warmup_steps=1)
        timer.tick()  # baseline mark (the warmup loop above already ran)
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = call(state, images, labels, 0.1)
            timer.tick()
        jax.block_until_ready(state.params)
        dt = time.perf_counter() - t0

    img_per_sec = batch * steps / dt
    tag = "" if grad_compression == "none" else f"_{grad_compression}"
    pct = timer.percentiles() or {}
    out = {
        "metric": f"{cfg.name}{tag}_train_throughput",
        "value": round(img_per_sec, 1),
        "unit": "images/sec",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 3),
        "sec_per_epoch": round(cfg.epoch_images / img_per_sec, 2),
        "n_devices": n_dev,
        "global_batch": batch,
        "img_per_sec_per_chip": round(img_per_sec / n_dev, 1),
        "step_ms": round(1000 * dt / steps, 2),
        # tail latency in the same schema the trainer's epoch summary and
        # `tpu_dist.obs summarize` report (p50/p95/p99), bench's ms units
        **{
            f"step_ms_{q}": round(1000 * v, 2) for q, v in sorted(pct.items())
        },
        "mfu": _mfu(flops_per_step, dt / steps, n_dev),
        # measured-loop seconds over total bench wall (compile + warmup
        # included): the bench-local goodput fraction
        "goodput_frac": round(dt / (time.perf_counter() - t_bench0), 4),
        # XLA's per-step cost accounting next to the throughput it explains
        # (same numbers the trainer publishes as device.* gauges)
        "flops_per_step": cost["flops_per_step"],
        "bytes_per_step": cost["bytes_per_step"],
        **hbm,
    }
    if grad_compression != "none":
        out["grad_compression"] = grad_compression
    if wire is not None:
        out["wire_bytes_per_step"] = wire
    if hlo_wire is not None:
        out["hlo_wire_bytes_per_step"] = hlo_wire
    out.update(_plan_fields(
        cost, n_dev=n_dev, step_s=dt / steps,
        grad_compression=grad_compression, bf16=cfg.bf16,
        grad_accum=cfg.grad_accum, wire_bytes=hlo_wire,
    ))
    if profile_dir:
        # read the capture back (obs/xprof): the attribution lands next to
        # the throughput it explains — a bench line with 40% collective
        # share and 10% overlap names its own bottleneck
        from tpu_dist.obs.profile import analyze_capture_quietly

        analysis, a_err = analyze_capture_quietly(profile_dir)
        if analysis is not None:
            out["profile_analysis"] = {
                k: analysis.get(k)
                for k in ("device_busy_s", "collective_frac",
                          "overlap_frac", "infeed_stall_s")
            }
        elif a_err:
            out["profile_analysis_error"] = a_err
    return _stamped(out)


def _run_fused(cfg: BenchConfig, mesh, model, optimizer, state, n_dev: int,
               batch: int, grad_compression: str = "none",
               t_bench0: float | None = None) -> dict:
    """Bench the device-resident fused-epoch path on the real 50k dataset:
    measures true seconds/epoch including shuffle + augmentation (all
    on-device), one jit call per epoch."""
    import time as _t

    import jax
    import jax.numpy as jnp

    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.data import synthetic_cifar
    from tpu_dist.train.epoch import make_fused_epoch, put_dataset_on_device

    imgs, lbls = synthetic_cifar(CIFAR_TRAIN, cfg.num_classes, cfg.image_size)
    dx, dy = put_dataset_on_device(mesh, imgs, lbls)
    runner = make_fused_epoch(
        model.apply, optimizer, mesh,
        batch_per_device=batch // n_dev,
        sync_bn=cfg.sync_bn,
        compute_dtype=jnp.bfloat16 if cfg.bf16 else jnp.float32,
        grad_compression=grad_compression,
    )
    from tpu_dist.train.epoch import fused_steps_per_epoch

    steps_per_epoch = fused_steps_per_epoch(int(dx.shape[0]), batch)
    # whole-epoch program: the scan multiplies per-trip collectives, so
    # normalize the audit back to one step
    wire = _wire_audit(runner, state, dx, dy, 0.1, 0, trips=steps_per_epoch)
    # AOT-compile once (cost analysis + the measured loop share it)
    try:
        compiled = runner.lower(state, dx, dy, 0.1, 0).compile()
        cost = _step_cost(compiled, loop_trips=steps_per_epoch)
        hbm = _hbm_fields(compiled)
        hlo_wire = _hlo_wire_audit(
            compiled, loop_trips=steps_per_epoch,
            per_step_div=steps_per_epoch,
        )
        call = compiled
    except Exception:
        cost, hbm, hlo_wire = (
            {"flops_per_step": None, "bytes_per_step": None}, {}, None,
        )
        call = runner
    flops_per_epoch = cost["flops_per_step"]  # trips-scaled: whole epoch

    # warmup epoch
    state, m = call(state, dx, dy, 0.1, 0)
    jax.block_until_ready(state.params)

    n_epochs = 3
    t0 = _t.perf_counter()
    for e in range(1, n_epochs + 1):
        state, m = call(state, dx, dy, 0.1, e)
    jax.block_until_ready(state.params)
    dt = (_t.perf_counter() - t0) / n_epochs

    n_images = int(dx.shape[0])
    img_per_sec = n_images / dt
    tag = "" if grad_compression == "none" else f"_{grad_compression}"
    out = {
        "metric": f"{cfg.name}{tag}_train_throughput",
        "value": round(img_per_sec, 1),
        "unit": "images/sec",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 3),
        "sec_per_epoch": round(dt, 2),
        "n_devices": n_dev,
        "global_batch": batch,
        "img_per_sec_per_chip": round(img_per_sec / n_dev, 1),
        "mfu": _mfu(flops_per_epoch, dt, n_dev),
        "goodput_frac": (
            round(
                (dt * n_epochs)
                / (_t.perf_counter() - t_bench0), 4,
            ) if t_bench0 is not None else None
        ),
        # per-STEP accounting (divide the trips-scaled epoch totals back)
        "flops_per_step": (
            round(flops_per_epoch / steps_per_epoch)
            if flops_per_epoch else None
        ),
        "bytes_per_step": (
            round(cost["bytes_per_step"] / steps_per_epoch)
            if cost["bytes_per_step"] else None
        ),
        **hbm,
    }
    if grad_compression != "none":
        out["grad_compression"] = grad_compression
    if wire is not None:
        out["wire_bytes_per_step"] = wire
    if hlo_wire is not None:
        out["hlo_wire_bytes_per_step"] = hlo_wire
    out.update(_plan_fields(
        # the record's per-step normalization of the trips-scaled totals
        {"flops_per_step": out["flops_per_step"],
         "bytes_per_step": out["bytes_per_step"]},
        n_dev=n_dev, step_s=dt / steps_per_epoch,
        grad_compression=grad_compression, bf16=cfg.bf16,
        grad_accum=cfg.grad_accum, wire_bytes=hlo_wire,
    ))
    return _stamped(out)


def run_attn(seq_len: int, steps: int, warmup: int, *, batch: int = 0,
             causal: bool = False) -> dict:
    """Long-sequence attention micro-bench: Pallas flash kernel vs the XLA
    [S,S]-materializing path, fwd+bwd, one JSON line.

    The reference has no attention at all (SURVEY §2.3); this is the
    long-context showcase for ``ops/flash_attention.py`` — at lengths where
    the XLA path's [B·H, S, S] f32 score tensor stops fitting in HBM
    (S=16k at these shapes wants ~17 GB for the scores alone on a 16 GB
    chip), flash keeps O(block²) per-core working sets. ``vs_baseline``
    here = flash speedup over the XLA path (>1 means the kernel wins;
    null when XLA could not run at all — the strongest possible win).
    """
    import jax
    import jax.numpy as jnp

    from tpu_dist.nn.attention import full_attention

    heads, d_head = 8, 128  # model dim 1024, MXU-native 128-lane head dim
    if batch <= 0:
        batch = max(1, 32_768 // seq_len)  # ~32k tokens per step
    shape = (batch, seq_len, heads, d_head)
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=shape), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=shape), jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=shape), jnp.bfloat16)

    def bench_impl(impl: str):
        def loss(q, k, v):
            if impl == "flash_xla_bwd":  # A/B: Pallas fwd, lax.scan bwd
                from tpu_dist.ops.flash_attention import flash_attention

                out = flash_attention(q, k, v, causal=causal, bwd="xla")
            else:
                out = full_attention(q, k, v, causal=causal, impl=impl)
            return out.astype(jnp.float32).sum()

        step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        try:
            call = step.lower(q, k, v).compile()
            for _ in range(warmup):
                jax.block_until_ready(call(q, k, v))
            t0 = time.perf_counter()
            for _ in range(steps):
                out = call(q, k, v)
            jax.block_until_ready(out)
            return (time.perf_counter() - t0) / steps, None
        except Exception as e:  # RESOURCE_EXHAUSTED at S=16k is the point
            return None, f"{type(e).__name__}: {(str(e).splitlines() or [''])[0][:160]}"

    flash_s, flash_err = bench_impl("flash")
    xla_s, xla_err = bench_impl("xla")
    # the round-4 Pallas backward vs the XLA-scan backward, same forward —
    # skipped when the flash forward itself could not run
    fxb_s, fxb_err = bench_impl("flash_xla_bwd") if flash_s else (None, "skipped")

    # analytic fwd+bwd FLOPs (QK^T + PV fwd = 4·S²·D/head; FA2 bwd ≈ 2.5×):
    # XLA cost analysis can't see inside pallas_call, so both impls use the
    # same formula — MFU comparable across the two columns
    flops = 14.0 * batch * heads * seq_len * seq_len * d_head
    if causal:
        flops /= 2
    tok_per_sec = round(batch * seq_len / flash_s, 1) if flash_s else None
    return _stamped({
        "metric": f"attn_s{seq_len}{'_causal' if causal else ''}_flash_fwd_bwd",
        "value": tok_per_sec,
        "unit": "tokens/sec",
        "vs_baseline": (
            round(xla_s / flash_s, 3) if flash_s and xla_s else None
        ),
        "seq_len": seq_len,
        "batch": batch,
        "heads": heads,
        "head_dim": d_head,
        "flash_ms": round(1000 * flash_s, 2) if flash_s else None,
        "xla_ms": round(1000 * xla_s, 2) if xla_s else None,
        "flash_xla_bwd_ms": round(1000 * fxb_s, 2) if fxb_s else None,
        "flash_xla_bwd_err": fxb_err,
        "flash_err": flash_err,
        "xla_err": xla_err,
        "mfu": _mfu(flops, flash_s, 1) if flash_s else None,
        "xla_mfu": _mfu(flops, xla_s, 1) if xla_s else None,
    })


def run_pp(cfg: BenchConfig, steps: int, warmup: int, pp: int,
           interleave: int, microbatches: int, dims: str = "b16") -> dict:
    """Pipeline-parallel bench: ViT-B/16 split into ``pp`` stages over a
    (data × pipe) mesh, GPipe (``interleave=1``) or interleaved virtual
    stages, with the schedule's bubble fraction in the output line.

    Needs ``pp`` to divide the visible device count — on the single-chip
    TPU run it with CPU host-platform emulation
    (``XLA_FLAGS=--xla_force_host_platform_device_count=8``); on a real
    multi-chip slice it measures the ICI pipeline directly.
    """
    t_bench0 = time.perf_counter()
    import jax
    import jax.numpy as jnp

    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.nn.vit_pp import ViTPipelineDef
    from tpu_dist.parallel.pipeline import bubble_fraction
    from tpu_dist.train.optim import SGD
    from tpu_dist.train.state import TrainState
    from tpu_dist.train.step import make_train_step

    if cfg.model != "vit_b16":
        raise SystemExit("--pp bench supports --config vit_b16_imagenet only")
    n = len(jax.devices())
    if n % pp:
        raise SystemExit(f"{n} devices not divisible by pp={pp}")
    # tiny dims: smoke/validate the schedule on CPU emulation; b16: the
    # real measurement shape
    depth, dim, heads, patch, img = (
        (12, 768, 12, 16, cfg.image_size) if dims == "b16"
        else (8, 64, 4, 4, 32)
    )
    if depth % (pp * interleave):
        raise SystemExit(
            f"depth {depth} must divide into pp*interleave={pp * interleave} "
            "equal chunks (try pp in {2,3,4,6,12}, interleave such that "
            f"pp*interleave divides {depth})"
        )
    mesh = mesh_lib.device_mesh(
        [n // pp, pp], [mesh_lib.DATA_AXIS, mesh_lib.PIPE_AXIS]
    )
    model = ViTPipelineDef(
        image_size=img, patch_size=patch, dim=dim, depth=depth,
        heads=heads, num_classes=cfg.num_classes,
        interleave=interleave, pp_stages=pp if interleave > 1 else 0,
    )
    cfg = __import__("dataclasses").replace(cfg, image_size=img)
    m = microbatches or pp
    optimizer = SGD(momentum=0.9, weight_decay=1e-4)
    params, st = model.init(jax.random.PRNGKey(0))
    specs = model.pp_param_specs(mesh_lib.PIPE_AXIS)
    state = TrainState(
        params=mesh_lib.place_host_tree(mesh, params, specs),
        bn_state=mesh_lib.place_host_tree(mesh, st),
        opt_state=mesh_lib.place_host_tree(mesh, optimizer.init(params), specs),
        step=mesh_lib.place_host_tree(mesh, jnp.zeros((), jnp.int32)),
    )
    step = make_train_step(
        model.apply, optimizer, mesh, sync_bn=False,
        compute_dtype=jnp.bfloat16 if cfg.bf16 else jnp.float32,
        pp_axis=mesh_lib.PIPE_AXIS, param_specs=specs,
        model_kwargs={"n_microbatches": m} if microbatches else None,
    )
    batch = cfg.global_batch
    n_data = n // pp
    if (batch // n_data) % m:
        batch = n_data * m * max(1, batch // (n_data * m))
    rng = np.random.default_rng(0)
    images = mesh_lib.shard_batch(
        mesh, rng.normal(size=(batch, cfg.image_size, cfg.image_size, 3)).astype(np.float32)
    )
    labels = mesh_lib.shard_batch(
        mesh, rng.integers(0, cfg.num_classes, batch).astype(np.int32)
    )
    try:
        compiled = step.lower(state, images, labels, 0.1).compile()
        flops = _step_cost(compiled)["flops_per_step"]
        call = compiled
    except Exception:
        flops = None
        call = step
    for _ in range(warmup):
        state, metrics = call(state, images, labels, 0.1)
    jax.block_until_ready(state.params)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = call(state, images, labels, 0.1)
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0
    img_per_sec = batch * steps / dt
    return _stamped({
        "metric": (
            f"{cfg.name}_pp{pp}x{interleave}_m{m}"
            + ("_tiny" if dims == "tiny" else "")
            + "_train_throughput"
        ),
        "value": round(img_per_sec, 1),
        "unit": "images/sec",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 3),
        "n_devices": n,
        "global_batch": batch,
        "pp_stages": pp,
        "pp_interleave": interleave,
        "pp_microbatches": m,
        "bubble_fraction": round(bubble_fraction(pp, m, interleave), 4),
        "step_ms": round(1000 * dt / steps, 2),
        "mfu": _mfu(flops, dt / steps, n),
        "goodput_frac": round(dt / (time.perf_counter() - t_bench0), 4),
    })


def _build_model(cfg: BenchConfig):
    from tpu_dist.nn import resnet18, resnet34, resnet50
    from tpu_dist.nn.resnet import resnet50_imagenet
    from tpu_dist.nn.vit import vit_b16

    builders = {
        "resnet18": resnet18, "resnet34": resnet34, "resnet50": resnet50,
        "resnet50_imagenet": lambda num_classes: resnet50_imagenet(
            num_classes, s2d_stem=cfg.s2d
        ),
        "vit_b16": lambda num_classes: vit_b16(num_classes, cfg.image_size),
    }
    return builders[cfg.model](num_classes=cfg.num_classes)


def run_ckpt(cfg: BenchConfig, warmup: int, mode: str, saves: int = 6) -> dict:
    """Sharded-checkpoint drill (``--ckpt``): how long does the STEP LOOP
    stay blocked per save?  ``sync`` pays uncommit + device→host snapshot
    + serialize + CRC32 + write + manifest commit inline;  ``async`` pays
    only uncommit + snapshot — the rest runs on the writer thread
    (``ckpt/checkpoint.py`` two-phase protocol).  A real compiled train
    step runs between saves so the async writer has compute to hide
    behind, and the drill proves the hidden work still happened: the
    drain is bounded-waited, the newest manifest is deep-verified
    (CRC32), and on the async path an injected EIO (``--fault_plan``
    ladder) MUST surface through the drain — the TD120 CLI probe; the
    caller exits 2 when ``ckpt_eio_probe`` comes back dead."""
    t_bench0 = time.perf_counter()
    import os  # noqa: PLC0415
    import shutil  # noqa: PLC0415
    import tempfile  # noqa: PLC0415

    import jax  # noqa: PLC0415
    import jax.numpy as jnp  # noqa: PLC0415

    from tpu_dist.ckpt import checkpoint as ckpt  # noqa: PLC0415
    from tpu_dist.comm import mesh as mesh_lib  # noqa: PLC0415
    from tpu_dist.resilience import faults  # noqa: PLC0415
    from tpu_dist.train.optim import SGD  # noqa: PLC0415
    from tpu_dist.train.state import TrainState  # noqa: PLC0415
    from tpu_dist.train.step import make_train_step  # noqa: PLC0415

    assert mode in ("sync", "async"), mode
    mesh = mesh_lib.data_parallel_mesh()
    n_dev = int(mesh.devices.size)
    batch = max(n_dev, (cfg.global_batch // n_dev) * n_dev)

    model = _build_model(cfg)
    optimizer = SGD(momentum=0.9, weight_decay=1e-4)
    params, bn_state = model.init(jax.random.PRNGKey(0))
    state = jax.device_put(
        TrainState.create(params, bn_state, optimizer), mesh_lib.replicated(mesh)
    )
    step = make_train_step(
        model.apply, optimizer, mesh, sync_bn=False,
        compute_dtype=jnp.bfloat16 if cfg.bf16 else jnp.float32,
    )
    rng = np.random.default_rng(0)
    images = mesh_lib.shard_batch(
        mesh,
        rng.normal(size=(batch, cfg.image_size, cfg.image_size, 3)).astype(np.float32),
    )
    labels = mesh_lib.shard_batch(
        mesh, rng.integers(0, cfg.num_classes, batch).astype(np.int32)
    )
    for _ in range(max(1, warmup)):
        state, _metrics = step(state, images, labels, 0.1)
    jax.block_until_ready(state.params)
    snap_bytes = ckpt.snapshot_sharded(state, 0).nbytes

    ckpt_dir = tempfile.mkdtemp(prefix=f"ckpt_bench_{mode}_")
    writer = ckpt.AsyncShardedCheckpointer() if mode == "async" else None
    blocked: list = []
    try:
        for i in range(saves):
            state, _metrics = step(state, images, labels, 0.1)
            jax.block_until_ready(state.params)
            # step boundary reached: from here to t1 is PURE save blocking
            t0 = time.perf_counter()
            if writer is None:
                ckpt.save_sharded(ckpt_dir, state, epoch=i)
            else:
                writer.save(ckpt_dir, state, epoch=i)
            blocked.append(time.perf_counter() - t0)
        t_drain0 = time.perf_counter()
        if writer is not None and not writer.close(timeout=600.0):
            raise RuntimeError("ckpt drill: async writer failed to drain")
        drain_ms = round(1000 * (time.perf_counter() - t_drain0), 3)

        latest = ckpt.latest_sharded_checkpoint(ckpt_dir)
        if latest is None or latest[1] != saves - 1:
            raise RuntimeError(
                f"ckpt drill: expected committed epoch {saves - 1}, "
                f"found {latest!r}"
            )
        ckpt.verify_sharded(latest[0], deep=True)  # raises on corruption

        eio_probe = None
        if mode == "async":
            # TD120 probe: arm an EIO on the next shard write and prove the
            # background error SURFACES at the drain — a clean probe means
            # async writes could silently lose checkpoints.
            probe_dir = os.path.join(ckpt_dir, "eio_probe")
            faults.configure("ckpt_write@call=1")
            probe_writer = ckpt.AsyncShardedCheckpointer()
            try:
                probe_writer.save(probe_dir, state, epoch=saves)
                probe_writer.wait(timeout=600.0)
                eio_probe = "dead"
            except OSError:
                eio_probe = "caught"
            finally:
                faults.clear()
                try:
                    probe_writer.close(timeout=60.0)
                except OSError:
                    pass  # the probe's own injected error draining out
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)

    out = {
        # no "value": blocked ms is lower-is-better; compare gates the
        # registry-declared ckpt_blocked_ms field instead (obs/compare.py)
        "metric": f"sharded_ckpt_{mode}",
        "unit": "ms blocked per save",
        "ckpt_mode": mode,
        "ckpt_blocked_ms": round(1000 * sum(blocked) / len(blocked), 3),
        "ckpt_blocked_ms_max": round(1000 * max(blocked), 3),
        "ckpt_saves": saves,
        "ckpt_snapshot_bytes": int(snap_bytes),
        "n_devices": n_dev,
        "wall_s": round(time.perf_counter() - t_bench0, 2),
    }
    if mode == "async":
        out["ckpt_drain_ms"] = drain_ms
        out["ckpt_eio_probe"] = eio_probe
    return _stamped(out)


def _guarded_backend_init(
    timeout_s: float, default_invocation: bool = False,
    archive: "str | None" = None,
) -> None:
    """Fail loudly (exit 3) if device discovery hangs — a wedged TPU tunnel
    must not hang the calling harness forever.

    Four consecutive driver rounds produced an empty bench artifact because
    the tunnel was wedged from outside this repo's control (rc=3, parsed
    null).  So for the DEFAULT driver-contract invocation only (plain
    ``python bench.py``, no mode/config flags), the unreachable path emits
    the most recent *committed* real-TPU capture (LAST_GOOD_BENCH.json,
    written only from a successful on-chip run) stamped ``stale: true``
    with its age and exits 0, so the driver artifact always carries the
    current best number and how old it is.  Non-default invocations
    (--attn/--config/--all/...) keep the bare exit-3 — a stale
    resnet18 line would be a wrong-metric artifact there.  A fresh capture
    overwrites the file and clears the staleness.
    """
    import datetime
    import os
    import sys

    from tpu_dist.comm.device_probe import bounded_device_discovery

    try:
        bounded_device_discovery(timeout_s)
        return
    except TimeoutError as e:
        print(f"bench: {e}", file=sys.stderr, flush=True)
    except Exception as e:
        # discovery FAILED fast (plugin/registration error, not a hang):
        # keep the real traceback visible rather than claiming a timeout
        import traceback  # noqa: PLC0415

        print(f"bench: device backend initialization failed: {e}",
              file=sys.stderr, flush=True)
        traceback.print_exc()
    # no devices either way — stale fallback for the driver-contract line
    if not default_invocation:
        os._exit(3)
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(here, "LAST_GOOD_BENCH.json")
    try:
        with open(path) as f:
            last = json.load(f)
        if not isinstance(last, dict):
            raise ValueError(f"expected a JSON object, got {type(last).__name__}")
        captured = last.get("captured_date", "")
        age = None
        if captured:
            age = (
                datetime.date.today()
                - datetime.date.fromisoformat(captured)
            ).days
        last.update(
            stale=True,
            age_days=age,
            note=(
                "TPU tunnel unreachable this run; this is the most "
                "recent committed on-chip capture, NOT a fresh number"
            ),
        )
        line = json.dumps(last)
        print(line, flush=True)
        print("bench: emitted stale last-good capture: " + line,
              file=sys.stderr, flush=True)
        if archive:
            # the stale fallback exits via os._exit (atexit never runs),
            # so the self-ingest happens here — the archive records the
            # re-emission FLAGGED stale, exactly the r03–r05 trajectory
            _self_ingest(archive, [last])
        os._exit(0)
    except (OSError, ValueError) as e:
        print(f"bench: no last-good capture available ({e})",
              file=sys.stderr, flush=True)
        os._exit(3)


def run_serve(
    cfg: BenchConfig, n_requests: int, *, max_batch: int = 8,
    tiny: bool = False,
) -> dict:
    """Serving micro-bench (``--serve``): drive the continuous-batching
    engine (``tpu_dist/serve``) with a bursty deterministic arrival
    pattern on the REAL clock and report the serving axis of the bench
    trajectory — ``requests_per_s`` (the headline ``value``),
    ``latency_p50_ms``/``latency_p99_ms`` (histogram upper bounds) and
    ``batch_occupancy`` — with the standard capture fingerprint, so a
    stale re-emission of a serving number is auto-flagged exactly like
    a training one. ``tiny`` swaps in a narrow ResNet for CPU-emulation
    validation (the measurement shape is the config's model)."""
    t0 = time.perf_counter()
    from tpu_dist.nn import resnet18, resnet34, resnet50
    from tpu_dist.obs import counters as counters_lib
    from tpu_dist.serve.engine import ServingEngine

    counters_lib.reset()
    if tiny:
        from tpu_dist.serve.drill import _drill_model

        model, image, classes, name = _drill_model(), 16, 10, "tiny"
    else:
        models = {
            "resnet18": resnet18, "resnet34": resnet34, "resnet50": resnet50,
        }
        if cfg.model not in models:
            raise ValueError(
                f"--serve benches the dense image models, got {cfg.model!r}"
            )
        model = models[cfg.model](num_classes=cfg.num_classes)
        image, classes, name = cfg.image_size, cfg.num_classes, cfg.model
    import jax

    params, bn_state = model.init(jax.random.PRNGKey(0))
    engine = ServingEngine(model, params, bn_state, max_batch=max_batch)
    engine.warmup((image, image, 3))
    rng = np.random.default_rng(0)
    payloads = rng.standard_normal(
        (min(n_requests, 64), image, image, 3)
    ).astype(np.float32)
    t_meas = time.perf_counter()
    submitted = 0
    done = 0
    burst_idx = 0
    while done < n_requests:
        if submitted < n_requests:
            # bursty arrivals: alternate 3- and 7-request bursts so the
            # batcher genuinely exercises several buckets
            burst = (3, 7)[burst_idx % 2]
            burst_idx += 1
            for _ in range(min(burst, n_requests - submitted)):
                engine.submit(payloads[submitted % len(payloads)],
                              id=submitted)
                submitted += 1
        done += len(engine.pump())
    meas_s = max(time.perf_counter() - t_meas, 1e-9)
    stats = engine.stats
    total_s = time.perf_counter() - t0
    return _stamped({
        "metric": f"serve_{name}_throughput",
        "value": round(done / meas_s, 1),
        "unit": "requests/sec",
        "requests_per_s": round(done / meas_s, 1),
        "latency_p50_ms": round((stats.total.quantile_bound(0.5) or 0) * 1e3, 3),
        "latency_p99_ms": round((stats.total.quantile_bound(0.99) or 0) * 1e3, 3),
        "ttfb_p99_ms": round((stats.ttfb.quantile_bound(0.99) or 0) * 1e3, 3),
        "batch_occupancy": round(stats.batch_occupancy() or 0.0, 4),
        "requests": done,
        "batches": stats.batches,
        "max_batch": max_batch,
        "image_size": image,
        "num_classes": classes,
        "retraces": counters_lib.get("compile.retraces"),
        "goodput_frac": round(meas_s / total_s, 4),
    })


def main() -> None:
    import os

    p = argparse.ArgumentParser()
    p.add_argument("--config", default="resnet18_cifar100", choices=sorted(CONFIGS))
    p.add_argument("--all", action="store_true", help="run every config (one line each)")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--warmup", type=int, default=10)
    p.add_argument(
        "--batch_size", type=int, default=0,
        help="override the config's global batch (0 = config default); "
             "probing the throughput/MFU-vs-batch curve without editing "
             "CONFIGS",
    )
    p.add_argument(
        "--init_timeout", type=float,
        default=float(os.environ.get("BENCH_INIT_TIMEOUT", "600")),
    )
    p.add_argument(
        "--lock_wait", type=float,
        default=float(os.environ.get("BENCH_LOCK_WAIT", "600")),
        help="seconds to wait for the machine-wide TPU lock before giving "
             "up with exit 4; a bounded probe/watcher releases it within "
             "its own timeout, so waiting beats instant refusal (round-3 "
             "driver bench died rc=4 exactly this way)",
    )
    p.add_argument(
        "--table", action="store_true",
        help="emit the reference README's comparison table (markdown), one "
             "row per training mode, measured on the visible devices",
    )
    p.add_argument(
        "--pp", type=int, default=0,
        help="pipeline-parallel bench: split ViT-B/16 into N stages over a "
             "(data x pipe) mesh; reports throughput + bubble_fraction "
             "(run with CPU device-count emulation on single-chip hosts)",
    )
    p.add_argument("--pp_interleave", type=int, default=1)
    p.add_argument(
        "--pp_dims", choices=("b16", "tiny"), default="b16",
        help="tiny swaps in a small ViT for schedule validation on CPU "
             "emulation; b16 is the measurement shape",
    )
    p.add_argument(
        "--pp_microbatches", type=int, default=0,
        help="microbatches M >= stages (0 = one per stage); larger M "
             "shrinks the bubble (S-1)/(vM+S-1)",
    )
    p.add_argument(
        "--attn", type=int, default=0, metavar="S",
        help="long-sequence attention micro-bench at sequence length S: "
             "Pallas flash kernel vs the XLA path, fwd+bwd (the "
             "long-context showcase; try 1024/4096/16384)",
    )
    p.add_argument(
        "--attn_all", action="store_true",
        help="run the attention micro-bench at S=1024, 4096, 16384 "
             "(one line each)",
    )
    p.add_argument("--attn_batch", type=int, default=0,
                   help="batch for --attn (0 = ~32k tokens/step)")
    p.add_argument(
        "--profile_dir", default="",
        help="capture an XLA/TPU profile of the measured steps to this dir "
             "(TensorBoard profile tab; single-config mode only)",
    )
    p.add_argument("--causal", action="store_true",
                   help="causal masking for --attn")
    p.add_argument(
        "--grad_compression",
        choices=("none", "bf16", "int8", "int8_ef", "sweep"),
        default="none",
        help="gradient wire format for the measured step; 'sweep' runs the "
             "config once per mode (one JSON line each) reporting "
             "wire_bytes_per_step from the static jaxpr audit (works on "
             "CPU emulation) alongside measured throughput",
    )
    p.add_argument(
        "--ckpt",
        choices=("off", "sync", "async", "sweep"),
        default="off",
        help="sharded-checkpoint drill: measure step-loop blocking time "
             "per save (ckpt_blocked_ms) for the synchronous vs the "
             "snapshot-then-write (--async_ckpt) composition; 'sweep' runs "
             "both, prints the blocking ratio, and exits 2 if the "
             "injected-EIO probe through the async drain comes back dead "
             "(the TD120 CLI gate)",
    )
    p.add_argument(
        "--serve", action="store_true",
        help="serving micro-bench: drive the continuous-batching engine "
             "(tpu_dist/serve) with bursty arrivals and emit "
             "requests_per_s / latency_p50_ms / latency_p99_ms / "
             "batch_occupancy as one fingerprinted bench record — the "
             "serving axis of the bench trajectory",
    )
    p.add_argument("--serve_requests", type=int, default=256,
                   help="requests driven through the engine (--serve)")
    p.add_argument("--serve_max_batch", type=int, default=8,
                   help="bucket-ladder top (--serve; power of two)")
    p.add_argument(
        "--serve_tiny", action="store_true",
        help="narrow-ResNet serving bench for CPU-emulation validation "
             "(the measurement shape is the config's model)",
    )
    p.add_argument(
        "--scaling", action="store_true",
        help="run the config on 1,2,4,...,N-device meshes and report "
             "scaling efficiency (BASELINE's 1→8→32 chip metric; limited "
             "by visible devices)",
    )
    p.add_argument(
        "--archive", default=None, metavar="PATH",
        help="self-ingest every emitted record into this longitudinal "
             "archive at exit (python -m tpu_dist.obs archive / trend; "
             "never-dies — an archive failure is counted to stderr, "
             "not fatal to the bench)",
    )
    args = p.parse_args()
    if args.archive:
        import atexit

        # normal exits (and sys.exit) archive whatever _stamped emitted;
        # the os._exit stale-fallback path self-ingests inline instead
        atexit.register(_self_ingest, args.archive)
    if args.batch_size:
        import dataclasses

        CONFIGS.update(
            {
                name: dataclasses.replace(c, global_batch=args.batch_size)
                for name, c in CONFIGS.items()
            }
        )

    # One-TPU-process rule: wait (bounded) for the machine-wide lock, then
    # refuse (exit 4, clear holder message) rather than start a second PJRT
    # client and wedge the tunnel. Must run before any backend init. No-op
    # when the platform is forced to CPU.
    from tpu_dist.comm import tpu_lock

    tpu_lock.guard_or_exit("bench", wait_s=args.lock_wait)

    # persistent XLA compile cache: repeat bench invocations skip the
    # ~20-40s first-compile cost
    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_comp_cache")

    _guarded_backend_init(
        args.init_timeout,
        archive=args.archive,
        default_invocation=(
            args.config == "resnet18_cifar100"
            and args.grad_compression == "none"
            and args.ckpt == "off"
            and not (args.all or args.table or args.scaling or args.pp
                     or args.attn or args.attn_all or args.profile_dir
                     or args.serve)
        ),
    )
    if args.ckpt != "off" and not args.table:
        import sys

        modes = ("sync", "async") if args.ckpt == "sweep" else (args.ckpt,)
        recs = {}
        for m in modes:
            recs[m] = run_ckpt(CONFIGS[args.config], args.warmup, m)
            print(json.dumps(recs[m]), flush=True)
        if args.ckpt == "sweep":
            ratio = recs["sync"]["ckpt_blocked_ms"] / max(
                recs["async"]["ckpt_blocked_ms"], 1e-9
            )
            print(json.dumps(_stamped({
                "metric": "sharded_ckpt_blocking_ratio",
                "value": round(ratio, 2),
                "unit": "x (sync blocked / async blocked)",
            })), flush=True)
        dead = [m for m, r in recs.items() if r.get("ckpt_eio_probe") == "dead"]
        if dead:
            print(
                "bench --ckpt: injected EIO came back CLEAN through the "
                "async drain — the TD120 fault detector is dead",
                file=sys.stderr,
            )
            sys.exit(2)
        return
    if args.serve:
        print(json.dumps(run_serve(
            CONFIGS[args.config], args.serve_requests,
            max_batch=args.serve_max_batch, tiny=args.serve_tiny,
        )), flush=True)
        return
    if args.attn or args.attn_all:
        lengths = (1024, 4096, 16384) if args.attn_all else (args.attn,)
        for s in lengths:
            print(json.dumps(run_attn(
                s, args.steps, args.warmup,
                batch=args.attn_batch, causal=args.causal,
            )), flush=True)
        return
    if args.pp:
        cfg_name = args.config if args.config.startswith("vit") else "vit_b16_imagenet"
        print(json.dumps(run_pp(
            CONFIGS[cfg_name], args.steps, args.warmup,
            args.pp, args.pp_interleave, args.pp_microbatches,
            dims=args.pp_dims,
        )))
        return
    if args.table:
        # reference README comparison-table parity (README.md:59-77): one
        # row per training mode, same model/dataset, epoch seconds
        rows = [
            ("dataparallel (DP ≡ DDP on TPU)", "resnet18_cifar100_fp32"),
            ("distributed + bf16 (apex path)", "resnet18_cifar100"),
            ("grad accumulation ×4", "resnet18_cifar100_ga4"),
            ("fused epoch (device-resident)", "resnet18_cifar100_fused"),
        ]
        from tpu_dist.obs.memory import fmt_bytes

        print("| mode | sec/epoch | images/sec | MFU | goodput | peak HBM "
              "| ckpt blocked/save | vs 4x2080Ti DDP+apex |")
        print("|---|---|---|---|---|---|---|---|")
        for label, name in rows:
            out = run(CONFIGS[name], args.steps, args.warmup)
            mfu = out.get("mfu")
            gp = out.get("goodput_frac")
            # XLA's static per-executable accounting (memory_analysis) —
            # already in every bench record; CPU-valid, so the memory
            # column gates even while the TPU tunnel is down
            hbm = out.get("peak_hbm_bytes")
            # checkpoint-blocking column: a short sharded-save drill per
            # row when --ckpt is given ('sweep' shows sync→async, the
            # two-phase protocol's before/after); 'n/a' keeps the default
            # table invocation's cost unchanged
            if args.ckpt == "off":
                ck = "n/a"
            elif args.ckpt == "sweep":
                cs = run_ckpt(CONFIGS[name], 2, "sync", saves=3)
                ca = run_ckpt(CONFIGS[name], 2, "async", saves=3)
                ck = (f"{cs['ckpt_blocked_ms']:.0f}→"
                      f"{ca['ckpt_blocked_ms']:.0f} ms")
            else:
                cr = run_ckpt(CONFIGS[name], 2, args.ckpt, saves=3)
                ck = f"{cr['ckpt_blocked_ms']:.0f} ms ({args.ckpt})"
            print(
                f"| {label} | {out['sec_per_epoch']} | {out['value']} "
                f"| {f'{mfu:.1%}' if mfu is not None else 'n/a'} "
                f"| {f'{gp:.1%}' if gp is not None else 'n/a'} "
                f"| {fmt_bytes(hbm) if hbm is not None else 'n/a'} "
                f"| {ck} "
                f"| {out['vs_baseline']}x |"
            )
        return
    if args.grad_compression == "sweep":
        # per-mode wire bytes (static, exact) + throughput, one line each —
        # the measured counterpart of the TD104 audit ratios
        for mode in ("none", "bf16", "int8", "int8_ef"):
            print(json.dumps(run(
                CONFIGS[args.config], args.steps, args.warmup,
                grad_compression=mode,
            )), flush=True)
        return
    if args.scaling:
        n = len(jax.devices())
        sizes = [s for s in (1, 2, 4, 8, 16, 32) if s <= n]
        base = None
        for s in sizes:
            out = run(CONFIGS[args.config], args.steps, args.warmup, n_devices=s)
            if base is None:
                base = out["value"]
            out["scaling_efficiency"] = round(out["value"] / (base * s), 3)
            print(json.dumps(out))
    elif args.all:
        for name in sorted(CONFIGS):
            try:
                print(json.dumps(run(CONFIGS[name], args.steps, args.warmup)),
                      flush=True)
            except Exception as e:  # e.g. RESOURCE_EXHAUSTED on the
                # 1024px XLA-attention config: record it, keep sweeping
                print(json.dumps({
                    "metric": f"{name}_train_throughput", "value": None,
                    "unit": "images/sec",
                    "error": f"{type(e).__name__}: {(str(e).splitlines() or [''])[0][:200]}",
                }), flush=True)
    else:
        print(json.dumps(run(
            CONFIGS[args.config], args.steps, args.warmup,
            profile_dir=args.profile_dir or None,
            grad_compression=args.grad_compression,
        )))


if __name__ == "__main__":
    main()
