"""Benchmark: ResNet-18 / CIFAR-100 training throughput on TPU.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.

Baseline (BASELINE.md): the reference's best configuration, DDP + apex on
4×RTX 2080 Ti, 14.5 s/epoch on CIFAR-100's 50,000 train images ≈ 3,448
img/s aggregate. ``vs_baseline`` is our aggregate images/sec over that
number (>1.0 = faster than the whole 4-GPU reference rig).

Runs on whatever devices are visible (1 real TPU chip under the driver;
any emulated mesh otherwise). Measures the steady-state compiled train
step, reference hyperparameters (global batch 256, SGD+momentum, SyncBN on,
bf16 compute — the apex-AMP-equivalent path).
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_IMG_PER_SEC = 50_000 / 14.5  # DDP+apex, 4x2080Ti (README.md:77)
CIFAR_TRAIN = 50_000


def main() -> None:
    import jax
    import jax.numpy as jnp

    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.nn import resnet18
    from tpu_dist.train.optim import SGD
    from tpu_dist.train.state import TrainState
    from tpu_dist.train.step import make_train_step

    mesh = mesh_lib.data_parallel_mesh()
    n_dev = int(mesh.devices.size)
    batch = 256
    if batch % n_dev:
        batch = n_dev * max(1, batch // n_dev)

    model = resnet18(num_classes=100)
    optimizer = SGD(momentum=0.9, weight_decay=1e-4)
    params, bn_state = model.init(jax.random.PRNGKey(0))
    state = jax.device_put(
        TrainState.create(params, bn_state, optimizer), mesh_lib.replicated(mesh)
    )
    step = make_train_step(
        model.apply, optimizer, mesh, sync_bn=True, compute_dtype=jnp.bfloat16
    )

    rng = np.random.default_rng(0)
    images = mesh_lib.shard_batch(
        mesh, rng.normal(size=(batch, 32, 32, 3)).astype(np.float32)
    )
    labels = mesh_lib.shard_batch(mesh, rng.integers(0, 100, batch).astype(np.int32))

    # warmup (compile + cache)
    for _ in range(10):
        state, metrics = step(state, images, labels, 0.1)
    jax.block_until_ready(state.params)

    n_steps = 100
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = step(state, images, labels, 0.1)
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0

    img_per_sec = batch * n_steps / dt
    sec_per_epoch = CIFAR_TRAIN / img_per_sec
    print(
        json.dumps(
            {
                "metric": "resnet18_cifar100_train_throughput",
                "value": round(img_per_sec, 1),
                "unit": "images/sec",
                "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 3),
                "sec_per_epoch": round(sec_per_epoch, 2),
                "n_devices": n_dev,
                "global_batch": batch,
                "img_per_sec_per_chip": round(img_per_sec / n_dev, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
