"""Run-telemetry subsystem (tpu_dist/obs): span tracing, counters,
heartbeat, straggler detection, the summarize/export-trace CLI, and the
TD106 telemetry-is-a-noop jaxpr gate."""

import json
import threading

import numpy as np
import pytest

from tpu_dist.obs import counters, spans
from tpu_dist.obs.heartbeat import Heartbeat, read as heartbeat_read
from tpu_dist.obs.straggler import epoch_skew
from tpu_dist.obs.summarize import (
    export_trace,
    format_text,
    load_records,
    summarize,
)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Spans/counters are process-global; isolate every test."""
    spans.disable()
    spans.drain()
    counters.reset()
    yield
    spans.disable()
    spans.drain()
    counters.reset()


# -- spans ------------------------------------------------------------------


def test_span_nesting_and_chrome_export(tmp_path):
    spans.enable()
    with spans.span("outer", epoch=1):
        with spans.span("inner/a"):
            pass
        with spans.span("inner/b", step=2):
            pass
    evts = spans.events()
    by_name = {e["name"]: e for e in evts}
    assert set(by_name) == {"outer", "inner/a", "inner/b"}
    # complete events close innermost-first; nesting is interval containment
    outer, a, b = by_name["outer"], by_name["inner/a"], by_name["inner/b"]
    for inner in (a, b):
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert a["ts"] + a["dur"] <= b["ts"]  # sequential siblings stay ordered
    assert outer["args"] == {"epoch": 1}
    # export: structurally valid Chrome trace-event JSON (Perfetto contract:
    # top-level traceEvents list; each event name/ph/ts/dur/pid/tid)
    path = spans.export_chrome_trace(str(tmp_path / "trace.json"))
    trace = json.loads(open(path).read())
    assert isinstance(trace["traceEvents"], list) and len(trace["traceEvents"]) == 3
    for e in trace["traceEvents"]:
        assert e["ph"] == "X"
        assert isinstance(e["name"], str)
        for k in ("ts", "dur", "pid", "tid"):
            assert isinstance(e[k], (int, float)), (k, e)


def test_spans_disabled_record_nothing():
    with spans.span("nope"):
        pass
    spans.add_event("also_nope", 0.0, 1.0)
    assert spans.events() == []


def test_spans_drain_clears_and_caps(monkeypatch):
    spans.enable()
    for i in range(5):
        with spans.span(f"s{i}"):
            pass
    got = spans.drain()
    assert [e["name"] for e in got] == [f"s{i}" for i in range(5)]
    assert spans.events() == []
    # overflow: drops are counted, never silent
    monkeypatch.setattr(spans, "MAX_EVENTS", 2)
    for i in range(4):
        with spans.span(f"t{i}"):
            pass
    assert len(spans.events()) == 2
    assert spans.dropped() == 2
    assert spans.to_chrome_trace()["metadata"]["tpu_dist_dropped_events"] == 2


# -- counters ---------------------------------------------------------------


def test_counter_thread_safety_exact_totals():
    n_threads, n_incs = 8, 2000

    def worker():
        for _ in range(n_incs):
            counters.inc("t.hits")
            counters.add_seconds("t.secs", 0.001)

    ts = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert counters.get("t.hits") == n_threads * n_incs
    assert abs(counters.get("t.secs") - n_threads * n_incs * 0.001) < 1e-6


def test_counters_under_live_loader_producer():
    """The loader's producer THREAD writes the registry concurrently with
    the consumer; totals must come out exact."""
    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.data import DataLoader, DistributedSampler

    mesh = mesh_lib.data_parallel_mesh()
    n = 64
    images = np.random.default_rng(0).normal(size=(n, 4, 4, 3)).astype(np.float32)
    labels = np.zeros(n, np.int32)
    sampler = DistributedSampler(n, 1, 0, shuffle=False)
    loader = DataLoader(images, labels, 16, sampler, mesh)
    seen = 0
    for _ in range(2):  # two epochs: counters accumulate across iterations
        for _batch in loader:
            counters.inc("test.consumer_side")
            seen += 1
    assert counters.get("loader.batches_produced") == seen
    assert counters.get("loader.batches_consumed") == seen
    assert counters.get("test.consumer_side") == seen
    assert counters.get("loader.data_wait_s") >= 0.0


def test_counter_delta_and_gauges():
    counters.inc("a", 3)
    counters.set_gauge("mode", "int8")
    first = counters.snapshot()
    counters.inc("a", 2)
    counters.inc("b")
    d = counters.delta(first, counters.snapshot())
    assert d == {"a": 2, "b": 1}  # gauge strings and zero deltas omitted
    assert counters.snapshot()["mode"] == "int8"


# -- heartbeat --------------------------------------------------------------


def test_heartbeat_advances_and_sweeps(tmp_path):
    path = str(tmp_path / "hb" / "heartbeat.json")
    hb = Heartbeat(path, min_interval=0.0)
    assert hb.beat(epoch=0, step=1)
    first = heartbeat_read(path)
    assert first["counter"] == 1 and first["epoch"] == 0 and first["step"] == 1
    assert hb.beat(epoch=0, step=2)
    second = heartbeat_read(path)
    assert second["counter"] == 2 and second["mono_s"] >= first["mono_s"]
    hb.sweep()
    assert heartbeat_read(path) is None


def test_heartbeat_throttle_and_force(tmp_path):
    path = str(tmp_path / "hb.json")
    hb = Heartbeat(path, min_interval=3600.0)
    assert hb.beat(epoch=0, step=0)          # first write always lands
    assert not hb.beat(epoch=0, step=1)      # inside the throttle window
    assert heartbeat_read(path)["counter"] == 1
    assert hb.beat(epoch=0, step=2, force=True)  # force bypasses
    assert heartbeat_read(path)["counter"] == 3  # counter never skipped


@pytest.mark.slow  # >10s e2e (two trainer compiles): excluded from the
# timed tier-1 gate; the unit heartbeat tests above and the e2e summarize
# run below keep gate coverage of this subsystem
def test_trainer_heartbeat_step_grain_and_clean_exit_sweep(tmp_path):
    from tests.helpers import tiny_resnet
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer, register_model

    register_model("tiny_obs_hb", lambda num_classes=10: tiny_resnet(num_classes))
    hb_path = str(tmp_path / "heartbeat.json")
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_obs_hb", num_classes=10,
        batch_size=64, epochs=1, steps_per_epoch=3, eval_every=0,
        synthetic_n=640, log_every=10, heartbeat_file=hb_path, seed=0,
    )
    trainer = Trainer(cfg)
    # step-grain advance: drive one epoch with the heartbeat attached
    trainer._heartbeat = Heartbeat(hb_path, min_interval=0.0)
    trainer.train_epoch(0)
    rec = heartbeat_read(hb_path)
    assert rec is not None and rec["counter"] == 3 and rec["step"] == 2
    # clean fit() exit sweeps the file — its absence is the "done" signal
    trainer._heartbeat = None
    trainer.fit()
    assert heartbeat_read(hb_path) is None


# -- straggler --------------------------------------------------------------


def test_straggler_skew_warning_multiprocess(capsys):
    """Multi-process epoch-skew detection via the injectable allgather:
    rows are per-process (epoch_time, stall_frac) exactly as a 4-host
    run's collective would return them."""
    rows = np.array([[10.0, 0.02], [10.2, 0.03], [25.0, 0.61], [9.9, 0.01]])
    rec = epoch_skew(10.0, 0.02, epoch=7, threshold=1.5, allgather=lambda row: rows)
    assert rec["straggler"] is True
    assert rec["worst_rank"] == 2
    assert rec["skew"] == pytest.approx(25.0 / np.median(rows[:, 0]), rel=1e-3)
    out = capsys.readouterr().out
    assert "straggler" in out and "process 2" in out and "(epoch 7)" in out
    assert counters.get("straggler.epochs_flagged") == 1


def test_straggler_quiet_when_balanced(capsys):
    rows = np.array([[10.0, 0.1], [10.5, 0.1], [9.8, 0.1]])
    rec = epoch_skew(10.0, 0.1, threshold=1.5, allgather=lambda row: rows)
    assert rec["straggler"] is False
    assert "straggler" not in capsys.readouterr().out


def test_straggler_single_process_trivial():
    rec = epoch_skew(12.5, 0.05, threshold=1.5)  # real (trivial) allgather
    assert rec["skew"] == 1.0 and rec["straggler"] is False
    assert rec["epoch_times"] == [12.5]


# -- MetricsHistory schema --------------------------------------------------


def test_history_schema_run_id_rel_s_and_counters(tmp_path):
    from tpu_dist.metrics.history import MetricsHistory

    counters.inc("x.hits", 4)
    path = str(tmp_path / "h.jsonl")
    with MetricsHistory(path, run_id="cfg1234-99") as h:
        h.log("train_epoch", epoch=0, loss=np.float32(1.5))
        counters.inc("x.hits")
        h.log("eval", epoch=0, top1=10.0)
    lines = [json.loads(l) for l in open(path)]
    assert len(lines) == 2
    for rec in lines:
        assert rec["schema_version"] == 15  # v15: causal decision tracing (ISSUE 19)
        assert rec["run_id"] == "cfg1234-99"
        assert isinstance(rec["rel_s"], float) and rec["rel_s"] >= 0
        assert "ts" in rec
    assert lines[0]["counters"]["x.hits"] == 4
    assert lines[1]["counters"]["x.hits"] == 5
    h.log("late", v=1)  # after close: silently disabled, never crashes
    assert len(open(path).readlines()) == 2


# -- StepTimer percentiles --------------------------------------------------


def test_step_timer_percentiles():
    from tpu_dist.obs.profile import StepTimer

    t = StepTimer(warmup_steps=1)
    t.tick()
    t.laps = [0.01 * (i + 1) for i in range(100)]  # deterministic laps
    p = t.percentiles()
    assert p["p50"] == pytest.approx(0.50)
    assert p["p95"] == pytest.approx(0.95)
    assert p["p99"] == pytest.approx(0.99)
    assert StepTimer(warmup_steps=5).percentiles() is None


# -- summarize / export-trace CLI ------------------------------------------


def _canned_jsonl(tmp_path):
    recs = [
        {"ts": 1.0, "rel_s": 5.0, "schema_version": 2, "run_id": "r-1",
         "kind": "train_epoch", "epoch": 0, "loss": 2.5,
         "epoch_time": 5.0, "images_per_sec": 1000.0,
         "step_time_p50": 0.010, "step_time_p95": 0.020,
         "step_time_p99": 0.040, "data_stall_frac": 0.25,
         "counters": {"ckpt.writes": 1, "loader.batches_consumed": 10}},
        {"ts": 2.0, "rel_s": 6.0, "schema_version": 2, "run_id": "r-1",
         "kind": "eval", "epoch": 0, "top1": 40.0, "top5": 80.0, "loss": 2.2},
        {"ts": 2.5, "rel_s": 8.0, "schema_version": 3, "run_id": "r-1",
         "kind": "device_stats", "epoch": 1, "step": 0,
         "grad_norm": 1.5, "param_norm": 12.0, "update_ratio": 0.003,
         "nonfinite_grads": 0.0},
        {"ts": 2.6, "rel_s": 9.0, "schema_version": 3, "run_id": "r-1",
         "kind": "device_stats", "epoch": 1, "step": 2,
         "grad_norm": 7.0, "param_norm": 12.1, "update_ratio": 0.009,
         "nonfinite_grads": 0.0},
        {"ts": 2.7, "rel_s": 9.1, "schema_version": 3, "run_id": "r-1",
         "kind": "anomaly", "epoch": 1, "step": 2,
         "anomaly": "grad_norm_explosion", "value": 7.0, "median": 1.5,
         "ratio": 4.667, "threshold": 4.0},
        {"ts": 3.0, "rel_s": 11.0, "schema_version": 3, "run_id": "r-1",
         "kind": "train_epoch", "epoch": 1, "loss": 2.0, "mfu": 0.42,
         "epoch_time": 4.0, "images_per_sec": 1250.0,
         "step_time_p50": 0.009, "step_time_p95": 0.015,
         "step_time_p99": 0.030, "data_stall_frac": 0.10,
         "counters": {"ckpt.writes": 3, "loader.batches_consumed": 20}},
        {"ts": 3.5, "rel_s": 11.2, "schema_version": 2, "run_id": "r-1",
         "kind": "straggler", "epoch": 1, "skew": 2.1, "worst_rank": 3,
         "max_s": 8.4, "median_s": 4.0},
        {"ts": 4.0, "rel_s": 12.0, "schema_version": 2, "run_id": "r-1",
         "kind": "spans",
         "events": [{"name": "ckpt/write", "ph": "X", "ts": 100.0,
                     "dur": 50.0, "pid": 0, "tid": 1}]},
    ]
    path = tmp_path / "run.jsonl"
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
        f.write('{"torn": tr')  # killed writer mid-line: tolerated
    return str(path)


def test_summarize_golden(tmp_path):
    path = _canned_jsonl(tmp_path)
    records, bad = load_records(path)
    assert len(records) == 8 and bad == 1
    report = summarize(records, bad)
    assert report["run_id"] == "r-1"
    assert report["totals"]["n_epochs"] == 2
    e0, e1 = report["epochs"]
    assert e0["images_per_sec"] == 1000.0 and e0["val_top1"] == 40.0
    assert e1["step_time_p99_s"] == 0.030 and e1["data_stall_frac"] == 0.10
    # counter deltas: first epoch from zero, second from the first snapshot
    assert e0["counter_deltas"] == {"ckpt.writes": 1, "loader.batches_consumed": 10}
    assert e1["counter_deltas"] == {"ckpt.writes": 2, "loader.batches_consumed": 10}
    assert report["stragglers"] == [
        {"epoch": 1, "skew": 2.1, "worst_rank": 3, "max_s": 8.4, "median_s": 4.0}
    ]
    # v3 health layer: per-epoch device_stats rollup, anomaly list, MFU
    assert "device_stats" not in e0 and e0["mfu"] is None
    assert e1["device_stats"] == {
        "samples": 2, "grad_norm_last": 7.0, "grad_norm_max": 7.0,
        "update_ratio_last": 0.009, "param_norm_last": 12.1,
    }
    assert e1["mfu"] == 0.42
    assert report["totals"]["mfu_mean"] == pytest.approx(0.42)
    assert report["anomalies"] == [{
        "epoch": 1, "step": 2, "anomaly": "grad_norm_explosion",
        "value": 7.0, "median": 1.5, "ratio": 4.667,
    }]
    text = format_text(report)
    assert "run r-1" in text and "1 unparsable line(s)" in text
    assert "straggler: epoch 1 process 3 at 2.1x median" in text
    assert "ckpt.writes+2" in text  # epoch-1 delta line
    assert "device: grad_norm last 7 / max 7" in text
    assert "anomaly: epoch 1 step 2 grad_norm_explosion value 7.0" in text
    assert "mean MFU 0.42" in text


def test_summarize_resets_deltas_at_resume_boundary():
    """Appending a resumed run (fresh run_id, fresh counter registry) to
    the same --log_file must not produce negative cross-run deltas."""
    records = [
        {"kind": "train_epoch", "epoch": 0, "run_id": "a-1",
         "epoch_time": 1.0, "counters": {"ckpt.writes": 5}},
        {"kind": "train_epoch", "epoch": 1, "run_id": "b-2",  # resumed
         "epoch_time": 1.0, "counters": {"ckpt.writes": 2}},
    ]
    report = summarize(records)
    e0, e1 = report["epochs"]
    assert e0["counter_deltas"] == {"ckpt.writes": 5}
    assert e1["counter_deltas"] == {"ckpt.writes": 2}  # NOT -3


def test_export_trace_offsets_resumed_run_segments():
    """A resumed run's restarted clock (fresh run_id, rel_s back to ~0)
    must be shifted past the first segment, not overlap it at ts≈0."""
    records = [
        {"kind": "train_epoch", "epoch": 0, "run_id": "a-1",
         "rel_s": 10.0, "epoch_time": 10.0},
        {"kind": "spans", "run_id": "a-1", "rel_s": 10.5,
         "events": [{"name": "ckpt/write", "ph": "X", "ts": 10.2e6,
                     "dur": 1e5, "pid": 0, "tid": 1}]},
        {"kind": "train_epoch", "epoch": 1, "run_id": "b-2",  # resumed
         "rel_s": 8.0, "epoch_time": 8.0},
    ]
    trace = export_trace(records)
    by_name = {e["name"]: e for e in trace["traceEvents"]}
    assert by_name["train_epoch/0"]["ts"] == pytest.approx(0.0)
    # segment b starts after everything in segment a (>= 10.5s here)
    resumed = by_name["train_epoch/1"]
    assert resumed["ts"] >= 10.5e6
    assert resumed["ts"] + resumed["dur"] >= 18.0e6


def test_summarize_cli_json_and_export_trace(tmp_path, capsys):
    from tpu_dist.obs.__main__ import main as obs_main

    path = _canned_jsonl(tmp_path)
    assert obs_main(["summarize", path, "--format", "json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["totals"]["n_epochs"] == 2
    out = str(tmp_path / "trace.json")
    assert obs_main(["export-trace", path, "-o", out]) == 0
    trace = json.loads(open(out).read())
    names = [e["name"] for e in trace["traceEvents"]]
    assert "ckpt/write" in names          # spans record passed through
    assert "train_epoch/0" in names       # synthesized epoch bar
    for e in trace["traceEvents"]:        # structurally Perfetto-loadable
        assert e["ph"] == "X" and isinstance(e["ts"], (int, float))
        assert isinstance(e["dur"], (int, float))
    # epoch bar reconstructed from rel_s: ends at rel_s, spans epoch_time
    bar = next(e for e in trace["traceEvents"] if e["name"] == "train_epoch/0")
    assert bar["ts"] == pytest.approx(0.0) and bar["dur"] == pytest.approx(5.0e6)
    assert obs_main(["summarize", str(tmp_path / "missing.jsonl")]) == 2


# -- TD106 + fetch-count parity --------------------------------------------


def test_td106_telemetry_noop_gate():
    from tpu_dist.analysis.jaxpr_audit import telemetry_noop_violations

    assert telemetry_noop_violations() == []


def test_td106_rule_registered():
    from tpu_dist.analysis.rules import RULES

    assert "TD106" in RULES and "TD007" in RULES


@pytest.mark.slow  # >10s e2e (two full fits): excluded from the timed
# tier-1 gate; runs in the CI observability step and the full suite
def test_trainer_fetch_count_unchanged_by_telemetry(tmp_path, monkeypatch):
    """Arming spans/counters/heartbeat must not add per-step device
    transfers: the _fetch_metrics call count is identical telemetry-on vs
    telemetry-off (acceptance criterion of the obs subsystem)."""
    from tests.helpers import tiny_resnet
    from tpu_dist.config import TrainConfig
    from tpu_dist.train import trainer as trainer_mod

    trainer_mod.register_model(
        "tiny_obs_fetch", lambda num_classes=10: tiny_resnet(num_classes)
    )
    calls = []
    real_fetch = trainer_mod._fetch_metrics
    monkeypatch.setattr(
        trainer_mod, "_fetch_metrics",
        lambda m: (calls.append(1), real_fetch(m))[1],
    )
    counts = []
    for armed in (False, True):
        calls.clear()
        cfg = TrainConfig(
            dataset="synthetic", model="tiny_obs_fetch", num_classes=10,
            batch_size=64, epochs=1, steps_per_epoch=4, eval_every=0,
            synthetic_n=640, log_every=2, seed=0,
            log_file=str(tmp_path / "armed.jsonl") if armed else None,
            heartbeat_file=str(tmp_path / "hb.json") if armed else None,
        )
        trainer_mod.Trainer(cfg).fit()
        counts.append(len(calls))
    assert counts[0] == counts[1], counts


# -- e2e: acceptance run ----------------------------------------------------


@pytest.mark.slow  # ~10 s full-fit e2e; CI observability step runs it
# without the slow filter (ISSUE 7 tier-1 budget)
def test_e2e_short_run_summarize_reports_everything(tmp_path, capsys):
    """The acceptance path: a short CPU run with --log_file, then
    `python -m tpu_dist.obs summarize` reports per-epoch throughput,
    p50/p95/p99, stall fraction, and counter deltas; export-trace output
    is valid trace-event JSON."""
    from tests.helpers import tiny_resnet
    from tpu_dist.config import TrainConfig
    from tpu_dist.obs.__main__ import main as obs_main
    from tpu_dist.train.trainer import Trainer, register_model

    register_model("tiny_obs_e2e", lambda num_classes=10: tiny_resnet(num_classes))
    log = str(tmp_path / "run.jsonl")
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_obs_e2e", num_classes=10,
        batch_size=64, epochs=2, steps_per_epoch=3, eval_every=1,
        synthetic_n=640, log_every=2, log_file=log,
        ckpt_dir=str(tmp_path / "ckpt"), save_every=1, seed=0,
    )
    Trainer(cfg).fit()
    capsys.readouterr()
    assert obs_main(["summarize", log, "--format", "json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["totals"]["n_epochs"] == 2
    for row in report["epochs"]:
        assert row["images_per_sec"] > 0
        assert row["step_time_p50_s"] > 0
        assert row["step_time_p95_s"] >= row["step_time_p50_s"]
        assert row["step_time_p99_s"] >= row["step_time_p95_s"]
        assert 0.0 <= row["data_stall_frac"] < 1.0
        assert row["counter_deltas"]["train.steps"] == 3
    # the checkpoint writes show up as counter deltas
    total_ckpt = sum(
        r["counter_deltas"].get("ckpt.writes", 0) for r in report["epochs"]
    )
    assert total_ckpt >= 1
    out = str(tmp_path / "trace.json")
    assert obs_main(["export-trace", log, "-o", out]) == 0
    trace = json.loads(open(out).read())
    assert len(trace["traceEvents"]) > 0
    names = {e["name"] for e in trace["traceEvents"]}
    assert "train/dispatch" in names or "train/compile+dispatch" in names
    assert "ckpt/write" in names
