"""Rematerialization (jax.checkpoint) leaves numerics bit-identical."""

import jax
import numpy as np

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tpu_dist.train.step import make_train_step
from tests.helpers import TinyConvNet


def test_remat_matches_plain():
    mesh = mesh_lib.data_parallel_mesh()
    model = TinyConvNet()
    opt = SGD()
    params, bn = model.init(jax.random.PRNGKey(0))
    state0 = jax.device_put(TrainState.create(params, bn, opt), mesh_lib.replicated(mesh))

    rng = np.random.default_rng(0)
    x = mesh_lib.shard_batch(mesh, rng.normal(size=(64, 8, 8, 3)).astype(np.float32))
    y = mesh_lib.shard_batch(mesh, rng.integers(0, 10, 64).astype(np.int32))

    outs = {}
    for remat in (False, True):
        step = make_train_step(model.apply, opt, mesh, donate=False, remat=remat)
        s, m = step(state0, x, y, 0.1)
        outs[remat] = (float(m["loss"]), jax.device_get(s.params))

    np.testing.assert_allclose(outs[True][0], outs[False][0], rtol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(outs[True][1]), jax.tree_util.tree_leaves(outs[False][1])
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)


def test_remat_composes_with_grad_accum_and_bf16():
    import jax.numpy as jnp

    mesh = mesh_lib.data_parallel_mesh()
    model = TinyConvNet()
    opt = SGD()
    params, bn = model.init(jax.random.PRNGKey(0))
    state = jax.device_put(TrainState.create(params, bn, opt), mesh_lib.replicated(mesh))
    step = make_train_step(
        model.apply, opt, mesh, donate=False, remat=True,
        grad_accum_steps=2, compute_dtype=jnp.bfloat16,
    )
    rng = np.random.default_rng(1)
    x = mesh_lib.shard_batch(mesh, rng.normal(size=(64, 8, 8, 3)).astype(np.float32))
    y = mesh_lib.shard_batch(mesh, rng.integers(0, 10, 64).astype(np.int32))
    s, m = step(state, x, y, 0.1)
    assert np.isfinite(float(m["loss"]))
