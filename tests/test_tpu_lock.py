"""One-TPU-process lockfile guard (VERDICT r2 #1).

The suite runs CPU-forced, so ``acquire()`` with default ``force_cpu_ok``
is a documented no-op here; the lock mechanics are exercised with
``force_cpu_ok=False``. Cross-process exclusion and crash-release are
tested against REAL subprocess holders (flock semantics, not simulated
PID files — the file contents are advisory, the kernel lock is the truth).
"""

import os
import signal
import subprocess
import sys
import time

import pytest

from tpu_dist.comm import tpu_lock

_HOLDER_SRC = """
import sys, time
from tpu_dist.comm import tpu_lock
lock = tpu_lock.acquire(owner="subproc_holder", path=sys.argv[1], force_cpu_ok=False)
print("HELD", flush=True)
time.sleep(float(sys.argv[2]) if len(sys.argv) > 2 else 60)
"""


def _spawn_holder(lock_path, hold_s=60.0):
    proc = subprocess.Popen(
        [sys.executable, "-c", _HOLDER_SRC, str(lock_path), str(hold_s)],
        stdout=subprocess.PIPE,
        text=True,
        cwd="/root/repo",
    )
    assert proc.stdout.readline().strip() == "HELD"
    return proc


@pytest.fixture
def lock_path(tmp_path):
    return str(tmp_path / "tpu.lock")


@pytest.fixture(autouse=True)
def _clear_held():
    # isolate the process-local reentrancy state between tests (force:
    # drop the flock regardless of leftover refcounts)
    for lock in list(tpu_lock._held.values()):
        lock.release(force=True)
    yield
    for lock in list(tpu_lock._held.values()):
        lock.release(force=True)


def test_cpu_forced_is_noop(lock_path):
    # conftest forces jax_platforms=cpu -> acquiring is a no-op
    assert tpu_lock.tpu_possible() is False
    assert tpu_lock.acquire(owner="t", path=lock_path) is None
    assert not os.path.exists(lock_path)


def test_acquire_writes_pid_and_owner(lock_path):
    lock = tpu_lock.acquire(owner="bench", path=lock_path, force_cpu_ok=False)
    assert lock is not None
    with open(lock_path) as f:
        pid_line, owner_line = f.read().splitlines()[:2]
    assert int(pid_line) == os.getpid()
    assert owner_line == "bench"
    lock.release()


def test_reentrant_same_process(lock_path):
    a = tpu_lock.acquire(owner="trainer", path=lock_path, force_cpu_ok=False)
    b = tpu_lock.acquire(owner="bench", path=lock_path, force_cpu_ok=False)
    assert b is a  # second acquire in the same process: same handle
    a.release()
    a.release()  # balanced: one per acquire


def test_nested_release_keeps_outer_claim(lock_path):
    """ADVICE r3 (medium): a nested claimant (Trainer inside bench.py) whose
    construction fails releases only ITS claim — the outer holder keeps the
    machine-wide lock, so a contender process is still refused."""
    outer = tpu_lock.acquire(owner="bench", path=lock_path, force_cpu_ok=False)
    inner = tpu_lock.acquire(owner="trainer", path=lock_path, force_cpu_ok=False)
    inner.release()  # the failed-Trainer path
    assert not outer._released
    # a second process must STILL be refused: the flock is held
    rc = subprocess.run(
        [
            sys.executable,
            "-c",
            "import sys\n"
            "from tpu_dist.comm import tpu_lock\n"
            "tpu_lock.acquire(owner='x', path=sys.argv[1], force_cpu_ok=False)\n",
            lock_path,
        ],
        cwd="/root/repo",
        capture_output=True,
        text=True,
    )
    assert rc.returncode != 0 and "TPULockError" in rc.stderr
    outer.release()  # last claim out: flock drops
    assert outer._released
    rc = subprocess.run(
        [
            sys.executable,
            "-c",
            "import sys\n"
            "from tpu_dist.comm import tpu_lock\n"
            "assert tpu_lock.acquire(owner='x', path=sys.argv[1], force_cpu_ok=False)\n",
            lock_path,
        ],
        cwd="/root/repo",
        capture_output=True,
        text=True,
    )
    assert rc.returncode == 0, rc.stderr


def test_wait_s_acquires_once_holder_exits(lock_path):
    """The round-3 driver-bench failure: landing mid-probe must wait the
    bounded holder out, not refuse instantly."""
    holder = _spawn_holder(lock_path, hold_s=1.5)
    try:
        t0 = time.monotonic()
        lock = tpu_lock.acquire(
            owner="bench", path=lock_path, force_cpu_ok=False, wait_s=30
        )
        assert lock is not None
        assert time.monotonic() - t0 < 29  # won as soon as the holder died
        lock.release()
    finally:
        holder.kill()
        holder.wait()


def test_wait_s_deadline_still_refuses(lock_path):
    holder = _spawn_holder(lock_path, hold_s=60)
    try:
        with pytest.raises(tpu_lock.TPULockError) as ei:
            tpu_lock.acquire(
                owner="bench", path=lock_path, force_cpu_ok=False, wait_s=1
            )
        assert "waited 1s" in str(ei.value)
    finally:
        holder.kill()
        holder.wait()


def test_live_holder_refused_with_clear_message(lock_path):
    holder = _spawn_holder(lock_path)
    try:
        with pytest.raises(tpu_lock.TPULockError) as ei:
            tpu_lock.acquire(owner="me", path=lock_path, force_cpu_ok=False)
        msg = str(ei.value)
        assert str(holder.pid) in msg and "subproc_holder" in msg
        assert "Refusing" in msg
    finally:
        holder.kill()
        holder.wait()


def test_clean_exit_releases_for_next_process(lock_path):
    holder = _spawn_holder(lock_path, hold_s=0.2)
    holder.wait()
    lock = tpu_lock.acquire(owner="next", path=lock_path, force_cpu_ok=False)
    assert lock is not None
    lock.release()


def test_sigkilled_holder_does_not_block(lock_path):
    # the round-1/2 failure mode: a SIGKILLed TPU process must not leave a
    # stale lock — flock is kernel-released on process death
    holder = _spawn_holder(lock_path)
    holder.send_signal(signal.SIGKILL)
    holder.wait()
    deadline = time.time() + 5
    lock = None
    while time.time() < deadline:
        try:
            lock = tpu_lock.acquire(owner="next", path=lock_path, force_cpu_ok=False)
            break
        except tpu_lock.TPULockError:
            time.sleep(0.05)
    assert lock is not None, "lock not released after holder SIGKILL"
    lock.release()


def test_release_then_reacquire_same_process(lock_path):
    a = tpu_lock.acquire(owner="a", path=lock_path, force_cpu_ok=False)
    a.release()
    b = tpu_lock.acquire(owner="b", path=lock_path, force_cpu_ok=False)
    assert b is not None and b is not a
    b.release()


def test_reentrant_guard_is_per_path(lock_path, tmp_path):
    a = tpu_lock.acquire(owner="a", path=lock_path, force_cpu_ok=False)
    other = str(tmp_path / "other.lock")
    b = tpu_lock.acquire(owner="a2", path=other, force_cpu_ok=False)
    assert b is not None and b is not a  # different path -> real new lock
    # re-acquiring the FIRST path again must still be the no-op handle,
    # not a self-refusal via a second open file description
    a2 = tpu_lock.acquire(owner="a3", path=lock_path, force_cpu_ok=False)
    assert a2 is a
    a.release()
    b.release()


def test_reentrancy_normalizes_path_spelling(lock_path):
    a = tpu_lock.acquire(owner="a", path=lock_path, force_cpu_ok=False)
    alias = os.path.dirname(lock_path) + "//" + os.path.basename(lock_path)
    b = tpu_lock.acquire(owner="b", path=alias, force_cpu_ok=False)
    assert b is a  # same inode via another spelling: no self-refusal
    a.release()


def test_unopenable_lock_raises_lock_error(lock_path, monkeypatch):
    # EACCES on open (another user's lockfile) must be a clean TPULockError
    # refusal, not a traceback; chmod can't simulate it under root, so
    # patch the open call
    def deny(*a, **k):
        raise PermissionError(13, "Permission denied")

    monkeypatch.setattr(tpu_lock.os, "open", deny)
    with pytest.raises(tpu_lock.TPULockError) as ei:
        tpu_lock.acquire(owner="x", path=lock_path, force_cpu_ok=False)
    assert "cannot open TPU lock" in str(ei.value)


def test_context_manager_releases(lock_path):
    with tpu_lock.acquire(owner="cm", path=lock_path, force_cpu_ok=False):
        # lock is held: a contender must be refused
        with pytest.raises(tpu_lock.TPULockError):
            rc = subprocess.run(
                [
                    sys.executable,
                    "-c",
                    "import sys\n"
                    "from tpu_dist.comm import tpu_lock\n"
                    "tpu_lock.acquire(owner='x', path=sys.argv[1], force_cpu_ok=False)\n",
                    lock_path,
                ],
                cwd="/root/repo",
                capture_output=True,
                text=True,
            )
            if rc.returncode != 0 and "TPULockError" in rc.stderr:
                raise tpu_lock.TPULockError(rc.stderr)
    # after exit: a fresh process can take it
    rc = subprocess.run(
        [
            sys.executable,
            "-c",
            "import sys\n"
            "from tpu_dist.comm import tpu_lock\n"
            "assert tpu_lock.acquire(owner='x', path=sys.argv[1], force_cpu_ok=False)\n",
            lock_path,
        ],
        cwd="/root/repo",
        capture_output=True,
    )
    assert rc.returncode == 0, rc.stderr


def test_guard_or_exit_exits_4(lock_path):
    holder = _spawn_holder(lock_path)
    try:
        orig_path, orig_fn = tpu_lock.DEFAULT_LOCK_PATH, tpu_lock.tpu_possible
        tpu_lock.DEFAULT_LOCK_PATH = lock_path
        tpu_lock.tpu_possible = lambda: True  # simulate a TPU-possible run
        try:
            with pytest.raises(SystemExit) as ei:
                tpu_lock.guard_or_exit("bench")
            assert ei.value.code == 4
        finally:
            tpu_lock.DEFAULT_LOCK_PATH = orig_path
            tpu_lock.tpu_possible = orig_fn
    finally:
        holder.kill()
        holder.wait()


def test_trainer_cpu_config_does_not_contend(tmp_path):
    # integration: constructing a Trainer under the CPU-forced suite must
    # not create the machine lock (no contention with a real TPU run)
    from tests.helpers import tiny_resnet
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer, register_model

    register_model("tiny_resnet", lambda num_classes=10: tiny_resnet(num_classes))
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet", num_classes=10,
        batch_size=64, epochs=1, steps_per_epoch=2, synthetic_n=128,
        ckpt_dir=str(tmp_path),
    )
    existed_before = os.path.exists(tpu_lock.DEFAULT_LOCK_PATH)
    t = Trainer(cfg)
    assert t._tpu_lock is None
    # no lockfile created by this CPU-forced construction (the path may
    # pre-exist from a real TPU run on this machine — flock files persist)
    assert os.path.exists(tpu_lock.DEFAULT_LOCK_PATH) == existed_before


def test_failed_trainer_construction_releases_lock(tmp_path, monkeypatch):
    """A constructor that raises (config validation) must not hold the TPU
    lock for the rest of the process (code-review r3)."""
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer

    acquired, released = [], []

    class FakeLock:
        def release(self):
            released.append(1)

    def fake_acquire(owner="x", path=None, force_cpu_ok=True):
        acquired.append(owner)
        return FakeLock()

    monkeypatch.setattr(tpu_lock, "acquire", fake_acquire)
    cfg = TrainConfig(
        dataset="synthetic", model="vit_tiny", num_classes=10, batch_size=32,
        sync_bn=False, fsdp=True, flash_attention=True,  # guarded combo
    )
    with pytest.raises(ValueError, match="flash_attention"):
        Trainer(cfg)
    assert acquired and released  # lock taken, then given back on the raise
