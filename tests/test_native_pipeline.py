"""Native C++ input pipeline (tpu_dist/csrc) vs the numpy reference path."""

import numpy as np
import pytest

from tpu_dist.data import native, synthetic_cifar, transforms


@pytest.fixture(scope="module")
def data():
    return synthetic_cifar(2_000, 100, seed=3)[0]


def test_eval_path_matches_numpy_exactly(data):
    idx = np.arange(0, 2_000, 7)
    out = native.gather_augment(data, idx, seed=0, train=False)
    np.testing.assert_allclose(out, transforms.normalize(data[idx]), atol=1e-6)


def test_train_path_deterministic_per_seed(data):
    idx = np.arange(256)
    a = native.gather_augment(data, idx, seed=42, train=True)
    b = native.gather_augment(data, idx, seed=42, train=True)
    c = native.gather_augment(data, idx, seed=43, train=True)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)


def test_train_crops_stay_in_padded_window(data):
    # constant image: every output pixel is either the constant (normalized)
    # or zero-padding (normalized 0)
    const = np.full((4, 32, 32, 3), 200, np.uint8)
    out = native.gather_augment(const, np.arange(4), seed=1, train=True)
    norm_const = (200 / 255.0 - transforms.CIFAR100_MEAN) / transforms.CIFAR100_STD
    norm_zero = (0.0 - transforms.CIFAR100_MEAN) / transforms.CIFAR100_STD
    for ch in range(3):
        vals = out[..., ch].ravel()
        ok = np.isclose(vals, norm_const[ch], atol=1e-5) | np.isclose(
            vals, norm_zero[ch], atol=1e-5
        )
        assert ok.all()


def test_gather_uses_indices(data):
    idx = np.array([5, 5, 9])
    out = native.gather_augment(data, idx, seed=0, train=False)
    np.testing.assert_array_equal(out[0], out[1])
    assert not np.array_equal(out[0], out[2])


def test_fallback_matches_when_lib_absent(data, monkeypatch):
    monkeypatch.setattr(native, "_load", lambda: None)
    idx = np.arange(64)
    out = native.gather_augment(data, idx, seed=0, train=False)
    np.testing.assert_allclose(out, transforms.normalize(data[idx]), atol=1e-6)
