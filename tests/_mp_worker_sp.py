"""Worker for the multi-host × sequence-parallel RING-FLASH test.

Launched by tests/test_multihost.py as 2 processes × 4 CPU devices: one
8-device global mesh laid out ``[data=2, seq=4]`` HOST-MAJOR, so every
seq group (the ring's ppermute neighborhood) is intra-host while the data
axis crosses hosts (the DCN side of the split). The local attention tile
runs the Pallas kernels in interpret mode — the full ring-flash
composition (ops/flash_attention.py::ring_flash_attention) across process
boundaries. The same ``run_sp_training`` is also called by the parent
test in-process (1 process × 8 devices) as the reference.

Usage: python tests/_mp_worker_sp.py <coordinator> <num_procs> <proc_id>
"""

import os
import sys

if __name__ == "__main__":
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    ).strip()

import jax  # noqa: E402

if __name__ == "__main__":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def _to_host(x) -> np.ndarray:
    if getattr(x, "is_fully_addressable", True):
        return np.asarray(jax.device_get(x))
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def run_sp_training():
    """Train a tiny ViT 3 steps with ring-flash sequence parallelism on a
    [data=2, seq=4] mesh built from ALL global devices; returns
    (loss, replicated-leaf fingerprint)."""
    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.nn.vit import ViTDef
    from tpu_dist.train.optim import SGD
    from tpu_dist.train.state import TrainState
    from tpu_dist.train.step import make_train_step

    n = jax.device_count()
    mesh = mesh_lib.device_mesh([n // 4, 4], ["data", "seq"])

    model = ViTDef(image_size=32, patch_size=4, dim=32, depth=2, heads=2,
                   num_classes=5)
    opt = SGD()
    params, s = model.init(jax.random.PRNGKey(0))
    st = TrainState.create(params, s, opt)
    state = TrainState(
        params=mesh_lib.place_host_tree(mesh, st.params),
        bn_state=mesh_lib.place_host_tree(mesh, st.bn_state),
        opt_state=mesh_lib.place_host_tree(mesh, st.opt_state),
        step=mesh_lib.place_host_tree(mesh, st.step),
    )
    step = make_train_step(
        model.apply, opt, mesh, sync_bn=False, donate=False,
        seq_axis="seq", model_kwargs={"attn_impl": "flash"},
    )

    rng = np.random.default_rng(0)
    all_x = rng.normal(size=(8, 32, 32, 3)).astype(np.float32)
    all_y = rng.integers(0, 5, 8).astype(np.int32)
    per = all_x.shape[0] // jax.process_count()
    lo = jax.process_index() * per
    xs = mesh_lib.shard_batch(mesh, all_x[lo:lo + per])
    ys = mesh_lib.shard_batch(mesh, all_y[lo:lo + per])

    for _ in range(3):
        state, metrics = step(state, xs, ys, 0.05)
    loss = float(_to_host(metrics["loss"]))
    fp = float(_to_host(state.params["patch"]["w"]).sum())
    return loss, fp


def main(coordinator: str, num_procs: int, proc_id: int) -> None:
    from tpu_dist.comm import mesh as mesh_lib

    mesh_lib.initialize_distributed(coordinator, num_procs, proc_id)
    assert jax.process_count() == num_procs
    assert jax.local_device_count() == 4
    loss, fp = run_sp_training()
    print(f"SPRESULT {proc_id} {loss:.6f} {fp:.6f}", flush=True)


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
