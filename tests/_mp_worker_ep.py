"""Worker for the multi-host × expert-parallel test.

Launched by tests/test_multihost.py as 2 processes × 4 CPU devices: one
8-device global mesh laid out ``[data=4, expert=2]`` HOST-MAJOR, so each
ep=2 expert group (and its all_to_all dispatch) is intra-host — the
ICI side of the ICI/DCN split. The same ``run_ep_training`` also runs
in the parent test in-process (1 × 8 devices) as the reference; loss,
replicated leaves and expert-sharded leaves must agree across layouts.

Usage: python tests/_mp_worker_ep.py <coordinator> <num_procs> <proc_id>
"""

import os
import sys

if __name__ == "__main__":
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    ).strip()

import jax  # noqa: E402

if __name__ == "__main__":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def _to_host(x) -> np.ndarray:
    if getattr(x, "is_fully_addressable", True):
        return np.asarray(jax.device_get(x))
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def run_ep_training():
    """Train the tiny MoE ViT 3 steps on a [data, expert=2] mesh over ALL
    global devices; returns (loss, replicated fingerprint, expert-sharded
    fingerprint)."""
    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.nn.vit_moe import vit_moe_tiny
    from tpu_dist.train.optim import SGD
    from tpu_dist.train.state import TrainState
    from tpu_dist.train.step import make_train_step

    n = jax.device_count()
    mesh = mesh_lib.device_mesh([n // 2, 2], ["data", "expert"])
    assert mesh_lib.model_axes_intra_host(mesh, ["expert"]), (
        "host-major mesh must keep expert groups intra-host"
    )

    model = vit_moe_tiny(num_classes=5)
    specs = model.ep_param_specs("expert")
    opt = SGD()
    params, s = model.init(jax.random.PRNGKey(0))
    st = TrainState.create(params, s, opt)
    state = TrainState(
        params=mesh_lib.place_host_tree(mesh, st.params, specs),
        bn_state=mesh_lib.place_host_tree(mesh, st.bn_state),
        opt_state=mesh_lib.place_host_tree(mesh, st.opt_state, specs),
        step=mesh_lib.place_host_tree(mesh, st.step),
    )
    step = make_train_step(
        model.apply, opt, mesh, sync_bn=False, donate=False,
        ep_axis="expert", param_specs=specs,
    )

    rng = np.random.default_rng(0)
    all_x = rng.normal(size=(16, 32, 32, 3)).astype(np.float32)
    all_y = rng.integers(0, 5, 16).astype(np.int32)
    # under ep>1 the batch shards over EVERY device ([data, expert] axes);
    # each process feeds its host-major slice of the global batch
    per = all_x.shape[0] // jax.process_count()
    lo = jax.process_index() * per
    axes = ("data", "expert")
    xs = mesh_lib.shard_batch(mesh, all_x[lo:lo + per], axis=axes)
    ys = mesh_lib.shard_batch(mesh, all_y[lo:lo + per], axis=axes)

    for _ in range(3):
        state, metrics = step(state, xs, ys, 0.05)
    loss = float(_to_host(metrics["loss"]))
    fp_rep = float(_to_host(state.params["patch"]["b"]).sum())
    # an expert-sharded leaf: first block's expert MLP input weights
    fp_ep = float(_to_host(state.params["blocks"][0]["moe"]["w_in"]).sum())
    return loss, fp_rep, fp_ep


def main(coordinator: str, num_procs: int, proc_id: int) -> None:
    from tpu_dist.comm import mesh as mesh_lib

    mesh_lib.initialize_distributed(coordinator, num_procs, proc_id)
    assert jax.process_count() == num_procs
    assert jax.local_device_count() == 4
    loss, fp_rep, fp_ep = run_ep_training()
    print(f"EPRESULT {proc_id} {loss:.6f} {fp_rep:.6f} {fp_ep:.6f}", flush=True)


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
