"""Smoke matrix: common flag combinations must train one finite step."""

import numpy as np
import pytest

from tpu_dist.config import TrainConfig
from tpu_dist.train.trainer import Trainer, register_model
from tests.helpers import tiny_resnet

register_model("tiny_resnet_m", lambda num_classes=10: tiny_resnet(num_classes))

COMBOS = [
    dict(bf16=True, grad_accu_steps=2),
    dict(bf16=True, shard_weight_update=True),
    dict(sync_bn=False, grad_accu_steps=2, label_smoothing=0.1),
    dict(bf16=True, grad_clip_norm=1.0, lr_schedule="cosine", warmup_epochs=1),
    dict(fused_optimizer=True, bf16=True),
    dict(bf16=True, grad_compression="bf16", grad_clip_norm=1.0),
    dict(grad_compression="bf16", shard_weight_update=True),
]


@pytest.mark.parametrize("combo", COMBOS, ids=[",".join(c) for c in COMBOS])
def test_flag_combo_trains(combo):
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_m", num_classes=10,
        batch_size=64, epochs=1, steps_per_epoch=2, log_every=1,
        eval_every=0, lr=0.05, synthetic_n=640, **combo,
    )
    out = Trainer(cfg).train_epoch(0)
    assert np.isfinite(out["loss"]), combo


PARALLEL_COMBOS = [
    dict(model="vit_tiny", sp=4, grad_accu_steps=2, sync_bn=False, batch_size=32),
    dict(model="vit_tiny", tp=4, grad_accu_steps=2, sync_bn=False, batch_size=32),
    dict(model="vit_moe_tiny", ep=4, grad_accu_steps=2, sync_bn=False, batch_size=32),
    dict(model="vit_tiny", sp=4, bf16=True, remat=True, sync_bn=False, batch_size=32),
    dict(model="vit_tiny", sp=4, grad_compression="bf16", sync_bn=False,
         batch_size=32),
    dict(model="vit_moe_tiny", ep=4, grad_compression="bf16", sync_bn=False,
         batch_size=32),
]


@pytest.mark.parametrize(
    "combo", PARALLEL_COMBOS,
    ids=["sp+ga", "tp+ga", "ep+ga", "sp+bf16+remat", "sp+gradcomp", "ep+gradcomp"],
)
def test_parallel_axes_compose_with_accum(combo):
    cfg = TrainConfig(
        dataset="synthetic", num_classes=10, epochs=1, steps_per_epoch=2,
        log_every=1, eval_every=0, lr=0.05, synthetic_n=320, **combo,
    )
    out = Trainer(cfg).train_epoch(0)
    assert np.isfinite(out["loss"]), combo
