"""Layer 4 (the ``--auto_shard`` planner) tested: search determinism (a
plan is a pure function of its inputs), the HBM-budget refusal matrix
through the typed ``--memory_check`` path, the calibration-gauge pricing
arithmetic, the TD118 plan-must-verify gate + the ``--inject-miscost``
dead-detector contract, the TD119 history/compare gate, the plan_report
schema round-trip with the forward-compat (skip-with-count) loader, the
registry pins (planner overrides vs step.py families, rules vs docs),
and the CLI exit contracts."""

import json
import os
import subprocess
import sys

import pytest

from tpu_dist.analysis import planner, shardlint
from tpu_dist.analysis.planner import PlanReportError
from tpu_dist.analysis.rules import RULES

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# explicit per-device budgets for the refusal matrix (bytes). The audit
# MLP's static ledger is ~3.8KiB/dev plain-DP and ~2.1KiB/dev under
# ZeRO-1, so 3000 B splits the two and 1000 B refuses both; computed
# budgets in the tests derive from the measured entries, these are only
# the coarse grid.
_BIG = 10**9


@pytest.fixture(scope="module")
def dp_report():
    """One shard-report over three families, shared by every pricing
    test in the module (compiling is the expensive part; planning from
    a report is pure arithmetic)."""
    report, violations = shardlint.build_shard_report(
        names=["dp_sgd", "zero1_sgd", "dp_int8"]
    )
    assert report["skips"] == {}
    assert violations == []
    return report


# -- search determinism ------------------------------------------------------


def test_build_plan_is_deterministic(dp_report):
    """Same inputs, same plan — byte-for-byte. No wall clock, no dict
    order, no RNG anywhere in the search."""
    kw = dict(shard_report=dp_report, hbm_budget_bytes=_BIG)
    a = planner.build_plan(**kw)
    b = planner.build_plan(**kw)
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)
    # ranking is (predicted_step_s, family): sorted and 1-based
    ranks = [r["rank"] for r in a["candidates"]]
    assert ranks == list(range(1, len(ranks) + 1))
    preds = [r["predicted_step_s"] for r in a["candidates"]]
    assert preds == sorted(preds)
    assert a["chosen"]["family"] == a["candidates"][0]["family"]
    assert a["schema"] == planner.SCHEMA


def test_plan_candidates_excludes_serve_and_oversized():
    names = planner.plan_candidates(8)
    assert "serve_eval" not in names  # serve prices a different objective
    assert "dp_sgd" in names and "zero1_sgd" in names
    # a 1-device "mesh" can't host the model-parallel families
    assert "tp_vit" not in planner.plan_candidates(1)
    assert names == sorted(names)


def test_applyable_only_restricts_to_train_overrides(dp_report):
    plan = planner.build_plan(
        shard_report=dp_report, hbm_budget_bytes=_BIG, applyable_only=True
    )
    for row in plan["candidates"]:
        assert row["applyable"]
        assert row["family"] in planner.FAMILY_TRAIN_OVERRIDES


# -- the HBM refusal matrix (the typed --memory_check path) ------------------


def test_hbm_budget_refusal_matrix(dp_report):
    from tpu_dist.obs import memory as memory_lib

    fams = dp_report["families"]
    dp_req = fams["dp_sgd"]["hbm"]["static_bytes_per_device"]
    z1_req = fams["zero1_sgd"]["hbm"]["static_bytes_per_device"]
    assert z1_req < dp_req  # ZeRO-1 shards the momentum

    # budget between the two (with headroom 0.9): dp refused, zero1 kept
    split = int(z1_req / 0.9) + 8
    assert split * 0.9 < dp_req
    plan = planner.build_plan(shard_report=dp_report, hbm_budget_bytes=split)
    assert "dp_sgd" in plan["refused"]
    assert plan["chosen"]["family"] == "zero1_sgd"
    # the refusal rode the REAL typed path, with its arithmetic recorded
    why = plan["refused"]["dp_sgd"]
    assert why["error"].startswith("InfeasibleMemoryError")
    assert why["required_bytes"] == dp_req
    assert why["budget_bytes"] == split
    assert plan["counts"]["refused"] == len(plan["refused"]) >= 1

    # budget below everything: every candidate refused, chosen is None —
    # counted, never silently dropped
    none = planner.build_plan(shard_report=dp_report, hbm_budget_bytes=64)
    assert none["chosen"] is None
    assert none["candidates"] == []
    assert set(none["refused"]) == {"dp_sgd", "zero1_sgd", "dp_int8"}

    # and the planner refuses through the SAME callable --memory_check
    # uses: the typed error, directly
    with pytest.raises(memory_lib.InfeasibleMemoryError):
        memory_lib.preflight_check(
            dp_req, budget_bytes=64, headroom=0.9, action="refuse"
        )


# -- pricing arithmetic (calibration-gauge correction) -----------------------


def test_price_candidate_gauge_arithmetic():
    """The documented model, checked against hand arithmetic:
    ``max(flops/Fr, bytes/Br) + wire/Br * (1 - overlap)`` with the
    cost model's 4-significant-digit rounding."""
    entry = {
        "hlo": {"bytes": 10**7, "by_kind": {
            "all-reduce": {"ops": 2, "elems": 100, "bytes": 10**7},
        }},
        "cost": {"flops_per_step": 2e9, "bytes_per_step": 1e8},
        "hbm": {"static_bytes_per_device": 1234},
        "mesh": "dp8",
    }
    gauges = {
        "cost.calibration_flops_per_s": 1e12,
        "cost.calibration_bytes_per_s": 1e10,
        "cost.calibration_overlap_frac": 0.5,
    }
    row = planner.price_candidate("dp_sgd", entry, n_devices=8, gauges=gauges)
    # compute 2e-3 s, memory 1e-2 s (dominates), comm 1e-3 s half-hidden
    assert row["predicted_step_s"] == pytest.approx(1e-2 + 0.5e-3)
    assert row["predicted"]["rate_source"] == "calibrated"
    assert row["wire_bytes"] == 10**7
    assert row["static_bytes_per_device"] == 1234
    assert row["priced_inventory"] == {
        "all-reduce": {"ops": 2, "elems": 100, "bytes": 10**7},
    }
    assert row["applyable"]


def test_pricing_gauges_defaults_vs_calibrated():
    g, source = planner.pricing_gauges()
    assert source == "uncalibrated-defaults"
    assert g["cost.calibration_flops_per_s"] == pytest.approx(1.0e12)
    # an explicit measured rate flips the stamp
    g2, source2 = planner.pricing_gauges(
        {"cost.calibration_bytes_per_s": 5e9}
    )
    assert source2 == "calibrated"
    assert g2["cost.calibration_bytes_per_s"] == pytest.approx(5e9)
    # a live published calibration flips it too (and is restored after)
    from tpu_dist.obs import counters as counters_lib

    counters_lib.set_gauge("cost.calibration_flops_per_s", 3e12)
    try:
        g3, source3 = planner.pricing_gauges()
        assert source3 == "calibrated"
        assert g3["cost.calibration_flops_per_s"] == pytest.approx(3e12)
    finally:
        counters_lib.reset()


def test_uncalibrated_defaults_make_cpu_plans_priceable(dp_report):
    """On CPU emulation chip_peak_flops() is None — without the fixed
    default rates nothing would price. Every candidate in a defaults
    plan is priced, and the report SAYS the rates were defaults."""
    plan = planner.build_plan(shard_report=dp_report, hbm_budget_bytes=_BIG)
    assert plan["gauge_source"] == "uncalibrated-defaults"
    assert plan["counts"]["candidates"] == 3
    for row in plan["candidates"]:
        assert row["predicted_step_s"] > 0


# -- TD118: plan-must-verify + the inject-miscost probe ----------------------


@pytest.fixture(scope="module")
def verified_plan(dp_report):
    plan = planner.build_plan(
        shard_report=dp_report, hbm_budget_bytes=_BIG,
        names=["dp_sgd", "zero1_sgd"],
    )
    probe, violations = planner.verify_plan(plan)
    return plan, probe, violations


def test_td118_clean_plan_verifies(verified_plan):
    plan, probe, violations = verified_plan
    assert violations == [], [v.format_text() for v in violations]
    assert probe["verified"] is True
    assert probe["family"] == plan["chosen"]["family"]
    assert probe["priced"] == probe["compiled"]
    assert probe["priced_wire_bytes"] == probe["compiled_wire_bytes"]


def test_td118_inject_miscost_must_be_caught(verified_plan):
    plan, _, _ = verified_plan
    bad = planner.inject_miscost(plan)
    # the original is untouched (deep copy)
    assert bad["chosen"]["wire_bytes"] != plan["chosen"]["wire_bytes"]
    probe, violations = planner.verify_plan(bad)
    assert violations, "the TD118 detector is dead"
    assert probe["verified"] is False
    assert all(v.rule == "TD118" for v in violations)
    assert any("wire" in v.message for v in violations)
    # the violation path names the plan, not a file
    assert violations[0].path.startswith("<plan:")


def test_td118_no_chosen_plan_is_not_verified():
    probe, violations = planner.verify_plan({"chosen": None})
    assert violations == []
    assert probe["verified"] is None


# -- TD119: planner-error-tracked --------------------------------------------


def test_planner_error_frac_arithmetic():
    from tpu_dist.obs import costmodel

    assert costmodel.planner_error_frac(1.0, 1.0) == 0.0
    assert costmodel.planner_error_frac(1.5, 1.0) == pytest.approx(0.5)
    assert costmodel.planner_error_frac(0.5, 1.0) == pytest.approx(0.5)
    # unpriceable / unmeasured → None (a skipped gate, never a fake 0)
    assert costmodel.planner_error_frac(None, 1.0) is None
    assert costmodel.planner_error_frac(1.0, None) is None
    assert costmodel.planner_error_frac(0.0, 1.0) is None
    assert costmodel.planner_error_frac(1.0, -2.0) is None


def test_td119_direction_registered_and_gates():
    from tpu_dist.obs import compare

    assert compare.direction_of("planner_error_frac") == ("lower", 0.02)
    assert any(m == "planner_error_frac" for m, _, _ in compare.REPORT_METRICS)
    assert any(f == "planner_error_frac" for f, _, _ in compare.BENCH_FIELDS)
    # drift growing past threshold+slack REGRESSES...
    base = {"planner_error_frac": 0.10}
    cand = {"planner_error_frac": 0.40}
    res = compare.compare_scalars(base, cand, threshold=0.05)
    rows = {r["metric"]: r for r in res["rows"]}
    assert rows["planner_error_frac"]["verdict"] == "REGRESSED"
    assert res["regressions"] >= 1
    # ...self-compare is clean...
    res0 = compare.compare_scalars(base, dict(base), threshold=0.05)
    assert {r["metric"]: r for r in res0["rows"]}[
        "planner_error_frac"]["verdict"] == "ok"
    # ...and SHRINKING drift is an improvement, never flagged
    res1 = compare.compare_scalars(cand, base, threshold=0.05)
    assert {r["metric"]: r for r in res1["rows"]}[
        "planner_error_frac"]["verdict"] == "ok"


def test_td119_plan_records_fold_into_summarize_and_scalars():
    from tpu_dist.obs import compare, summarize

    records = [
        {"kind": "train_epoch", "schema_version": 12, "epoch": 0,
         "loss": 2.0, "epoch_time_s": 10.0, "images_per_sec": 100.0},
        # the fit()-start announcement...
        {"kind": "plan", "schema_version": 12, "epoch": 0,
         "family": "zero1_sgd", "mode": "apply", "applied": True,
         "predicted_step_s": 4.3e-7, "gauge_source": "uncalibrated-defaults"},
        # ...superseded by the post-profile TD119 drift record
        {"kind": "plan", "schema_version": 12, "epoch": 0,
         "family": "zero1_sgd", "mode": "apply",
         "predicted_step_s": 4.3e-7, "achieved_step_s": 5.0e-7,
         "planner_error_frac": 0.14},
    ]
    report = summarize.summarize(records)
    assert len(report["plan_records"]) == 2
    assert report["plan"]["family"] == "zero1_sgd"
    assert report["plan"]["planner_error_frac"] == pytest.approx(0.14)
    scal = compare.report_scalars(report)
    assert scal["planner_error_frac"] == pytest.approx(0.14)
    # a plan-less log keeps the scalar None (skipped, never faked)
    plain = summarize.summarize(records[:1])
    assert plain["plan"] is None
    assert compare.report_scalars(plain)["planner_error_frac"] is None
    # the drift line shows up in the text rendering
    assert "planner_error_frac=0.14" in summarize.format_text(report)


# -- plan_report.json round-trip + forward compat ----------------------------


def test_plan_report_roundtrip(tmp_path, dp_report):
    plan = planner.build_plan(shard_report=dp_report, hbm_budget_bytes=_BIG)
    path = str(tmp_path / "plan_report.json")
    planner.save_plan_report(plan, path)
    loaded = planner.load_plan_report(path)
    assert loaded["schema"] == planner.SCHEMA
    assert loaded["chosen"]["family"] == plan["chosen"]["family"]
    assert "load_notes" not in loaded

    # a foreign tag is a typed, loud error
    bad = dict(plan, schema="shard_report_v1")
    with open(str(tmp_path / "foreign.json"), "w") as f:
        json.dump(bad, f)
    with pytest.raises(PlanReportError, match="not a plan_report"):
        planner.load_plan_report(str(tmp_path / "foreign.json"))

    # SAME-version candidate missing pricing keys = corruption, not
    # forward compat: still the hard typed error
    broken = json.loads(json.dumps(plan))
    del broken["candidates"][0]["priced_inventory"]
    with open(str(tmp_path / "broken.json"), "w") as f:
        json.dump(broken, f)
    with pytest.raises(PlanReportError, match="missing"):
        planner.load_plan_report(str(tmp_path / "broken.json"))


def test_plan_report_newer_schema_tolerated_with_count(tmp_path, dp_report):
    """Satellite: a v2 report from a future writer loads — additive
    fields ignored, candidates missing the v1 pricing keys skipped WITH
    a count (the summarize KNOWN_KINDS discipline), never a hard error
    and never a silent drop."""
    plan = planner.build_plan(shard_report=dp_report, hbm_budget_bytes=_BIG)
    future = json.loads(json.dumps(plan))
    future["schema"] = "plan_report_v2"
    future["some_v2_field"] = {"new": True}
    # one v2-only candidate this reader can't price
    future["candidates"].append({"family": "hypothetical_v2_family"})
    path = str(tmp_path / "future.json")
    with open(path, "w") as f:
        json.dump(future, f)
    loaded = planner.load_plan_report(path)
    notes = loaded["load_notes"]
    assert notes["newer_schema"] == "plan_report_v2"
    assert notes["skipped_count"] == 1
    assert "hypothetical_v2_family" in notes["skipped_candidates"]
    # the v1-complete candidates (and the chosen plan) survive
    assert {c["family"] for c in loaded["candidates"]} == {
        c["family"] for c in plan["candidates"]
    }
    assert loaded["chosen"]["family"] == plan["chosen"]["family"]


def test_shard_report_newer_schema_tolerated_with_count(tmp_path, dp_report):
    """The same forward-compat discipline retrofitted onto
    load_shard_report: a newer-versioned report keeps its readable
    families and skips-with-count the ones missing required keys."""
    future = json.loads(json.dumps(dp_report))
    future["schema"] = "shard_report_v2"
    future["families"]["v2_only"] = {"note": "no v1 keys at all"}
    path = str(tmp_path / "future_shard.json")
    with open(path, "w") as f:
        json.dump(future, f)
    loaded = shardlint.load_shard_report(path)
    assert "v2_only" not in loaded["families"]
    assert loaded["load_notes"]["skipped_count"] == 1
    assert "dp_sgd" in loaded["families"]
    # same-version missing keys still raise (corruption, not compat) —
    # pinned by test_shardlint.py::test_shard_report_roundtrip


# -- registry pins -----------------------------------------------------------


def test_rules_registry_has_td118_td119():
    assert RULES["TD118"].name == "plan-must-verify"
    assert RULES["TD119"].name == "planner-error-tracked"


def test_family_overrides_pin_against_step_registry():
    """Every applyable family is a registered shardlint family, every
    override names a real TrainConfig field, and the bench-side inverse
    lookup round-trips — a family added to step.py that --auto_shard
    apply should reach must land in FAMILY_TRAIN_OVERRIDES too."""
    import dataclasses

    from tpu_dist.config import TrainConfig

    registered = set(shardlint.registered_families())
    fields = {f.name for f in dataclasses.fields(TrainConfig)}
    for name, overrides in planner.FAMILY_TRAIN_OVERRIDES.items():
        assert name in registered, name
        assert set(overrides) <= fields, (name, overrides)
        # the overrides construct a valid config
        cfg = TrainConfig(**overrides)
        assert planner.family_of(
            grad_compression=cfg.grad_compression, bf16=cfg.bf16,
            grad_accu_steps=cfg.grad_accu_steps,
            shard_weight_update=cfg.shard_weight_update, fsdp=cfg.fsdp,
        ) == name
    # an off-registry combo gets an honest None, not a nearest match
    assert planner.family_of(grad_compression="int8", bf16=True) is None
    # plan-only families refuse application with the typed KeyError
    with pytest.raises(KeyError, match="plan-only"):
        planner.family_train_overrides("tp_vit")


# -- the CLI exit contracts --------------------------------------------------


def _run_plan_cli(*args, timeout=300):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    return subprocess.run(
        [sys.executable, "-m", "tpu_dist.analysis", "plan", *args],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=timeout,
    )


@pytest.mark.slow  # tier-1 budget (ISSUE 17): gates in analysis.yml
def test_cli_plan_text_json_and_inject_miscost(tmp_path):
    """One invocation covers the whole happy-path contract: json format,
    plan_report written, TD118 verified, the inject-miscost probe caught
    (exit 0 — a caught probe is the detector working)."""
    out = str(tmp_path / "plan_report.json")
    r = _run_plan_cli(
        "--family", "dp_sgd", "--family", "zero1_sgd",
        "--format", "json", "--inject-miscost", "--out", out,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    plan = json.loads(r.stdout)
    assert plan["schema"] == "plan_report_v1"
    assert plan["verification"]["verified"] is True
    assert plan["injected_miscost_probe"]["caught"] is True
    assert plan["injected_miscost_probe"]["violations"]
    # the written report loads through the schema-pinned loader
    assert planner.load_plan_report(out)["chosen"]["family"] == (
        plan["chosen"]["family"]
    )


@pytest.mark.slow  # tier-1 budget (ISSUE 18): gates in analysis.yml
def test_cli_plan_text_refusal_and_unknown_family(tmp_path):
    # text format with a budget that refuses the dp family
    r = _run_plan_cli(
        "--family", "dp_sgd", "--family", "zero1_sgd",
        "--hbm_budget_bytes", "3000", "--inject-miscost",
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "REFUSED" in r.stdout
    assert "InfeasibleMemoryError" in r.stdout
    assert "chosen zero1_sgd" in r.stdout
    assert "TD118 verified" in r.stdout
    # the probe outcome is a visible line, not exit-code-only
    assert "inject-miscost probe CAUGHT" in r.stdout
    # an unknown family is exit 2 with the registry named
    r2 = _run_plan_cli("--family", "nope", timeout=120)
    assert r2.returncode == 2
    assert "unknown famil" in r2.stderr
    # a budget under every candidate: no chosen plan -> nothing proves
    # the detector alive -> --inject-miscost must exit 2, not pass
    r3 = _run_plan_cli(
        "--family", "dp_sgd", "--hbm_budget_bytes", "64",
        "--inject-miscost",
    )
    assert r3.returncode == 2
    assert "detector is dead" in r3.stderr
