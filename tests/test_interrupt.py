"""Interrupt-safe checkpointing: a KeyboardInterrupt mid-fit snapshots."""

import numpy as np
import pytest

from tpu_dist.config import TrainConfig
from tpu_dist.train.trainer import Trainer, register_model
from tpu_dist.ckpt import latest_checkpoint
from tests.helpers import tiny_resnet

register_model("tiny_resnet_i", lambda num_classes=10: tiny_resnet(num_classes))


def test_interrupt_saves_emergency_checkpoint(tmp_path, monkeypatch):
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_i", num_classes=10,
        batch_size=64, epochs=5, steps_per_epoch=1, log_every=10,
        eval_every=0, ckpt_dir=str(tmp_path), save_every=100,
        synthetic_n=640,
    )
    t = Trainer(cfg)
    calls = {"n": 0}
    orig = t.train_epoch

    def interrupting(epoch, start_step=0, start_examples=0):
        calls["n"] += 1
        if calls["n"] == 2:
            raise KeyboardInterrupt
        return orig(epoch, start_step=start_step)

    monkeypatch.setattr(t, "train_epoch", interrupting)
    with pytest.raises(KeyboardInterrupt):
        t.fit()
    # interrupted mid-epoch 1 -> snapshot is filed under epoch 0, so resume
    # re-runs the incomplete epoch 1 instead of skipping its remainder
    found = latest_checkpoint(str(tmp_path))
    assert found is not None  # emergency snapshot written
    assert found[1] == 0
    t2 = Trainer(cfg.replace(resume=True))
    assert t2.start_epoch == 1
    assert np.isfinite(float(t2.state.params["fc"]["b"][0]))


def test_interrupt_in_first_epoch_saves_nothing(tmp_path, monkeypatch):
    """An interrupt inside epoch 0 writes no snapshot: a fresh start re-runs
    epoch 0 anyway, and a partial-epoch ckpt would masquerade as complete."""
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_i", num_classes=10,
        batch_size=64, epochs=5, steps_per_epoch=1, log_every=10,
        eval_every=0, ckpt_dir=str(tmp_path), save_every=100,
        synthetic_n=640,
    )
    t = Trainer(cfg)

    def interrupting(epoch, start_step=0, start_examples=0):
        raise KeyboardInterrupt

    monkeypatch.setattr(t, "train_epoch", interrupting)
    with pytest.raises(KeyboardInterrupt):
        t.fit()
    assert latest_checkpoint(str(tmp_path)) is None


def test_interrupt_between_epochs_saves_completed_epoch(tmp_path, monkeypatch):
    """Ctrl-C in the eval/save window after train_epoch(N) returned saves the
    COMPLETE epoch-N state under N (not N-1 — that would re-train a finished
    epoch)."""
    import tpu_dist.train.trainer as trainer_mod

    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_i", num_classes=10,
        batch_size=64, epochs=5, steps_per_epoch=1, log_every=10,
        eval_every=1, ckpt_dir=str(tmp_path), save_every=100,
        synthetic_n=640,
    )
    t = Trainer(cfg)

    def interrupting_validate(*a, **kw):
        raise KeyboardInterrupt

    monkeypatch.setattr(trainer_mod, "validate", interrupting_validate)
    with pytest.raises(KeyboardInterrupt):
        t.fit()
    found = latest_checkpoint(str(tmp_path))
    assert found is not None and found[1] == 0  # epoch 0 completed -> saved as 0
    t2 = Trainer(cfg.replace(resume=True))
    assert t2.start_epoch == 1  # epoch 0 not re-run


def test_interrupt_mid_epoch_keeps_clean_boundary_ckpt(tmp_path, monkeypatch):
    """A mid-epoch interrupt must not overwrite an existing clean
    end-of-epoch checkpoint with mid-epoch state."""
    import os

    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_i", num_classes=10,
        batch_size=64, epochs=5, steps_per_epoch=1, log_every=10,
        eval_every=0, ckpt_dir=str(tmp_path), save_every=1,  # ckpt each epoch
        synthetic_n=640,
    )
    t = Trainer(cfg)
    calls = {"n": 0}
    orig = t.train_epoch
    ckpt0 = os.path.join(str(tmp_path), "ckpt_0.npz")
    clean_mtime = {}

    def interrupting(epoch, start_step=0, start_examples=0):
        calls["n"] += 1
        if calls["n"] == 2:
            # clean ckpt_0 exists now (save_every=1); record its mtime
            # BEFORE the emergency path gets a chance to rewrite it
            clean_mtime["t"] = os.path.getmtime(ckpt0)
            raise KeyboardInterrupt
        return orig(epoch, start_step=start_step)

    monkeypatch.setattr(t, "train_epoch", interrupting)
    with pytest.raises(KeyboardInterrupt):
        t.fit()
    # the mid-epoch-1 interrupt must keep the clean boundary ckpt untouched
    found = latest_checkpoint(str(tmp_path))
    assert found is not None and found[1] == 0
    assert os.path.getmtime(ckpt0) == clean_mtime["t"]
