"""Interrupt-safe checkpointing: a KeyboardInterrupt mid-fit snapshots."""

import numpy as np
import pytest

from tpu_dist.config import TrainConfig
from tpu_dist.train.trainer import Trainer, register_model
from tpu_dist.ckpt import latest_checkpoint
from tests.helpers import tiny_resnet

register_model("tiny_resnet_i", lambda num_classes=10: tiny_resnet(num_classes))


def test_interrupt_saves_emergency_checkpoint(tmp_path, monkeypatch):
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_i", num_classes=10,
        batch_size=64, epochs=5, steps_per_epoch=1, log_every=10,
        eval_every=0, ckpt_dir=str(tmp_path), save_every=100,
        synthetic_n=640,
    )
    t = Trainer(cfg)
    calls = {"n": 0}
    orig = t.train_epoch

    def interrupting(epoch):
        calls["n"] += 1
        if calls["n"] == 2:
            raise KeyboardInterrupt
        return orig(epoch)

    monkeypatch.setattr(t, "train_epoch", interrupting)
    with pytest.raises(KeyboardInterrupt):
        t.fit()
    found = latest_checkpoint(str(tmp_path))
    assert found is not None  # emergency snapshot written
    # resume picks it up
    t2 = Trainer(cfg.replace(resume=True))
    assert t2.start_epoch >= 1
    assert np.isfinite(float(t2.state.params["fc"]["b"][0]))
