"""Interleaved (virtual-stage) pipeline schedule: numerics identical to
GPipe/sequential, bubble accounting strictly smaller (VERDICT r1 #7)."""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.config import TrainConfig
from tpu_dist.nn.vit import ViTDef
from tpu_dist.nn.vit_pp import ViTPipelineDef
from tpu_dist.parallel.pipeline import bubble_fraction
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tpu_dist.train.step import make_train_step
from tpu_dist.train.trainer import Trainer, register_model


def _model(interleave=1):
    return ViTPipelineDef(
        image_size=16, patch_size=4, dim=32, depth=8, heads=4, num_classes=5,
        interleave=interleave, pp_stages=4 if interleave > 1 else 0,
    )


def test_bubble_fraction_shrinks_with_interleave():
    g = bubble_fraction(4, 4)              # GPipe: 3/7
    i2 = bubble_fraction(4, 4, interleave=2)  # 3/11
    assert abs(g - 3 / 7) < 1e-12
    assert abs(i2 - 3 / 11) < 1e-12
    assert i2 < g


def test_interleaved_sequential_forward_matches_plain_vit():
    """Device-major storage + un-permutation: the sequential path of an
    interleaved def must equal the plain ViT forward from the same key."""
    import jax.numpy as jnp

    pp = _model(interleave=2)
    plain = ViTDef(image_size=16, patch_size=4, dim=32, depth=8, heads=4,
                   num_classes=5)
    p_pp, s = pp.init(jax.random.PRNGKey(0))
    p_plain, _ = plain.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16, 16, 3), jnp.float32)
    out_pp, _ = pp.apply(p_pp, s, x)
    out_plain, _ = plain.apply(p_plain, {}, x)
    np.testing.assert_allclose(np.asarray(out_pp), np.asarray(out_plain),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.slow  # tier-1 budget (ISSUE 17): gates in analysis.yml
def test_interleaved_pp_training_matches_single_device():
    model = _model(interleave=2)
    opt = SGD()
    mesh2d = mesh_lib.device_mesh([2, 4], ["data", "pipe"])
    mesh1 = mesh_lib.device_mesh([1], ["data"], jax.devices()[:1])
    specs = model.pp_param_specs("pipe")

    params, s = model.init(jax.random.PRNGKey(0))
    st = TrainState.create(params, s, opt)
    place = lambda tree: jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh2d, spec)), tree, specs
    )
    s_pp = TrainState(
        params=place(st.params),
        bn_state=jax.device_put(st.bn_state, mesh_lib.replicated(mesh2d)),
        opt_state=place(st.opt_state),
        step=jax.device_put(st.step, mesh_lib.replicated(mesh2d)),
    )
    s_1 = jax.device_put(st, mesh_lib.replicated(mesh1))

    step_pp = make_train_step(
        model.apply, opt, mesh2d, sync_bn=False, donate=False,
        pp_axis="pipe", param_specs=specs,
    )
    step_1 = make_train_step(model.apply, opt, mesh1, sync_bn=False, donate=False)

    rng = np.random.default_rng(0)
    for _ in range(3):
        x = rng.normal(size=(8, 16, 16, 3)).astype(np.float32)
        y = rng.integers(0, 5, 8).astype(np.int32)
        s_pp, m_pp = step_pp(
            s_pp, mesh_lib.shard_batch(mesh2d, x), mesh_lib.shard_batch(mesh2d, y), 0.05
        )
        s_1, m_1 = step_1(
            s_1, mesh_lib.shard_batch(mesh1, x), mesh_lib.shard_batch(mesh1, y), 0.05
        )

    np.testing.assert_allclose(float(m_pp["loss"]), float(m_1["loss"]), rtol=1e-4)
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(s_pp.params)),
        jax.tree_util.tree_leaves(jax.device_get(s_1.params)),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5)


def test_trainer_pp_interleaved_e2e():
    register_model(
        "vit_pp_d8",
        lambda num_classes=10: ViTPipelineDef(
            image_size=32, dim=32, depth=8, heads=4, num_classes=num_classes
        ),
    )
    cfg = TrainConfig(
        dataset="synthetic", model="vit_pp_d8", num_classes=10, batch_size=16,
        epochs=1, steps_per_epoch=2, log_every=1, eval_every=0, lr=0.05,
        pp=4, pp_interleave=2, sync_bn=False, synthetic_n=160,
    )
    out = Trainer(cfg).train_epoch(0)
    assert np.isfinite(out["loss"])


@pytest.mark.slow  # tier-1 budget (ISSUE 17): gates in analysis.yml
def test_interleaved_m2s_matches_single_device():
    """M = 2S: the buffered lap-boundary handoff (depth M-S+1 ring buffer)
    must reproduce sequential numerics exactly (VERDICT r2 #7)."""
    model = _model(interleave=2)
    opt = SGD()
    mesh2d = mesh_lib.device_mesh([2, 4], ["data", "pipe"])
    mesh1 = mesh_lib.device_mesh([1], ["data"], jax.devices()[:1])
    specs = model.pp_param_specs("pipe")

    params, s = model.init(jax.random.PRNGKey(2))
    st = TrainState.create(params, s, opt)
    place = lambda tree: jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh2d, spec)), tree, specs
    )
    s_pp = TrainState(
        params=place(st.params),
        bn_state=jax.device_put(st.bn_state, mesh_lib.replicated(mesh2d)),
        opt_state=place(st.opt_state),
        step=jax.device_put(st.step, mesh_lib.replicated(mesh2d)),
    )
    s_1 = jax.device_put(st, mesh_lib.replicated(mesh1))

    step_pp = make_train_step(
        model.apply, opt, mesh2d, sync_bn=False, donate=False,
        pp_axis="pipe", param_specs=specs,
        model_kwargs={"n_microbatches": 8},  # M = 2S with S = 4
    )
    step_1 = make_train_step(model.apply, opt, mesh1, sync_bn=False, donate=False)

    rng = np.random.default_rng(3)
    for _ in range(2):
        x = rng.normal(size=(32, 16, 16, 3)).astype(np.float32)
        y = rng.integers(0, 5, 32).astype(np.int32)
        s_pp, m_pp = step_pp(
            s_pp, mesh_lib.shard_batch(mesh2d, x), mesh_lib.shard_batch(mesh2d, y), 0.05
        )
        s_1, m_1 = step_1(
            s_1, mesh_lib.shard_batch(mesh1, x), mesh_lib.shard_batch(mesh1, y), 0.05
        )

    np.testing.assert_allclose(float(m_pp["loss"]), float(m_1["loss"]), rtol=1e-4)
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(s_pp.params)),
        jax.tree_util.tree_leaves(jax.device_get(s_1.params)),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5)


def test_bubble_shrinks_past_the_m_eq_s_corner():
    # the whole point of lifting M == S: more microbatches, smaller bubble
    assert bubble_fraction(4, 8, interleave=2) < bubble_fraction(4, 4, interleave=2)


def test_interleave_rejects_bad_configs():
    import pytest

    with pytest.raises(ValueError, match="pp_microbatches >= pp"):
        Trainer(TrainConfig(
            dataset="synthetic", model="vit_pp_tiny", num_classes=10,
            batch_size=16, pp=4, pp_interleave=2, pp_microbatches=2,
            sync_bn=False, synthetic_n=160,
        ))
    with pytest.raises(ValueError, match="n_microbatches >= n_stages"):
        # direct API misuse: interleaved schedule with M < S
        from tpu_dist.parallel.pipeline import pipeline_apply_interleaved

        import jax.numpy as jnp
        from tpu_dist.comm.compat import shard_map
        from jax.sharding import PartitionSpec as P

        mesh = mesh_lib.device_mesh([4], ["pipe"], jax.devices()[:4])
        shard_map(
            lambda x: pipeline_apply_interleaved(
                lambda p, h: h, None, x, "pipe", 4, 2
            ),
            mesh=mesh, in_specs=(P(),), out_specs=P(), check_vma=False,
        )(jnp.zeros((2, 2, 4)))


@pytest.mark.slow  # tier-1 budget (ISSUE 18): gates in analysis.yml
def test_interleaved_ckpt_refuses_layout_mismatch(tmp_path):
    """Interleaved storage permutes block order on disk — resuming under a
    different pp/pp_interleave must be refused, not run silently wrong."""
    import pytest

    register_model(
        "vit_pp_d8b",
        lambda num_classes=10: ViTPipelineDef(
            image_size=32, dim=32, depth=8, heads=4, num_classes=num_classes
        ),
    )
    cfg = TrainConfig(
        dataset="synthetic", model="vit_pp_d8b", num_classes=10, batch_size=16,
        epochs=1, steps_per_epoch=1, log_every=1, eval_every=0, lr=0.05,
        pp=4, pp_interleave=2, sync_bn=False, synthetic_n=160,
        ckpt_dir=str(tmp_path), save_every=1,
    )
    Trainer(cfg).fit()

    # same layout: resumes fine
    t2 = Trainer(cfg.replace(resume=True, epochs=1))
    assert t2.start_epoch == 1

    # different interleave: refused with a clear message
    with pytest.raises(ValueError, match="layout-specific"):
        Trainer(cfg.replace(resume=True, pp_interleave=1, pp_microbatches=0))


def test_interleave_without_pp_is_refused():
    import pytest

    with pytest.raises(ValueError, match="no effect without pp"):
        Trainer(TrainConfig(
            dataset="synthetic", model="vit_tiny", num_classes=10,
            batch_size=16, pp_interleave=2, sync_bn=False, synthetic_n=160,
        ))


@pytest.mark.slow  # tier-1 budget (ISSUE 18): gates in analysis.yml
def test_untagged_ckpt_refused_by_interleaved_resume(tmp_path):
    """A pre-layout-tag checkpoint (logical block order) must not be
    resumed by an interleaved config."""
    import json
    import numpy as np
    import pytest
    from tpu_dist import ckpt as ckpt_lib

    register_model(
        "vit_pp_d8c",
        lambda num_classes=10: ViTPipelineDef(
            image_size=32, dim=32, depth=8, heads=4, num_classes=num_classes
        ),
    )
    cfg = TrainConfig(
        dataset="synthetic", model="vit_pp_d8c", num_classes=10, batch_size=16,
        epochs=1, steps_per_epoch=1, log_every=1, eval_every=0, lr=0.05,
        pp=4, sync_bn=False, synthetic_n=160,
        ckpt_dir=str(tmp_path), save_every=1,
    )
    Trainer(cfg).fit()
    # strip the layout tag to simulate an old checkpoint
    path = ckpt_lib.latest_checkpoint(str(tmp_path))[0]
    with np.load(path) as z:
        flat = {k: z[k] for k in z.files}
    meta = json.loads(bytes(flat["__meta__"].tobytes()).decode())
    meta.pop("pp_interleave"); meta.pop("pp")
    flat["__meta__"] = np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8)
    with open(path, "wb") as f:
        np.savez(f, **flat)

    with pytest.raises(ValueError, match="no pipeline-layout tag"):
        Trainer(cfg.replace(resume=True, pp_interleave=2))


def test_interleave_on_unsupporting_model_is_refused():
    """A registered pp-capable model without interleave fields gets a clean
    ValueError, not a dataclasses TypeError."""
    import pytest

    class PPButNoInterleave:
        depth = 4
        def init(self, key):  # pragma: no cover - never reached
            raise NotImplementedError
        def apply(self, params, state, x, *, train=False, axis_name=None,
                  pp_axis=None, n_microbatches=0):  # pragma: no cover
            raise NotImplementedError
        def pp_param_specs(self, axis):  # pragma: no cover
            raise NotImplementedError

    register_model("pp_no_ilv", lambda num_classes=10: PPButNoInterleave())
    with pytest.raises(ValueError, match="interleaved schedule"):
        Trainer(TrainConfig(
            dataset="synthetic", model="pp_no_ilv", num_classes=10,
            batch_size=16, pp=4, pp_interleave=2, sync_bn=False,
            synthetic_n=160,
        ))
