"""The HBM observability layer (ISSUE 14, ``tpu_dist/obs/memory.py``):
static per-leaf ledger arithmetic (sharded extents included), the
census/allocator reconciliation identity on a real CPU fit, the
RESOURCE_EXHAUSTED parser matrix, pre-flight feasibility units and the
trainer's refuse path, the peak-HBM compare gate, the `obs memory` CLI,
OOM postmortem verdicts, the TD115 noop gate, and the schema-v11 pins."""

import json
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist.obs import costmodel
from tpu_dist.obs import memory as memory_lib

# -- static ledger: per-leaf byte arithmetic --------------------------------


def test_static_ledger_matches_hand_byte_arithmetic():
    params = {
        "w": jnp.ones((4, 8), jnp.float32),      # 128 B
        "b": jnp.ones((8,), jnp.bfloat16),       # 16 B
    }
    led = memory_lib.static_ledger(params=params, opt_state=None)
    sec = led["sections"]["params"]
    assert sec["bytes_total"] == 4 * 8 * 4 + 8 * 2 == 144
    assert sec["bytes_per_device"] == 144  # replicated: per-device == total
    assert sec["n_leaves"] == 2 and sec["sharded_leaves"] == 0
    assert led["sections"]["opt_state"]["bytes_total"] == 0
    assert led["bytes_per_device"] == 144 and led["n_leaves"] == 2
    # top leaves sorted by size, carrying shape/dtype for the report
    assert sec["top"][0]["bytes_per_device"] == 128
    assert sec["top"][0]["shape"] == [4, 8]


def test_static_ledger_counts_zero1_shards_at_sharded_extent():
    """A ZeRO-1 flat momentum vector laid P('data') over the 8-device
    mesh must count ceil(L/8) elements per chip, not L — the whole point
    of weight-update sharding (arXiv:2004.13336)."""
    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.comm.quantize import padded_len
    from tpu_dist.train.step import init_sharded_opt_state

    mesh = mesh_lib.data_parallel_mesh()
    n = int(mesh.devices.size)
    if n < 2:
        pytest.skip("needs the emulated multi-device mesh")
    params = {"w": jnp.ones((13, 7), jnp.float32), "b": jnp.ones((5,))}
    L = 13 * 7 + 5
    opt = init_sharded_opt_state(params, mesh)
    led = memory_lib.static_ledger(opt_state=opt)
    sec = led["sections"]["opt_state"]
    P_len = padded_len(L, n)
    assert sec["bytes_total"] == P_len * 4
    assert sec["bytes_per_device"] == P_len // n * 4
    assert sec["sharded_leaves"] == 1


def test_static_ledger_accepts_shape_dtype_structs():
    # the trainer's batch row is a ShapeDtypeStruct (no real arrays at
    # construction); the ledger must price it from metadata alone
    led = memory_lib.static_ledger(batch={
        "images": jax.ShapeDtypeStruct((8, 32, 32, 3), jnp.float32),
        "labels": jax.ShapeDtypeStruct((8,), jnp.int32),
    })
    assert led["bytes_per_device"] == 8 * 32 * 32 * 3 * 4 + 8 * 4


# -- census + reconciliation -------------------------------------------------


def test_reconciliation_identity_exact_by_construction():
    keep = jnp.ones((64, 64))  # held alive through the census
    census = memory_lib.live_census()
    assert census["n_arrays"] >= 1
    assert census["bytes_device0"] >= keep.nbytes
    # CPU backend: no allocator stats -> the census is the authority
    rec = memory_lib.reconcile(census, costmodel.device_memory_stats())
    assert (
        rec["attributed_bytes"] + rec["unattributed_bytes"]
        == rec["bytes_in_use"]
    )
    # a real allocator: unattributed is DEFINED as the difference (the
    # workspace/fragmentation gauge), so the identity is exact even when
    # the allocator holds more -- or less (donated buffers) -- than the
    # census can name
    for in_use in (rec["attributed_bytes"] + 4096,
                   max(rec["attributed_bytes"] - 512, 0)):
        r2 = memory_lib.reconcile(census, {"bytes_in_use": in_use})
        assert r2["source"] == "allocator"
        assert (
            r2["attributed_bytes"] + r2["unattributed_bytes"]
            == r2["bytes_in_use"] == in_use
        )
    del keep


def test_ledger_record_and_gauges(monkeypatch):
    from tpu_dist.obs import counters

    counters.reset()
    led = memory_lib.static_ledger(params={"w": jnp.ones((16,))})
    rec = memory_lib.ledger(
        static=led, xla={"argument_bytes": 10, "output_bytes": 4,
                         "temp_bytes": 2, "generated_code_bytes": 1,
                         "peak_bytes": 17},
    )
    memory_lib.publish_ledger(rec)
    snap = counters.snapshot()
    assert snap["mem.static_bytes_per_device"] == 64
    assert snap["mem.xla_peak_bytes"] == 17
    assert snap["mem.attributed_bytes"] == rec["reconciliation"][
        "attributed_bytes"
    ]
    assert memory_lib.record_peak_hbm(rec) == 17  # xla beats census on CPU
    assert "static" in memory_lib.summary_line(rec)
    counters.reset()


# -- per-device allocator stats (the costmodel satellite fix) ---------------


class _FakeDev:
    def __init__(self, stats):
        self._stats = stats

    def memory_stats(self):
        return self._stats


def test_device_memory_stats_reports_worst_chip_and_skew(monkeypatch):
    """The device-0-only read hid a hot chip behind a cool device 0 —
    the scalar keys must now be the MAX across local devices, with
    min/skew gauges making the imbalance visible."""
    devs = [
        _FakeDev({"bytes_in_use": 100, "peak_bytes_in_use": 150,
                  "bytes_limit": 1000}),
        _FakeDev({"bytes_in_use": 900, "peak_bytes_in_use": 950,
                  "bytes_limit": 1000}),
        _FakeDev(None),  # a device without stats is skipped, not fatal
    ]
    monkeypatch.setattr(jax, "local_devices", lambda: devs)
    out = costmodel.device_memory_stats()
    assert out["bytes_in_use"] == 900          # the worst chip, not dev 0
    assert out["bytes_in_use_min"] == 100
    assert out["bytes_in_use_skew"] == 800     # the imbalance gauge
    assert out["peak_bytes_in_use"] == 950
    assert out["mem_devices_reporting"] == 2


def test_device_memory_stats_none_on_statless_backend(monkeypatch):
    monkeypatch.setattr(
        jax, "local_devices", lambda: [_FakeDev(None), _FakeDev({})]
    )
    assert costmodel.device_memory_stats() is None


def test_chip_hbm_budget_table():
    gib = 1024 ** 3
    assert costmodel.chip_hbm_bytes("TPU v5e") == 16 * gib
    assert costmodel.chip_hbm_bytes("TPU v5p chip") == 95 * gib
    assert costmodel.chip_hbm_bytes("TPU v4") == 32 * gib
    assert costmodel.chip_hbm_bytes("cpu") is None  # never a guess


# -- RESOURCE_EXHAUSTED parser matrix ---------------------------------------

_GPU_OOM = """RESOURCE_EXHAUSTED: Out of memory while trying to allocate 2684354560 bytes.
BufferAssignment OOM Debugging.
Largest program allocations in hbm:
  1. Size: 2.50G
     Operator: op_name="jit(train_step)/dot_general"
     Shape: f32[8192,81920]
  2. Size: 640.0M
     XLA Label: fusion
     Shape: bf16[320,1024,1024]
"""

_TPU_OOM = (
    "RESOURCE_EXHAUSTED: XLA:TPU compile permanent error. "
    "Ran out of memory in memory space hbm. Used 15.90G of 15.48G hbm. "
    "Exceeded hbm capacity by 430.5M. Total hbm usage >= 16.43G:\n"
    "    reserved        530.00M\n    program          15.90G\n"
)


def test_parse_oom_gpu_shape_with_buffer_table():
    r = memory_lib.parse_resource_exhausted(_GPU_OOM)
    assert r["requested_bytes"] == 2684354560
    assert [b["size_bytes"] for b in r["buffers"]] == [
        int(2.5 * 1024 ** 3), int(640.0 * 1024 ** 2)
    ]
    assert r["buffers"][0]["op"] == "jit(train_step)/dot_general"
    assert r["buffers"][0]["shape"] == "f32[8192,81920]"
    assert r["buffers"][1]["op"] == "fusion"
    assert r["buffers_bytes"] == sum(b["size_bytes"] for b in r["buffers"])
    assert "RESOURCE_EXHAUSTED" in r["headline"]


def test_parse_oom_tpu_used_of_capacity_shape():
    r = memory_lib.parse_resource_exhausted(_TPU_OOM)
    assert r["used_bytes"] == int(15.90 * 1024 ** 3)
    assert r["limit_bytes"] == int(15.48 * 1024 ** 3)
    assert r["excess_bytes"] == int(430.5 * 1024 ** 2)
    line = memory_lib.oom_summary_line(r)
    assert "used" in line and "15.9GiB" in line


def test_parse_oom_truncated_text_still_yields_report():
    # the flight ring caps fatal messages at ~200 chars: the table is
    # gone but the headline + requested size survive
    r = memory_lib.parse_resource_exhausted(_GPU_OOM[:90])
    assert r is not None
    assert r["requested_bytes"] == 2684354560
    assert "buffers" not in r


def test_parse_oom_garbage_and_foreign_errors_return_none():
    assert memory_lib.parse_resource_exhausted("") is None
    assert memory_lib.parse_resource_exhausted("hello world") is None
    assert memory_lib.parse_resource_exhausted(
        "ValueError: shapes (3,) and (4,) not aligned"
    ) is None


# -- pre-flight feasibility --------------------------------------------------


def test_feasibility_headroom_units():
    gib = 1024 ** 3
    f = memory_lib.feasibility(10 * gib, 16 * gib, headroom=0.5)
    assert not f["fits"] and f["allowed_bytes"] == 8 * gib
    assert f["utilization"] == pytest.approx(10 / 16, abs=1e-4)
    assert memory_lib.feasibility(10 * gib, 16 * gib, headroom=0.9)["fits"]
    with pytest.raises(ValueError):
        memory_lib.feasibility(1, 0)
    with pytest.raises(ValueError):
        memory_lib.feasibility(1, 100, headroom=0.0)


def test_preflight_check_actions():
    # refuse: the typed error, before any compile
    with pytest.raises(memory_lib.InfeasibleMemoryError, match="exceeds"):
        memory_lib.preflight_check(
            2048, budget_bytes=1024, action="refuse"
        )
    # warn: report returned, caller prints
    rep = memory_lib.preflight_check(2048, budget_bytes=1024, action="warn")
    assert rep is not None and not rep["fits"]
    # off / unknown chip without an override: no lint, never a guess
    assert memory_lib.preflight_check(
        2048, budget_bytes=1024, action="off"
    ) is None
    assert memory_lib.preflight_check(
        2048, action="warn", chip_kind="cpu"
    ) is None
    with pytest.raises(ValueError, match="off|warn|refuse"):
        memory_lib.preflight_check(1, budget_bytes=10, action="bogus")


def _tiny_cfg(**kw):
    from tests.helpers import tiny_resnet
    from tpu_dist.config import TrainConfig
    from tpu_dist.train import trainer as trainer_mod

    trainer_mod.register_model(
        "tiny_memory", lambda num_classes=10: tiny_resnet(num_classes)
    )
    base = dict(
        dataset="synthetic", model="tiny_memory", num_classes=10,
        batch_size=32, epochs=1, steps_per_epoch=2, eval_every=0,
        synthetic_n=64, log_every=1, seed=0,
    )
    base.update(kw)
    return TrainConfig(**base)


def test_trainer_preflight_refuses_infeasible_budget():
    from tpu_dist.train.trainer import Trainer

    with pytest.raises(memory_lib.InfeasibleMemoryError, match="per-chip"):
        Trainer(_tiny_cfg(hbm_budget_bytes=1024, memory_check="refuse"))
    # the same budget under 'warn' constructs (and stamps the gauge)
    t = Trainer(_tiny_cfg(hbm_budget_bytes=1024, memory_check="warn"))
    assert not t._mem_feasibility["fits"]
    assert t._mem_static["bytes_per_device"] > 1024


def test_cpu_fit_logs_memory_record_with_exact_reconciliation(tmp_path):
    """The acceptance drill: a real CPU fit writes ONE schema-v11
    'memory' record whose reconciliation identity holds exactly, whose
    static section prices the params the model actually has, and whose
    mem.* gauges ride the epoch counters."""
    from tpu_dist.train.trainer import Trainer

    log = tmp_path / "run.jsonl"
    t = Trainer(_tiny_cfg(log_file=str(log)))
    t.fit()
    records = [json.loads(l) for l in open(log) if l.strip()]
    mems = [r for r in records if r.get("kind") == "memory"]
    assert len(mems) == 1, [r.get("kind") for r in records]
    m = mems[0]
    assert m["schema_version"] == 15
    rc = m["reconciliation"]
    assert (
        rc["attributed_bytes"] + rc["unattributed_bytes"]
        == rc["bytes_in_use"]
    )
    assert rc["source"] in ("census", "allocator")
    # static section: params priced from the real state
    params_bytes = sum(
        math.prod(l.shape) * l.dtype.itemsize
        for l in jax.tree_util.tree_leaves(t.state.params)
    )
    assert m["static"]["sections"]["params"]["bytes_total"] == params_bytes
    # the census saw the live state (params at minimum)
    assert m["census"]["bytes_device0"] >= params_bytes
    # the xla waterfall was captured (telemetry armed -> AOT analysis)
    assert m["xla"]["argument_bytes"] > 0
    assert m["xla"]["peak_bytes"] > 0
    # mem.* gauges flowed into the epoch record's counter snapshot
    epoch_rec = next(r for r in records if r.get("kind") == "train_epoch")
    assert epoch_rec["counters"]["mem.static_bytes_per_device"] > 0
    assert epoch_rec["counters"]["mem.xla_peak_bytes"] == m["xla"]["peak_bytes"]
    # summarize folds it + derives the gate scalar
    from tpu_dist.obs import summarize as summ

    report = summ.summarize(records)
    assert report["memory_records"] and report["memory"]
    assert report["memory"]["peak_hbm_bytes"] is not None
    assert "memory ledger:" in summ.format_text(report)


# -- compare gate ------------------------------------------------------------


def _history_with_peak(path, peak):
    recs = [
        {"ts": 1.0, "rel_s": 1.0, "schema_version": 11, "run_id": "r",
         "kind": "train_epoch", "epoch": 0, "epoch_time": 2.0,
         "images_per_sec": 1000.0, "loss": 1.0},
        {"ts": 2.0, "rel_s": 2.0, "schema_version": 11, "run_id": "r",
         "kind": "memory", "xla": {"peak_bytes": peak},
         "reconciliation": {"attributed_bytes": 0,
                            "unattributed_bytes": 0, "bytes_in_use": 0,
                            "source": "census"},
         "census": {"n_arrays": 0, "bytes_device0": 0}},
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return str(path)


def test_compare_exits_1_on_peak_hbm_regression_0_on_improvement(tmp_path):
    from tpu_dist.obs.__main__ import main as obs_main

    gib = 1024 ** 3
    base = _history_with_peak(tmp_path / "b.jsonl", 10 * gib)
    worse = _history_with_peak(tmp_path / "c.jsonl", 12 * gib)
    better = _history_with_peak(tmp_path / "d.jsonl", 9 * gib)
    assert obs_main(["compare", base, worse]) == 1   # higher = regression
    assert obs_main(["compare", base, better]) == 0  # lower never flags
    assert obs_main(["compare", base, base]) == 0    # self-compare clean


def test_peak_hbm_direction_registered_and_in_bench_fields():
    from tpu_dist.obs import compare as cmp

    assert cmp.direction_of("peak_hbm_bytes")[0] == "lower"
    assert "peak_hbm_bytes" in {f[0] for f in cmp.BENCH_FIELDS}
    assert "peak_hbm_bytes" in {m[0] for m in cmp.REPORT_METRICS}


# -- alerts ------------------------------------------------------------------


def test_memory_headroom_low_builtin_rule_fires_on_sustained_breach():
    from tpu_dist.obs import alerts as alerts_lib

    assert "memory_headroom_low" in alerts_lib.BUILTIN_RULES
    engine = alerts_lib.AlertEngine(alerts_lib.load_rules("default"))
    fired = []
    for _ in range(2):  # sustain=2
        fired.extend(engine.observe({"mem.headroom_frac": 0.05}))
    assert [f["rule"] for f in fired] == ["memory_headroom_low"]
    # a healthy window clears it; a backend that never publishes the
    # gauge (CPU) never advances the streak
    engine.observe({"mem.headroom_frac": 0.5})
    assert engine.active()["memory_headroom_low"] == 0.0


# -- obs memory CLI ----------------------------------------------------------


def test_obs_memory_cli_report_and_exit_codes(tmp_path, capsys):
    from tpu_dist.obs.__main__ import main as obs_main

    log = _history_with_peak(tmp_path / "r.jsonl", 3 * 1024 ** 3)
    assert obs_main(["memory", log]) == 0
    out = capsys.readouterr().out
    assert "peak HBM" in out and "3.0GiB" in out
    # a history with no memory telemetry: exit 1, loud
    empty = tmp_path / "e.jsonl"
    empty.write_text(json.dumps({
        "ts": 1.0, "kind": "train_epoch", "epoch": 0, "schema_version": 11,
    }) + "\n")
    assert obs_main(["memory", str(empty)]) == 1
    assert obs_main(["memory", str(tmp_path / "missing.jsonl")]) == 2


def test_obs_memory_cli_oom_parse(tmp_path, capsys):
    from tpu_dist.obs.__main__ import main as obs_main

    oom = tmp_path / "oom.txt"
    oom.write_text(_GPU_OOM)
    assert obs_main(["memory", "--oom", str(oom)]) == 0
    out = capsys.readouterr().out
    assert "requested 2.5GiB" in out and "dot_general" in out
    garbage = tmp_path / "g.txt"
    garbage.write_text("nothing to see")
    assert obs_main(["memory", "--oom", str(garbage)]) == 1


# -- OOM drill: postmortem verdict -------------------------------------------


def test_induced_oom_yields_postmortem_verdict_oom(tmp_path):
    """The acceptance drill, host-side: a rank dies on
    RESOURCE_EXHAUSTED — its flight ring holds the (truncated) fatal
    slot and the full oom.json landed beside it. The postmortem verdict
    must be 'oom' with the parsed allocation report, and the history
    record must render per-rank through summarize and tail."""
    from tpu_dist.obs import flight as flight_lib
    from tpu_dist.obs import postmortem as postmortem_lib

    crash = tmp_path / "crash"
    crash.mkdir()
    rec = flight_lib.FlightRecorder(
        str(crash / flight_lib.RING_NAME), run_id="oomtest", rank=0
    )
    rec.step(0, 3)

    class XlaRuntimeError(Exception):
        pass

    err = XlaRuntimeError(_TPU_OOM)
    rec.fatal(XlaRuntimeError, err, None)
    rec.close("exit", clean=False)
    report = memory_lib.parse_resource_exhausted(str(err))
    memory_lib.write_oom_report(
        str(crash / memory_lib.OOM_NAME), report,
        snapshot={"static": {"bytes_per_device": 123, "sections": {}}},
    )
    pm, bundle = postmortem_lib.run_postmortem([str(crash)])
    assert bundle is not None
    rank0 = pm["ranks"][0]
    assert rank0["verdict"] == "oom"
    assert rank0["oom"]["oom"]["used_bytes"] == int(15.90 * 1024 ** 3)
    text = postmortem_lib.format_text(pm)
    assert "OOM" in text and "rank 0: OOM" in text
    # the history record carries the per-rank oom map + renders via the
    # shared rank_summary formatter
    hist = postmortem_lib.history_record(pm, bundle)
    assert hist["verdicts"]["0"] == "oom"
    assert "used 15.9GiB" in hist["oom"]["0"]
    assert "OOM" in postmortem_lib.rank_summary(hist, "0")


def test_ring_only_oom_falls_back_to_fatal_slot_parse(tmp_path):
    """No oom.json (lost with the filesystem): the truncated fatal slot
    alone must still classify the verdict as oom."""
    from tpu_dist.obs import flight as flight_lib
    from tpu_dist.obs import postmortem as postmortem_lib

    crash = tmp_path / "crash"
    crash.mkdir()
    rec = flight_lib.FlightRecorder(
        str(crash / flight_lib.RING_NAME), run_id="oomtest", rank=0
    )

    class XlaRuntimeError(Exception):
        pass

    rec.fatal(XlaRuntimeError, XlaRuntimeError(_GPU_OOM), None)
    rec.close("exit", clean=False)
    pm, _ = postmortem_lib.run_postmortem([str(crash)])
    assert pm["ranks"][0]["verdict"] == "oom"
    assert pm["ranks"][0]["oom"]["source"] == "flight_ring"


def test_trainer_oom_teardown_writes_event_and_artifact(tmp_path, monkeypatch):
    """End-to-end: a RESOURCE_EXHAUSTED propagating out of the step loop
    leaves (a) a 'memory' event:oom history record with the parsed
    report + the live ledger snapshot, (b) oom.json beside the flight
    ring, (c) a ring whose postmortem verdict is 'oom'."""
    from tpu_dist.obs import postmortem as postmortem_lib
    from tpu_dist.train import trainer as trainer_mod

    log = tmp_path / "run.jsonl"
    crash = tmp_path / "crash"
    cfg = _tiny_cfg(log_file=str(log), crash_dir=str(crash))
    t = trainer_mod.Trainer(cfg)

    class XlaRuntimeError(Exception):
        pass

    def boom(*a, **kw):
        raise XlaRuntimeError(_TPU_OOM)

    monkeypatch.setattr(t, "train_epoch", boom)
    with pytest.raises(XlaRuntimeError):
        t.fit()
    records = [json.loads(l) for l in open(log) if l.strip()]
    ooms = [
        r for r in records
        if r.get("kind") == "memory" and r.get("event") == "oom"
    ]
    assert len(ooms) == 1
    assert ooms[0]["oom"]["used_bytes"] == int(15.90 * 1024 ** 3)
    assert ooms[0]["ledger"]["static"]["bytes_per_device"] > 0
    # the artifact landed and the postmortem classifies the rank
    assert (crash / memory_lib.OOM_NAME).exists()
    pm, _ = postmortem_lib.run_postmortem([str(crash)])
    assert pm["ranks"][0]["verdict"] == "oom"
    # summarize + tail render the crash
    from tpu_dist.obs import summarize as summ
    from tpu_dist.obs.tail import TailState

    assert "OOM" in summ.format_text(summ.summarize(records))
    ts = TailState()
    ts.add(records)
    assert any("OOM" in e for e in ts.events)


# -- TD115 gate + registry ---------------------------------------------------


def test_td115_registered_beside_the_noop_family():
    from tpu_dist.analysis.rules import RULES

    assert "TD115" in RULES
    assert RULES["TD115"].name == "memory-ledger-not-noop"
    # the whole armed-vs-off family is present
    for rid in ("TD105", "TD106", "TD107", "TD108", "TD109", "TD110",
                "TD111", "TD112", "TD113", "TD114", "TD115"):
        assert rid in RULES


def test_td115_memory_ledger_noop_gate():
    from tpu_dist.analysis.jaxpr_audit import memory_ledger_noop_violations

    assert memory_ledger_noop_violations() == []


# -- schema v11 pins ---------------------------------------------------------


def test_schema_v15_pins_and_future_kind_tolerance():
    from tpu_dist.metrics.history import SCHEMA_VERSION
    from tpu_dist.obs import summarize as summ
    from tpu_dist.obs.postmortem import POSTMORTEM_SCHEMA_VERSION
    from tpu_dist.fleet.scheduler import FLEET_SCHEMA_VERSION

    assert SCHEMA_VERSION == POSTMORTEM_SCHEMA_VERSION == 15
    assert FLEET_SCHEMA_VERSION == 15
    assert summ.SUPPORTED_SCHEMA == 15
    assert "memory" in summ.KNOWN_KINDS
    assert "tenancy" in summ.KNOWN_KINDS  # v14: the co-scheduling ledger
    # a v16 log's unknown kind: skipped WITH a count, never an error
    report = summ.summarize([
        {"kind": "train_epoch", "epoch": 0, "schema_version": 11,
         "ts": 1.0, "rel_s": 1.0, "epoch_time": 1.0,
         "images_per_sec": 10.0, "loss": 1.0},
        {"kind": "mem_hologram", "schema_version": 16, "ts": 2.0},
    ])
    assert report["skipped_kinds"] == {"mem_hologram": 1}
    assert report["newer_schema_records"] == 1
    assert report["totals"]["n_epochs"] == 1


def test_fmt_bytes_units():
    assert memory_lib.fmt_bytes(512) == "512B"
    assert memory_lib.fmt_bytes(1536) == "1.5KiB"
    assert memory_lib.fmt_bytes(3 * 1024 ** 3) == "3.0GiB"
    assert memory_lib.fmt_bytes(None) == "-"
    assert memory_lib.fmt_bytes(-2048) == "-2.0KiB"
