"""Device-side training health (ISSUE 5): in-step norms (--device_metrics
+ TD107), cost/MFU/memory accounting (obs/costmodel), rolling-window
anomaly detection (obs/anomaly), and the run-compare regression gate
(obs/compare + the CLI exit-code contract)."""

import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.obs import counters
from tpu_dist.obs import costmodel
from tpu_dist.obs.anomaly import AnomalyDetector
from tpu_dist.obs.device_stats import compute_device_stats
from tpu_dist.obs.summarize import format_text, summarize


@pytest.fixture(autouse=True)
def _clean_counters():
    counters.reset()
    yield
    counters.reset()


# -- device_stats: the in-step scalars --------------------------------------


def test_compute_device_stats_known_values():
    grads = {"a": jnp.asarray([3.0, 4.0]), "b": jnp.zeros((2, 2))}
    params = {"a": jnp.asarray([1.0, 0.0]), "b": jnp.zeros((2, 2))}
    new = {"a": jnp.asarray([1.0, 0.2]), "b": jnp.zeros((2, 2))}
    s = jax.tree_util.tree_map(float, compute_device_stats(grads, params, new))
    assert s["grad_norm"] == pytest.approx(5.0)
    assert s["param_norm"] == pytest.approx(1.0)
    assert s["update_ratio"] == pytest.approx(0.2)
    assert s["nonfinite_grads"] == 0.0


def test_compute_device_stats_counts_nonfinite_leaves():
    grads = {
        "ok": jnp.ones(3),
        "nan": jnp.asarray([1.0, float("nan")]),
        "inf": jnp.asarray([float("inf")]),
    }
    p = {k: jnp.ones_like(v) for k, v in grads.items()}
    s = compute_device_stats(grads, p, p)
    assert float(s["nonfinite_grads"]) == 2.0  # leaves, not elements
    assert float(s["param_norm"]) > 0.0
    assert float(s["update_ratio"]) == 0.0  # params unchanged


def test_compute_device_stats_empty_tree_is_defined():
    s = compute_device_stats({}, {}, {})
    assert float(s["grad_norm"]) == 0.0
    assert float(s["update_ratio"]) == 0.0


def test_train_step_device_metrics_values_match_host_arithmetic():
    """The fused-in scalars must equal what host numpy computes from the
    actual before/after params — the update_ratio reflects the APPLIED
    update (momentum, wd, lr all included)."""
    from tests.helpers import TinyMLP
    from tpu_dist.train.optim import SGD
    from tpu_dist.train.state import TrainState
    from tpu_dist.train.step import make_train_step

    mesh = mesh_lib.data_parallel_mesh()
    model = TinyMLP()
    params, st = model.init(jax.random.PRNGKey(0))
    opt = SGD(momentum=0.9, weight_decay=1e-4)
    state = jax.device_put(
        TrainState.create(params, st, opt), mesh_lib.replicated(mesh)
    )
    step = make_train_step(
        model.apply, opt, mesh, sync_bn=False,
        compute_dtype=jnp.float32, device_metrics=True, donate=False,
    )
    n = mesh.devices.size
    rng = np.random.default_rng(0)
    images = mesh_lib.shard_batch(
        mesh, rng.normal(size=(8 * n, 2, 2, 3)).astype(np.float32)
    )
    labels = mesh_lib.shard_batch(
        mesh, rng.integers(0, 10, 8 * n).astype(np.int32)
    )
    before = jax.device_get(state.params)
    new_state, metrics = step(state, images, labels, 0.1)
    m = {k: float(v) for k, v in jax.device_get(metrics).items()}
    after = jax.device_get(new_state.params)
    b = np.concatenate([np.ravel(x) for x in jax.tree_util.tree_leaves(before)])
    a = np.concatenate([np.ravel(x) for x in jax.tree_util.tree_leaves(after)])
    assert m["param_norm"] == pytest.approx(np.linalg.norm(b), rel=1e-5)
    assert m["update_ratio"] == pytest.approx(
        np.linalg.norm(a - b) / np.linalg.norm(b), rel=1e-4
    )
    assert m["grad_norm"] > 0.0 and m["nonfinite_grads"] == 0.0
    # the scalars ride the ordinary metrics dict — the standard keys stay
    assert {"loss", "acc1", "acc5"} <= set(m)


def test_train_step_refuses_device_metrics_on_sharded_paths():
    from tests.helpers import TinyMLP
    from tpu_dist.train.optim import SGD
    from tpu_dist.train.step import make_train_step

    mesh = mesh_lib.data_parallel_mesh()
    model = TinyMLP()
    opt = SGD()
    with pytest.raises(ValueError, match="replicated-param"):
        make_train_step(
            model.apply, opt, mesh, sync_bn=False,
            shard_weight_update=True, device_metrics=True,
        )
    tp_mesh = mesh_lib.device_mesh(
        [mesh.devices.size // 2, 2],
        [mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS],
    )
    with pytest.raises(ValueError, match="replicated-param"):
        make_train_step(
            model.apply, opt, tp_mesh, sync_bn=False,
            tp_axis=mesh_lib.MODEL_AXIS, device_metrics=True,
        )


# -- TD107: the zero-cost contract ------------------------------------------


def test_td107_rule_registered():
    from tpu_dist.analysis.rules import RULES

    assert "TD107" in RULES
    assert "device-metrics" in RULES["TD107"].name


def test_td107_noop_gate():
    """Flag off ⇒ byte-identical jaxpr; flag on ⇒ collective and transfer
    inventories unchanged on the pure-DP path (the acceptance criterion)."""
    from tpu_dist.analysis.jaxpr_audit import device_metrics_noop_violations

    assert device_metrics_noop_violations() == []


def test_td107_audit_case_in_registry():
    from tpu_dist.analysis.jaxpr_audit import audit_all, registered_cases

    assert "dp_device_metrics" in registered_cases()
    report, violations = audit_all(names=["dp_device_metrics"])
    assert not violations
    assert report["dp_device_metrics"]["collectives"]


# -- costmodel ---------------------------------------------------------------


class _FakeAnalyzable:
    def __init__(self, ca=None, ma=None, raise_ca=False):
        self._ca, self._ma, self._raise = ca, ma, raise_ca

    def cost_analysis(self):
        if self._raise:
            raise RuntimeError("unimplemented")
        return self._ca

    def memory_analysis(self):
        if self._ma is None:
            raise RuntimeError("unimplemented")
        return self._ma


def test_chip_peak_flops_prefix_match_and_unknown():
    assert costmodel.chip_peak_flops("TPU v4 lite") == pytest.approx(275e12)
    assert costmodel.chip_peak_flops("TPU v5p slice") == pytest.approx(459e12)
    # longest prefix wins: v5 lite must not fall through to bare v5
    assert costmodel.chip_peak_flops("TPU v5 lite") == pytest.approx(197e12)
    assert costmodel.chip_peak_flops("cpu") is None
    assert costmodel.chip_peak_flops("Tesla V100") is None


def test_step_cost_normalizes_list_and_scales_trips():
    # older jax: one dict per device in a list
    obj = _FakeAnalyzable(ca=[{"flops": 100.0, "bytes accessed": 10.0}])
    assert costmodel.step_cost(obj, loop_trips=4) == {
        "flops_per_step": 400.0, "bytes_per_step": 40.0,
    }
    # missing/zero/raising all degrade to None, never raise
    assert costmodel.step_cost(_FakeAnalyzable(ca={"flops": 0.0})) == {
        "flops_per_step": None, "bytes_per_step": None,
    }
    assert costmodel.step_cost(_FakeAnalyzable(raise_ca=True)) == {
        "flops_per_step": None, "bytes_per_step": None,
    }


def test_mfu_arithmetic_and_none_paths():
    # 1e12 flops in 0.1 s on 2 chips of 123e12 peak = 10/24.6
    assert costmodel.mfu(1e12, 0.1, 2, peak=123e12) == pytest.approx(
        1e12 / 0.1 / (2 * 123e12), abs=1e-4
    )
    assert costmodel.mfu(None, 0.1, 1, peak=1e12) is None
    assert costmodel.mfu(1e12, 0.0, 1, peak=1e12) is None
    assert costmodel.mfu(1e12, 0.1, 1, peak=None) is None  # unknown chip


def test_memory_analysis_bytes_aliasing_and_unavailable():
    class MA:
        argument_size_in_bytes = 100
        output_size_in_bytes = 50
        temp_size_in_bytes = 30
        generated_code_size_in_bytes = 5
        alias_size_in_bytes = 60

    out = costmodel.memory_analysis_bytes(_FakeAnalyzable(ma=MA()))
    assert out["peak_bytes"] == 100 + 50 + 30 + 5 - 60
    assert costmodel.memory_analysis_bytes(_FakeAnalyzable()) is None


def test_analyze_jitted_reads_real_cost_without_compiling():
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((8, 8))
    cost = costmodel.analyze_jitted(f, x)
    assert cost is not None and cost["flops_per_step"] and cost["flops_per_step"] > 0


def test_publish_sets_gauges():
    costmodel.publish({"flops_per_step": 123.0, "bytes_per_step": None})
    snap = counters.snapshot()
    assert snap["device.flops_per_step"] == 123.0
    assert "device.bytes_per_step" not in snap
    costmodel.publish(None)  # no-op, never raises


def test_compile_watcher_counts_events_and_retraces():
    class FakeJit:
        def __init__(self):
            self.size = 0

        def _cache_size(self):
            return self.size

    fj = FakeJit()
    w = costmodel.CompileWatcher(fj)
    assert w.observe() is False  # nothing compiled yet
    fj.size = 1  # first trace: an event, NOT a retrace
    assert w.observe() is False
    assert counters.get("compile.events") == 1
    assert counters.get("compile.retraces") == 0
    assert w.observe() is False  # steady state: no growth, no counts
    fj.size = 3  # mid-run growth: two retraces
    assert w.observe() is True
    assert counters.get("compile.events") == 3
    assert counters.get("compile.retraces") == 2


def test_compile_watcher_degrades_without_cache_api():
    w = costmodel.CompileWatcher(object())  # no _cache_size attribute
    assert w.observe() is False and counters.get("compile.events") == 0


def test_install_compile_listener_idempotent():
    assert costmodel.install_compile_listener() is True
    assert costmodel.install_compile_listener() is True


# -- anomaly detector --------------------------------------------------------


def test_anomaly_warmup_then_loss_spike_with_cooldown():
    det = AnomalyDetector(window=8, loss_spike=3.0, min_points=3)
    assert det.observe(loss=100.0) == []  # window cold: no median yet
    for i in range(3):
        assert det.observe(epoch=0, step=i, loss=1.0) == []
    f = det.observe(epoch=0, step=3, loss=10.0)
    assert len(f) == 1 and f[0]["anomaly"] == "loss_spike"
    assert f[0]["ratio"] == pytest.approx(10.0 / f[0]["median"], rel=0.01)
    # cooldown: the plateau right after yields no second record...
    assert det.observe(loss=10.0) == []
    # ...and spikes ENTER the window, so the median self-limits: after the
    # cooldown a 10.0 against a window full of 10.0s is not an anomaly
    for _ in range(8):
        det.observe(loss=10.0)
    assert det.observe(loss=10.0) == []


def test_anomaly_grad_norm_explosion_and_nonfinite():
    det = AnomalyDetector(window=6, grad_spike=10.0, min_points=2)
    for _ in range(3):
        det.observe(grad_norm=1.0)
    f = det.observe(epoch=1, step=7, grad_norm=50.0)
    assert [x["anomaly"] for x in f] == ["grad_norm_explosion"]
    f = det.observe(loss=float("nan"), nonfinite=2.0)
    kinds = {x["anomaly"] for x in f}
    assert kinds == {"nonfinite_loss", "nonfinite_grads"}
    # a nonfinite grad_norm must not poison the rolling window
    det.observe(grad_norm=float("inf"))
    assert all(math.isfinite(v) for v in det._gnorms)


def test_anomaly_cooldown_decays_per_observation_not_per_spike():
    """A kind must come OFF cooldown after min_points observations of any
    kind — an isolated later anomaly separated by healthy steps has to
    fire again (the cooldown exists to collapse a plateau into one
    record, not to swallow distinct events)."""
    det = AnomalyDetector(window=8, loss_spike=3.0, min_points=3)
    for _ in range(3):
        det.observe(loss=1.0)
    assert [f["anomaly"] for f in det.observe(loss=10.0)] == ["loss_spike"]
    # healthy steps tick the cooldown down (and wash the spike out of the
    # rolling window)...
    for _ in range(10):
        assert det.observe(loss=1.0) == []
    # ...so a second, distinct spike fires a second finding
    assert [f["anomaly"] for f in det.observe(loss=10.0)] == ["loss_spike"]
    # same contract for the nonfinite stream: nan, recovery, nan again
    det2 = AnomalyDetector(window=8, min_points=2)
    assert len(det2.observe(loss=float("nan"))) == 1
    for _ in range(3):
        det2.observe(loss=1.0)
    assert len(det2.observe(loss=float("nan"))) == 1


def test_anomaly_rejects_degenerate_window():
    with pytest.raises(ValueError):
        AnomalyDetector(window=1)


# -- compare: the regression gate -------------------------------------------


def _epoch_rec(epoch, ips, loss, run_id="r", mfu=None, **extra):
    rec = {
        "kind": "train_epoch", "epoch": epoch, "run_id": run_id,
        "loss": loss, "epoch_time": 2.0, "images_per_sec": ips,
        "step_time_p50": 0.01, "step_time_p95": 0.02,
        "step_time_p99": 0.03, "data_stall_frac": 0.05,
    }
    if mfu is not None:
        rec["mfu"] = mfu
    rec.update(extra)
    return rec


def _write_jsonl(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    return str(path)


def test_compare_self_is_zero_regressions(tmp_path):
    from tpu_dist.obs import compare as cmp

    p = _write_jsonl(
        tmp_path / "a.jsonl",
        [_epoch_rec(0, 1000.0, 2.0, mfu=0.3),
         _epoch_rec(1, 1100.0, 1.5, mfu=0.31),
         {"kind": "eval", "epoch": 1, "top1": 55.0}],
    )
    result = cmp.compare_files(p, p)
    assert result["regressions"] == 0 and result["compared"] == 8
    assert "REGRESSED" not in cmp.format_text(result)


def test_compare_flags_regressions_and_respects_direction(tmp_path):
    from tpu_dist.obs import compare as cmp

    base = _write_jsonl(
        tmp_path / "base.jsonl", [_epoch_rec(0, 1000.0, 2.0, mfu=0.30)]
    )
    # throughput down 20%, p95 up 50%, loss up, MFU down beyond slack
    worse = _write_jsonl(
        tmp_path / "cand.jsonl",
        [_epoch_rec(0, 800.0, 2.5, mfu=0.20, step_time_p95=0.03)],
    )
    result = cmp.compare_files(base, worse, threshold=0.05)
    verdicts = {r["metric"]: r["verdict"] for r in result["rows"]}
    assert verdicts["images_per_sec_mean"] == "REGRESSED"
    assert verdicts["step_time_p95_s"] == "REGRESSED"
    assert verdicts["mfu_mean"] == "REGRESSED"
    assert verdicts["step_time_p50_s"] == "ok"
    # better-than-baseline is never flagged
    better = _write_jsonl(
        tmp_path / "better.jsonl", [_epoch_rec(0, 2000.0, 1.0, mfu=0.5)]
    )
    assert cmp.compare_files(base, better)["regressions"] == 0


def test_compare_absolute_slack_quiets_noise_floor(tmp_path):
    from tpu_dist.obs import compare as cmp

    # stall 0.1% vs 0.3%: a 3x relative blowup but inside the 2-point
    # absolute slack — must NOT regress (the quiet-run noise floor)
    base = _write_jsonl(
        tmp_path / "b.jsonl", [_epoch_rec(0, 1000.0, 2.0, data_stall_frac=0.001)]
    )
    cand = _write_jsonl(
        tmp_path / "c.jsonl", [_epoch_rec(0, 1000.0, 2.0, data_stall_frac=0.003)]
    )
    result = cmp.compare_files(base, cand)
    row = next(r for r in result["rows"] if r["metric"] == "data_stall_frac")
    assert row["verdict"] == "ok"


def test_compare_missing_metrics_reported_skipped_not_dropped(tmp_path):
    from tpu_dist.obs import compare as cmp

    base = _write_jsonl(tmp_path / "b.jsonl", [_epoch_rec(0, 1000.0, 2.0)])
    cand = _write_jsonl(tmp_path / "c.jsonl", [_epoch_rec(0, 1000.0, 2.0)])
    result = cmp.compare_files(base, cand)  # no mfu/eval/goodput/capture
    skipped = {r["metric"] for r in result["rows"] if r["verdict"] == "skipped"}
    assert skipped == {"mfu_mean", "final_val_top1", "goodput_frac",
                       "overlap_frac", "collective_frac",
                       "peak_hbm_bytes", "planner_error_frac", "ckpt_s",
                       "preempt_for_serve_s"}
    assert result["skipped"] == 9


def test_compare_bench_mode_matches_by_metric_name(tmp_path):
    from tpu_dist.obs import compare as cmp

    base = _write_jsonl(tmp_path / "b.json", [
        {"metric": "resnet18_train_throughput", "value": 2600.0,
         "sec_per_epoch": 19.2, "step_ms": 97.0, "mfu": 0.32},
        {"metric": "only_in_base", "value": 1.0},
    ])
    cand = _write_jsonl(tmp_path / "c.json", [
        {"metric": "resnet18_train_throughput", "value": 2000.0,
         "sec_per_epoch": 25.0, "step_ms": 126.0, "mfu": 0.25},
    ])
    result = cmp.compare_files(base, cand, bench=True)
    verdicts = {r["metric"]: r["verdict"] for r in result["rows"]}
    assert verdicts["resnet18_train_throughput.value"] == "REGRESSED"
    assert verdicts["resnet18_train_throughput.sec_per_epoch"] == "REGRESSED"
    assert verdicts["only_in_base"] == "skipped"
    # self-compare in bench mode too
    assert cmp.compare_files(base, base, bench=True)["regressions"] == 0


def test_compare_unusable_inputs_raise(tmp_path):
    from tpu_dist.obs import compare as cmp

    empty = _write_jsonl(tmp_path / "empty.jsonl", [])
    good = _write_jsonl(tmp_path / "g.jsonl", [_epoch_rec(0, 1000.0, 2.0)])
    with pytest.raises(ValueError):
        cmp.compare_files(empty, good)
    no_epochs = _write_jsonl(
        tmp_path / "ne.jsonl", [{"kind": "eval", "epoch": 0, "top1": 1.0}]
    )
    with pytest.raises(ValueError):
        cmp.compare_files(no_epochs, good)


def test_compare_cli_exit_code_contract(tmp_path, capsys):
    """Exit 0 on self-compare, 1 on a regression, 2 on a broken gate —
    the CI contract from the acceptance criteria."""
    from tpu_dist.obs.__main__ import main as obs_main

    base = _write_jsonl(
        tmp_path / "b.jsonl",
        [_epoch_rec(0, 1000.0, 2.0), _epoch_rec(1, 1000.0, 1.8)],
    )
    worse = _write_jsonl(
        tmp_path / "w.jsonl",
        [_epoch_rec(0, 700.0, 2.0), _epoch_rec(1, 700.0, 1.8)],
    )
    assert obs_main(["compare", base, base]) == 0
    assert obs_main(["compare", base, worse]) == 1
    # --format json stays machine-readable on both verdicts
    assert obs_main(["compare", base, worse, "--format", "json"]) == 1
    out = capsys.readouterr().out.splitlines()
    result = json.loads("\n".join(out[out.index("{"):]))
    assert result["regressions"] >= 1
    # a generous threshold waves the same diff through
    assert obs_main(["compare", base, worse, "--threshold", "0.5"]) == 0
    assert obs_main(["compare", base, str(tmp_path / "missing.jsonl")]) == 2
    torn = tmp_path / "torn.jsonl"
    torn.write_text('{"kind": "train_ep')  # only a torn line: unusable
    assert obs_main(["compare", base, str(torn)]) == 2


# -- summarize over the new record kinds ------------------------------------


def test_summarize_aggregates_device_stats_and_anomalies():
    records = [
        _epoch_rec(0, 1000.0, 2.0, mfu=0.31),
        {"kind": "device_stats", "epoch": 0, "step": 0,
         "grad_norm": 1.5, "param_norm": 10.0, "update_ratio": 0.002},
        {"kind": "device_stats", "epoch": 0, "step": 2,
         "grad_norm": 9.0, "param_norm": 10.1, "update_ratio": 0.004},
        {"kind": "device_stats", "epoch": 0, "step": 4,
         "grad_norm": 1.2, "param_norm": 10.2, "update_ratio": 0.003},
        {"kind": "anomaly", "epoch": 0, "step": 2,
         "anomaly": "grad_norm_explosion", "value": 9.0, "median": 1.4,
         "ratio": 6.4},
    ]
    report = summarize(records)
    ds = report["epochs"][0]["device_stats"]
    assert ds["samples"] == 3
    assert ds["grad_norm_max"] == 9.0  # the spike, not the last sample
    assert ds["grad_norm_last"] == 1.2
    assert ds["update_ratio_last"] == 0.003
    assert report["epochs"][0]["mfu"] == 0.31
    assert report["totals"]["mfu_mean"] == pytest.approx(0.31)
    assert report["anomalies"] == [{
        "epoch": 0, "step": 2, "anomaly": "grad_norm_explosion",
        "value": 9.0, "median": 1.4, "ratio": 6.4,
    }]
    text = format_text(report)
    assert "grad_norm last 1.2 / max 9" in text
    assert "anomaly: epoch 0 step 2 grad_norm_explosion value 9.0" in text
    assert "mean MFU 0.31" in text


def test_summarize_surfaces_mid_run_retraces():
    records = [
        _epoch_rec(0, 1000.0, 2.0, counters={"compile.events": 1}),
        _epoch_rec(1, 900.0, 1.9,
                   counters={"compile.events": 3, "compile.retraces": 2}),
    ]
    report = summarize(records)
    assert "retraces" not in report["epochs"][0]
    assert report["epochs"][1]["retraces"] == 2
    assert "2 mid-run retrace(s)" in format_text(report)


# -- trainer wiring ----------------------------------------------------------


def _tiny_cfg(**kw):
    from tests.helpers import tiny_resnet
    from tpu_dist.config import TrainConfig
    from tpu_dist.train import trainer as trainer_mod

    trainer_mod.register_model(
        "tiny_dev_health", lambda num_classes=10: tiny_resnet(num_classes)
    )
    base = dict(
        dataset="synthetic", model="tiny_dev_health", num_classes=10,
        batch_size=32, epochs=1, steps_per_epoch=4, eval_every=0,
        synthetic_n=128, log_every=2, seed=0,
    )
    base.update(kw)
    return TrainConfig(**base)


@pytest.mark.slow  # ~4 s (several Trainer constructions); CI device-
# health step runs it without the slow filter (ISSUE 7 tier-1 budget)
def test_trainer_refuses_device_metrics_on_excluded_engines(tmp_path):
    from tpu_dist.train.trainer import Trainer

    with pytest.raises(ValueError, match="replicated-param"):
        Trainer(_tiny_cfg(device_metrics=True, shard_weight_update=True))
    with pytest.raises(ValueError, match="per-step metrics fetch"):
        Trainer(_tiny_cfg(device_metrics=True, fused_epoch=True))


def test_trainer_refuses_snapshot_action_without_ckpt_dir():
    from tpu_dist.train.trainer import Trainer

    with pytest.raises(ValueError, match="needs --ckpt_dir"):
        Trainer(_tiny_cfg(anomaly_action="snapshot"))
    with pytest.raises(ValueError, match="off|warn|snapshot"):
        Trainer(_tiny_cfg(anomaly_action="bogus"))


def test_observe_health_records_warns_and_snapshots(tmp_path):
    """The full action path, driven with canned metrics: device_stats +
    anomaly history records, per-step TensorBoard scalars, and the
    snapshot action writing an exact mid-epoch checkpoint stamped with
    the anomaly kind."""
    import tpu_dist.ckpt as ckpt_lib
    from tpu_dist.metrics.history import MetricsHistory
    from tpu_dist.train.trainer import Trainer

    ckpt_dir = str(tmp_path / "ckpt")
    t = Trainer(_tiny_cfg(
        anomaly_action="snapshot", anomaly_window=4, anomaly_loss_spike=2.0,
        ckpt_dir=ckpt_dir, device_metrics=True,
    ))
    scalars = []

    class FakeTB:
        def add_scalar(self, tag, value, step):
            scalars.append((tag, value, step))

    t._tb = FakeTB()
    log = tmp_path / "h.jsonl"
    with MetricsHistory(str(log), run_id="t") as h:
        t._history = h
        nb = 10
        for step, loss in enumerate([1.0, 1.1, 0.9, 1.0]):
            t._observe_health(0, step, nb, {
                "loss": loss, "grad_norm": 1.0, "param_norm": 5.0,
                "update_ratio": 1e-3, "nonfinite_grads": 0.0,
            })
        t._observe_health(0, 4, nb, {
            "loss": 8.0, "grad_norm": 1.1, "param_norm": 5.0,
            "update_ratio": 1e-3, "nonfinite_grads": 0.0,
        })
    t._history = None
    recs = [json.loads(l) for l in open(log)]
    kinds = [r["kind"] for r in recs]
    assert kinds.count("device_stats") == 5
    anom = [r for r in recs if r["kind"] == "anomaly"]
    assert len(anom) == 1 and anom[0]["anomaly"] == "loss_spike"
    assert anom[0]["step"] == 4 and anom[0]["ratio"] == pytest.approx(8.0)
    # snapshot: exact mid-epoch checkpoint stamped with the finding,
    # written OFF the ckpt_{N} namespace so later saves never clobber it
    path = os.path.join(ckpt_dir, "anomaly_0_s5.npz")
    assert os.path.exists(path)
    assert not os.path.exists(os.path.join(ckpt_dir, "ckpt_0.npz"))
    meta = ckpt_lib.read_meta(path)
    assert meta["anomaly"] == "loss_spike" and meta["mid_epoch_step"] == 5
    assert counters.get("anomaly.findings") == 1
    assert counters.get("anomaly.snapshots") == 1
    # per-step TB scalars at the global step, loss + the device norms
    tags = {s[0] for s in scalars}
    assert {"step/loss", "step/grad_norm", "step/update_ratio"} <= tags
    assert (("step/loss", 8.0, 4)) in scalars


def test_observe_health_epoch_grain_snapshot_for_fused_path(tmp_path):
    """The fused path observes at step=None (epoch-mean loss only); the
    snapshot action must still write a checkpoint — a clean end-of-epoch
    one, stamped with the finding, NOT a silent degrade to warn."""
    import tpu_dist.ckpt as ckpt_lib
    from tpu_dist.train.trainer import Trainer

    ckpt_dir = str(tmp_path / "ckpt")
    t = Trainer(_tiny_cfg(
        anomaly_action="snapshot", anomaly_window=4, anomaly_loss_spike=2.0,
        ckpt_dir=ckpt_dir,
    ))
    for epoch, loss in enumerate([1.0, 1.1, 0.9, 1.0]):
        t._observe_health(epoch, None, 0, {"loss": loss})
    t._observe_health(4, None, 0, {"loss": 9.0})
    path = os.path.join(ckpt_dir, "anomaly_4.npz")
    assert os.path.exists(path)
    meta = ckpt_lib.read_meta(path)
    assert meta["anomaly"] == "loss_spike"
    assert "mid_epoch_step" not in meta  # clean epoch-boundary checkpoint
    assert counters.get("anomaly.snapshots") == 1


@pytest.mark.slow  # two short fits (~30 s): CI observability step + full suite
def test_e2e_device_metrics_run_logs_and_fetch_parity(tmp_path, monkeypatch):
    """Acceptance: a --device_metrics run writes device_stats records the
    summarize CLI reports, publishes the cost gauges, and issues EXACTLY
    as many per-step fetches as a metrics-off run (the fetch-count half
    of TD107)."""
    from tpu_dist.train import trainer as trainer_mod

    calls = []
    real_fetch = trainer_mod._fetch_metrics
    monkeypatch.setattr(
        trainer_mod, "_fetch_metrics",
        lambda m: (calls.append(1), real_fetch(m))[1],
    )
    counts = {}
    log = str(tmp_path / "dm.jsonl")
    for dm in (False, True):
        calls.clear()
        cfg = _tiny_cfg(
            device_metrics=dm, log_file=log if dm else None, epochs=1,
            steps_per_epoch=4, log_every=2,
        )
        trainer_mod.Trainer(cfg).fit()
        counts[dm] = len(calls)
    assert counts[False] == counts[True], counts
    recs = [json.loads(l) for l in open(log)]
    ds = [r for r in recs if r["kind"] == "device_stats"]
    assert ds and all(
        {"grad_norm", "param_norm", "update_ratio", "nonfinite_grads"}
        <= set(r) for r in ds
    )
    te = [r for r in recs if r["kind"] == "train_epoch"]
    assert te and te[0]["counters"]["device.flops_per_step"] > 0
    assert te[0]["counters"]["compile.events"] >= 1
    assert "compile.retraces" not in te[0]["counters"]  # clean run
    # the summarize CLI surfaces the device block
    from tpu_dist.obs.__main__ import main as obs_main

    assert obs_main(["summarize", log]) == 0


@pytest.mark.slow  # full fit (~15 s)
def test_e2e_mfu_reported_when_chip_peak_known(tmp_path, monkeypatch):
    """With a (stubbed) known chip peak, the epoch summary, the history
    record, and the compare scalars all carry MFU."""
    from tpu_dist.train import trainer as trainer_mod

    # a deliberately tiny stub peak: the tiny model's real flop count over
    # a CPU-emulation step time must still round to a nonzero "MFU"
    monkeypatch.setattr(costmodel, "chip_peak_flops", lambda kind=None: 1e6)
    log = str(tmp_path / "mfu.jsonl")
    cfg = _tiny_cfg(log_file=log, epochs=1, steps_per_epoch=4)
    result = trainer_mod.Trainer(cfg).fit()
    assert 0.0 < result["mfu"]
    te = [json.loads(l) for l in open(log) if '"train_epoch"' in l]
    assert te[0]["mfu"] == result["mfu"]
    from tpu_dist.obs.compare import load_history_scalars

    assert load_history_scalars(log)["mfu_mean"] == result["mfu"]


def test_fused_steps_per_epoch():
    from tpu_dist.train.epoch import fused_steps_per_epoch

    assert fused_steps_per_epoch(50_000, 256) == 195
    assert fused_steps_per_epoch(100, 256) == 1  # never zero trips
