"""The analyzer analyzed: fixture snippets per lint rule, suppression and
baseline mechanics, jaxpr-audit budgets, and the CLI gate contract
(exit 0 on the real repo, non-zero on a planted violation)."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from tpu_dist.analysis import baseline as baseline_lib
from tpu_dist.analysis.jaxpr_audit import (
    CollectiveBudget,
    _compare,
    audit_all,
    audit_case,
)
from tpu_dist.analysis.lint import lint_paths, lint_source

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(snippet: str, path: str = "tpu_dist/fake/mod.py"):
    return lint_source(textwrap.dedent(snippet), path)


def _rules(violations):
    return [v.rule for v in violations]


# -- TD001: host sync inside traced functions -------------------------------


def test_td001_item_in_jitted_fn():
    vs = _lint(
        """
        import jax

        @jax.jit
        def step(x):
            return x.item()
        """
    )
    assert _rules(vs) == ["TD001"]
    assert vs[0].line == 6


def test_td001_nested_factory_shard_map():
    # the factory itself is host code; its nested fn passed to shard_map is
    # traced — and helpers the traced fn calls are traced transitively
    vs = _lint(
        """
        import numpy as np
        from tpu_dist.comm.compat import shard_map

        def helper(x):
            return np.asarray(x)

        def make_step(mesh):
            def step_local(x):
                return helper(x) + 1
            return shard_map(step_local, mesh=mesh, in_specs=None, out_specs=None)
        """
    )
    assert _rules(vs) == ["TD001"]


def test_td001_host_code_not_flagged():
    vs = _lint(
        """
        import numpy as np

        def host_metrics(x):
            return float(np.asarray(x).mean())
        """
    )
    assert vs == []


# -- TD002: unguarded non-rank-0 I/O ---------------------------------------


def test_td002_unguarded_print():
    # an unguarded bare print is BOTH violations: every process duplicates
    # it (TD002) and it bypasses the logging layer (TD007)
    vs = _lint(
        """
        def log_epoch(loss):
            print(f"loss {loss}")
        """
    )
    assert _rules(vs) == ["TD002", "TD007"]


def test_td002_guard_spellings_pass():
    vs = _lint(
        """
        import jax
        from tpu_dist.comm.mesh import is_primary

        def a(loss):
            if jax.process_index() == 0:
                print(loss)

        def b(loss):
            if is_primary():
                print(loss)

        def c(rank, loss):
            if rank != 0:
                return
            print(loss)

        def d(path, rec):
            pid = jax.process_index()
            if pid != 0:
                return
            with open(path, "w") as f:
                f.write(rec)
        """
    )
    # every guard spelling satisfies TD002; the guarded PRINTS still carry
    # TD007 (the bare-print rule is guard-agnostic — route through
    # rank0_print), while the guarded file write carries nothing
    assert _rules(vs) == ["TD007", "TD007", "TD007"]


def test_td002_file_write_and_logger():
    vs = _lint(
        """
        import logging

        def dump(path, logger):
            logging.info("hi")
            logger.warning("hi")
            with open(path, "a") as f:
                f.write("x")
        """
    )
    assert sorted(_rules(vs)) == ["TD002", "TD002", "TD002"]


# -- TD007: bare print outside the logging layer ----------------------------


def test_td007_allowlist_paths():
    # the logging layer itself may print (it IS the sink)...
    vs = _lint("def f(x):\n    print(x)\n", "tpu_dist/metrics/logging.py")
    assert "TD007" not in _rules(vs)  # (TD002 still applies there)
    # ...as may the CLI report modules, exempt from both rules
    vs = _lint("def f(x):\n    print(x)\n", "tpu_dist/obs/__main__.py")
    assert _rules(vs) == []
    # everywhere else the print is flagged even under a rank-0 guard
    vs = _lint(
        """
        import jax

        def f(x):
            if jax.process_index() == 0:
                print(x)
        """
    )
    assert _rules(vs) == ["TD007"]


# -- TD003: hot-path jit without donation ----------------------------------


def test_td003_hot_factory_flagged_cold_not():
    vs = _lint(
        """
        import jax

        def make_train_step(f):
            return jax.jit(f)

        def make_eval_renderer(f):
            return jax.jit(f)

        def make_fused_epoch(f):
            return jax.jit(f, donate_argnums=(0,))
        """
    )
    assert _rules(vs) == ["TD003"]
    assert "make_train_step" in vs[0].message


# -- TD004: version-fragile imports ----------------------------------------


def test_td004_fragile_import_spellings():
    vs = _lint(
        """
        from jax import shard_map
        from jax.experimental.shard_map import shard_map as sm
        from jax.experimental import pjit
        """
    )
    assert _rules(vs) == ["TD004", "TD004", "TD004"]


def test_td004_compat_module_exempt_and_clean_import():
    assert _lint("from jax import shard_map\n", "tpu_dist/comm/compat.py") == []
    assert _lint("from tpu_dist.comm.compat import shard_map\n") == []


# -- TD005: trace-time nondeterminism --------------------------------------


def test_td005_np_random_and_time_in_trace():
    vs = _lint(
        """
        import time
        import numpy as np
        import jax

        @jax.jit
        def step(x):
            noise = np.random.rand(*x.shape)
            t0 = time.time()
            return x + noise + t0
        """
    )
    assert sorted(_rules(vs)) == ["TD005", "TD005"]


def test_td005_jax_random_and_host_np_random_ok():
    vs = _lint(
        """
        import numpy as np
        import jax

        @jax.jit
        def step(x, key):
            return x + jax.random.normal(key, x.shape)

        def host_shuffle(n):
            return np.random.default_rng(0).permutation(n)
        """
    )
    assert vs == []


# -- TD006: silently swallowed exceptions -----------------------------------


def test_td006_silent_pass_and_bare_except_flagged():
    vs = _lint(
        """
        def prune(path):
            try:
                remove(path)
            except OSError:
                pass

        def anything(x):
            try:
                return x()
            except:
                return None
        """
    )
    assert _rules(vs) == ["TD006", "TD006"]
    assert "OSError" in vs[0].message
    assert "bare" in vs[1].message


def test_td006_allowlisted_types_and_handled_bodies_pass():
    vs = _lint(
        """
        import queue

        def probe():
            try:
                import optional_dep
            except ImportError:
                pass
            try:
                cleanup()
            except FileNotFoundError:
                pass
            try:
                q.get_nowait()
            except queue.Empty:
                pass

        def handled():
            try:
                risky()
            except OSError as e:
                raise RuntimeError("risky failed") from e
        """
    )
    assert vs == []


def test_td006_tuple_needs_every_type_allowlisted():
    vs = _lint(
        """
        def mixed():
            try:
                go()
            except (FileNotFoundError, OSError):
                pass
        """
    )
    assert _rules(vs) == ["TD006"]


def test_td006_inline_suppression():
    vs = _lint(
        """
        def prune(path):
            try:
                remove(path)
            except OSError:  # tpu-dist: ignore[TD006] — best-effort prune
                pass
        """
    )
    assert vs == []


# -- TD008: rank-guarded collective call sites ------------------------------


def test_td008_rank_guarded_collective_flagged():
    vs = _lint(
        """
        import jax
        from jax import lax
        from tpu_dist.comm.collectives import barrier

        def bad_branch(x, rank):
            if rank == 0:
                return lax.pmean(x, "data")
            return x

        def bad_early_return(x, rank):
            if rank != 0:
                return x
            barrier()
            return x
        """
    )
    assert _rules(vs) == ["TD008", "TD008"]
    assert "pmean" in vs[0].message
    assert "deadlock" in vs[0].message


def test_td008_unguarded_and_host_guard_pass():
    # the correct shape: collective on EVERY rank, rank guard only
    # around the host-side action — plus the audited inline-ignore
    vs = _lint(
        """
        from jax import lax
        from tpu_dist.metrics.logging import rank0_print

        def good(x, rank):
            y = lax.pmean(x, "data")
            if rank == 0:
                rank0_print(y)
            return y

        def audited(x, rank):
            if rank == 0:
                return lax.pmean(x, "data")  # tpu-dist: ignore[TD008] — single-process tool
            return x
        """
    )
    assert vs == []


def test_td008_multihost_utils_and_polarity_inversion():
    vs = _lint(
        """
        from jax.experimental import multihost_utils

        def bad(tree, rank):
            if not rank:
                multihost_utils.sync_global_devices("ckpt")
        """
    )
    assert _rules(vs) == ["TD008"]


# -- suppressions & baseline ------------------------------------------------


def test_inline_and_block_suppressions():
    vs = _lint(
        """
        def a(loss):
            print(loss)  # tpu-dist: ignore[TD002,TD007]

        def b(loss):
            # tpu-dist: ignore[TD002, TD007] — multi-line explanation of why
            # this print is deliberate on every process
            print(loss)

        def c(loss):
            print(loss)  # tpu-dist: ignore[TD001]  (wrong rule: still flagged)
        """
    )
    assert _rules(vs) == ["TD002", "TD007"]
    assert vs[0].line == 11


def test_baseline_filters_and_reports_stale():
    vs = _lint(
        """
        def a(loss):
            print(loss)
        """
    )
    assert _rules(vs) == ["TD002", "TD007"]
    entries = [
        {"rule": "TD002", "path": "tpu_dist/fake/mod.py", "snippet": "print(loss)"},
        {"rule": "TD007", "path": "tpu_dist/fake/mod.py", "snippet": "print(loss)"},
        {"rule": "TD002", "path": "tpu_dist/fake/mod.py", "snippet": "print(gone)"},
    ]
    new, stale = baseline_lib.apply(vs, entries)
    assert new == []
    assert [e["snippet"] for e in stale] == ["print(gone)"]


# -- clean-file negative ----------------------------------------------------


def test_clean_realistic_module():
    vs = _lint(
        """
        import jax
        import jax.numpy as jnp
        from tpu_dist.comm.compat import shard_map
        from tpu_dist.metrics.logging import rank0_print

        def make_train_step(opt, mesh):
            def step_local(state, batch, key):
                x = batch + jax.random.normal(key, batch.shape)
                return state, jnp.mean(x)
            sharded = shard_map(
                step_local, mesh=mesh, in_specs=None, out_specs=None
            )
            return jax.jit(sharded, donate_argnums=(0,))

        def report(metrics):
            rank0_print(f"loss {metrics['loss']:.3f}")
        """
    )
    assert vs == []


@pytest.mark.quick  # the quick-slice analysis representative: pure-AST,
# no subprocess/jaxpr compile (test_cli_clean_on_repo moved to slow,
# ISSUE 17 tier-1 budget)
def test_repo_is_lint_clean():
    vs = lint_paths([os.path.join(REPO, "tpu_dist")], root=REPO)
    assert vs == [], "\n".join(v.format_text() for v in vs)


# -- Layer 2: jaxpr audit ---------------------------------------------------


def test_dp_step_collective_count():
    counts, violations = audit_case("dp_sgd")
    # THE data-parallel budget: one multi-operand grad pmean + three metric
    # reduces, nothing else (no transfers inside the step)
    assert counts["collectives"] == {"psum": 4}
    assert counts["transfers"] == 0
    assert violations == []


def test_grad_accum_adds_no_collectives():
    plain, _ = audit_case("dp_sgd")
    accum, violations = audit_case("dp_sgd_accum4")
    assert accum["collectives"] == plain["collectives"]  # no_sync contract
    assert violations == []


def test_zero1_swaps_allreduce_for_rs_ag():
    counts, violations = audit_case("zero1_sgd")
    assert counts["collectives"]["reduce_scatter"] == 1
    assert counts["collectives"]["all_gather"] == 1
    assert violations == []


def test_scan_body_collectives_count_per_trip():
    """A collective INSIDE a scan body multiplies by the trip count — the
    property that lets TD101 catch a grad reduce accidentally moved inside
    the accumulation scan (the no_sync violation), which would otherwise
    count the same as the single post-scan reduce."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from tpu_dist.analysis.jaxpr_audit import trace_counts
    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.comm.compat import shard_map

    mesh = mesh_lib.data_parallel_mesh()
    n = mesh.devices.size

    def local(x):  # 3 rows per device -> scan of length 3, one pmean per trip
        def body(c, t):
            return c + lax.pmean(t, mesh_lib.DATA_AXIS), None

        out, _ = lax.scan(body, jnp.zeros_like(x[0]), x)
        return out

    f = shard_map(local, mesh=mesh, in_specs=P("data"), out_specs=P(), check_vma=False)
    counts = trace_counts(f, jax.ShapeDtypeStruct((3 * n, 4), jnp.float32))
    assert counts["collectives"]["psum"] == 3, counts


@pytest.mark.slow  # tier-1 budget (ISSUE 17): gates in analysis.yml
def test_audit_all_clean_and_budget_mismatch_detected():
    report, violations = audit_all()
    assert violations == []
    assert set(report) >= {"dp_sgd", "dp_sgd_accum4", "dp_bf16", "zero1_sgd"}
    # a drifted budget must produce TD101
    counts, _ = audit_case("dp_sgd")
    vs = _compare("dp_sgd", counts, CollectiveBudget({"psum": 3}))
    assert [v.rule for v in vs] == ["TD101"]
    # and an undeclared bf16 promotion must produce TD103
    bf16, _ = audit_case("dp_bf16")
    vs = _compare(
        "dp_bf16",
        bf16,
        CollectiveBudget({"psum": 4}, bf16_to_f32=bf16["bf16_to_f32"] - 1),
    )
    assert [v.rule for v in vs] == ["TD103"]


# -- CLI gate contract ------------------------------------------------------


def _run_cli(args, cwd=REPO):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the CLI configures its own backend
    return subprocess.run(
        [sys.executable, "-m", "tpu_dist.analysis", *args],
        cwd=cwd,
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )


def test_cli_nonzero_on_planted_violation(tmp_path):
    bad = tmp_path / "bad_mod.py"
    bad.write_text(
        "from jax import shard_map\n"
        "def noisy(loss):\n"
        "    print(loss)\n"
    )
    r = _run_cli([str(bad), "--no-jaxpr", "--format", "json"])
    assert r.returncode == 1, r.stdout + r.stderr
    out = json.loads(r.stdout)
    assert {v["rule"] for v in out["violations"]} == {"TD002", "TD004", "TD007"}


@pytest.mark.slow  # tier-1 budget (ISSUE 17): gates in analysis.yml
def test_cli_clean_on_repo():
    # the acceptance gate: lint + jaxpr audit over the real package, exit 0
    r = _run_cli(["--format", "json"])
    assert r.returncode == 0, r.stdout + r.stderr
    out = json.loads(r.stdout)
    assert out["counts"]["new"] == 0
    assert out["jaxpr_report"]["dp_sgd"]["collectives"] == {"psum": 4}
