"""Cross-replica weight-update sharding (ZeRO-1, arXiv:2004.13336) ≡ the
plain allreduce+full-update path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tpu_dist.train.step import init_sharded_opt_state, make_train_step
from tests.helpers import TinyConvNet


def test_sharded_update_matches_plain():
    mesh = mesh_lib.data_parallel_mesh()
    model = TinyConvNet()
    opt = SGD()
    params, bn = model.init(jax.random.PRNGKey(0))

    plain_state = jax.device_put(
        TrainState.create(params, bn, opt), mesh_lib.replicated(mesh)
    )
    z1_state = TrainState(
        params=jax.device_put(params, mesh_lib.replicated(mesh)),
        bn_state=jax.device_put(bn, mesh_lib.replicated(mesh)),
        opt_state=init_sharded_opt_state(params, mesh),
        step=jax.device_put(jnp.zeros((), jnp.int32), mesh_lib.replicated(mesh)),
    )

    plain_step = make_train_step(model.apply, opt, mesh, donate=False)
    z1_step = make_train_step(
        model.apply, opt, mesh, donate=False, shard_weight_update=True
    )

    rng = np.random.default_rng(0)
    for i in range(3):
        x = mesh_lib.shard_batch(mesh, rng.normal(size=(64, 8, 8, 3)).astype(np.float32))
        y = mesh_lib.shard_batch(mesh, rng.integers(0, 10, 64).astype(np.int32))
        plain_state, mp = plain_step(plain_state, x, y, 0.1)
        z1_state, mz = z1_step(z1_state, x, y, 0.1)

    np.testing.assert_allclose(float(mp["loss"]), float(mz["loss"]), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(plain_state.params),
        jax.tree_util.tree_leaves(z1_state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


@pytest.mark.slow  # tier-1 budget (ISSUE 17): gates in analysis.yml
def test_trainer_zero1_e2e_with_resume(tmp_path):
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer, register_model
    from tests.helpers import tiny_resnet

    register_model("tiny_resnet_z1", lambda num_classes=10: tiny_resnet(num_classes))
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_z1", num_classes=10,
        batch_size=64, epochs=1, steps_per_epoch=3, log_every=10, lr=0.1,
        eval_every=0, shard_weight_update=True, ckpt_dir=str(tmp_path),
        save_every=1, synthetic_n=640,
    )
    t = Trainer(cfg)
    out = t.fit()
    assert np.isfinite(out["loss"])
    t2 = Trainer(cfg.replace(resume=True, epochs=2))
    assert t2.start_epoch == 1
    assert len(t2.state.opt_state.sharding.device_set) == 8
    out2 = t2.fit()
    assert np.isfinite(out2["loss"])


def test_sharded_opt_state_is_actually_sharded():
    mesh = mesh_lib.data_parallel_mesh()
    model = TinyConvNet()
    params, _ = model.init(jax.random.PRNGKey(0))
    b = init_sharded_opt_state(params, mesh)
    # 8 shards, each 1/8 of the padded flat length
    assert len(b.sharding.device_set) == 8
    shard = b.addressable_shards[0]
    assert shard.data.shape[0] == b.shape[0] // 8


def test_sharded_update_matches_plain_adamw():
    """ZeRO-1 generalizes past SGD (VERDICT r4 weak #3): AdamW's mu/nu ride
    the same flat-shard layout, and the 'auto' decay mask — rank-based, so
    invisible in a flat vector — is applied positionally (flat_wd). The
    flat path must match the plain per-leaf AdamW step exactly."""
    from tpu_dist.train.optim import AdamW

    mesh = mesh_lib.data_parallel_mesh()
    model = TinyConvNet()
    opt = AdamW(weight_decay=0.05)  # auto mask: conv/dense decayed, bias/bn not
    params, bn = model.init(jax.random.PRNGKey(0))

    plain_state = jax.device_put(
        TrainState.create(params, bn, opt), mesh_lib.replicated(mesh)
    )
    z1_state = TrainState(
        params=jax.device_put(params, mesh_lib.replicated(mesh)),
        bn_state=jax.device_put(bn, mesh_lib.replicated(mesh)),
        opt_state=init_sharded_opt_state(params, mesh, optimizer=opt),
        step=jax.device_put(jnp.zeros((), jnp.int32), mesh_lib.replicated(mesh)),
    )

    plain_step = make_train_step(model.apply, opt, mesh, donate=False)
    z1_step = make_train_step(
        model.apply, opt, mesh, donate=False, shard_weight_update=True
    )

    rng = np.random.default_rng(1)
    for _ in range(3):
        x = mesh_lib.shard_batch(mesh, rng.normal(size=(64, 8, 8, 3)).astype(np.float32))
        y = mesh_lib.shard_batch(mesh, rng.integers(0, 10, 64).astype(np.int32))
        plain_state, mp = plain_step(plain_state, x, y, 0.01)
        z1_state, mz = z1_step(z1_state, x, y, 0.01)

    np.testing.assert_allclose(float(mp["loss"]), float(mz["loss"]), rtol=1e-5)
    assert int(z1_state.opt_state["count"]) == 3
    for a, b in zip(
        jax.tree_util.tree_leaves(plain_state.params),
        jax.tree_util.tree_leaves(z1_state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


@pytest.mark.slow  # tier-1 budget (ISSUE 17): gates in analysis.yml
def test_trainer_zero1_adamw_e2e_with_resume(tmp_path):
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer, register_model
    from tests.helpers import tiny_resnet

    register_model("tiny_resnet_z1a", lambda num_classes=10: tiny_resnet(num_classes))
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_z1a", num_classes=10,
        batch_size=64, epochs=1, steps_per_epoch=3, log_every=10, lr=0.01,
        eval_every=0, shard_weight_update=True, optimizer="adamw",
        ckpt_dir=str(tmp_path), save_every=1, synthetic_n=640,
    )
    t = Trainer(cfg)
    out = t.fit()
    assert np.isfinite(out["loss"])
    t2 = Trainer(cfg.replace(resume=True, epochs=2))
    assert t2.start_epoch == 1
    # restored flat mu/nu stay 1/8-sharded; count restored
    assert len(t2.state.opt_state["mu"].sharding.device_set) == 8
    assert int(t2.state.opt_state["count"]) == 3
    out2 = t2.fit()
    assert np.isfinite(out2["loss"])
