"""Live telemetry: OpenMetrics export, alert rules, `obs tail`, TD109.

The live half of ``tpu_dist/obs`` (docs/observability.md "Live export"):

* exposition rendering against a strict OpenMetrics line grammar,
* atomic textfile publication (no torn exposition ever observable),
* the rank-0-only HTTP ``/metrics`` endpoint under concurrent scrapes,
* the alert engine's sustain / cooldown / delta state machine and the
  TOML/JSON spec loader (builtin library included),
* ``obs tail`` golden against a recorded JSONL + the torn-tail follower,
* heartbeat torn-read hardening (NFS atomic-replace races),
* bench capture fingerprints: ``compare --bench`` / ``summarize
  --bench`` flag byte-identical re-emitted captures as STALE,
* the TD109 jaxpr gate: exporter + alert engine armed ⇒ traced step
  byte-identical,
* e2e acceptance (slow): a live run scraped mid-flight — counter values
  match the JSONL for the same epoch window, a stall_frac rule fires an
  ``alert`` record + ``alert_active`` gauge in-run.
"""

import io
import json
import os
import re
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from tpu_dist.obs import alerts as alerts_lib
from tpu_dist.obs import counters
from tpu_dist.obs import export as export_lib
from tpu_dist.obs.export import MetricsExporter

_HERE = os.path.dirname(__file__)
_REPO_ROOT = os.path.dirname(os.path.abspath(_HERE))


# -- OpenMetrics rendering ---------------------------------------------------

# strict line grammar: TYPE declarations, samples (bare or one-label), EOF
_TYPE_RE = re.compile(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* gauge$")
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"\})?'
    r" -?[0-9].*$"
)


def _assert_valid_exposition(text: str):
    lines = text.splitlines()
    assert lines, "empty exposition"
    assert lines[-1] == "# EOF", f"missing # EOF terminator: {lines[-3:]}"
    assert text.endswith("# EOF\n")
    declared = set()
    for line in lines[:-1]:
        if line.startswith("# TYPE "):
            assert _TYPE_RE.match(line), f"bad TYPE line: {line!r}"
            declared.add(line.split()[2])
            continue
        assert _SAMPLE_RE.match(line), f"bad sample line: {line!r}"
        name = line.split("{", 1)[0].split(" ", 1)[0]
        assert name in declared, f"sample before its TYPE: {line!r}"
        value = line.rsplit(" ", 1)[1]
        float(value)  # must parse


def test_render_passes_strict_line_grammar():
    text = export_lib.render(
        {
            "train.steps": 42,
            "train.images_per_sec": 1234.5,
            "loader.data_wait_s": 0.25,
            "ckpt.bytes_written": 10_000_000,
        },
        {"alert_active": {"stall_high": 1.0, "mfu_low": 0.0}},
    )
    _assert_valid_exposition(text)


def test_render_skips_non_numeric_and_sanitizes_names():
    text = export_lib.render({
        "run.id": "abc-123",          # info gauge: not a number → skipped
        "run.grad_compression": "int8",
        "train.steps": 3,
        "weird name!": 1,
    })
    _assert_valid_exposition(text)
    assert "abc-123" not in text and "int8" not in text
    vals = export_lib.parse(text)
    assert vals[export_lib.metric_name("train.steps")] == 3
    assert export_lib.metric_name("weird name!") == "tpu_dist_weird_name_"
    assert vals["tpu_dist_weird_name_"] == 1


def test_metric_name_prefix_and_grammar():
    for raw in ("train.steps", "9lives", "a.b-c/d", "mem.bytes_in_use"):
        name = export_lib.metric_name(raw)
        assert name.startswith("tpu_dist_")
        assert re.match(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$", name), name


def test_parse_roundtrip_including_labels():
    text = export_lib.render(
        {"a.b": 1.5, "c": 2},
        {"alert_active": {"r1": 1.0}},
    )
    vals = export_lib.parse(text)
    assert vals[export_lib.metric_name("a.b")] == 1.5
    assert vals['tpu_dist_alert_active{rule="r1"}'] == 1.0


# -- textfile publication ----------------------------------------------------


def test_textfile_write_is_atomic_no_partial_observable(tmp_path):
    """A reader polling the textfile while the writer republishes in a
    tight loop must only ever see complete, EOF-terminated expositions —
    the tmp+rename discipline, observed from the outside."""
    path = str(tmp_path / "m.prom")
    ex = MetricsExporter(textfile=path, min_interval=0.0)
    stop = threading.Event()
    bad = []

    def reader():
        while not stop.is_set():
            try:
                with open(path) as f:
                    text = f.read()
            except FileNotFoundError:
                continue
            if not text.endswith("# EOF\n"):
                bad.append(text[-40:])

    t = threading.Thread(target=reader)
    t.start()
    try:
        for i in range(300):
            ex.update({"train.steps": i, "filler.value": i * 2.5}, force=True)
    finally:
        stop.set()
        t.join()
        ex.close()
    assert not bad, f"torn exposition observed: {bad[:3]}"
    _assert_valid_exposition(open(path).read())


def test_textfile_throttle_matches_heartbeat_grain(tmp_path):
    path = str(tmp_path / "m.prom")
    ex = MetricsExporter(textfile=path, min_interval=60.0)
    assert ex.update({"a": 1}) is True          # first write lands
    assert ex.update({"a": 2}) is False         # throttled
    assert export_lib.parse(open(path).read())["tpu_dist_a"] == 1
    assert ex.update({"a": 3}, force=True) is True  # force bypasses
    assert export_lib.parse(open(path).read())["tpu_dist_a"] == 3
    ex.close()


# -- HTTP endpoint -----------------------------------------------------------


def test_http_endpoint_refused_on_nonzero_rank():
    with pytest.raises(ValueError, match="rank-0-only"):
        MetricsExporter(port=0, rank=3)
    # textfile-only export works on any rank (per-rank derived paths)
    ex = MetricsExporter(rank=3)
    ex.close()


def test_http_endpoint_serves_last_snapshot_under_concurrent_scrapes():
    ex = MetricsExporter(port=0, rank=0)
    try:
        ex.update({"train.steps": 0}, force=True)
        url = f"http://127.0.0.1:{ex.port}/metrics"
        errors = []

        def scraper():
            for _ in range(20):
                try:
                    with urllib.request.urlopen(url, timeout=10) as r:
                        assert r.status == 200
                        ctype = r.headers["Content-Type"]
                        body = r.read().decode()
                    assert "openmetrics-text" in ctype
                    _assert_valid_exposition(body)
                except Exception as e:  # surfaced below with context
                    errors.append(repr(e))
                    return

        threads = [threading.Thread(target=scraper) for _ in range(4)]
        for t in threads:
            t.start()
        # republish concurrently with the scrape storm
        for i in range(50):
            ex.update({"train.steps": i}, force=True)
        for t in threads:
            t.join()
        assert not errors, errors
        # non-/metrics paths 404
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{ex.port}/nope", timeout=10
            )
    finally:
        ex.close()


def test_scrape_helper_reads_textfile_and_http(tmp_path):
    path = str(tmp_path / "m.prom")
    ex = MetricsExporter(textfile=path, port=0, rank=0)
    try:
        ex.update({"train.steps": 7}, force=True)
        for vals in (
            export_lib.scrape(textfile=path),
            export_lib.scrape(port=ex.port),
        ):
            assert vals[export_lib.metric_name("train.steps")] == 7
    finally:
        ex.close()
    assert export_lib.scrape(textfile=str(tmp_path / "absent")) is None
    assert export_lib.scrape() is None


# -- alert rules: spec loading ----------------------------------------------


def test_load_rules_default_library():
    rules = alerts_lib.load_rules("default")
    names = {r.name for r in rules}
    assert {"stall_high", "mfu_low", "goodput_low", "grad_norm_high",
            "heartbeat_stale", "retrace"} <= names


def test_load_rules_toml_with_builtin_override(tmp_path):
    spec = tmp_path / "rules.toml"
    spec.write_text(
        "# comment\n"
        "[[rule]]\n"
        'name = "stall"\n'
        'metric = "data_stall_frac"\n'
        'op = ">"\n'
        "threshold = 0.5\n"
        "sustain = 3\n"
        "cooldown = 2\n"
        "profile = true\n"
        "\n"
        "[[rule]]\n"
        'builtin = "mfu_low"\n'
        "threshold = 0.4\n"
    )
    rules = alerts_lib.load_rules(str(spec))
    assert len(rules) == 2
    stall, mfu = rules
    assert (stall.sustain, stall.cooldown, stall.profile) == (3, 2, True)
    assert mfu.name == "mfu_low" and mfu.threshold == 0.4
    assert mfu.op == "<"  # inherited from the builtin


def test_load_rules_json(tmp_path):
    spec = tmp_path / "rules.json"
    spec.write_text(json.dumps({"rule": [
        {"name": "r1", "metric": "m", "op": "<", "threshold": 1.0},
    ]}))
    (rule,) = alerts_lib.load_rules(str(spec))
    assert rule.name == "r1" and rule.sustain == 1


def test_example_rules_file_parses():
    # the shipped example must stay loadable (it is the docs' grammar)
    rules = alerts_lib.load_rules(
        os.path.join(_REPO_ROOT, "tools", "alert_rules.toml")
    )
    assert {r.name for r in rules} >= {"stall_high", "mfu_low", "retrace"}


@pytest.mark.parametrize("body,err", [
    ('[[rule]]\nname = "x"\nmetric = "m"\nop = "!!"\nthreshold = 1\n', "op"),
    ('[[rule]]\nname = "x"\nmetric = "m"\nop = ">"\nthreshold = 1\nsustain = 0\n',
     "sustain"),
    ('[[rule]]\nname = "x"\nmetric = "m"\nop = ">"\n', "missing"),
    ('[[rule]]\nbuiltin = "nope"\n', "builtin"),
    ('[[rule]]\nname = "x"\nmetric = "m"\nop = ">"\nthreshold = 1\nbogus = 2\n',
     "unknown field"),
    ('[[rule]]\nname = "x"\nmetric = "m"\nop = ">"\nthreshold = 1\n'
     '[[rule]]\nname = "x"\nmetric = "m"\nop = "<"\nthreshold = 2\n',
     "duplicate"),
    ('[[rule]]\nname = "x"\nmetric = "m"\nop = ">"\nthreshold = "0.3"\n',
     "threshold must be a number"),
], ids=["bad-op", "zero-sustain", "missing-fields", "unknown-builtin",
        "unknown-field", "dup-names", "quoted-threshold"])
def test_load_rules_rejects_malformed_specs(tmp_path, body, err):
    spec = tmp_path / "rules.toml"
    spec.write_text(body)
    with pytest.raises(ValueError, match=err):
        alerts_lib.load_rules(str(spec))


def test_load_rules_rejects_unknown_extension_and_empty(tmp_path):
    with pytest.raises(ValueError, match="toml"):
        alerts_lib.load_rules("rules.yaml")
    empty = tmp_path / "empty.toml"
    empty.write_text("# nothing\n")
    with pytest.raises(ValueError, match="non-empty"):
        alerts_lib.load_rules(str(empty))


# -- alert engine: sustain / cooldown / delta --------------------------------


def _engine(**kw):
    defaults = dict(name="r", metric="m", op=">", threshold=10.0)
    defaults.update(kw)
    return alerts_lib.AlertEngine([alerts_lib.AlertRule(**defaults)])


def test_sustain_requires_consecutive_breaches():
    eng = _engine(sustain=3)
    assert eng.observe({"m": 20}) == []
    assert eng.observe({"m": 20}) == []
    assert eng.observe({"m": 5}) == []     # clean window resets the streak
    assert eng.observe({"m": 20}) == []
    assert eng.observe({"m": 20}) == []
    fired = eng.observe({"m": 20})
    assert len(fired) == 1 and fired[0]["sustained"] == 3
    assert fired[0]["rule"] == "r" and fired[0]["op"] == ">"


def test_cooldown_suppresses_refire_then_releases():
    eng = _engine(sustain=1, cooldown=2)
    assert len(eng.observe({"m": 20})) == 1   # fires
    assert eng.observe({"m": 20}) == []       # cooldown 2→1
    assert eng.observe({"m": 20}) == []       # cooldown 1→0
    assert len(eng.observe({"m": 20})) == 1   # refires


def test_absent_metric_leaves_streak_untouched():
    eng = _engine(sustain=2)
    assert eng.observe({"m": 20}) == []
    # a window at another cadence without the metric: neither advance
    # nor reset (the mixed epoch/step feeding contract)
    assert eng.observe({"other": 1}) == []
    fired = eng.observe({"m": 20})
    assert len(fired) == 1


def test_delta_rule_fires_on_change_not_level():
    eng = _engine(metric="compile.retraces", threshold=0.0, delta=True)
    assert eng.observe({"compile.retraces": 5}) == []   # first sighting
    assert eng.observe({"compile.retraces": 5}) == []   # no change
    fired = eng.observe({"compile.retraces": 6})        # +1 this window
    assert len(fired) == 1 and fired[0]["value"] == 1.0
    assert fired[0].get("delta") is True


def test_seed_deltas_baselines_counters_born_mid_run():
    """A counter that does not exist yet (compile.retraces before the
    first retrace) must alert on its FIRST increment once seeded — not
    spend that increment establishing a baseline."""
    eng = _engine(metric="compile.retraces", threshold=0.0, delta=True)
    eng.seed_deltas({"train.steps": 5})        # retraces absent → baseline 0
    fired = eng.observe({"compile.retraces": 1})
    assert len(fired) == 1 and fired[0]["value"] == 1.0
    # seeding with a live value baselines there instead
    eng2 = _engine(metric="compile.retraces", threshold=0.0, delta=True)
    eng2.seed_deltas({"compile.retraces": 4})
    assert eng2.observe({"compile.retraces": 4}) == []
    assert len(eng2.observe({"compile.retraces": 5})) == 1


def test_active_gauge_tracks_sustained_state():
    eng = _engine(sustain=2, cooldown=10)
    eng.observe({"m": 20})
    assert eng.active() == {"r": 0.0}         # breaching, not yet sustained
    eng.observe({"m": 20})
    assert eng.active() == {"r": 1.0}         # fired
    eng.observe({"m": 20})
    assert eng.active() == {"r": 1.0}         # still breaching in cooldown
    eng.observe({"m": 1})
    assert eng.active() == {"r": 0.0}         # clean window clears it


def test_engine_rejects_duplicate_rule_names():
    r = alerts_lib.AlertRule("r", "m", ">", 1.0)
    with pytest.raises(ValueError, match="duplicate"):
        alerts_lib.AlertEngine([r, r])


# -- heartbeat torn-read hardening ------------------------------------------


def test_heartbeat_read_returns_previous_parse_on_torn_file(tmp_path):
    from tpu_dist.obs import heartbeat as hb_lib

    path = str(tmp_path / "hb.json")
    hb = hb_lib.Heartbeat(path)
    hb.beat(epoch=1, step=5, force=True)
    good = hb_lib.read(path)
    assert good["epoch"] == 1 and good["step"] == 5
    before = counters.get("heartbeat.torn_reads")
    # a torn write (atomic-replace race on NFS): truncate mid-JSON
    full = open(path).read()
    with open(path, "w") as f:
        f.write(full[: len(full) // 2])
    torn = hb_lib.read(path)
    assert torn == good                      # previous parse, not None
    assert counters.get("heartbeat.torn_reads") == before + 1
    # a genuinely absent file is still the clean-exit signal
    os.remove(path)
    assert hb_lib.read(path) is None
    # ...and the stale cache must not resurrect after the removal
    with open(path, "w") as f:
        f.write("{not json")
    assert hb_lib.read(path) is None


# -- bench capture fingerprints: stale detection -----------------------------


def _bench_rec(metric, value, cap):
    return {"metric": metric, "value": value, "unit": "images/sec",
            "mfu": 0.5, "capture": cap}


def test_compare_bench_flags_reemitted_capture_as_stale(tmp_path):
    from tpu_dist.obs import compare as compare_lib

    cap = {"host": "h1", "bench_run_id": "abc123", "mono_s": 10.0}
    fresh = {"host": "h1", "bench_run_id": "def456", "mono_s": 99.0}
    base = tmp_path / "base.json"
    cand = tmp_path / "cand.json"
    base.write_text(
        json.dumps(_bench_rec("m1", 100.0, cap)) + "\n"
        + json.dumps(_bench_rec("m2", 50.0, cap)) + "\n"
    )
    # candidate re-emits m1's capture byte-identically; m2 is fresh
    cand.write_text(
        json.dumps(_bench_rec("m1", 100.0, cap)) + "\n"
        + json.dumps(_bench_rec("m2", 52.0, fresh)) + "\n"
    )
    result = compare_lib.compare_files(
        str(base), str(cand), threshold=0.05, bench=True
    )
    stale_rows = [r for r in result["rows"] if r["verdict"] == "STALE"]
    assert len(stale_rows) == 1 and stale_rows[0]["metric"] == "m1"
    assert result["stale"] == 1
    assert result["regressions"] == 0
    # stale rows never count as compared — an all-stale candidate
    # compares nothing and the CLI exits 2 (broken gate, never a pass)
    cand.write_text(
        json.dumps(_bench_rec("m1", 100.0, cap)) + "\n"
        + json.dumps(_bench_rec("m2", 50.0, cap)) + "\n"
    )
    from tpu_dist.obs.__main__ import main as obs_main

    rc = obs_main(["compare", str(base), str(cand), "--bench"])
    assert rc == 2


def test_compare_bench_flags_selfdeclared_stale_fallback(tmp_path):
    """bench's last-good fallback stamps stale:true on the record it
    re-emits (fresh fingerprint or none at all) — the gate must flag it,
    not compare it as a fresh measurement."""
    from tpu_dist.obs import compare as compare_lib

    base = tmp_path / "base.json"
    cand = tmp_path / "cand.json"
    base.write_text(json.dumps(_bench_rec(
        "m1", 100.0, {"host": "h1", "bench_run_id": "aaa111", "mono_s": 1.0}
    )) + "\n")
    cand.write_text(json.dumps({
        **_bench_rec("m1", 100.0,
                     {"host": "h1", "bench_run_id": "bbb222", "mono_s": 2.0}),
        "stale": True,
    }) + "\n")
    result = compare_lib.compare_files(
        str(base), str(cand), threshold=0.05, bench=True
    )
    assert result["stale"] == 1 and result["compared"] == 0
    (row,) = result["rows"]
    assert row["verdict"] == "STALE" and row["candidate"] == "stale capture"


def test_bench_summarize_flags_duplicate_and_selfdeclared_stale(tmp_path, capsys):
    from tpu_dist.obs.__main__ import main as obs_main

    cap = {"host": "h1", "bench_run_id": "abc123", "mono_s": 10.0}
    path = tmp_path / "bench.json"
    path.write_text(
        json.dumps(_bench_rec("m1", 100.0, cap)) + "\n"
        + json.dumps(_bench_rec("m1_again", 100.0, cap)) + "\n"  # re-emission
        + json.dumps({"metric": "legacy", "value": 1.0}) + "\n"  # pre-stamp
        + json.dumps({"metric": "fallback", "value": 2.0, "stale": True,
                      "age_days": 30,
                      "capture": {"host": "h1", "bench_run_id": "zzz",
                                  "mono_s": 1.0}}) + "\n"
    )
    assert obs_main(["summarize", str(path), "--bench"]) == 0
    out = capsys.readouterr().out
    assert "2 STALE" in out
    assert "re-emits m1" in out
    assert "1 without capture fingerprint" in out
    assert "30d old" in out


def test_bench_stamps_capture_fingerprint():
    import bench

    rec = bench._stamped({"metric": "x", "value": 1.0})
    cap = rec["capture"]
    assert cap["host"] == socket.gethostname()
    assert re.match(r"^[0-9a-f]{12}$", cap["bench_run_id"])
    assert isinstance(cap["mono_s"], float)
    # two records from one process share the invocation id but carry
    # distinct capture instants — only a byte-identical COPY matches
    rec2 = bench._stamped({"metric": "y", "value": 2.0})
    assert rec2["capture"]["bench_run_id"] == cap["bench_run_id"]


# -- obs tail ----------------------------------------------------------------


def test_log_follower_consumes_only_complete_lines(tmp_path):
    from tpu_dist.obs.tail import LogFollower

    path = str(tmp_path / "run.jsonl")
    f = open(path, "w")
    fol = LogFollower(path)
    assert fol.poll() == []
    f.write('{"kind": "train_epoch", "epoch": 0}\n{"kind": "ev')
    f.flush()
    recs = fol.poll()
    assert [r["kind"] for r in recs] == ["train_epoch"]  # torn tail held
    f.write('al", "epoch": 0}\n')
    f.flush()
    recs = fol.poll()
    assert [r["kind"] for r in recs] == ["eval"]         # completed now
    # garbage line: counted, not fatal (the summarize tolerance)
    f.write("not json\n")
    f.flush()
    assert fol.poll() == []
    assert fol.bad_lines == 1
    f.close()


def test_log_follower_resets_on_truncation(tmp_path):
    from tpu_dist.obs.tail import LogFollower

    path = str(tmp_path / "run.jsonl")
    with open(path, "w") as f:
        f.write('{"kind": "train_epoch", "epoch": 0}\n')
    fol = LogFollower(path)
    assert len(fol.poll()) == 1
    with open(path, "w") as f:  # rotated: a fresh run reused the path
        f.write('{"kind": "eval", "epoch": 7}\n')
    recs = fol.poll()
    # detection is size-based (a shrunken file resets the cursor); the
    # rotated content is re-read from the start
    assert len(recs) == 1 and recs[0]["epoch"] == 7


_GOLDEN_RECORDS = [
    {"kind": "train_epoch", "epoch": 0, "run_id": "r1", "schema_version": 5,
     "images_per_sec": 1234.5, "step_time_p50": 0.012,
     "data_stall_frac": 0.05, "mfu": 0.41, "loss": 2.31},
    {"kind": "goodput", "epoch": 0, "run_id": "r1",
     "window_s": 10.0, "productive_s": 8.0},
    {"kind": "eval", "epoch": 0, "run_id": "r1", "top1": 12.5},
    {"kind": "train_epoch", "epoch": 1, "run_id": "r1", "schema_version": 5,
     "images_per_sec": 1500.0, "step_time_p50": 0.010,
     "data_stall_frac": 0.35, "mfu": 0.45, "loss": 2.10},
    {"kind": "alert", "epoch": 1, "run_id": "r1", "rule": "stall_high",
     "metric": "data_stall_frac", "value": 0.35, "op": ">",
     "threshold": 0.3, "sustained": 2},
    {"kind": "straggler", "epoch": 1, "run_id": "r1", "worst_rank": 3,
     "skew": 1.8},
    {"kind": "anomaly", "epoch": 1, "step": 4, "run_id": "r1",
     "anomaly": "loss_spike", "value": 9.9},
]

_GOLDEN_EXPECTED = (
    "run r1 — 7 record(s), 2 epoch(s), 1 alert(s) fired",
    "epoch     img/s   p50_ms  stall%    mfu  goodput      loss  val_top1",
    "    0    1234.5     12.0     5.0  0.410    80.0%    2.3100     12.50",
    "    1    1500.0     10.0    35.0  0.450        -    2.1000         -",
    "  ALERT stall_high: data_stall_frac 0.35 > 0.3 (sustained 2 "
    "window(s), epoch 1)",
    "  straggler: process 3 at 1.8x median (epoch 1)",
    "  anomaly loss_spike at epoch 1 step 4: value 9.9",
    "heartbeat: #9 epoch 1 step 4 phase 'train', age 2.5s",
)


def test_tail_golden_render_from_recorded_jsonl(tmp_path):
    """The dashboard frame is a stable, deterministic rendering of a
    recorded log (fixed clock injected) — the golden the docs quote."""
    from tpu_dist.obs.tail import LogFollower, TailState

    path = str(tmp_path / "run.jsonl")
    with open(path, "w") as f:
        for rec in _GOLDEN_RECORDS:
            f.write(json.dumps(rec) + "\n")
    state = TailState()
    state.add(LogFollower(path).poll())
    hb = {"counter": 9, "epoch": 1, "step": 4, "phase": "train", "ts": 100.0}
    out = state.render(hb, now_wall=102.5)
    assert out == "\n".join(_GOLDEN_EXPECTED), out


def test_tail_marks_stale_heartbeat_and_resume_segments():
    from tpu_dist.obs.tail import TailState

    state = TailState()
    state.add([
        {"kind": "train_epoch", "epoch": 0, "run_id": "a", "loss": 1.0},
        {"kind": "train_epoch", "epoch": 1, "run_id": "b", "loss": 0.9},
    ])
    out = state.render(
        {"counter": 1, "epoch": 1, "step": 0, "phase": "train", "ts": 0.0},
        now_wall=120.0,
    )
    assert "STALE" in out                      # 120s-old beat
    assert "resumed: new segment b" in out


def test_tail_cli_once_renders_and_exits(tmp_path, capsys):
    from tpu_dist.obs.__main__ import main as obs_main

    path = tmp_path / "run.jsonl"
    with open(path, "w") as f:
        for rec in _GOLDEN_RECORDS:
            f.write(json.dumps(rec) + "\n")
    assert obs_main(["tail", str(path), "--once"]) == 0
    out = capsys.readouterr().out
    assert "run r1" in out and "ALERT stall_high" in out
    # an empty/absent log is exit 1, like the other subcommands
    assert obs_main(["tail", str(tmp_path / "absent.jsonl"), "--once"]) == 1


def test_tail_follow_exits_on_final_record(tmp_path):
    """Follow mode: a concurrent writer appends epochs then the run-end
    totals record; the loop must pick them up incrementally and exit."""
    from tpu_dist.obs.tail import run_tail

    path = str(tmp_path / "run.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps(_GOLDEN_RECORDS[0]) + "\n")

    def writer():
        time.sleep(0.3)
        with open(path, "a") as f:
            f.write(json.dumps(_GOLDEN_RECORDS[3]) + "\n")
            f.flush()
            time.sleep(0.3)
            f.write(json.dumps({
                "kind": "goodput", "final": True, "run_id": "r1",
                "goodput_frac": 0.7, "elapsed_s": 12.0,
            }) + "\n")

    t = threading.Thread(target=writer)
    t.start()
    buf = io.StringIO()
    rc = run_tail(path, interval=0.1, stream=buf)
    t.join()
    assert rc == 0
    out = buf.getvalue()
    assert "run ended: goodput 70.0%" in out
    assert "1500.0" in out                     # the appended epoch arrived


# -- summarize: alert records ------------------------------------------------


def test_summarize_folds_alert_records():
    from tpu_dist.obs.summarize import format_text, summarize

    report = summarize(_GOLDEN_RECORDS)
    assert report["alerts"] == [{
        "epoch": 1, "rule": "stall_high", "metric": "data_stall_frac",
        "value": 0.35, "threshold": 0.3, "op": ">", "sustained": 2,
    }]
    text = format_text(report)
    assert "alert: stall_high fired at epoch 1" in text
    assert "sustained 2 window(s)" in text


# -- TD109 -------------------------------------------------------------------


def test_td109_live_export_noop_gate():
    from tpu_dist.analysis.jaxpr_audit import live_export_noop_violations

    assert live_export_noop_violations() == []


def test_td109_rule_registered():
    from tpu_dist.analysis.rules import RULES

    assert "TD109" in RULES


# -- e2e acceptance ----------------------------------------------------------


@pytest.mark.slow  # full trainer fit (~20 s incl. compiles): excluded from
# the timed tier-1 gate; gates in the CI export step, which runs this
# module without the slow filter
def test_e2e_live_run_scrape_matches_jsonl_and_stall_rule_fires(tmp_path):
    """Acceptance: during a live run, scraping rank 0's /metrics (and
    reading --metrics_file) returns OpenMetrics-parseable output whose
    counter values match the JSONL for the same epoch window, and a
    threshold rule on stall_frac demonstrably fires an ``alert`` record
    + ``alert_active`` exporter gauge in-run."""
    from tests.helpers import tiny_resnet
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer, register_model

    register_model(
        "tiny_live_e2e", lambda num_classes=10: tiny_resnet(num_classes)
    )
    log = str(tmp_path / "run.jsonl")
    mf = str(tmp_path / "metrics.prom")
    rules = tmp_path / "rules.toml"
    # any measured stall sustains this rule from epoch 0 — the point is
    # to watch the full fire path (record + gauge) on a real run
    rules.write_text(
        "[[rule]]\n"
        'name = "stall_watch"\n'
        'metric = "data_stall_frac"\n'
        'op = ">="\n'
        "threshold = 0.0\n"
        "sustain = 1\n"
        "cooldown = 0\n"
    )
    with socket.socket() as s:  # cfg takes a real port (0 means off)
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_live_e2e", num_classes=10,
        batch_size=64, epochs=2, steps_per_epoch=3, eval_every=0,
        synthetic_n=640, log_every=2, log_file=log, seed=0,
        metrics_file=mf, metrics_port=port, alert_rules=str(rules),
        heartbeat_file=str(tmp_path / "hb.json"),
    )
    trainer = Trainer(cfg)

    scrapes = []
    stop = threading.Event()

    def scraper():
        # live mid-run scrapes of BOTH surfaces, concurrent with training
        while not stop.is_set():
            port = trainer._exporter.port if trainer._exporter else None
            if port:
                try:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics", timeout=5
                    ) as r:
                        scrapes.append(r.read().decode())
                except OSError:
                    pass
            time.sleep(0.1)

    t = threading.Thread(target=scraper)
    t.start()
    try:
        trainer.fit()
    finally:
        stop.set()
        t.join()
    assert scrapes, "no live scrape landed during the run"
    for text in scrapes:
        _assert_valid_exposition(text)
    # the textfile's final exposition survives the run (left behind by
    # design) and its counters match the JSONL's last snapshot exactly
    final = export_lib.parse(open(mf).read())
    records = [json.loads(line) for line in open(log)]
    last_counters = [
        r["counters"] for r in records if isinstance(r.get("counters"), dict)
    ][-1]
    for name in ("train.steps", "train.epochs", "heartbeat.beats",
                 "loader.batches_consumed", "alerts.fired"):
        assert final[export_lib.metric_name(name)] == pytest.approx(
            last_counters[name]
        ), name
    # per-epoch-window match: a mid-run scrape taken at the epoch-1
    # boundary carries epoch 0's closed rollup — its train.steps gauge
    # must equal the JSONL train_epoch record's counter for that window
    epoch_recs = [r for r in records if r.get("kind") == "train_epoch"]
    assert len(epoch_recs) == 2
    mid = [
        export_lib.parse(s) for s in scrapes
        if export_lib.parse(s).get(export_lib.metric_name("train.epoch")) == 0
    ]
    if mid:  # timing-dependent which scrapes landed inside epoch 0's window
        assert mid[-1][export_lib.metric_name("train.steps")] <= (
            epoch_recs[0]["counters"]["train.steps"]
        )
    # the stall rule fired in-run: alert record in the JSONL...
    alerts = [r for r in records if r.get("kind") == "alert"]
    assert alerts and alerts[0]["rule"] == "stall_watch"
    assert alerts[0]["metric"] == "data_stall_frac"
    assert records[0]["schema_version"] == 15  # v15: causal decision tracing (ISSUE 19)
    # ...and the exporter gauge flipped (active through the final window:
    # cooldown 0 + every epoch breaches, so the last exposition holds 1)
    assert final['tpu_dist_alert_active{rule="stall_watch"}'] == 1.0
    # the dashboard renders the finished run (CLI smoke over real data)
    from tpu_dist.obs.tail import LogFollower, TailState

    state = TailState()
    state.add(LogFollower(log).poll())
    frame = state.render(None)
    assert "ALERT stall_watch" in frame and "run ended" in frame


@pytest.mark.slow  # two coordinated trainer processes (~1 min): excluded
# from the timed tier-1 gate; gates in the CI export step. Skips where the
# jaxlib CPU backend lacks cross-process collectives (the test_multihost
# contract).
def test_e2e_two_process_run_rank0_endpoint_and_per_rank_textfiles(tmp_path):
    """A REAL 2-process CPU run under the launcher: rank 0 binds the
    /metrics endpoint and is scraped live from outside, rank 1 serves no
    endpoint but writes its derived .h1 textfile — and the watchdog
    plumbing (--metrics_dir) injects the paths."""
    port = None
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO_ROOT
    env.pop("XLA_FLAGS", None)
    mdir = tmp_path / "metrics"
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "tpu_dist.cli.launch",
            "--nproc", "2", "--devices_per_proc", "1",
            "--metrics_dir", str(mdir), "--",
            sys.executable, "-m", "tpu_dist.cli.train",
            "--dataset", "synthetic", "--model", "resnet18",
            "--num_classes", "100", "--synthetic_n", "256",
            "--batch_size", "32", "--epochs", "2", "--steps_per_epoch", "2",
            "--eval_every", "0", "--seed", "0", "--log_every", "1",
            "--metrics_port", str(port),
            "--log_file", str(tmp_path / "run.jsonl"),
        ],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=_REPO_ROOT,
    )
    scrapes = []
    try:
        deadline = time.monotonic() + 240
        while proc.poll() is None and time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=2
                ) as r:
                    scrapes.append(r.read().decode())
            except OSError:
                pass
            time.sleep(0.25)
        out, _ = proc.communicate(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    if "Multiprocess computations aren't implemented on the CPU backend" in out:
        pytest.skip("CPU backend lacks multiprocess collectives in this jaxlib")
    assert proc.returncode == 0, out
    for text in scrapes:
        _assert_valid_exposition(text)
    # per-rank textfiles: rank 0 bare, rank 1 derived .h1 — and rank 1
    # never bound a port (a second bind on the same port would have
    # crashed the run; the rank-0-only refusal is also unit-tested)
    base = str(mdir / "metrics.prom")
    v0 = export_lib.scrape(textfile=base)
    v1 = export_lib.scrape(textfile=base + ".h1")
    assert v0 and v1
    assert v0[export_lib.metric_name("train.steps")] == 4
    assert v1[export_lib.metric_name("train.steps")] == 4
