"""Pallas fused SGD kernel ≡ the plain jnp update (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist.ops.fused_sgd import fused_sgd_leaf
from tpu_dist.train.optim import SGD


@pytest.mark.parametrize("shape", [(7,), (128,), (33, 5), (3, 3, 4, 16), (1000,)])
def test_fused_leaf_matches_plain(shape):
    rng = np.random.default_rng(0)
    p = jnp.asarray(rng.normal(size=shape), jnp.float32)
    g = jnp.asarray(rng.normal(size=shape), jnp.float32)
    b = jnp.asarray(rng.normal(size=shape), jnp.float32)
    lr, mu, wd = 0.1, 0.9, 1e-4

    new_p, new_b = fused_sgd_leaf(p, g, b, lr, momentum=mu, weight_decay=wd)

    gg = g + wd * p
    bb = mu * b + gg
    np.testing.assert_allclose(np.asarray(new_b), np.asarray(bb), rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(new_p), np.asarray(p - lr * bb), rtol=1e-6, atol=1e-7)


def test_fused_optimizer_matches_plain_on_tree():
    rng = np.random.default_rng(1)
    params = {
        "a": jnp.asarray(rng.normal(size=(17, 9)), jnp.float32),
        "nested": {"b": jnp.asarray(rng.normal(size=(130,)), jnp.float32)},
    }
    grads = jax.tree_util.tree_map(
        lambda t: jnp.asarray(rng.normal(size=t.shape), jnp.float32), params
    )

    plain, fused = SGD(), SGD(fused=True)
    sp = plain.init(params)
    sf = fused.init(params)
    pp, pg = params, sp
    fp, fg = params, sf
    for i in range(3):
        pp, pg = plain.update(grads, pg, pp, 0.05)
        fp, fg = fused.update(grads, fg, fp, 0.05)

    for a, b in zip(jax.tree_util.tree_leaves(pp), jax.tree_util.tree_leaves(fp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)


def test_fused_under_jit():
    p = jnp.ones((64, 64))
    g = jnp.full((64, 64), 0.5)
    b = jnp.zeros((64, 64))

    @jax.jit
    def step(p, g, b, lr):
        return fused_sgd_leaf(p, g, b, lr)

    new_p, new_b = step(p, g, b, 0.1)
    expect_b = 0.5 + 1e-4
    np.testing.assert_allclose(np.asarray(new_b), np.full((64, 64), expect_b), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(new_p), np.full((64, 64), 1 - 0.1 * expect_b), rtol=1e-6)


def test_fused_nesterov_rejected():
    with pytest.raises(ValueError, match="nesterov"):
        SGD(fused=True, nesterov=True)
