"""DistributedSampler semantics (reference torch sampler contract,
``distributed.py:70,74,81``)."""

import numpy as np
import pytest

from tpu_dist.data.sampler import DistributedSampler


def test_shards_partition_everything():
    n, shards = 103, 4
    samplers = [DistributedSampler(n, shards, i, shuffle=True, seed=7) for i in range(shards)]
    allidx = np.concatenate([s.indices() for s in samplers])
    # padded total divides evenly; union covers all examples
    assert len(allidx) == samplers[0].total_size == 104
    assert set(allidx.tolist()) == set(range(n))


def test_same_permutation_across_shards():
    a = DistributedSampler(100, 4, 0, seed=3)
    b = DistributedSampler(100, 4, 1, seed=3)
    a.set_epoch(5)
    b.set_epoch(5)
    # interleaved: shard i takes positions i, i+4, ... of ONE global order
    ga, gb = a.indices(), b.indices()
    assert len(set(ga) & set(gb)) == 0


def test_set_epoch_changes_order():
    s = DistributedSampler(100, 2, 0, seed=0)
    s.set_epoch(0)
    e0 = s.indices().copy()
    s.set_epoch(1)
    e1 = s.indices().copy()
    assert not np.array_equal(e0, e1)
    s.set_epoch(0)
    assert np.array_equal(s.indices(), e0)  # deterministic per epoch


def test_no_shuffle_is_identity_order():
    s = DistributedSampler(8, 2, 0, shuffle=False)
    assert s.indices().tolist() == [0, 2, 4, 6]


def test_pad_mask_marks_wraparound():
    # 10 examples over 4 shards -> total 12, two pads at global tail
    samplers = [DistributedSampler(10, 4, i, shuffle=False) for i in range(4)]
    masks = [s.pad_mask() for s in samplers]
    assert sum(int(m.sum()) for m in masks) == 10
    real = sum((s.indices()[m]).tolist().__len__() for s, m in zip(samplers, masks))
    assert real == 10


def test_drop_last():
    s = DistributedSampler(103, 4, 3, drop_last=True)
    assert len(s) == 25
    assert s.pad_mask().all()


def test_bad_shard_id():
    with pytest.raises(ValueError):
        DistributedSampler(10, 2, 2)
