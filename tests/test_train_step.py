"""The compiled data-parallel train step: convergence, DP-equivalence,
grad-accum ``no_sync`` semantics, bf16 policy (SURVEY §4 test strategy)."""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tpu_dist.train.step import make_train_step
from tests.helpers import TinyConvNet, TinyMLP


def _state(model, mesh, seed=0):
    params, bn = model.init(jax.random.PRNGKey(seed))
    st = TrainState.create(params, bn, SGD())
    return jax.device_put(st, mesh_lib.replicated(mesh))


def _batch(mesh, n=64, c=10, hw=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, hw, hw, 3)).astype(np.float32)
    y = rng.integers(0, c, n).astype(np.int32)
    return mesh_lib.shard_batch(mesh, x), mesh_lib.shard_batch(mesh, y), x, y


def test_loss_decreases():
    mesh = mesh_lib.data_parallel_mesh()
    model = TinyConvNet()
    opt = SGD()
    step = make_train_step(model.apply, opt, mesh)
    state = _state(model, mesh)
    xs, ys, _, _ = _batch(mesh)
    losses = []
    for _ in range(60):
        state, m = step(state, xs, ys, 0.1)
        losses.append(float(m["loss"]))
    # tiny model + random labels: expect clear but not dramatic memorization
    assert losses[-1] < losses[0] - 0.2, losses[::20]
    assert int(state.step) == 60


def test_dp_equivalence_8dev_vs_1dev():
    """Same seed + same global batch: 8-device pmean'd step ≡ 1-device step
    (the DDP≡DP-on-TPU claim; reference's integration check, SURVEY §4)."""
    model = TinyConvNet()
    opt = SGD()
    mesh8 = mesh_lib.data_parallel_mesh()
    mesh1 = mesh_lib.device_mesh([1], ["data"], jax.devices()[:1])

    s8 = _state(model, mesh8)
    s1 = _state(model, mesh1)
    step8 = make_train_step(model.apply, opt, mesh8, sync_bn=True, donate=False)
    step1 = make_train_step(model.apply, opt, mesh1, sync_bn=True, donate=False)

    for i in range(3):
        x8, y8, xh, yh = _batch(mesh8, seed=i)
        x1 = mesh_lib.shard_batch(mesh1, xh)
        y1 = mesh_lib.shard_batch(mesh1, yh)
        s8, m8 = step8(s8, x8, y8, 0.1)
        s1, m1 = step1(s1, x1, y1, 0.1)

    np.testing.assert_allclose(float(m8["loss"]), float(m1["loss"]), rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(s8.params), jax.tree_util.tree_leaves(s1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)
    for a, b in zip(jax.tree_util.tree_leaves(s8.bn_state), jax.tree_util.tree_leaves(s1.bn_state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


def test_grad_accum_no_sync_equivalence():
    """K sub-batches with one boundary pmean ≡ single big batch (torch
    no_sync semantics, distributed_gradient_accumulation.py:99-111).
    Exact on a BN-free model."""
    model = TinyMLP(in_dim=8 * 8 * 3)
    opt = SGD()
    mesh = mesh_lib.data_parallel_mesh()
    s0 = _state(model, mesh)

    xs, ys, _, _ = _batch(mesh)
    out = {}
    for k in (1, 2, 4):
        step = make_train_step(model.apply, opt, mesh, grad_accum_steps=k, donate=False)
        s, m = step(s0, xs, ys, 0.1)
        out[k] = (np.asarray(jax.tree_util.tree_leaves(s.params)[0]), float(m["loss"]))

    for k in (2, 4):
        np.testing.assert_allclose(out[k][0], out[1][0], rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(out[k][1], out[1][1], rtol=1e-5)


def test_bf16_policy_keeps_master_f32():
    model = TinyConvNet()
    opt = SGD()
    mesh = mesh_lib.data_parallel_mesh()
    step = make_train_step(model.apply, opt, mesh, compute_dtype=jnp.bfloat16)
    state = _state(model, mesh)
    xs, ys, _, _ = _batch(mesh)
    state, m = step(state, xs, ys, 0.1)
    # master params stay f32 (apex-AMP replacement: bf16 compute only)
    assert all(t.dtype == jnp.float32 for t in jax.tree_util.tree_leaves(state.params))
    assert np.isfinite(float(m["loss"]))


def test_sync_bn_toggle_changes_training():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8, 8, 3)).astype(np.float32)
    x[:32] += 5.0  # replica-dependent distribution
    y = rng.integers(0, 10, 64).astype(np.int32)
    model = TinyConvNet()
    opt = SGD()
    mesh = mesh_lib.data_parallel_mesh()
    xs, ys = mesh_lib.shard_batch(mesh, x), mesh_lib.shard_batch(mesh, y)

    outs = {}
    for sync in (True, False):
        step = make_train_step(model.apply, opt, mesh, sync_bn=sync, donate=False)
        s, _ = step(_state(model, mesh), xs, ys, 0.1)
        outs[sync] = np.asarray(s.bn_state["bn"]["var"])
    # running MEANS coincide (avg of local means == global mean), but the
    # variance distinguishes: avg of local vars < global var when replica
    # distributions differ (law of total variance)
    assert not np.allclose(outs[True], outs[False])
    assert outs[False].mean() < outs[True].mean()


def test_grad_compression_bf16_close_not_identical():
    """--grad_compression bf16 (DDP bf16_compress_hook equivalent): the
    wire format of the cross-replica reduce changes, the update math stays
    f32 — one step lands within bf16 rounding of the uncompressed step,
    while actually differing (proof the cast happened)."""
    mesh = mesh_lib.data_parallel_mesh()
    model = TinyConvNet()
    opt = SGD()
    xs, ys, _, _ = _batch(mesh)

    plain = make_train_step(model.apply, opt, mesh, donate=False)
    comp = make_train_step(
        model.apply, opt, mesh, donate=False, grad_compression="bf16"
    )
    s0 = _state(model, mesh)
    s_plain, _ = plain(s0, xs, ys, 0.1)
    s_comp, _ = comp(s0, xs, ys, 0.1)

    diffs = []
    for a, b in zip(
        jax.tree_util.tree_leaves(s_plain.params),
        jax.tree_util.tree_leaves(s_comp.params),
    ):
        a, b = np.asarray(a), np.asarray(b)
        assert a.dtype == b.dtype == np.float32  # update stays f32
        np.testing.assert_allclose(b, a, rtol=2e-2, atol=2e-3)
        diffs.append(float(np.abs(a - b).max()))
    assert max(diffs) > 0.0, "compressed path produced bit-identical params"


def test_grad_compression_composes_with_accum_and_zero1():
    from tpu_dist.train.step import init_sharded_opt_state

    mesh = mesh_lib.data_parallel_mesh()
    model = TinyConvNet()
    opt = SGD()
    xs, ys, _, _ = _batch(mesh)

    # grad accumulation: local f32 accumulation, compressed boundary reduce
    step_ga = make_train_step(
        model.apply, opt, mesh, grad_accum_steps=2, grad_compression="bf16",
        donate=False,
    )
    s_ga, m = step_ga(_state(model, mesh), xs, ys, 0.1)
    assert np.isfinite(float(m["loss"]))

    # ZeRO-1: compressed reduce-scatter wire
    s0 = _state(model, mesh)
    flat_opt = init_sharded_opt_state(s0.params, mesh)
    s0 = TrainState(s0.params, s0.bn_state, flat_opt, s0.step)
    step_z1 = make_train_step(
        model.apply, opt, mesh, shard_weight_update=True,
        grad_compression="bf16", donate=False,
    )
    s_z1, m = step_z1(s0, xs, ys, 0.1)
    assert np.isfinite(float(m["loss"]))

    import pytest

    with pytest.raises(ValueError, match="grad_compression"):
        make_train_step(model.apply, opt, mesh, grad_compression="int3")
