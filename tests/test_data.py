"""Data pipeline: transforms, loader sharding/prefetch, CIFAR reader."""

import numpy as np
import pytest

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.data import DataLoader, DistributedSampler, synthetic_cifar, transforms
from tpu_dist.data.cifar import load_cifar100


def test_normalize_matches_reference_constants():
    x = np.full((2, 32, 32, 3), 128, np.uint8)
    y = transforms.normalize(x)
    expect = (128 / 255.0 - transforms.CIFAR100_MEAN) / transforms.CIFAR100_STD
    np.testing.assert_allclose(y[0, 0, 0], expect, rtol=1e-6)


def test_random_crop_shape_and_determinism():
    x = np.random.default_rng(0).integers(0, 255, (8, 32, 32, 3)).astype(np.uint8)
    a = transforms.random_crop_batch(x, np.random.default_rng(5))
    b = transforms.random_crop_batch(x, np.random.default_rng(5))
    c = transforms.random_crop_batch(x, np.random.default_rng(6))
    assert a.shape == x.shape
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)


def test_crop_windows_come_from_padded_image():
    x = np.ones((1, 8, 8, 3), np.uint8) * 7
    out = transforms.random_crop_batch(x, np.random.default_rng(0), padding=4)
    # every output pixel is either original (7) or zero padding
    assert set(np.unique(out)) <= {0, 7}


def test_loader_yields_sharded_batches():
    mesh = mesh_lib.data_parallel_mesh()
    imgs, lbls = synthetic_cifar(200, 10)
    sampler = DistributedSampler(200, 1, 0, seed=0)
    dl = DataLoader(imgs, lbls, 40, sampler, mesh,
                    transform=transforms.train_augment, seed=0)
    batches = list(dl)
    assert len(batches) == len(dl) == 5
    x, y = batches[0]
    assert x.shape == (40, 32, 32, 3) and y.shape == (40,)
    assert x.dtype == np.float32
    assert len(x.sharding.device_set) == 8  # spread over the mesh


def test_loader_epoch_reshuffle_changes_batches():
    mesh = mesh_lib.data_parallel_mesh()
    imgs, lbls = synthetic_cifar(64, 10)
    sampler = DistributedSampler(64, 1, 0, seed=0)
    dl = DataLoader(imgs, lbls, 64, sampler, mesh, seed=0)
    sampler.set_epoch(0)
    y0 = np.asarray(next(iter(dl))[1])
    sampler.set_epoch(1)
    y1 = np.asarray(next(iter(dl))[1])
    assert not np.array_equal(y0, y1)


def test_loader_early_break_no_thread_leak():
    import threading

    mesh = mesh_lib.data_parallel_mesh()
    imgs, lbls = synthetic_cifar(512, 10)
    dl = DataLoader(imgs, lbls, 32, DistributedSampler(512, 1, 0), mesh)
    before = threading.active_count()
    for _ in range(4):
        for i, _b in enumerate(dl):
            if i >= 1:
                break
    import time

    time.sleep(0.3)
    assert threading.active_count() <= before + 1


def test_indivisible_batch_rejected():
    mesh = mesh_lib.data_parallel_mesh()
    imgs, lbls = synthetic_cifar(64, 10)
    with pytest.raises(ValueError, match="divide"):
        DataLoader(imgs, lbls, 30, DistributedSampler(64, 1, 0), mesh)


def test_cifar_missing_data_is_loud(tmp_path):
    with pytest.raises(FileNotFoundError, match="CIFAR-100 not found"):
        load_cifar100(str(tmp_path))


def test_cifar100_reads_pickle_layout(tmp_path):
    import pickle

    root = tmp_path / "cifar-100-python"
    root.mkdir()
    rng = np.random.default_rng(0)
    raw = rng.integers(0, 256, (6, 3072), dtype=np.int64).astype(np.uint8)
    with open(root / "train", "wb") as f:
        pickle.dump({"data": raw, "fine_labels": list(range(6))}, f)
    imgs, lbls = load_cifar100(str(tmp_path), train=True)
    assert imgs.shape == (6, 32, 32, 3) and lbls.tolist() == [0, 1, 2, 3, 4, 5]
    # channel-major 3072 -> NHWC round trip
    np.testing.assert_array_equal(
        imgs[0], raw[0].reshape(3, 32, 32).transpose(1, 2, 0)
    )


def test_cifar10_reads_batch_layout(tmp_path):
    import pickle

    from tpu_dist.data.cifar import load_cifar10

    root = tmp_path / "cifar-10-batches-py"
    root.mkdir()
    rng = np.random.default_rng(1)
    for i in range(1, 6):
        raw = rng.integers(0, 256, (4, 3072), dtype=np.int64).astype(np.uint8)
        with open(root / f"data_batch_{i}", "wb") as f:
            pickle.dump({"data": raw, "labels": [i] * 4}, f)
    imgs, lbls = load_cifar10(str(tmp_path), train=True)
    assert imgs.shape == (20, 32, 32, 3)
    assert lbls.tolist() == sum(([i] * 4 for i in range(1, 6)), [])
    with pytest.raises(FileNotFoundError, match="CIFAR-10 not found"):
        load_cifar10(str(tmp_path / "nope"))


def test_train_pad_wraps_distinct_samples():
    """The last partial train batch pads with wrap-around samples from the
    epoch stream (torch DistributedSampler semantics), not one repeated
    example (which would give a single image pad× gradient weight)."""
    mesh = mesh_lib.data_parallel_mesh()
    # 72 examples, batch 16 -> last batch has 8 real + 8 pad
    imgs, lbls = synthetic_cifar(72, 10)
    lbls = np.arange(72).astype(np.int32) % 10  # identifiable labels
    sampler = DistributedSampler(72, 1, 0, seed=0, shuffle=False)
    dl = DataLoader(imgs, lbls, 16, sampler, mesh, seed=0, batch_divisor=8)
    batches = [np.asarray(y) for _, y in dl]
    last = batches[-1]
    # tail = first 8 of the epoch stream (wrap-around), not last[7] repeated
    np.testing.assert_array_equal(last[8:], batches[0][:8])
    assert not np.all(last[8:] == last[7])
