"""Elastic scale-up + fleet scheduling (docs/resilience.md "Scale-up &
fleet scheduling"): the capacity-probe state machine, the supervisor's
resize/census-capped/same-size-budget policy, the goodput-aware chip
arbiter, the in-process 4->8 grow-resume (bit-exact state), the obs
satellites (GROWN rendering, fleet records, recovery_s attribution), and
the TD112 traced-noop gate.

World-size changes are driven three ways: pure policy units (no
processes), stub children through ``cli/launch.py``'s probe-armed
supervisor (the relaunch mechanics without jax in the loop), and
in-process by handing the Trainer a smaller mesh first and resuming on
the full one (full fidelity for the grow state-remap). The multi-phase
subprocess drill is ``python -m tpu_dist.fleet.drill`` (``make
fleet-drill``), exercised by a slow-marked test here.
"""

import json
import os
import signal
import sys

import jax
import numpy as np
import pytest

from tpu_dist.ckpt import checkpoint as ckpt_lib
from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.comm.quantize import padded_len
from tpu_dist.config import TrainConfig
from tpu_dist.elastic import supervisor as sup
from tpu_dist.fleet import capacity as capacity_lib
from tpu_dist.fleet.scheduler import (
    FLEET_SCHEMA_VERSION,
    FleetPolicy,
    FleetScheduler,
    RunSignals,
    RunSpec,
    read_signals,
)
from tpu_dist.obs import counters as counters_lib
from tpu_dist.obs import export as export_lib
from tpu_dist.resilience import faults, preemption
from tpu_dist.resilience.preemption import PREEMPTION_EXIT_CODE
from tpu_dist.resilience.retry import backoff_delays
from tpu_dist.train.trainer import Trainer, register_model
from tests.helpers import TinyMLP

# Same probe model as tests/test_elastic.py: L = 49338 ≡ 2 (mod 8), so
# padded_len(L, 4) = 49340 != 49344 = padded_len(L, 8) — the 4->8 GROW
# genuinely reshapes the ZeRO-1 flat vectors (and the EF residual row
# count always changes with the extent).
register_model(
    "tiny_mlp_fl", lambda num_classes=10: TinyMLP(num_classes, width=16, in_dim=3072)
)

L_TINY = 3072 * 16 + 16 + 16 * 10 + 10  # 49338


@pytest.fixture(autouse=True)
def _clean_fault_state():
    faults.clear()
    preemption.clear()
    prev = ckpt_lib.set_io_retries(0)
    yield
    faults.clear()
    preemption.clear()
    ckpt_lib.set_io_retries(prev)


def _cfg(ckpt_dir, **kw):
    base = dict(
        dataset="synthetic", model="tiny_mlp_fl", num_classes=10,
        batch_size=64, epochs=2, steps_per_epoch=3, log_every=50,
        eval_every=0, save_every=1, synthetic_n=256, seed=0,
        ckpt_dir=ckpt_dir, num_workers=1,
    )
    base.update(kw)
    return TrainConfig(**base)


def _mesh(n):
    return mesh_lib.data_parallel_mesh(jax.devices()[:n])


# -- capacity probe: targets + state machine ---------------------------------


def test_grow_and_shrink_targets():
    # grow: largest feasible divisor the capacity staffs, strictly above
    # current, never past the (max_procs-capped) original
    assert sup.grow_target(8, 4, available=8) == 8
    assert sup.grow_target(8, 4, available=7) is None  # 8 not staffable
    assert sup.grow_target(8, 2, available=5) == 4
    assert sup.grow_target(8, 4, available=8, max_procs=4) is None
    assert sup.grow_target(8, 2, available=8, max_procs=4) == 4
    assert sup.grow_target(8, 8, available=16) is None  # already full
    assert sup.grow_target(6, 3, available=6) == 6
    # shrink: largest feasible at/below capacity, strictly below current,
    # never under the floor — and never "shrink to death"
    assert sup.shrink_target(8, 8, available=4, min_procs=1) == 4
    assert sup.shrink_target(8, 8, available=5, min_procs=1) == 4
    assert sup.shrink_target(8, 4, available=3, min_procs=1) == 2
    assert sup.shrink_target(8, 4, available=3, min_procs=4) is None
    assert sup.shrink_target(8, 1, available=0, min_procs=1) is None


def test_capacity_probe_interval_grow_cooldown_and_shrink():
    avail = [8]
    probe = sup.CapacityProbe(
        lambda: avail[0], original=8, min_procs=1, interval=10.0,
    )
    # first poll only arms the timer — a fresh world settles in peace
    assert probe.poll(4, now=0.0) is None
    assert probe.poll(4, now=9.9) is None  # inside the interval
    assert probe.poll(4, now=10.0) == 8    # grow: capacity staffs 8
    assert probe.grows == 1
    # the grow armed the deterministic retry.py cooldown
    # (cooldown_base defaults to 2*interval): next decision not before
    # t=10+20, even though the plain interval would re-probe at t=20
    assert probe.poll(4, now=20.0) is None
    assert probe.poll(4, now=29.9) is None
    assert probe.poll(4, now=30.0) == 8
    assert probe.grows == 2
    # second cooldown doubles: backoff_delays(2, 20, 600)[1] = 40
    assert backoff_delays(2, 20.0, 600.0)[1] == 40.0
    assert probe.poll(4, now=50.0) is None
    assert probe.poll(4, now=70.0) == 8
    # shrinks (donations) are NOT cooled down: the chips are gone
    avail[0] = 2
    assert probe.poll(4, now=80.0) == 2
    avail[0] = 1
    assert probe.poll(2, now=90.0) == 1
    # ...and a shrink RESETS the grow streak: the next donate->receive
    # cycle starts the cooldown ladder from the base again, instead of
    # paying 2^k of the run's lifetime grow count — while the cooldown
    # ARMED by the last grow still stands (anti-flap)
    avail[0] = 8
    assert probe.poll(2, now=100.0) is None  # standing cooldown holds
    assert probe.poll(2, now=150.0) == 8     # it expires, grow fires
    assert probe.grows == 1                  # fresh streak, not 4
    assert probe.poll(2, now=160.0) is None  # base cooldown (20s), not 160s
    assert probe.poll(2, now=170.0) == 8
    # an unanswerable census is a no-op, never a resize
    avail2 = sup.CapacityProbe(lambda: None, original=8, interval=1.0)
    assert avail2.poll(4, now=0.0) is None
    assert avail2.poll(4, now=5.0) is None


def test_capacity_probe_reset_timer_and_available():
    probe = sup.CapacityProbe(lambda: 8, original=8, interval=10.0)
    assert probe.poll(4, now=0.0) is None
    probe.reset_timer(now=25.0)  # a new round spawned at t=25
    assert probe.poll(4, now=30.0) is None  # its interval restarted
    assert probe.poll(4, now=35.0) == 8
    assert probe.available() == 8

    def boom():
        raise OSError("census backend gone")

    assert sup.CapacityProbe(boom, original=8).available() is None


def test_make_census_resolution_order(tmp_path):
    cap = str(tmp_path / "allocation")
    # missing file -> env -> default
    census = capacity_lib.make_census(cap, default=8, env={})
    assert census() == 8
    census = capacity_lib.make_census(
        cap, default=8, env={capacity_lib.CAPACITY_ENV: "6"}
    )
    assert census() == 6
    capacity_lib.write_allocation(cap, 4)
    assert census() == 4  # the file wins once it exists
    assert capacity_lib.read_allocation(cap) == 4
    # torn/garbage file degrades to the fallbacks, never raises
    with open(cap, "w") as f:
        f.write("not-a-number")
    assert census() == 6
    assert capacity_lib.read_allocation(str(tmp_path / "missing")) is None
    # garbage ENV values degrade to the default too — "--4" passes an
    # isdigit-after-lstrip check but must not crash the probe mid-run
    for bad in ("--4", "+-5", "4.5", "", "  ", "x9"):
        c = capacity_lib.make_census(
            None, default=8, env={capacity_lib.CAPACITY_ENV: bad}
        )
        assert c() == 8, bad
    c = capacity_lib.make_census(
        None, default=8, env={capacity_lib.CAPACITY_ENV: "+6"}
    )
    assert c() == 6


# -- supervisor: resize rounds, census cap, same-size budget -----------------


def test_supervise_resize_rounds_do_not_burn_budget():
    calls = []
    sleeps = []

    def rounds(n, idx):
        calls.append((n, idx))
        if idx == 0:  # the scheduler took half our chips: donate
            return sup.RoundResult(
                PREEMPTION_EXIT_CODE, {i: 75 for i in range(n)}, resize_to=4
            )
        if idx == 1:  # capacity returned: grow back
            return sup.RoundResult(
                PREEMPTION_EXIT_CODE, {i: 75 for i in range(n)}, resize_to=8
            )
        return sup.RoundResult(0, {i: 0 for i in range(n)})

    rc = sup.supervise(
        rounds, nproc=8, min_procs=1, max_restarts=0,  # NO failure budget
        sleep=sleeps.append,
    )
    assert rc == 0
    assert calls == [(8, 0), (4, 1), (8, 2)]
    assert sleeps == []  # resizes wait no failure backoff

    # the launcher's own SIGTERM outranks a pending resize
    assert sup.supervise(
        lambda n, i: sup.RoundResult(75, {0: 75}, resize_to=8),
        nproc=4, min_procs=1, max_restarts=5, sleep=lambda _s: None,
        should_continue=lambda: False,
    ) == 75


def test_supervise_census_caps_failure_relaunch():
    calls = []
    probe = sup.CapacityProbe(lambda: 4, original=8, interval=1.0)

    def rounds(n, idx):
        calls.append((n, idx))
        if idx == 0:  # whole-pod preemption, but the census says half
            # the chips are gone — same-size retry would hang forever
            return sup.RoundResult(75, {i: 75 for i in range(n)})
        return sup.RoundResult(0, {i: 0 for i in range(n)})

    rc = sup.supervise(
        rounds, nproc=8, min_procs=1, max_restarts=3,
        sleep=lambda _s: None, probe=probe,
    )
    assert rc == 0
    assert calls == [(8, 0), (4, 1)]

    # census below the floor: give up with the round's code
    probe2 = sup.CapacityProbe(lambda: 1, original=8, interval=1.0)
    assert sup.supervise(
        lambda n, i: sup.RoundResult(75, {j: 75 for j in range(n)}),
        nproc=8, min_procs=4, max_restarts=3, sleep=lambda _s: None,
        probe=probe2,
    ) == 75

    # a census-capped size change starts a FRESH same-size streak: with
    # same_size_retries=1 the run gets one full retry at 4 before the
    # step-down to 2, even though the 8->4 cap already spent one
    calls2 = []
    probe3 = sup.CapacityProbe(lambda: 4, original=8, interval=1.0)
    sup.supervise(
        lambda n, i: (calls2.append(n) or
                      sup.RoundResult(75, {j: 75 for j in range(n)})),
        nproc=8, min_procs=2, max_restarts=4, sleep=lambda _s: None,
        probe=probe3, same_size_retries=1,
    )
    assert calls2 == [8, 4, 4, 2, 2]


def test_supervise_same_size_retry_budget_steps_down():
    calls = []

    def rounds(n, idx):
        calls.append((n, idx))
        return sup.RoundResult(75, {i: 75 for i in range(n)})

    said = []
    rc = sup.supervise(
        rounds, nproc=8, min_procs=2, max_restarts=4,
        sleep=lambda _s: None, announce=said.append, same_size_retries=2,
    )
    # 2 same-size retries at 8, then step down to 4, then its own budget
    assert rc == 75
    assert [n for n, _ in calls] == [8, 8, 8, 4, 4]
    assert any("stepping down to 4" in m for m in said)

    # at the floor there is nowhere to step down: keep retrying same size
    calls.clear()
    sup.supervise(
        rounds, nproc=4, min_procs=4, max_restarts=3,
        sleep=lambda _s: None, same_size_retries=1,
    )
    assert [n for n, _ in calls] == [4, 4, 4, 4]

    # a real loss resets the same-size streak (census path still rules)
    seq = iter([
        sup.RoundResult(75, {i: 75 for i in range(8)}),          # whole pod
        sup.RoundResult(75, {0: 75, 1: -signal.SIGKILL} |
                        {i: 75 for i in range(2, 8)}),           # 1 lost
        sup.RoundResult(0, {i: 0 for i in range(4)}),
    ])
    calls.clear()
    rc = sup.supervise(
        lambda n, i: (calls.append((n, i)) or next(seq)),
        nproc=8, min_procs=1, max_restarts=4, sleep=lambda _s: None,
        same_size_retries=2,
    )
    assert rc == 0
    assert [n for n, _ in calls] == [8, 8, 4]


# -- scheduler: policy units on synthetic signals ----------------------------


def _sig(run, stall, alerts=(), alive=None):
    return RunSignals(
        run=run, data_stall_frac=stall, goodput_frac=0.5, mfu=0.3,
        active_alerts=tuple(alerts), alive=alive,
    )


def _fleet(**kw):
    args = dict(
        runs=[RunSpec("a", 8, min_procs=2), RunSpec("b", 8, min_procs=2)],
        allocations={"a": 8, "b": 4},
        total_chips=12,
    )
    args.update(kw)
    return FleetScheduler(**args)


def test_scheduler_donates_then_grants_one_tick_later():
    """The two-phase move: a donation banks the chips as PENDING (the
    donor needs its checkpoint/relaunch window to vacate them — granting
    in the same instant would oversubscribe the pool); the recipient is
    granted from the matured free pool at the NEXT tick. At no point do
    the written allocations plus the free pool exceed the chips that
    are actually vacant."""
    s = _fleet()
    sig = {"a": _sig("a", 0.62), "b": _sig("b", 0.02)}
    ds = s.decide(0, sig)
    assert len(ds) == 1
    d = ds[0]
    assert d["kind"] == "fleet" and d["action"] == "donate"
    assert d["donor"] == "a" and d["recipient"] is None
    assert d["for_run"] == "b"
    assert d["alloc_after"] == {"a": 4, "b": 4}  # b NOT grown yet
    assert d["chips"] == 4 and d["pending_after"] == 4
    # auditable: the decision carries the signals that justified it
    assert d["inputs"]["a"]["data_stall_frac"] == 0.62
    assert d["inputs"]["b"]["data_stall_frac"] == 0.02
    assert "data-stalled donates" in d["reason"]
    # deterministic: same state + same signals => same decision
    assert s.decide(0, sig) == ds
    s.apply(d, 0)
    assert s.alloc == {"a": 4, "b": 4}
    assert s.pending == 4 and s.free == 0
    # never oversubscribed: allocations + vacant chips <= total
    assert sum(s.alloc.values()) + s.pending + s.free <= s.total_chips + 4
    assert sum(s.alloc.values()) + s.free <= s.total_chips
    # still tick 0: the banked chips are NOT grantable yet
    s.mature_pending(0)
    assert s.decide(0, sig) == []
    # next tick: they mature and the starved recipient is granted
    s.mature_pending(1)
    assert s.pending == 0 and s.free == 4
    [g] = s.decide(1, sig)
    assert g["action"] == "grant"
    assert g["donor"] is None and g["recipient"] == "b"
    assert g["alloc_after"] == {"a": 4, "b": 8} and g["free_after"] == 0
    s.apply(g, 1)
    assert s.alloc == {"a": 4, "b": 8}


def test_scheduler_thresholds_and_vetoes():
    # below the donate threshold: nobody moves
    s = _fleet()
    assert s.decide(0, {"a": _sig("a", 0.39), "b": _sig("b", 0.02)}) == []
    # recipient not compute-bound enough: no move
    assert s.decide(0, {"a": _sig("a", 0.62), "b": _sig("b", 0.12)}) == []
    # alert-veto: a firing run never receives chips
    assert s.decide(0, {
        "a": _sig("a", 0.62), "b": _sig("b", 0.02, alerts=("grad_norm_high",)),
    }) == []
    # dead heartbeat vetoes both roles
    assert s.decide(0, {
        "a": _sig("a", 0.62, alive=False), "b": _sig("b", 0.02),
    }) == []
    assert s.decide(0, {
        "a": _sig("a", 0.62), "b": _sig("b", 0.02, alive=False),
    }) == []
    # absent signals make a run ineligible (never default to a number)
    assert s.decide(0, {"a": _sig("a", None), "b": _sig("b", 0.02)}) == []
    assert s.decide(0, {"a": _sig("a", 0.62)}) == []


def test_scheduler_never_below_min_procs():
    s = _fleet(
        runs=[RunSpec("a", 8, min_procs=8), RunSpec("b", 8, min_procs=2)],
        allocations={"a": 8, "b": 4}, total_chips=12,
    )
    # a's floor IS its allocation: it cannot donate no matter how stalled
    assert s.decide(0, {"a": _sig("a", 0.99), "b": _sig("b", 0.0)}) == []


def test_scheduler_cooldown_and_hysteresis():
    s = _fleet(policy=FleetPolicy(move_cooldown=2, hysteresis=0.05))
    sig = {"a": _sig("a", 0.62), "b": _sig("b", 0.02)}
    [d] = s.step(0, sig)  # donate: a 8->4, 4 chips pending
    assert d["action"] == "donate" and s.alloc == {"a": 4, "b": 4}
    [g] = s.step(1, sig)  # matured: grant b 4->8
    assert g["action"] == "grant" and s.alloc == {"a": 4, "b": 8}
    # cooldown: a (moved at 0) sits out through tick 2, b (moved at 1)
    # through tick 3
    flipped = {"a": _sig("a", 0.02), "b": _sig("b", 0.62)}
    assert s.step(2, flipped) == []
    assert s.step(3, flipped) == []
    # after the cooldown, hysteresis gates the REVERSAL: b (which just
    # received) must breach donate+hysteresis to donate back, and a
    # (which just donated) must be under receive-hysteresis to receive
    nearly = {"a": _sig("a", 0.08), "b": _sig("b", 0.43)}
    assert s.step(4, nearly) == []  # 0.43 < 0.40+0.05; 0.08 > 0.10-0.05
    decisively = {"a": _sig("a", 0.03), "b": _sig("b", 0.62)}
    [d2] = s.step(4, decisively)
    assert d2["action"] == "donate"
    assert d2["donor"] == "b" and d2["for_run"] == "a"


def test_scheduler_free_pool_grow_needs_no_donor(tmp_path):
    s = FleetScheduler(
        [RunSpec("a", 8, min_procs=2)],
        fleet_dir=str(tmp_path), allocations={"a": 4}, total_chips=8,
    )
    assert s.free == 4
    [d] = s.step(0, {"a": _sig("a", 0.02)}, ts=123.0)
    assert d["donor"] is None and d["recipient"] == "a"
    assert d["alloc_after"] == {"a": 8} and d["free_after"] == 0
    assert "free pool" in d["reason"]
    # the actuator wrote the allocation file and the audit record
    assert capacity_lib.read_allocation(s.allocation_path("a")) == 8
    recs = [json.loads(l) for l in open(s.history_path())]
    assert recs[0]["kind"] == "fleet" and recs[0]["ts"] == 123.0
    assert recs[0]["schema_version"] == FLEET_SCHEMA_VERSION


def test_fleet_schema_version_pinned_to_history():
    # scheduler.py keeps a literal (it must stay jax-free); this pin is
    # what stops the two from drifting silently
    from tpu_dist.metrics.history import SCHEMA_VERSION

    assert FLEET_SCHEMA_VERSION == SCHEMA_VERSION


def test_scheduler_rejects_bad_configs():
    with pytest.raises(ValueError, match="feasible"):
        FleetScheduler([RunSpec("a", 8)], allocations={"a": 5})
    with pytest.raises(ValueError, match="total_chips"):
        FleetScheduler([RunSpec("a", 8)], allocations={"a": 8}, total_chips=4)
    with pytest.raises(ValueError, match="duplicate"):
        FleetScheduler([RunSpec("a", 8), RunSpec("a", 4)])
    with pytest.raises(ValueError, match="receive_stall_frac"):
        FleetPolicy(donate_stall_frac=0.1, receive_stall_frac=0.4)
    with pytest.raises(ValueError, match="min_procs"):
        RunSpec("a", 4, min_procs=5)


def test_read_signals_scrapes_a_real_exposition(tmp_path):
    prom = str(tmp_path / "metrics.prom")
    with open(prom, "w") as f:
        f.write(export_lib.render(
            {
                "train.data_stall_frac": 0.45,
                "goodput.goodput_frac": 0.61,
                "train.mfu": 0.33,
                "train.epoch": 3,
            },
            labeled={"alert_active": {"stall_high": 1, "mfu_low": 0}},
        ))
    sig = read_signals("r0", prom)
    assert sig.data_stall_frac == 0.45
    assert sig.goodput_frac == 0.61
    assert sig.mfu == 0.33
    assert sig.epoch == 3
    assert sig.active_alerts == ("stall_high",)  # 0-valued gauge ignored
    assert sig.alive is None  # no heartbeat source configured
    # absent exposition degrades to all-None, never raises
    empty = read_signals("r1", str(tmp_path / "missing.prom"))
    assert empty.data_stall_frac is None and empty.active_alerts == ()


def test_scheduler_exposition_uses_run_label(tmp_path):
    s = _fleet()
    text = s.exposition()
    assert 'tpu_dist_fleet_allocation{run="a"} 8' in text
    assert 'tpu_dist_fleet_allocation{run="b"} 4' in text
    assert "tpu_dist_fleet_decisions 0" in text
    path = str(tmp_path / "fleet.prom")
    s.write_exposition(path)
    vals = export_lib.scrape(textfile=path)
    assert vals['tpu_dist_fleet_allocation{run="b"}'] == 4.0
    # the default labeled family still renders rule= (alerts unchanged)
    assert 'alert_active{rule="x"}' in export_lib.render(
        {}, labeled={"alert_active": {"x": 1}}
    )
    # gauges for the scheduler's own registry snapshot
    assert counters_lib.snapshot()["fleet.allocation.a"] == 8


# -- launcher e2e: probe-driven resize with stub children --------------------


def test_launcher_probe_resize_stub_children(tmp_path):
    """cli/launch.py e2e (no jax): the census is authoritative from
    birth — a 4-proc submission whose allocation says 2 launches round 0
    at 2 (never on another run's chips); capacity returns mid-round and
    the probe grows it to 4 with --resume — restart budget untouched at
    every step."""
    from tpu_dist.cli.launch import main as launch_main

    marker = str(tmp_path / "worlds.txt")
    cap = str(tmp_path / "allocation")
    capacity_lib.write_allocation(cap, 2)
    child = (
        "import os, signal, sys, time\n"
        "argv = sys.argv\n"
        "n = int(argv[argv.index('--num_processes') + 1])\n"
        "rank = int(argv[argv.index('--process_id') + 1])\n"
        "resume = '--resume' in argv\n"
        "if rank == 0:\n"
        f"    open({marker!r}, 'a').write(\n"
        "        f\"{n} {int(resume)} \"\n"
        "        f\"{os.environ.get('TPU_DIST_ELASTIC_RESTARTS')}\\n\")\n"
        "signal.signal(signal.SIGTERM, lambda *a: sys.exit(75))\n"
        "if resume and n == 4:\n"
        "    sys.exit(0)\n"  # grown to full size: run completes
        "if n == 2 and rank == 0:\n"
        "    time.sleep(0.1)\n"
        f"    open({cap!r} + '.t', 'w').write('4')\n"
        f"    os.replace({cap!r} + '.t', {cap!r})\n"
        "time.sleep(60)\n"
    )
    rc = launch_main([
        "--nproc", "4", "--elastic_min_procs", "1",
        "--elastic_max_restarts", "0",  # resizes need NO failure budget
        "--elastic_backoff", "0.01", "--elastic_probe_interval", "0.2",
        "--elastic_capacity_file", cap, "--",
        sys.executable, "-c", child,
    ])
    assert rc == 0
    lines = [l.split() for l in open(marker).read().splitlines()]
    # round 0 at the GRANTED 2 (fresh start, no --resume), grown to 4
    assert lines == [["2", "0", "0"], ["4", "1", "1"]]


def test_launcher_refuses_start_below_the_floor(tmp_path):
    """A census granting fewer procs than --elastic_min_procs at launch
    is a loud refusal, not a run squatting on someone else's chips."""
    from tpu_dist.cli.launch import main as launch_main

    cap = str(tmp_path / "allocation")
    capacity_lib.write_allocation(cap, 1)
    rc = launch_main([
        "--nproc", "4", "--elastic_min_procs", "2",
        "--elastic_probe_interval", "0.2",
        "--elastic_capacity_file", cap, "--",
        sys.executable, "-c", "import sys; sys.exit(0)",
    ])
    assert rc == 1


# -- trainer e2e: in-process 4 -> 8 grow-resume ------------------------------


def test_trainer_grow_resume_zero1_ef_is_bit_exact(tmp_path):
    """The scale-up tentpole at the state layer: a ZeRO-1 + int8_ef run
    saved on a 4-device mesh resumes onto the full 8-device mesh —
    params bit-identical, ZeRO-1 momentum's logical prefix bit-identical
    with a zero tail at the LARGER padded length, EF aggregate preserved,
    ``elastic.grows`` counted — and keeps training at the new extent."""
    d = str(tmp_path)
    log = os.path.join(d, "run.jsonl")
    cfg = _cfg(d, shard_weight_update=True, grad_compression="int8_ef",
               log_file=log)
    t = Trainer(cfg, mesh=_mesh(4))
    t.fit()
    ck = ckpt_lib.latest_checkpoint(d)
    assert ck is not None and ck[1] == 1
    with np.load(ck[0]) as z:
        saved = {k: np.array(z[k]) for k in z.files if k != "__meta__"}
    meta = ckpt_lib.read_meta(ck[0])
    assert meta["elastic"] == {"dp": 4, "procs": 1, "params_len": L_TINY}
    old_r1 = saved["['ef']['r1']"].reshape(4, padded_len(L_TINY, 4))

    t2 = Trainer(cfg.replace(resume=True))  # default mesh: all 8 devices
    assert t2.start_epoch == 2
    assert counters_lib.get("resume.resharded") == 1
    assert counters_lib.get("elastic.grows") == 1
    # params: world-size-independent, bit-identical
    for (path_a, a) in jax.tree_util.tree_flatten_with_path(t2.state.params)[0]:
        key = jax.tree_util.keystr(path_a)
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), saved[f"['params']{key}"]
        )
    # ZeRO-1 momentum: logical prefix bit-identical, grown tail zero
    mom = np.asarray(jax.device_get(t2.state.opt_state))
    assert mom.shape == (padded_len(L_TINY, 8),)
    np.testing.assert_array_equal(mom[:L_TINY], saved["['opt_state']"][:L_TINY])
    assert not mom[L_TINY:].any()
    # EF r1: aggregate residual preserved exactly across MORE replica rows
    r1 = np.asarray(jax.device_get(t2.state.ef["r1"])).reshape(
        8, padded_len(L_TINY, 8)
    )
    np.testing.assert_array_equal(
        r1.sum(axis=0, dtype=np.float32)[:L_TINY],
        old_r1[:, :L_TINY].sum(axis=0, dtype=np.float32),
    )
    # ...and the grown trainer actually trains an epoch at dp=8
    last = t2.fit(3)
    assert np.isfinite(last["loss"]) and last["steps"] == 3
    recs = [json.loads(l) for l in open(log)]
    resumes = [r for r in recs if r.get("kind") == "resume"]
    assert resumes and resumes[-1]["resharded"] is True
    assert resumes[-1]["dp"] == 8 and resumes[-1]["prev_dp"] == 4
    assert counters_lib.snapshot()["elastic.world_size"] == 8


def test_trainer_grow_without_remappable_leaves_still_counts(tmp_path):
    """A run with NO dp-extent-dependent leaves (plain per-leaf momentum,
    no ZeRO-1/EF) grows 4->8 with zero remapped leaves — resharded stays
    False, but it still GREW: ``elastic.grows`` must count it and the
    resume record must carry the world change (which is also what routes
    the relaunch gap to recovery_s offline)."""
    d = str(tmp_path)
    log = os.path.join(d, "run.jsonl")
    cfg = _cfg(d, epochs=1, log_file=log)
    Trainer(cfg, mesh=_mesh(4)).fit()
    t2 = Trainer(cfg.replace(resume=True))  # default mesh: 8 devices
    assert counters_lib.get("elastic.grows") == 1
    assert counters_lib.get("resume.resharded") == 0  # nothing re-laid
    t2.fit(2)
    recs = [json.loads(l) for l in open(log)]
    resumes = [r for r in recs if r.get("kind") == "resume"]
    assert resumes and resumes[-1]["prev_dp"] == 4
    assert resumes[-1]["dp"] == 8 and resumes[-1]["resharded"] is False


# -- observability satellites ------------------------------------------------


def _resume_rec(run_id, ts, rel_s, **kw):
    rec = {"kind": "resume", "run_id": run_id, "ts": ts, "rel_s": rel_s,
           "schema_version": 8}
    rec.update(kw)
    return rec


def _fleet_rec(**kw):
    rec = {"kind": "fleet", "schema_version": 8, "tick": 0,
           "action": "move", "donor": "a", "recipient": "b", "chips": 4,
           "alloc_before": {"a": 8, "b": 4}, "alloc_after": {"a": 4, "b": 8},
           "reason": "a 62% data-stalled donates to compute-bound b",
           "inputs": {"a": {"data_stall_frac": 0.62}}, "ts": 5.0,
           "run_id": "sched"}
    rec.update(kw)
    return rec


def test_run_ledger_charges_grow_gap_to_recovery():
    from tpu_dist.obs import goodput

    def gp(run, ts, rel, **kw):
        rec = {"kind": "goodput", "run_id": run, "ts": ts, "rel_s": rel}
        rec.update(kw)
        return rec

    records = [
        gp("a", 10.0, 5.0, final=True, productive_s=4.0, elapsed_s=5.0,
           goodput_frac=0.8),
        # 6s checkpoint->relaunch gap; the new segment opens with a GROW
        # resume whose remap happened to re-lay nothing (resharded False,
        # world changed): a voluntary resize must never inflate preempt_s
        _resume_rec("b", 16.0, 0.0, epoch=1, dp=8, prev_dp=4,
                    resharded=False),
        gp("b", 20.0, 4.0, final=True, productive_s=3.0, elapsed_s=4.0,
           goodput_frac=0.75),
    ]
    led = goodput.run_ledger(records)
    assert led["recovery_s"] == pytest.approx(6.0)
    assert led["preempt_s"] == pytest.approx(0.0)
    # a same-size restart still charges preempt_s
    records[1] = _resume_rec("b", 16.0, 0.0, epoch=1, dp=8, prev_dp=8,
                             resharded=False)
    led = goodput.run_ledger(records)
    assert led["preempt_s"] == pytest.approx(6.0)
    assert led["recovery_s"] == pytest.approx(0.0)


def test_tail_renders_grown_and_fleet_events():
    from tpu_dist.obs.tail import TailState

    st = TailState()
    st.add([
        _resume_rec("a", 1.0, 0.0, epoch=1, world=8, dp=8, prev_dp=4,
                    resharded=True, restarts=2),
        _fleet_rec(),
    ])
    assert any("GROWN from dp=4" in e for e in st.events)
    assert not any("RESHARDED" in e for e in st.events)
    assert any(
        "fleet: a -> b (4 chip(s))" in e and "data-stalled" in e
        for e in st.events
    )
    # the shrink direction still reads RESHARDED
    st2 = TailState()
    st2.add([_resume_rec("a", 1.0, 0.0, epoch=1, world=4, dp=4, prev_dp=8,
                         resharded=True)])
    assert any("RESHARDED from dp=8" in e for e in st2.events)


def test_summarize_renders_grow_segments_and_fleet_decisions():
    from tpu_dist.obs.summarize import format_text, summarize

    records = [
        {"kind": "train_epoch", "epoch": 0, "run_id": "a", "ts": 1.0,
         "rel_s": 1.0, "schema_version": 8, "epoch_time": 1.0,
         "images_per_sec": 50.0, "loss": 2.0},
        _resume_rec("b", 10.0, 0.5, epoch=1, world=8, dp=8, prev_dp=4,
                    resharded=True, restarts=2),
        _fleet_rec(run_id="b", ts=11.0),
        {"kind": "train_epoch", "epoch": 1, "run_id": "b", "ts": 12.0,
         "rel_s": 1.5, "schema_version": 8, "epoch_time": 1.0,
         "images_per_sec": 100.0, "loss": 1.5},
    ]
    rep = summarize(records)
    assert rep["world_sizes"] == [4, 8]
    assert rep["fleet_decisions"][0]["recipient"] == "b"
    assert rep["fleet_decisions"][0]["inputs"]["a"]["data_stall_frac"] == 0.62
    assert not rep["skipped_kinds"]  # 'fleet' is a KNOWN kind now
    text = format_text(rep)
    assert "GROWN from dp=4" in text
    assert "world size changed mid-run (elastic): dp 4 -> 8" in text
    assert "fleet: tick 0: a -> b (4 chip(s))" in text
    assert "[alloc a:8->4, b:4->8]" in text


def test_pod_report_surfaces_grows_and_fleet_decisions():
    from tpu_dist.obs.aggregate import format_text, pod_report

    records = [
        _resume_rec("a", 1.0, 0.0, epoch=0, world=4, dp=4, prev_dp=8,
                    resharded=True),
        _resume_rec("b", 9.0, 0.0, epoch=1, world=8, dp=8, prev_dp=4,
                    resharded=True),
        _fleet_rec(run_id="b", ts=10.0),
    ]
    rep = pod_report([("host0", records)])
    assert rep["hosts"][0]["world_sizes"] == [8, 4, 8]
    assert rep["hosts"][0]["fleet_decisions"]
    text = format_text(rep)
    assert "1 grow(s)" in text
    assert "fleet (host0) tick 0: a -> b (4 chip(s))" in text


# -- TD112: grow-resume is invisible to the compiled program -----------------


def test_td112_registered_and_gate_passes():
    from tpu_dist.analysis.jaxpr_audit import elastic_grow_noop_violations
    from tpu_dist.analysis.rules import RULES

    assert "TD112" in RULES and RULES["TD112"].name == "elastic-grow-not-noop"
    assert elastic_grow_noop_violations() == []


# -- the full subprocess drill (make fleet-drill) ----------------------------


def test_fleet_drill_fleet_phase(tmp_path):
    """The arbitration half of the drill runs in tier-1: two supervised
    stub runs, a real scrape, a real decision, real relaunches — no jax
    subprocesses."""
    from tpu_dist.fleet.drill import main as drill_main

    assert drill_main([
        "--workdir", str(tmp_path), "--phase", "fleet",
    ]) == 0


@pytest.mark.slow  # four subprocess training phases (compiles included):
# excluded from the timed tier-1 gate; gates in the CI fleet step
def test_fleet_drill_grow_phase(tmp_path):
    from tpu_dist.fleet.drill import main as drill_main

    assert drill_main([
        "--workdir", str(tmp_path), "--phase", "grow",
        "--devices", "8", "--shrink_to", "4", "--model", "vit_tiny",
        "--epochs", "3", "--steps_per_epoch", "3", "--batch_size", "32",
        "--kill_epoch", "1", "--kill_step", "1",
    ]) == 0
