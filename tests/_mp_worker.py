"""Worker for the multi-process (multi-host emulation) test.

Launched by tests/test_multihost.py: 2 processes × 4 CPU devices = one
8-device global mesh across "hosts". Exercises the real multi-host path:
jax.distributed rendezvous, global mesh construction, per-process data
sharding, make_array_from_process_local_data, pmean'd training step.

Usage: python tests/_mp_worker.py <coordinator> <num_procs> <proc_id>
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main(coordinator: str, num_procs: int, proc_id: int) -> None:
    from tpu_dist.comm import mesh as mesh_lib

    mesh_lib.initialize_distributed(coordinator, num_procs, proc_id)
    assert jax.process_count() == num_procs
    assert jax.local_device_count() == 4

    from tpu_dist.data import DistributedSampler
    from tpu_dist.nn import layers as L
    from tpu_dist.train.optim import SGD
    from tpu_dist.train.state import TrainState
    from tpu_dist.train.step import make_train_step

    mesh = mesh_lib.data_parallel_mesh()
    assert mesh.devices.size == 4 * num_procs

    # per-host disjoint data shards, same global permutation
    sampler = DistributedSampler(64, num_procs, proc_id, seed=0)
    sampler.set_epoch(0)
    idx = sampler.indices()

    class M:
        def init(self, key):
            k1, k2 = jax.random.split(key)
            p = {"conv": L.conv_init(k1, 3, 8, 3), "fc": L.linear_init(k2, 8, 10)}
            pb, sb = L.bn_init(8)
            p["bn"] = pb
            return p, {"bn": sb}

        def apply(self, params, state, x, *, train=False, axis_name=None):
            y = L.conv_apply(params["conv"], x, 1, 1)
            y, ns = L.bn_apply(params["bn"], state["bn"], y, train=train, axis_name=axis_name)
            y = L.relu(y)
            return L.linear_apply(params["fc"], L.global_avg_pool(y)), {"bn": ns}

    model = M()
    opt = SGD()
    params, bn = model.init(jax.random.PRNGKey(0))
    state = jax.device_put(TrainState.create(params, bn, opt), mesh_lib.replicated(mesh))
    step = make_train_step(model.apply, opt, mesh, sync_bn=True)

    # deterministic global dataset; each process feeds ITS shard
    rng = np.random.default_rng(0)
    all_x = rng.normal(size=(64, 8, 8, 3)).astype(np.float32)
    all_y = rng.integers(0, 10, 64).astype(np.int32)
    xs = mesh_lib.shard_batch(mesh, all_x[idx])
    ys = mesh_lib.shard_batch(mesh, all_y[idx])

    for _ in range(3):
        state, metrics = step(state, xs, ys, 0.1)
    loss = float(metrics["loss"])

    # replicated state must be identical across hosts; print for the parent
    p0 = float(np.asarray(state.params["fc"]["b"].addressable_shards[0].data)[0])
    print(f"RESULT {proc_id} loss={loss:.6f} p0={p0:.6f}", flush=True)

    # fused device-resident epoch, multi-host placement
    from tpu_dist.data import synthetic_cifar
    from tpu_dist.train.epoch import make_fused_epoch, put_dataset_on_device

    imgs, lbls = synthetic_cifar(128, 10, image_size=8, seed=0)
    dx, dy = put_dataset_on_device(mesh, imgs, lbls)
    f_params, f_bn = model.init(jax.random.PRNGKey(0))
    f_state = jax.device_put(
        TrainState.create(f_params, f_bn, opt), mesh_lib.replicated(mesh)
    )
    import jax.numpy as jnp

    runner = make_fused_epoch(
        model.apply, opt, mesh, batch_per_device=4, compute_dtype=jnp.float32
    )
    f_state, fm = runner(f_state, dx, dy, 0.1, 0)
    print(f"FUSED {proc_id} loss={float(fm['loss']):.6f}", flush=True)


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
