"""Trainer end-to-end on the emulated mesh, with a registered tiny model."""

import numpy as np

from tpu_dist.config import TrainConfig
from tpu_dist.train.trainer import Trainer, register_model
from tests.helpers import tiny_resnet

register_model("tiny_resnet", lambda num_classes=10: tiny_resnet(num_classes))


def _cfg(**kw):
    base = dict(
        dataset="synthetic", model="tiny_resnet", num_classes=10,
        batch_size=64, epochs=1, steps_per_epoch=4, log_every=10,
        eval_every=0, lr=0.1, seed=0, synthetic_n=640,  # small eval set
    )
    base.update(kw)
    return TrainConfig(**base)


def test_fit_trains_and_checkpoints(tmp_path):
    cfg = _cfg(ckpt_dir=str(tmp_path), save_every=1, eval_every=1)
    t = Trainer(cfg)
    out = t.fit()
    assert np.isfinite(out["loss"])
    assert "val_top1" in out
    assert (tmp_path / "ckpt_0.npz").exists()

    # resume continues from the saved epoch
    t2 = Trainer(cfg.replace(resume=True, epochs=2))
    assert t2.start_epoch == 1


def test_grad_accum_config_path():
    t = Trainer(_cfg(grad_accu_steps=2, batch_size=64))
    out = t.train_epoch(0)
    assert np.isfinite(out["loss"])


def test_invalid_grad_accum_rejected():
    import pytest

    with pytest.raises(ValueError, match="grad_accu_steps"):
        Trainer(_cfg(batch_size=8, grad_accu_steps=3))


def test_vit_through_trainer_registry():
    cfg = _cfg(model="vit_tiny", num_classes=10, steps_per_epoch=2)
    out = Trainer(cfg).train_epoch(0)
    assert np.isfinite(out["loss"])


def test_fused_optimizer_through_trainer():
    cfg = _cfg(fused_optimizer=True, steps_per_epoch=2)
    out = Trainer(cfg).train_epoch(0)
    assert np.isfinite(out["loss"])


def test_config_argparse_bridge():
    import argparse

    from tpu_dist.config import add_reference_flags, config_from_args

    p = add_reference_flags(argparse.ArgumentParser())
    args = p.parse_args(
        ["--batch_size", "128", "--lr", "0.05", "--grad_accu_steps", "4",
         "--bf16", "--no_sync_bn", "--seed", "3",
         "--lr_milestones", "10", "15", "--lr_gamma", "0.1"]
    )
    cfg = config_from_args(args)
    assert cfg.batch_size == 128 and cfg.lr == 0.05
    assert cfg.grad_accu_steps == 4 and cfg.bf16 and not cfg.sync_bn
    assert cfg.seed == 3
    assert cfg.lr_milestones == (10, 15) and cfg.lr_gamma == 0.1
    # defaults keep the reference's hard-coded schedule (distributed.py:64)
    assert config_from_args(p.parse_args([])).lr_milestones == (60, 120, 160)
    # reference-compat flags accepted silently
    p.parse_args(["--local_rank", "2", "--gpu", "0,1"])


def test_adamw_decay_mask_resume_guard(tmp_path):
    """ADVICE r3: the opt-state shapes are mask-independent, so a resume
    under a different decay mask must be refused loudly, not silently
    change the update math mid-run."""
    import pytest

    cfg = _cfg(
        optimizer="adamw", ckpt_dir=str(tmp_path), save_every=1, epochs=1
    )
    Trainer(cfg).fit()

    # same mask: resumes fine
    t2 = Trainer(cfg.replace(resume=True, epochs=2))
    assert t2.start_epoch == 1

    # flipped mask: refused with guidance naming the trained-with mask
    with pytest.raises(ValueError, match="adamw_decay_mask"):
        Trainer(cfg.replace(resume=True, epochs=2, adamw_decay_mask="all"))
