"""Serving subsystem tests (ISSUE 13, ``docs/serving.md``).

Covers: streaming latency-histogram units (buckets / merge / quantile
bounds / serialization), queue+batcher determinism on the injectable
clock, bucket-ladder retrace-freedom via ``CompileWatcher`` (and the
watcher's new ``baseline()``/in-watcher-warning contract), checkpoint →
serving-weights round-trips through the elastic ``Remapper``, SLO rule
fire/sustain/cooldown, the OpenMetrics histogram grammar round-trip
through ``export.parse``, the ``obs compare --slo`` exit contract, the
TD114 gate + registry, schema-v10 ``serve`` record rendering in
summarize/tail, and (slow) the full ``make serve-drill`` e2e plus the
``bench.py --serve`` record shape.
"""

from __future__ import annotations

import json
import re
import subprocess
import sys

import numpy as np
import pytest

from tpu_dist.obs import counters as counters_lib
from tpu_dist.obs import export as export_lib
from tpu_dist.serve import slo as slo_lib
from tpu_dist.serve.drill import (
    IMAGE_SHAPE,
    ManualClock,
    _drill_model,
    replay,
    write_training_ckpt,
)
from tpu_dist.serve.engine import (
    ServingEngine,
    batch_buckets,
    bucket_for,
    dequantize_weights,
    load_serving_state,
    quantize_weights,
)


class _TinyMLP:
    """Smallest model with the nn contract (init/apply → (logits, state))
    — engine tests must not pay a ResNet compile per case."""

    classes = 10

    def init(self, key):
        import jax
        import jax.numpy as jnp

        k1, k2 = jax.random.split(key)
        d = int(np.prod(IMAGE_SHAPE))
        params = {
            "w1": jax.random.normal(k1, (d, 16), jnp.float32) * 0.05,
            "b1": jnp.zeros((16,), jnp.float32),
            "w2": jax.random.normal(k2, (16, self.classes), jnp.float32) * 0.05,
            "b2": jnp.zeros((self.classes,), jnp.float32),
        }
        return params, {}

    def apply(self, params, state, x, *, train=False, axis_name=None, **kw):
        import jax.numpy as jnp

        h = jnp.maximum(
            x.reshape(x.shape[0], -1) @ params["w1"] + params["b1"], 0.0
        )
        return h @ params["w2"] + params["b2"], state


def _mlp_engine(**kw):
    import jax

    model = _TinyMLP()
    params, bn = model.init(jax.random.PRNGKey(0))
    eng = ServingEngine(model, params, bn, max_batch=kw.pop("max_batch", 4), **kw)
    return model, eng


@pytest.fixture(autouse=True)
def _fresh_registry():
    counters_lib.reset()
    yield
    counters_lib.reset()


# -- histogram units ---------------------------------------------------------


def test_histogram_buckets_and_sum_count():
    h = slo_lib.LatencyHistogram()
    for v in (0.0, 5e-5, 1e-4, 2e-4, 0.5):
        h.observe(v)
    assert h.count == 5 and sum(h.counts) == 5
    # le-semantics: 1e-4 lands in the FIRST bucket (v <= edge)
    assert h.counts[0] == 3
    assert h.min == 0.0 and h.max == 0.5
    assert h.sum == pytest.approx(0.50035, abs=1e-9)


def test_histogram_quantile_bound_is_conservative():
    h = slo_lib.LatencyHistogram()
    for v in (0.001, 0.001, 0.001, 0.1):
        h.observe(v)
    p50 = h.quantile_bound(0.5)
    assert p50 is not None and p50 >= 0.001  # upper bound, never under
    # one bucket of slack at most: 0.001 sits in bucket le=0.0016
    assert p50 <= 0.0016000000000000003
    # overflow bucket returns the exact max
    h.observe(1e9)
    assert h.quantile_bound(1.0) == 1e9
    assert slo_lib.LatencyHistogram().quantile_bound(0.5) is None
    with pytest.raises(ValueError):
        h.quantile_bound(1.5)


def test_histogram_merge_and_layout_refusal():
    a, b = slo_lib.LatencyHistogram(), slo_lib.LatencyHistogram()
    for v in (0.001, 0.01):
        a.observe(v)
    for v in (0.1, 1.0):
        b.observe(v)
    a.merge(b)
    assert a.count == 4
    assert a.sum == pytest.approx(1.111)
    assert a.min == 0.001 and a.max == 1.0
    with pytest.raises(ValueError):
        a.merge(slo_lib.LatencyHistogram(edges=(0.1, 1.0)))


def test_histogram_dict_roundtrip_compact():
    h = slo_lib.LatencyHistogram()
    for v in (0.002, 0.002, 0.3):
        h.observe(v)
    d = h.to_dict()
    # compact: only the two non-zero buckets serialize
    assert len(d["buckets"]) == 2
    h2 = slo_lib.LatencyHistogram.from_dict(d)
    assert h2.counts == h.counts and h2.count == h.count
    assert h2.quantile_bound(0.5) == h.quantile_bound(0.5)
    with pytest.raises(ValueError):
        slo_lib.LatencyHistogram.from_dict({"edges": 3, "count": 0})
    # corrupt bucket indices must refuse, not write out of range (or
    # silently into the overflow bucket via a negative index)
    for bad in ("99", "-1"):
        with pytest.raises(ValueError):
            slo_lib.LatencyHistogram.from_dict(
                {"edges": len(slo_lib.DEFAULT_EDGES),
                 "buckets": {bad: 1}, "count": 1}
            )


def test_serve_report_skips_corrupt_latency_hist(tmp_path):
    """One torn/corrupt latency_hist record must not crash the report
    CLI — the loader's skip-and-continue discipline."""
    log = _serve_log(tmp_path / "s.jsonl", 10.0, 20.0, 100.0, "r")
    with open(log, "a") as f:
        f.write(json.dumps({
            "ts": 9.0, "rel_s": 9.0, "schema_version": 10, "kind": "serve",
            "run_id": "r", "window_s": 1.0, "completed": 1,
            "latency_hist": {"edges": 22, "buckets": {"99": 1}, "count": 1},
        }) + "\n")
    from tpu_dist.obs.summarize import load_records

    records, _ = load_records(log)
    rep = slo_lib.serve_report(records)
    assert rep["n_windows"] == 4  # the corrupt hist is skipped, not fatal
    assert slo_lib.format_report_text(rep)


# -- buckets -----------------------------------------------------------------


def test_bucket_ladder_and_lookup():
    assert batch_buckets(8) == (1, 2, 4, 8)
    assert bucket_for(1, (1, 2, 4, 8)) == 1
    assert bucket_for(3, (1, 2, 4, 8)) == 4
    assert bucket_for(8, (1, 2, 4, 8)) == 8
    with pytest.raises(ValueError):
        batch_buckets(6)  # non-power-of-two ladder top
    with pytest.raises(ValueError):
        bucket_for(9, (1, 2, 4, 8))


# -- engine: determinism, retrace freedom, invariants ------------------------


def test_engine_replay_is_deterministic(tmp_path):
    """Two replays of the same trace on the manual clock produce
    IDENTICAL serving telemetry — histograms, occupancy, queue depths,
    and the serve records (modulo wall-clock stamps)."""
    import jax

    model = _TinyMLP()
    params, bn = model.init(jax.random.PRNGKey(0))
    weights = {"params": params, "bn_state": bn}
    outs = [
        replay(str(tmp_path), f"run{i}", model, weights, auto_step_s=0.0005)
        for i in (0, 1)
    ]
    s0, s1 = outs[0]["stats"], outs[1]["stats"]
    assert s0.total.counts == s1.total.counts
    assert s0.total.sum == pytest.approx(s1.total.sum, abs=1e-12)
    assert s0.queue_depth_max == s1.queue_depth_max
    assert s0.batches == s1.batches
    assert s0.occupancy_sum == pytest.approx(s1.occupancy_sum)
    recs = []
    for i in (0, 1):
        with open(outs[i]["log"]) as f:
            recs.append([
                json.loads(l) for l in f
                if json.loads(l).get("kind") == "serve"
            ])
    drop = ("ts", "rel_s", "run_id", "counters")
    a = [{k: v for k, v in r.items() if k not in drop} for r in recs[0]]
    b = [{k: v for k, v in r.items() if k not in drop} for r in recs[1]]
    assert a == b and a  # identical windows, and there were some


def test_engine_zero_retraces_on_bucket_ladder_then_detects_drift(tmp_path):
    from tpu_dist.metrics.history import MetricsHistory

    hist = MetricsHistory(str(tmp_path / "s.jsonl"), run_id="rt")
    model, eng = _mlp_engine(history=hist)
    eng.warmup(IMAGE_SHAPE)
    rng = np.random.default_rng(0)
    for n in (1, 2, 3, 4, 4, 1):  # every bucket, repeatedly
        for _ in range(n):
            eng.submit(rng.standard_normal(IMAGE_SHAPE).astype(np.float32))
        done = eng.pump()
        assert len(done) == n
        assert all(r.result.shape == (10,) for r in done)
    assert counters_lib.get("compile.retraces") == 0
    assert eng.stats.check_invariants() == []
    # an off-ladder payload shape IS a retrace — counted, evented (same
    # element count so the MLP still runs; the AVAL is what drifted)
    eng.submit(rng.standard_normal((int(np.prod(IMAGE_SHAPE)),))
               .astype(np.float32))
    eng.pump()
    assert counters_lib.get("compile.retraces") == 1
    assert counters_lib.get("serve.retraces") == 1
    hist.close()
    recs = [json.loads(l) for l in open(tmp_path / "s.jsonl")]
    events = [r for r in recs if r.get("kind") == "serve" and r.get("event")]
    assert events and events[0]["event"] == "retrace"


def test_engine_phase_split_partitions_total():
    model, eng = _mlp_engine(clock=ManualClock(auto_step_s=0.001))
    eng.warmup(IMAGE_SHAPE)
    for i in range(3):
        eng.submit(np.zeros(IMAGE_SHAPE, np.float32), arrival_s=0.0)
    (done) = eng.pump()
    for r in done:
        assert r.total_s == pytest.approx(sum(r.phase_s.values()), abs=1e-9)
        assert r.ttfb_s <= r.total_s
        assert r.phase_s["queue_wait"] >= 0
    assert eng.stats.check_invariants() == []
    # a FUTURE-dated arrival (replay that didn't advance its clock, or a
    # frontend on another clock origin) clamps consistently: the phase
    # split must still partition the total, not overshoot it
    eng.submit(np.zeros(IMAGE_SHAPE, np.float32), arrival_s=1e9)
    (late,) = eng.pump()
    assert late.phase_s["queue_wait"] == 0.0
    assert late.total_s == pytest.approx(sum(late.phase_s.values()), abs=1e-9)
    assert eng.stats.check_invariants() == []


def test_compile_watcher_baseline_and_in_watcher_warning(capsys):
    from tpu_dist.obs.costmodel import CompileWatcher

    class Stub:
        def __init__(self):
            self.n = 0

        def _cache_size(self):
            return self.n

    stub = Stub()
    w = CompileWatcher(stub, name="stub step")
    stub.n = 4  # warmup compiled 4 bucket signatures
    assert w.baseline() == 4
    assert counters_lib.get("compile.events") == 4
    assert counters_lib.get("compile.retraces") == 0
    assert w.observe() is False  # steady state
    stub.n = 5
    assert w.observe(context="epoch 1 step 2") is True
    assert counters_lib.get("compile.retraces") == 1
    out = capsys.readouterr().out
    assert "stub step RECOMPILED at epoch 1 step 2" in out
    # without baseline(): the first observation's first compile is free
    counters_lib.reset()
    stub2 = Stub()
    w2 = CompileWatcher(stub2, warn=False)
    stub2.n = 1
    assert w2.observe() is False
    stub2.n = 2
    assert w2.observe() is True
    assert counters_lib.get("compile.retraces") == 1


# -- checkpoint → serving weights --------------------------------------------


def test_serving_restore_through_remapper_bit_exact(tmp_path):
    """A dp=4 ZeRO-1 training checkpoint loads onto the 1-process
    serving extent THROUGH the elastic Remapper, params/bn bit-exact."""
    import jax

    model = _drill_model()
    saved = write_training_ckpt(str(tmp_path / "ck"), model, dp=4)
    out = load_serving_state(str(tmp_path / "ck"), model)
    assert [k for k, kind in out["remapped"] if kind == "zero1_flat"]
    for pa, la in zip(
        jax.tree_util.tree_leaves(saved["params"]),
        jax.tree_util.tree_leaves(out["params"]),
    ):
        assert np.array_equal(np.asarray(pa), np.asarray(la))
    for pa, la in zip(
        jax.tree_util.tree_leaves(saved["bn_state"]),
        jax.tree_util.tree_leaves(out["bn_state"]),
    ):
        assert np.array_equal(np.asarray(pa), np.asarray(la))
    assert out["step"] == 120 and out["epoch"] == 3
    assert counters_lib.get("serve.weights_remapped") == 1


def test_serving_restore_per_leaf_momentum_no_remap(tmp_path):
    """A plain-SGD checkpoint (per-leaf momentum tree, no flat layout)
    loads verbatim — the opt subtree is mirrored, nothing remaps."""
    import jax

    from tpu_dist import ckpt as ckpt_lib
    from tpu_dist.train.state import TrainState

    model = _TinyMLP()
    params, bn = model.init(jax.random.PRNGKey(3))
    mom = jax.tree_util.tree_map(lambda a: np.asarray(a) * 0 + 0.5, params)
    state = TrainState(params=params, bn_state=bn, opt_state=mom,
                       step=np.asarray(7, np.int32))
    ckpt_lib.save(str(tmp_path / "ck"), state, epoch=1)
    out = load_serving_state(str(tmp_path / "ck"), model)
    assert out["remapped"] == []
    for pa, la in zip(
        jax.tree_util.tree_leaves(params),
        jax.tree_util.tree_leaves(out["params"]),
    ):
        assert np.array_equal(np.asarray(pa), np.asarray(la))


def test_serving_restore_quarantines_corrupt_newest(tmp_path):
    """The ladder discipline: a corrupt newest checkpoint is quarantined
    and the older one serves."""
    import os

    import shutil

    model = _drill_model()
    ckdir = str(tmp_path / "ck")
    saved = write_training_ckpt(ckdir, model, dp=2)
    # "newest" = a truncated copy (a torn write: the archive directory is
    # gone — exactly what the ladder's CKPT_READ_ERRORS quarantine)
    newest = os.path.join(ckdir, "ckpt_9.npz")
    shutil.copy(saved["path"], newest)
    size = os.path.getsize(newest)
    with open(newest, "r+b") as f:
        f.truncate(size // 2)
    out = load_serving_state(ckdir, model)
    assert out["epoch"] == 3
    assert not os.path.exists(newest)  # moved aside
    assert os.path.exists(newest + ".corrupt")


def test_serving_restore_refuses_wrong_model(tmp_path):
    from tpu_dist.elastic.errors import ConfigMismatchError

    write_training_ckpt(str(tmp_path / "ck"), _drill_model(), dp=2)
    with pytest.raises((ConfigMismatchError, KeyError)):
        load_serving_state(str(tmp_path / "ck"), _TinyMLP())


# -- int8 weight quantization ------------------------------------------------


def test_quantized_weights_roundtrip_and_serve():
    import jax

    model = _TinyMLP()
    params, bn = model.init(jax.random.PRNGKey(0))
    q, shapes = quantize_weights(params)
    back = dequantize_weights(q, shapes)
    for orig, deq in zip(
        jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(back)
    ):
        orig = np.asarray(orig)
        deq = np.asarray(deq).reshape(orig.shape)
        # per-chunk symmetric int8: error bounded by scale/2 per element
        bound = np.abs(orig).max() / 127.0 * 0.5 + 1e-9
        assert np.max(np.abs(orig - deq)) <= bound * 2
    eng = ServingEngine(model, params, bn, max_batch=2, quantize=True)
    eng.warmup(IMAGE_SHAPE)
    eng.submit(np.zeros(IMAGE_SHAPE, np.float32))
    done = eng.pump()
    assert done[0].result.shape == (10,)
    assert np.all(np.isfinite(done[0].result))
    assert counters_lib.get("compile.retraces") == 0


# -- SLO rules ---------------------------------------------------------------


def test_slo_rule_fire_sustain_cooldown():
    from tpu_dist.obs.alerts import AlertRule

    eng = slo_lib.make_slo_engine([
        AlertRule("p99", "serve.latency_p99_ms", ">", 100.0,
                  sustain=2, cooldown=1),
    ])
    breach = {"serve.latency_p99_ms": 250.0}
    calm = {"serve.latency_p99_ms": 10.0}
    assert eng.observe(breach) == []          # streak 1 < sustain
    assert len(eng.observe(breach)) == 1      # sustained → fires
    assert eng.active() == {"p99": 1.0}
    assert eng.observe(breach) == []          # cooldown drains
    assert len(eng.observe(breach)) == 1      # re-fires after cooldown
    assert eng.observe(calm) == []
    assert eng.active() == {"p99": 0.0}


def test_slo_retrace_delta_rule_fires_on_first_retrace():
    eng = slo_lib.make_slo_engine(slo_lib.load_slo_rules("default"))
    win = {"compile.retraces": 0.0}
    assert not [a for a in eng.observe(win) if a["rule"] == "serve_retrace"]
    win = {"compile.retraces": 1.0}
    fired = [a for a in eng.observe(win) if a["rule"] == "serve_retrace"]
    assert fired and fired[0]["delta"] is True


def test_load_slo_rules_specs(tmp_path):
    rules = slo_lib.load_slo_rules("default")
    assert {r.name for r in rules} >= {"slo_p99_high", "serve_retrace"}
    spec = tmp_path / "slo.toml"
    spec.write_text(
        '[[rule]]\nbuiltin = "slo_p99_high"\nthreshold = 50.0\n'
        '[[rule]]\nname = "q"\nmetric = "serve.queue_depth"\n'
        'op = ">"\nthreshold = 10\n'
    )
    loaded = slo_lib.load_slo_rules(str(spec))
    assert loaded[0].name == "slo_p99_high" and loaded[0].threshold == 50.0
    assert loaded[1].metric == "serve.queue_depth"
    bad = tmp_path / "bad.toml"
    bad.write_text('[[rule]]\nbuiltin = "no_such_slo"\n')
    with pytest.raises(ValueError):
        slo_lib.load_slo_rules(str(bad))


# -- exposition histogram grammar --------------------------------------------


def test_exposition_histogram_grammar_roundtrip():
    st = slo_lib.ServeStats()
    st.on_batch(2, 2)
    for v in (0.002, 0.004, 0.05):
        st.on_request_done(v, v / 2, {p: v / 10 for p in slo_lib.PHASES})
    text = export_lib.render(
        {"serve.requests": 3}, histograms=st.histogram_families()
    )
    fam = export_lib.metric_name("serve.latency_seconds")
    # grammar: TYPE line, le-labelled cumulative buckets ending at +Inf,
    # then _sum and _count
    assert f"# TYPE {fam} histogram" in text
    bucket_lines = [
        l for l in text.splitlines() if l.startswith(fam + "_bucket")
    ]
    assert bucket_lines[-1].startswith(fam + '_bucket{le="+Inf"}')
    for line in bucket_lines:
        assert re.match(
            r'^[a-zA-Z_:][a-zA-Z0-9_:]*_bucket\{le="[^"]+"\} \d+$', line
        ), line
    parsed = export_lib.parse(text)
    assert parsed[fam + "_count"] == 3
    assert parsed[fam + "_sum"] == pytest.approx(0.056)
    # cumulative monotone, +Inf equals count
    cums = [float(l.rsplit(" ", 1)[1]) for l in bucket_lines]
    assert cums == sorted(cums) and cums[-1] == 3
    assert parsed[fam + '_bucket{le="+Inf"}'] == 3


# -- compare --slo -----------------------------------------------------------


def _serve_log(path, p50, p99, rps, run_id):
    recs = [
        {"ts": float(i), "rel_s": float(i), "schema_version": 10,
         "kind": "serve", "run_id": run_id, "window_s": 1.0,
         "requests": 10, "completed": 10, "requests_per_s": rps,
         "latency_p50_ms": p50, "latency_p99_ms": p99,
         "ttfb_p99_ms": p99 * 0.8, "availability": 1.0,
         "batch_occupancy": 0.9}
        for i in range(3)
    ]
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    return str(path)


def test_compare_slo_exit_contract(tmp_path, capsys):
    from tpu_dist.obs import __main__ as obs_main

    base = _serve_log(tmp_path / "b.jsonl", 10.0, 20.0, 100.0, "b")
    worse = _serve_log(tmp_path / "w.jsonl", 30.0, 60.0, 95.0, "w")
    better = _serve_log(tmp_path / "g.jsonl", 5.0, 10.0, 120.0, "g")
    assert obs_main.main(["compare", base, worse, "--slo"]) == 1
    capsys.readouterr()
    assert obs_main.main(["compare", base, better, "--slo"]) == 0
    out = capsys.readouterr().out
    assert "REGRESSED" not in out  # lower latency is never flagged
    # two serve-less logs: the gate compares nothing → broken gate, 2
    t1, t2 = tmp_path / "t1.jsonl", tmp_path / "t2.jsonl"
    for p in (t1, t2):
        p.write_text(json.dumps({
            "ts": 1.0, "rel_s": 1.0, "schema_version": 10,
            "kind": "train_epoch", "epoch": 0, "run_id": "t",
            "images_per_sec": 10.0, "epoch_time": 1.0, "loss": 1.0,
        }) + "\n")
    assert obs_main.main(["compare", str(t1), str(t2), "--slo"]) == 2
    # --slo composes with neither --bench nor --goodput
    assert obs_main.main(["compare", base, worse, "--slo", "--bench"]) == 2
    assert obs_main.main(["compare", base, worse, "--slo", "--goodput"]) == 2


def test_metric_direction_registry():
    from tpu_dist.obs import compare as compare_lib

    assert compare_lib.direction_of("serve_latency_p99_ms") == ("lower", 0.0)
    assert compare_lib.direction_of("serve_requests_per_s") == ("higher", 0.0)
    # suffix defaults for future metrics: latencies lower, rates higher
    assert compare_lib.direction_of("future_thing_ms") == ("lower", 0.0)
    assert compare_lib.direction_of("future_rate_per_s") == ("higher", 0.0)
    with pytest.raises(KeyError):
        compare_lib.direction_of("mystery_metric")
    # the derived tables agree with the registry — no hand-rolled rows
    for key, direction, slack in (
        compare_lib.REPORT_METRICS + compare_lib.SLO_METRICS
    ):
        assert (direction, slack) == compare_lib.direction_of(key)
    slo_keys = {m[0] for m in compare_lib.SLO_METRICS}
    assert "serve_latency_p99_ms" in slo_keys
    assert "serve_requests_per_s" in slo_keys


# -- schema v10 rendering ----------------------------------------------------


def test_serve_records_render_in_summarize_and_tail(tmp_path):
    from tpu_dist.obs import tail as tail_lib
    from tpu_dist.obs.summarize import format_text, load_records, summarize

    log = _serve_log(tmp_path / "s.jsonl", 10.0, 20.0, 100.0, "r")
    with open(log, "a") as f:
        f.write(json.dumps({
            "ts": 4.0, "rel_s": 4.0, "schema_version": 10, "kind": "serve",
            "run_id": "r", "event": "retrace", "bucket": 4, "n_real": 3,
        }) + "\n")
        f.write(json.dumps({
            "ts": 5.0, "rel_s": 5.0, "schema_version": 10, "kind": "alert",
            "run_id": "r", "rule": "slo_p99_high",
            "metric": "serve.latency_p99_ms", "value": 600.0,
            "threshold": 500.0, "op": ">", "sustained": 2,
        }) + "\n")
    records, bad = load_records(log)
    report = summarize(records, bad)
    assert len(report["serve_windows"]) == 3
    assert report["serve_events"] == [
        {"event": "retrace", "bucket": 4, "n_real": 3}
    ]
    assert report["skipped_kinds"] == {}  # serve is a KNOWN kind
    text = format_text(report)
    assert "serving SLO windows" in text
    assert "RETRACE on a bucket-4 batch" in text
    state = tail_lib.TailState()
    state.add(records)
    frame = state.render()
    assert "serve: 100.0 req/s" in frame
    assert "serve RETRACE" in frame
    # the offline serve report CLI engine over the same records
    rep = slo_lib.serve_report(records)
    assert rep["n_windows"] == 3 and len(rep["alerts"]) == 1
    out = slo_lib.format_report_text(rep)
    assert "SLO ALERT slo_p99_high" in out


def test_serve_record_schema_v10_stamp(tmp_path):
    from tpu_dist.metrics.history import SCHEMA_VERSION, MetricsHistory

    assert SCHEMA_VERSION == 15  # v15: causal decision tracing (ISSUE 19)
    path = str(tmp_path / "h.jsonl")
    with MetricsHistory(path, run_id="s10") as h:
        h.log("serve", window_s=1.0, completed=4, latency_p50_ms=3.0)
    rec = json.loads(open(path).read())
    assert rec["schema_version"] == 15 and rec["kind"] == "serve"


def test_serve_cli_report(tmp_path, capsys):
    from tpu_dist.serve import __main__ as serve_main

    log = _serve_log(tmp_path / "s.jsonl", 10.0, 20.0, 100.0, "r")
    assert serve_main.main(["report", log]) == 0
    assert "serve report — 3 window(s)" in capsys.readouterr().out
    empty = tmp_path / "empty.jsonl"
    empty.write_text(json.dumps({"kind": "train_epoch", "epoch": 0}) + "\n")
    assert serve_main.main(["report", str(empty)]) == 1
    assert serve_main.main(["report", str(tmp_path / "nope.jsonl")]) == 2


# -- TD114 -------------------------------------------------------------------


def test_td114_registry_and_audit_all_wiring():
    import inspect

    from tpu_dist.analysis import jaxpr_audit
    from tpu_dist.analysis.rules import RULES

    assert RULES["TD114"].name == "serving-slo-not-noop"
    assert "serving_slo_noop_violations" in inspect.getsource(
        jaxpr_audit.audit_all
    )


def test_td114_gate_serving_slo_is_noop():
    from tpu_dist.analysis.jaxpr_audit import serving_slo_noop_violations

    assert serving_slo_noop_violations() == []


# -- e2e ---------------------------------------------------------------------


@pytest.mark.slow
def test_serve_drill_e2e(tmp_path):
    from tpu_dist.serve.drill import run_drill

    summary = run_drill(str(tmp_path / "drill"))
    assert summary["retraces_post_warmup"] == 0
    assert summary["compare_slo"] == {
        "regression_rc": 1, "improvement_rc": 0,
    }
    assert any(kind == "zero1_flat" for _, kind in summary["remapped"])


@pytest.mark.slow
def test_bench_serve_emits_fingerprinted_record(tmp_path):
    out = subprocess.run(
        [sys.executable, "bench.py", "--serve", "--serve_tiny",
         "--serve_requests", "24", "--serve_max_batch", "4"],
        capture_output=True, text=True, timeout=560,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    for field in ("requests_per_s", "latency_p50_ms", "latency_p99_ms",
                  "batch_occupancy"):
        assert isinstance(rec[field], (int, float)), field
    assert rec["retraces"] == 0
    # the PR 7 capture fingerprint rides along → stale re-emissions of a
    # serving number are auto-flagged by obs compare --bench
    assert rec["capture"]["bench_run_id"]
    assert rec["unit"] == "requests/sec"
