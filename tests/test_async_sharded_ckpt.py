"""Snapshot-then-write sharded checkpointing (--sharded_ckpt +
--async_ckpt, ckpt/checkpoint.py::AsyncShardedCheckpointer) and the
overlap autotuner's TD121 gate (tpu_dist/analysis/overlap.py).

TD120 pins the composition's two invariants: the traced train step is
byte-identical whether or not a background writer is armed, and an
async-written checkpoint restores bit-exact to a synchronous sharded
save of the same state. The fault probes (EIO mid-background, SIGKILL
during the write, SIGTERM mid-run) must all be CAUGHT — a probe that
comes back clean means the detector is dead.

TD121 pins the tuner contract: every knob moves the collective
schedule, never the payload-byte inventory shardlint pins.
"""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from tpu_dist.ckpt import checkpoint as ckpt_lib
from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.config import TrainConfig
from tpu_dist.resilience import faults, preemption
from tpu_dist.resilience.preemption import PREEMPTION_EXIT_CODE
from tpu_dist.train.state import TrainState
from tpu_dist.train.trainer import Trainer, register_model
from tests.helpers import TinyConvNet, tiny_resnet
from tests.test_sharded_ckpt import _fsdp_like_state

register_model("tiny_resnet_asc", lambda num_classes=10: tiny_resnet(num_classes))


@pytest.fixture(autouse=True)
def _clean_fault_state():
    faults.clear()
    preemption.clear()
    prev = ckpt_lib.set_io_retries(0)
    yield
    ckpt_lib.set_io_retries(prev)
    faults.clear()
    preemption.clear()


def _tree_equal(a, b):
    for x, y in zip(
        jax.tree_util.tree_leaves(a._asdict()),
        jax.tree_util.tree_leaves(b._asdict()),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _shard_crcs(ckpt_dir, stem):
    """{shard_file: {entry: crc32}} — the bit-identity comparison key
    (npz BYTES differ across saves via zip timestamps; the per-entry
    CRC32 stamps + restored-array equality are the format's identity)."""
    out = {}
    for nm in sorted(os.listdir(ckpt_dir)):
        if nm.startswith(f"{stem}.shard") and nm.endswith(".npz"):
            with np.load(os.path.join(ckpt_dir, nm)) as z:
                out[nm] = json.loads(bytes(z["__crc__"].tobytes()).decode())
    return out


# --------------------------------------------------------------------------
# TD120: restore bit-exact to the synchronous sharded format
# --------------------------------------------------------------------------


def test_async_save_bit_identical_to_sync(tmp_path):
    mesh = mesh_lib.data_parallel_mesh()
    state = _fsdp_like_state(mesh)
    sync_dir, async_dir = str(tmp_path / "sync"), str(tmp_path / "async")

    mpath_sync = ckpt_lib.save_sharded(sync_dir, state, 3, extra_meta={"k": 1})
    w = ckpt_lib.AsyncShardedCheckpointer()
    mpath_async = w.save(async_dir, state, 3, extra_meta={"k": 1})
    assert w.close(timeout=60.0)

    # same manifest name, same per-entry CRC32 stamps shard-for-shard
    assert os.path.basename(mpath_sync) == os.path.basename(mpath_async)
    assert _shard_crcs(sync_dir, "ckpt_3") == _shard_crcs(async_dir, "ckpt_3")
    ckpt_lib.verify_sharded(mpath_async, deep=True)
    assert ckpt_lib.read_sharded_meta(mpath_async)["k"] == 1

    # and the restored trees are bit-equal to each other AND the source
    r_sync = ckpt_lib.restore_sharded(mpath_sync, _fsdp_like_state(mesh))
    r_async = ckpt_lib.restore_sharded(mpath_async, _fsdp_like_state(mesh))
    _tree_equal(r_sync, r_async)
    _tree_equal(state, r_async)


def test_traced_step_byte_identical_with_writer_armed(tmp_path):
    """TD120's other half: arming the background writer must not change
    the traced step program — the snapshot is jax.device_get at the step
    boundary, never a traced op."""
    from tpu_dist.train.optim import SGD
    from tpu_dist.train.step import make_train_step

    mesh = mesh_lib.data_parallel_mesh()
    model = TinyConvNet(num_classes=10, width=16)
    params, bn = model.init(jax.random.PRNGKey(0))
    opt = SGD(momentum=0.9)
    state = TrainState.create(params, bn, opt)
    step = make_train_step(model.apply, opt, mesh, sync_bn=False, donate=False)
    x = np.zeros((8, 8, 8, 3), np.float32)
    y = np.zeros((8,), np.int32)

    before = str(jax.make_jaxpr(step)(state, x, y, 0.1))
    w = ckpt_lib.AsyncShardedCheckpointer()
    w.save(str(tmp_path), _fsdp_like_state(mesh), 0)
    during = str(jax.make_jaxpr(step)(state, x, y, 0.1))
    assert w.close(timeout=60.0)
    after = str(jax.make_jaxpr(step)(state, x, y, 0.1))
    assert before == during == after


def test_async_blocks_only_for_snapshot(tmp_path, monkeypatch):
    """The submit path must return before the publish runs: slow the
    background write down and prove save() does not wait for it."""
    ev_started = []
    real_write = ckpt_lib._write_shard_file

    def slow_write(ckpt_dir, snap):
        ev_started.append(time.monotonic())
        time.sleep(0.5)
        return real_write(ckpt_dir, snap)

    monkeypatch.setattr(ckpt_lib, "_write_shard_file", slow_write)
    mesh = mesh_lib.data_parallel_mesh()
    state = _fsdp_like_state(mesh)
    w = ckpt_lib.AsyncShardedCheckpointer()
    t0 = time.monotonic()
    w.save(str(tmp_path), state, 0)
    blocked = time.monotonic() - t0
    assert blocked < 0.4, f"save() blocked {blocked:.2f}s on the publish"
    assert w.close(timeout=60.0)
    ckpt_lib.verify_sharded(
        os.path.join(str(tmp_path), "ckpt_0.manifest.json"), deep=True
    )


# --------------------------------------------------------------------------
# TD120: the EIO probe must be caught (dead detector = broken gate)
# --------------------------------------------------------------------------


def test_eio_mid_background_surfaces_at_drain(tmp_path):
    mesh = mesh_lib.data_parallel_mesh()
    state = _fsdp_like_state(mesh)
    w = ckpt_lib.AsyncShardedCheckpointer()
    w.save(str(tmp_path), state, 0)
    assert w.wait(timeout=60.0)  # epoch 0 committed clean

    faults.configure("ckpt_write@call=1")  # next shard write: EIO
    w.save(str(tmp_path), state, 1)
    with pytest.raises(OSError, match="fault-injected"):
        w.wait(timeout=60.0)
    faults.clear()
    w.close(timeout=60.0)

    # the failed epoch never committed; the ladder still points at 0
    found = ckpt_lib.latest_sharded_checkpoint(str(tmp_path))
    assert found is not None and found[1] == 0
    ckpt_lib.verify_sharded(found[0], deep=True)


def test_eio_retry_ladder_recovers_in_background(tmp_path):
    """--ckpt_io_retries still covers the background write: one injected
    EIO, two retries — the save must succeed and commit."""
    ckpt_lib.set_io_retries(2)
    faults.configure("ckpt_write@call=1")
    mesh = mesh_lib.data_parallel_mesh()
    state = _fsdp_like_state(mesh)
    w = ckpt_lib.AsyncShardedCheckpointer()
    w.save(str(tmp_path), state, 0)
    assert w.close(timeout=60.0)
    found = ckpt_lib.latest_sharded_checkpoint(str(tmp_path))
    assert found is not None and found[1] == 0
    ckpt_lib.verify_sharded(found[0], deep=True)


def test_bounded_drain_refuses_loudly(tmp_path, monkeypatch):
    """A drain that cannot finish in time returns False with in_flight
    still counted — the Trainer's _ckpt_close turns that into the
    counted ckpt.drain_abandoned loss, never a silent one."""
    real_write = ckpt_lib._write_shard_file

    def slow_write(ckpt_dir, snap):
        time.sleep(1.5)
        return real_write(ckpt_dir, snap)

    monkeypatch.setattr(ckpt_lib, "_write_shard_file", slow_write)
    mesh = mesh_lib.data_parallel_mesh()
    w = ckpt_lib.AsyncShardedCheckpointer()
    w.save(str(tmp_path), _fsdp_like_state(mesh), 0)
    assert w.close(timeout=0.05) is False
    assert w.in_flight == 1  # the abandoned write is COUNTED, not hidden


def test_same_stem_resave_drains_first(tmp_path):
    """Two saves to one stem (ckpt_best overwrite): the second submit
    must drain the first so the main-thread uncommit cannot race the
    background commit."""
    mesh = mesh_lib.data_parallel_mesh()
    state = _fsdp_like_state(mesh)
    w = ckpt_lib.AsyncShardedCheckpointer()
    w.save_best(str(tmp_path), state, 0, metric=1.0)
    w.save_best(str(tmp_path), state, 1, metric=2.0)
    assert w.close(timeout=60.0)
    mpath = os.path.join(str(tmp_path), "ckpt_best.manifest.json")
    ckpt_lib.verify_sharded(mpath, deep=True)
    assert ckpt_lib.read_sharded_meta(mpath)["metric"] == 2.0


# --------------------------------------------------------------------------
# Elastic: cross-extent restore of an async-written checkpoint
# --------------------------------------------------------------------------


def test_cross_extent_elastic_restore_of_async_written_ckpt(tmp_path):
    """A ZeRO-1 flat vector written by the BACKGROUND path at extent 8
    remaps onto a 4-device template exactly like a synchronous save —
    restore semantics are unchanged by who wrote the bytes."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_dist.comm.quantize import padded_len
    from tpu_dist.elastic.remap import elastic_stamp, make_remapper

    def _mesh(n):
        return mesh_lib.device_mesh(
            [n], [mesh_lib.DATA_AXIS], jax.devices()[:n]
        )

    L = 26  # padded_len(26, 8)=32 vs padded_len(26, 4)=28: real reshape
    mesh8, mesh4 = _mesh(8), _mesh(4)
    w_arr = np.arange(24, dtype=np.float32).reshape(8, 3)
    b_arr = np.asarray([7.0, 9.0], np.float32)
    mom = np.zeros(padded_len(L, 8), np.float32)
    mom[:L] = np.arange(L, dtype=np.float32) * 1e-3
    st8 = TrainState(
        params={
            "b": jax.device_put(b_arr, NamedSharding(mesh8, P())),
            "w": jax.device_put(w_arr, NamedSharding(mesh8, P("data"))),
        },
        bn_state={},
        opt_state=jax.device_put(mom, NamedSharding(mesh8, P("data"))),
        step=jax.device_put(np.asarray(5, np.int32), NamedSharding(mesh8, P())),
    )
    writer = ckpt_lib.AsyncShardedCheckpointer()
    mpath = writer.save(
        str(tmp_path), st8, 0, extra_meta={"elastic": elastic_stamp(8, 1, L)}
    )
    assert writer.close(timeout=60.0)

    tmpl4 = TrainState(
        params={
            "b": jax.device_put(np.zeros_like(b_arr), NamedSharding(mesh4, P())),
            "w": jax.device_put(
                np.zeros_like(w_arr), NamedSharding(mesh4, P("data"))
            ),
        },
        bn_state={},
        opt_state=jax.device_put(
            np.zeros(padded_len(L, 4), np.float32),
            NamedSharding(mesh4, P("data")),
        ),
        step=jax.device_put(np.asarray(0, np.int32), NamedSharding(mesh4, P())),
    )
    rm = make_remapper(tmpl4, ckpt_lib.read_sharded_meta(mpath), 4)
    out = ckpt_lib.restore_sharded(mpath, tmpl4, remap=rm)
    np.testing.assert_array_equal(np.asarray(out.params["w"]), w_arr)
    got = np.asarray(out.opt_state)
    assert got.shape == (padded_len(L, 4),)
    np.testing.assert_array_equal(got[:L], mom[:L])
    assert int(np.asarray(out.step)) == 5


# --------------------------------------------------------------------------
# Crash probes: SIGKILL mid-write, SIGTERM mid-run (subprocess, slow)
# --------------------------------------------------------------------------

_SIGKILL_CHILD = r"""
import os, sys, time
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
from tpu_dist.ckpt import checkpoint as ckpt_lib
from tpu_dist.comm import mesh as mesh_lib
from tests.test_sharded_ckpt import _fsdp_like_state

ckpt_dir = sys.argv[1]
mesh = mesh_lib.data_parallel_mesh()
state = _fsdp_like_state(mesh)
ckpt_lib.save_sharded(ckpt_dir, state, 0)  # the committed floor

real = ckpt_lib._write_shard_file
def slow(d, snap):
    print("WRITE_STARTED", flush=True)  # parent kills -9 on this line
    time.sleep(30)
    return real(d, snap)
ckpt_lib._write_shard_file = slow

w = ckpt_lib.AsyncShardedCheckpointer()
w.save(ckpt_dir, state, 1)
w.wait()  # never returns: SIGKILL lands mid-write
"""


@pytest.mark.slow
def test_sigkill_during_background_write_leaves_restorable_ladder(tmp_path):
    """Kill -9 while the background writer is mid-publish: whatever
    latest_sharded_checkpoint then returns must deep-verify and restore
    — the uncommit-first / manifest-last ordering means the torn epoch
    is invisible, not half-visible."""
    env = dict(os.environ, PYTHONPATH=os.path.dirname(os.path.dirname(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-c", _SIGKILL_CHILD, str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(__file__)),
    )
    try:
        deadline = time.monotonic() + 300
        for line in proc.stdout:
            if "WRITE_STARTED" in line:
                break
            if time.monotonic() > deadline:
                raise AssertionError("child never reached the write")
        proc.kill()  # SIGKILL: no cleanup, no drain
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == -signal.SIGKILL

    found = ckpt_lib.latest_sharded_checkpoint(str(tmp_path))
    assert found is not None and found[1] == 0, found
    ckpt_lib.verify_sharded(found[0], deep=True)
    mesh = mesh_lib.data_parallel_mesh()
    restored = ckpt_lib.restore_sharded(found[0], _fsdp_like_state(mesh))
    _tree_equal(_fsdp_like_state(mesh), restored)


@pytest.mark.slow
def test_cli_sigterm_drains_async_sharded_then_exit_75(tmp_path):
    """SIGTERM mid-run with the async+sharded composition: the trainer
    finishes the in-flight step, emergency-saves, DRAINS the background
    writer, and the CLI maps it to exit 75 — with a committed,
    deep-verifiable sharded checkpoint on disk."""
    from tpu_dist.cli.train import main

    with pytest.raises(SystemExit) as ei:
        main([
            "--dataset", "synthetic", "--model", "tiny_resnet_asc",
            "--num_classes", "10", "--batch_size", "64", "--epochs", "2",
            "--steps_per_epoch", "3", "--eval_every", "0", "--save_every",
            "1", "--synthetic_n", "256", "--seed", "0", "--log_every", "50",
            "--no_sync_bn", "--ckpt_dir", str(tmp_path),
            "--sharded_ckpt", "--async_ckpt",
            "--fault_plan", "sigterm@epoch=0:step=1",
        ])
    assert ei.value.code == PREEMPTION_EXIT_CODE
    found = ckpt_lib.latest_sharded_checkpoint(str(tmp_path))
    assert found is not None, sorted(os.listdir(tmp_path))
    ckpt_lib.verify_sharded(found[0], deep=True)


@pytest.mark.slow
def test_trainer_async_sharded_resume_and_ckpt_accounting(tmp_path):
    """e2e: the once-refused --sharded_ckpt + --async_ckpt composition
    trains, commits every epoch, resumes from the manifest, and the
    goodput ledger accounts the (shrunken) blocking window in ckpt_s
    with the partition invariant intact."""
    log = str(tmp_path / "hist.jsonl")
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_asc", num_classes=10,
        batch_size=64, epochs=2, steps_per_epoch=2, eval_every=0,
        synthetic_n=256, sync_bn=False, sharded_ckpt=True, async_ckpt=True,
        ckpt_dir=str(tmp_path), save_every=1, log_every=10, log_file=log,
    )
    t = Trainer(cfg)
    t.fit()
    found = ckpt_lib.latest_sharded_checkpoint(str(tmp_path))
    assert found is not None and found[1] == 1
    ckpt_lib.verify_sharded(found[0], deep=True)

    # ckpt_s accounts the blocking window; the bucket partition stays
    # exact (buckets + unattributed == elapsed, the ledger invariant)
    from tpu_dist.obs import goodput as goodput_lib

    records = [json.loads(l) for l in open(log)]
    ledger = goodput_lib.run_ledger(records)
    assert ledger is not None and ledger["ckpt_s"] > 0.0
    parts = sum(ledger[f"{b}_s"] for b in goodput_lib.ALL_BUCKETS)
    assert abs(parts - ledger["elapsed_s"]) < 1e-3, ledger

    t2 = Trainer(cfg.replace(resume=True))
    assert t2.start_epoch == 2  # both epochs committed and visible


# --------------------------------------------------------------------------
# TD121: tuner knobs are schedule-only (payload pinned, schedule moves)
# --------------------------------------------------------------------------

from tpu_dist.analysis import overlap as overlap_lib  # noqa: E402


def _handcrafted_report():
    """A minimal structurally-valid tune_report_v1 with recorded
    inventories — lets the gate/probe/loader tests run without a single
    compile."""
    base = {
        "family": "zero1_sgd", "knobs": {},
        "wire": {"payload_bytes": 1000, "quantized_payload_bytes": 0,
                 "sideband_bytes": 0},
        "collective_ops": 2, "jaxpr_collectives": 2,
        "fingerprint": [["reduce-scatter", "f32", 100],
                        ["all-gather", "f32", 100]],
        "distances": [3, 1],
        "schedule": {"collectives": 2, "total_distance": 4,
                     "mean_distance": 2.0, "min_distance": 1},
    }
    cand = json.loads(json.dumps(base))
    cand["knobs"] = {"rs_ag_chunks": 2}
    cand["fingerprint"] = [["reduce-scatter", "f32", 50]] * 2 + [
        ["all-gather", "f32", 50]] * 2
    cand["distances"] = [5, 4, 2, 1]
    cand["collective_ops"] = 4
    cand["schedule"] = {"collectives": 4, "total_distance": 12,
                        "mean_distance": 3.0, "min_distance": 1}
    cand["td121"] = {"clean": True, "violations": []}
    return {
        "schema": overlap_lib.SCHEMA,
        "backend": "cpu", "device_kind": "cpu", "n_devices": 8,
        "jax_version": jax.__version__,
        "objective": "hlo_schedule_proxy",
        "measured_overlap_frac": None,
        "families": {"zero1_sgd": {
            "baseline": base, "candidates": [base, cand],
            "chosen": {"knobs": cand["knobs"], "schedule": cand["schedule"],
                       "gain_frac": 0.5},
        }},
        "skips": {},
        "counts": {"families": 1, "skipped": 0, "violations": 0},
    }


def test_td121_gate_payload_and_vacuous_knob():
    report = _handcrafted_report()
    assert overlap_lib.recheck_report(report) == []

    # payload moved -> violation
    bad = overlap_lib.inject_payload(report)
    vs = overlap_lib.recheck_report(bad)
    assert vs and all(v.rule == "TD121" for v in vs)
    assert "payload" in vs[0].message

    # knob that changed NOTHING -> also a violation (vacuous search space)
    vac = json.loads(json.dumps(report))
    cand = vac["families"]["zero1_sgd"]["candidates"][1]
    base = vac["families"]["zero1_sgd"]["baseline"]
    for k in ("fingerprint", "distances", "jaxpr_collectives",
              "collective_ops", "schedule"):
        cand[k] = json.loads(json.dumps(base[k]))
    vs2 = overlap_lib.recheck_report(vac)
    assert vs2 and "did not move" in vs2[0].message


def test_tune_report_roundtrip_and_forward_compat(tmp_path):
    report = _handcrafted_report()
    path = str(tmp_path / "tune_report.json")
    overlap_lib.save_tune_report(report, path)
    back = overlap_lib.load_tune_report(path)
    assert back["families"].keys() == report["families"].keys()
    assert overlap_lib.chosen_knobs(back, "zero1_sgd") == {"rs_ag_chunks": 2}
    assert overlap_lib.chosen_knobs(back, "dp_sgd") == {}

    # NEWER schema: tolerated, unreadable families skipped with a count
    newer = json.loads(json.dumps(report))
    newer["schema"] = "tune_report_v2"
    newer["families"]["future_fam"] = {"chosen": {"v2_only": True}}
    overlap_lib.save_tune_report(newer, path)
    got = overlap_lib.load_tune_report(path)
    assert "future_fam" not in got["families"]
    assert got["load_notes"]["skipped_count"] == 1

    # foreign tag: typed refusal
    foreign = json.loads(json.dumps(report))
    foreign["schema"] = "plan_report_v1"
    overlap_lib.save_tune_report(foreign, path)
    with pytest.raises(overlap_lib.TuneReportError, match="not a tune_report"):
        overlap_lib.load_tune_report(path)

    # same-version entry missing required chosen keys: typed refusal
    broken = json.loads(json.dumps(report))
    del broken["families"]["zero1_sgd"]["chosen"]["schedule"]
    overlap_lib.save_tune_report(broken, path)
    with pytest.raises(overlap_lib.TuneReportError, match="missing"):
        overlap_lib.load_tune_report(path)


def test_knob_refusal_walls():
    """make_train_step refuses out-of-scope knob combinations before any
    trace — a tuner knob silently ignored would be a lying report."""
    from tpu_dist.train.optim import SGD
    from tpu_dist.train.step import make_train_step

    mesh = mesh_lib.data_parallel_mesh()
    model = TinyConvNet(num_classes=10, width=16)
    opt = SGD(momentum=0.9)
    for bad in (
        dict(pmean_fusion="nope"),
        dict(pmean_fusion="per_leaf", shard_weight_update=True),
        dict(pmean_fusion="per_leaf", grad_compression="int8"),
        dict(rs_ag_chunks=0),
        dict(rs_ag_chunks=2),  # needs shard_weight_update
        dict(rs_ag_chunks=2, shard_weight_update=True,
             grad_compression="int8"),
    ):
        with pytest.raises(ValueError):
            make_train_step(model.apply, opt, mesh, sync_bn=False, **bad)


@pytest.mark.slow
def test_knob_numerics_bit_exact():
    """The semantics-preserving contract, executed: per-leaf pmean and
    chunked RS+AG produce bit-identical params/metrics to the fused /
    unchunked defaults (and a huge chunk count clamps, not crashes)."""
    import jax.numpy as jnp

    from tpu_dist.train import step as step_lib
    from tpu_dist.train.optim import SGD

    mesh = mesh_lib.data_parallel_mesh()
    model = TinyConvNet(num_classes=10, width=16)
    params, bn = model.init(jax.random.PRNGKey(0))
    x = np.random.default_rng(0).normal(size=(64, 8, 8, 3)).astype(np.float32)
    y = np.random.default_rng(1).integers(0, 10, size=(64,)).astype(np.int32)

    def run(**kw):
        opt = SGD(momentum=0.9)
        st = TrainState.create(params, bn, opt)
        if kw.get("shard_weight_update"):
            st = st._replace(opt_state=step_lib.init_sharded_opt_state(
                params, mesh, optimizer=opt
            ))
        step = step_lib.make_train_step(
            model.apply, opt, mesh, sync_bn=False, donate=False, **kw
        )
        st2, m = step(st, x, y, jnp.float32(0.1))
        leaves = [np.asarray(l) for l in jax.tree_util.tree_leaves(st2.params)]
        return leaves, {k: float(v) for k, v in m.items()}

    p_f, m_f = run()
    p_l, m_l = run(pmean_fusion="per_leaf")
    for a, b in zip(p_f, p_l):
        np.testing.assert_array_equal(a, b)
    assert m_f == m_l

    p_z1, m_z1 = run(shard_weight_update=True)
    p_z4, m_z4 = run(shard_weight_update=True, rs_ag_chunks=4)
    for a, b in zip(p_z1, p_z4):
        np.testing.assert_array_equal(a, b)
    assert m_z1 == m_z4

    p_big, _ = run(shard_weight_update=True, rs_ag_chunks=10_000_000)
    for a, b in zip(p_z1, p_big):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_tune_real_families_clean_and_probe_caught():
    """The full search on the audit models: zero TD121 violations, no
    skipped families, every chosen knob recorded — and the injected-
    payload probe flags, proving the detector lives (CLI exit-2 path)."""
    report, violations = overlap_lib.tune()
    assert violations == [], [v.message for v in violations]
    assert report["skips"] == {}, report["skips"]
    assert set(report["families"]) == set(overlap_lib.tunable_families())
    for fam, entry in report["families"].items():
        assert "knobs" in entry["chosen"], fam
        # every non-baseline candidate carried a TD121 verdict
        for cand in entry["candidates"]:
            if cand["knobs"]:
                assert cand["td121"]["clean"], (fam, cand["knobs"])

    flagged = overlap_lib.recheck_report(overlap_lib.inject_payload(report))
    assert flagged, "injected payload perturbation NOT flagged: dead detector"
    assert overlap_lib.recheck_report(report) == []
