"""Integration: accuracy (not just loss) climbs on a learnable task.

The reference's implicit integration test is run-to-convergence on
CIFAR-100 (SURVEY §4); with no dataset in this environment, a deterministic
learnable mapping (labels = quadrant of the brightest image region) stands
in: a model that generalizes must push accuracy well above chance.
"""

import jax
import pytest
import numpy as np

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tpu_dist.train.step import make_train_step
from tests.helpers import TinyConvNet


def _learnable_batch(n, rng):
    """Images whose label is the quadrant (0-3) containing the bright blob."""
    x = rng.normal(scale=0.3, size=(n, 8, 8, 3)).astype(np.float32)
    labels = rng.integers(0, 4, n).astype(np.int32)
    for i, lab in enumerate(labels):
        r, c = divmod(int(lab), 2)
        x[i, r * 4 : r * 4 + 4, c * 4 : c * 4 + 4, :] += 2.0
    return x, labels


def test_accuracy_rises_above_chance():
    mesh = mesh_lib.data_parallel_mesh()
    model = TinyConvNet(num_classes=4, width=16)
    opt = SGD(momentum=0.9, weight_decay=1e-4)
    params, bn = model.init(jax.random.PRNGKey(0))
    state = jax.device_put(TrainState.create(params, bn, opt), mesh_lib.replicated(mesh))
    step = make_train_step(model.apply, opt, mesh)

    rng = np.random.default_rng(0)
    accs = []
    for i in range(80):
        x, y = _learnable_batch(64, rng)
        xs = mesh_lib.shard_batch(mesh, x)
        ys = mesh_lib.shard_batch(mesh, y)
        state, m = step(state, xs, ys, 0.05)
        accs.append(float(m["acc1"]))
    # fresh data every step → this is generalization, not memorization
    assert np.mean(accs[-10:]) > 60.0, np.mean(accs[-10:])  # chance = 25%


@pytest.mark.slow  # >10s e2e: excluded from the timed tier-1 gate; the
# quick slice keeps a fast representative of this subsystem in the gate
def test_trainer_converges_on_learnable_dataset():
    """Full Trainer (streaming pipeline + eval) reaches well-above-chance
    VALIDATION accuracy on the learnable synthetic task — the closest
    possible stand-in for the reference's run-to-convergence check."""
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer, register_model
    from tests.helpers import tiny_resnet

    register_model("tiny_conv_q", lambda num_classes=4: tiny_resnet(num_classes))
    cfg = TrainConfig(
        dataset="synthetic_learnable", model="tiny_conv_q", num_classes=4,
        batch_size=256, epochs=8, eval_every=8, lr=0.05, synthetic_n=2048,
        log_every=100, sync_bn=True,
    )
    out = Trainer(cfg).fit()
    assert out["val_top1"] > 55.0, out  # chance = 25%


@pytest.mark.slow  # two 20-epoch fits, ~8 min on the CPU mesh; the pinned
# seed-0 operating point (docstring) also assumes the original JAX stack's
# RNG/numerics stream — re-pin when re-enabling on a new stack
def test_multifactor_convergence_and_schedule_matters(tmp_path):
    """VERDICT r2 #4: discriminating convergence evidence. The multifactor
    task (16 classes, two independent factors, 20% train-label noise,
    data/synthetic.py::synthetic_multifactor) is NOT memorizable in one
    epoch — the loss must *keep declining* across 20 epochs — and the
    reference's MultiStepLR decay (distributed.py:64 semantics) must
    *visibly matter*: constant LR at the same base rate lands measurably
    below the scheduled run on val top-1.
    Measured operating point (8-dev CPU mesh, seed 0, re-measured r5
    after the loader's per-batch RNG keying for exact mid-epoch resume
    changed the augmentation stream): scheduled 98.9% vs constant 97.2%
    val top-1 — the r4 stream's 5.3-point gap was partly realization
    luck; the schedule's direction is stable, its margin is not (r5
    cross-seed spot-check: ~0.5 points at seed 2), so the test PINS
    seed 0 (deterministic end to end) and floors the assert at 1.0
    point with both arms >90%.  Both arms reach the calibrated
    label-noise CE floor (~1.1 for 20% noise over 16 classes), which
    pins the train-loss asserts."""
    import json

    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer, register_model
    from tests.helpers import tiny_resnet

    register_model("tiny_mf", lambda num_classes=16: tiny_resnet(num_classes))

    def fit(milestones, tag):
        cfg = TrainConfig(
            dataset="synthetic_multifactor", model="tiny_mf", num_classes=16,
            batch_size=256, epochs=20, eval_every=20, lr=0.8,
            lr_milestones=milestones, lr_gamma=0.1, synthetic_n=4096,
            log_every=1000, sync_bn=True, seed=0,
            log_file=str(tmp_path / f"{tag}.jsonl"),
        )
        out = Trainer(cfg).fit()
        losses = [
            json.loads(line)["loss"]
            for line in open(tmp_path / f"{tag}.jsonl")
            if json.loads(line).get("kind") == "train_epoch"
        ]
        return out, losses

    sched, losses = fit((10, 15), "sched")
    # a declining CURVE, not epoch-0 memorization: starts near ln(16) and
    # is still there after a FULL epoch (the quadrant task this replaces
    # was memorized by mid-epoch-0), then keeps dropping for many epochs
    assert losses[0] > 2.3, losses[0]
    assert losses[1] > 2.0, losses[1]
    assert losses[-1] < 0.5 * losses[1], (losses[1], losses[-1])
    # final-accuracy window: way above 6.25% chance, and the train loss
    # sits at the label-noise floor rather than 0.0 (no flatline-at-100)
    assert 90.0 <= sched["val_top1"] <= 100.0, sched
    assert losses[-1] > 0.7, losses[-1]  # 20% resampled labels keep CE > 0

    const, _ = fit((10**6,), "const")
    # the schedule is load-bearing: disabling the milestones costs
    # validation accuracy (measured 1.7 points at this operating point,
    # r4 stream measured 5.3 — see docstring)
    assert const["val_top1"] >= 90.0, const
    assert sched["val_top1"] - const["val_top1"] >= 1.0, (sched, const)
