"""Integration: accuracy (not just loss) climbs on a learnable task.

The reference's implicit integration test is run-to-convergence on
CIFAR-100 (SURVEY §4); with no dataset in this environment, a deterministic
learnable mapping (labels = quadrant of the brightest image region) stands
in: a model that generalizes must push accuracy well above chance.
"""

import jax
import numpy as np

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tpu_dist.train.step import make_train_step
from tests.helpers import TinyConvNet


def _learnable_batch(n, rng):
    """Images whose label is the quadrant (0-3) containing the bright blob."""
    x = rng.normal(scale=0.3, size=(n, 8, 8, 3)).astype(np.float32)
    labels = rng.integers(0, 4, n).astype(np.int32)
    for i, lab in enumerate(labels):
        r, c = divmod(int(lab), 2)
        x[i, r * 4 : r * 4 + 4, c * 4 : c * 4 + 4, :] += 2.0
    return x, labels


def test_accuracy_rises_above_chance():
    mesh = mesh_lib.data_parallel_mesh()
    model = TinyConvNet(num_classes=4, width=16)
    opt = SGD(momentum=0.9, weight_decay=1e-4)
    params, bn = model.init(jax.random.PRNGKey(0))
    state = jax.device_put(TrainState.create(params, bn, opt), mesh_lib.replicated(mesh))
    step = make_train_step(model.apply, opt, mesh)

    rng = np.random.default_rng(0)
    accs = []
    for i in range(80):
        x, y = _learnable_batch(64, rng)
        xs = mesh_lib.shard_batch(mesh, x)
        ys = mesh_lib.shard_batch(mesh, y)
        state, m = step(state, xs, ys, 0.05)
        accs.append(float(m["acc1"]))
    # fresh data every step → this is generalization, not memorization
    assert np.mean(accs[-10:]) > 60.0, np.mean(accs[-10:])  # chance = 25%


def test_trainer_converges_on_learnable_dataset():
    """Full Trainer (streaming pipeline + eval) reaches well-above-chance
    VALIDATION accuracy on the learnable synthetic task — the closest
    possible stand-in for the reference's run-to-convergence check."""
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer, register_model
    from tests.helpers import tiny_resnet

    register_model("tiny_conv_q", lambda num_classes=4: tiny_resnet(num_classes))
    cfg = TrainConfig(
        dataset="synthetic_learnable", model="tiny_conv_q", num_classes=4,
        batch_size=256, epochs=8, eval_every=8, lr=0.05, synthetic_n=2048,
        log_every=100, sync_bn=True,
    )
    out = Trainer(cfg).fit()
    assert out["val_top1"] > 55.0, out  # chance = 25%
