"""Worker for the multi-host × tensor-parallel test (VERDICT r1 #6).

Launched by tests/test_multihost.py as 2 processes × 4 CPU devices: one
8-device global mesh laid out ``[data=4, model=2]`` HOST-MAJOR, so every
tp=2 group is intra-host (the ICI side of the ICI/DCN split). The same
``run_tp_training`` is also called by the parent test in-process
(1 process × 8 devices) as the reference — replicated leaves, TP-sharded
leaves and the loss must come out identical across both layouts and across
both workers.

Usage: python tests/_mp_worker_tp.py <coordinator> <num_procs> <proc_id>
"""

import os
import sys

if __name__ == "__main__":
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    ).strip()

import jax  # noqa: E402

if __name__ == "__main__":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def _to_host(x) -> np.ndarray:
    """Full global value of a (possibly cross-process-sharded) array."""
    if getattr(x, "is_fully_addressable", True):
        return np.asarray(jax.device_get(x))
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def run_tp_training():
    """Train a tiny Megatron-TP ViT 3 steps on a [data, model=2] mesh built
    from ALL global devices; returns (loss, replicated-leaf fingerprint,
    TP-sharded-leaf fingerprint)."""
    import jax.numpy as jnp  # noqa: F401

    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.nn.vit import ViTDef
    from tpu_dist.train.optim import SGD
    from tpu_dist.train.state import TrainState
    from tpu_dist.train.step import make_train_step

    n = jax.device_count()
    mesh = mesh_lib.device_mesh([n // 2, 2], ["data", "model"])
    assert mesh_lib.model_axes_intra_host(mesh, ["model"]), (
        "host-major mesh must keep tp groups intra-host"
    )

    model = ViTDef(image_size=16, patch_size=4, dim=32, depth=2, heads=4, num_classes=5)
    specs = model.tp_param_specs("model")
    opt = SGD()
    params, s = model.init(jax.random.PRNGKey(0))
    st = TrainState.create(params, s, opt)
    state = TrainState(
        params=mesh_lib.place_host_tree(mesh, st.params, specs),
        bn_state=mesh_lib.place_host_tree(mesh, st.bn_state),
        opt_state=mesh_lib.place_host_tree(mesh, st.opt_state, specs),
        step=mesh_lib.place_host_tree(mesh, st.step),
    )
    step = make_train_step(
        model.apply, opt, mesh, sync_bn=False, donate=False,
        tp_axis="model", param_specs=specs,
    )

    rng = np.random.default_rng(0)
    all_x = rng.normal(size=(16, 16, 16, 3)).astype(np.float32)
    all_y = rng.integers(0, 5, 16).astype(np.int32)
    # each process feeds ITS slice of the global batch (host-major rows)
    per = all_x.shape[0] // jax.process_count()
    lo = jax.process_index() * per
    xs = mesh_lib.shard_batch(mesh, all_x[lo:lo + per])
    ys = mesh_lib.shard_batch(mesh, all_y[lo:lo + per])

    for _ in range(3):
        state, metrics = step(state, xs, ys, 0.05)
    loss = float(_to_host(metrics["loss"]))
    fp_rep = float(_to_host(state.params["patch"]["b"]).sum())
    fp_tp = float(_to_host(state.params["blocks"][0]["qkv"]["w"]).sum())
    return loss, fp_rep, fp_tp


def main(coordinator: str, num_procs: int, proc_id: int) -> None:
    from tpu_dist.comm import mesh as mesh_lib

    mesh_lib.initialize_distributed(coordinator, num_procs, proc_id)
    assert jax.process_count() == num_procs
    assert jax.local_device_count() == 4
    loss, fp_rep, fp_tp = run_tp_training()
    print(f"TPRESULT {proc_id} {loss:.6f} {fp_rep:.6f} {fp_tp:.6f}", flush=True)


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
