"""Checkpoint/resume (rank-0 save pattern made real, SURVEY §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist.ckpt import latest_checkpoint, restore, save
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState


def _state(seed=0):
    k = jax.random.PRNGKey(seed)
    params = {"w": jax.random.normal(k, (4, 3)), "nested": {"b": jnp.ones(2)}}
    bn = {"bn": {"mean": jnp.full(3, 0.5), "var": jnp.full(3, 2.0)}}
    return TrainState.create(params, bn, SGD())


def test_roundtrip(tmp_path):
    st = _state()
    st = st._replace(step=jnp.int32(42))
    save(str(tmp_path), st, epoch=3)
    found = latest_checkpoint(str(tmp_path))
    assert found is not None
    path, epoch = found
    assert epoch == 3
    rt = restore(path, _state(seed=9))  # template with different values
    for a, b in zip(jax.tree_util.tree_leaves(rt), jax.tree_util.tree_leaves(st)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_latest_picks_newest(tmp_path):
    save(str(tmp_path), _state(), epoch=1)
    save(str(tmp_path), _state(), epoch=10)
    save(str(tmp_path), _state(), epoch=2)
    assert latest_checkpoint(str(tmp_path))[1] == 10


def test_restore_shape_mismatch_is_loud(tmp_path):
    save(str(tmp_path), _state(), epoch=0)
    path, _ = latest_checkpoint(str(tmp_path))
    bad = _state()._replace(params={"w": jnp.zeros((5, 5)), "nested": {"b": jnp.ones(2)}})
    with pytest.raises(ValueError, match="shape mismatch"):
        restore(path, bad)


def test_missing_dir_is_none():
    assert latest_checkpoint("/tmp/definitely_missing_dir_xyz") is None


def test_save_best_roundtrip(tmp_path):
    from tpu_dist.ckpt import save_best

    st = _state()
    path = save_best(str(tmp_path), st, epoch=4, metric=71.2)
    assert path.endswith("ckpt_best.npz")
    rt = restore(path, _state(seed=5))
    np.testing.assert_allclose(
        np.asarray(rt.params["w"]), np.asarray(st.params["w"])
    )
    # best ckpt is not picked up by latest_checkpoint (epoch-numbered only)
    assert latest_checkpoint(str(tmp_path)) is None
