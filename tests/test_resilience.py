"""Resilience subsystem (docs/resilience.md): chaos-driven end-to-end tests.

Every fault here is injected through the deterministic ``--fault_plan``
machinery (tpu_dist/resilience/faults.py), so each scenario replays
bit-identically: SIGTERM mid-epoch resumes to the exact golden trajectory,
a corrupt newest checkpoint is quarantined with fallback to an older
epoch, transient write errors retry to a complete file, an injected NaN
drives the existing auto-recover path, and a dead loader producer raises
instead of hanging the epoch.
"""

import os
import signal
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist import ckpt as ckpt_lib
from tpu_dist.ckpt import (
    CheckpointCorruptError,
    latest_checkpoint,
    read_meta,
    verify_npz,
)
from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.config import TrainConfig
from tpu_dist.data import DataLoader, DistributedSampler, synthetic_cifar
from tpu_dist.data.loader import LoaderProducerDiedError
from tpu_dist.resilience import FaultPlan, FaultPlanError, faults, preemption
from tpu_dist.resilience.preemption import PREEMPTION_EXIT_CODE, PreemptedError
from tpu_dist.resilience.retry import backoff_delays, retry_call
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tpu_dist.train.trainer import (
    Trainer,
    TrainingDivergedError,
    register_model,
)
from tests.helpers import TinyMLP

register_model(
    "tiny_mlp_rs", lambda num_classes=10: TinyMLP(num_classes, width=16, in_dim=3072)
)


@pytest.fixture(autouse=True)
def _clean_fault_state():
    """Every test starts and ends with no plan installed, no pending
    preemption flag, and the module-default retry count."""
    faults.clear()
    preemption.clear()
    prev = ckpt_lib.set_io_retries(0)
    yield
    faults.clear()
    preemption.clear()
    ckpt_lib.set_io_retries(prev)


def _cfg(ckpt_dir, **kw):
    base = dict(
        dataset="synthetic", model="tiny_mlp_rs", num_classes=10,
        batch_size=64, epochs=2, steps_per_epoch=3, log_every=50,
        eval_every=0, save_every=1, synthetic_n=256, seed=0,
        ckpt_dir=ckpt_dir, num_workers=1,
    )
    base.update(kw)
    return TrainConfig(**base)


def _ckpt_state(seed=0):
    k = jax.random.PRNGKey(seed)
    params = {"w": jax.random.normal(k, (4, 3)), "nested": {"b": jnp.ones(2)}}
    return TrainState.create(params, {}, SGD())


def _params_equal(a, b) -> bool:
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb)
    )


@pytest.fixture(scope="module")
def golden(tmp_path_factory):
    """One uninterrupted 2-epoch run — the bit-identity reference for every
    chaos scenario in this module."""
    d = tmp_path_factory.mktemp("golden")
    t = Trainer(_cfg(str(d)))
    last = t.fit()
    return jax.device_get(t.state.params), last


# -- fault-plan parsing ------------------------------------------------------


def test_fault_plan_parse_roundtrip():
    p = FaultPlan.parse(
        "ckpt_write@call=2:times=3;sigterm@epoch=1:step=5;"
        "ckpt_corrupt@epoch=0:mode=bitflip:seed=7;loader_stall@batch=4"
    )
    assert [c.site for c in p.clauses] == [
        "ckpt_write", "sigterm", "ckpt_corrupt", "loader_stall",
    ]
    assert p.clauses[0].params == {"call": 2, "times": 3}
    assert p.clauses[1].params == {"epoch": 1, "step": 5}
    assert p.clauses[2].params["seed"] == 7


@pytest.mark.parametrize(
    "bad",
    [
        "nosuchsite@x=1",            # unknown site
        "sigterm@",                  # missing required step
        "ckpt_write@call=abc",       # non-integer coordinate
        "ckpt_corrupt@epoch=0:mode=banana",  # bad corruption mode
        "sigterm@step=1:frac=0.5",   # key not allowed for the site
        "sigterm",                   # no trigger at all
        "  ;  ",                     # no clauses
    ],
)
def test_fault_plan_rejects_malformed_specs(bad):
    with pytest.raises(FaultPlanError):
        FaultPlan.parse(bad)


def test_fault_plan_env_fallback_and_clear(monkeypatch):
    monkeypatch.setenv(faults.ENV_VAR, "nan_loss@step=3")
    plan = faults.configure(None)
    assert plan is not None and plan.clauses[0].site == "nan_loss"
    monkeypatch.delenv(faults.ENV_VAR)
    assert faults.configure(None) is None  # no cfg + no env => cleared
    assert faults.active() is None


def test_clauses_are_one_shot_by_default():
    faults.install("nan_loss@step=2")
    assert faults.on_step(0, 1) == frozenset()
    assert faults.NAN_LOSS in faults.on_step(0, 2)
    assert faults.on_step(1, 2) == frozenset()  # disarmed after firing


# -- retry ladder ------------------------------------------------------------


def test_backoff_schedule_is_deterministic():
    assert backoff_delays(4, 0.05, 2.0) == (0.05, 0.1, 0.2, 0.4)
    assert backoff_delays(3, 1.0, 1.5) == (1.0, 1.5, 1.5)  # capped
    assert backoff_delays(0) == ()


def test_retry_call_succeeds_after_transients_and_reraises_on_exhaustion():
    calls, sleeps = {"n": 0}, []

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise OSError(5, "eio")
        return "ok"

    assert retry_call(flaky, retries=3, sleep=sleeps.append) == "ok"
    assert sleeps == [0.05, 0.1]  # the deterministic schedule, injectable

    def always():
        raise OSError(28, "enospc")

    with pytest.raises(OSError, match="enospc"):
        retry_call(always, retries=1, sleep=sleeps.append)
    # non-retryable types propagate immediately (no sleeps consumed)
    n0 = len(sleeps)

    def typeerr():
        raise TypeError("not transient")

    with pytest.raises(TypeError):
        retry_call(typeerr, retries=3, sleep=sleeps.append)
    assert len(sleeps) == n0  # propagated without sleeping


def test_transient_ckpt_write_failures_retry_to_a_complete_file(
    tmp_path, monkeypatch
):
    import tpu_dist.resilience.retry as retry_mod

    sleeps = []
    monkeypatch.setattr(retry_mod.time, "sleep", sleeps.append)
    ckpt_lib.set_io_retries(2)
    faults.install("ckpt_write@call=1:times=2")  # first two ATTEMPTS fail
    st = _ckpt_state()
    path = ckpt_lib.save(str(tmp_path), st, epoch=0)
    assert path is not None and os.path.exists(path)
    verify_npz(path)  # complete and CRC-clean after the retries
    assert sleeps == [0.05, 0.1]
    # restored bytes match the state that was saved
    rt = ckpt_lib.restore(path, _ckpt_state(seed=9))
    assert _params_equal(rt.params, st.params)


def test_ckpt_write_retry_exhaustion_raises_and_leaves_no_checkpoint(tmp_path):
    ckpt_lib.set_io_retries(1)
    faults.install("ckpt_write@call=1:times=5")
    with pytest.raises(OSError):
        ckpt_lib.save(
            str(tmp_path), _ckpt_state(), epoch=0,
        )
    assert latest_checkpoint(str(tmp_path)) is None  # nothing partial


# -- checkpoint integrity ----------------------------------------------------


def test_crc_stamps_written_and_verified(tmp_path):
    path = ckpt_lib.save(str(tmp_path), _ckpt_state(), epoch=0)
    meta = verify_npz(path)
    assert set(meta["crc32"]) >= {"['params']['w']", "['step']"}
    assert read_meta(path)["epoch"] == 0


def test_crc_detects_silent_single_bit_corruption(tmp_path):
    """Rewrite one entry with a flipped bit but a VALID zip container —
    only the per-entry CRC stamp can catch this class of corruption."""
    path = ckpt_lib.save(str(tmp_path), _ckpt_state(), epoch=0)
    with np.load(path) as z:
        data = {k: z[k] for k in z.files}
    arr = data["['params']['w']"].copy()
    arr.view(np.uint8)[0] ^= 1
    data["['params']['w']"] = arr
    with open(path, "wb") as f:  # valid archive, stale __meta__ CRCs
        np.savez(f, **data)
    with pytest.raises(CheckpointCorruptError, match="CRC32 mismatch"):
        verify_npz(path)


def test_restore_verify_catches_corruption_in_its_single_read(tmp_path):
    """The trainer ladder fuses CRC verification into restore's one
    decompression pass — restore(verify=True) must catch what a separate
    verify_npz pass would."""
    path = ckpt_lib.save(str(tmp_path), _ckpt_state(), epoch=0)
    with np.load(path) as z:
        data = {k: z[k] for k in z.files}
    arr = data["['params']['w']"].copy()
    arr.view(np.uint8)[0] ^= 1
    data["['params']['w']"] = arr
    with open(path, "wb") as f:  # valid archive, stale __meta__ CRCs
        np.savez(f, **data)
    with pytest.raises(CheckpointCorruptError, match="CRC32 mismatch"):
        ckpt_lib.restore(path, _ckpt_state(seed=9), verify=True)
    # unverified restore still loads it (the --no_ckpt_verify contract)
    ckpt_lib.restore(path, _ckpt_state(seed=9), verify=False)


def test_fused_epoch_refuses_stepwise_fault_clauses(tmp_path):
    """Step/batch-grain clauses would silently never fire under
    --fused_epoch (no step grain, loader bypassed) — refuse loudly."""
    cfg = _cfg(
        str(tmp_path), fused_epoch=True, steps_per_epoch=None,
        fault_plan="sigterm@epoch=1:step=0",
    )
    with pytest.raises(ValueError, match="fused_epoch compiles away"):
        Trainer(cfg)
    # ckpt-grain clauses stay legal under fused (epoch-boundary saves)
    t = Trainer(cfg.replace(fault_plan="ckpt_corrupt@epoch=7"))
    assert faults.active() is not None


def test_truncated_and_bitflipped_files_fail_verification(tmp_path):
    p0 = ckpt_lib.save(str(tmp_path), _ckpt_state(), epoch=0)
    p1 = ckpt_lib.save(str(tmp_path), _ckpt_state(), epoch=1)
    faults.truncate_file(p0, frac=0.4)
    faults.bitflip_file(p1, seed=3)
    with pytest.raises(CheckpointCorruptError):
        verify_npz(p0)
    with pytest.raises(CheckpointCorruptError):
        verify_npz(p1)


def test_sharded_verify_detects_corruption_and_quarantine_hides_it(tmp_path):
    d = str(tmp_path)
    mpath = ckpt_lib.save_sharded(d, _ckpt_state(), 0)
    assert ckpt_lib.verify_sharded(mpath)["epoch"] == 0  # clean roundtrip
    shard = next(n for n in os.listdir(d) if ".shard" in n)
    faults.bitflip_file(os.path.join(d, shard), seed=1)
    with pytest.raises(CheckpointCorruptError):
        ckpt_lib.verify_sharded(mpath)
    # quarantining the MANIFEST uncommits the checkpoint: invisible now
    ckpt_lib.quarantine(mpath)
    assert ckpt_lib.latest_sharded_checkpoint(d) is None


def test_sharded_verify_catches_missing_stamped_entry(tmp_path):
    """A valid zip that silently LOST an entry must fail verification (the
    restore would otherwise die mid-assembly instead of falling back)."""
    d = str(tmp_path)
    mpath = ckpt_lib.save_sharded(d, _ckpt_state(), 0)
    shard = os.path.join(d, next(n for n in os.listdir(d) if ".shard" in n))
    with np.load(shard) as z:
        data = {k: z[k] for k in z.files}
    dropped = next(k for k in data if k not in ("__crc__",))
    del data[dropped]
    with open(shard, "wb") as f:  # valid archive, entry gone
        np.savez(f, **data)
    with pytest.raises(CheckpointCorruptError, match="missing from archive"):
        ckpt_lib.verify_sharded(mpath)
    # shallow mode (multi-process restores) catches it too — it is a
    # directory-level property, no decompression needed
    with pytest.raises(CheckpointCorruptError, match="missing from archive"):
        ckpt_lib.verify_sharded(mpath, deep=False)


def test_stale_tmp_files_ignored_and_swept(tmp_path):
    d = str(tmp_path)
    ckpt_lib.save(d, _ckpt_state(), epoch=0)
    stray = os.path.join(d, "ckpt_5.npz.tmp")  # crash-leaked torn write
    with open(stray, "wb") as f:
        f.write(b"partial")
    # never reported as a checkpoint...
    assert latest_checkpoint(d) == (os.path.join(d, "ckpt_0.npz"), 0)
    # ...and the keep_last prune sweeps it
    ckpt_lib.save(d, _ckpt_state(), epoch=1, keep_last=5)
    assert not os.path.exists(stray)


def test_restore_ladder_quarantines_corrupt_newest_and_falls_back(tmp_path):
    d = str(tmp_path)
    cfg = _cfg(d)
    Trainer(cfg).fit()  # writes clean ckpt_0 and ckpt_1
    p1 = os.path.join(d, "ckpt_1.npz")
    faults.truncate_file(p1, frac=0.4)  # torn newest checkpoint
    t2 = Trainer(cfg.replace(resume=True))
    # fell back to epoch 0 (a restored clean ckpt_1 would give start_epoch 2)
    assert t2.start_epoch == 1
    assert os.path.exists(p1 + ".corrupt")  # quarantined, kept for forensics
    assert latest_checkpoint(d)[1] == 0  # the corrupt file is invisible now


# -- preemption (SIGTERM) ----------------------------------------------------


def test_sigterm_handler_sets_flag_cooperatively():
    token = preemption.install()
    try:
        assert not preemption.requested()
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.time() + 5.0
        while not preemption.requested() and time.time() < deadline:
            time.sleep(0.01)
        assert preemption.requested()
    finally:
        preemption.clear()
        preemption.restore(token)


def test_sigterm_midepoch_emergency_saves_and_resume_is_bit_identical(
    tmp_path, golden
):
    gparams, glast = golden
    d = str(tmp_path)
    cfg = _cfg(d, fault_plan="sigterm@epoch=1:step=1")
    t = Trainer(cfg)
    with pytest.raises(PreemptedError):
        t.fit()
    # the in-flight step finished: exact snapshot of epoch 1 after 2 steps
    found = latest_checkpoint(d)
    assert found is not None and found[1] == 1
    assert read_meta(found[0])["mid_epoch_step"] == 2
    # resume (no fault plan) replays the identical remaining stream
    t2 = Trainer(cfg.replace(fault_plan=None, resume=True))
    assert t2.start_epoch == 1 and t2._resume_step == 2
    last = t2.fit()
    assert last["loss"] == glast["loss"]  # bit-identical, not just close
    assert _params_equal(jax.device_get(t2.state.params), gparams)


def test_sigterm_after_final_step_replays_the_epoch_record(tmp_path, golden):
    """The nastiest preemption point: SIGTERM lands after the epoch's LAST
    step, so the resumed epoch has zero steps left.  The snapshot stamps the
    final step's fetched metrics (``mid_epoch_metrics``) and the resume
    replays them, so the epoch record still matches the uninterrupted run
    bit-for-bit instead of being logged without a loss."""
    gparams, glast = golden
    d = str(tmp_path)
    cfg = _cfg(d, fault_plan="sigterm@epoch=1:step=2")
    with pytest.raises(PreemptedError):
        Trainer(cfg).fit()
    found = latest_checkpoint(d)
    assert found is not None and found[1] == 1
    meta = read_meta(found[0])
    assert meta["mid_epoch_step"] == 3  # every step of the epoch ran
    assert meta["mid_epoch_metrics"]["loss"] == glast["loss"]
    t2 = Trainer(cfg.replace(fault_plan=None, resume=True))
    assert t2.start_epoch == 1 and t2._resume_step == 3
    last = t2.fit()  # zero steps remain: the record is replayed, not empty
    assert last["loss"] == glast["loss"]
    assert _params_equal(jax.device_get(t2.state.params), gparams)


def test_cli_maps_preemption_to_distinct_exit_code(tmp_path):
    from tpu_dist.cli.train import main

    with pytest.raises(SystemExit) as ei:
        main([
            "--dataset", "synthetic", "--model", "tiny_mlp_rs",
            "--num_classes", "10", "--batch_size", "64", "--epochs", "2",
            "--steps_per_epoch", "3", "--eval_every", "0", "--save_every",
            "1", "--synthetic_n", "256", "--seed", "0", "--log_every", "50",
            "--ckpt_dir", str(tmp_path),
            "--fault_plan", "sigterm@epoch=0:step=1",
        ])
    assert ei.value.code == PREEMPTION_EXIT_CODE


def test_launcher_propagates_preemption_exit_code():
    import sys

    from tpu_dist.cli.launch import main as launch_main

    rc = launch_main([
        "--nproc", "2", "--",
        sys.executable, "-c",
        f"import sys; sys.exit({PREEMPTION_EXIT_CODE})",
    ])
    assert rc == PREEMPTION_EXIT_CODE


def test_launcher_crash_outranks_concurrent_preemption():
    """A child crashing for real while another is preempted must surface
    the CRASH code — '75, requeue me' would loop the orchestrator on a
    genuine bug forever."""
    import sys

    from tpu_dist.cli.launch import main as launch_main

    code = (
        "import signal, sys, time\n"
        "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
        "rank = int(sys.argv[sys.argv.index('--process_id') + 1])\n"
        "time.sleep(0.3 * rank)\n"
        f"sys.exit({PREEMPTION_EXIT_CODE} if rank == 0 else 1)\n"
    )
    rc = launch_main(["--nproc", "2", "--", sys.executable, "-c", code])
    assert rc == 1


def test_sigterm_during_fused_epoch_keeps_the_completed_epoch(tmp_path):
    """The fused path's cooperative point is the epoch boundary — and by
    then the epoch IS complete, so the emergency snapshot must file it
    under this epoch, not discard it as '0 steps done'."""
    d = str(tmp_path)
    cfg = _cfg(d, fused_epoch=True, steps_per_epoch=None)
    t = Trainer(cfg)
    orig = t._fused_runner

    def preempted_runner(state, *a, **kw):
        out = orig(state, *a, **kw)
        os.kill(os.getpid(), signal.SIGTERM)  # lands during the epoch
        return out

    t._fused_runner = preempted_runner
    with pytest.raises(PreemptedError):
        t.fit()
    found = latest_checkpoint(d)
    assert found is not None and found[1] == 0  # epoch 0's work survived
    assert "mid_epoch_step" not in read_meta(found[0])  # a CLEAN boundary
    assert Trainer(cfg.replace(resume=True)).start_epoch == 1


# -- NaN injection drives the existing auto-recover path ---------------------


def test_nan_fault_raises_divergence_without_auto_recover(tmp_path):
    cfg = _cfg(str(tmp_path), fault_plan="nan_loss@epoch=0:step=1")
    with pytest.raises(TrainingDivergedError, match="fault-injected"):
        Trainer(cfg).fit()


def test_nan_fault_fires_auto_recover_and_run_completes(tmp_path):
    d = str(tmp_path)
    cfg = _cfg(
        d, fault_plan="nan_loss@epoch=1:step=0", auto_recover=1,
        log_file=os.path.join(d, "hist.jsonl"),
    )
    t = Trainer(cfg)
    t.fit()  # epoch 0 saves; epoch 1 "diverges" once, recovers, completes
    assert t._lr_scale == cfg.recover_lr_factor  # backoff applied
    with open(os.path.join(d, "hist.jsonl")) as f:
        assert any('"auto_recover"' in line for line in f)
    assert latest_checkpoint(d)[1] == 1  # the rerun epoch finished and saved


# -- loader hang-proofing ----------------------------------------------------


def test_loader_producer_death_raises_instead_of_hanging():
    mesh = mesh_lib.data_parallel_mesh()
    imgs, lbls = synthetic_cifar(128, 10, seed=1)
    faults.install("loader_stall@batch=1")
    dl = DataLoader(
        imgs, lbls, 32, DistributedSampler(128, 1, 0), mesh, seed=0,
        watchdog_timeout=0.2,
    )
    got = 0
    t0 = time.time()
    with pytest.raises(LoaderProducerDiedError, match="producer thread died"):
        for _ in dl:
            got += 1
    assert got == 1  # batch 0 arrived; the producer died before batch 1
    assert time.time() - t0 < 30.0  # watchdog, not a hang


@pytest.mark.slow  # real sleeps: excluded from the timed tier-1 gate
def test_real_clock_backoff_actually_sleeps(tmp_path):
    """The injectable-clock tests above patch sleep; this exercises the
    REAL time.sleep path the production writer uses."""
    ckpt_lib.set_io_retries(2)
    faults.install("ckpt_write@call=1:times=2")
    t0 = time.time()
    path = ckpt_lib.save(str(tmp_path), _ckpt_state(), epoch=0)
    assert time.time() - t0 >= 0.15  # the 0.05 + 0.1 schedule really ran
    verify_npz(path)


@pytest.mark.slow  # waits out the default 5s watchdog tick
def test_loader_watchdog_fires_at_default_timeout():
    mesh = mesh_lib.data_parallel_mesh()
    imgs, lbls = synthetic_cifar(128, 10, seed=1)
    faults.install("loader_stall@batch=0")
    dl = DataLoader(imgs, lbls, 32, DistributedSampler(128, 1, 0), mesh, seed=0)
    t0 = time.time()
    with pytest.raises(LoaderProducerDiedError):
        for _ in dl:
            pass
    assert time.time() - t0 < 60.0  # bounded by the watchdog, not a hang


def test_loader_unfaulted_epoch_still_completes():
    mesh = mesh_lib.data_parallel_mesh()
    imgs, lbls = synthetic_cifar(128, 10, seed=1)
    dl = DataLoader(
        imgs, lbls, 32, DistributedSampler(128, 1, 0), mesh, seed=0,
        watchdog_timeout=0.2,
    )
    assert sum(1 for _ in dl) == len(dl)


# -- the traced step is unchanged when a plan is armed -----------------------


def test_fault_injection_points_are_traced_noops():
    from tpu_dist.analysis.jaxpr_audit import fault_noop_violations

    assert fault_noop_violations() == []


# -- the composite acceptance scenario ---------------------------------------


def test_composite_chaos_run_finishes_bit_identical_to_golden(
    tmp_path, golden
):
    """ISSUE 3 acceptance: transient ckpt-write EIO + SIGTERM mid-epoch +
    corrupt newest checkpoint → emergency save, restart, quarantine,
    fallback to the integrity-verified snapshot, finish bit-identical."""
    gparams, glast = golden
    d = str(tmp_path)
    plan = (
        "ckpt_write@call=1:times=1;"        # EIO on the first write attempt
        "sigterm@epoch=1:step=0;"           # preempted mid-epoch 1
        "ckpt_corrupt@epoch=1:mode=truncate"  # ...and the emergency snapshot tears
    )
    cfg = _cfg(d, fault_plan=plan, ckpt_io_retries=2)
    t = Trainer(cfg)
    with pytest.raises(PreemptedError):
        t.fit()
    # the transient EIO was retried: clean ckpt_0 exists and verifies
    verify_npz(os.path.join(d, "ckpt_0.npz"))
    # restart: the torn emergency ckpt_1 is quarantined, ckpt_0 restores
    t2 = Trainer(cfg.replace(fault_plan=None, resume=True))
    assert os.path.exists(os.path.join(d, "ckpt_1.npz.corrupt"))
    assert t2.start_epoch == 1 and t2._resume_step == 0
    last = t2.fit()  # re-runs epoch 1 from the clean boundary
    assert last["loss"] == glast["loss"]
    assert _params_equal(jax.device_get(t2.state.params), gparams)
