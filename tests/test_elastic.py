"""Elastic training (docs/resilience.md "Elastic training"): mesh-shape-
portable checkpoints, the consumed-prefix sampler re-partition, the
launcher's shrink-on-failure supervisor, and the TD111 traced-noop gate.

The world-size changes here are driven two ways: in-process by handing the
Trainer a smaller device mesh (8 emulated CPU devices -> a 4-device mesh —
full fidelity for the state-remap path, deterministic and fast), and
out-of-process through ``cli/launch.py``'s elastic supervisor with stub
children (the relaunch policy without jax in the loop). The full
multi-phase subprocess drill is ``python -m tpu_dist.elastic.drill``
(``make elastic-drill``), exercised by a slow-marked test here.
"""

import json
import os
import signal
import sys

import jax
import numpy as np
import pytest

from tpu_dist.ckpt import checkpoint as ckpt_lib
from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.comm.quantize import padded_len
from tpu_dist.config import TrainConfig
from tpu_dist.data import DistributedSampler
from tpu_dist.elastic import supervisor as sup
from tpu_dist.elastic.errors import ConfigMismatchError, ElasticShapeMismatch
from tpu_dist.elastic.remap import (
    Remapper,
    classify,
    elastic_stamp,
    make_remapper,
    params_len,
)
from tpu_dist.obs import counters as counters_lib
from tpu_dist.resilience import faults, preemption
from tpu_dist.resilience.preemption import PREEMPTION_EXIT_CODE, PreemptedError
from tpu_dist.train.state import TrainState
from tpu_dist.train.trainer import Trainer, register_model
from tests.helpers import TinyMLP

# TinyMLP(10, width=16, in_dim=3072) ravels to L = 49338 ≡ 2 (mod 8), so
# padded_len(L, 8) = 49344 != 49340 = padded_len(L, 4): the 8->4 shrink
# genuinely reshapes the ZeRO-1 flat vectors (and the EF residual row
# count always changes with the extent) — the remap path cannot be
# vacuously green.
register_model(
    "tiny_mlp_el", lambda num_classes=10: TinyMLP(num_classes, width=16, in_dim=3072)
)

L_TINY = 3072 * 16 + 16 + 16 * 10 + 10  # 49338


@pytest.fixture(autouse=True)
def _clean_fault_state():
    faults.clear()
    preemption.clear()
    prev = ckpt_lib.set_io_retries(0)
    yield
    faults.clear()
    preemption.clear()
    ckpt_lib.set_io_retries(prev)


def _cfg(ckpt_dir, **kw):
    base = dict(
        dataset="synthetic", model="tiny_mlp_el", num_classes=10,
        batch_size=64, epochs=2, steps_per_epoch=3, log_every=50,
        eval_every=0, save_every=1, synthetic_n=256, seed=0,
        ckpt_dir=ckpt_dir, num_workers=1,
    )
    base.update(kw)
    return TrainConfig(**base)


def _mesh(n):
    return mesh_lib.data_parallel_mesh(jax.devices()[:n])


def _flat_ckpt(path):
    with np.load(path) as z:
        return {k: np.array(z[k]) for k in z.files if k != "__meta__"}


# -- remap unit layer: the (n_old, n_new) property sweep ---------------------


@pytest.mark.parametrize(
    "n_old,n_new",
    [(8, 4), (4, 8), (8, 2), (2, 8), (8, 3), (3, 8), (6, 4), (2, 5),
     (1, 8), (8, 1)],
)
def test_remap_round_trip_reconstructs_global_arrays(n_old, n_new):
    """Grow and shrink, divisor and non-divisor: the ZeRO-1 flat vector's
    logical prefix is copied bit-exactly (zero tails both sides), r2 is
    bit-exact per coordinate, and r1's aggregate (the sum over replica
    rows — the only thing the next reduce sees) is preserved exactly."""
    L = 37
    rng = np.random.default_rng(n_old * 100 + n_new)
    p_old, p_new = padded_len(L, n_old), padded_len(L, n_new)

    mom = np.zeros(p_old, np.float32)
    mom[:L] = rng.normal(size=L).astype(np.float32)
    r1 = rng.normal(size=(n_old * p_old,)).astype(np.float32)
    r2 = np.zeros(p_old, np.float32)
    r2[:L] = rng.normal(size=L).astype(np.float32)

    rm = Remapper(L, n_new, n_old=n_old)
    out_mom = rm("['opt_state']", mom, np.zeros(p_new, np.float32))
    assert out_mom.dtype == np.float32
    np.testing.assert_array_equal(out_mom[:L], mom[:L])  # bit-exact
    assert not out_mom[L:].any()

    out_r1 = rm("['ef']['r1']", r1, np.zeros(n_new * p_new, np.float32))
    rows_old = r1.reshape(n_old, p_old)
    rows_new = out_r1.reshape(n_new, p_new)
    crop = min(L, p_old, p_new)
    np.testing.assert_array_equal(
        rows_new.sum(axis=0, dtype=np.float32)[:crop],
        rows_old[:, :crop].sum(axis=0, dtype=np.float32),
    )  # aggregate residual preserved to the bit
    assert not rows_new[1:].any()  # folded into replica 0

    out_r2 = rm("['ef']['r2']", r2, np.zeros(p_new, np.float32))
    np.testing.assert_array_equal(out_r2[:L], r2[:L])
    assert not out_r2[L:].any()
    assert len(rm.used) == 3


def test_remap_refuses_nonzero_tail_and_unknown_keys():
    L = 10
    rm = Remapper(L, 4, n_old=8)
    bad = np.ones(16, np.float32)  # nonzero past L: not the ZeRO-1 layout
    with pytest.raises(ConfigMismatchError, match="nonzero"):
        rm("['opt_state']['mu']", bad, np.zeros(12, np.float32))
    # a params-shaped leaf is never elastic — the hook declines (None)
    assert rm("['params']['w']", np.zeros((4, 3)), np.zeros((2, 3))) is None


def test_remap_r1_requires_the_dp_stamp():
    rm = Remapper(10, 4)  # n_old unknown (pre-stamp checkpoint)
    with pytest.raises(ConfigMismatchError, match="stamp"):
        rm("['ef']['r1']", np.zeros(96, np.float32), np.zeros(48, np.float32))


def test_classify_and_stamp():
    assert classify("['ef']['r1']", (96,), (48,), 10) == "ef_r1"
    assert classify("['ef']['r2']", (12,), (10,), 10) == "ef_r2"
    assert classify("['opt_state']['mu']", (16,), (12,), 10) == "zero1_flat"
    assert classify("['opt_state']['w1']", (4, 3), (2, 3), 10) is None
    assert classify("['params']['w']", (16,), (12,), 10) is None
    st = elastic_stamp(8, 2, 49338)
    assert st == {"dp": 8, "procs": 2, "params_len": 49338}


def test_make_remapper_rejects_a_different_model():
    state = TrainState(
        params={"w": np.zeros(10, np.float32)}, bn_state={}, opt_state=(),
        step=np.asarray(0, np.int32),
    )
    with pytest.raises(ConfigMismatchError, match="different model"):
        make_remapper(state, {"elastic": {"dp": 8, "params_len": 99}}, 4)
    rm = make_remapper(state, {"elastic": {"dp": 8, "params_len": 10}}, 4)
    assert rm.n_old == 8 and rm.L == params_len(state.params) == 10


def test_ckpt_raises_typed_errors_without_a_remapper(tmp_path):
    """The restore-ladder split: a dp-extent shape change is the BENIGN
    typed error (ElasticShapeMismatch — retry with a remapper); a param
    shape change is ConfigMismatchError. Both stay ValueError for old
    callers."""
    L = 37
    params = {"w": np.arange(L, dtype=np.float32)}
    st8 = TrainState(params, {}, np.zeros(padded_len(L, 8), np.float32),
                     np.asarray(0, np.int32))
    path = ckpt_lib.save(str(tmp_path), st8, epoch=0)
    tmpl4 = TrainState(params, {}, np.zeros(padded_len(L, 3), np.float32),
                       np.asarray(0, np.int32))
    with pytest.raises(ElasticShapeMismatch) as ei:
        ckpt_lib.restore(path, tmpl4)
    assert isinstance(ei.value, ValueError)
    assert ei.value.key == "['opt_state']"
    bad = TrainState({"w": np.zeros(L + 1, np.float32)}, {},
                     np.zeros(padded_len(L, 8), np.float32),
                     np.asarray(0, np.int32))
    with pytest.raises(ConfigMismatchError, match="shape mismatch"):
        ckpt_lib.restore(path, bad)


def test_sharded_restore_remaps_across_extents(tmp_path):
    """Sharded format: a ZeRO-1 flat vector saved as 8 device slices
    reassembles (allgather-then-reslice) and remaps onto a 4-device
    template bit-exactly; world-size-independent leaves reslice as
    before."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    # w (8,3) + b (2,) ravel to L = 26 ≡ 2 (mod 8): padded_len(26, 8) = 32
    # vs padded_len(26, 4) = 28 — the flat vector genuinely reshapes
    L = 26
    mesh8, mesh4 = _mesh(8), _mesh(4)
    w = np.arange(24, dtype=np.float32).reshape(8, 3)
    b = np.asarray([7.0, 9.0], np.float32)
    mom = np.zeros(padded_len(L, 8), np.float32)
    mom[:L] = np.arange(L, dtype=np.float32) * 1e-3
    st8 = TrainState(
        params={
            "b": jax.device_put(b, NamedSharding(mesh8, P())),
            "w": jax.device_put(w, NamedSharding(mesh8, P("data"))),
        },
        bn_state={},
        opt_state=jax.device_put(mom, NamedSharding(mesh8, P("data"))),
        step=jax.device_put(np.asarray(5, np.int32), NamedSharding(mesh8, P())),
    )
    mpath = ckpt_lib.save_sharded(
        str(tmp_path), st8, 0, extra_meta={"elastic": elastic_stamp(8, 1, L)}
    )
    tmpl4 = TrainState(
        params={
            "b": jax.device_put(np.zeros_like(b), NamedSharding(mesh4, P())),
            "w": jax.device_put(
                np.zeros_like(w), NamedSharding(mesh4, P("data"))
            ),
        },
        bn_state={},
        opt_state=jax.device_put(
            np.zeros(padded_len(L, 4), np.float32), NamedSharding(mesh4, P("data"))
        ),
        step=jax.device_put(np.asarray(0, np.int32), NamedSharding(mesh4, P())),
    )
    with pytest.raises(ElasticShapeMismatch):
        ckpt_lib.restore_sharded(mpath, tmpl4)
    rm = make_remapper(tmpl4, ckpt_lib.read_sharded_meta(mpath), 4)
    out = ckpt_lib.restore_sharded(mpath, tmpl4, remap=rm)
    np.testing.assert_array_equal(np.asarray(out.params["w"]), w)
    np.testing.assert_array_equal(np.asarray(out.params["b"]), b)
    got = np.asarray(out.opt_state)
    assert got.shape == (padded_len(L, 4),)
    np.testing.assert_array_equal(got[:L], mom[:L])
    assert not got[L:].any()
    assert rm.used == [("['opt_state']", "zero1_flat")]
    assert int(np.asarray(out.step)) == 5


def test_missing_ef_cold_start_survives_a_world_change(tmp_path):
    """A pre-EF checkpoint restored at a NEW extent with int8_ef on:
    residuals cold-start at zeros shaped for the new world."""
    L = 37
    params = {"w": np.arange(L, dtype=np.float32)}
    st8 = TrainState(params, {}, np.zeros(padded_len(L, 8), np.float32),
                     np.asarray(0, np.int32))  # no ef saved
    path = ckpt_lib.save(
        str(tmp_path), st8, epoch=0,
        extra_meta={"elastic": elastic_stamp(8, 1, L)},
    )
    p4 = padded_len(L, 4)
    tmpl = TrainState(
        params, {}, np.zeros(p4, np.float32), np.asarray(0, np.int32),
        ef={"r1": np.zeros(4 * p4, np.float32)},
    )
    out = ckpt_lib.restore(
        path, tmpl, remap=make_remapper(tmpl, ckpt_lib.read_meta(path), 4)
    )
    assert out.ef["r1"].shape == (4 * p4,) and not out.ef["r1"].any()
    np.testing.assert_array_equal(np.asarray(out.opt_state)[:L], np.zeros(L))


# -- sampler: consumed-prefix re-partitioning --------------------------------


def test_sampler_offset_repartitions_without_drop_or_dup():
    """4 shards consume k global batches; 2 NEW shards with the offset
    pick up exactly the not-yet-seen examples — union equals the full
    epoch, no example dropped or double-seen."""
    N, n_old, n_new, gbatch, k = 120, 4, 2, 20, 2
    old = [DistributedSampler(N, n_old, j, seed=7) for j in range(n_old)]
    for s in old:
        s.set_epoch(3)
    per_old = gbatch // n_old
    consumed = np.concatenate(
        [s.indices()[: k * per_old] for s in old]
    )
    order = np.random.default_rng(7 + 3).permutation(N)
    # lockstep shards => the union of per-shard prefixes IS the global prefix
    assert sorted(consumed) == sorted(order[: k * gbatch])

    new = [DistributedSampler(N, n_new, j, seed=7) for j in range(n_new)]
    remaining = []
    for s in new:
        s.set_epoch(3)
        s.set_offset(k * gbatch)
        remaining.append(s.indices())
    rest = np.concatenate(remaining)
    assert sorted(np.concatenate([consumed, rest])) == sorted(range(N))
    # next epoch: set_epoch clears the offset — full partition again
    for s in new:
        s.set_epoch(4)
        assert s.offset == 0 and len(s) == -(-N // n_new)


def test_sampler_offset_equals_iter_from_for_same_world():
    """Same shard count: the offset path is exactly the per-shard stream
    suffix iter_from consumes — the strict generalization claim."""
    N, n, gbatch, k = 128, 4, 16, 3
    for j in range(n):
        a = DistributedSampler(N, n, j, seed=5)
        a.set_epoch(1)
        suffix = a.indices()[k * (gbatch // n):]
        b = DistributedSampler(N, n, j, seed=5)
        b.set_epoch(1)
        b.set_offset(k * gbatch)
        np.testing.assert_array_equal(b.indices(), suffix)


def test_sampler_offset_validation():
    s = DistributedSampler(10, 2, 0)
    with pytest.raises(ValueError):
        s.set_offset(-1)
    with pytest.raises(ValueError):
        s.set_offset(11)


# -- trainer e2e: in-process world shrink ------------------------------------


def test_trainer_shrink_resume_zero1_ef_is_bit_exact(tmp_path):
    """The tentpole e2e at the state layer: a ZeRO-1 + int8_ef run saved
    at 8 devices resumes onto a 4-device mesh — params/momentum logical
    content bit-identical, EF aggregate preserved, resharded counted —
    and keeps training at the new extent."""
    d = str(tmp_path)
    log = os.path.join(d, "run.jsonl")
    cfg = _cfg(d, shard_weight_update=True, grad_compression="int8_ef",
               log_file=log)
    t = Trainer(cfg)
    t.fit()
    ck = ckpt_lib.latest_checkpoint(d)
    assert ck is not None and ck[1] == 1
    saved = _flat_ckpt(ck[0])
    meta = ckpt_lib.read_meta(ck[0])
    assert meta["elastic"] == {"dp": 8, "procs": 1, "params_len": L_TINY}
    old_r1 = saved["['ef']['r1']"].reshape(8, padded_len(L_TINY, 8))

    t2 = Trainer(cfg.replace(resume=True), mesh=_mesh(4))
    assert t2.start_epoch == 2
    assert counters_lib.get("resume.resharded") == 1
    # params: world-size-independent, bit-identical
    for (path_a, a) in jax.tree_util.tree_flatten_with_path(t2.state.params)[0]:
        key = jax.tree_util.keystr(path_a)
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), saved[f"['params']{key}"]
        )
    # ZeRO-1 momentum: logical prefix bit-identical, new tail zero
    mom = np.asarray(jax.device_get(t2.state.opt_state))
    assert mom.shape == (padded_len(L_TINY, 4),)
    np.testing.assert_array_equal(mom[:L_TINY], saved["['opt_state']"][:L_TINY])
    assert not mom[L_TINY:].any()
    # EF r1: aggregate residual preserved exactly at the new extent
    r1 = np.asarray(jax.device_get(t2.state.ef["r1"])).reshape(
        4, padded_len(L_TINY, 4)
    )
    np.testing.assert_array_equal(
        r1.sum(axis=0, dtype=np.float32)[:L_TINY],
        old_r1[:, :L_TINY].sum(axis=0, dtype=np.float32),
    )
    # ...and the shrunk trainer actually trains an epoch at dp=4
    last = t2.fit(3)
    assert np.isfinite(last["loss"]) and last["steps"] == 3
    # observability: the resume record marks the segment boundary
    recs = [json.loads(l) for l in open(log)]
    resumes = [r for r in recs if r.get("kind") == "resume"]
    assert resumes and resumes[-1]["resharded"] is True
    assert resumes[-1]["dp"] == 4 and resumes[-1]["prev_dp"] == 8
    assert counters_lib.snapshot()["elastic.world_size"] == 4


def test_sigterm_midepoch_then_shrink_matches_golden(tmp_path):
    """ISSUE 10 acceptance (in-process half): SIGTERM an 8-device ZeRO-1
    run mid-epoch; the emergency snapshot is exact; resume on 4 devices
    restores it bit-identically (logical content) and the continued loss
    trajectory matches the uninterrupted golden run within the
    golden-trajectory tolerance."""
    gdir = str(tmp_path / "golden")
    cfg_g = _cfg(gdir, shard_weight_update=True)
    tg = Trainer(cfg_g)
    glast = tg.fit()
    gparams = jax.device_get(tg.state.params)

    d = str(tmp_path / "elastic")
    cfg = _cfg(d, shard_weight_update=True,
               fault_plan="sigterm@epoch=1:step=1")
    t = Trainer(cfg)
    with pytest.raises(PreemptedError):
        t.fit()
    ck = ckpt_lib.latest_checkpoint(d)
    assert ck is not None and ck[1] == 1
    meta = ckpt_lib.read_meta(ck[0])
    assert meta["mid_epoch_step"] == 2
    assert meta["mid_epoch_examples"] == 2 * 64 and meta["mid_epoch_procs"] == 1
    saved = _flat_ckpt(ck[0])

    t2 = Trainer(
        cfg.replace(fault_plan=None, resume=True), mesh=_mesh(4)
    )
    assert t2.start_epoch == 1 and t2._resume_step == 2
    # allgathered restored state == the emergency save, bit-exact where
    # dtype allows (params verbatim; momentum's logical prefix)
    for (path_a, a) in jax.tree_util.tree_flatten_with_path(t2.state.params)[0]:
        key = jax.tree_util.keystr(path_a)
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), saved[f"['params']{key}"]
        )
    mom = np.asarray(jax.device_get(t2.state.opt_state))
    np.testing.assert_array_equal(mom[:L_TINY], saved["['opt_state']"][:L_TINY])
    last = t2.fit()
    # different reduce extent => float-order differences only: the
    # existing golden-trajectory tolerance
    np.testing.assert_allclose(last["loss"], glast["loss"], rtol=2e-3)
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(t2.state.params)),
        jax.tree_util.tree_leaves(gparams),
    ):
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=1e-5)


def test_offset_resume_runs_only_the_remaining_examples(tmp_path):
    """A mid-epoch snapshot stamped from a DIFFERENT process count drops
    the per-shard step replay and re-enters via the consumed-example
    offset: the resumed epoch runs exactly the remaining global batches."""
    d = str(tmp_path)
    cfg = _cfg(d, epochs=1)
    t = Trainer(cfg)
    ckpt_lib.save(
        d, t.state, epoch=0,
        extra_meta={
            "mid_epoch_step": 1, "mid_epoch_batch_size": 64,
            "mid_epoch_seed": 0, "mid_epoch_procs": 2,
            "mid_epoch_examples": 64,
            "elastic": elastic_stamp(8, 2, L_TINY),
        },
    )
    t2 = Trainer(cfg.replace(resume=True))
    assert t2.start_epoch == 0
    assert t2._resume_step == 0 and t2._resume_examples == 64
    last = t2.fit()
    # 256 examples, 64 consumed -> 3 of the 4 global batches remain
    assert last["steps"] == 3
    # a SECOND mid-epoch stamp from inside the offset epoch carries the
    # cumulative example position (offset + steps * global batch)
    meta = ckpt_lib.read_meta(ckpt_lib.latest_checkpoint(d)[0])
    assert "mid_epoch_step" not in meta  # clean end-of-epoch save


def test_mid_epoch_examples_stamp_clamps_to_dataset(tmp_path):
    """The final batch of a drop_last=False epoch is wrap-around padded
    (steps * global_batch can exceed N): the examples stamp clamps to the
    dataset size so a later elastic resume's set_offset can never be
    asked for a position outside the epoch."""
    cfg = _cfg(str(tmp_path), synthetic_n=200)  # 4 padded steps of 64
    t = Trainer(cfg)
    pos = t._mid_epoch_position(4)
    assert pos["mid_epoch_examples"] == 200  # min(4 * 64, N)
    assert pos["mid_epoch_step"] == 4
    # and a (legally) end-of-data offset resumes as an empty epoch
    s = DistributedSampler(200, 1, 0)
    s.set_offset(200)
    assert len(s) == 0 and s.indices().size == 0


# -- faults: rank_kill clause ------------------------------------------------


def test_rank_kill_clause_parses_and_matches(monkeypatch):
    plan = faults.FaultPlan.parse("rank_kill@step=2:rank=3")
    assert plan.clauses[0].site == "rank_kill"
    assert plan.clauses[0].params == {"step": 2, "rank": 3}
    with pytest.raises(faults.FaultPlanError, match="missing required"):
        faults.FaultPlan.parse("rank_kill@step=2")  # rank is required

    kills = []
    monkeypatch.setattr(faults.os, "kill", lambda pid, sig: kills.append(sig))
    faults.install("rank_kill@step=2:rank=3")
    assert faults.on_step(0, 2, rank=0) == frozenset()  # wrong rank
    assert faults.on_step(0, 2, rank=None) == frozenset()  # unknown rank
    assert faults.RANK_KILL in faults.on_step(0, 2, rank=3)
    assert kills == [signal.SIGKILL]
    assert faults.on_step(0, 2, rank=3) == frozenset()  # one-shot


def test_fused_epoch_refuses_rank_kill(tmp_path):
    cfg = _cfg(str(tmp_path), fused_epoch=True, steps_per_epoch=None,
               fault_plan="rank_kill@step=0:rank=0")
    with pytest.raises(ValueError, match="fused_epoch compiles away"):
        Trainer(cfg)


# -- supervisor policy -------------------------------------------------------


def test_next_world_size_policy():
    assert sup.feasible_sizes(8) == [8, 4, 2, 1]
    assert sup.next_world_size(8, survivors=7, min_procs=1) == 4
    assert sup.next_world_size(8, survivors=4, min_procs=1) == 4
    assert sup.next_world_size(8, survivors=3, min_procs=1) == 2
    assert sup.next_world_size(8, survivors=3, min_procs=4) is None
    assert sup.next_world_size(6, survivors=5, min_procs=1) == 3
    assert sup.next_world_size(8, survivors=0, min_procs=1) is None


def test_supervise_shrinks_retries_and_gives_up():
    calls = []
    sleeps = []

    def rounds(n, restart):
        calls.append((n, restart))
        if restart == 0:
            # rank 2 died hard, the rest preempted: 3 survivors of 4
            return sup.RoundResult(
                PREEMPTION_EXIT_CODE,
                {0: 75, 1: 75, 2: -signal.SIGKILL, 3: 75},
            )
        return sup.RoundResult(0, {i: 0 for i in range(n)})

    rc = sup.supervise(
        rounds, nproc=4, min_procs=1, max_restarts=3,
        backoff_base=0.5, sleep=sleeps.append,
    )
    assert rc == 0
    assert calls == [(4, 0), (2, 1)]  # largest divisor of 4 staffed by 3
    assert sleeps == [0.5]  # deterministic backoff, injectable

    # whole-pod preemption retries at the SAME size
    calls.clear()

    def rounds2(n, restart):
        calls.append((n, restart))
        if restart == 0:
            return sup.RoundResult(75, {i: 75 for i in range(n)})
        return sup.RoundResult(0, {i: 0 for i in range(n)})

    assert sup.supervise(rounds2, nproc=4, min_procs=2, max_restarts=2,
                         sleep=lambda _s: None) == 0
    assert calls == [(4, 0), (4, 1)]

    # budget exhaustion surfaces the real exit code
    assert sup.supervise(
        lambda n, r: sup.RoundResult(1, {0: 1}),
        nproc=1, min_procs=1, max_restarts=2, sleep=lambda _s: None,
    ) == 1

    # below the floor: give up with the round's code
    assert sup.supervise(
        lambda n, r: sup.RoundResult(75, {0: 75, 1: -signal.SIGKILL}),
        nproc=2, min_procs=2, max_restarts=5, sleep=lambda _s: None,
    ) == 75

    # the launcher's own SIGTERM stands elastic down
    assert sup.supervise(
        lambda n, r: sup.RoundResult(75, {i: 75 for i in range(n)}),
        nproc=2, min_procs=1, max_restarts=5, sleep=lambda _s: None,
        should_continue=lambda: False,
    ) == 75

    # ...including when the stop request lands DURING the backoff sleep:
    # no fresh world may spawn after it
    rounds_run = []
    stop = [False]

    def stopping_sleep(_s):
        stop[0] = True

    rc = sup.supervise(
        lambda n, r: (rounds_run.append((n, r)) or
                      sup.RoundResult(75, {i: 75 for i in range(n)})),
        nproc=2, min_procs=1, max_restarts=5, sleep=stopping_sleep,
        should_continue=lambda: not stop[0],
    )
    assert rc == 75 and rounds_run == [(2, 0)]  # round 1 never spawned


def test_launcher_elastic_relaunches_stub_children(tmp_path):
    """cli/launch.py e2e with stub children (no jax): round 0 loses rank
    2 to a SIGKILL while the others preempt; the supervisor relaunches
    at world size 2 with --resume injected and the restart env stamped."""
    from tpu_dist.cli.launch import main as launch_main

    marker = str(tmp_path / "world.txt")
    child = (
        "import os, signal, sys, time\n"
        "argv = sys.argv\n"
        "rank = int(argv[argv.index('--process_id') + 1])\n"
        "n = int(argv[argv.index('--num_processes') + 1])\n"
        "if '--resume' in argv:\n"
        f"    open({marker!r}, 'a').write(\n"
        "        f\"{n} {os.environ.get('TPU_DIST_ELASTIC_RESTARTS')}\\n\")\n"
        "    sys.exit(0)\n"
        "if rank == 2:\n"
        "    os.kill(os.getpid(), signal.SIGKILL)\n"
        "signal.signal(signal.SIGTERM, lambda *a: sys.exit(75))\n"
        "time.sleep(30)\n"
    )
    rc = launch_main([
        "--nproc", "4", "--elastic_min_procs", "1",
        "--elastic_max_restarts", "2", "--elastic_backoff", "0.01", "--",
        sys.executable, "-c", child,
    ])
    assert rc == 0
    lines = open(marker).read().split()
    assert lines == ["2", "1", "2", "1"]  # 2 ranks, restart #1


def test_launcher_non_elastic_path_unchanged():
    """Without --elastic_min_procs the launcher is the single-round tool
    it always was: a preemption propagates 75, no relaunch."""
    from tpu_dist.cli.launch import main as launch_main

    rc = launch_main([
        "--nproc", "2", "--",
        sys.executable, "-c", f"import sys; sys.exit({PREEMPTION_EXIT_CODE})",
    ])
    assert rc == PREEMPTION_EXIT_CODE


# -- observability satellites ------------------------------------------------


def _resume_rec(run_id, ts, rel_s, **kw):
    rec = {"kind": "resume", "run_id": run_id, "ts": ts, "rel_s": rel_s,
           "schema_version": 7}
    rec.update(kw)
    return rec


def test_summarize_renders_world_size_segments():
    from tpu_dist.obs.summarize import format_text, summarize

    records = [
        {"kind": "train_epoch", "epoch": 0, "run_id": "a", "ts": 1.0,
         "rel_s": 1.0, "schema_version": 7, "epoch_time": 1.0,
         "images_per_sec": 100.0, "loss": 2.0},
        _resume_rec("b", 10.0, 0.5, epoch=1, world=4, dp=4, prev_dp=8,
                    resharded=True, restarts=1, mid_epoch_step=2),
        {"kind": "train_epoch", "epoch": 1, "run_id": "b", "ts": 11.0,
         "rel_s": 1.5, "schema_version": 7, "epoch_time": 1.0,
         "images_per_sec": 50.0, "loss": 1.5},
    ]
    rep = summarize(records)
    assert rep["resumes"][0]["resharded"] is True
    # the first (fresh) segment logs no resume record: its extent is
    # seeded from the resumed checkpoint's prev_dp stamp
    assert rep["world_sizes"] == [8, 4]
    text = format_text(rep)
    assert "world size changed mid-run (elastic): dp 8 -> 4" in text
    assert "RESHARDED from dp=8" in text
    assert "elastic restart #1" in text
    assert not rep["skipped_kinds"]  # 'resume' is a KNOWN kind now


def test_run_ledger_charges_reshard_gap_to_recovery():
    from tpu_dist.obs import goodput

    def gp(run, ts, rel, **kw):
        rec = {"kind": "goodput", "run_id": run, "ts": ts, "rel_s": rel}
        rec.update(kw)
        return rec

    records = [
        gp("a", 10.0, 5.0, final=True, productive_s=4.0, elapsed_s=5.0,
           goodput_frac=0.8),
        # 6s relaunch gap; the new segment opens with a RESHARDED resume
        _resume_rec("b", 16.0, 0.0, epoch=1, dp=4, prev_dp=8, resharded=True),
        gp("b", 20.0, 4.0, final=True, productive_s=3.0, elapsed_s=4.0,
           goodput_frac=0.75),
    ]
    led = goodput.run_ledger(records)
    assert led["n_segments"] == 2
    assert led["restart_gap_s"] == pytest.approx(6.0)
    assert led["recovery_s"] == pytest.approx(6.0)  # reshard, not preempt
    assert led["preempt_s"] == pytest.approx(0.0)
    assert led["elapsed_s"] == pytest.approx(5.0 + 4.0 + 6.0)

    # a plain (non-resharded) restart still charges preempt_s
    records[1] = _resume_rec("b", 16.0, 0.0, epoch=1, dp=8, resharded=False)
    led = goodput.run_ledger(records)
    assert led["preempt_s"] == pytest.approx(6.0)
    assert led["recovery_s"] == pytest.approx(0.0)


def test_tail_renders_resume_segment_line():
    from tpu_dist.obs.tail import TailState

    st = TailState()
    st.add([
        _resume_rec("a", 1.0, 0.0, epoch=1, world=4, dp=4, prev_dp=8,
                    resharded=True, restarts=1),
    ])
    assert any("RESHARDED from dp=8" in e for e in st.events)
    assert any("restart #1" in e for e in st.events)


def test_pod_report_surfaces_world_changes():
    from tpu_dist.obs.aggregate import format_text, pod_report

    records = [
        _resume_rec("a", 1.0, 0.0, epoch=0, world=8, dp=8, resharded=False),
        _resume_rec("b", 9.0, 0.0, epoch=1, world=4, dp=4, prev_dp=8,
                    resharded=True),
    ]
    rep = pod_report([("host0", records)])
    assert rep["hosts"][0]["world_sizes"] == [8, 4]
    assert "elastic on host0" in format_text(rep)


# -- TD111: elastic resume is invisible to the compiled program --------------


def test_td111_registered_and_gate_passes():
    from tpu_dist.analysis.jaxpr_audit import elastic_resume_noop_violations
    from tpu_dist.analysis.rules import RULES

    assert "TD111" in RULES and RULES["TD111"].name == "elastic-resume-not-noop"
    assert elastic_resume_noop_violations() == []


@pytest.mark.slow  # two multi-process training rounds (compiles included)
def test_launcher_elastic_real_training_round_trip(tmp_path):
    """The launcher supervisor over REAL multi-process training: a 2-process
    run is preempted mid-epoch (deterministic sigterm fault at epoch 1 step
    0, with a collective mid-epoch snapshot landing first), the supervisor
    relaunches with --resume, and the relaunched world finishes cleanly —
    exit 0 end to end. Skips where this jaxlib's CPU backend lacks
    cross-process collectives (the test_multihost contract)."""
    import subprocess

    d = str(tmp_path)
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [
            sys.executable, "-m", "tpu_dist.cli.launch",
            "--nproc", "2", "--devices_per_proc", "1",
            "--elastic_min_procs", "1", "--elastic_max_restarts", "2",
            "--elastic_backoff", "0.01", "--",
            sys.executable, "-m", "tpu_dist.cli.train",
            "--dataset", "synthetic", "--model", "vit_tiny",
            "--num_classes", "10", "--synthetic_n", "64",
            "--batch_size", "16", "--epochs", "2", "--steps_per_epoch", "2",
            "--eval_every", "0", "--save_every", "1", "--log_every", "50",
            "--seed", "0", "--ckpt_dir", d,
            "--log_file", os.path.join(d, "run.jsonl"),
            "--mid_epoch_save_every", "1",
            "--fault_plan", "sigterm@epoch=1:step=0",
        ],
        env=env, capture_output=True, text=True, timeout=540,
    )
    out = proc.stdout + proc.stderr
    if "Multiprocess computations aren't implemented on the CPU backend" in out:
        pytest.skip("CPU backend lacks multiprocess collectives in this jaxlib")
    assert proc.returncode == 0, out
    assert "elastic: relaunching at world size 2" in out
    recs = [json.loads(l) for l in open(os.path.join(d, "run.jsonl"))]
    resumes = [r for r in recs if r.get("kind") == "resume"]
    # the relaunched rank 0 logged its segment boundary: mid-epoch re-entry
    assert resumes and resumes[-1]["mid_epoch_step"] == 1
    assert resumes[-1]["restarts"] == 1


# -- the full subprocess drill (make elastic-drill) --------------------------


@pytest.mark.slow  # three subprocess training phases (compiles included):
# excluded from the timed tier-1 gate; gates in the CI elastic step
def test_elastic_drill_cli(tmp_path):
    from tpu_dist.elastic.drill import main as drill_main

    assert drill_main([
        "--workdir", str(tmp_path), "--devices", "8", "--shrink_to", "4",
        "--model", "vit_tiny", "--epochs", "2", "--steps_per_epoch", "3",
        "--batch_size", "32", "--kill_step", "1",
    ]) == 0
