"""Exact mid-epoch resume: interrupt at step k, resume, train-to-identical
parameters vs an uninterrupted run.

The reference has no checkpointing at all (SURVEY §5); its interrupt story is
"re-run the epoch". This framework's emergency snapshot stamps the completed
step count (``mid_epoch_step``) into the checkpoint meta, and ``--resume``
re-enters the SAME epoch at that batch. Exactness rests on two properties
tested here:

* the sampler's epoch-seeded permutation + the loader's per-batch RNG keying
  make batch b bit-identical whether or not batches 0..b-1 were produced in
  this process (``DataLoader.iter_from``),
* the snapshot pairs (state, steps_done) atomically, so the restored state
  is exactly the one after ``steps_done`` optimizer steps.
"""

import numpy as np
import pytest

from tpu_dist.ckpt import latest_checkpoint, read_meta
from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.config import TrainConfig
from tpu_dist.data.loader import DataLoader
from tpu_dist.data.sampler import DistributedSampler
from tpu_dist.train.trainer import Trainer, register_model
from tests.helpers import tiny_resnet

register_model("tiny_resnet_mer", lambda num_classes=10: tiny_resnet(num_classes))


def _cfg(**kw):
    base = dict(
        dataset="synthetic", model="tiny_resnet_mer", num_classes=10,
        batch_size=64, epochs=2, log_every=100, eval_every=0,
        save_every=100, synthetic_n=640,  # 10 batches/epoch
    )
    base.update(kw)
    return TrainConfig(**base)


def _params_equal(a, b):
    import jax

    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_loader_iter_from_matches_full_tail():
    """iter_from(k) must reproduce the full iteration's batches k.. exactly,
    including the augmentation stream (per-batch RNG keying)."""
    rng = np.random.default_rng(0)
    images = rng.normal(size=(100, 8, 8, 3)).astype(np.float32)
    labels = rng.integers(0, 10, size=100).astype(np.int32)
    sampler = DistributedSampler(100, shuffle=True, seed=3)
    sampler.set_epoch(1)

    def noisy(imgs, g):
        return imgs + g.normal(size=imgs.shape).astype(np.float32)

    mesh = mesh_lib.device_mesh([1], ["data"], __import__("jax").devices()[:1])
    loader = DataLoader(images, labels, batch_size=20, sampler=sampler,
                        mesh=mesh, transform=noisy, batch_divisor=1)
    full = [(np.asarray(i), np.asarray(l)) for i, l in loader]
    tail = [(np.asarray(i), np.asarray(l)) for i, l in loader.iter_from(2)]
    assert len(full) == 5 and len(tail) == 3
    for (fi, fl), (ti, tl) in zip(full[2:], tail):
        np.testing.assert_array_equal(fi, ti)
        np.testing.assert_array_equal(fl, tl)


@pytest.mark.slow  # >10s e2e: excluded from the timed tier-1 gate; the
# quick slice keeps a fast representative of this subsystem in the gate
def test_interrupt_at_step_k_resume_matches_uninterrupted(tmp_path, monkeypatch):
    # A: the uninterrupted reference trajectory
    t_full = Trainer(_cfg())
    t_full.fit()
    want = t_full.state

    # B: same run, interrupted mid-epoch 1 before its 4th step dispatches
    cfg = _cfg(ckpt_dir=str(tmp_path))
    t = Trainer(cfg)
    calls = {"n": 0}
    orig_step = t.train_step

    def interrupting(state, images, labels, lr):
        calls["n"] += 1
        if calls["n"] == 14:  # epoch 0 = 10 calls; epoch 1 step idx 3
            raise KeyboardInterrupt
        return orig_step(state, images, labels, lr)

    monkeypatch.setattr(t, "train_step", interrupting)
    with pytest.raises(KeyboardInterrupt):
        t.fit()

    found = latest_checkpoint(str(tmp_path))
    assert found is not None
    path, epoch = found
    assert epoch == 1
    assert read_meta(path).get("mid_epoch_step") == 3

    # C: resume — must re-enter epoch 1 at step 3 and finish bit-identical
    t2 = Trainer(cfg.replace(resume=True))
    assert t2.start_epoch == 1
    assert t2._resume_step == 3
    t2.fit()
    assert int(t2.state.step) == int(want.step)
    _params_equal(t2.state.params, want.params)
    _params_equal(t2.state.bn_state, want.bn_state)
    _params_equal(t2.state.opt_state, want.opt_state)


def test_reinterrupt_before_first_resumed_step_keeps_exact_position(
    tmp_path, monkeypatch
):
    """Interrupt again immediately after a mid-epoch resume (before any new
    step): the emergency path must re-save the SAME position, not regress to
    a clean-epoch-boundary save of a state that already holds k extra steps."""
    cfg = _cfg(ckpt_dir=str(tmp_path))
    t = Trainer(cfg)
    calls = {"n": 0}
    orig_step = t.train_step

    def interrupting(state, images, labels, lr):
        calls["n"] += 1
        if calls["n"] == 14:
            raise KeyboardInterrupt
        return orig_step(state, images, labels, lr)

    monkeypatch.setattr(t, "train_step", interrupting)
    with pytest.raises(KeyboardInterrupt):
        t.fit()

    t2 = Trainer(cfg.replace(resume=True))

    def immediate(state, images, labels, lr):
        raise KeyboardInterrupt

    monkeypatch.setattr(t2, "train_step", immediate)
    with pytest.raises(KeyboardInterrupt):
        t2.fit()
    path, epoch = latest_checkpoint(str(tmp_path))
    assert epoch == 1
    assert read_meta(path).get("mid_epoch_step") == 3

    # same but the interrupt lands BEFORE train_epoch even starts (the fit
    # preamble window) — the atomic _progress position must still re-save
    # the exact restore point, not misfile the k-step state as a clean
    # epoch boundary (reviewer finding r5)
    t3 = Trainer(cfg.replace(resume=True))

    def preamble_interrupt(epoch, start_step=0, start_examples=0):
        raise KeyboardInterrupt

    monkeypatch.setattr(t3, "train_epoch", preamble_interrupt)
    with pytest.raises(KeyboardInterrupt):
        t3.fit()
    path, epoch = latest_checkpoint(str(tmp_path))
    assert epoch == 1
    assert read_meta(path).get("mid_epoch_step") == 3


@pytest.mark.slow  # >10s e2e: excluded from the timed tier-1 gate; the
# quick slice keeps a fast representative of this subsystem in the gate
def test_mid_epoch_resume_sharded_ckpt(tmp_path, monkeypatch):
    """The exact-resume meta rides the sharded-checkpoint format too: the
    emergency snapshot goes through ShardedCheckpointer with the same
    mid_epoch_step stamp, and --resume re-enters at the exact batch."""
    from tpu_dist.ckpt import latest_sharded_checkpoint, read_sharded_meta

    t_full = Trainer(_cfg())
    t_full.fit()
    want = t_full.state

    cfg = _cfg(ckpt_dir=str(tmp_path), sharded_ckpt=True)
    t = Trainer(cfg)
    calls = {"n": 0}
    orig_step = t.train_step

    def interrupting(state, images, labels, lr):
        calls["n"] += 1
        if calls["n"] == 14:
            raise KeyboardInterrupt
        return orig_step(state, images, labels, lr)

    monkeypatch.setattr(t, "train_step", interrupting)
    with pytest.raises(KeyboardInterrupt):
        t.fit()

    found = latest_sharded_checkpoint(str(tmp_path))
    assert found is not None
    path, epoch = found
    assert epoch == 1
    assert read_sharded_meta(path).get("mid_epoch_step") == 3

    t2 = Trainer(cfg.replace(resume=True))
    assert t2.start_epoch == 1 and t2._resume_step == 3
    t2.fit()
    _params_equal(t2.state.params, want.params)
    _params_equal(t2.state.opt_state, want.opt_state)


@pytest.mark.slow  # >10s e2e: excluded from the timed tier-1 gate; the
# quick slice keeps a fast representative of this subsystem in the gate
def test_periodic_mid_epoch_snapshots_survive_kill(tmp_path):
    """--mid_epoch_save_every: periodic exact snapshots DURING the epoch,
    so a hard kill (no interrupt handler, no emergency save) loses at most
    N steps — resume re-enters at the last snapshot's batch and finishes
    bit-identical to an uninterrupted run."""
    from tpu_dist.ckpt import latest_checkpoint, read_meta

    t_full = Trainer(_cfg(epochs=1))
    t_full.fit()
    want = t_full.state

    cfg = _cfg(epochs=1, ckpt_dir=str(tmp_path), mid_epoch_save_every=4)
    t = Trainer(cfg)
    # simulate kill -9 after the epoch's work: run the raw epoch (which
    # writes snapshots at steps 4 and 8 of 10) and abandon the trainer
    # without fit()'s clean end-of-epoch save or any emergency path
    t.train_epoch(0)
    path, epoch = latest_checkpoint(str(tmp_path))
    assert epoch == 0
    assert read_meta(path).get("mid_epoch_step") == 8

    t2 = Trainer(cfg.replace(resume=True))
    assert t2.start_epoch == 0 and t2._resume_step == 8
    t2.fit()
    assert int(t2.state.step) == int(want.step)
    _params_equal(t2.state.params, want.params)
    _params_equal(t2.state.opt_state, want.opt_state)


def test_mid_epoch_save_every_rejected_with_fused_epoch():
    with pytest.raises(ValueError, match="no step boundary"):
        Trainer(_cfg(fused_epoch=True, mid_epoch_save_every=2,
                     batch_size=256, synthetic_n=512))


def test_mid_epoch_resume_refuses_batch_size_drift(tmp_path, monkeypatch):
    """The step offset only pins the data position under the same batch
    size/seed — a mismatched resume must refuse, not silently skip data."""
    cfg = _cfg(ckpt_dir=str(tmp_path))
    t = Trainer(cfg)
    calls = {"n": 0}
    orig_step = t.train_step

    def interrupting(state, images, labels, lr):
        calls["n"] += 1
        if calls["n"] == 14:
            raise KeyboardInterrupt
        return orig_step(state, images, labels, lr)

    monkeypatch.setattr(t, "train_step", interrupting)
    with pytest.raises(KeyboardInterrupt):
        t.fit()
    with pytest.raises(ValueError, match="wrong data position"):
        Trainer(cfg.replace(resume=True, batch_size=32))
    with pytest.raises(ValueError, match="wrong data position"):
        Trainer(cfg.replace(resume=True, seed=7))
