"""Pod telemetry plane: the federated scrape hub + causal arbitration
tracing (docs/observability.md "Pod telemetry hub").

The hub's tolerance contract (a torn mid-rename exposition serves the
last good parse and is COUNTED; a stale-heartbeat run is marked dead
with its last-seen age, never silently dropped; mixed textfile + HTTP
sources aggregate side by side), the federated page grammar (per-run
label injection, pod rollups, ``# EOF`` termination), the scheduler's
ONE scrape fan-in (``read_signals``-via-hub byte-identical to the
direct sample, and the regression pin that ``fleet/scheduler.py``
never opens a metrics file itself again), the allocation-file decision
channel (``write_allocation`` tokens → ``read_allocation_meta`` →
``stamp_decision_env``), the ``preempt_for_serve_s`` goodput
attribution with the exact bucket partition, the ``obs hub`` CLI, and
the TD123 traced-noop gate with its vacuity guard.

The live-trainer e2e (a real fit scraped mid-run through the hub) is
slow-marked; it gates in the analysis.yml hub step, which runs this
module without the slow filter.
"""

import dataclasses
import inspect
import json
import os
import time

import pytest

from tpu_dist.obs import export as export_lib
from tpu_dist.obs import hub as hub_lib
from tpu_dist.obs.hub import HubServer, RunSource, TelemetryHub, parse_source


def _write(path, text):
    with open(path, "w") as f:
        f.write(text)


def _prom(tmp_path, name, alerts=None, **gauges):
    path = str(tmp_path / f"{name}.prom")
    _write(path, export_lib.render(gauges, {"alert_active": alerts or {}}))
    return path


def _hb(tmp_path, name, ts):
    path = str(tmp_path / f"{name}.hb")
    _write(path, json.dumps({"ts": ts, "phase": "train"}))
    return path


# -- sources & sampling ------------------------------------------------------


def test_run_source_validation():
    with pytest.raises(ValueError, match="run name"):
        RunSource("")
    with pytest.raises(ValueError, match="metrics_file or a port"):
        RunSource("r")
    with pytest.raises(ValueError, match="kind"):
        RunSource("r", metrics_file="m", kind="batch")
    with pytest.raises(ValueError, match="at least one"):
        TelemetryHub([])
    with pytest.raises(ValueError, match="duplicate"):
        TelemetryHub([
            RunSource("r", metrics_file="a"),
            RunSource("r", metrics_file="b"),
        ])


def test_sample_run_heartbeat_verdicts(tmp_path):
    prom = _prom(tmp_path, "t", **{"train.mfu": 0.4})
    now = 1000.0
    # fresh beat: alive, age reported
    s = hub_lib.sample_run(
        "t", metrics_file=prom, heartbeat_file=_hb(tmp_path, "f", now - 3),
        now=now,
    )
    assert s["alive"] is True and s["heartbeat_age_s"] == 3.0
    assert s["scraped"] and s["source"] == "textfile"
    assert s["values"][export_lib.metric_name("train.mfu")] == 0.4
    # stale beat: dead, WITH its last-seen age (never an unexplained drop)
    s = hub_lib.sample_run(
        "t", metrics_file=prom, heartbeat_file=_hb(tmp_path, "s", now - 120),
        now=now,
    )
    assert s["alive"] is False and s["heartbeat_age_s"] == 120.0
    # absent beat on a run contracted to beat: fail closed
    assert hub_lib.sample_run(
        "t", metrics_file=prom,
        heartbeat_file=str(tmp_path / "never.hb"), now=now,
    )["alive"] is False
    # no heartbeat configured at all: liveness unknowable, not dead
    assert hub_lib.sample_run("t", metrics_file=prom)["alive"] is None


# -- tolerance: torn / dead / absent, all counted ----------------------------


def test_torn_exposition_serves_last_good_and_counts(tmp_path):
    prom = _prom(tmp_path, "t", **{"train.mfu": 0.4})
    hub = TelemetryHub([RunSource("t", metrics_file=prom)])
    good = hub.collect()["runs"]["t"]["values"]
    assert good  # the good parse is now cached
    # a non-atomic publisher caught mid-write: no trailing "# EOF"
    _write(prom, "# TYPE tpu_dist_train_mfu gauge\ntpu_dist_train_mfu 0.9")
    snap = hub.collect()
    s = snap["runs"]["t"]
    assert s["torn"] is True
    assert s["values"] == good  # the suspect parse was NOT served
    assert snap["drops"]["torn"] == 1
    assert snap["drops_total"]["torn"] == 1 and hub.drops_total["torn"] == 1
    # the tear heals: fresh values replace the cache, no new drop
    _write(prom, export_lib.render({"train.mfu": 0.5}))
    snap = hub.collect()
    assert snap["runs"]["t"]["torn"] is False
    assert snap["drops"] == {"torn": 0, "dead": 0, "absent": 0}
    assert snap["drops_total"]["torn"] == 1  # cumulative survives


def test_torn_with_no_last_good_is_counted_not_absent(tmp_path):
    prom = str(tmp_path / "t.prom")
    _write(prom, "tpu_dist_train_mfu 0.9")  # torn from the very first scrape
    hub = TelemetryHub([RunSource("t", metrics_file=prom)])
    snap = hub.collect()
    s = snap["runs"]["t"]
    assert s["torn"] is True and s["values"] == {}
    assert s["absent"] is False  # torn, not silently "never published"
    assert snap["drops"] == {"torn": 1, "dead": 0, "absent": 0}


def test_dead_run_marked_with_age_never_dropped(tmp_path):
    now = 5000.0
    hub = TelemetryHub([
        RunSource("live", metrics_file=_prom(tmp_path, "a", **{"train.mfu": 0.4}),
                  heartbeat_file=_hb(tmp_path, "a", now - 1)),
        RunSource("gone", metrics_file=_prom(tmp_path, "b", **{"train.mfu": 0.1}),
                  heartbeat_file=_hb(tmp_path, "b", now - 300)),
    ])
    snap = hub.collect(now=now)
    dead = snap["runs"]["gone"]
    # the dead run STAYS in the snapshot — marked, aged, values intact
    assert dead["dead"] is True and dead["heartbeat_age_s"] == 300.0
    assert dead["values"]
    assert snap["rollup"]["runs_dead"] == 1
    assert snap["rollup"]["runs_aggregated"] == 2
    assert snap["drops"]["dead"] == 1
    page = hub.federated(snap)
    assert 'tpu_dist_hub_run_up{run="gone"} 0' in page
    assert 'tpu_dist_hub_run_up{run="live"} 1' in page
    assert 'tpu_dist_hub_run_heartbeat_age_s{run="gone"} 300' in page


def test_absent_exposition_counted(tmp_path):
    hub = TelemetryHub([
        RunSource("ghost", metrics_file=str(tmp_path / "nothing.prom")),
    ])
    snap = hub.collect()
    assert snap["runs"]["ghost"]["absent"] is True
    assert snap["drops"]["absent"] == 1
    assert snap["rollup"]["runs_aggregated"] == 0


def test_mixed_textfile_and_http_sources(tmp_path):
    text_prom = _prom(tmp_path, "t", **{"train.mfu": 0.4})
    with HubServer(0) as server:
        server.publish(export_lib.render({"serve.queue_depth": 7.0}))
        hub = TelemetryHub([
            RunSource("filerun", metrics_file=text_prom),
            RunSource("httprun", port=server.port, kind="serve"),
            # textfile PREFERRED, http the fallback when the file is gone
            RunSource("fallback", metrics_file=str(tmp_path / "gone.prom"),
                      port=server.port, kind="serve"),
        ])
        snap = hub.collect()
    assert snap["runs"]["filerun"]["source"] == "textfile"
    assert snap["runs"]["httprun"]["source"] == "http"
    assert snap["runs"]["fallback"]["source"] == "http"
    q = export_lib.metric_name("serve.queue_depth")
    assert snap["runs"]["httprun"]["values"][q] == 7.0
    assert snap["runs"]["fallback"]["values"][q] == 7.0
    assert snap["rollup"]["runs_aggregated"] == 3


# -- federation: labels, rollups, grammar ------------------------------------


def test_label_injection_bare_and_already_labeled():
    assert TelemetryHub._labeled("tpu_dist_train_mfu", "r") == (
        'tpu_dist_train_mfu{run="r"}'
    )
    assert TelemetryHub._labeled(
        'tpu_dist_alert_active{rule="slo_p99_high"}', "sv"
    ) == 'tpu_dist_alert_active{rule="slo_p99_high",run="sv"}'
    # a hostile run name cannot break the label grammar
    assert TelemetryHub._labeled("m", 'a"b') == 'm{run="a\\"b"}'


def test_federated_page_rollups_and_roundtrip(tmp_path):
    now = 2000.0
    fleet_prom = str(tmp_path / "fleet.prom")
    _write(fleet_prom, export_lib.render({
        "fleet.total_chips": 11, "fleet.free_chips": 1,
        "fleet.pending_chips": 0, "fleet.decisions": 4,
        "fleet.preemptions": 2, "fleet.last_decision_id": 3,
    }))
    hub = TelemetryHub(
        [
            RunSource("tr", metrics_file=_prom(
                tmp_path, "tr",
                **{"train.data_stall_frac": 0.3, "goodput.goodput_frac": 0.8},
            ), heartbeat_file=_hb(tmp_path, "tr", now - 1)),
            RunSource("sv", metrics_file=_prom(
                tmp_path, "sv", alerts={"slo_p99_high": 1.0},
                **{"goodput.goodput_frac": 0.6, "serve.queue_depth": 9.0},
            ), kind="serve"),
        ],
        fleet_exposition=fleet_prom,
    )
    snap = hub.collect(now=now)
    roll = snap["rollup"]
    assert roll["total_chips"] == 11.0 and roll["free_chips"] == 1.0
    assert roll["last_decision_id"] == 3.0
    assert roll["goodput_by_kind"] == {"train": 0.8, "serve": 0.6}
    assert roll["worst_stall_frac"] == 0.3 and roll["worst_stall_run"] == "tr"
    assert roll["breach_count"] == 1  # the sv run's firing slo_* alert
    page = hub.federated(snap)
    assert page.endswith("# EOF\n")
    parsed = export_lib.parse(page)
    assert parsed["tpu_dist_pod_runs_aggregated"] == 2.0
    assert parsed["tpu_dist_pod_total_chips"] == 11.0
    assert parsed["tpu_dist_pod_last_decision_id"] == 3.0
    assert parsed["tpu_dist_pod_breach_count"] == 1.0
    assert parsed['tpu_dist_pod_goodput_frac{kind="serve"}'] == 0.6
    assert parsed['tpu_dist_hub_drops_total{reason="torn"}'] == 0.0
    # every run sample round-trips with its run label injected
    assert parsed['tpu_dist_serve_queue_depth{run="sv"}'] == 9.0
    assert parsed[
        'tpu_dist_alert_active{rule="slo_p99_high",run="sv"}'
    ] == 1.0
    # atomic publish: the written page equals the rendered one
    out = str(tmp_path / "federated.prom")
    hub.write(out, snap)
    with open(out) as f:
        assert f.read() == page


# -- the scheduler's one fan-in ----------------------------------------------


def test_signals_via_hub_byte_identical_to_direct_sample(tmp_path):
    """The 2-run fan-in contract: feeding one hub snapshot through
    ``signals_from_hub`` yields byte-identical RunSignals to calling
    ``read_signals`` per run — one scrape pass, same verdicts."""
    from tpu_dist.fleet.scheduler import read_signals, signals_from_hub

    now = 3000.0
    tr_prom = _prom(
        tmp_path, "tr",
        **{"train.data_stall_frac": 0.45, "goodput.goodput_frac": 0.5,
           "train.mfu": 0.31, "train.epoch": 2},
    )
    tr_hb = _hb(tmp_path, "tr", now - 2)
    sv_prom = _prom(
        tmp_path, "sv", alerts={"slo_availability_low": 1.0},
        **{"serve.queue_depth": 12.0, "serve.availability": 0.8,
           "serve.latency_p99_ms": 950.0},
    )
    sv_hb = _hb(tmp_path, "sv", now - 90)  # dead — verdict must carry over
    hub = TelemetryHub([
        RunSource("tr", metrics_file=tr_prom, heartbeat_file=tr_hb),
        RunSource("sv", metrics_file=sv_prom, heartbeat_file=sv_hb,
                  kind="serve"),
    ])
    via_hub = signals_from_hub(hub.collect(now=now))
    direct = {
        "tr": read_signals("tr", tr_prom, heartbeat_file=tr_hb, now=now),
        "sv": read_signals("sv", sv_prom, heartbeat_file=sv_hb, now=now),
    }
    assert set(via_hub) == {"tr", "sv"}
    for run in direct:
        assert via_hub[run] == direct[run]
        assert repr(via_hub[run]) == repr(direct[run])
    assert via_hub["sv"].alive is False
    assert via_hub["tr"].data_stall_frac == 0.45
    assert via_hub["sv"].active_alerts == ("slo_availability_low",)


def test_scheduler_has_no_direct_scrape_path():
    """Regression pin: the hub is the scheduler's ONLY signal source.
    ``fleet/scheduler.py`` must never again open a metrics textfile,
    scrape an endpoint, or read a heartbeat itself — ``read_signals``
    delegates to ``obs/hub.py::sample_run`` and pod-scale callers feed
    ``signals_from_hub`` one collected snapshot."""
    from tpu_dist.fleet import scheduler

    src = inspect.getsource(scheduler)
    assert "export_lib.scrape" not in src
    assert "heartbeat_lib" not in src
    assert "from tpu_dist.obs import heartbeat" not in src
    assert "hub_lib.sample_run" in inspect.getsource(scheduler.read_signals)
    assert "signals_from_sample" in inspect.getsource(
        scheduler.signals_from_hub
    )


# -- the allocation-file decision channel ------------------------------------


def test_allocation_decision_tokens_roundtrip(tmp_path):
    from tpu_dist.fleet import capacity as capacity_lib

    path = str(tmp_path / "alloc")
    capacity_lib.write_allocation(
        path, 4, decision_id=7, cause="serve_breach"
    )
    # the integer channel stays readable by every pre-tracing reader
    assert capacity_lib.read_allocation(path) == 4
    meta = capacity_lib.read_allocation_meta(path)
    assert meta == {"decision_id": 7, "cause": "serve_breach"}
    # a tokenless writer (or an absent file): all-None, never raises
    _write(path, "8\n")
    assert capacity_lib.read_allocation_meta(path) == {
        "decision_id": None, "cause": None,
    }
    assert capacity_lib.read_allocation_meta(str(tmp_path / "gone")) == {
        "decision_id": None, "cause": None,
    }


def test_stamp_decision_env_sets_and_clears(tmp_path):
    from tpu_dist.elastic.supervisor import (
        DECISION_CAUSE_ENV,
        DECISION_ID_ENV,
        stamp_decision_env,
    )
    from tpu_dist.fleet import capacity as capacity_lib

    path = str(tmp_path / "alloc")
    capacity_lib.write_allocation(path, 4, decision_id=9, cause="goodput")
    env: dict = {}
    meta = stamp_decision_env(env, path)
    assert env[DECISION_ID_ENV] == "9" and env[DECISION_CAUSE_ENV] == "goodput"
    assert meta["decision_id"] == 9
    # the arbitration window closed (tokenless rewrite): a relaunch must
    # NOT inherit the dead id from the launcher's own environment
    capacity_lib.write_allocation(path, 8)
    stamp_decision_env(env, path)
    assert DECISION_ID_ENV not in env and DECISION_CAUSE_ENV not in env


# -- goodput attribution: the serve-preempt bucket ---------------------------


def _segments(resume_extra):
    rec = {
        "kind": "resume", "run_id": "b", "ts": 130.0, "rel_s": 10.0,
        "dp": 4, "prev_dp": 8, "resharded": True,
    }
    rec.update(resume_extra)
    return [
        {"kind": "goodput", "run_id": "a", "ts": 100.0, "final": True,
         "productive_s": 50.0, "data_stall_s": 10.0, "elapsed_s": 60.0},
        rec,
        {"kind": "goodput", "run_id": "b", "ts": 150.0, "final": True,
         "productive_s": 20.0, "elapsed_s": 20.0},
    ]


def test_serve_breach_gap_charged_to_preempt_for_serve(tmp_path):
    """A world-change gap whose resume carries the propagated
    ``decision_id`` with cause ``serve_breach`` is the CHOSEN cost of
    the co-scheduling policy — it lands in ``preempt_for_serve_s``, not
    ``recovery_s``, and the partition stays exact."""
    from tpu_dist.obs import goodput as goodput_lib

    gp = goodput_lib.run_ledger(_segments(
        {"decision_id": 3, "decision_cause": "serve_breach"}
    ))
    assert gp["preempt_for_serve_s"] == 20.0
    assert gp["recovery_s"] == 0.0 and gp["preempt_s"] == 0.0
    assert gp["restart_gap_s"] == 20.0
    bucket_sum = sum(gp[f"{b}_s"] for b in goodput_lib.ALL_BUCKETS)
    assert bucket_sum == pytest.approx(gp["elapsed_s"], abs=1e-9)
    # the phrase layer names the arbitration
    assert "[decision #3]" in goodput_lib.fleet_move_phrase(
        {"donor": "tr", "chips": 4, "decision_id": 3, "preempt": True}
    )


def test_elastic_gap_without_decision_stays_recovery():
    """The split is EXACT: the same gap without a propagated id (a
    chip-loss shrink, a probe-driven grow) still reads as elastic
    recovery — and a serve_breach cause with no id (a torn propagation)
    must NOT be trusted into the serve bucket."""
    from tpu_dist.obs import goodput as goodput_lib

    for extra in ({}, {"decision_cause": "serve_breach"},
                  {"decision_id": 3, "decision_cause": "goodput"}):
        gp = goodput_lib.run_ledger(_segments(extra))
        assert gp["recovery_s"] == 20.0, extra
        assert gp["preempt_for_serve_s"] == 0.0, extra
        bucket_sum = sum(gp[f"{b}_s"] for b in goodput_lib.ALL_BUCKETS)
        assert bucket_sum == pytest.approx(gp["elapsed_s"], abs=1e-9)


# -- obs pod: the rendered chain + the chip-ownership Gantt ------------------


def test_pod_report_decision_chains_and_gantt():
    """``obs pod`` joins every artifact stamped with one ``decision_id``
    into a rendered causal chain (an id with moves but no resume is
    surfaced INCOMPLETE, never dropped) and synthesizes the per-chip
    ownership Gantt track from the tenancy snapshots."""
    from tpu_dist.obs import aggregate

    ctl = [
        {"kind": "fleet", "schema_version": 15, "ts": 100.0, "tick": 3,
         "action": "donate", "donor": "tr", "for_run": "sv", "chips": 4,
         "preempt": True, "decision_id": 1, "cause": "serve_breach",
         "alloc_after": {"tr": 4, "sv": 2}},
        {"kind": "fleet", "schema_version": 15, "ts": 101.0, "tick": 4,
         "action": "grant", "recipient": "sv", "chips": 4, "preempt": True,
         "decision_id": 1, "cause": "serve_breach", "chained": True,
         "alloc_after": {"tr": 4, "sv": 6}},
        # a second decision nobody relaunched for — the bug the tracing
        # exists to catch must render, not vanish
        {"kind": "fleet", "schema_version": 15, "ts": 110.0, "tick": 9,
         "action": "donate", "donor": "sv", "for_run": "tr", "chips": 2,
         "decision_id": 2, "cause": "serve_release",
         "alloc_after": {"tr": 4, "sv": 4}},
        {"kind": "tenancy", "schema_version": 15, "ts": 100.0, "tick": 3,
         "alloc": {"tr": 4, "sv": 2}, "free": 1, "pending": 4,
         "total_chips": 11, "decision_id": 1},
        {"kind": "tenancy", "schema_version": 15, "ts": 101.0, "tick": 4,
         "alloc": {"tr": 4, "sv": 6}, "free": 1, "pending": 0,
         "total_chips": 11, "decision_id": 1},
    ]
    tr = [
        {"kind": "resume", "schema_version": 15, "ts": 130.0, "epoch": 1,
         "dp": 4, "prev_dp": 8, "resharded": True, "restarts": 1,
         "decision_id": 1, "decision_cause": "serve_breach"},
    ]
    report = aggregate.pod_report([("ctl", ctl), ("tr", tr)])
    chains = report["decision_chains"]
    assert [c["decision_id"] for c in chains] == [1, 2]
    full, dangling = chains
    assert full["cause"] == "serve_breach" and full["complete"] is True
    assert [m["action"] for m in full["moves"]] == ["donate", "grant"]
    assert full["resumes"][0]["host"] == "tr"
    assert dangling["complete"] is False and not dangling["resumes"]
    text = aggregate.format_text(report)
    assert "decision #1" in text and "serve_breach" in text
    assert "tr resumed dp=4" in text
    assert "INCOMPLETE" in text  # the dangling chain is loud
    # the Gantt: one metadata row per chip, ownership bars stamped with
    # the decision that laid them out
    trace = aggregate.pod_trace([("ctl", ctl), ("tr", tr)])
    gantt = [e for e in trace["traceEvents"] if e.get("cat") == "tenancy"]
    assert gantt, "no chip-ownership bars synthesized"
    owners = {e["name"] for e in gantt}
    assert {"tr", "sv", "free", "pending"} <= owners
    assert any(e["args"].get("decision_id") == 1 for e in gantt)
    rows = {
        e["tid"] for e in trace["traceEvents"]
        if e.get("name") == "thread_name"
        and "chip" in str(e.get("args", {}).get("name", ""))
    }
    assert len(rows) == 11  # one row per pod chip


# -- CLI ----------------------------------------------------------------------


def test_parse_source_grammar():
    s = parse_source("svc=/pod/svc.prom,hb=/pod/svc.hb,port=9100,kind=serve")
    assert s == RunSource(
        "svc", metrics_file="/pod/svc.prom", heartbeat_file="/pod/svc.hb",
        port=9100, kind="serve",
    )
    assert parse_source("tr=port:9090") == RunSource("tr", port=9090)
    for bad in ("noequals", "r=m,garbage", "r=m,zz=1"):
        with pytest.raises(ValueError):
            parse_source(bad)


def test_hub_cli_once(tmp_path, capsys):
    from tpu_dist.obs.__main__ import main

    prom = _prom(tmp_path, "tr", **{"train.mfu": 0.4})
    out = str(tmp_path / "federated.prom")
    assert main([
        "hub", "--run", f"tr={prom}", "--once", "--out", out,
    ]) == 0
    assert "federated 1 run(s)" in capsys.readouterr().out
    with open(out) as f:
        page = f.read()
    assert page.endswith("# EOF\n")
    assert 'tpu_dist_train_mfu{run="tr"}' in page
    # zero runs aggregated is a FAILED pass, never a quiet empty page
    assert main([
        "hub", "--run", f"ghost={tmp_path / 'gone.prom'}", "--once",
    ]) == 1
    assert main(["hub", "--once"]) == 2  # no --run at all


# -- TD123: the plane is control-plane only ----------------------------------


def test_td123_registered_and_audit_all_wired():
    from tpu_dist.analysis import jaxpr_audit
    from tpu_dist.analysis.rules import RULES

    assert "TD123" in RULES
    assert RULES["TD123"].name == "pod-telemetry-control-plane-only"
    assert "pod_hub_noop_violations" in inspect.getsource(
        jaxpr_audit.audit_all
    )


def test_td123_gate_pod_telemetry_plane_is_noop():
    from tpu_dist.analysis.jaxpr_audit import pod_hub_noop_violations

    assert pod_hub_noop_violations() == []


def test_td123_probe_is_vacuity_guarded(monkeypatch):
    """A hub that aggregated runs but whose arbitration chain never
    fired proves nothing: gut the scheduler's decide and the probe must
    REPORT, not pass (the dead-detector contract)."""
    from tpu_dist.analysis.jaxpr_audit import pod_hub_noop_violations
    from tpu_dist.fleet import scheduler as fleet_lib

    monkeypatch.setattr(
        fleet_lib.FleetScheduler, "decide", lambda self, tick, sig: []
    )
    vs = pod_hub_noop_violations()
    assert len(vs) == 1 and vs[0].rule == "TD123"
    assert "did not actually run" in vs[0].message


# -- e2e: a live run scraped through the hub ---------------------------------


@pytest.mark.slow  # full trainer fit (~20 s incl. compiles): excluded from
# the timed tier-1 gate; gates in the CI hub step, which runs this module
# without the slow filter
def test_e2e_live_run_hub_signals_match_direct(tmp_path):
    """Acceptance: a REAL training run publishing its exposition +
    heartbeat, federated live alongside a second (serve-kind) source —
    mid-run and at the end, ``signals_from_hub`` over one hub snapshot
    is byte-identical to the direct per-run ``read_signals`` path, and
    the federated page stays OpenMetrics-parseable throughout."""
    import threading

    from tests.helpers import tiny_resnet
    from tpu_dist.config import TrainConfig
    from tpu_dist.fleet.scheduler import read_signals, signals_from_hub
    from tpu_dist.train.trainer import Trainer, register_model

    register_model(
        "tiny_hub_e2e", lambda num_classes=10: tiny_resnet(num_classes)
    )
    mf = str(tmp_path / "metrics.prom")
    hb = str(tmp_path / "hb.json")
    sv_prom = _prom(
        tmp_path, "sv", alerts={"slo_p99_high": 1.0},
        **{"serve.queue_depth": 9.0, "serve.availability": 0.8},
    )
    sv_hb = _hb(tmp_path, "sv", time.time())
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_hub_e2e", num_classes=10,
        batch_size=64, epochs=2, steps_per_epoch=3, eval_every=0,
        synthetic_n=640, log_every=2, seed=0,
        log_file=str(tmp_path / "run.jsonl"),
        metrics_file=mf, heartbeat_file=hb,
    )
    hub = TelemetryHub([
        RunSource("tr", metrics_file=mf, heartbeat_file=hb),
        RunSource("sv", metrics_file=sv_prom, heartbeat_file=sv_hb,
                  kind="serve"),
    ])
    matches = []
    stop = threading.Event()

    def scraper():
        while not stop.is_set():
            now = time.time()
            snap = hub.collect(now=now)
            if snap["runs"]["tr"]["values"]:
                via_hub = signals_from_hub(snap)
                direct = {
                    "tr": read_signals("tr", mf, heartbeat_file=hb, now=now),
                    "sv": read_signals(
                        "sv", sv_prom, heartbeat_file=sv_hb, now=now
                    ),
                }
                # the run is LIVE: a publish can land between the hub
                # pass and the direct scrape — only identical-input
                # pairs are comparable, and at least one must land
                if all(
                    dataclasses.asdict(via_hub[r]) ==
                    dataclasses.asdict(direct[r]) for r in direct
                ):
                    matches.append(hub.federated(snap))
            time.sleep(0.1)

    t = threading.Thread(target=scraper)
    t.start()
    try:
        Trainer(cfg).fit()
    finally:
        stop.set()
        t.join()
    # post-run the exposition is static: one comparison is GUARANTEED
    # comparable (the mid-run ones above are best-effort live evidence)
    now = time.time()
    snap = hub.collect(now=now)
    via_hub = signals_from_hub(snap)
    assert dataclasses.asdict(via_hub["tr"]) == dataclasses.asdict(
        read_signals("tr", mf, heartbeat_file=hb, now=now)
    )
    assert dataclasses.asdict(via_hub["sv"]) == dataclasses.asdict(
        read_signals("sv", sv_prom, heartbeat_file=sv_hb, now=now)
    )
    matches.append(hub.federated(snap))
    assert matches, "no hub-vs-direct comparison landed"
    for page in matches:
        assert page.endswith("# EOF\n")
        parsed = export_lib.parse(page)
        assert parsed["tpu_dist_pod_runs_aggregated"] == 2.0
        assert 'tpu_dist_hub_run_up{run="tr"}' in parsed
    # the final textfile (left behind by design) still federates, the
    # swept heartbeat now reads dead — marked with the sweep, not dropped
    final = hub.collect()
    assert final["runs"]["tr"]["values"]
    assert not os.path.exists(hb)  # clean exit swept the beat
    assert final["runs"]["tr"]["dead"] is True
