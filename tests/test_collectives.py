"""Collectives layer over the 8-device emulated mesh (NCCL-replacement, N1)."""

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import PartitionSpec as P
from tpu_dist.comm.compat import shard_map

from tpu_dist.comm import collectives as C
from tpu_dist.comm import mesh as mesh_lib


def _mesh():
    return mesh_lib.data_parallel_mesh()


def test_mesh_has_8_devices():
    assert _mesh().devices.size == 8


def test_reduce_mean_matches_reference_semantics():
    """reduce_mean ≡ clone → all_reduce(SUM) → /nprocs (utils/util.py:5-9)."""
    mesh = _mesh()
    x = np.arange(8, dtype=np.float32)  # one value per replica

    f = jax.jit(
        shard_map(
            lambda v: C.reduce_mean(v, "data"),
            mesh=mesh, in_specs=P("data"), out_specs=P("data"),
        )
    )
    out = np.asarray(f(x))
    np.testing.assert_allclose(out, np.full(8, x.mean()), rtol=1e-6)


def test_reduce_sum_and_allgather():
    mesh = _mesh()
    x = np.arange(8, dtype=np.float32)
    f = jax.jit(
        shard_map(
            lambda v: (C.reduce_sum(v, "data"), C.all_gather(v, "data")),
            mesh=mesh, in_specs=P("data"), out_specs=(P("data"), P()),
            check_vma=False,  # all_gather outputs aren't vma-inferred as replicated
        )
    )
    s, g = f(x)
    np.testing.assert_allclose(np.asarray(s), np.full(8, 28.0))
    np.testing.assert_allclose(np.asarray(g), x)


def test_broadcast_from_rank0():
    """DDP init-time parameter broadcast semantics (distributed.py:60)."""
    mesh = _mesh()
    x = np.arange(8, dtype=np.float32) + 1.0
    f = jax.jit(
        shard_map(
            lambda v: C.broadcast_from(v, "data", src=0),
            mesh=mesh, in_specs=P("data"), out_specs=P("data"),
        )
    )
    np.testing.assert_allclose(np.asarray(f(x)), np.full(8, 1.0))


def test_barrier_and_host_allreduce():
    mesh = _mesh()
    C.barrier(mesh)  # must simply not deadlock
    out = C.host_allreduce_mean(jnp.float32(3.5), mesh)
    assert float(out) == 3.5
