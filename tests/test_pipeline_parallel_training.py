"""End-to-end pipeline-parallel training (DP×PP, staged ViT)."""

import jax
import numpy as np
import pytest

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.config import TrainConfig
from tpu_dist.nn.vit_pp import ViTPipelineDef
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tpu_dist.train.step import make_train_step
from tpu_dist.train.trainer import Trainer


def _model():
    return ViTPipelineDef(image_size=16, patch_size=4, dim=32, depth=4, heads=4,
                          num_classes=5)


def test_dp_pp_training_matches_single_device():
    from jax.sharding import NamedSharding

    model = _model()
    opt = SGD()
    mesh2d = mesh_lib.device_mesh([2, 4], ["data", "pipe"])
    mesh1 = mesh_lib.device_mesh([1], ["data"], jax.devices()[:1])
    specs = model.pp_param_specs("pipe")

    params, s = model.init(jax.random.PRNGKey(0))
    st = TrainState.create(params, s, opt)
    place = lambda tree: jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh2d, spec)), tree, specs
    )
    s_pp = TrainState(
        params=place(st.params),
        bn_state=jax.device_put(st.bn_state, mesh_lib.replicated(mesh2d)),
        opt_state=place(st.opt_state),
        step=jax.device_put(st.step, mesh_lib.replicated(mesh2d)),
    )
    s_1 = jax.device_put(st, mesh_lib.replicated(mesh1))

    step_pp = make_train_step(
        model.apply, opt, mesh2d, sync_bn=False, donate=False,
        pp_axis="pipe", param_specs=specs,
    )
    step_1 = make_train_step(model.apply, opt, mesh1, sync_bn=False, donate=False)

    rng = np.random.default_rng(0)
    for _ in range(3):
        x = rng.normal(size=(8, 16, 16, 3)).astype(np.float32)
        y = rng.integers(0, 5, 8).astype(np.int32)
        s_pp, m_pp = step_pp(
            s_pp, mesh_lib.shard_batch(mesh2d, x), mesh_lib.shard_batch(mesh2d, y), 0.05
        )
        s_1, m_1 = step_1(
            s_1, mesh_lib.shard_batch(mesh1, x), mesh_lib.shard_batch(mesh1, y), 0.05
        )

    np.testing.assert_allclose(float(m_pp["loss"]), float(m_1["loss"]), rtol=1e-4)
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(s_pp.params)),
        jax.tree_util.tree_leaves(jax.device_get(s_1.params)),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5)


@pytest.mark.slow  # tier-1 budget (ISSUE 17): gates in analysis.yml
def test_trainer_pp_e2e_with_eval_and_resume(tmp_path):
    cfg = TrainConfig(
        dataset="synthetic", model="vit_pp_tiny", num_classes=10, batch_size=16,
        epochs=1, steps_per_epoch=2, log_every=1, lr=0.05, eval_every=1,
        pp=4, sync_bn=False, synthetic_n=160, ckpt_dir=str(tmp_path), save_every=1,
    )
    t = Trainer(cfg)
    assert t.n_data == 2 and t.n_devices == 8
    out = t.fit()
    assert np.isfinite(out["loss"]) and "val_top1" in out

    t2 = Trainer(cfg.replace(resume=True, epochs=2))
    assert t2.start_epoch == 1
    blk_w = t2.state.params["blocks"]["qkv"]["w"]
    assert len(blk_w.sharding.device_set) == 8  # stages restored sharded
    assert np.isfinite(t2.fit()["loss"])


def test_trainer_pp_microbatches_flag():
    cfg = TrainConfig(
        dataset="synthetic", model="vit_pp_tiny", num_classes=10, batch_size=16,
        epochs=1, steps_per_epoch=1, log_every=1, lr=0.05, eval_every=0,
        pp=4, pp_microbatches=8, sync_bn=False, synthetic_n=160,
    )
    out = Trainer(cfg).train_epoch(0)
    assert np.isfinite(out["loss"])


def test_trainer_pp_rejects_bad_configs():
    import pytest

    with pytest.raises(ValueError, match="pipeline parallelism"):
        Trainer(TrainConfig(dataset="synthetic", model="resnet18", pp=4, synthetic_n=512))
    with pytest.raises(ValueError, match="not divisible by pp"):
        Trainer(TrainConfig(dataset="synthetic", model="vit_pp_tiny", pp=8,
                            batch_size=64, synthetic_n=512))
