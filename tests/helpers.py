"""Shared test fixtures: tiny models that compile fast on the emulated mesh."""

from __future__ import annotations

import jax

from tpu_dist.nn import layers as L
from tpu_dist.nn.resnet import ResNetDef


def tiny_resnet(num_classes: int = 10) -> ResNetDef:
    """Reference ResNet topology at 1/8 width — same code paths, ~40x fewer
    FLOPs, seconds to compile on the 8-device CPU mesh."""
    return ResNetDef("basic", (1, 1, 1, 1), num_classes, widths=(8, 8, 16, 16))


class TinyConvNet:
    """conv+bn+fc micro-model exercising every layer primitive."""

    def __init__(self, num_classes: int = 10, width: int = 8):
        self.num_classes = num_classes
        self.width = width

    def init(self, key):
        k1, k2 = jax.random.split(key)
        params = {"conv": L.conv_init(k1, 3, self.width, 3)}
        params["bn"], bn_state = L.bn_init(self.width)
        params["fc"] = L.linear_init(k2, self.width, self.num_classes)
        return params, {"bn": bn_state}

    def apply(self, params, state, x, *, train=False, axis_name=None):
        y = L.conv_apply(params["conv"], x, 1, 1)
        y, ns = L.bn_apply(params["bn"], state["bn"], y, train=train, axis_name=axis_name)
        y = L.relu(y)
        y = L.global_avg_pool(y)
        return L.linear_apply(params["fc"], y), {"bn": ns}


class TinyMLP:
    """BN-free model: exact arithmetic equivalence tests (grad accum, DP)."""

    def __init__(self, num_classes: int = 10, width: int = 16, in_dim: int = 12):
        self.num_classes = num_classes
        self.width = width
        self.in_dim = in_dim

    def init(self, key):
        k1, k2 = jax.random.split(key)
        return {
            "l1": L.linear_init(k1, self.in_dim, self.width),
            "l2": L.linear_init(k2, self.width, self.num_classes),
        }, {}

    def apply(self, params, state, x, *, train=False, axis_name=None):
        x = x.reshape(x.shape[0], -1)
        y = L.relu(L.linear_apply(params["l1"], x))
        return L.linear_apply(params["l2"], y), state
