"""Property-based checks of the DistributedSampler invariants."""

import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from tpu_dist.data.sampler import DistributedSampler


@settings(max_examples=60, deadline=None)
@given(
    n=st.integers(1, 400),
    shards=st.integers(1, 9),
    seed=st.integers(0, 1000),
    epoch=st.integers(0, 5),
    drop_last=st.booleans(),
)
def test_partition_invariants(n, shards, seed, epoch, drop_last):
    samplers = [
        DistributedSampler(n, shards, i, shuffle=True, seed=seed, drop_last=drop_last)
        for i in range(shards)
    ]
    for s in samplers:
        s.set_epoch(epoch)
    idx = [s.indices() for s in samplers]
    masks = [s.pad_mask() for s in samplers]

    # equal shard sizes, consistent with len()
    sizes = {len(i) for i in idx}
    assert len(sizes) == 1
    assert sizes.pop() == len(samplers[0])

    if drop_last:
        # no duplicates anywhere; every index is real
        allidx = np.concatenate(idx) if idx[0].size else np.array([], int)
        assert len(set(allidx.tolist())) == len(allidx)
        assert all(m.all() for m in masks)
    else:
        # real (mask=True) positions cover every example exactly once
        real = np.concatenate(
            [i[m] for i, m in zip(idx, masks)]
        ) if idx[0].size else np.array([], int)
        assert sorted(real.tolist()) == list(range(n))

    # indices always in range
    for i in idx:
        if i.size:
            assert i.min() >= 0 and i.max() < n


@settings(max_examples=30, deadline=None)
@given(n=st.integers(2, 300), shards=st.integers(1, 8), seed=st.integers(0, 100))
def test_epoch_determinism(n, shards, seed):
    a = DistributedSampler(n, shards, 0, seed=seed)
    b = DistributedSampler(n, shards, 0, seed=seed)
    a.set_epoch(3)
    b.set_epoch(3)
    np.testing.assert_array_equal(a.indices(), b.indices())
