"""Force an 8-device CPU mesh for the test suite.

This is the TPU-world analogue of torch's gloo-on-CPU "fake backend" pattern
(SURVEY §4): XLA's host-platform device-count flag emulates a multi-chip
slice in one process, so every distributed code path (pmean grads, SyncBN,
sharded eval) is exercised without TPU hardware.

NOTE on mechanism: the platform switch is done via ``jax.config`` AFTER
importing jax, not by exporting ``JAX_PLATFORMS=cpu`` into the process
environment — some TPU runtime environments install a sitecustomize that
registers the TPU PJRT plugin at interpreter start and misbehaves when the
env var contradicts it. ``jax.config.update`` after import, before the first
backend use, is always safe.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# NOTE: deliberately NO persistent compilation cache here — in this
# environment cached XLA:CPU AOT artifacts can be loaded on a host with
# different CPU features (containers migrate), which XLA warns may SIGILL.
# Cold compiles cost ~2 extra minutes; flaky SIGILLs cost more.
