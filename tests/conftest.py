"""Force an 8-device CPU mesh for the test suite.

This is the TPU-world analogue of torch's gloo-on-CPU "fake backend" pattern
(SURVEY §4): XLA's host-platform device-count flag emulates a multi-chip
slice in one process, so every distributed code path (pmean grads, SyncBN,
sharded eval) is exercised without TPU hardware.

NOTE on mechanism: the platform switch is done via ``jax.config`` AFTER
importing jax, not by exporting ``JAX_PLATFORMS=cpu`` into the process
environment — some TPU runtime environments install a sitecustomize that
registers the TPU PJRT plugin at interpreter start and misbehaves when the
env var contradicts it. ``jax.config.update`` after import, before the first
backend use, is always safe.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# NOTE: deliberately NO persistent compilation cache here — in this
# environment cached XLA:CPU AOT artifacts can be loaded on a host with
# different CPU features (containers migrate), which XLA warns may SIGILL.
# Cold compiles cost ~2 extra minutes; flaky SIGILLs cost more.


def pytest_configure(config):
    # quick = a <5-min slice that still touches every component (one test
    # per subsystem); the full suite stays the merge bar.  Select with
    # ``pytest -m quick``; the unmarked complement runs with ``-m "not quick"``.
    config.addinivalue_line(
        "markers", "quick: fast cross-component smoke slice (pytest -m quick)"
    )
    # slow = multi-minute statistical/convergence runs, excluded from the
    # tier-1 gate (which runs with -m 'not slow' under a hard timeout).
    #
    # TIER-1 TIME BUDGET: the gate is `timeout -k 10 870` around the whole
    # 'not slow' suite (ROADMAP.md "Tier-1 verify") — the suite must stay
    # comfortably under 870 s wall on one CPU host or the timeout TRUNCATES
    # it mid-alphabet and the gate reads as a pass over a partial run.
    # When a PR pushes the wall time near the limit, re-mark its heaviest
    # e2e tests `slow` AND make sure their module runs in a CI step without
    # the slow filter (.github/workflows/analysis.yml), so coverage moves
    # to CI instead of silently vanishing. PR 6 overran (~917 s); PR 7
    # moved ~60 s of e2e into `slow` to restore margin; PR 17 moved
    # ~280 s (the 20 heaviest multi-axis fits, now in the analysis.yml
    # "Trainer e2e suite" step) after host drift pushed the full run
    # to ~1000 s.
    config.addinivalue_line(
        "markers", "slow: multi-minute runs excluded from the tier-1 gate"
    )


# The quick slice, curated centrally (VERDICT r4 #8: split before the full
# suite crosses 30 min).  Entries are nodeid substrings: a bare module name
# marks the whole (fast, unit-level) module; "module::test" marks one cheap
# representative of a component whose full module is compile-heavy.  Chosen
# from --durations=60 data so the slice stays under ~5 min solo while still
# crossing every subsystem: models, data, metrics, collectives, BN, eval,
# step/trainer, ckpt (plain/async/sharded/mid-epoch), schedules/guard,
# optim, ZeRO-1, FSDP, SP/TP/EP/PP/PP×TP, attention (ring/ulysses/flash),
# fused epoch/eval, observability, CLI/launcher, native pipeline, bench.
_QUICK = (
    "test_metrics.py", "test_collectives.py", "test_sampler.py::",
    "test_ckpt.py", "test_eval.py", "test_bn.py", "test_data.py",
    "test_cli.py", "test_bench_configs.py", "test_golden_trajectory.py",
    "test_elastic.py", "test_fleet.py",
    "test_tpu_lock.py", "test_regularization.py", "test_remat.py",
    "test_native_pipeline.py", "test_tensorboard.py",
    "test_launch_and_history.py", "test_fused_sgd.py", "test_observability.py",
    "test_obs.py", "test_device_health.py", "test_goodput.py",
    "test_export.py", "test_xprof.py", "test_flight.py", "test_serve.py",
    "test_memory.py", "test_tenancy.py", "test_hub.py", "test_archive.py",
    "test_models.py::test_param_count_parity[resnet18",
    "test_models.py::test_eval_uses_running_stats",
    "test_vit.py::test_vit_forward_shape",
    "test_vit.py::test_vit_rejects_oversized_images",
    "test_train_step.py::test_dp_equivalence_8dev_vs_1dev",
    "test_train_step.py::test_grad_accum_no_sync_equivalence",
    "test_train_step.py::test_bf16_policy_keeps_master_f32",
    "test_trainer.py::test_config_argparse_bridge",
    "test_attention.py::test_full_attention_matches_manual_softmax",
    "test_attention.py::test_ring_equals_full_8way",
    "test_attention.py::test_ulysses_equals_full_4way",
    "test_flash_attention.py::test_attention_dispatch_impl",
    "test_flash_attention.py::test_flash_bf16_dtype_and_accuracy",
    "test_fsdp.py::test_fsdp_specs_rules",
    "test_fsdp.py::test_fsdp_matches_plain_dp_with_bn",
    "test_parallel.py::test_tp_mlp_matches_dense",
    "test_parallel.py::test_moe_ep_matches_dense",
    "test_parallel.py::test_pipeline_matches_sequential",
    "test_seq_parallel_training.py::test_dp_sp_training_matches_single_device",
    "test_tensor_parallel_training.py::test_dp_tp_training_matches_single_device",
    "test_expert_parallel_training.py::test_trainer_ep_rejects_bad_configs",
    "test_pipeline_parallel_training.py::test_trainer_pp_microbatches_flag",
    "test_pp_tp_training.py::test_dp_pp_tp_training_matches_single_device",
    "test_mid_epoch_resume.py::test_loader_iter_from_matches_full_tail",
    "test_interrupt.py::test_interrupt_in_first_epoch_saves_nothing",
    "test_sharded_ckpt.py::test_sharded_roundtrip_and_no_duplication",
    "test_sharded_ckpt.py::test_resume_format_mismatch_is_loud",
    "test_async_ckpt.py::test_async_save_matches_sync",
    "test_weight_update_sharding.py::test_sharded_update_matches_plain",
    "test_optim.py::test_sgd_matches_torch_semantics",
    "test_optim.py::test_multistep_lr_schedule",
    "test_optim.py::test_adamw_matches_optax",
    "test_schedules_and_guard.py::test_cosine_schedule_shape",
    "test_schedules_and_guard.py::test_nan_guard_raises",
    "test_fused_epoch.py::test_fused_epoch_runs_all_steps_and_trains",
    "test_fused_eval.py::test_fused_eval_counts_and_matches_direct_forward",
    "test_quantized_collectives.py::test_quantize_scale_correctness_and_error_bound",
    "test_quantized_collectives.py::test_td104_wire_bytes_int8_vs_bf16_vs_none",
    "test_shardlint.py::test_parser_synthetic_module",
    "test_shardlint.py::test_td116_matrix_clean_and_exact",
    "test_shardlint.py::test_td117_injected_bad_in_shardings_caught",
    "test_shardlint.py::test_rules_registry_matches_docs_table",
    "test_planner.py::test_build_plan_is_deterministic",
    "test_planner.py::test_hbm_budget_refusal_matrix",
    "test_planner.py::test_price_candidate_gauge_arithmetic",
    "test_planner.py::test_td118_inject_miscost_must_be_caught",
    "test_planner.py::test_td119_direction_registered_and_gates",
    "test_optim.py::test_lars_lamb_golden_trajectory_pins",
    "test_optim.py::test_linear_scaling_rule_and_warmup",
    "test_async_sharded_ckpt.py::test_async_save_bit_identical_to_sync",
    "test_async_sharded_ckpt.py::test_eio_mid_background_surfaces_at_drain",
    "test_async_sharded_ckpt.py::test_td121_gate_payload_and_vacuous_knob",
    "test_async_sharded_ckpt.py::test_tune_report_roundtrip_and_forward_compat",
)


def pytest_collection_modifyitems(config, items):
    import pytest  # noqa: PLC0415

    for item in items:
        # slow-marked tests never join the quick slice, even when their
        # whole module is listed — the markers would contradict (quick is
        # the <5-min slice; slow is the >10s excluded-from-timed-gates set)
        if item.get_closest_marker("slow"):
            continue
        if any(q in item.nodeid for q in _QUICK):
            item.add_marker(pytest.mark.quick)
