"""Layer 3 (`shardlint`) tested: the optimized-HLO collective parser
(synthetic modules, version-drift robustness), the TD116
compiled-vs-predicted agreement on the audit matrix (exact on the audit
MLP), the TD117 injected-reshard catch, the quantized-mode ratio pins at
the HLO level, the shard_report schema round-trip, the rules-registry /
docs table parity, and the compare-gate registration of
``hlo_wire_bytes_per_step``."""

import json
import os
import re

import pytest

from tpu_dist.analysis import shardlint
from tpu_dist.analysis.rules import RULES
from tpu_dist.analysis.shardlint import (
    HLOCollective,
    HLOParseError,
    ShardReportError,
    parse_hlo_collectives,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- the parser on synthetic HLO ---------------------------------------------


_SYNTHETIC = """\
HloModule synthetic, entry_computation_layout={(f32[128]{0})->f32[128]{0}}

%add (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %s = f32[] add(f32[] %a, f32[] %b)
}

%loop_body (p: (s32[], f32[64])) -> (s32[], f32[64]) {
  %p = (s32[], f32[64]) parameter(0)
  %i = s32[] get-tuple-element((s32[], f32[64]) %p), index=0
  %x = f32[64] get-tuple-element((s32[], f32[64]) %p), index=1
  %perm = f32[64] collective-permute(f32[64] %x), channel_id=5, source_target_pairs={{0,1},{1,0}}
  ROOT %t = (s32[], f32[64]) tuple(s32[] %i, f32[64] %perm)
}

%loop_cond (p: (s32[], f32[64])) -> pred[] {
  %p = (s32[], f32[64]) parameter(0)
  %i = s32[] get-tuple-element((s32[], f32[64]) %p), index=0
  ROOT %lt = pred[] compare(s32[] %i, s32[] %i), direction=LT
}

ENTRY %main (x: f32[128]) -> f32[128] {
  %x = f32[128] parameter(0)
  %ar = f32[128] all-reduce(f32[128] %x), channel_id=1, replica_groups={{0,1,2,3}}, use_global_device_ids=true, to_apply=%add, metadata={op_name="jit(step)/psum"}
  %rs = f32[32] reduce-scatter(f32[128] %ar), channel_id=2, replica_groups=[1,4]<=[4], dimensions={0}, to_apply=%add
  %ag = f32[128] all-gather(f32[32] %rs), channel_id=3, replica_groups={{0,1,2,3}}, dimensions={0}
  %a2a = (s8[16]{0}, s8[16]{0}) all-to-all(s8[16]{0} %x, s8[16]{0} %x), replica_groups={{0,1}}
  %w = (s32[], f32[64]) while((s32[], f32[64]) %x), condition=%loop_cond, body=%loop_body
  ROOT %out = f32[128] copy(f32[128] %ag)
}
"""


def test_parser_synthetic_module():
    ops = parse_hlo_collectives(_SYNTHETIC, loop_trips=3)
    by_kind = {op.kind: op for op in ops}
    assert sorted(by_kind) == [
        "all-gather", "all-reduce", "all-to-all",
        "collective-permute", "reduce-scatter",
    ]
    ar = by_kind["all-reduce"]
    assert (ar.elems, ar.wire_bytes) == (128, 128 * 4 * 2)  # 2 ring legs
    assert ar.replica_groups == "{{0,1,2,3}}"
    assert ar.channel_id == 1
    assert ar.op_name == "jit(step)/psum"
    # reduce-scatter costed on its operand; iota-format groups captured
    rs = by_kind["reduce-scatter"]
    assert (rs.elems, rs.wire_bytes) == (128, 512)
    assert rs.replica_groups == "[1,4]<=[4]"
    # all-gather costed on its gathered OUTPUT
    ag = by_kind["all-gather"]
    assert (ag.elems, ag.wire_bytes) == (128, 512)
    # variadic tuple all-to-all: every int8 operand counted, int bytes
    a2a = by_kind["all-to-all"]
    assert (a2a.elems, a2a.wire_bytes, a2a.int_bytes) == (32, 32, 32)
    # the while-resident permute is multiplied by the declared trip count
    cp = by_kind["collective-permute"]
    assert cp.in_loop and cp.loop_trips == 3
    assert (cp.elems, cp.wire_bytes) == (64 * 3, 64 * 4 * 3)
    assert cp.replica_groups == "{{0,1},{1,0}}"


def test_parser_async_start_done_pairs():
    text = (
        "HloModule async\n\n"
        "ENTRY %main (x: f32[32]) -> f32[128] {\n"
        "  %x = f32[32] parameter(0)\n"
        "  %s = (f32[32]{0}, f32[128]{0}) all-gather-start(f32[32] %x), "
        "channel_id=1, replica_groups={{0,1,2,3}}, dimensions={0}\n"
        "  ROOT %d = f32[128] all-gather-done((f32[32]{0}, f32[128]{0}) %s)\n"
        "}\n"
    )
    ops = parse_hlo_collectives(text)
    # -start folds into its base kind, costed on the true output; -done
    # is skipped (counting both would double the wire)
    assert len(ops) == 1
    assert ops[0].kind == "all-gather"
    assert (ops[0].elems, ops[0].wire_bytes) == (128, 512)


# -- robustness: drifted/truncated/foreign inputs never crash audit ----------


def test_parser_typed_errors():
    with pytest.raises(HLOParseError, match="empty"):
        parse_hlo_collectives("")
    with pytest.raises(HLOParseError, match="StableHLO/MLIR"):
        parse_hlo_collectives('module @jit_f {\n  stablehlo.add\n}\n')
    with pytest.raises(HLOParseError, match="not HLO"):
        parse_hlo_collectives("definitely not a module dump")
    with pytest.raises(HLOParseError, match="truncated"):
        parse_hlo_collectives(
            "HloModule m\n\nENTRY %main (a: f32[2]) -> f32[2] {\n"
            "  %a = f32[2] parameter(0)\n"  # no closing brace
        )


def test_parser_version_drift_degrades_not_crashes():
    # a renamed future opcode is simply not a collective; a missing
    # replica_groups parses to None instead of crashing
    text = (
        "HloModule m\n\n"
        "ENTRY %main (a: f32[8]) -> f32[8] {\n"
        "  %a = f32[8] parameter(0)\n"
        "  %r = f32[8] all-reduce(f32[8] %a), channel_id=1, to_apply=%add\n"
        "  %z = f32[8] fancy-new-reduce(f32[8] %r), replica_groups={{0,1}}\n"
        "}\n"
    )
    ops = parse_hlo_collectives(text)
    assert len(ops) == 1
    assert ops[0].replica_groups is None
    assert ops[0].wire_bytes == 8 * 4 * 2


def test_collective_free_jit_yields_empty_inventory():
    import jax
    import jax.numpy as jnp

    jitted = jax.jit(lambda x: x * 2.0 + 1.0)
    text = jitted.lower(jnp.ones((16,))).compile().as_text()
    assert parse_hlo_collectives(text) == []


def test_shard_all_skips_broken_family_with_count(monkeypatch):
    def broken(mesh):
        raise RuntimeError("builder exploded")

    monkeypatch.setitem(
        shardlint._FAMILIES,
        "broken",
        shardlint.ConfigFamily("broken", broken),
    )
    report, violations = shardlint.shard_all(names=["dp_sgd", "broken"])
    assert "dp_sgd" in report["families"]
    assert report["skips"]["broken"].startswith("RuntimeError")
    assert report["counts"]["skipped"] == 1
    assert violations == []


# -- TD116 on the audit matrix (exact on the audit MLP) ----------------------


@pytest.fixture(scope="module")
def dp_matrix():
    names = [
        "dp_sgd", "dp_wire_bf16", "dp_int8", "dp_int8_ef",
        "zero1_sgd", "zero1_int8",
    ]
    report, violations = shardlint.shard_all(names=names)
    assert report["skips"] == {}
    return report, violations


def test_td116_matrix_clean_and_exact(dp_matrix):
    report, violations = dp_matrix
    assert violations == [], [v.format_text() for v in violations]
    for name, fam in report["families"].items():
        v = fam["verdict"]
        assert v["agree"], (name, v)
        # EXACT agreement on the audit MLP: the two accountings price the
        # same elements, and integer legs the same bytes
        assert v["hlo"]["elems"] == v["predicted"]["elems"], name
        assert v["hlo"]["int_bytes"] == v["predicted"]["int_bytes"], name
    # absolute pins for the flagship cases (480-param MLP, 8-dev mesh):
    # f32 allreduce family moves 480*4*2 grad + 8 loss + 16 count bytes
    assert report["families"]["dp_sgd"]["hlo"]["bytes"] == 3864
    # ZeRO-1: RS(480)+AG(480) moves exactly what the allreduce moved
    assert report["families"]["zero1_sgd"]["hlo"]["bytes"] == 3864
    # the quantized two-stage reduce: int8 payload both legs + scales
    assert report["families"]["dp_int8"]["hlo"]["bytes"] == 1048


def test_float_wire_regime_detection(dp_matrix):
    report, _ = dp_matrix
    fams = report["families"]
    # f32 wire is native everywhere
    assert fams["dp_sgd"]["hlo"]["float_wire"] == "native"
    # the CPU backend's float-normalization pass widens the bf16 wire to
    # f32 — detected and DECLARED, not silently passed or spuriously
    # flagged (on TPU this comes back "native")
    assert fams["dp_wire_bf16"]["hlo"]["float_wire"] in (
        "native", "widened_to_f32",
    )
    # int8 legs can never be float-normalized: they stay byte-exact
    assert (
        fams["dp_int8"]["verdict"]["hlo"]["int_bytes"]
        == fams["dp_int8"]["verdict"]["predicted"]["int_bytes"]
        > 0
    )


def test_hlo_ratio_pins_quantized_modes(dp_matrix):
    """The TD104 ratio pins hold on the COMPILED artifact: across the
    wire modes {none, bf16, int8, int8_ef} the quantized gradient payload
    stays <= 0.5x the bf16 mode's and <= 0.25x the uncompressed mode's —
    the compiler must not silently widen a quantized leg (it cannot
    float-normalize int8). Equality allowed: the audit MLP's 480 params
    divide every mesh width, so padding is zero."""
    report, _ = dp_matrix
    payload = {
        name: report["families"][name]["hlo"]["wire"]["payload_bytes"]
        for name in ("dp_sgd", "dp_wire_bf16", "dp_int8", "dp_int8_ef")
    }
    assert payload["dp_int8"] <= 0.5 * payload["dp_wire_bf16"]
    assert payload["dp_int8"] <= 0.25 * payload["dp_sgd"]
    assert payload["dp_int8_ef"] <= 0.5 * payload["dp_wire_bf16"]
    assert payload["dp_int8_ef"] <= 0.25 * payload["dp_sgd"]
    # and the quantized payload is genuinely integer on the wire
    assert report["families"]["dp_int8"]["hlo"]["wire"][
        "quantized_payload_bytes"
    ] == payload["dp_int8"]


# -- TD117: the injected unintended reshard ----------------------------------


def test_td117_injected_bad_in_shardings_caught():
    from tpu_dist.comm import mesh as mesh_lib

    m = mesh_lib.data_parallel_mesh()
    inj = shardlint.injected_bad_zero1(m)
    report, violations = shardlint.shard_case(
        "zero1_sgd", m, step_override=inj
    )
    rules = {v.rule for v in violations}
    assert "TD117" in rules, [v.format_text() for v in violations]
    td117 = [v for v in violations if v.rule == "TD117"]
    # the finding names op kind, bytes, and the replica groups involved
    assert any("all-gather" in v.message for v in td117)
    assert any("replica_groups" in v.message or "B" in v.message
               for v in td117)
    assert report["verdict"]["agree"] is False


def test_td117_gspmd_family_kind_gate():
    ops = [
        HLOCollective(
            kind="collective-permute", shape="f32[64]", dtype="f32",
            elems=64, wire_bytes=256, int_bytes=0, float_bytes=256,
            replica_groups="{{0,1}}", channel_id=9, op_name="x",
            source="", computation="main", in_loop=False, loop_trips=1,
        )
    ]
    vs = shardlint.check_expected_kinds(
        "fsdp", ops, ("all-reduce", "all-gather", "reduce-scatter")
    )
    assert [v.rule for v in vs] == ["TD117"]
    assert "collective-permute" in vs[0].message


# -- the model-parallel + gspmd + serve families -----------------------------


def test_extended_families_clean():
    report, violations = shardlint.shard_all(
        names=["fsdp", "tp_vit", "sp_vit", "serve_eval"]
    )
    assert report["skips"] == {}
    assert violations == [], [v.format_text() for v in violations]
    fams = report["families"]
    # GSPMD inserted real collectives for fsdp even though the jaxpr
    # predicts none — the kind gate passed and the bytes are reported
    assert fams["fsdp"]["hlo"]["bytes"] > 0
    assert fams["fsdp"]["verdict"]["skipped_td116"]
    # ring attention: the permutes live INSIDE the ring scan and the
    # loop-trip pricing still matches the jaxpr model exactly
    sp_ops = fams["sp_vit"]["collectives"]
    assert any(
        o["kind"] == "collective-permute" and o["in_loop"] for o in sp_ops
    )
    # the serve forward step carries only the metric reduces
    assert set(fams["serve_eval"]["hlo"]["by_kind"]) == {"all-reduce"}


# -- shard_report.json: schema-pinned round-trip -----------------------------


def test_shard_report_roundtrip(tmp_path):
    report, _ = shardlint.build_shard_report(names=["dp_sgd"])
    path = str(tmp_path / "shard_report.json")
    shardlint.save_shard_report(report, path)
    loaded = shardlint.load_shard_report(path)
    assert loaded["schema"] == shardlint.SCHEMA
    fam = loaded["families"]["dp_sgd"]
    assert fam["hlo"]["bytes"] == report["families"]["dp_sgd"]["hlo"]["bytes"]
    # planner-facing keys present
    for key in ("collectives", "hbm", "cost", "predicted_step", "verdict"):
        assert key in fam
    # a FOREIGN schema tag is a typed, loud error (a newer
    # shard_report_vN is tolerated instead — see
    # test_planner.py::test_shard_report_newer_schema_tolerated_with_count)
    bad = dict(loaded, schema="plan_report_v1")
    bad_path = str(tmp_path / "bad.json")
    with open(bad_path, "w") as f:
        json.dump(bad, f)
    with pytest.raises(ShardReportError, match="schema"):
        shardlint.load_shard_report(bad_path)
    # a family entry missing planner keys is equally loud
    broken = json.loads(json.dumps(loaded))
    del broken["families"]["dp_sgd"]["predicted_step"]
    broken_path = str(tmp_path / "broken.json")
    with open(broken_path, "w") as f:
        json.dump(broken, f)
    with pytest.raises(ShardReportError, match="missing"):
        shardlint.load_shard_report(broken_path)


def test_predicted_step_time_calibration():
    from tpu_dist.obs import costmodel

    cost = {"flops_per_step": 2e9, "bytes_per_step": 1e8}
    gauges = {
        "cost.calibration_flops_per_s": 1e12,
        "cost.calibration_bytes_per_s": 1e10,
        "cost.calibration_overlap_frac": 0.5,
    }
    out = costmodel.predicted_step_time(
        cost, wire_bytes=10**7, gauges=gauges, n_devices=8
    )
    assert out["rate_source"] == "calibrated"
    assert out["compute_s"] == pytest.approx(2e-3)
    assert out["memory_s"] == pytest.approx(1e-2)
    # comm is half-hidden by the measured overlap
    assert out["predicted_step_s"] == pytest.approx(1e-2 + 0.5e-3)
    # no gauges, no chip peak (CPU): nothing priced, or spec-sheet fallback
    none = costmodel.predicted_step_time(
        {}, wire_bytes=None, gauges={}, n_devices=8
    )
    assert none == {}
    peaked = costmodel.predicted_step_time(
        cost, gauges={}, n_devices=2, peak=1e12
    )
    assert peaked["rate_source"] == "spec_peak"
    assert peaked["predicted_step_s"] == pytest.approx(1e-3)


def test_lower_and_compile_is_cached():
    import jax
    import jax.numpy as jnp

    from tpu_dist.obs import costmodel

    jitted = jax.jit(lambda x: x + 1.0)
    x = jnp.ones((8,))
    l1, c1 = costmodel.lower_and_compile(jitted, x)
    l2, c2 = costmodel.lower_and_compile(jitted, x)
    assert c1 is c2 and l1 is l2
    # a different signature is a different executable
    _, c3 = costmodel.lower_and_compile(jitted, jnp.ones((4,)))
    assert c3 is not c1


# -- one source of truth: RULES registry == docs table == CLI JSON -----------


def test_rules_registry_matches_docs_table():
    """Every rule in RULES has a `### TDxxx \\`name\\`` section in
    docs/analysis.md and vice versa — a new rule cannot land
    half-registered (the CLI JSON enumerates the same registry)."""
    doc = open(os.path.join(REPO, "docs", "analysis.md")).read()
    doc_rules = dict(re.findall(r"^### (TD\d{3}) `([\w-]+)`", doc, re.M))
    assert set(doc_rules) == set(RULES), (
        "docs/analysis.md sections vs RULES registry: "
        f"doc-only={sorted(set(doc_rules) - set(RULES))} "
        f"registry-only={sorted(set(RULES) - set(doc_rules))}"
    )
    for rid, rule in RULES.items():
        assert doc_rules[rid] == rule.name, (
            f"{rid}: doc name {doc_rules[rid]!r} != registry {rule.name!r}"
        )


def test_cli_json_enumerates_full_registry():
    import subprocess
    import sys

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, "-m", "tpu_dist.analysis", "--no-jaxpr",
         "--format", "json", "tpu_dist/analysis/rules.py"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    out = json.loads(r.stdout)
    ids = [e["id"] for e in out["rules"]]
    assert ids == sorted(RULES)
    assert {"TD001", "TD008", "TD104", "TD116", "TD117"} <= set(ids)


# -- the compare gate knows the new metric -----------------------------------


def test_hlo_wire_bytes_gates_as_regression():
    from tpu_dist.obs import compare

    assert compare.direction_of("hlo_wire_bytes_per_step") == ("lower", 0.0)
    assert any(
        f == "hlo_wire_bytes_per_step" for f, _, _ in compare.BENCH_FIELDS
    )
    # higher compiled-comm bytes on the candidate side REGRESSES...
    base = {"m": {"metric": "m", "hlo_wire_bytes_per_step": 1000}}
    cand = {"m": {"metric": "m", "hlo_wire_bytes_per_step": 1200}}
    res = compare.compare_bench(base, cand, threshold=0.05)
    rows = {r["metric"]: r for r in res["rows"]}
    assert rows["m.hlo_wire_bytes_per_step"]["verdict"] == "REGRESSED"
    # ...and fewer bytes is an improvement, never flagged
    res = compare.compare_bench(cand, base, threshold=0.05)
    rows = {r["metric"]: r for r in res["rows"]}
    assert rows["m.hlo_wire_bytes_per_step"]["verdict"] == "ok"
