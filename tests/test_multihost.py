"""Multi-host path: 2 processes × 4 devices, real jax.distributed rendezvous.

The TPU-world equivalent of launching the reference with
``torch.distributed.launch --nproc_per_node=2`` (SURVEY §2.2 N8): the
coordinator replaces the TCP store, each process owns its local devices and
feeds its data shard, and the replicated state must come out identical.
"""

import os
import socket
import subprocess
import sys


_WORKER = os.path.join(os.path.dirname(__file__), "_mp_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_training_agrees():
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""  # skip TPU plugin registration
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo_root  # also drops the TPU sitecustomize
    env.pop("XLA_FLAGS", None)

    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, coord, "2", str(i)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=240)
        outs.append(out)
        assert p.returncode == 0, out

    results, fused = {}, {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT"):
                _, pid, loss, p0 = line.split()
                results[pid] = (loss, p0)
            elif line.startswith("FUSED"):
                _, pid, loss = line.split()
                fused[pid] = loss
    assert set(results) == {"0", "1"}, outs
    # both hosts see the same reduced loss and identical replicated params
    assert results["0"] == results["1"], results
    # fused device-resident epoch also agrees across hosts
    assert set(fused) == {"0", "1"}, outs
    assert fused["0"] == fused["1"], fused


def test_two_process_tensor_parallel_matches_single_process():
    """2 hosts × 4 devices, tp=2 on a host-major [data=4, model=2] mesh
    (VERDICT r1 #6): every tp group intra-host, workers agree with each
    other AND with the same training run on a single-process 8-device mesh.
    """
    _WORKER_TP = os.path.join(os.path.dirname(__file__), "_mp_worker_tp.py")
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo_root
    env.pop("XLA_FLAGS", None)

    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER_TP, coord, "2", str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=repo_root,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=240)
        outs.append(out)
        assert p.returncode == 0, out

    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("TPRESULT"):
                _, pid, loss, fp_rep, fp_tp = line.split()
                results[pid] = (loss, fp_rep, fp_tp)
    assert set(results) == {"0", "1"}, outs
    assert results["0"] == results["1"], results

    # single-process reference on this test process's own 8-device mesh
    from tests._mp_worker_tp import run_tp_training

    ref_loss, ref_rep, ref_tp = run_tp_training()
    loss, fp_rep, fp_tp = (float(v) for v in results["0"])
    assert abs(loss - ref_loss) < 1e-4, (loss, ref_loss)
    assert abs(fp_rep - ref_rep) < 1e-4, (fp_rep, ref_rep)
    assert abs(fp_tp - ref_tp) < 1e-3, (fp_tp, ref_tp)
