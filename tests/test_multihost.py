"""Multi-host path: 2 processes × 4 devices, real jax.distributed rendezvous.

The TPU-world equivalent of launching the reference with
``torch.distributed.launch --nproc_per_node=2`` (SURVEY §2.2 N8): the
coordinator replaces the TCP store, each process owns its local devices and
feeds its data shard, and the replicated state must come out identical.
"""

import os
import pytest
import socket
import subprocess
import sys

_HERE = os.path.dirname(__file__)
_REPO_ROOT = os.path.dirname(os.path.abspath(_HERE))

# set by the first test that discovers this jaxlib's CPU backend cannot run
# cross-process collectives (one mutable cell, module-session scope)
_NO_MP_CPU = [False]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch_workers(worker_script: str, result_prefix: str, nprocs: int = 2,
                    extra_args: tuple = ()):
    """Fan out ``worker_script`` over ``nprocs`` rendezvoused processes and
    parse its ``<result_prefix> <pid> <fields...>`` lines.

    Returns ``{pid: (fields...)}`` with every process's result; asserts all
    workers exited 0. One place owns the CPU-forcing env recipe (empty
    PALLAS_AXON_POOL_IPS skips the TPU plugin; PYTHONPATH drops the TPU
    sitecustomize) so a future env fix lands once, not per-test."""
    if _NO_MP_CPU[0]:
        pytest.skip("CPU backend lacks multiprocess collectives in this jaxlib")
    worker = os.path.join(_HERE, worker_script)
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""  # skip TPU plugin registration
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO_ROOT  # also drops the TPU sitecustomize
    env.pop("XLA_FLAGS", None)

    procs = [
        subprocess.Popen(
            [sys.executable, worker, coord, str(nprocs), str(i), *extra_args],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=_REPO_ROOT,
        )
        for i in range(nprocs)
    ]
    outs = []
    failed = []
    for p in procs:
        out, _ = p.communicate(timeout=240)
        outs.append(out)
        if p.returncode != 0:
            failed.append(out)
    if failed:
        if any(
            "Multiprocess computations aren't implemented on the CPU backend"
            in out
            for out in failed
        ):
            # this jaxlib's CPU backend has no cross-process collectives at
            # all (newer jaxlibs route them through gloo) — environmental,
            # not a code failure; remember so sibling tests skip without
            # paying the two-process boot cost again
            _NO_MP_CPU[0] = True
            pytest.skip("CPU backend lacks multiprocess collectives in this jaxlib")
        raise AssertionError(failed[0])

    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith(result_prefix + " "):
                fields = line.split()
                results[fields[1]] = tuple(fields[2:])
    assert set(results) == {str(i) for i in range(nprocs)}, outs
    return results, outs


def test_two_process_training_agrees():
    results, outs = _launch_workers("_mp_worker.py", "RESULT")
    # both hosts see the same reduced loss and identical replicated params
    assert results["0"] == results["1"], results
    # fused device-resident epoch also agrees across hosts
    fused, _ = {}, None
    for out in outs:
        for line in out.splitlines():
            if line.startswith("FUSED "):
                _, pid, loss = line.split()
                fused[pid] = loss
    assert set(fused) == {"0", "1"}, outs
    assert fused["0"] == fused["1"], fused


def test_two_process_tensor_parallel_matches_single_process():
    """2 hosts × 4 devices, tp=2 on a host-major [data=4, model=2] mesh
    (VERDICT r1 #6): every tp group intra-host, workers agree with each
    other AND with the same training run on a single-process 8-device mesh.
    """
    results, _ = _launch_workers("_mp_worker_tp.py", "TPRESULT")
    assert results["0"] == results["1"], results

    # single-process reference on this test process's own 8-device mesh
    from tests._mp_worker_tp import run_tp_training

    ref_loss, ref_rep, ref_tp = run_tp_training()
    loss, fp_rep, fp_tp = (float(v) for v in results["0"])
    assert abs(loss - ref_loss) < 1e-4, (loss, ref_loss)
    assert abs(fp_rep - ref_rep) < 1e-4, (fp_rep, ref_rep)
    assert abs(fp_tp - ref_tp) < 1e-3, (fp_tp, ref_tp)


def test_two_process_expert_parallel_matches_single_process():
    """2 hosts × 4 devices, ep=2 on a host-major [data=4, expert=2] mesh:
    every expert group (and its all_to_all dispatch) intra-host; workers
    agree with each other AND with the same run on a single-process
    8-device mesh."""
    results, _ = _launch_workers("_mp_worker_ep.py", "EPRESULT")
    assert results["0"] == results["1"], results

    # single-process reference on this test process's own 8-device mesh
    from tests._mp_worker_ep import run_ep_training

    ref_loss, ref_rep, ref_ep = run_ep_training()
    loss, fp_rep, fp_ep = (float(v) for v in results["0"])
    assert abs(loss - ref_loss) < 1e-4, (loss, ref_loss)
    assert abs(fp_rep - ref_rep) < 1e-4, (fp_rep, ref_rep)
    assert abs(fp_ep - ref_ep) < 1e-3, (fp_ep, ref_ep)


def test_two_process_pp_tp_matches_single_process():
    """2 hosts × 4 devices, pp=2 × tp=2 on a host-major
    [data=2, pipe=2, model=2] mesh (the Megatron layout): the stage ring's
    ppermute AND each block's TP psums stay intra-host while the data axis
    crosses processes. Workers agree with each other AND with the same run
    on a single-process 8-device mesh."""
    results, _ = _launch_workers("_mp_worker_pp_tp.py", "PPTPRESULT")
    assert results["0"] == results["1"], results

    from tests._mp_worker_pp_tp import run_pp_tp_training

    ref_loss, ref_rep, ref_blk = run_pp_tp_training()
    loss, fp_rep, fp_blk = (float(v) for v in results["0"])
    assert abs(loss - ref_loss) < 1e-4, (loss, ref_loss)
    assert abs(fp_rep - ref_rep) < 1e-4, (fp_rep, ref_rep)
    assert abs(fp_blk - ref_blk) < 1e-3, (fp_blk, ref_blk)


def test_two_process_ring_flash_sp_matches_single_process():
    """2 hosts × 4 devices, sp=4 RING-FLASH on a host-major [data=2, seq=4]
    mesh: the ring's ppermute neighborhood stays intra-host while the data
    axis crosses processes; the Pallas local tiles (interpret mode) run
    the full ring-flash composition across a real jax.distributed
    rendezvous. Workers agree with each other AND with the same training
    run on a single-process 8-device mesh."""
    results, _ = _launch_workers("_mp_worker_sp.py", "SPRESULT")
    assert results["0"] == results["1"], results

    from tests._mp_worker_sp import run_sp_training

    ref_loss, ref_fp = run_sp_training()
    loss, fp = (float(v) for v in results["0"])
    assert abs(loss - ref_loss) < 1e-4, (loss, ref_loss)
    assert abs(fp - ref_fp) < 1e-3, (fp, ref_fp)


def test_two_process_sharded_ckpt_no_gather(tmp_path):
    """2 hosts × 4 devices, params P('data') over the global mesh: each
    process writes ONLY its own 1/2 of the sharded leaves (byte-checked in
    the worker — the no-gather-at-save property), the rank-0 manifest
    commits, and a cross-process overlap-only restore hands every process
    its partition back, equal to the original values."""
    results, _ = _launch_workers(
        "_mp_worker_ckpt.py", "CKRESULT", extra_args=(str(tmp_path),)
    )
    assert results["0"] == results["1"], results
    # exactly two shard files + one manifest on the shared dir
    names = sorted(os.listdir(tmp_path))
    assert names == [
        "ckpt_5.manifest.json",
        "ckpt_5.shard0of2.npz",
        "ckpt_5.shard1of2.npz",
    ], names
