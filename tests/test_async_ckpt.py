"""AsyncCheckpointer (ckpt/checkpoint.py): background writes publish the
same bytes as the sync path, in order, with errors surfaced — never lost."""

import numpy as np
import pytest

from tpu_dist import ckpt as ckpt_lib
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tests.helpers import TinyMLP

import jax


def _state(seed=0):
    model = TinyMLP()
    params, st = model.init(jax.random.PRNGKey(seed))
    return TrainState.create(params, st, SGD())


def test_async_save_matches_sync(tmp_path):
    state = _state()
    sync_dir, async_dir = tmp_path / "sync", tmp_path / "async"
    ckpt_lib.save(str(sync_dir), state, 3, extra_meta={"pp": 1})

    ac = ckpt_lib.AsyncCheckpointer()
    path = ac.save(str(async_dir), state, 3, extra_meta={"pp": 1})
    ac.wait()

    with np.load(sync_dir / "ckpt_3.npz") as a, np.load(path) as b:
        assert set(a.files) == set(b.files)
        for k in a.files:
            np.testing.assert_array_equal(a[k], b[k])
    assert ckpt_lib.read_meta(path)["epoch"] == 3
    assert ckpt_lib.read_meta(path)["pp"] == 1


def test_async_keep_last_prunes_in_order(tmp_path):
    state = _state()
    ac = ckpt_lib.AsyncCheckpointer()
    for e in range(4):
        ac.save(str(tmp_path), state, e, keep_last=2)
    ac.wait()
    found = ckpt_lib.latest_checkpoint(str(tmp_path))
    assert found is not None and found[1] == 3
    import os

    kept = sorted(n for n in os.listdir(tmp_path) if n.startswith("ckpt_"))
    assert kept == ["ckpt_2.npz", "ckpt_3.npz"]


def test_async_save_best_roundtrip(tmp_path):
    state = _state()
    ac = ckpt_lib.AsyncCheckpointer()
    ac.save_best(str(tmp_path), state, 5, 73.2)
    ac.wait()
    meta = ckpt_lib.read_meta(str(tmp_path / "ckpt_best.npz"))
    assert meta["epoch"] == 5 and abs(meta["metric"] - 73.2) < 1e-9
    restored = ckpt_lib.restore(str(tmp_path / "ckpt_best.npz"), _state(seed=1))
    for a, b in zip(
        jax.tree_util.tree_leaves(restored.params),
        jax.tree_util.tree_leaves(state.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_async_error_surfaces_on_wait(tmp_path):
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("file in the way")
    ac = ckpt_lib.AsyncCheckpointer()
    ac.save(str(blocker), _state(), 0)  # writer thread will fail on makedirs
    with pytest.raises(Exception):
        ac.wait()
    ac.wait()  # error is consumed once; subsequent waits are clean


@pytest.mark.slow  # >10s e2e: excluded from the timed tier-1 gate; the
# quick slice keeps a fast representative of this subsystem in the gate
def test_trainer_async_ckpt_e2e(tmp_path):
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer, register_model
    from tests.helpers import tiny_resnet

    register_model("tiny_resnet_ack", lambda num_classes=10: tiny_resnet(num_classes))
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_ack", num_classes=10,
        batch_size=64, epochs=1, steps_per_epoch=2, log_every=10,
        eval_every=1, save_every=1, async_ckpt=True, ckpt_dir=str(tmp_path),
    )
    t = Trainer(cfg)
    t.fit(1)
    # fit() waited: files are fully published, resumable immediately
    assert (tmp_path / "ckpt_0.npz").exists()
    assert (tmp_path / "ckpt_best.npz").exists()
    t2 = Trainer(cfg.replace(resume=True, epochs=2))
    assert t2.start_epoch == 1
    for a, b in zip(
        jax.tree_util.tree_leaves(t.state.params),
        jax.tree_util.tree_leaves(t2.state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)
