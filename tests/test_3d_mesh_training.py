"""3-D DP×TP×SP training: Megatron sharding + ring attention on one mesh."""

import jax
import numpy as np
import pytest

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.config import TrainConfig
from tpu_dist.nn.vit import ViTDef
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tpu_dist.train.step import make_train_step
from tpu_dist.train.trainer import Trainer


@pytest.mark.slow  # tier-1 budget (ISSUE 18): gates in analysis.yml
def test_dp_tp_sp_training_matches_single_device():
    from jax.sharding import NamedSharding

    model = ViTDef(image_size=32, patch_size=4, dim=32, depth=2, heads=4, num_classes=5)
    opt = SGD()
    mesh3d = mesh_lib.device_mesh([2, 2, 2], ["data", "model", "seq"])
    mesh1 = mesh_lib.device_mesh([1], ["data"], jax.devices()[:1])
    specs = model.tp_param_specs("model")

    params, s = model.init(jax.random.PRNGKey(0))
    st = TrainState.create(params, s, opt)
    place = lambda tree: jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh3d, spec)), tree, specs
    )
    s_3d = TrainState(
        params=place(st.params),
        bn_state=jax.device_put(st.bn_state, mesh_lib.replicated(mesh3d)),
        opt_state=place(st.opt_state),
        step=jax.device_put(st.step, mesh_lib.replicated(mesh3d)),
    )
    s_1 = jax.device_put(st, mesh_lib.replicated(mesh1))

    step_3d = make_train_step(
        model.apply, opt, mesh3d, sync_bn=False, donate=False,
        tp_axis="model", seq_axis="seq", param_specs=specs,
    )
    step_1 = make_train_step(model.apply, opt, mesh1, sync_bn=False, donate=False)

    rng = np.random.default_rng(0)
    for _ in range(3):
        x = rng.normal(size=(8, 32, 32, 3)).astype(np.float32)
        y = rng.integers(0, 5, 8).astype(np.int32)
        s_3d, m3 = step_3d(
            s_3d, mesh_lib.shard_batch(mesh3d, x), mesh_lib.shard_batch(mesh3d, y), 0.05
        )
        s_1, m1 = step_1(
            s_1, mesh_lib.shard_batch(mesh1, x), mesh_lib.shard_batch(mesh1, y), 0.05
        )

    np.testing.assert_allclose(float(m3["loss"]), float(m1["loss"]), rtol=1e-4)
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(s_3d.params)),
        jax.tree_util.tree_leaves(jax.device_get(s_1.params)),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5)


@pytest.mark.slow  # tier-1 budget (ISSUE 17): gates in analysis.yml
def test_trainer_3d_e2e():
    cfg = TrainConfig(
        dataset="synthetic", model="vit_tiny", num_classes=10, batch_size=16,
        epochs=1, steps_per_epoch=2, log_every=1, lr=0.05, eval_every=1,
        sp=2, tp=2, sync_bn=False, synthetic_n=160,
    )
    t = Trainer(cfg)
    assert t.n_data == 2 and t.n_devices == 8
    assert t.mesh.shape == {"data": 2, "model": 2, "seq": 2}
    out = t.fit()
    assert np.isfinite(out["loss"]) and "val_top1" in out


def test_trainer_still_rejects_other_combos():
    import pytest

    with pytest.raises(ValueError, match="only sp\\+tp"):
        Trainer(TrainConfig(dataset="synthetic", model="vit_moe_tiny", ep=2, pp=2,
                            synthetic_n=160))


def test_dp_tp_sp_ulysses_training_matches_single_device():
    """Same 3-D equivalence with the all_to_all (ulysses) SP strategy: each
    TP shard's 2 local heads redistribute over the 2-way seq axis."""
    from jax.sharding import NamedSharding

    model = ViTDef(image_size=32, patch_size=4, dim=32, depth=2, heads=4, num_classes=5)
    opt = SGD()
    mesh3d = mesh_lib.device_mesh([2, 2, 2], ["data", "model", "seq"])
    mesh1 = mesh_lib.device_mesh([1], ["data"], jax.devices()[:1])
    specs = model.tp_param_specs("model")

    params, s = model.init(jax.random.PRNGKey(0))
    st = TrainState.create(params, s, opt)
    place = lambda tree: jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh3d, spec)), tree, specs
    )
    s_3d = TrainState(
        params=place(st.params),
        bn_state=jax.device_put(st.bn_state, mesh_lib.replicated(mesh3d)),
        opt_state=place(st.opt_state),
        step=jax.device_put(st.step, mesh_lib.replicated(mesh3d)),
    )
    s_1 = jax.device_put(st, mesh_lib.replicated(mesh1))

    step_3d = make_train_step(
        model.apply, opt, mesh3d, sync_bn=False, donate=False,
        tp_axis="model", seq_axis="seq", param_specs=specs,
        model_kwargs={"sp_mode": "ulysses"},
    )
    step_1 = make_train_step(model.apply, opt, mesh1, sync_bn=False, donate=False)

    rng = np.random.default_rng(2)
    for _ in range(3):
        x = rng.normal(size=(8, 32, 32, 3)).astype(np.float32)
        y = rng.integers(0, 5, 8).astype(np.int32)
        s_3d, m3 = step_3d(
            s_3d, mesh_lib.shard_batch(mesh3d, x), mesh_lib.shard_batch(mesh3d, y), 0.05
        )
        s_1, m1 = step_1(
            s_1, mesh_lib.shard_batch(mesh1, x), mesh_lib.shard_batch(mesh1, y), 0.05
        )

    np.testing.assert_allclose(float(m3["loss"]), float(m1["loss"]), rtol=1e-4)
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(s_3d.params)),
        jax.tree_util.tree_leaves(jax.device_get(s_1.params)),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5)
