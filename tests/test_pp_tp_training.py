"""End-to-end PP×TP training (DP×PP×TP — the Megatron layout: tensor
parallelism inside each pipeline stage).

Beyond the reference's scope (SURVEY §2.3: no model parallelism anywhere).
Pins: (a) the combined layout trains to the same parameters as a single
device, (b) the stacked block leaves really shard over BOTH the pipe and
model axes, (c) the Trainer CLI path (--pp + --tp) wires it end to end.
"""

import jax
import numpy as np
import pytest

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.config import TrainConfig
from tpu_dist.nn.vit_pp import ViTPipelineDef
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tpu_dist.train.step import make_train_step
from tpu_dist.train.trainer import Trainer


def _model():
    return ViTPipelineDef(image_size=16, patch_size=4, dim=32, depth=4, heads=4,
                          num_classes=5)


def test_dp_pp_tp_training_matches_single_device():
    from jax.sharding import NamedSharding

    model = _model()
    opt = SGD()
    mesh3d = mesh_lib.device_mesh([2, 2, 2], ["data", "pipe", "model"])
    mesh1 = mesh_lib.device_mesh([1], ["data"], jax.devices()[:1])
    specs = model.pp_tp_param_specs("pipe", "model")

    params, s = model.init(jax.random.PRNGKey(0))
    st = TrainState.create(params, s, opt)
    place = lambda tree: jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh3d, spec)),
        tree, specs,
    )
    s_pt = TrainState(
        params=place(st.params),
        bn_state=jax.device_put(st.bn_state, mesh_lib.replicated(mesh3d)),
        opt_state=place(st.opt_state),
        step=jax.device_put(st.step, mesh_lib.replicated(mesh3d)),
    )
    s_1 = jax.device_put(st, mesh_lib.replicated(mesh1))

    # block leaves must live on all 8 devices, split over pipe AND model
    qkv_w = s_pt.params["blocks"]["qkv"]["w"]
    assert len(qkv_w.sharding.device_set) == 8
    assert qkv_w.sharding.shard_shape(qkv_w.shape) == (2, 32, 48)  # depth/2, d, 3d/2

    step_pt = make_train_step(
        model.apply, opt, mesh3d, sync_bn=False, donate=False,
        pp_axis="pipe", tp_axis="model", param_specs=specs,
    )
    step_1 = make_train_step(model.apply, opt, mesh1, sync_bn=False, donate=False)

    rng = np.random.default_rng(0)
    for _ in range(3):
        x = rng.normal(size=(8, 16, 16, 3)).astype(np.float32)
        y = rng.integers(0, 5, 8).astype(np.int32)
        s_pt, m_pt = step_pt(
            s_pt, mesh_lib.shard_batch(mesh3d, x), mesh_lib.shard_batch(mesh3d, y), 0.05
        )
        s_1, m_1 = step_1(
            s_1, mesh_lib.shard_batch(mesh1, x), mesh_lib.shard_batch(mesh1, y), 0.05
        )

    np.testing.assert_allclose(float(m_pt["loss"]), float(m_1["loss"]), rtol=1e-4)
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(s_pt.params)),
        jax.tree_util.tree_leaves(jax.device_get(s_1.params)),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5)


def test_dp_pp_tp_with_grad_clip_matches_single_device():
    """Shard-aware global-norm clip under BOTH model axes (blocks leaves
    grouped by (pipe, model) in clip_grads — one psum over both)."""
    from jax.sharding import NamedSharding

    model = _model()
    opt = SGD()
    mesh3d = mesh_lib.device_mesh([2, 2, 2], ["data", "pipe", "model"])
    mesh1 = mesh_lib.device_mesh([1], ["data"], jax.devices()[:1])
    specs = model.pp_tp_param_specs("pipe", "model")
    params, s = model.init(jax.random.PRNGKey(1))
    st = TrainState.create(params, s, opt)
    place = lambda tree: jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh3d, spec)),
        tree, specs,
    )
    s_pt = TrainState(place(st.params),
                      jax.device_put(st.bn_state, mesh_lib.replicated(mesh3d)),
                      place(st.opt_state),
                      jax.device_put(st.step, mesh_lib.replicated(mesh3d)))
    s_1 = jax.device_put(st, mesh_lib.replicated(mesh1))
    # tight clip so the scale actually engages
    step_pt = make_train_step(model.apply, opt, mesh3d, sync_bn=False,
                              donate=False, pp_axis="pipe", tp_axis="model",
                              param_specs=specs, grad_clip_norm=0.1)
    step_1 = make_train_step(model.apply, opt, mesh1, sync_bn=False,
                             donate=False, grad_clip_norm=0.1)
    rng = np.random.default_rng(2)
    x = rng.normal(size=(8, 16, 16, 3)).astype(np.float32)
    y = rng.integers(0, 5, 8).astype(np.int32)
    s_pt, _ = step_pt(s_pt, mesh_lib.shard_batch(mesh3d, x),
                      mesh_lib.shard_batch(mesh3d, y), 0.05)
    s_1, _ = step_1(s_1, mesh_lib.shard_batch(mesh1, x),
                    mesh_lib.shard_batch(mesh1, y), 0.05)
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(s_pt.params)),
        jax.tree_util.tree_leaves(jax.device_get(s_1.params)),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5)


@pytest.mark.slow  # >10s e2e: excluded from the timed tier-1 gate; the
# quick slice keeps a fast representative of this subsystem in the gate
def test_trainer_pp_tp_e2e_with_eval(tmp_path):
    cfg = TrainConfig(
        dataset="synthetic", model="vit_pp_tiny", num_classes=10, batch_size=16,
        epochs=1, steps_per_epoch=2, log_every=1, lr=0.05, eval_every=1,
        pp=2, tp=2, sync_bn=False, synthetic_n=160, ckpt_dir=str(tmp_path),
        save_every=1,
    )
    t = Trainer(cfg)
    assert t.n_data == 2 and t.n_devices == 8
    assert tuple(t.mesh.axis_names) == ("data", "pipe", "model")
    out = t.fit()
    assert np.isfinite(out["loss"]) and "val_top1" in out

    t2 = Trainer(cfg.replace(resume=True, epochs=2))
    assert t2.start_epoch == 1
    blk_w = t2.state.params["blocks"]["qkv"]["w"]
    assert len(blk_w.sharding.device_set) == 8  # restored sharded over pipe×model
    assert np.isfinite(t2.fit()["loss"])


def test_interleaved_pp_tp_training_matches_single_device():
    """Interleave composes too: virtual stages (device-major chunk storage)
    × TP inside each chunk, on the same [data, pipe, model] mesh."""
    from jax.sharding import NamedSharding

    model = ViTPipelineDef(image_size=16, patch_size=4, dim=32, depth=8,
                           heads=4, num_classes=5, interleave=2, pp_stages=2)
    opt = SGD()
    mesh3d = mesh_lib.device_mesh([2, 2, 2], ["data", "pipe", "model"])
    mesh1 = mesh_lib.device_mesh([1], ["data"], jax.devices()[:1])
    specs = model.pp_tp_param_specs("pipe", "model")
    params, s = model.init(jax.random.PRNGKey(3))
    st = TrainState.create(params, s, opt)
    place = lambda tree: jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh3d, spec)),
        tree, specs,
    )
    s_pt = TrainState(place(st.params),
                      jax.device_put(st.bn_state, mesh_lib.replicated(mesh3d)),
                      place(st.opt_state),
                      jax.device_put(st.step, mesh_lib.replicated(mesh3d)))
    s_1 = jax.device_put(st, mesh_lib.replicated(mesh1))
    step_pt = make_train_step(model.apply, opt, mesh3d, sync_bn=False,
                              donate=False, pp_axis="pipe", tp_axis="model",
                              param_specs=specs,
                              model_kwargs={"n_microbatches": 2})
    step_1 = make_train_step(model.apply, opt, mesh1, sync_bn=False, donate=False)
    rng = np.random.default_rng(4)
    for _ in range(2):
        x = rng.normal(size=(8, 16, 16, 3)).astype(np.float32)
        y = rng.integers(0, 5, 8).astype(np.int32)
        s_pt, m_pt = step_pt(s_pt, mesh_lib.shard_batch(mesh3d, x),
                             mesh_lib.shard_batch(mesh3d, y), 0.05)
        s_1, m_1 = step_1(s_1, mesh_lib.shard_batch(mesh1, x),
                          mesh_lib.shard_batch(mesh1, y), 0.05)
    np.testing.assert_allclose(float(m_pt["loss"]), float(m_1["loss"]), rtol=1e-4)
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(s_pt.params)),
        jax.tree_util.tree_leaves(jax.device_get(s_1.params)),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5)


def test_trainer_tp_only_on_pipeline_model():
    """--tp without --pp on a vit_pp_* model: the stacked-block storage
    trains under pure Megatron TP (reviewer finding r5: the tp capability
    check passes for vit_pp now that apply takes tp_axis, so the specs
    must exist too)."""
    cfg = TrainConfig(
        dataset="synthetic", model="vit_pp_tiny", num_classes=10, batch_size=16,
        epochs=1, steps_per_epoch=2, log_every=1, lr=0.05, eval_every=0,
        tp=2, sync_bn=False, synthetic_n=160,
    )
    t = Trainer(cfg)
    qkv_w = t.state.params["blocks"]["qkv"]["w"]
    # vit_pp_tiny: depth 4 stacked (unsharded), dim 64, qkv out-dim
    # 3*64=192 split over tp=2
    assert qkv_w.shape == (4, 64, 192)
    assert qkv_w.sharding.shard_shape(qkv_w.shape) == (4, 64, 96)
    out = t.train_epoch(0)
    assert np.isfinite(out["loss"])


def test_trainer_rejects_unsupported_pp_combos():
    with pytest.raises(ValueError, match="may be combined"):
        Trainer(TrainConfig(dataset="synthetic", model="vit_pp_tiny",
                            pp=2, sp=2, batch_size=16, synthetic_n=160,
                            sync_bn=False))
    with pytest.raises(ValueError, match="may be combined"):
        Trainer(TrainConfig(dataset="synthetic", model="vit_moe_tiny",
                            ep=2, tp=2, batch_size=16, synthetic_n=160,
                            sync_bn=False))
