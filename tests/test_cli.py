"""CLI layer: flag parsing, presets, trainer wiring (SURVEY §1 L4)."""

import pytest

from tpu_dist.cli import (
    dataparallel,
    dataparallel_apex,
    distributed,
    distributed_apex,
    distributed_gradient_accumulation,
    distributed_mp,
    train,
)


def test_train_cli_constructs_trainer_and_runs_zero_epochs(capsys):
    # epochs=0: full CLI -> config -> Trainer init path without jit compiles
    train.main(["--epochs", "0", "--dataset", "synthetic", "--batch_size", "64"])
    out = capsys.readouterr().out
    assert "model=resnet18" in out and "devices=8" in out


def test_presets_set_their_flags(monkeypatch):
    seen = {}

    def fake_main(argv=None, **preset):
        seen["argv"] = list(argv or [])
        seen["preset"] = preset

    for mod, expect_preset, expect_argv in [
        (dataparallel, {}, []),
        (dataparallel_apex, {"bf16": True}, []),
        (distributed, {}, []),
        (distributed_mp, {}, ["--seed", "1"]),
        (distributed_apex, {"bf16": True}, ["--seed", "1"]),
        (
            distributed_gradient_accumulation,
            {"drop_last": True},
            ["--grad_accu_steps", "4"],
        ),
    ]:
        monkeypatch.setattr(mod, "_main", fake_main)
        mod.main([])
        assert seen["preset"] == expect_preset, mod.__name__
        assert seen["argv"] == expect_argv, mod.__name__


def test_seed_flag_not_overridden_by_preset(monkeypatch):
    seen = {}
    monkeypatch.setattr(distributed_mp, "_main", lambda argv=None, **p: seen.update(argv=argv))
    distributed_mp.main(["--seed", "7"])
    assert seen["argv"] == ["--seed", "7"]


def test_unknown_flag_fails_loud():
    with pytest.raises(SystemExit):
        train.main(["--definitely_not_a_flag"])


def test_backend_flag_xla_only():
    """BASELINE north star names `--backend=xla`; nccl/gloo get a pointed
    refusal, not a silent ignore."""
    import argparse

    import pytest

    from tpu_dist.config import add_reference_flags, config_from_args

    p = add_reference_flags(argparse.ArgumentParser())
    cfg = config_from_args(p.parse_args(["--backend", "xla"]))
    assert cfg is not None
    with pytest.raises(SystemExit, match="nccl"):
        config_from_args(p.parse_args(["--backend", "nccl"]))
