"""End-to-end sequence-parallel TRAINING (DP×SP) through make_train_step:
2×4 mesh with ring attention ≡ single-device training."""

import jax
import numpy as np
import pytest

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.nn.vit import ViTDef
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tpu_dist.train.step import make_train_step


def _model():
    return ViTDef(image_size=32, patch_size=4, dim=32, depth=2, heads=2, num_classes=5)


def _state(model, mesh):
    params, s = model.init(jax.random.PRNGKey(0))
    return jax.device_put(TrainState.create(params, s, SGD()), mesh_lib.replicated(mesh))


def test_dp_sp_training_matches_single_device():
    model = _model()
    opt = SGD()

    mesh2d = mesh_lib.device_mesh([2, 4], ["data", "seq"])
    mesh1 = mesh_lib.device_mesh([1], ["data"], jax.devices()[:1])

    step_sp = make_train_step(
        model.apply, opt, mesh2d, sync_bn=False, donate=False, seq_axis="seq"
    )
    step_1 = make_train_step(model.apply, opt, mesh1, sync_bn=False, donate=False)

    s_sp = _state(model, mesh2d)
    s_1 = _state(model, mesh1)

    rng = np.random.default_rng(0)
    for i in range(3):
        x = rng.normal(size=(8, 32, 32, 3)).astype(np.float32)
        y = rng.integers(0, 5, 8).astype(np.int32)
        xs = mesh_lib.shard_batch(mesh2d, x)
        ys = mesh_lib.shard_batch(mesh2d, y)
        s_sp, m_sp = step_sp(s_sp, xs, ys, 0.05)
        s_1, m_1 = step_1(
            s_1, mesh_lib.shard_batch(mesh1, x), mesh_lib.shard_batch(mesh1, y), 0.05
        )

    np.testing.assert_allclose(float(m_sp["loss"]), float(m_1["loss"]), rtol=1e-4)
    for a, b in zip(
        jax.tree_util.tree_leaves(s_sp.params), jax.tree_util.tree_leaves(s_1.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-5)


@pytest.mark.slow  # tier-1 budget (ISSUE 17): gates in analysis.yml
def test_trainer_sp_e2e():
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer

    cfg = TrainConfig(
        dataset="synthetic", model="vit_tiny", num_classes=10, batch_size=16,
        epochs=1, steps_per_epoch=2, log_every=1, lr=0.05, eval_every=1,
        sp=4, sync_bn=False, synthetic_n=160,
    )
    t = Trainer(cfg)
    assert t.n_data == 2 and t.n_devices == 8
    out = t.fit()  # train + distributed eval, both over the 2-D mesh
    assert np.isfinite(out["loss"])
    assert "val_top1" in out


def test_trainer_sp_rejects_non_sp_model():
    import pytest

    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer

    with pytest.raises(ValueError, match="sequence parallelism"):
        Trainer(TrainConfig(dataset="synthetic", model="resnet18", sp=4, synthetic_n=512))


def test_seq_axis_composes_with_zero1():
    """SP + ZeRO-1 weight-update sharding ≡ plain SP."""
    import jax.numpy as jnp

    from tpu_dist.train.step import init_sharded_opt_state

    model = _model()
    opt = SGD()
    mesh2d = mesh_lib.device_mesh([2, 4], ["data", "seq"])

    s_plain = _state(model, mesh2d)
    params, s = model.init(jax.random.PRNGKey(0))
    s_z1 = TrainState(
        params=jax.device_put(params, mesh_lib.replicated(mesh2d)),
        bn_state=jax.device_put(s, mesh_lib.replicated(mesh2d)),
        opt_state=init_sharded_opt_state(params, mesh2d),
        step=jax.device_put(jnp.zeros((), jnp.int32), mesh_lib.replicated(mesh2d)),
    )
    step_plain = make_train_step(
        model.apply, opt, mesh2d, sync_bn=False, donate=False, seq_axis="seq"
    )
    step_z1 = make_train_step(
        model.apply, opt, mesh2d, sync_bn=False, donate=False, seq_axis="seq",
        shard_weight_update=True,
    )
    rng = np.random.default_rng(0)
    for _ in range(2):
        x = mesh_lib.shard_batch(mesh2d, rng.normal(size=(8, 32, 32, 3)).astype(np.float32))
        y = mesh_lib.shard_batch(mesh2d, rng.integers(0, 5, 8).astype(np.int32))
        s_plain, mp = step_plain(s_plain, x, y, 0.05)
        s_z1, mz = step_z1(s_z1, x, y, 0.05)
    np.testing.assert_allclose(float(mp["loss"]), float(mz["loss"]), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(s_plain.params), jax.tree_util.tree_leaves(s_z1.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_dp_sp_ulysses_training_matches_single_device():
    """Same equivalence as the ring test, all_to_all strategy."""
    model = _model()
    opt = SGD()

    mesh2d = mesh_lib.device_mesh([4, 2], ["data", "seq"])  # heads=2 -> sp=2
    mesh1 = mesh_lib.device_mesh([1], ["data"], jax.devices()[:1])

    step_sp = make_train_step(
        model.apply, opt, mesh2d, sync_bn=False, donate=False, seq_axis="seq",
        model_kwargs={"sp_mode": "ulysses"},
    )
    step_1 = make_train_step(model.apply, opt, mesh1, sync_bn=False, donate=False)

    s_sp = _state(model, mesh2d)
    s_1 = _state(model, mesh1)

    rng = np.random.default_rng(1)
    for _ in range(3):
        x = rng.normal(size=(8, 32, 32, 3)).astype(np.float32)
        y = rng.integers(0, 5, 8).astype(np.int32)
        s_sp, m_sp = step_sp(
            s_sp, mesh_lib.shard_batch(mesh2d, x), mesh_lib.shard_batch(mesh2d, y), 0.05
        )
        s_1, m_1 = step_1(
            s_1, mesh_lib.shard_batch(mesh1, x), mesh_lib.shard_batch(mesh1, y), 0.05
        )

    np.testing.assert_allclose(float(m_sp["loss"]), float(m_1["loss"]), rtol=1e-4)
    for a, b in zip(
        jax.tree_util.tree_leaves(s_sp.params), jax.tree_util.tree_leaves(s_1.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-5)


@pytest.mark.slow  # tier-1 budget (ISSUE 18): gates in analysis.yml
def test_trainer_sp_ulysses_e2e():
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer

    cfg = TrainConfig(
        dataset="synthetic", model="vit_tiny", num_classes=10, batch_size=16,
        epochs=1, steps_per_epoch=2, log_every=1, lr=0.05, eval_every=1,
        sp=4, sp_mode="ulysses", sync_bn=False, synthetic_n=160,
    )
    t = Trainer(cfg)
    out = t.fit()
    assert np.isfinite(out["loss"])
    assert "val_top1" in out


def test_trainer_ulysses_rejects_indivisible_heads():
    import pytest

    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer

    # vit_tiny has 4 heads; sp=8 does not divide them
    with pytest.raises(ValueError, match="heads"):
        Trainer(TrainConfig(
            dataset="synthetic", model="vit_tiny", num_classes=10,
            batch_size=16, sp=8, sp_mode="ulysses", sync_bn=False,
            synthetic_n=160,
        ))


def test_trainer_3d_ulysses_heads_validation():
    """sp x tp: the ulysses check must use per-TP-shard heads."""
    import pytest

    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer

    base = dict(
        dataset="synthetic", model="vit_tiny", num_classes=10, batch_size=16,
        sync_bn=False, synthetic_n=160, sp_mode="ulysses",
    )
    # vit_tiny: 4 heads. tp=2 -> 2 local heads; sp=2 divides -> constructs
    Trainer(TrainConfig(**base, tp=2, sp=2))
    # tp=2 -> 2 local heads; sp=4 would need 8 global: clear early error
    with pytest.raises(ValueError, match="per-shard heads"):
        Trainer(TrainConfig(**{**base, "batch_size": 32}, tp=2, sp=4))


def test_dp_sp_ring_flash_training_matches_single_device():
    """DP×SP with the RING-FLASH composition (Pallas local tiles inside
    the K/V rotation, ops/flash_attention.py::ring_flash_attention) trains
    to the same parameters as single-device XLA attention."""
    model = _model()
    opt = SGD()

    mesh2d = mesh_lib.device_mesh([2, 4], ["data", "seq"])
    mesh1 = mesh_lib.device_mesh([1], ["data"], jax.devices()[:1])

    step_sp = make_train_step(
        model.apply, opt, mesh2d, sync_bn=False, donate=False, seq_axis="seq",
        model_kwargs={"attn_impl": "flash"},
    )
    step_1 = make_train_step(model.apply, opt, mesh1, sync_bn=False, donate=False)

    s_sp = _state(model, mesh2d)
    s_1 = _state(model, mesh1)

    rng = np.random.default_rng(2)
    for _ in range(3):
        x = rng.normal(size=(8, 32, 32, 3)).astype(np.float32)
        y = rng.integers(0, 5, 8).astype(np.int32)
        s_sp, m_sp = step_sp(
            s_sp, mesh_lib.shard_batch(mesh2d, x), mesh_lib.shard_batch(mesh2d, y), 0.05
        )
        s_1, m_1 = step_1(
            s_1, mesh_lib.shard_batch(mesh1, x), mesh_lib.shard_batch(mesh1, y), 0.05
        )

    np.testing.assert_allclose(float(m_sp["loss"]), float(m_1["loss"]), rtol=1e-4)
    for a, b in zip(
        jax.tree_util.tree_leaves(s_sp.params), jax.tree_util.tree_leaves(s_1.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-5)
