"""Distributed evaluation: exact counts, pad masking (fix of SURVEY §3.4)."""

import jax
import numpy as np

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tpu_dist.train.step import make_eval_step
from tests.helpers import TinyConvNet


def test_eval_sums_ignore_padding():
    model = TinyConvNet(num_classes=10)
    mesh = mesh_lib.data_parallel_mesh()
    params, bn = model.init(jax.random.PRNGKey(0))
    state = jax.device_put(
        TrainState.create(params, bn, SGD()), mesh_lib.replicated(mesh)
    )
    eval_step = make_eval_step(model.apply, mesh)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8, 8, 3)).astype(np.float32)
    y = rng.integers(0, 10, 64).astype(np.int32)

    full = np.ones(64, np.float32)
    half = np.concatenate([np.ones(32, np.float32), np.zeros(32, np.float32)])

    s_full = {k: float(v) for k, v in eval_step(
        state, *map(lambda a: mesh_lib.shard_batch(mesh, a), (x, y, full))).items()}
    s_half = {k: float(v) for k, v in eval_step(
        state, *map(lambda a: mesh_lib.shard_batch(mesh, a), (x, y, half))).items()}

    assert s_full["count"] == 64 and s_half["count"] == 32

    # masked half must equal evaluating only the first 32 (padded duplicates
    # contribute nothing) — this is exactly what the reference got wrong
    x32 = np.concatenate([x[:32], x[:32]])  # duplicates in padding slots
    y32 = np.concatenate([y[:32], y[:32]])
    s_dup = {k: float(v) for k, v in eval_step(
        state, *map(lambda a: mesh_lib.shard_batch(mesh, a), (x32, y32, half))).items()}
    np.testing.assert_allclose(s_dup["loss"], s_half["loss"], rtol=1e-5)
    assert s_dup["top1"] == s_half["top1"]


def test_eval_top1_matches_numpy():
    model = TinyConvNet(num_classes=10)
    mesh = mesh_lib.data_parallel_mesh()
    params, bn = model.init(jax.random.PRNGKey(0))
    state = jax.device_put(
        TrainState.create(params, bn, SGD()), mesh_lib.replicated(mesh)
    )
    eval_step = make_eval_step(model.apply, mesh)

    rng = np.random.default_rng(1)
    x = rng.normal(size=(64, 8, 8, 3)).astype(np.float32)
    y = rng.integers(0, 10, 64).astype(np.int32)
    logits, _ = model.apply(params, bn, x, train=False)
    expect_top1 = int((np.argmax(np.asarray(logits), -1) == y).sum())

    sums = eval_step(state, *map(lambda a: mesh_lib.shard_batch(mesh, a),
                                 (x, y, np.ones(64, np.float32))))
    assert int(float(sums["top1"])) == expect_top1
