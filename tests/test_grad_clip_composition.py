"""Grad-clip composes with every model-parallel axis (lifted walls).

Each test trains a few steps WITH an aggressively small clip norm (so the
clip is guaranteed active every step) under TP / EP / PP, and asserts the
resulting parameters are identical to a reference run without model
parallelism. The norm under model parallelism is computed shard-aware
(tpu_dist/train/step.py::clip_grads): sharded leaves contribute via one
psum over their model axes, replicated leaves locally.
"""

import pytest
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.config import TrainConfig
from tpu_dist.nn import functional as F
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tpu_dist.train.step import make_train_step
from tpu_dist.train.trainer import Trainer

CLIP = 0.05  # far below typical init grad norms -> clip active every step


def _place(tree, mesh, specs):
    return jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)), tree, specs
    )


def _sharded_state(st, mesh, specs):
    return TrainState(
        params=_place(st.params, mesh, specs),
        bn_state=jax.device_put(st.bn_state, mesh_lib.replicated(mesh)),
        opt_state=_place(st.opt_state, mesh, specs),
        step=jax.device_put(st.step, mesh_lib.replicated(mesh)),
    )


def _assert_params_match(a_state, b_params):
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(a_state.params)),
        jax.tree_util.tree_leaves(jax.device_get(b_params)),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5)


def test_grad_clip_under_tp_matches_single_device():
    from tpu_dist.nn.vit import ViTDef

    model = ViTDef(image_size=32, patch_size=4, dim=32, depth=2, heads=4, num_classes=5)
    opt = SGD()
    mesh2d = mesh_lib.device_mesh([2, 4], ["data", "model"])
    mesh1 = mesh_lib.device_mesh([1], ["data"], jax.devices()[:1])
    specs = model.tp_param_specs("model")

    params, s = model.init(jax.random.PRNGKey(0))
    st = TrainState.create(params, s, opt)
    s_tp = _sharded_state(st, mesh2d, specs)
    s_1 = jax.device_put(st, mesh_lib.replicated(mesh1))

    step_tp = make_train_step(
        model.apply, opt, mesh2d, sync_bn=False, donate=False,
        tp_axis="model", param_specs=specs, grad_clip_norm=CLIP,
    )
    step_1 = make_train_step(
        model.apply, opt, mesh1, sync_bn=False, donate=False, grad_clip_norm=CLIP
    )
    step_1_noclip = make_train_step(
        model.apply, opt, mesh1, sync_bn=False, donate=False
    )
    s_noclip = jax.device_put(st, mesh_lib.replicated(mesh1))

    rng = np.random.default_rng(0)
    for _ in range(3):
        x = rng.normal(size=(8, 32, 32, 3)).astype(np.float32)
        y = rng.integers(0, 5, 8).astype(np.int32)
        s_tp, _ = step_tp(
            s_tp, mesh_lib.shard_batch(mesh2d, x), mesh_lib.shard_batch(mesh2d, y), 0.05
        )
        s_1, _ = step_1(
            s_1, mesh_lib.shard_batch(mesh1, x), mesh_lib.shard_batch(mesh1, y), 0.05
        )
        s_noclip, _ = step_1_noclip(
            s_noclip, mesh_lib.shard_batch(mesh1, x), mesh_lib.shard_batch(mesh1, y), 0.05
        )

    _assert_params_match(s_tp, s_1.params)
    # sanity: the clip actually changed the trajectory
    diffs = [
        float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
        for a, b in zip(
            jax.tree_util.tree_leaves(jax.device_get(s_1.params)),
            jax.tree_util.tree_leaves(jax.device_get(s_noclip.params)),
        )
    ]
    assert max(diffs) > 1e-5, "clip norm never activated — test is vacuous"


def test_grad_clip_under_ep_matches_dense_reference():
    from tpu_dist.nn.vit_moe import ViTMoEDef

    model = ViTMoEDef(image_size=16, patch_size=4, dim=32, depth=1, heads=4,
                      n_experts=8, capacity_factor=8.0, num_classes=5)
    opt = SGD(momentum=0.9, weight_decay=0.0)
    mesh2d = mesh_lib.device_mesh([2, 4], ["data", "expert"])
    specs = model.ep_param_specs("expert")

    params, s = model.init(jax.random.PRNGKey(0))
    st = TrainState.create(params, s, opt)
    s_ep = _sharded_state(st, mesh2d, specs)
    step_ep = make_train_step(
        model.apply, opt, mesh2d, sync_bn=False, donate=False,
        ep_axis="expert", param_specs=specs, grad_clip_norm=CLIP,
    )

    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 16, 16, 3)).astype(np.float32)
    y = rng.integers(0, 5, 16).astype(np.int32)

    # host reference: mean of 8 shard losses, global-norm clip, plain SGD
    def ref_loss(p):
        tot = 0.0
        for i in range(8):
            logits, _ = model.apply(p, {}, jnp.asarray(x[i * 2: (i + 1) * 2]))
            tot = tot + F.cross_entropy(logits, jnp.asarray(y[i * 2: (i + 1) * 2]))
        return tot / 8

    def clip(g):
        sq = sum(jnp.sum(jnp.square(l)) for l in jax.tree_util.tree_leaves(g))
        scale = jnp.minimum(1.0, CLIP / jnp.maximum(jnp.sqrt(sq), 1e-12))
        return jax.tree_util.tree_map(lambda l: l * scale, g)

    ref_p, ref_b = params, opt.init(params)
    for _ in range(2):
        g = clip(jax.grad(ref_loss)(ref_p))
        ref_p, ref_b = opt.update(g, ref_b, ref_p, 0.05)

    xs = mesh_lib.shard_batch(mesh2d, x, ("data", "expert"))
    ys = mesh_lib.shard_batch(mesh2d, y, ("data", "expert"))
    for _ in range(2):
        s_ep, _ = step_ep(s_ep, xs, ys, 0.05)

    _assert_params_match(s_ep, ref_p)


def test_grad_clip_under_pp_matches_single_device():
    from tpu_dist.nn.vit_pp import ViTPipelineDef

    model = ViTPipelineDef(image_size=16, patch_size=4, dim=32, depth=4, heads=4,
                           num_classes=5)
    opt = SGD()
    mesh2d = mesh_lib.device_mesh([2, 4], ["data", "pipe"])
    mesh1 = mesh_lib.device_mesh([1], ["data"], jax.devices()[:1])
    specs = model.pp_param_specs("pipe")

    params, s = model.init(jax.random.PRNGKey(0))
    st = TrainState.create(params, s, opt)
    s_pp = _sharded_state(st, mesh2d, specs)
    s_1 = jax.device_put(st, mesh_lib.replicated(mesh1))

    step_pp = make_train_step(
        model.apply, opt, mesh2d, sync_bn=False, donate=False,
        pp_axis="pipe", param_specs=specs, grad_clip_norm=CLIP,
    )
    step_1 = make_train_step(
        model.apply, opt, mesh1, sync_bn=False, donate=False, grad_clip_norm=CLIP
    )

    rng = np.random.default_rng(0)
    for _ in range(3):
        x = rng.normal(size=(8, 16, 16, 3)).astype(np.float32)
        y = rng.integers(0, 5, 8).astype(np.int32)
        s_pp, _ = step_pp(
            s_pp, mesh_lib.shard_batch(mesh2d, x), mesh_lib.shard_batch(mesh2d, y), 0.05
        )
        s_1, _ = step_1(
            s_1, mesh_lib.shard_batch(mesh1, x), mesh_lib.shard_batch(mesh1, y), 0.05
        )

    _assert_params_match(s_pp, s_1.params)


@pytest.mark.slow  # >10s e2e: excluded from the timed tier-1 gate; the
# quick slice keeps a fast representative of this subsystem in the gate
def test_trainer_accepts_clip_with_model_parallelism():
    """The trainer-level walls are lifted too: tp/ep/pp + grad_clip_norm
    train a finite step end to end."""
    for kw in (
        dict(model="vit_tiny", tp=4),
        dict(model="vit_moe_tiny", ep=4),
        dict(model="vit_pp_tiny", pp=4),
    ):
        cfg = TrainConfig(
            dataset="synthetic", num_classes=10, batch_size=32, epochs=1,
            steps_per_epoch=2, log_every=1, eval_every=0, lr=0.05,
            sync_bn=False, synthetic_n=320, grad_clip_norm=1.0, **kw,
        )
        out = Trainer(cfg).train_epoch(0)
        assert np.isfinite(out["loss"]), kw
