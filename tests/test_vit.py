"""ViT family: shapes, training, sequence-parallel forward parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from tpu_dist.comm.compat import shard_map
from jax.sharding import PartitionSpec as P

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.nn.vit import ViTDef, vit_b16, vit_tiny
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tpu_dist.train.step import make_train_step


@pytest.mark.slow  # tier-1 budget (ISSUE 17): gates in analysis.yml
def test_vit_b16_param_count():
    # ViT-B/16 published size ≈ 86.6M (ImageNet-1k head, no cls token here)
    p, _ = vit_b16().init(jax.random.PRNGKey(0))
    n = sum(x.size for x in jax.tree_util.tree_leaves(p))
    assert 85e6 < n < 88e6, n


def test_vit_s16_param_count():
    from tpu_dist.nn.vit import vit_s16

    p, _ = vit_s16().init(jax.random.PRNGKey(0))
    n = sum(x.size for x in jax.tree_util.tree_leaves(p))
    # ViT-S/16 published ≈ 22M (cls-token variant); mean-pool variant close
    assert 20e6 < n < 23e6, n


@pytest.mark.slow  # tier-1 budget (ISSUE 18): gates in analysis.yml
def test_vit_b16_accepts_smaller_images():
    # --model vit_b16 on CIFAR-sized input: uses the leading pos embeddings
    m = vit_b16(num_classes=10)
    p, s = m.init(jax.random.PRNGKey(0))
    logits, _ = m.apply(p, s, jax.random.normal(jax.random.PRNGKey(1), (1, 32, 32, 3)))
    assert logits.shape == (1, 10)


def test_vit_rejects_oversized_images():
    import pytest

    m = vit_tiny(image_size=32)
    p, s = m.init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="positional"):
        m.apply(p, s, jax.random.normal(jax.random.PRNGKey(1), (1, 64, 64, 3)))


def test_vit_forward_shape():
    m = vit_tiny()
    p, s = m.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    logits, _ = m.apply(p, s, x)
    assert logits.shape == (2, 10)
    assert bool(jnp.all(jnp.isfinite(logits)))


@pytest.mark.slow  # tier-1 budget (ISSUE 18): gates in analysis.yml
def test_vit_trains_in_dp_step():
    mesh = mesh_lib.data_parallel_mesh()
    m = vit_tiny()
    opt = SGD()
    p, s = m.init(jax.random.PRNGKey(0))
    state = jax.device_put(TrainState.create(p, s, opt), mesh_lib.replicated(mesh))
    step = make_train_step(m.apply, opt, mesh, sync_bn=False)

    rng = np.random.default_rng(0)
    x = mesh_lib.shard_batch(mesh, rng.normal(size=(32, 32, 32, 3)).astype(np.float32))
    y = mesh_lib.shard_batch(mesh, rng.integers(0, 10, 32).astype(np.int32))
    losses = []
    for _ in range(20):
        state, met = step(state, x, y, 0.05)
        losses.append(float(met["loss"]))
    assert losses[-1] < losses[0]


def test_vit_seq_parallel_matches_single_device():
    """Sequence-parallel ViT forward over a 4-way 'seq' axis ≡ full forward."""
    m = ViTDef(image_size=32, patch_size=4, dim=32, depth=2, heads=2, num_classes=5)
    p, s = m.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))

    ref, _ = m.apply(p, s, x)

    mesh = mesh_lib.device_mesh([4], ["seq"], jax.devices()[:4])
    tokens = m.patchify(x)  # [B, 64, patch_dim]

    def f(p, tokens):
        out, _ = m.apply(p, {}, None, tokens=tokens, seq_axis="seq")
        return out

    sp = jax.jit(
        shard_map(
            f, mesh=mesh,
            in_specs=(P(), P(None, "seq")),
            out_specs=P(),
            check_vma=False,
        )
    )
    out = sp(p, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)
