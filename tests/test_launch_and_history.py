"""Launcher CLI, checkpoint pruning, JSONL metrics history."""

import json
import os
import sys

import jax.numpy as jnp
import numpy as np

from tpu_dist.ckpt import latest_checkpoint, save
from tpu_dist.cli.launch import main as launch_main
from tpu_dist.metrics.history import MetricsHistory
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState


def test_launcher_spawns_and_propagates_success(tmp_path):
    marker = tmp_path / "out"
    rc = launch_main([
        "--nproc", "2", "--devices_per_proc", "1", "--",
        sys.executable, "-c",
        (
            "import sys, pathlib\n"
            "args = dict(zip(sys.argv[1::2], sys.argv[2::2]))\n"
            f"pathlib.Path(r'{marker}' + args['--process_id']).write_text(args['--num_processes'])\n"
        ),
    ])
    assert rc == 0
    assert (tmp_path / "out0").read_text() == "2"
    assert (tmp_path / "out1").read_text() == "2"


def test_launcher_propagates_failure():
    # rank 1 dies with code 3, rank 0 exits clean: the launcher must return
    # the first non-zero child code. (Keyed off the injected --process_id —
    # NOT argv[-1], which is the launcher-appended port and made the old
    # version of this test flip on port numbers ending in 0.)
    rc = launch_main([
        "--nproc", "2", "--devices_per_proc", "1", "--",
        sys.executable, "-c",
        "import sys; a = sys.argv; "
        "sys.exit(3 if a[a.index('--process_id') + 1] == '1' else 0)",
    ])
    assert rc == 3


def test_ckpt_keep_last(tmp_path):
    st = TrainState.create({"w": jnp.ones(3)}, {}, SGD())
    for e in range(5):
        save(str(tmp_path), st, e, keep_last=2)
    kept = sorted(f for f in os.listdir(tmp_path) if f.endswith(".npz"))
    assert kept == ["ckpt_3.npz", "ckpt_4.npz"]
    assert latest_checkpoint(str(tmp_path))[1] == 4


def test_metrics_history_jsonl(tmp_path):
    path = str(tmp_path / "log" / "metrics.jsonl")
    h = MetricsHistory(path)
    h.log("train_epoch", epoch=0, loss=np.float32(1.5), images_per_sec=100.0)
    h.log("eval", epoch=0, top1=12.5)
    lines = [json.loads(l) for l in open(path)]
    assert len(lines) == 2
    assert lines[0]["kind"] == "train_epoch" and lines[0]["loss"] == 1.5
    assert lines[1]["top1"] == 12.5
    assert all("ts" in l for l in lines)


def test_metrics_history_disabled():
    h = MetricsHistory(None)
    h.log("train_epoch", loss=1.0)  # must be a no-op, no error
