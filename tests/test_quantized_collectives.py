"""Int8 gradient wire format (grad_compression='int8'/'int8_ef'):
quantize/dequantize round-trip, stochastic-rounding unbiasedness,
step-level closeness to the uncompressed reduce across all three
consumers (per-step, fused-epoch, ZeRO-1), error-feedback residual
checkpointing, convergence parity, and the TD104 static wire-byte
ratios (the acceptance criterion: int8 ≤ 0.5× bf16, ≤ 0.25× f32)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.comm.quantize import dequantize_int8, padded_len, quantize_int8
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tpu_dist.train.step import (
    init_ef_state,
    init_sharded_opt_state,
    make_train_step,
)
from tests.helpers import TinyConvNet, TinyMLP


def _state(model, mesh, seed=0, ef=None):
    params, bn = model.init(jax.random.PRNGKey(seed))
    st = TrainState.create(params, bn, SGD())
    st = jax.device_put(st, mesh_lib.replicated(mesh))
    if ef is not None:
        st = st._replace(ef=ef)
    return st


def _batch(mesh, n=64, c=10, hw=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, hw, hw, 3)).astype(np.float32)
    y = rng.integers(0, c, n).astype(np.int32)
    return mesh_lib.shard_batch(mesh, x), mesh_lib.shard_batch(mesh, y)


def _leaves(tree):
    return [np.asarray(t) for t in jax.tree_util.tree_leaves(tree)]


# -- quantize/dequantize ------------------------------------------------------


def test_quantize_scale_correctness_and_error_bound():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, 300)).astype(np.float32)) * 3.0
    q, s = quantize_int8(x, chunk=64)  # ragged tail: 300 = 4*64 + 44
    assert q.shape == x.shape and q.dtype == jnp.int8
    assert s.shape == (4, 5)
    # scale = per-chunk max|x| / 127: the extreme of each chunk maps to ±127
    blocks = np.pad(np.asarray(x), ((0, 0), (0, 20))).reshape(4, 5, 64)
    np.testing.assert_allclose(
        np.asarray(s), np.abs(blocks).max(-1) / 127.0, rtol=1e-6
    )
    # deterministic rounding: |error| <= scale/2 per element
    err = np.abs(np.asarray(dequantize_int8(q, s, chunk=64)) - np.asarray(x))
    per_elem_scale = np.repeat(np.asarray(s), 64, axis=-1)[:, :300]
    assert (err <= per_elem_scale / 2 + 1e-7).all()
    # all-zero chunks survive exactly
    z = jnp.zeros((128,), jnp.float32)
    qz, sz = quantize_int8(z)
    assert np.asarray(dequantize_int8(qz, sz)).max() == 0.0


def test_stochastic_rounding_unbiased_under_fixed_keys():
    # E over keys of dequant(quantize(x, key)) == x: average the estimate
    # over many fixed keys and watch the error shrink ~1/sqrt(K)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(512,)).astype(np.float32))
    _, s = quantize_int8(x, chunk=128)
    acc = np.zeros(512, np.float64)
    K = 250
    for i in range(K):
        q, s_i = quantize_int8(x, chunk=128, key=jax.random.PRNGKey(i))
        acc += np.asarray(dequantize_int8(q, s_i, chunk=128), np.float64)
    mean_err = np.abs(acc / K - np.asarray(x))
    scale = np.repeat(np.asarray(s), 128)[:512]
    # per-element standard error of the mean is scale/sqrt(12K); allow 6 sigma
    assert (mean_err <= 6.0 * scale / np.sqrt(12 * K) + 1e-7).all()
    # and a single stochastic draw stays within one scale step
    q1, s1 = quantize_int8(x, chunk=128, key=jax.random.PRNGKey(123))
    err1 = np.abs(np.asarray(dequantize_int8(q1, s1, chunk=128)) - np.asarray(x))
    assert (err1 <= scale + 1e-7).all()


def test_padded_len():
    assert padded_len(480, 8) == 480
    assert padded_len(481, 8) == 488
    assert padded_len(1, 8) == 8


# -- the three consumers ------------------------------------------------------


def test_int8_step_close_to_uncompressed_and_differs():
    mesh = mesh_lib.data_parallel_mesh()
    model = TinyConvNet()
    opt = SGD()
    xs, ys = _batch(mesh)
    s0 = _state(model, mesh)
    plain = make_train_step(model.apply, opt, mesh, donate=False)
    comp = make_train_step(
        model.apply, opt, mesh, donate=False, grad_compression="int8"
    )
    s_p, m_p = plain(s0, xs, ys, 0.1)
    s_c, m_c = comp(s0, xs, ys, 0.1)
    assert np.isfinite(float(m_c["loss"]))
    diffs = []
    for a, b in zip(_leaves(s_p.params), _leaves(s_c.params)):
        assert a.dtype == b.dtype == np.float32  # update math stays f32
        np.testing.assert_allclose(b, a, rtol=5e-2, atol=5e-3)
        diffs.append(float(np.abs(a - b).max()))
    assert max(diffs) > 0.0, "quantized path produced bit-identical params"


def test_int8_ef_residuals_update_and_match_quant_error():
    mesh = mesh_lib.data_parallel_mesh()
    model = TinyMLP(in_dim=8 * 8 * 3)
    opt = SGD()
    xs, ys = _batch(mesh)
    params, _ = model.init(jax.random.PRNGKey(0))
    ef0 = init_ef_state(params, mesh)
    s0 = _state(model, mesh, ef=ef0)
    step = make_train_step(
        model.apply, opt, mesh, donate=False, grad_compression="int8_ef"
    )
    s1, _ = step(s0, xs, ys, 0.1)
    r1 = np.asarray(s1.ef["r1"])
    r2 = np.asarray(s1.ef["r2"])
    assert np.abs(r1).max() > 0.0 and np.abs(r2).max() > 0.0
    # residuals are quantization error: bounded by one chunk scale of the
    # (1/n-scaled) gradient — far below the gradient magnitude itself
    assert np.abs(r1).max() < 1e-1
    # second step consumes them (no blow-up, state keeps training)
    s2, m2 = step(s1, xs, ys, 0.1)
    assert np.isfinite(float(m2["loss"]))
    assert int(s2.step) == 2


def test_int8_grad_accum_and_zero1_compose():
    mesh = mesh_lib.data_parallel_mesh()
    model = TinyConvNet()
    opt = SGD()
    xs, ys = _batch(mesh)

    step_ga = make_train_step(
        model.apply, opt, mesh, grad_accum_steps=2, grad_compression="int8",
        donate=False,
    )
    _, m = step_ga(_state(model, mesh), xs, ys, 0.1)
    assert np.isfinite(float(m["loss"]))

    # ZeRO-1: quantized reduce-scatter leg, param all-gather untouched
    s0 = _state(model, mesh)
    flat_opt = init_sharded_opt_state(s0.params, mesh)
    efz = init_ef_state(s0.params, mesh, zero1=True)
    s0 = s0._replace(opt_state=flat_opt, ef=efz)
    step_z1 = make_train_step(
        model.apply, opt, mesh, shard_weight_update=True,
        grad_compression="int8_ef", donate=False,
    )
    plain_z1 = make_train_step(
        model.apply, opt, mesh, shard_weight_update=True, donate=False,
    )
    s_q, m_q = step_z1(s0, xs, ys, 0.1)
    s_p, _ = plain_z1(s0._replace(ef=()), xs, ys, 0.1)
    assert np.isfinite(float(m_q["loss"]))
    assert "r1" in s_q.ef and "r2" not in s_q.ef  # no quantized second leg
    for a, b in zip(_leaves(s_p.params), _leaves(s_q.params)):
        np.testing.assert_allclose(b, a, rtol=5e-2, atol=5e-3)


def test_int8_refuses_model_parallel_axes():
    mesh = mesh_lib.data_parallel_mesh()
    model = TinyMLP(in_dim=8 * 8 * 3)
    with pytest.raises(ValueError, match="grad_compression"):
        make_train_step(
            model.apply, SGD(), mesh, grad_compression="int8",
            seq_axis="seq",
        )
    with pytest.raises(ValueError, match="grad_compression"):
        make_train_step(model.apply, SGD(), mesh, grad_compression="fp8")


# -- error-feedback residual checkpointing -----------------------------------


def test_ef_residuals_checkpoint_roundtrip(tmp_path):
    from tpu_dist import ckpt as ckpt_lib

    mesh = mesh_lib.data_parallel_mesh()
    model = TinyMLP(in_dim=8 * 8 * 3)
    opt = SGD()
    xs, ys = _batch(mesh)
    params, _ = model.init(jax.random.PRNGKey(0))
    s0 = _state(model, mesh, ef=init_ef_state(params, mesh))
    step = make_train_step(
        model.apply, opt, mesh, donate=False, grad_compression="int8_ef"
    )
    s1, _ = step(s0, xs, ys, 0.1)

    path = ckpt_lib.save(str(tmp_path), s1, epoch=0)
    restored = ckpt_lib.restore(path, s1)
    np.testing.assert_array_equal(
        np.asarray(restored.ef["r1"]), np.asarray(s1.ef["r1"])
    )
    np.testing.assert_array_equal(
        np.asarray(restored.ef["r2"]), np.asarray(s1.ef["r2"])
    )

    # enabling int8_ef on a checkpoint written WITHOUT residuals: restore
    # cold-starts them at zero instead of refusing the checkpoint
    s_plain = _state(model, mesh)
    p2 = ckpt_lib.save(str(tmp_path / "old"), s_plain, epoch=0)
    restored2 = ckpt_lib.restore(p2, s1)
    assert np.abs(np.asarray(restored2.ef["r1"])).max() == 0.0
    assert np.abs(np.asarray(restored2.ef["r2"])).max() == 0.0


@pytest.mark.slow  # resnet18 epochs on the emulated CPU mesh (~minutes)
def test_trainer_int8_ef_fit_and_resume(tmp_path):
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer

    cfg = TrainConfig(
        dataset="synthetic_learnable", num_classes=4, model="resnet18",
        batch_size=64, synthetic_n=128, epochs=1, lr=0.05, eval_every=0,
        save_every=1, ckpt_dir=str(tmp_path), grad_compression="int8_ef",
        num_workers=1, log_every=10, seed=0,
    )
    t = Trainer(cfg)
    out = t.fit()
    assert np.isfinite(out["loss"])
    r1 = np.asarray(jax.device_get(t.state.ef["r1"]))
    assert np.abs(r1).max() > 0.0

    t2 = Trainer(cfg.replace(resume=True, epochs=2))
    assert t2.start_epoch == 1
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(t2.state.ef["r1"])), r1
    )


def test_trainer_refuses_int8_with_model_parallelism():
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer

    cfg = TrainConfig(
        dataset="synthetic", batch_size=64, num_workers=1,
        model="vit_tiny", num_classes=100, tp=2, grad_compression="int8",
    )
    with pytest.raises(ValueError, match="grad_compression"):
        Trainer(cfg)


# -- convergence parity -------------------------------------------------------


def test_int8_ef_convergence_parity_with_uncompressed():
    """Short training run: int8_ef's final loss lands within tolerance of
    the uncompressed run's (the EQuARX claim at CIFAR scale — the wire
    format must not change what is learned)."""
    mesh = mesh_lib.data_parallel_mesh()
    model = TinyConvNet()
    opt = SGD()
    xs, ys = _batch(mesh)

    def train(mode):
        params, _ = model.init(jax.random.PRNGKey(0))
        ef = init_ef_state(params, mesh) if mode == "int8_ef" else None
        s = _state(model, mesh, ef=ef)
        step = make_train_step(
            model.apply, opt, mesh, donate=False, grad_compression=mode
        )
        losses = []
        for _ in range(60):
            s, m = step(s, xs, ys, 0.1)
            losses.append(float(m["loss"]))
        return losses

    base = train("none")
    quant = train("int8_ef")
    # both memorize the batch the same way
    assert base[-1] < base[0] - 0.2
    assert quant[-1] < quant[0] - 0.2
    assert abs(quant[-1] - base[-1]) < 0.15, (base[-1], quant[-1])


# -- static wire-byte audit (the acceptance criterion) ------------------------


def test_td104_wire_bytes_int8_vs_bf16_vs_none():
    """jaxpr audit confirms the int8 gradient collective payload is ≤0.5×
    the bf16 wire mode's and ≤0.25× the uncompressed mode's — for BOTH the
    per-step and the fused-epoch paths — and that the audit's own TD104
    gate would fire on a violation."""
    from tpu_dist.analysis.jaxpr_audit import audit_all, wire_ratio_violations

    cases = [
        "dp_sgd", "dp_wire_bf16", "dp_int8", "dp_int8_ef",
        "fused_none", "fused_bf16", "fused_int8", "fused_int8_ef",
        "zero1_sgd", "zero1_int8",
    ]
    report, violations = audit_all(names=cases)
    assert violations == [], [v.message for v in violations]

    pay = {c: report[c]["wire"]["payload_bytes"] for c in cases}
    # per-step path
    assert pay["dp_int8"] <= 0.5 * pay["dp_wire_bf16"]
    assert pay["dp_int8"] <= 0.25 * pay["dp_sgd"]
    # error feedback must be pure local arithmetic: identical collective
    # inventory (count AND wire bytes) to plain int8
    assert report["dp_int8_ef"]["collectives"] == report["dp_int8"]["collectives"]
    assert report["dp_int8_ef"]["wire"] == report["dp_int8"]["wire"]
    # fused-epoch path (whole-epoch scan totals; same ratios)
    assert pay["fused_int8"] <= 0.5 * pay["fused_bf16"]
    assert pay["fused_int8"] <= 0.25 * pay["fused_none"]
    assert report["fused_int8_ef"]["wire"] == report["fused_int8"]["wire"]
    # ZeRO-1: the GRAD leg (the quantized payload) shrinks 4× vs the f32
    # reduce-scatter; the param all-gather rightly stays full-width
    q = report["zero1_int8"]["wire"]["quantized_payload_bytes"]
    rs = report["zero1_sgd"]["wire"]["by_prim"]["reduce_scatter"]
    assert q <= 0.25 * rs
    # sideband (scales + scalar metrics) is reported, small, never hidden
    assert 0 < report["dp_int8"]["wire"]["sideband_bytes"] < 0.25 * pay["dp_int8"]

    # the gate fires when a quantized case regresses past its ratio
    bad = dict(report)
    bad["dp_int8"] = {"wire": {"payload_bytes": pay["dp_wire_bf16"]}}
    vs = wire_ratio_violations(bad)
    assert any(v.rule == "TD104" for v in vs)
