"""Pallas flash attention (ops/flash_attention.py) ≡ the XLA path.

Runs in interpret mode on the CPU mesh; checks forward AND custom-VJP
backward against ``full_attention`` over block-divisible, ragged (197),
and causal shapes, plus the dispatch/Trainer wiring."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist.nn.attention import (
    attention,
    full_attention,
    get_default_attention_impl,
    set_default_attention_impl,
)
from tpu_dist.ops.flash_attention import flash_attention


@pytest.mark.parametrize(
    "b,s,h,d,causal",
    [
        (2, 64, 2, 32, False),   # block-divisible
        (1, 197, 3, 64, False),  # ViT-B/16 length: padding + masking path
        (2, 40, 2, 16, True),    # causal, ragged
    ],
)
def test_flash_matches_xla_fwd_bwd(b, s, h, d, causal):
    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32) for _ in range(3)
    )
    ref = full_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    ct = jnp.asarray(rng.normal(size=ref.shape), jnp.float32)

    def loss(fn):
        return lambda q, k, v: (fn(q, k, v) * ct).sum()

    g_ref = jax.grad(loss(lambda *a: full_attention(*a, causal=causal)),
                     argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(
        loss(lambda *a: flash_attention(*a, causal=causal, block_q=32, block_k=32)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b_ in zip(g_ref, g_fl):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=2e-5)


def test_flash_bf16_dtype_and_accuracy():
    rng = np.random.default_rng(1)
    q, k, v = (
        jnp.asarray(rng.normal(size=(2, 64, 2, 32)), jnp.bfloat16) for _ in range(3)
    )
    out = flash_attention(q, k, v, block_q=32, block_k=32)
    assert out.dtype == jnp.bfloat16
    ref = full_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2
    )


def test_flash_block_size_invariance():
    rng = np.random.default_rng(2)
    q, k, v = (
        jnp.asarray(rng.normal(size=(1, 96, 2, 16)), jnp.float32) for _ in range(3)
    )
    a = flash_attention(q, k, v, block_q=16, block_k=48)
    b = flash_attention(q, k, v, block_q=96, block_k=96)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_attention_dispatch_impl():
    rng = np.random.default_rng(3)
    q, k, v = (
        jnp.asarray(rng.normal(size=(1, 32, 2, 16)), jnp.float32) for _ in range(3)
    )
    assert get_default_attention_impl() == "xla"
    try:
        set_default_attention_impl("flash")
        out = attention(q, k, v)
    finally:
        set_default_attention_impl("xla")
    ref = attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    with pytest.raises(ValueError):
        set_default_attention_impl("nope")


def test_trainer_flash_attention_e2e():
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer

    cfg = TrainConfig(
        dataset="synthetic", model="vit_tiny", num_classes=10, batch_size=16,
        epochs=1, steps_per_epoch=2, log_every=10, eval_every=0,
        synthetic_n=64, sync_bn=False, flash_attention=True,
    )
    try:
        out = Trainer(cfg).train_epoch(0)
    finally:
        set_default_attention_impl("xla")
    assert np.isfinite(out["loss"])


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_bwd_matches_xla_bwd(causal):
    """The two backward formulations (tiled Pallas kernels vs blockwise
    lax.scan) are the same math — grads must agree to f32 round-off, on
    a ragged length exercising both padding paths."""
    rng = np.random.default_rng(3)
    b, s, h, d = 2, 100, 2, 32
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32) for _ in range(3)
    )
    ct = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)

    def grads(bwd):
        def loss(q, k, v):
            out = flash_attention(
                q, k, v, causal=causal, block_q=32, block_k=32, bwd=bwd
            )
            return jnp.vdot(out, ct)

        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    for gp, gx, name in zip(grads("pallas"), grads("xla"), "qkv"):
        np.testing.assert_allclose(
            np.asarray(gp), np.asarray(gx), atol=3e-5,
            err_msg=f"d{name} mismatch between pallas and xla backward",
        )
