"""Profile analytics (ISSUE 9): the ``obs/xprof.py`` capture analyzer —
category attribution summing to device busy time, comm/compute overlap,
malformed-capture hardening, the auto-analyze hook, cost-model
calibration, the TD110 noop gate, and the summarize/compare/tail/pod/CLI
surfaces of ``profile_analysis`` records (schema v6)."""

import gzip
import json
import os

import pytest

from tpu_dist.obs import counters, spans, xprof
from tpu_dist.obs import profile as profile_lib
from tpu_dist.obs.summarize import format_text, summarize


@pytest.fixture(autouse=True)
def _clean_telemetry():
    spans.disable()
    spans.drain()
    counters.reset()
    yield
    spans.disable()
    spans.drain()
    counters.reset()


# -- synthetic trace builders ------------------------------------------------


def _meta(pid=1, pname="/device:TPU:0", threads=((10, "XLA Ops"),)):
    evs = [{"ph": "M", "pid": pid, "name": "process_name",
            "args": {"name": pname}}]
    for tid, tname in threads:
        evs.append({"ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
                    "args": {"name": tname}})
    return evs


def _x(name, ts, dur, pid=1, tid=10, args=None):
    e = {"ph": "X", "pid": pid, "tid": tid, "name": name, "ts": ts, "dur": dur}
    if args is not None:
        e["args"] = args
    return e


def _write_capture(root, events, host="host0", run="run1"):
    """Lay events out exactly as jax.profiler does:
    ``<root>/plugins/profile/<run>/<host>.trace.json.gz``."""
    d = os.path.join(str(root), "plugins", "profile", run)
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"{host}.trace.json.gz")
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)
    return path


# -- classification ----------------------------------------------------------


def test_classify_categories():
    assert xprof.classify("dot.6") == "matmul_conv"
    assert xprof.classify("convolution.12") == "matmul_conv"
    assert xprof.classify("triton_gemm_fusion.3") == "matmul_conv"
    assert xprof.classify("all-reduce.12") == "collective"
    assert xprof.classify("all-gather-start.2") == "collective"
    assert xprof.classify("infeed.1") == "infeed_outfeed"
    assert xprof.classify("outfeed.4") == "infeed_outfeed"
    assert xprof.classify("conv.2") == "matmul_conv"
    # near-miss names that must NOT read as collectives/matmuls —
    # 'convert' (the ubiquitous dtype cast) above all
    assert xprof.classify("convert.5") == "fusion_other"
    assert xprof.classify("reduce-window") == "fusion_other"
    assert xprof.classify("reduce.16") == "fusion_other"
    assert xprof.classify("reduce_bitcast_fusion") == "fusion_other"
    assert xprof.classify("fusion.3") == "fusion_other"
    assert xprof.classify("tanh.11.clone") == "fusion_other"
    # runtime bookkeeping (uppercase/space/colon) → host
    assert xprof.classify("TfrtCpuExecutable::Execute") == "host"
    assert xprof.classify("D2D Dispatch") == "host"
    assert xprof.classify("$profiler.py:91 start_trace") == "host"


def test_collective_kind_folds_async_halves():
    assert xprof.collective_kind("all-reduce.3") == "all-reduce"
    assert xprof.collective_kind("all-gather-start.2") == "all-gather"
    assert xprof.collective_kind("all-gather-done.2") == "all-gather"
    assert xprof.collective_kind("reduce-scatter.9") == "reduce-scatter"
    assert xprof.collective_kind("collective-permute-start.1") == "collective-permute"
    assert xprof.collective_kind("recv-done.2") == "recv"
    assert xprof.collective_kind("reduce.1") is None
    assert xprof.collective_kind("dot.6") is None


# -- self-time / interval math ----------------------------------------------


def test_self_time_subtracts_nested_children():
    # parent [0,100] wraps child [10,30]: self 80 + 20, sum == union 100
    evs = [(0.0, 100.0, 0), (10.0, 20.0, 1)]
    selfs = xprof._self_times_us(evs)
    assert selfs[0] == pytest.approx(80.0)
    assert selfs[1] == pytest.approx(20.0)
    assert sum(selfs.values()) == pytest.approx(100.0)


def test_self_time_clips_jitter_overhang():
    # "child" [90,120] overhangs parent [0,100] (clock jitter): clipped,
    # so the thread's self times still sum to the parent union
    evs = [(0.0, 100.0, 0), (90.0, 30.0, 1)]
    selfs = xprof._self_times_us(evs)
    assert sum(selfs.values()) == pytest.approx(100.0)


def test_interval_union_and_intersection():
    assert xprof._union_len([(0, 10), (5, 20), (30, 40)]) == 30
    assert xprof._intersect_len([(0, 10)], [(5, 25)]) == 5
    assert xprof._intersect_len([(0, 10)], [(20, 25)]) == 0


# -- synthetic capture analysis ----------------------------------------------


def test_categories_sum_to_busy_and_known_values(tmp_path):
    evs = _meta() + [
        _x("dot.1", 0, 50),          # matmul 50
        _x("fusion.2", 50, 30),      # fusion 30
        _x("all-reduce.3", 80, 20),  # collective 20
        _x("infeed.4", 100, 10),     # infeed 10
        _x("SparseCoreV0::Step", 110, 5),  # runtime → host 5
    ]
    _write_capture(tmp_path, evs)
    r = xprof.analyze_capture(str(tmp_path))
    us = 1e-6
    assert r["categories"]["matmul_conv"] == pytest.approx(50 * us)
    assert r["categories"]["fusion_other"] == pytest.approx(30 * us)
    assert r["categories"]["collective"] == pytest.approx(20 * us)
    assert r["categories"]["infeed_outfeed"] == pytest.approx(10 * us)
    assert r["categories"]["host"] == pytest.approx(5 * us)
    assert sum(r["categories"].values()) == pytest.approx(
        r["device_busy_s"], abs=1e-12
    )
    assert r["infeed_stall_s"] == pytest.approx(10 * us)
    assert r["collectives"] == {"all-reduce": pytest.approx(20 * us)}
    assert r["collective_frac"] == pytest.approx(20 / 115, abs=1e-3)
    assert r["analyzed"] == r["n_traces"] == 1


def test_overlap_fraction_on_overlapped_workload(tmp_path):
    # comm [0,100] on thread 10 vs compute [50,250] on thread 11: half the
    # collective hides under compute → overlap 0.5
    evs = _meta(threads=((10, "XLA Ops"), (11, "XLA Ops #2"))) + [
        _x("all-reduce.1", 0, 100, tid=10),
        _x("dot.2", 50, 200, tid=11),
    ]
    _write_capture(tmp_path, evs)
    r = xprof.analyze_capture(str(tmp_path))
    ov = r["overlap"]
    assert ov["comm_s"] == pytest.approx(100e-6)
    assert ov["compute_s"] == pytest.approx(200e-6)
    assert ov["overlapped_s"] == pytest.approx(50e-6)
    assert ov["overlap_frac"] == pytest.approx(0.5)


def test_overlap_zero_when_serialized_and_none_without_comm(tmp_path):
    evs = _meta() + [
        _x("all-reduce.1", 0, 100),
        _x("dot.2", 100, 100),       # back-to-back, same thread: no overlap
    ]
    _write_capture(tmp_path, evs)
    r = xprof.analyze_capture(str(tmp_path))
    assert r["overlap"]["overlap_frac"] == 0.0
    d2 = tmp_path / "nocomm"
    _write_capture(d2, _meta() + [_x("dot.1", 0, 100)])
    r2 = xprof.analyze_capture(str(d2))
    assert r2["overlap"]["overlap_frac"] is None
    assert r2["collective_frac"] == 0.0


def test_top_ops_ranked_by_self_time_excluding_runtime(tmp_path):
    evs = _meta() + [
        _x("dot.1", 0, 60),
        _x("dot.1", 100, 60),
        _x("tanh.2", 200, 50),
        _x("ThreadpoolListener::Record", 300, 500),  # host: not a top op
    ]
    _write_capture(tmp_path, evs)
    r = xprof.analyze_capture(str(tmp_path), top_k=2)
    assert [o["name"] for o in r["top_ops"]] == ["dot.1", "tanh.2"]
    assert r["top_ops"][0]["count"] == 2
    assert r["top_ops"][0]["self_s"] == pytest.approx(120e-6)


def test_multi_trace_capture_merges_hosts(tmp_path):
    _write_capture(tmp_path, _meta() + [_x("dot.1", 0, 100)], host="h0")
    _write_capture(
        tmp_path, _meta() + [_x("all-reduce.2", 0, 50)], host="h1"
    )
    r = xprof.analyze_capture(str(tmp_path))
    assert r["n_traces"] == 2 and r["analyzed"] == 2
    assert r["device_busy_s"] == pytest.approx(150e-6)
    assert r["categories"]["matmul_conv"] == pytest.approx(100e-6)
    assert r["categories"]["collective"] == pytest.approx(50e-6)


def test_cpu_host_fallback_selects_by_hlo_content(tmp_path):
    # no /device: process — /host:CPU with hlo_op-stamped events scattered
    # across pools, runtime noise unstamped (the jax CPU backend layout)
    evs = _meta(pname="/host:CPU", threads=(
        (10, "tf_XLAEigen/1"), (11, "tf_XLATfrtCpuClient/2"), (12, "python"),
    )) + [
        _x("dot.1", 0, 100, tid=11, args={"hlo_op": "dot.1"}),
        _x("tanh.2", 0, 40, tid=10, args={"hlo_module": "jit_f"}),
        _x("PjitFunction(f)", 0, 5000, tid=12),              # runtime: out
        _x("TfrtCpuExecutable::Execute", 0, 400, tid=11),    # runtime: out
    ]
    _write_capture(tmp_path, evs)
    r = xprof.analyze_capture(str(tmp_path))
    assert r["device_busy_s"] == pytest.approx(140e-6)
    assert r["categories"]["host"] == 0.0


# -- malformed captures: typed errors, partial reports, counted drops --------


def test_empty_capture_dir_typed_error(tmp_path):
    with pytest.raises(xprof.EmptyCaptureError):
        xprof.analyze_capture(str(tmp_path))
    with pytest.raises(xprof.EmptyCaptureError):
        xprof.analyze_capture(str(tmp_path / "never_made"))


def test_truncated_gzip_typed_error(tmp_path):
    path = _write_capture(tmp_path, _meta() + [_x("dot.1", 0, 10)])
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[: len(blob) // 2])  # cut the gzip stream mid-member
    with pytest.raises(xprof.MalformedTraceError):
        xprof.analyze_capture(str(tmp_path))


def test_torn_json_tail_typed_error(tmp_path):
    d = tmp_path / "plugins" / "profile" / "run1"
    d.mkdir(parents=True)
    with gzip.open(d / "h.trace.json.gz", "wt") as f:
        f.write('{"traceEvents": [{"ph": "X", "name": "dot.1", "ts')  # torn
    with pytest.raises(xprof.MalformedTraceError):
        xprof.analyze_capture(str(tmp_path))


def test_no_device_track_typed_error(tmp_path):
    _write_capture(tmp_path, [
        {"ph": "M", "pid": 9, "name": "process_name",
         "args": {"name": "some_other_tool"}},
        _x("whatever", 0, 10, pid=9),
    ])
    with pytest.raises(xprof.NoDeviceTrackError):
        xprof.analyze_capture(str(tmp_path))


def test_partial_report_counts_drops_never_raises(tmp_path):
    """One good + one truncated + one trackless trace file: the report is
    PARTIAL — good numbers, drops counted by kind, errors listed."""
    _write_capture(tmp_path, _meta() + [_x("dot.1", 0, 100)], host="good")
    bad = _write_capture(tmp_path, _meta() + [_x("dot.2", 0, 9)], host="trunc")
    blob = open(bad, "rb").read()
    with open(bad, "wb") as f:
        f.write(blob[:20])
    _write_capture(tmp_path, [_x("x", 0, 1, pid=99)], host="trackless")
    r = xprof.analyze_capture(str(tmp_path))
    assert r["analyzed"] == 1 and r["n_traces"] == 3
    assert r["dropped"] == {"malformed_trace": 1, "no_device_track": 1}
    assert len(r["errors"]) == 2
    assert r["device_busy_s"] == pytest.approx(100e-6)
    assert "dropped" in xprof.summary_line(r)


def test_analyze_capture_quietly_never_raises(tmp_path):
    rec, err = profile_lib.analyze_capture_quietly(str(tmp_path / "missing"))
    assert rec is None
    assert ("no *.trace.json.gz" in err) or ("not a directory" in err)
    assert counters.get("xprof.analyze_errors") == 1
    _write_capture(tmp_path, _meta() + [_x("dot.1", 0, 100)])
    rec, err = profile_lib.analyze_capture_quietly(str(tmp_path))
    assert err is None
    assert rec["device_busy_s"] == pytest.approx(100e-6)
    assert counters.get("xprof.analyses") == 1


# -- the auto-analyze hook on a REAL capture ---------------------------------


def test_hook_analyzes_real_cpu_capture(tmp_path):
    """Acceptance: a real CPU-backend capture closed by the profiler's
    stop path yields an attribution whose category seconds sum to device
    busy time, attached to the stop event by the hook."""
    import jax
    import jax.numpy as jnp

    prof = profile_lib.TriggeredProfiler(
        str(tmp_path), window_steps=2, cooldown_steps=0, max_captures=1,
        analyze=True,
    )
    prof.arm("anomaly_test")
    ev = prof.on_step(0)
    assert ev["event"] == "start"
    f = jax.jit(lambda x, w: jnp.tanh(x @ w).sum())
    x = jnp.ones((128, 128))
    for _ in range(4):
        jax.block_until_ready(f(x, x))
    ev = prof.on_step(2)
    assert ev["event"] == "stop"
    analysis = ev.get("analysis")
    assert analysis is not None, ev.get("analysis_error")
    assert analysis["device_busy_s"] > 0
    assert sum(analysis["categories"].values()) == pytest.approx(
        analysis["device_busy_s"], abs=1e-9
    )
    assert analysis["categories"]["matmul_conv"] > 0  # the 128x128 dot
    assert counters.get("xprof.analyses") == 1
    # and the trainer-facing one-liner renders from the compact record
    line = xprof.summary_line(analysis)
    assert "device busy" in line and "matmul/conv" in line


def test_hook_off_and_hook_failure_are_contained(tmp_path, monkeypatch):
    import jax

    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    prof = profile_lib.TriggeredProfiler(
        str(tmp_path / "a"), window_steps=1, max_captures=1, analyze=False,
    )
    prof.arm("x")
    prof.on_step(0)
    ev = prof.on_step(1)
    assert ev["event"] == "stop"
    assert "analysis" not in ev and "analysis_error" not in ev
    # analyze on, fake backend → empty capture dir → contained error
    prof2 = profile_lib.TriggeredProfiler(
        str(tmp_path / "b"), window_steps=1, max_captures=1, analyze=True,
    )
    prof2.arm("y")
    prof2.on_step(0)
    ev = prof2.on_step(1)
    assert ev["event"] == "stop"
    assert "analysis" not in ev and ev["analysis_error"]
    assert counters.get("xprof.analyze_errors") == 1


def test_real_pmap_capture_attribution_and_overlap(tmp_path):
    """A real 8-device CPU pmap+psum capture: collectives appear by kind,
    the invariant holds, and the overlap fraction is well-formed."""
    import jax
    import jax.numpy as jnp

    n = jax.local_device_count()
    f = jax.pmap(
        lambda x, w: jax.lax.psum(jnp.tanh(x @ w), "i").sum(), axis_name="i"
    )
    x = jnp.ones((n, 96, 96))
    jax.block_until_ready(f(x, x))  # compile outside the window
    jax.profiler.start_trace(str(tmp_path))
    for _ in range(4):
        jax.block_until_ready(f(x, x))
    jax.profiler.stop_trace()
    r = xprof.analyze_capture(str(tmp_path))
    assert sum(r["categories"].values()) == pytest.approx(
        r["device_busy_s"], abs=1e-9
    )
    assert r["collectives"].get("all-reduce", 0) > 0
    assert r["categories"]["matmul_conv"] > 0
    ov = r["overlap"]["overlap_frac"]
    assert ov is not None and 0.0 <= ov <= 1.0


# -- cost-model calibration --------------------------------------------------


def test_calibration_rates_and_fractions():
    from tpu_dist.obs import costmodel

    analysis = {
        "device_busy_s": 2.0,
        "categories": {"matmul_conv": 1.0, "fusion_other": 0.5,
                       "collective": 0.4, "infeed_outfeed": 0.1, "host": 0.0},
        "collective_frac": 0.2,
        "overlap_frac": 0.25,
    }
    cost = {"flops_per_step": 1e9, "bytes_per_step": 2e6}
    cal = costmodel.calibration(
        cost, analysis, steps=10, n_devices=2, peak=1e12
    )
    # concurrent-wall compute per step = 1.5s / 10 / 2 = 0.075s; the
    # aggregate achieved rate over the AGGREGATE peak (peak×n_devices) —
    # the same flops_per_step convention mfu() applies, so the two
    # published efficiency numbers always agree
    assert cal["cost.calibration_flops_per_s"] == pytest.approx(
        1e9 / 0.075, rel=1e-3
    )
    assert cal["cost.calibration_compute_frac"] == pytest.approx(
        1e9 / 0.075 / (1e12 * 2), abs=1e-4
    )
    # busy per device-step = 2.0 / 10 / 2 = 0.1s
    assert cal["cost.calibration_bytes_per_s"] == pytest.approx(2e7, rel=1e-3)
    assert cal["cost.calibration_collective_frac"] == 0.2
    assert cal["cost.calibration_overlap_frac"] == 0.25
    assert cal["cost.calibration_steps"] == 10


def test_calibration_degrades_without_steps_cost_or_peak():
    from tpu_dist.obs import costmodel

    analysis = {"device_busy_s": 1.0, "collective_frac": 0.3,
                "overlap_frac": 0.5,
                "categories": {"matmul_conv": 0.7, "fusion_other": 0.0,
                               "collective": 0.3, "infeed_outfeed": 0.0,
                               "host": 0.0}}
    # no steps: only the fraction gauges
    cal = costmodel.calibration({"flops_per_step": 1e9}, analysis, steps=None)
    assert set(cal) == {"cost.calibration_collective_frac",
                       "cost.calibration_overlap_frac"}
    # steps but no cost numbers: fractions + steps only
    cal = costmodel.calibration({}, analysis, steps=4)
    assert "cost.calibration_flops_per_s" not in cal
    assert cal["cost.calibration_steps"] == 4
    # unknown chip (CPU): rate yes, peak fraction omitted
    cal = costmodel.calibration(
        {"flops_per_step": 1e9}, analysis, steps=4, peak=None
    )
    assert "cost.calibration_flops_per_s" in cal
    assert "cost.calibration_compute_frac" not in cal
    assert costmodel.calibration({}, None, steps=4) == {}


def test_calibration_gauges_reach_registry_and_exposition():
    from tpu_dist.obs import costmodel, export

    costmodel.publish_calibration({
        "cost.calibration_overlap_frac": 0.4,
        "cost.calibration_flops_per_s": 1.5e12,
    })
    snap = counters.snapshot()
    assert snap["cost.calibration_overlap_frac"] == 0.4
    text = export.render({
        k: v for k, v in snap.items() if isinstance(v, (int, float))
    })
    assert "tpu_dist_cost_calibration_overlap_frac 0.4" in text
    assert export.parse(text)["tpu_dist_cost_calibration_flops_per_s"] == 1.5e12


# -- TD110 -------------------------------------------------------------------


@pytest.mark.slow  # ~20 s: traces the DP step 4x + a REAL capture window
# that the hook then analyzes; gates in the CI xprof step (no slow filter)
def test_td110_xprof_hook_noop_gate():
    from tpu_dist.analysis.jaxpr_audit import xprof_hook_noop_violations

    assert xprof_hook_noop_violations() == []


def test_td110_rule_registered():
    from tpu_dist.analysis.jaxpr_audit import xprof_hook_noop_violations  # noqa: F401
    from tpu_dist.analysis.rules import RULES

    assert "TD110" in RULES
    assert RULES["TD110"].name == "xprof-hook-not-noop"


# -- summarize / compare / tail / pod / CLI over profile_analysis ------------


def _epoch_rec(run_id, epoch, **kw):
    return {"kind": "train_epoch", "epoch": epoch, "run_id": run_id,
            "schema_version": 6, "ts": 10.0 + epoch, "rel_s": 1.0 + epoch,
            "epoch_time": 1.0, "images_per_sec": 100.0, "loss": 1.0, **kw}


def _analysis_rec(run_id, epoch, overlap, coll, **kw):
    return {
        "kind": "profile_analysis", "epoch": epoch, "run_id": run_id,
        "schema_version": 6, "ts": 10.5 + epoch, "rel_s": 1.5 + epoch,
        "reason": "anomaly_loss_spike", "dir": f"/prof/cap{epoch}",
        "steps": 8, "device_busy_s": 1.0,
        "categories": {"matmul_conv": 0.5, "fusion_other": 0.2,
                       "collective": coll, "infeed_outfeed": 0.05,
                       "host": 0.25 - coll},
        "collectives": {"all-reduce": coll},
        "collective_frac": coll, "overlap_frac": overlap,
        "infeed_stall_s": 0.05,
        "calibration": {"cost.calibration_overlap_frac": overlap,
                        "cost.calibration_steps": 8},
        **kw,
    }


def test_summarize_folds_profile_analysis_and_renders_table():
    records = [
        _epoch_rec("r1", 0),
        _analysis_rec("r1", 0, 0.42, 0.15),
        {"kind": "profile_analysis", "run_id": "r1", "schema_version": 6,
         "ts": 12.0, "rel_s": 3.0, "epoch": 1, "reason": "retrace",
         "dir": "/prof/cap1", "error": "no device track"},
    ]
    rep = summarize(records)
    assert len(rep["profile_analyses"]) == 2
    assert rep["profile_analyses"][0]["overlap_frac"] == 0.42
    assert rep["skipped_kinds"] == {}        # v6 kind is KNOWN to this reader
    text = format_text(rep)
    assert "capture attribution" in text
    assert "42.0%" in text                   # the overlap column
    assert "calibration:" in text
    assert "analysis FAILED: no device track" in text


def test_compare_gates_on_injected_overlap_regression(tmp_path, capsys):
    from tpu_dist.obs.__main__ import main as obs_main

    base = tmp_path / "base.jsonl"
    cand = tmp_path / "cand.jsonl"
    for path, overlap, coll in ((base, 0.5, 0.2), (cand, 0.1, 0.2)):
        with open(path, "w") as f:
            for rec in (_epoch_rec("r", 0), _analysis_rec("r", 0, overlap, coll)):
                f.write(json.dumps(rec) + "\n")
    rc = obs_main(["compare", str(base), str(cand)])
    out = capsys.readouterr().out
    assert rc == 1                           # overlap collapsed → regression
    assert "overlap_frac" in out and "REGRESSED" in out
    # collective share growing is also a gated regression
    with open(cand, "w") as f:
        for rec in (_epoch_rec("r", 0), _analysis_rec("r", 0, 0.5, 0.6)):
            f.write(json.dumps(rec) + "\n")
    assert obs_main(["compare", str(base), str(cand)]) == 1
    out = capsys.readouterr().out
    assert "collective_frac" in out and "REGRESSED" in out
    # identical logs: no regression, analysis metrics compared not skipped
    assert obs_main(["compare", str(base), str(base)]) == 0


def test_compare_skips_analysis_metrics_on_captureless_runs(tmp_path):
    from tpu_dist.obs import compare as compare_lib

    a = tmp_path / "a.jsonl"
    with open(a, "w") as f:
        f.write(json.dumps(_epoch_rec("r", 0)) + "\n")
    result = compare_lib.compare_files(str(a), str(a))
    rows = {r["metric"]: r["verdict"] for r in result["rows"]}
    assert rows["overlap_frac"] == "skipped"
    assert rows["collective_frac"] == "skipped"
    assert result["regressions"] == 0


def test_tail_shows_one_line_attribution():
    from tpu_dist.obs.tail import TailState

    st = TailState()
    st.add([_epoch_rec("r", 0), _analysis_rec("r", 0, 0.37, 0.21)])
    frame = st.render(None)
    assert "capture analysis (anomaly_loss_spike)" in frame
    assert "overlap 37%" in frame
    st.add([{"kind": "profile_analysis", "reason": "retrace",
             "error": "truncated gzip", "run_id": "r", "epoch": 1}])
    assert "capture analysis FAILED (retrace): truncated gzip" in st.render(None)


def test_pod_report_lists_captures_with_analysis_rollups(tmp_path):
    from tpu_dist.obs import aggregate

    stop = {"kind": "profile", "run_id": "r", "schema_version": 6,
            "ts": 11.0, "rel_s": 2.0, "epoch": 0, "event": "stop",
            "reason": "straggler", "start_step": 4, "stop_step": 12,
            "steps": 8, "dir": "/prof/h1/cap0"}
    hosts = [
        ("h0", [_epoch_rec("r", 0)]),
        ("h1", [_epoch_rec("r", 0), stop,
                _analysis_rec("r", 0, 0.3, 0.25, dir="/prof/h1/cap0")]),
    ]
    rep = aggregate.pod_report(hosts)
    assert rep["hosts"][1]["profile_analyses"][0]["overlap_frac"] == 0.3
    text = aggregate.format_text(rep)
    assert "captures on h1:" in text
    assert "/prof/h1/cap0" in text
    assert "overlap 30%" in text
    assert "captures on h0:" not in text


def test_xprof_cli_text_json_and_exit_codes(tmp_path, capsys):
    from tpu_dist.obs.__main__ import main as obs_main

    _write_capture(tmp_path, _meta() + [
        _x("dot.1", 0, 60), _x("all-reduce.2", 60, 40),
    ])
    assert obs_main(["xprof", str(tmp_path)]) == 0
    text = capsys.readouterr().out
    assert "device busy" in text and "all-reduce" in text
    assert obs_main(["xprof", str(tmp_path), "--format", "json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["categories"]["matmul_conv"] == pytest.approx(60e-6)
    # a single trace FILE (e.g. pulled out of a capture) also analyzes
    trace_file = xprof.find_traces(str(tmp_path))[0]
    assert obs_main(["xprof", trace_file]) == 0
    capsys.readouterr()
    # unusable capture → 1; missing path → 2 (the broken-gate distinction)
    empty = tmp_path / "empty"
    empty.mkdir()
    assert obs_main(["xprof", str(empty)]) == 1
    assert obs_main(["xprof", str(tmp_path / "missing")]) == 2


def test_history_schema_round_trip(tmp_path):
    from tpu_dist.metrics.history import SCHEMA_VERSION, MetricsHistory

    assert SCHEMA_VERSION == 15  # v15: causal decision tracing (ISSUE 19)
    path = str(tmp_path / "h.jsonl")
    with MetricsHistory(path, run_id="r9") as h:
        h.log("profile_analysis", epoch=0, reason="manual",
              device_busy_s=0.5, overlap_frac=0.4,
              categories={"matmul_conv": 0.5})
    rec = json.loads(open(path).read())
    assert rec["schema_version"] == 15
    assert rec["kind"] == "profile_analysis"
    assert rec["categories"] == {"matmul_conv": 0.5}


# -- e2e: trainer auto-analysis on a real run --------------------------------


@pytest.mark.slow  # >10s e2e (full trainer fit + compile): excluded from
# the timed tier-1 gate; gates in the CI xprof step (no slow filter)
def test_e2e_trainer_capture_emits_analysis_record_and_gauges(tmp_path, capsys):
    """Acceptance: a short real run with a manual capture produces a
    ``profile_analysis`` history record whose categories sum to busy,
    ``cost.calibration_*`` gauges in the registry/log, the rank-0
    summary line, and a summarize report with the attribution table."""
    from tests.helpers import tiny_resnet
    from tpu_dist.config import TrainConfig
    from tpu_dist.obs.__main__ import main as obs_main
    from tpu_dist.obs.summarize import load_records
    from tpu_dist.train.trainer import Trainer, register_model

    register_model(
        "tiny_xprof_e2e", lambda num_classes=10: tiny_resnet(num_classes)
    )
    log = str(tmp_path / "run.jsonl")
    prof_dir = str(tmp_path / "prof")
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_xprof_e2e", num_classes=10,
        batch_size=64, epochs=1, steps_per_epoch=5, synthetic_n=640,
        log_every=4, log_file=log, seed=0,
        profile_dir=prof_dir, profile_steps="1:3",
    )
    Trainer(cfg).fit()
    records, bad = load_records(log)
    assert bad == 0
    analyses = [r for r in records if r["kind"] == "profile_analysis"]
    assert len(analyses) == 1, [r["kind"] for r in records]
    pa = analyses[0]
    assert pa["schema_version"] == 15
    assert pa.get("error") is None
    assert pa["device_busy_s"] > 0
    assert sum(pa["categories"].values()) == pytest.approx(
        pa["device_busy_s"], abs=1e-9
    )
    assert pa["steps"] == 2 and pa["reason"] == "manual"
    # the calibration gauges landed in the record and the registry
    cal = pa.get("calibration") or {}
    assert cal.get("cost.calibration_steps") == 2
    assert cal.get("cost.calibration_bytes_per_s", 0) > 0
    snap = counters.snapshot()
    assert snap.get("cost.calibration_bytes_per_s", 0) > 0
    assert counters.get("xprof.analyses") == 1
    # summarize renders the attribution table over the real log
    capsys.readouterr()
    assert obs_main(["summarize", log]) == 0
    text = capsys.readouterr().out
    assert "capture attribution" in text and "calibration:" in text
