"""Tensor / expert / pipeline parallelism primitives (tpu_dist/parallel)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from tpu_dist.comm.compat import shard_map
from jax.sharding import PartitionSpec as P

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.parallel import (
    MoE,
    column_parallel_dense,
    pipeline_apply,
    row_parallel_dense,
    shard_columns,
    shard_rows,
)


def test_tp_mlp_matches_dense():
    """column→gelu→row parallel MLP over 4-way model axis ≡ single device."""
    mesh = mesh_lib.device_mesh([4], ["model"], jax.devices()[:4])
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
    w1 = jnp.asarray(rng.normal(size=(16, 32)), jnp.float32) * 0.1
    w2 = jnp.asarray(rng.normal(size=(32, 16)), jnp.float32) * 0.1
    b1 = jnp.asarray(rng.normal(size=(32,)), jnp.float32)
    b2 = jnp.asarray(rng.normal(size=(16,)), jnp.float32)

    ref = jax.nn.gelu(x @ w1 + b1) @ w2 + b2

    def f(x, w1l, b1l, w2l, b2):
        h = jax.nn.gelu(column_parallel_dense(x, w1l, "model", b1l))
        return row_parallel_dense(h, w2l, "model", b2)

    tp = jax.jit(
        shard_map(
            f, mesh=mesh,
            in_specs=(P(), P(None, "model"), P("model"), P("model", None), P()),
            out_specs=P(),
            check_vma=False,
        )
    )
    out = tp(x, w1, b1, w2, b2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_tp_shard_helpers_roundtrip():
    w = jnp.arange(24.0).reshape(4, 6)
    cols = [shard_columns(w, 3, i) for i in range(3)]
    np.testing.assert_array_equal(np.concatenate(cols, axis=1), np.asarray(w))
    rows = [shard_rows(w, 2, i) for i in range(2)]
    np.testing.assert_array_equal(np.concatenate(rows, axis=0), np.asarray(w))


@pytest.mark.parametrize("top_k", [1, 2])
def test_moe_ep_matches_dense(top_k):
    """Expert-parallel MoE over 4-way expert axis ≡ dense single-device MoE
    on the same global token set (Switch top-1 and GShard top-2)."""
    n_ep = 4
    mesh = mesh_lib.device_mesh([n_ep], ["expert"], jax.devices()[:n_ep])
    moe = MoE(n_experts=8, capacity_factor=8.0, top_k=top_k)  # no drops
    rng = np.random.default_rng(0)
    d, f = 16, 32
    params = moe.init(jax.random.PRNGKey(0), d, f)
    T_loc = 8
    x = jnp.asarray(rng.normal(size=(n_ep * T_loc, d)), jnp.float32)

    def f(router, w_in_l, w_out_l, x_l):
        return moe.apply_ep(router, w_in_l, w_out_l, x_l, "expert")

    ep = jax.jit(
        shard_map(
            f, mesh=mesh,
            in_specs=(P(), P("expert"), P("expert"), P("expert")),
            out_specs=P("expert"),
            check_vma=False,
        )
    )
    out = ep(params["router"], params["w_in"], params["w_out"], x)

    expect = jnp.concatenate(
        [moe.apply_dense(params, x[i * T_loc : (i + 1) * T_loc]) for i in range(n_ep)]
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect), rtol=2e-4, atol=2e-5)


def test_moe_capacity_drops_tokens():
    moe = MoE(n_experts=2, capacity_factor=0.5)  # capacity 1 slot for 4 tokens
    params = moe.init(jax.random.PRNGKey(1), 8, 16)
    x = jnp.asarray(np.random.default_rng(2).normal(size=(4, 8)), jnp.float32)
    out = moe.apply_dense(params, x)
    # at most 2 tokens (1 per expert) produce nonzero output
    nonzero = np.asarray((jnp.abs(out).sum(-1) > 1e-6))
    assert nonzero.sum() <= 2


def test_pipeline_matches_sequential():
    """4-stage pipeline over 'pipe' axis ≡ applying the 4 stages in order."""
    n_stages, n_micro = 4, 6
    mesh = mesh_lib.device_mesh([n_stages], ["pipe"], jax.devices()[:n_stages])
    rng = np.random.default_rng(0)
    d = 8
    ws = jnp.asarray(rng.normal(size=(n_stages, d, d)), jnp.float32) * 0.3
    x = jnp.asarray(rng.normal(size=(n_micro, 4, d)), jnp.float32)

    def stage_fn(w, h):
        return jnp.tanh(h @ w)

    # sequential reference
    ref = x
    for s in range(n_stages):
        ref = jnp.tanh(ref @ ws[s])

    pp = jax.jit(
        shard_map(
            lambda w_l, xm: pipeline_apply(stage_fn, w_l[0], xm, "pipe", n_stages),
            mesh=mesh,
            in_specs=(P("pipe"), P()),
            out_specs=P(),
            check_vma=False,
        )
    )
    out = pp(ws, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_pipeline_differentiable_per_device():
    """Production convention: grads taken INSIDE shard_map (per-device loss
    replica, as make_train_step does) match the sequential reference."""
    n_stages, n_micro, d = 4, 4, 6
    mesh = mesh_lib.device_mesh([n_stages], ["pipe"], jax.devices()[:n_stages])
    rng = np.random.default_rng(1)
    ws = jnp.asarray(rng.normal(size=(n_stages, d, d)), jnp.float32) * 0.3
    x = jnp.asarray(rng.normal(size=(n_micro, 2, d)), jnp.float32)

    def stage_fn(w, h):
        return jnp.tanh(h @ w)

    def local(w_l, xm):
        def lf(w_l):
            out = pipeline_apply(stage_fn, w_l[0], xm, "pipe", n_stages)
            return jnp.sum(out ** 2)

        return jax.grad(lf)(w_l)

    g_pp = shard_map(
        local, mesh=mesh, in_specs=(P("pipe"), P()), out_specs=P("pipe"),
        check_vma=False,
    )(ws, x)

    def loss_seq(ws):
        h = x
        for s in range(n_stages):
            h = jnp.tanh(h @ ws[s])
        return jnp.sum(h ** 2)

    g_seq = jax.grad(loss_seq)(ws)
    np.testing.assert_allclose(np.asarray(g_pp), np.asarray(g_seq), rtol=1e-3, atol=1e-4)


def test_moe_top2_matches_manual_reference():
    """Independent numpy ground truth: with ample capacity, each token's
    output is the renormalized-gate-weighted sum of its two experts."""
    moe = MoE(n_experts=4, capacity_factor=16.0, top_k=2)
    d, f, T = 8, 12, 6
    params = jax.tree_util.tree_map(
        np.asarray, moe.init(jax.random.PRNGKey(6), d, f)
    )
    x = np.random.default_rng(7).normal(size=(T, d)).astype(np.float32)

    logits = x @ params["router"].astype(np.float32)
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    ref = np.zeros_like(x)
    for t in range(T):
        top2 = np.argsort(probs[t])[::-1][:2]
        g = probs[t][top2] / probs[t][top2].sum()
        for gi, e in zip(g, top2):
            h = np.asarray(jax.nn.gelu(x[t] @ params["w_in"][e]))
            ref[t] += gi * (h @ params["w_out"][e])

    out = np.asarray(moe.apply_dense(jax.tree_util.tree_map(jnp.asarray, params), jnp.asarray(x)))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_moe_top2_first_choices_outrank_second_choices():
    """Choice-major priority, pinned on a hand-built case where the rule
    actually decides the outcome: token 0's SECOND choice and token 1's
    FIRST choice want the same expert's single slot — the first choice
    must win even though token 0 comes earlier.

    (A token-major regression — e.g. reshape(T*k, E) without the
    transpose — would give token 0's second choice the slot and fail.)"""
    moe = MoE(n_experts=2, capacity_factor=0.25, top_k=2)  # C = 1
    # router picked so token 0 ranks [E0, E1], token 1 ranks [E1, E0]
    params = {"router": jnp.asarray([[2.0, 1.0], [1.0, 2.0]], jnp.float32)}
    x = jnp.asarray([[1.0, 0.0], [0.0, 1.0]], jnp.float32)
    C = moe._capacity(2)
    assert C == 1
    pack, _, _ = moe._route(params, x, C)
    pack = np.asarray(pack)  # [T, E, C]
    assert pack[0, 0].sum() == 1.0, "token 0's FIRST choice (E0) keeps its slot"
    assert pack[1, 1].sum() == 1.0, "token 1's FIRST choice (E1) wins the slot"
    assert pack[0, 1].sum() == 0.0, "token 0's SECOND choice (E1) is dropped"
    assert pack[1, 0].sum() == 0.0, "token 1's SECOND choice (E0) is dropped"


@pytest.mark.slow  # tier-1 budget (ISSUE 17): gates in analysis.yml
def test_trainer_moe_top2_e2e():
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer

    cfg = TrainConfig(
        dataset="synthetic", model="vit_moe_tiny", num_classes=10,
        batch_size=16, epochs=1, steps_per_epoch=2, log_every=1, lr=0.05,
        eval_every=1, ep=4, moe_top_k=2, sync_bn=False, synthetic_n=160,
    )
    t = Trainer(cfg)
    assert t.model.top_k == 2
    out = t.fit()
    assert np.isfinite(out["loss"])


def test_moe_aux_loss_values():
    """Load-balancing loss: ~1 for a uniform router, ~E when collapsed."""
    moe = MoE(n_experts=4, capacity_factor=4.0, top_k=1)
    d = 8
    T = 64
    x = jnp.asarray(np.random.default_rng(10).normal(size=(T, d)), jnp.float32)

    # near-uniform router: tiny weights -> probs ~ 1/E, f_e ~ 1/E
    params_uniform = {"router": jnp.zeros((d, 4), jnp.float32) + 1e-6 * jnp.asarray(
        np.random.default_rng(11).normal(size=(d, 4)), jnp.float32
    )}
    _, _, aux_u = moe._route(params_uniform, x, moe._capacity(T))
    assert abs(float(aux_u) - 1.0) < 0.15

    # collapsed router: everything to expert 0 -> f_0=1, P_0~1 -> aux ~ E
    params_collapsed = {"router": jnp.zeros((d, 4), jnp.float32).at[:, 0].set(50.0)}
    xpos = jnp.abs(x)  # keep logits for expert 0 dominant
    _, _, aux_c = moe._route(params_collapsed, xpos, moe._capacity(T))
    assert float(aux_c) > 2.5


@pytest.mark.slow  # tier-1 budget (ISSUE 18): gates in analysis.yml
def test_moe_aux_loss_threads_through_train_step():
    """vit_moe returns the aux loss in its state; the train step must pop
    it (stable TrainState structure) and fold coef*aux into the loss."""
    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.nn.vit_moe import vit_moe_tiny
    from tpu_dist.train.optim import SGD
    from tpu_dist.train.state import TrainState
    from tpu_dist.train.step import make_train_step

    mesh = mesh_lib.data_parallel_mesh()
    model = vit_moe_tiny(num_classes=5)
    opt = SGD()
    params, st = model.init(jax.random.PRNGKey(12))
    state0 = jax.device_put(
        TrainState.create(params, st, opt), mesh_lib.replicated(mesh)
    )

    rng = np.random.default_rng(13)
    x = mesh_lib.shard_batch(mesh, rng.normal(size=(16, 32, 32, 3)).astype(np.float32))
    y = mesh_lib.shard_batch(mesh, rng.integers(0, 5, 16).astype(np.int32))

    losses = {}
    for coef in (0.0, 10.0):
        step = make_train_step(
            model.apply, opt, mesh, sync_bn=False, donate=False, moe_aux_coef=coef
        )
        s1, m1 = step(state0, x, y, 0.0)
        # structure unchanged -> a second step reuses the SAME compiled fn
        s2, m2 = step(s1, x, y, 0.0)
        assert jax.tree_util.tree_structure(s1) == jax.tree_util.tree_structure(state0)
        losses[coef] = float(m1["loss"])
    # aux > 0 always, so the coef=10 objective is strictly larger
    assert losses[10.0] > losses[0.0] + 1e-3
