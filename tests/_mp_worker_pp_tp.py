"""Worker for the multi-host PP×TP (Megatron layout) test.

Launched by tests/test_multihost.py as 2 processes × 4 CPU devices: one
8-device global mesh laid out ``[data=2, pipe=2, model=2]`` HOST-MAJOR,
so every pipe×model group of 4 is intra-host (the stage ring's ppermute
and each block's TP psums stay on the ICI side of the ICI/DCN split,
only the data axis crosses processes).  The same ``run_pp_tp_training``
is also called by the parent test in-process (1 process × 8 devices) as
the reference.

Usage: python tests/_mp_worker_pp_tp.py <coordinator> <num_procs> <proc_id>
"""

import os
import sys

if __name__ == "__main__":
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    ).strip()

import jax  # noqa: E402

if __name__ == "__main__":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def _to_host(x) -> np.ndarray:
    if getattr(x, "is_fully_addressable", True):
        return np.asarray(jax.device_get(x))
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def run_pp_tp_training():
    """Train a tiny staged+TP ViT 3 steps on a [data=2, pipe=2, model=2]
    mesh from ALL global devices; returns (loss, replicated fingerprint,
    pipe×model-sharded block fingerprint)."""
    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.nn.vit_pp import ViTPipelineDef
    from tpu_dist.train.optim import SGD
    from tpu_dist.train.state import TrainState
    from tpu_dist.train.step import make_train_step

    mesh = mesh_lib.device_mesh([2, 2, 2], ["data", "pipe", "model"])
    assert mesh_lib.model_axes_intra_host(mesh, ["pipe", "model"]), (
        "host-major mesh must keep the pipe ring and tp groups intra-host"
    )

    model = ViTPipelineDef(image_size=16, patch_size=4, dim=32, depth=4,
                           heads=4, num_classes=5)
    specs = model.pp_tp_param_specs("pipe", "model")
    opt = SGD()
    params, s = model.init(jax.random.PRNGKey(0))
    st = TrainState.create(params, s, opt)
    state = TrainState(
        params=mesh_lib.place_host_tree(mesh, st.params, specs),
        bn_state=mesh_lib.place_host_tree(mesh, st.bn_state),
        opt_state=mesh_lib.place_host_tree(mesh, st.opt_state, specs),
        step=mesh_lib.place_host_tree(mesh, st.step),
    )
    step = make_train_step(
        model.apply, opt, mesh, sync_bn=False, donate=False,
        pp_axis="pipe", tp_axis="model", param_specs=specs,
    )

    rng = np.random.default_rng(0)
    all_x = rng.normal(size=(8, 16, 16, 3)).astype(np.float32)
    all_y = rng.integers(0, 5, 8).astype(np.int32)
    per = all_x.shape[0] // jax.process_count()
    lo = jax.process_index() * per
    xs = mesh_lib.shard_batch(mesh, all_x[lo:lo + per])
    ys = mesh_lib.shard_batch(mesh, all_y[lo:lo + per])

    for _ in range(3):
        state, metrics = step(state, xs, ys, 0.05)
    loss = float(_to_host(metrics["loss"]))
    fp_rep = float(_to_host(state.params["patch"]["b"]).sum())
    fp_blk = float(_to_host(state.params["blocks"]["qkv"]["w"]).sum())
    return loss, fp_rep, fp_blk


def main(coordinator: str, num_procs: int, proc_id: int) -> None:
    from tpu_dist.comm import mesh as mesh_lib

    mesh_lib.initialize_distributed(coordinator, num_procs, proc_id)
    assert jax.process_count() == num_procs
    assert jax.local_device_count() == 4
    loss, fp_rep, fp_blk = run_pp_tp_training()
    print(f"PPTPRESULT {proc_id} {loss:.6f} {fp_rep:.6f} {fp_blk:.6f}", flush=True)


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
