"""The self-contained tfevents writer (metrics/tensorboard.py) must produce
files the OFFICIAL TensorBoard reader parses — record framing (masked
CRC32C), protobuf wire format, and values all checked by round-trip."""

import pytest
import numpy as np

from tpu_dist.metrics.tensorboard import SummaryWriter, _crc32c


def test_crc32c_known_vectors():
    # standard CRC32C test vectors
    assert _crc32c(b"") == 0x00000000
    assert _crc32c(b"123456789") == 0xE3069283
    assert _crc32c(b"\x00" * 32) == 0x8A9136AA


def test_roundtrip_via_tensorboard_reader(tmp_path):
    from tensorboard.backend.event_processing import event_accumulator

    with SummaryWriter(str(tmp_path)) as w:
        for step in range(5):
            w.add_scalar("train/loss", 2.0 / (step + 1), step)
        w.add_scalar("eval/top1", 73.25, 4)

    ea = event_accumulator.EventAccumulator(str(tmp_path))
    ea.Reload()
    tags = ea.Tags()["scalars"]
    assert set(tags) == {"train/loss", "eval/top1"}
    losses = ea.Scalars("train/loss")
    assert [e.step for e in losses] == [0, 1, 2, 3, 4]
    np.testing.assert_allclose(
        [e.value for e in losses], [2.0 / (s + 1) for s in range(5)], rtol=1e-6
    )
    (top1,) = ea.Scalars("eval/top1")
    assert top1.step == 4 and abs(top1.value - 73.25) < 1e-4


@pytest.mark.slow  # >10s e2e: excluded from the timed tier-1 gate; the
# quick slice keeps a fast representative of this subsystem in the gate
def test_trainer_writes_tensorboard(tmp_path):
    from tensorboard.backend.event_processing import event_accumulator

    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer, register_model
    from tests.helpers import tiny_resnet

    register_model("tiny_resnet_tb", lambda num_classes=10: tiny_resnet(num_classes))
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_tb", num_classes=10,
        batch_size=64, epochs=2, steps_per_epoch=2, log_every=10,
        eval_every=2, tensorboard_dir=str(tmp_path),
    )
    Trainer(cfg).fit(2)

    ea = event_accumulator.EventAccumulator(str(tmp_path))
    ea.Reload()
    tags = set(ea.Tags()["scalars"])
    assert {"train/loss", "train/lr", "eval/top1"} <= tags
    assert [e.step for e in ea.Scalars("train/loss")] == [0, 1]
    assert [e.step for e in ea.Scalars("eval/top1")] == [1]
