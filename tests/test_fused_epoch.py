"""Device-resident fused-epoch runner (tpu_dist/train/epoch.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.data import synthetic_cifar
from tpu_dist.train.epoch import make_fused_epoch, put_dataset_on_device
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tests.helpers import TinyConvNet


def _setup(n=256, bpd=4):
    mesh = mesh_lib.data_parallel_mesh()
    imgs, lbls = synthetic_cifar(n, 10, image_size=8, seed=0)
    dx, dy = put_dataset_on_device(mesh, imgs, lbls)
    model = TinyConvNet()
    opt = SGD()
    params, bn = model.init(jax.random.PRNGKey(0))
    state = jax.device_put(TrainState.create(params, bn, opt), mesh_lib.replicated(mesh))
    runner = make_fused_epoch(
        model.apply, opt, mesh, batch_per_device=bpd, compute_dtype=jnp.float32
    )
    return mesh, dx, dy, state, runner


def test_fused_epoch_runs_all_steps_and_trains():
    mesh, dx, dy, state, runner = _setup(n=256, bpd=4)
    # 256 examples / 8 devices = 32 local; bpd 4 -> 8 steps/epoch
    s1, m1 = runner(state, dx, dy, 0.1, 0)
    assert int(s1.step) == 8
    losses = [float(m1["loss"])]
    s = s1
    for e in range(1, 6):
        s, m = runner(s, dx, dy, 0.1, e)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses
    assert int(s.step) == 48


def test_fused_epoch_deterministic_per_epoch_idx():
    _, dx, dy, state, runner = _setup()
    a, ma = runner(state, dx, dy, 0.1, 0)
    _, dx2, dy2, state2, runner2 = _setup()
    b, mb = runner2(state2, dx2, dy2, 0.1, 0)
    np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]), rtol=1e-6)
    for x, y in zip(jax.tree_util.tree_leaves(a.params), jax.tree_util.tree_leaves(b.params)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6)


def test_fused_epoch_reshuffles_between_epochs():
    _, dx, dy, state, runner = _setup()
    s1, m1 = runner(state, dx, dy, 0.0, 0)  # lr=0: params frozen
    s2, m2 = runner(s1, dx, dy, 0.0, 1)
    # with lr=0 the only difference between epochs is batch order/augment →
    # metrics differ unless shuffling is broken
    assert float(m1["loss"]) != float(m2["loss"])


def test_fused_epoch_grad_compression():
    """The fused path honors the shared grad-compression contract: bf16
    wire trains (finite, close to uncompressed), bad modes are refused at
    build time (same validation as make_train_step)."""
    import pytest

    mesh = mesh_lib.data_parallel_mesh()
    imgs, lbls = synthetic_cifar(256, 10, image_size=8, seed=0)
    dx, dy = put_dataset_on_device(mesh, imgs, lbls)
    model = TinyConvNet()
    opt = SGD()
    params, bn = model.init(jax.random.PRNGKey(0))
    # host copies: the runner donates its input state, and device_put can
    # alias rather than copy — a donated alias would poison the second use
    params = jax.tree_util.tree_map(np.asarray, params)
    bn = jax.tree_util.tree_map(np.asarray, bn)

    def fresh_state():
        return jax.device_put(
            TrainState.create(params, bn, opt), mesh_lib.replicated(mesh)
        )

    plain = make_fused_epoch(
        model.apply, opt, mesh, batch_per_device=4, compute_dtype=jnp.float32
    )
    comp = make_fused_epoch(
        model.apply, opt, mesh, batch_per_device=4, compute_dtype=jnp.float32,
        grad_compression="bf16",
    )
    s_p, m_p = plain(fresh_state(), dx, dy, 0.1, 0)
    s_c, m_c = comp(fresh_state(), dx, dy, 0.1, 0)
    assert np.isfinite(float(m_c["loss"]))
    for a, b in zip(
        jax.tree_util.tree_leaves(s_p.params), jax.tree_util.tree_leaves(s_c.params)
    ):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=3e-2, atol=3e-3)

    with pytest.raises(ValueError, match="grad_compression"):
        make_fused_epoch(
            model.apply, opt, mesh, batch_per_device=4, grad_compression="fp16"
        )
