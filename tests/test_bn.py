"""SyncBatchNorm: cross-replica statistics (reference ``distributed.py:59``,
SURVEY §2.2 N5)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
from tpu_dist.comm.compat import shard_map

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.nn import layers as L


def _run_bn(x_global, axis_name):
    mesh = mesh_lib.data_parallel_mesh()
    params, state = L.bn_init(x_global.shape[-1])

    def f(p, s, x):
        y, ns = L.bn_apply(p, s, x, train=True, axis_name=axis_name)
        return y, ns

    sharded = jax.jit(
        shard_map(
            f, mesh=mesh,
            in_specs=(P(), P(), P("data")),
            out_specs=(P("data"), P("data") if axis_name is None else P()),
            check_vma=False,
        )
    )
    return sharded(params, state, x_global)


def test_sync_bn_normalizes_with_global_stats():
    # per-replica distributions differ wildly; only SYNC BN centers globally
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 4, 4, 3)).astype(np.float32)
    x[:8] += 10.0  # first replicas see shifted data

    y_sync, _ = _run_bn(x, "data")
    y = np.asarray(y_sync)
    # global mean of normalized output ~ 0, var ~ 1
    np.testing.assert_allclose(y.mean(axis=(0, 1, 2)), 0.0, atol=1e-4)
    np.testing.assert_allclose(y.var(axis=(0, 1, 2)), 1.0, atol=1e-3)
    # within the shifted half, mean stays clearly positive (global stats used)
    assert y[:8].mean() > 0.5


def test_local_bn_normalizes_per_replica():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 4, 4, 3)).astype(np.float32)
    x[:8] += 10.0

    y_local, _ = _run_bn(x, None)
    y = np.asarray(y_local)
    # each replica normalized independently -> both halves centered
    np.testing.assert_allclose(y[:8].mean(), 0.0, atol=1e-3)
    np.testing.assert_allclose(y[8:].mean(), 0.0, atol=1e-3)


def test_sync_bn_running_stats_match_global_batch():
    rng = np.random.default_rng(1)
    x = rng.normal(loc=2.0, scale=3.0, size=(32, 2, 2, 5)).astype(np.float32)
    _, ns = _run_bn(x, "data")
    mean = np.asarray(ns["mean"])
    got = mean / L.BN_MOMENTUM  # running = 0.9*0 + 0.1*batch_mean
    np.testing.assert_allclose(got, x.mean(axis=(0, 1, 2)), rtol=1e-4, atol=1e-4)
    n = x.size // x.shape[-1]
    var_unbiased = x.var(axis=(0, 1, 2)) * n / (n - 1)
    np.testing.assert_allclose(
        np.asarray(ns["var"]) - 0.9, 0.1 * var_unbiased, rtol=1e-3, atol=1e-4
    )


def test_bn_eval_matches_torch_formula():
    params, state = L.bn_init(3)
    params = {"scale": jnp.array([1.0, 2.0, 0.5]), "bias": jnp.array([0.0, 1.0, -1.0])}
    state = {"mean": jnp.array([0.5, -0.5, 0.0]), "var": jnp.array([4.0, 1.0, 0.25])}
    x = jnp.ones((2, 2, 2, 3))
    y, _ = L.bn_apply(params, state, x, train=False)
    expect = (np.ones(3) - np.array([0.5, -0.5, 0.0])) / np.sqrt(
        np.array([4.0, 1.0, 0.25]) + 1e-5
    ) * np.array([1.0, 2.0, 0.5]) + np.array([0.0, 1.0, -1.0])
    np.testing.assert_allclose(np.asarray(y)[0, 0, 0], expect, rtol=1e-5, atol=1e-6)
