"""Device-resident fused eval: exact sums, matches the streaming evaluator."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.config import TrainConfig
from tpu_dist.data import synthetic_cifar
from tpu_dist.train.epoch import make_fused_eval, put_dataset_on_device
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tpu_dist.train.trainer import Trainer, register_model
from tests.helpers import TinyConvNet, tiny_resnet

register_model("tiny_resnet_fe", lambda num_classes=10: tiny_resnet(num_classes))


def test_fused_eval_counts_and_matches_direct_forward():
    mesh = mesh_lib.data_parallel_mesh()
    model = TinyConvNet(num_classes=10)
    params, bn = model.init(jax.random.PRNGKey(0))
    state = jax.device_put(
        TrainState.create(params, bn, SGD()), mesh_lib.replicated(mesh)
    )
    # 131 examples: not a multiple of 8 devices nor of the batch
    n = 131
    imgs, lbls = synthetic_cifar(n, 10, image_size=8, seed=3)
    pad = (-n) % 8
    imgs_p = np.concatenate([imgs, np.zeros((pad,) + imgs.shape[1:], imgs.dtype)])
    lbls_p = np.concatenate([lbls, np.full(pad, -1, lbls.dtype)])
    dx, dy = put_dataset_on_device(mesh, imgs_p, lbls_p)

    ev = make_fused_eval(model.apply, mesh, batch_per_device=4, compute_dtype=jnp.float32)
    sums = {k: float(v) for k, v in ev(state, dx, dy).items()}
    assert sums["count"] == n

    # ground truth: direct forward over the raw set
    from tpu_dist.data.transforms import CIFAR100_MEAN, CIFAR100_STD

    x = (imgs.astype(np.float32) / 255.0 - CIFAR100_MEAN) / CIFAR100_STD
    logits, _ = model.apply(params, bn, jnp.asarray(x), train=False)
    expect_top1 = int((np.argmax(np.asarray(logits), -1) == lbls).sum())
    assert int(sums["top1"]) == expect_top1


@pytest.mark.slow  # >10s e2e: excluded from the timed tier-1 gate; the
# quick slice keeps a fast representative of this subsystem in the gate
def test_trainer_fused_mode_evaluates():
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_fe", num_classes=10,
        batch_size=256, epochs=1, eval_every=1, fused_epoch=True,
        synthetic_n=1024, log_every=100,
    )
    out = Trainer(cfg).fit()
    assert "val_top1" in out and np.isfinite(out["val_loss"])
