"""Optimizer parity with ``torch.optim.SGD(lr, momentum=0.9, weight_decay=1e-4)``
(reference ``distributed.py:63``) and MultiStepLR (``:64``)."""

import jax
import numpy as np
import jax.numpy as jnp
import pytest

from tpu_dist.train.optim import SGD, multistep_lr


def test_sgd_matches_torch_semantics():
    import torch

    w0 = np.random.default_rng(0).normal(size=(5, 3)).astype(np.float32)

    # torch ground truth
    tw = torch.nn.Parameter(torch.tensor(w0.copy()))
    opt = torch.optim.SGD([tw], lr=0.1, momentum=0.9, weight_decay=1e-4)
    grads = [np.random.default_rng(i + 1).normal(size=w0.shape).astype(np.float32) for i in range(4)]
    for g in grads:
        opt.zero_grad()
        tw.grad = torch.tensor(g.copy())
        opt.step()

    # ours
    sgd = SGD(momentum=0.9, weight_decay=1e-4)
    p = {"w": jnp.array(w0)}
    b = sgd.init(p)
    for g in grads:
        p, b = sgd.update({"w": jnp.array(g)}, b, p, 0.1)

    np.testing.assert_allclose(np.asarray(p["w"]), tw.detach().numpy(), rtol=1e-5, atol=1e-6)


def test_multistep_lr_schedule():
    sched = multistep_lr(0.1, (60, 120, 160), 0.2)
    assert sched(0) == 0.1
    assert sched(59) == 0.1
    assert np.isclose(sched(60), 0.02)
    assert np.isclose(sched(119), 0.02)
    assert np.isclose(sched(120), 0.004)
    assert np.isclose(sched(160), 0.0008)
    assert np.isclose(sched(199), 0.0008)


def test_adamw_matches_optax():
    import optax

    from tpu_dist.train.optim import AdamW

    # decay_mask="all" matches optax.adamw's unmasked default exactly
    opt = AdamW(b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.01, decay_mask="all")
    ref = optax.adamw(
        learning_rate=0.02, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.01
    )

    params = {
        "w": jnp.asarray(np.random.default_rng(0).normal(size=(4, 3)), jnp.float32),
        "b": jnp.zeros((3,), jnp.float32),
    }
    ours_p, ours_s = params, opt.init(params)
    ref_p, ref_s = params, ref.init(params)

    rng = np.random.default_rng(1)
    for _ in range(5):
        grads = jax.tree_util.tree_map(
            lambda p: jnp.asarray(rng.normal(size=p.shape), jnp.float32), params
        )
        ours_p, ours_s = opt.update(grads, ours_s, ours_p, 0.02)
        updates, ref_s = ref.update(grads, ref_s, ref_p)
        ref_p = optax.apply_updates(ref_p, updates)

    for a, b in zip(
        jax.tree_util.tree_leaves(ours_p), jax.tree_util.tree_leaves(ref_p)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_adamw_auto_mask_matches_optax_masked():
    """Default decay_mask='auto' == optax.adamw with the standard
    rank>1 mask: biases/norm scales get no decay (ADVICE r2)."""
    import optax

    from tpu_dist.train.optim import AdamW

    opt = AdamW(weight_decay=0.05)
    mask = lambda params: jax.tree_util.tree_map(lambda p: p.ndim > 1, params)
    ref = optax.adamw(learning_rate=0.02, weight_decay=0.05, mask=mask)

    params = {
        "w": jnp.asarray(np.random.default_rng(0).normal(size=(4, 3)), jnp.float32),
        "b": jnp.ones((3,), jnp.float32),  # nonzero so decay would show
        "ln": {"scale": jnp.ones((4,), jnp.float32)},
    }
    ours_p, ours_s = params, opt.init(params)
    ref_p, ref_s = params, ref.init(params)
    rng = np.random.default_rng(1)
    for _ in range(5):
        grads = jax.tree_util.tree_map(
            lambda p: jnp.asarray(rng.normal(size=p.shape), jnp.float32), params
        )
        ours_p, ours_s = opt.update(grads, ours_s, ours_p, 0.02)
        updates, ref_s = ref.update(grads, ref_s, ref_p)
        ref_p = optax.apply_updates(ref_p, updates)
    for a, b in zip(
        jax.tree_util.tree_leaves(ours_p), jax.tree_util.tree_leaves(ref_p)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


@pytest.mark.slow  # tier-1 budget (ISSUE 17): gates in analysis.yml
def test_trainer_adamw_e2e_with_resume(tmp_path):
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer, register_model
    from tests.helpers import tiny_resnet

    register_model("tiny_resnet_aw", lambda num_classes=10: tiny_resnet(num_classes))
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_aw", num_classes=10,
        batch_size=64, epochs=1, steps_per_epoch=2, log_every=10, lr=1e-3,
        eval_every=0, optimizer="adamw", ckpt_dir=str(tmp_path), save_every=1,
    )
    t = Trainer(cfg)
    out = t.fit(1)
    assert np.isfinite(out["loss"])
    t2 = Trainer(cfg.replace(resume=True, epochs=2))
    assert t2.start_epoch == 1
    # AdamW's count buffer survives the roundtrip
    assert int(np.asarray(t2.state.opt_state["count"])) == int(
        np.asarray(t.state.opt_state["count"])
    )


def test_fsdp_adamw_matches_plain(tmp_path):
    """AdamW under FSDP: mu/nu shard like params, count replicates; the
    trajectory matches the replicated engine."""
    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.parallel.fsdp import fsdp_specs, make_fsdp_train_step
    from tpu_dist.train.optim import AdamW
    from tpu_dist.train.state import TrainState
    from tpu_dist.train.step import make_train_step
    from tests.helpers import TinyMLP

    mesh = mesh_lib.data_parallel_mesh()
    model = TinyMLP(width=128, in_dim=16)
    opt = AdamW()
    params, st = model.init(jax.random.PRNGKey(2))
    specs = fsdp_specs(params, mesh, min_size=64)
    opt_state = opt.init(params)
    opt_specs = fsdp_specs(opt_state, mesh, min_size=64)

    plain = jax.device_put(
        TrainState.create(params, st, opt), mesh_lib.replicated(mesh)
    )
    fsdp = TrainState(
        params=mesh_lib.place_host_tree(mesh, params, specs),
        bn_state=mesh_lib.place_host_tree(mesh, st),
        opt_state=mesh_lib.place_host_tree(mesh, opt_state, opt_specs),
        step=mesh_lib.place_host_tree(mesh, jnp.zeros((), jnp.int32)),
    )
    mu_leaf = fsdp.opt_state["mu"]["l1"]["w"]
    assert any(s is not None for s in mu_leaf.sharding.spec), "mu not sharded"

    plain_step = make_train_step(model.apply, opt, mesh, sync_bn=False, donate=False)
    fsdp_step = make_fsdp_train_step(
        model.apply, opt, mesh, specs, opt_specs=opt_specs, donate=False
    )

    rng = np.random.default_rng(3)
    for _ in range(3):
        x = mesh_lib.shard_batch(mesh, rng.normal(size=(64, 4, 4, 1)).astype(np.float32))
        y = mesh_lib.shard_batch(mesh, rng.integers(0, 10, 64).astype(np.int32))
        plain, mp = plain_step(plain, x, y, 1e-3)
        fsdp, mf = fsdp_step(fsdp, x, y, 1e-3)

    np.testing.assert_allclose(float(mp["loss"]), float(mf["loss"]), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(plain.params), jax.tree_util.tree_leaves(fsdp.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def _large_batch_trajectory(opt, steps=4, lr=0.1):
    """Shared deterministic trajectory for the LARS/LAMB golden pins: a
    2-D weight (adapted + decayed) and a 1-D bias (excluded, like
    AdamW's ``auto`` mask)."""
    w0 = np.random.default_rng(0).normal(size=(4, 3)).astype(np.float32)
    b0 = (np.ones(3) * 0.5).astype(np.float32)
    p = {"w": jnp.array(w0), "b": jnp.array(b0)}
    s = opt.init(p)
    for i in range(steps):
        g = {
            "w": jnp.array(np.random.default_rng(i + 1).normal(size=(4, 3)).astype(np.float32)),
            "b": jnp.array(np.random.default_rng(100 + i).normal(size=(3,)).astype(np.float32)),
        }
        p, s = opt.update(g, s, p, lr)
    return p, s


def test_lars_matches_numpy_reference():
    """4 steps against an independent numpy transcription of the paper's
    update: ``local = η‖p‖/(‖g‖+wd‖p‖)``, momentum on the decayed+scaled
    gradient, rank≤1 leaves plain SGD-momentum."""
    from tpu_dist.train.optim import LARS

    mu, wd, eta, eps = 0.9, 1e-4, 1e-3, 1e-9
    w = np.random.default_rng(0).normal(size=(4, 3)).astype(np.float32)
    b = (np.ones(3) * 0.5).astype(np.float32)
    bw = np.zeros_like(w)
    bb = np.zeros_like(b)
    for i in range(4):
        gw = np.random.default_rng(i + 1).normal(size=(4, 3)).astype(np.float32)
        gb = np.random.default_rng(100 + i).normal(size=(3,)).astype(np.float32)
        pn, gn = np.linalg.norm(w), np.linalg.norm(gw)
        local = eta * pn / (gn + wd * pn + eps) if pn > 0 and gn > 0 else 1.0
        bw = mu * bw + local * (gw + wd * w)
        w = w - 0.1 * bw
        bb = mu * bb + gb  # no adaptation, no decay on rank-1
        b = b - 0.1 * bb

    p, _ = _large_batch_trajectory(LARS())
    np.testing.assert_allclose(np.asarray(p["w"]), w, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(p["b"]), b, rtol=1e-5, atol=1e-6)


def test_lamb_matches_numpy_reference():
    """Bias-corrected Adam direction, decoupled decay folded into the
    update, then the ‖p‖/‖u‖ trust ratio — numpy-transcribed."""
    from tpu_dist.train.optim import LAMB

    b1, b2, eps, wd = 0.9, 0.999, 1e-6, 0.01
    w = np.random.default_rng(0).normal(size=(4, 3)).astype(np.float32)
    b = (np.ones(3) * 0.5).astype(np.float32)
    mw = np.zeros_like(w); vw = np.zeros_like(w)
    mb = np.zeros_like(b); vb = np.zeros_like(b)
    for i in range(4):
        gw = np.random.default_rng(i + 1).normal(size=(4, 3)).astype(np.float32)
        gb = np.random.default_rng(100 + i).normal(size=(3,)).astype(np.float32)
        t = i + 1
        bc1, bc2 = 1.0 - b1 ** t, 1.0 - b2 ** t
        mw = b1 * mw + (1 - b1) * gw; vw = b2 * vw + (1 - b2) * gw**2
        mb = b1 * mb + (1 - b1) * gb; vb = b2 * vb + (1 - b2) * gb**2
        uw = (mw / bc1) / (np.sqrt(vw / bc2) + eps) + wd * w
        r = np.linalg.norm(w) / (np.linalg.norm(uw) + eps)
        w = w - 0.1 * r * uw
        ub = (mb / bc1) / (np.sqrt(vb / bc2) + eps)  # no decay, ratio 1
        b = b - 0.1 * ub

    p, _ = _large_batch_trajectory(LAMB())
    np.testing.assert_allclose(np.asarray(p["w"]), w, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(p["b"]), b, rtol=1e-4, atol=1e-5)


def test_lars_lamb_golden_trajectory_pins():
    """Hard numeric pins of the shared trajectory — a silent change to
    either update rule (new default, reordered decay, dropped bias
    correction) moves these and fails loudly."""
    from tpu_dist.train.optim import LAMB, LARS

    p, s = _large_batch_trajectory(LARS())
    assert float(jnp.sum(p["w"])) == pytest.approx(0.26377815, rel=1e-4)
    assert float(p["w"][0, 0]) == pytest.approx(0.12542857, rel=1e-4)
    assert float(jnp.sum(p["b"])) == pytest.approx(1.37308383, rel=1e-4)
    # momentum state mirrors the param tree (ckpt/state_specs contract)
    assert set(s) == {"w", "b"}

    p, s = _large_batch_trajectory(LAMB())
    assert float(jnp.sum(p["w"])) == pytest.approx(-1.01437378, rel=1e-4)
    assert float(p["w"][0, 0]) == pytest.approx(-0.18847042, rel=1e-4)
    assert float(jnp.sum(p["b"])) == pytest.approx(1.30420136, rel=1e-4)
    # state layout is AdamW's exactly — checkpoints interop
    assert set(s) == {"mu", "nu", "count"}
    assert int(np.asarray(s["count"])) == 4


def test_linear_scaling_rule_and_warmup():
    from tpu_dist.train.optim import linear_scaled_lr

    assert linear_scaled_lr(0.1, 256, 2048) == pytest.approx(0.8)
    assert linear_scaled_lr(0.1, 256, 256) == pytest.approx(0.1)
    with pytest.raises(ValueError):
        linear_scaled_lr(0.1, 0, 256)
    with pytest.raises(ValueError):
        linear_scaled_lr(0.1, 256, -1)

    # warmup ramps linearly to base_lr, then the milestones take over
    sched = multistep_lr(0.8, (10, 20), 0.1, warmup_epochs=5)
    assert sched(0) == pytest.approx(0.8 / 5)
    assert sched(3) == pytest.approx(0.8 * 4 / 5)
    assert sched(4) == pytest.approx(0.8)
    assert sched(9) == pytest.approx(0.8)
    assert sched(10) == pytest.approx(0.08)
    # warmup_epochs=0 stays the reference MultiStepLR (no ramp)
    assert multistep_lr(0.8, (10,), 0.1)(0) == pytest.approx(0.8)


@pytest.mark.slow  # tier-1 budget (ISSUE 18): gates in analysis.yml
def test_trainer_lars_e2e_and_refusals(tmp_path):
    """LARS end-to-end through the Trainer with the full large-batch
    recipe (linear scaling + warmup), plus the two config refusals: the
    fused SGD kernel and the ZeRO-1 flat layout both destroy the
    per-layer norms LARS needs."""
    import pytest

    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer

    cfg = TrainConfig(
        dataset="synthetic", model="vit_tiny", num_classes=10, batch_size=64,
        epochs=1, steps_per_epoch=2, log_every=10, lr=0.1, lr_base_batch=256,
        warmup_epochs=1, eval_every=0, optimizer="lars", sync_bn=False,
        synthetic_n=256,
    )
    out = Trainer(cfg).fit()
    assert np.isfinite(out["loss"])

    with pytest.raises(ValueError, match="fused"):
        Trainer(cfg.replace(optimizer="lars", fused_optimizer=True))
    with pytest.raises(ValueError, match="ZeRO-1"):
        Trainer(cfg.replace(optimizer="lamb", shard_weight_update=True))


@pytest.mark.slow  # tier-1 budget (ISSUE 17): gates in analysis.yml
def test_trainer_adamw_tp_e2e():
    """AdamW under tensor parallelism: {mu,nu,count} placed/spec'd via
    optimizer.state_specs, train + eval run (the pytree-mismatch trap)."""
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer

    cfg = TrainConfig(
        dataset="synthetic", model="vit_tiny", num_classes=10, batch_size=16,
        epochs=1, steps_per_epoch=2, log_every=1, lr=1e-3, eval_every=1,
        tp=2, sync_bn=False, synthetic_n=160, optimizer="adamw",
    )
    out = Trainer(cfg).fit()
    assert np.isfinite(out["loss"])
    assert "val_top1" in out
