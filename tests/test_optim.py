"""Optimizer parity with ``torch.optim.SGD(lr, momentum=0.9, weight_decay=1e-4)``
(reference ``distributed.py:63``) and MultiStepLR (``:64``)."""

import numpy as np
import jax.numpy as jnp

from tpu_dist.train.optim import SGD, multistep_lr


def test_sgd_matches_torch_semantics():
    import torch

    w0 = np.random.default_rng(0).normal(size=(5, 3)).astype(np.float32)

    # torch ground truth
    tw = torch.nn.Parameter(torch.tensor(w0.copy()))
    opt = torch.optim.SGD([tw], lr=0.1, momentum=0.9, weight_decay=1e-4)
    grads = [np.random.default_rng(i + 1).normal(size=w0.shape).astype(np.float32) for i in range(4)]
    for g in grads:
        opt.zero_grad()
        tw.grad = torch.tensor(g.copy())
        opt.step()

    # ours
    sgd = SGD(momentum=0.9, weight_decay=1e-4)
    p = {"w": jnp.array(w0)}
    b = sgd.init(p)
    for g in grads:
        p, b = sgd.update({"w": jnp.array(g)}, b, p, 0.1)

    np.testing.assert_allclose(np.asarray(p["w"]), tw.detach().numpy(), rtol=1e-5, atol=1e-6)


def test_multistep_lr_schedule():
    sched = multistep_lr(0.1, (60, 120, 160), 0.2)
    assert sched(0) == 0.1
    assert sched(59) == 0.1
    assert np.isclose(sched(60), 0.02)
    assert np.isclose(sched(119), 0.02)
    assert np.isclose(sched(120), 0.004)
    assert np.isclose(sched(160), 0.0008)
    assert np.isclose(sched(199), 0.0008)
