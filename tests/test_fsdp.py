"""FSDP (ZeRO-3 via GSPMD, parallel/fsdp.py) ≡ the plain data-parallel path.

Sharding annotations must change the schedule, never the math: every test
here drives the SAME batches through the explicit shard_map DP engine and
the GSPMD FSDP engine and asserts identical trajectories, while separately
asserting that the FSDP state really is sharded (the whole point)."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.parallel.fsdp import (
    fsdp_specs,
    make_fsdp_eval_step,
    make_fsdp_train_step,
)
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tpu_dist.train.step import make_train_step
from tests.helpers import TinyConvNet, TinyMLP


def _mesh():
    return mesh_lib.data_parallel_mesh()


def test_fsdp_specs_rules():
    mesh = _mesh()  # 8 devices
    params = {
        "big_div": jnp.zeros((3, 3, 16, 64)),     # 64 % 8 == 0 -> sharded dim 3
        "big_lead": jnp.zeros((256, 5)),          # 256 % 8 == 0 -> sharded dim 0
        "big_nodiv": jnp.zeros((9, 121)),         # no dim divisible by 8
        "small": jnp.zeros((64,)),                # below min_size
        "scalar": jnp.zeros(()),
    }
    specs = fsdp_specs(params, mesh)
    assert specs["big_div"] == P(None, None, None, "data")
    assert specs["big_lead"] == P("data", None)
    assert specs["big_nodiv"] == P()
    assert specs["small"] == P()
    assert specs["scalar"] == P()


def _fsdp_state(mesh, params, bn, opt, specs):
    return TrainState(
        params=mesh_lib.place_host_tree(mesh, params, specs),
        bn_state=mesh_lib.place_host_tree(mesh, bn),
        opt_state=mesh_lib.place_host_tree(mesh, opt.init(params), specs),
        step=mesh_lib.place_host_tree(mesh, jnp.zeros((), jnp.int32)),
    )


def _assert_some_leaf_sharded(state):
    sharded = [
        l for l in jax.tree_util.tree_leaves(state.params)
        if any(s is not None for s in l.sharding.spec)
    ]
    assert sharded, "FSDP state has no sharded param leaf — specs degenerated"


def test_fsdp_matches_plain_dp_with_bn():
    """TinyConvNet has BatchNorm: checks GSPMD's global-batch statistics
    equal the shard_map SyncBN pmean path."""
    mesh = _mesh()
    model = TinyConvNet(width=16)
    opt = SGD()
    params, bn = model.init(jax.random.PRNGKey(0))
    specs = fsdp_specs(params, mesh, min_size=64)

    plain = jax.device_put(
        TrainState.create(params, bn, opt), mesh_lib.replicated(mesh)
    )
    fsdp = _fsdp_state(mesh, params, bn, opt, specs)
    _assert_some_leaf_sharded(fsdp)

    plain_step = make_train_step(model.apply, opt, mesh, donate=False, sync_bn=True)
    fsdp_step = make_fsdp_train_step(model.apply, opt, mesh, specs, donate=False)

    rng = np.random.default_rng(0)
    for _ in range(3):
        x = mesh_lib.shard_batch(mesh, rng.normal(size=(64, 8, 8, 3)).astype(np.float32))
        y = mesh_lib.shard_batch(mesh, rng.integers(0, 10, 64).astype(np.int32))
        plain, mp = plain_step(plain, x, y, 0.1)
        fsdp, mf = fsdp_step(fsdp, x, y, 0.1)

    for k in ("loss", "acc1", "acc5"):
        np.testing.assert_allclose(float(mp[k]), float(mf[k]), rtol=1e-5, atol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(plain.params),
        jax.tree_util.tree_leaves(fsdp.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(plain.bn_state),
        jax.tree_util.tree_leaves(fsdp.bn_state),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_fsdp_grad_accum_with_bn_matches_plain():
    """The hard case: BatchNorm + accumulation. Chunk membership must match
    the shard_map engine's per-device order or per-chunk global BN stats
    (and thus grads AND running stats) silently diverge."""
    mesh = _mesh()
    model = TinyConvNet(width=16)
    opt = SGD()
    params, bn = model.init(jax.random.PRNGKey(5))
    specs = fsdp_specs(params, mesh, min_size=64)

    plain = jax.device_put(
        TrainState.create(params, bn, opt), mesh_lib.replicated(mesh)
    )
    fsdp = _fsdp_state(mesh, params, bn, opt, specs)

    kw = dict(donate=False, grad_accum_steps=2)
    plain_step = make_train_step(model.apply, opt, mesh, sync_bn=True, **kw)
    fsdp_step = make_fsdp_train_step(model.apply, opt, mesh, specs, **kw)

    rng = np.random.default_rng(6)
    for _ in range(2):
        x = mesh_lib.shard_batch(mesh, rng.normal(size=(64, 8, 8, 3)).astype(np.float32))
        y = mesh_lib.shard_batch(mesh, rng.integers(0, 10, 64).astype(np.int32))
        plain, mp = plain_step(plain, x, y, 0.1)
        fsdp, mf = fsdp_step(fsdp, x, y, 0.1)

    for k in ("loss", "acc1", "acc5"):
        np.testing.assert_allclose(float(mp[k]), float(mf[k]), rtol=1e-5, atol=1e-5)
    for tree in ("params", "bn_state"):
        for a, b in zip(
            jax.tree_util.tree_leaves(getattr(plain, tree)),
            jax.tree_util.tree_leaves(getattr(fsdp, tree)),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            )


def test_fsdp_grad_accum_and_clip_match_plain():
    """K=2 accumulation + global-norm clip, both engines, exact math model
    (TinyMLP is BN-free so trajectories are arithmetically identical)."""
    mesh = _mesh()
    model = TinyMLP(width=128, in_dim=16)
    opt = SGD()
    params, st = model.init(jax.random.PRNGKey(1))
    specs = fsdp_specs(params, mesh, min_size=64)

    plain = jax.device_put(
        TrainState.create(params, st, opt), mesh_lib.replicated(mesh)
    )
    fsdp = _fsdp_state(mesh, params, st, opt, specs)
    _assert_some_leaf_sharded(fsdp)

    kw = dict(donate=False, grad_accum_steps=2, grad_clip_norm=0.5)
    plain_step = make_train_step(model.apply, opt, mesh, sync_bn=False, **kw)
    fsdp_step = make_fsdp_train_step(model.apply, opt, mesh, specs, **kw)

    rng = np.random.default_rng(2)
    for _ in range(3):
        x = mesh_lib.shard_batch(mesh, rng.normal(size=(64, 4, 4, 1)).astype(np.float32))
        y = mesh_lib.shard_batch(mesh, rng.integers(0, 10, 64).astype(np.int32))
        plain, mp = plain_step(plain, x, y, 0.1)
        fsdp, mf = fsdp_step(fsdp, x, y, 0.1)

    np.testing.assert_allclose(float(mp["loss"]), float(mf["loss"]), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(plain.params),
        jax.tree_util.tree_leaves(fsdp.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_fsdp_eval_step_sums_contract():
    """Masked global sums: padding rows contribute nothing, count is exact."""
    mesh = _mesh()
    model = TinyMLP(width=128, in_dim=16)
    params, st = model.init(jax.random.PRNGKey(3))
    opt = SGD()
    specs = fsdp_specs(params, mesh, min_size=64)
    state = _fsdp_state(mesh, params, st, opt, specs)

    eval_step = make_fsdp_eval_step(model.apply, mesh, specs)
    rng = np.random.default_rng(4)
    x = rng.normal(size=(16, 4, 4, 1)).astype(np.float32)
    y = rng.integers(0, 10, 16).astype(np.int32)
    mask = np.ones(16, np.float32)
    mask[-3:] = 0.0  # sampler padding
    sums = eval_step(
        state,
        mesh_lib.shard_batch(mesh, x),
        mesh_lib.shard_batch(mesh, y),
        mesh_lib.shard_batch(mesh, mask),
    )
    assert float(sums["count"]) == 13.0
    assert float(sums["top1"]) <= 13.0
    assert np.isfinite(float(sums["loss"]))


@pytest.mark.slow  # >10s e2e: excluded from the timed tier-1 gate; the
# quick slice keeps a fast representative of this subsystem in the gate
def test_trainer_fsdp_e2e_with_resume(tmp_path):
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer, register_model
    from tests.helpers import tiny_resnet

    register_model("tiny_resnet_fsdp", lambda num_classes=10: tiny_resnet(num_classes))
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_fsdp", num_classes=10,
        batch_size=64, epochs=1, steps_per_epoch=3, log_every=10, lr=0.1,
        eval_every=1, fsdp=True, ckpt_dir=str(tmp_path), save_every=1,
    )
    t = Trainer(cfg)
    _assert_some_leaf_sharded(t.state)
    out = t.fit(1)
    assert np.isfinite(out["loss"])
    assert "val_top1" in out

    # resume restores into the sharded layout and continues
    t2 = Trainer(cfg.replace(resume=True, epochs=2))
    assert t2.start_epoch == 1
    _assert_some_leaf_sharded(t2.state)
    for a, b in zip(
        jax.tree_util.tree_leaves(t.state.params),
        jax.tree_util.tree_leaves(t2.state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)


def test_trainer_fsdp_flag_walls():
    import pytest

    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer

    base = dict(
        dataset="synthetic", num_classes=10, batch_size=16, epochs=1,
        synthetic_n=64, fsdp=True,
    )
    for bad in (
        dict(sp=2, model="vit_tiny"),  # sp/ep/pp stay refused; tp composes
        dict(shard_weight_update=True),
        dict(fused_epoch=True),
        dict(fused_optimizer=True),
        dict(debug_replica_check=True),
    ):
        with pytest.raises(ValueError):
            Trainer(TrainConfig(**base, **bad))


# -- FSDP x TP (VERDICT r2 #5) -----------------------------------------------


def _mesh_2d(tp=2):
    n = len(jax.devices())
    return mesh_lib.device_mesh(
        [n // tp, tp], [mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS]
    )


def test_compose_fsdp_specs_overlay():
    from tpu_dist.parallel.fsdp import compose_fsdp_specs

    mesh = _mesh_2d(tp=2)  # data=4, model=2
    params = {
        "qkv_w": jnp.zeros((64, 192)),   # model on dim1 -> data on dim0
        "proj_w": jnp.zeros((64, 64)),   # model on dim0 -> data on dim1
        "free": jnp.zeros((128, 33)),    # no model spec -> data on dim0
        "small_b": jnp.zeros((192,)),    # model on dim0, below min_size
        "tiny": jnp.zeros((8,)),
    }
    mspecs = {
        "qkv_w": P(None, "model"),
        "proj_w": P("model", None),
        "free": P(),
        "small_b": P("model"),
        "tiny": P(),
    }
    specs = compose_fsdp_specs(params, mesh, mspecs, min_size=1024)
    assert specs["qkv_w"] == P("data", "model")
    assert specs["proj_w"] == P("model", "data")
    assert specs["free"] == P("data")
    assert specs["small_b"] == P("model")  # model sharding preserved
    assert specs["tiny"] == P()


def test_fsdp_tp_matches_plain_dp():
    """FSDP x TP (GSPMD spec overlay) must be arithmetically identical to
    plain replicated DP: specs change the schedule, never the math."""
    from tpu_dist.nn.vit import vit_tiny
    from tpu_dist.parallel.fsdp import compose_fsdp_specs

    model = vit_tiny(num_classes=10, image_size=16)
    opt = SGD()
    params, st = model.init(jax.random.PRNGKey(7))

    mesh1 = _mesh()            # 8-way plain DP reference
    mesh2 = _mesh_2d(tp=2)     # data=4 x model=2
    specs = compose_fsdp_specs(
        params, mesh2, model.tp_param_specs(mesh_lib.MODEL_AXIS), min_size=256
    )
    # the composition must actually use BOTH axes somewhere
    flat = [tuple(s) for s in jax.tree_util.tree_leaves(
        specs, is_leaf=lambda s: isinstance(s, P))]
    assert any("model" in f and "data" in f for f in flat), flat

    plain = jax.device_put(
        TrainState.create(params, st, opt), mesh_lib.replicated(mesh1)
    )
    fsdp = _fsdp_state(mesh2, params, st, opt, specs)
    _assert_some_leaf_sharded(fsdp)

    plain_step = make_train_step(model.apply, opt, mesh1, donate=False, sync_bn=False)
    fsdp_step = make_fsdp_train_step(model.apply, opt, mesh2, specs, donate=False)

    rng = np.random.default_rng(8)
    for _ in range(3):
        x = rng.normal(size=(32, 16, 16, 3)).astype(np.float32)
        y = rng.integers(0, 10, 32).astype(np.int32)
        plain, mp = plain_step(
            plain, mesh_lib.shard_batch(mesh1, x), mesh_lib.shard_batch(mesh1, y), 0.1
        )
        fsdp, mf = fsdp_step(
            fsdp, mesh_lib.shard_batch(mesh2, x), mesh_lib.shard_batch(mesh2, y), 0.1
        )

    for k in ("loss", "acc1", "acc5"):
        np.testing.assert_allclose(float(mp[k]), float(mf[k]), rtol=1e-4, atol=1e-4)
    for a, b in zip(
        jax.tree_util.tree_leaves(plain.params),
        jax.tree_util.tree_leaves(fsdp.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_trainer_fsdp_tp_e2e_adamw(tmp_path):
    """--fsdp --tp 2 trains, evals, checkpoints, resumes (AdamW state specs
    composed through optimizer.state_specs)."""
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer

    cfg = TrainConfig(
        dataset="synthetic", model="vit_tiny", num_classes=10, batch_size=32,
        epochs=1, steps_per_epoch=3, log_every=10, lr=0.01, eval_every=1,
        fsdp=True, tp=2, sync_bn=False, optimizer="adamw",
        ckpt_dir=str(tmp_path), save_every=1, synthetic_n=128,
    )
    t = Trainer(cfg)
    # both mesh axes exist and params use the model axis somewhere
    assert dict(t.mesh.shape) == {"data": 4, "model": 2}
    flat = [
        tuple(l.sharding.spec)
        for l in jax.tree_util.tree_leaves(t.state.params)
    ]
    assert any("model" in f for f in flat), flat
    assert any("data" in f for f in flat), flat
    out = t.fit(1)
    assert np.isfinite(out["loss"])
    t2 = Trainer(cfg.replace(resume=True, epochs=2))
    assert t2.start_epoch == 1
    for a, b in zip(
        jax.tree_util.tree_leaves(t.state.params),
        jax.tree_util.tree_leaves(t2.state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)
