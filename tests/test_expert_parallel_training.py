"""End-to-end expert-parallel training (DP×EP, MoE ViT)."""

import pytest
import jax
import numpy as np

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.config import TrainConfig
from tpu_dist.nn import functional as F
from tpu_dist.nn.vit_moe import ViTMoEDef
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tpu_dist.train.step import make_train_step
from tpu_dist.train.trainer import Trainer


def _model():
    # big capacity factor: no token drops → exact per-shard dense parity
    return ViTMoEDef(image_size=16, patch_size=4, dim=32, depth=1, heads=4,
                     n_experts=8, capacity_factor=8.0, num_classes=5)


@pytest.mark.slow  # >10s e2e: excluded from the timed tier-1 gate; the
# quick slice keeps a fast representative of this subsystem in the gate
def test_dp_ep_training_matches_per_shard_dense():
    """2×4 DP×EP step ≡ dense MoE computed shard-by-shard on one device
    (routing/capacity is per token shard in both)."""
    from jax.sharding import NamedSharding

    model = _model()
    opt = SGD(momentum=0.9, weight_decay=0.0)
    mesh2d = mesh_lib.device_mesh([2, 4], ["data", "expert"])
    specs = model.ep_param_specs("expert")

    params, s = model.init(jax.random.PRNGKey(0))
    st = TrainState.create(params, s, opt)
    place = lambda tree: jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh2d, spec)), tree, specs
    )
    s_ep = TrainState(
        params=place(st.params),
        bn_state=jax.device_put(st.bn_state, mesh_lib.replicated(mesh2d)),
        opt_state=place(st.opt_state),
        step=jax.device_put(st.step, mesh_lib.replicated(mesh2d)),
    )
    # aux coef 0: this test pins the dispatch/gradient math against a
    # train=False host reference; the aux objective has its own test
    # (test_parallel.py::test_moe_aux_loss_threads_through_train_step)
    step_ep = make_train_step(
        model.apply, opt, mesh2d, sync_bn=False, donate=False,
        ep_axis="expert", param_specs=specs, moe_aux_coef=0.0,
    )

    # host-side reference: same per-shard routing, gradient = mean of
    # 8 shard losses, plain SGD
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 16, 16, 3)).astype(np.float32)
    y = rng.integers(0, 5, 16).astype(np.int32)

    import jax.numpy as jnp

    def ref_loss(p):
        tot = 0.0
        for i in range(8):
            logits, _ = model.apply(p, {}, jnp.asarray(x[i * 2 : (i + 1) * 2]))
            tot = tot + F.cross_entropy(logits, jnp.asarray(y[i * 2 : (i + 1) * 2]))
        return tot / 8

    ref_p, ref_b = params, opt.init(params)
    for _ in range(2):
        g = jax.grad(ref_loss)(ref_p)
        ref_p, ref_b = opt.update(g, ref_b, ref_p, 0.05)

    xs = mesh_lib.shard_batch(mesh2d, x, ("data", "expert"))
    ys = mesh_lib.shard_batch(mesh2d, y, ("data", "expert"))
    for _ in range(2):
        s_ep, m = step_ep(s_ep, xs, ys, 0.05)

    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(s_ep.params)),
        jax.tree_util.tree_leaves(jax.device_get(ref_p)),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5)


@pytest.mark.slow  # >10s e2e: excluded from the timed tier-1 gate; the
# quick slice keeps a fast representative of this subsystem in the gate
def test_trainer_ep_e2e_with_eval_and_resume(tmp_path):
    cfg = TrainConfig(
        dataset="synthetic", model="vit_moe_tiny", num_classes=10, batch_size=16,
        epochs=1, steps_per_epoch=2, log_every=1, lr=0.05, eval_every=1,
        ep=4, sync_bn=False, synthetic_n=160, ckpt_dir=str(tmp_path), save_every=1,
    )
    t = Trainer(cfg)
    assert t.n_devices == 8
    out = t.fit()
    assert np.isfinite(out["loss"]) and "val_top1" in out

    t2 = Trainer(cfg.replace(resume=True, epochs=2))
    assert t2.start_epoch == 1
    w_in = t2.state.params["blocks"][0]["moe"]["w_in"]
    assert len(w_in.sharding.device_set) == 8  # experts restored sharded
    assert np.isfinite(t2.fit()["loss"])


def test_trainer_ep_rejects_bad_configs():
    import pytest

    with pytest.raises(ValueError, match="expert parallelism"):
        Trainer(TrainConfig(dataset="synthetic", model="resnet18", ep=4, synthetic_n=512))
    with pytest.raises(ValueError, match="sp\\+tp"):
        Trainer(TrainConfig(dataset="synthetic", model="vit_moe_tiny", ep=2, tp=2,
                            synthetic_n=512))
