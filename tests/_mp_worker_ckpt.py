"""Worker for the multi-host sharded-checkpoint test.

2 processes × 4 CPU devices, one [data=8] global mesh, params/momentum
sharded P('data') (the ZeRO case). Each process must write ONLY its own
shard file (no gather — the point of the format), the manifest commits
on rank 0, and a cross-process restore must hand every process exactly
its local partition back.

Usage: python tests/_mp_worker_ckpt.py <coordinator> <num_procs> <proc_id> <ckpt_dir>
"""

import os
import sys

if __name__ == "__main__":
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    ).strip()

import jax  # noqa: E402

if __name__ == "__main__":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def run_ckpt_roundtrip(ckpt_dir: str):
    from jax.sharding import PartitionSpec as P

    from tpu_dist.ckpt import checkpoint as ckpt_lib
    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.train.state import TrainState

    mesh = mesh_lib.device_mesh([jax.device_count()], ["data"])
    rng = np.random.default_rng(7)
    host_params = {
        "w": rng.normal(size=(16, 8)).astype(np.float32),   # sharded P('data')
        "b": rng.normal(size=(8,)).astype(np.float32),      # replicated
    }

    def place(x, spec):
        return mesh_lib.place_host_tree(mesh, x, spec)

    params = {
        "w": place(host_params["w"], P("data")),
        "b": place(host_params["b"], P()),
    }
    momentum = {
        "w": place(np.zeros_like(host_params["w"]), P("data")),
        "b": place(np.zeros_like(host_params["b"]), P()),
    }
    state = TrainState(
        params=params,
        bn_state={},
        opt_state=momentum,
        step=place(np.asarray(3, np.int32), P()),
    )
    ckpt_lib.save_sharded(ckpt_dir, state, 5, extra_meta={"pp": 1})

    # every process sees the committed manifest on the shared fs
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices("saved")
    manifest = os.path.join(ckpt_dir, "ckpt_5.manifest.json")
    assert os.path.exists(manifest), "manifest missing after commit"

    # each process's shard file holds ONLY its local rows of w (8 of 16)
    pid = jax.process_index()
    with np.load(os.path.join(ckpt_dir, f"ckpt_5.shard{pid}of2.npz")) as z:
        w_keys = [k for k in z.files if k.startswith("['params']['w']")]
        local_w_rows = sum(z[k].shape[0] for k in w_keys)
    assert local_w_rows == 8, (pid, local_w_rows)

    restored = ckpt_lib.restore_sharded(manifest, state)
    # the restored global array equals the original on every process
    got = np.asarray(
        multihost_utils.process_allgather(restored.params["w"], tiled=True)
    )
    np.testing.assert_array_equal(got, host_params["w"])
    np.testing.assert_array_equal(
        np.asarray(restored.params["b"].addressable_shards[0].data),
        host_params["b"],
    )
    assert int(np.asarray(restored.step.addressable_shards[0].data)) == 3
    assert ckpt_lib.read_sharded_meta(manifest)["pp"] == 1
    return float(got.sum())


def main(coordinator: str, num_procs: int, proc_id: int, ckpt_dir: str) -> None:
    from tpu_dist.comm import mesh as mesh_lib

    mesh_lib.initialize_distributed(coordinator, num_procs, proc_id)
    assert jax.process_count() == num_procs
    fp = run_ckpt_roundtrip(ckpt_dir)
    print(f"CKRESULT {proc_id} {fp:.6f}", flush=True)


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
