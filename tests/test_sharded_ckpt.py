"""Sharded checkpointing (--sharded_ckpt): per-process shard files + a
rank-0 manifest commit marker, NO gather at save time — the FSDP/ZeRO-
scale format (ckpt/checkpoint.py::save_sharded)."""

import json
import os

import jax
import numpy as np
import pytest

from tpu_dist.ckpt import checkpoint as ckpt_lib
from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.config import TrainConfig
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tpu_dist.train.trainer import Trainer, register_model
from tests.helpers import TinyConvNet, tiny_resnet

register_model("tiny_resnet_sc", lambda num_classes=10: tiny_resnet(num_classes))


def _fsdp_like_state(mesh):
    """Params/momentum sharded over the data axis (the ZeRO case sharded
    ckpts exist for), BN replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    model = TinyConvNet(num_classes=10, width=16)
    params, bn = model.init(jax.random.PRNGKey(0))
    st = TrainState.create(params, bn, SGD())

    def shard(x):
        x = np.asarray(x)
        if x.ndim and x.shape[0] % 8 == 0:
            return jax.device_put(x, NamedSharding(mesh, P("data")))
        return jax.device_put(x, NamedSharding(mesh, P()))

    return TrainState(
        params=jax.tree_util.tree_map(shard, st.params),
        bn_state=jax.tree_util.tree_map(shard, st.bn_state),
        opt_state=jax.tree_util.tree_map(shard, st.opt_state),
        step=jax.device_put(st.step, NamedSharding(mesh, P())),
    )


def test_sharded_roundtrip_and_no_duplication(tmp_path):
    mesh = mesh_lib.data_parallel_mesh()
    state = _fsdp_like_state(mesh)
    mpath = ckpt_lib.save_sharded(str(tmp_path), state, 3, extra_meta={"pp": 1})
    assert mpath and mpath.endswith("ckpt_3.manifest.json")

    found = ckpt_lib.latest_sharded_checkpoint(str(tmp_path))
    assert found == (mpath, 3)
    assert ckpt_lib.read_sharded_meta(mpath)["pp"] == 1

    # single process -> one shard file; its bytes hold each distinct slice
    # ONCE (replica_id dedup): total elements == state elements
    shard_files = [n for n in os.listdir(tmp_path) if ".shard" in n]
    assert shard_files == ["ckpt_3.shard0of1.npz"]
    with np.load(tmp_path / shard_files[0]) as z:
        # __crc__ is the per-shard integrity stamp (sideband, not a slice)
        stored = sum(
            int(np.prod(z[k].shape)) for k in z.files if k != "__crc__"
        )
    want = sum(
        int(np.prod(np.shape(l)))
        for l in jax.tree_util.tree_leaves(state._asdict())
    )
    assert stored == want, (stored, want)

    template = _fsdp_like_state(mesh)
    restored = ckpt_lib.restore_sharded(mpath, template)
    for a, b in zip(
        jax.tree_util.tree_leaves(state._asdict()),
        jax.tree_util.tree_leaves(restored._asdict()),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_pruning_uncommits_manifest_first(tmp_path):
    mesh = mesh_lib.data_parallel_mesh()
    state = _fsdp_like_state(mesh)
    for e in range(4):
        ckpt_lib.save_sharded(str(tmp_path), state, e, keep_last=2)
    names = sorted(os.listdir(tmp_path))
    assert "ckpt_3.manifest.json" in names and "ckpt_2.manifest.json" in names
    assert not any(n.startswith(("ckpt_0.", "ckpt_1.")) for n in names), names


def test_sharded_incomplete_is_invisible_and_refused(tmp_path):
    mesh = mesh_lib.data_parallel_mesh()
    state = _fsdp_like_state(mesh)
    mpath = ckpt_lib.save_sharded(str(tmp_path), state, 0)
    # no manifest -> invisible to discovery
    os.rename(mpath, str(tmp_path / "stash.json"))
    assert ckpt_lib.latest_sharded_checkpoint(str(tmp_path)) is None
    # manifest claiming more shards than exist -> loud refusal
    man = json.load(open(tmp_path / "stash.json"))
    man["n_shards"] = 2
    with open(tmp_path / "ckpt_0.manifest.json", "w") as f:
        json.dump(man, f)
    with pytest.raises(FileNotFoundError, match="2 shard files"):
        ckpt_lib.restore_sharded(
            str(tmp_path / "ckpt_0.manifest.json"), _fsdp_like_state(mesh)
        )


def test_trainer_fsdp_sharded_ckpt_resume(tmp_path):
    """e2e: FSDP trainer saves sharded, resumes from the manifest, params
    match. (async+sharded — once refused, now the snapshot-then-write
    path — is covered in tests/test_async_sharded_ckpt.py.)"""
    cfg = TrainConfig(
        dataset="synthetic", model="vit_tiny", num_classes=10, batch_size=64,
        epochs=1, steps_per_epoch=2, eval_every=0, synthetic_n=640,
        sync_bn=False, fsdp=True, sharded_ckpt=True,
        ckpt_dir=str(tmp_path), save_every=1, log_every=10,
    )
    t = Trainer(cfg)
    t.fit()
    assert (tmp_path / "ckpt_0.manifest.json").exists()
    assert (tmp_path / "ckpt_0.shard0of1.npz").exists()
    assert not (tmp_path / "ckpt_0.npz").exists()  # no gathered file

    t2 = Trainer(cfg.replace(resume=True, epochs=2))
    assert t2.start_epoch == 1
    for a, b in zip(
        jax.tree_util.tree_leaves(t.state.params),
        jax.tree_util.tree_leaves(t2.state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_best_save_uncommits_before_overwrite(tmp_path):
    """save_best over an existing committed ckpt_best deletes the old
    manifest BEFORE replacing shard files — a crash mid-overwrite leaves an
    uncommitted (invisible) checkpoint, never a committed mixed one."""
    mesh = mesh_lib.data_parallel_mesh()
    s = _fsdp_like_state(mesh)
    ckpt_lib.ShardedCheckpointer.save_best(str(tmp_path), s, 3, 71.5)
    meta = ckpt_lib.read_sharded_meta(str(tmp_path / "ckpt_best.manifest.json"))
    assert meta["metric"] == 71.5 and meta["epoch"] == 3
    ckpt_lib.ShardedCheckpointer.save_best(str(tmp_path), s, 7, 82.0)
    meta = ckpt_lib.read_sharded_meta(str(tmp_path / "ckpt_best.manifest.json"))
    assert meta["metric"] == 82.0 and meta["epoch"] == 7


def test_pruning_sweeps_orphaned_shards(tmp_path):
    """Shard files whose epoch was never committed (crash before manifest)
    are swept by the next keep_last pruning pass."""
    mesh = mesh_lib.data_parallel_mesh()
    s = _fsdp_like_state(mesh)
    # fake a crashed epoch-0 save: shard file, no manifest
    ckpt_lib.save_sharded(str(tmp_path), s, 0)
    os.remove(tmp_path / "ckpt_0.manifest.json")
    for e in (1, 2, 3):
        ckpt_lib.save_sharded(str(tmp_path), s, e, keep_last=2)
    names = os.listdir(tmp_path)
    assert not any(n.startswith(("ckpt_0.", "ckpt_1.")) for n in names), names
    assert any(n.startswith("ckpt_2.") for n in names)


def test_resume_format_mismatch_is_loud(tmp_path):
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_sc", num_classes=10,
        batch_size=64, epochs=1, steps_per_epoch=2, eval_every=0,
        synthetic_n=640, ckpt_dir=str(tmp_path), save_every=1, log_every=10,
    )
    Trainer(cfg).fit()  # plain-format checkpoints on disk
    with pytest.raises(ValueError, match="plain format"):
        Trainer(cfg.replace(resume=True, sharded_ckpt=True))


try:  # optional dep: only the property-based case needs it
    from hypothesis import given, settings, strategies as st  # noqa: E402
except ImportError:
    st = None

if st is None:

    @pytest.mark.skip(reason="hypothesis not installed")
    def test_sharded_roundtrip_property():
        """Stub so the missing property coverage shows up as a SKIP in
        reports instead of silently vanishing."""

else:

    @settings(max_examples=25, deadline=None)
    @given(
        rows=st.integers(1, 40),
        cols=st.integers(1, 12),
        shard_rows=st.booleans(),
        seed=st.integers(0, 100),
    )
    def test_sharded_roundtrip_property(tmp_path_factory, rows, cols, shard_rows, seed):
        """Any (shape, sharding) combination JAX can place round-trips
        bit-exact through the shard-piece format (JAX refuses indivisible
        NamedShardings outright, so divisible-sharded and replicated leaves
        are the whole space)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        tmp_path = tmp_path_factory.mktemp("shards")
        mesh = mesh_lib.data_parallel_mesh()
        rng = np.random.default_rng(seed)
        w = rng.normal(size=(rows, cols)).astype(np.float32)
        n_dev = int(mesh.devices.size)
        spec = P("data") if (shard_rows and rows % n_dev == 0) else P()
        params = {"w": jax.device_put(w, NamedSharding(mesh, spec))}
        state = TrainState(
            params=params, bn_state={}, opt_state={},
            step=jax.device_put(np.asarray(seed, np.int32), NamedSharding(mesh, P())),
        )
        mpath = ckpt_lib.save_sharded(str(tmp_path), state, 0)
        restored = ckpt_lib.restore_sharded(mpath, state)
        np.testing.assert_array_equal(np.asarray(restored.params["w"]), w)
        assert int(np.asarray(restored.step)) == seed


def test_zero1_sharded_ckpt_resume(tmp_path):
    """ZeRO-1's flat P('data') optimizer state — the original sharded-leaf
    case — saves shardwise and resumes bit-exact."""
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_sc", num_classes=10,
        batch_size=64, epochs=1, steps_per_epoch=2, eval_every=0,
        synthetic_n=640, shard_weight_update=True, sharded_ckpt=True,
        ckpt_dir=str(tmp_path), save_every=1, log_every=10,
    )
    t = Trainer(cfg)
    t.fit()
    assert (tmp_path / "ckpt_0.manifest.json").exists()
    t2 = Trainer(cfg.replace(resume=True, epochs=2))
    assert t2.start_epoch == 1
    for a, b in zip(
        jax.tree_util.tree_leaves(t.state.opt_state),
        jax.tree_util.tree_leaves(t2.state.opt_state),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
