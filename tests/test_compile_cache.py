"""Persistent compile cache: --compile_cache_dir populates an XLA cache a
second invocation of the same config loads from (VERDICT r1 #8).

The cache setting is process-global jax.config state (that is how XLA's
persistent cache works); this test restores it afterwards so later tests in
the same process don't keep writing into the tmp dir.
"""

import os

import jax
import numpy as np

from tpu_dist.config import TrainConfig
from tpu_dist.train.trainer import Trainer, register_model
from tests.helpers import tiny_resnet

register_model("tiny_resnet_cc", lambda num_classes=10: tiny_resnet(num_classes))


def test_compile_cache_populated_and_reused(tmp_path):
    cache = str(tmp_path / "xla_cache")
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_cc", num_classes=10,
        batch_size=64, epochs=1, steps_per_epoch=1, log_every=10,
        eval_every=0, lr=0.05, synthetic_n=640, compile_cache_dir=cache,
    )
    # the persistent cache initializes ONCE per process (lazily, at the
    # first compile): when earlier tests in the suite have already compiled
    # with no cache dir, the config update below would be a silent no-op —
    # reset so it re-initializes against this test's tmp dir
    from jax._src import compilation_cache as _cc

    _cc.reset_cache()
    try:
        t = Trainer(cfg)
        # the tiny model can compile in <1s; persist everything so the
        # assertion below can't fail on a fast host
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        out = t.train_epoch(0)
        assert np.isfinite(out["loss"])
        entries = os.listdir(cache)
        assert entries, "compile cache dir is empty — nothing was persisted"
        mtimes = {e: os.path.getmtime(os.path.join(cache, e)) for e in entries}

        # same config again: loads from cache (no new entries, mtimes unchanged)
        out2 = Trainer(cfg).train_epoch(0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        assert np.isfinite(out2["loss"])
        entries2 = set(os.listdir(cache))
        assert entries2 == set(entries)
        for e, t_ in mtimes.items():
            if e.endswith("-atime"):
                # some JAX versions track cache reads in an -atime sidecar
                # that is rewritten on every hit — only the artifact
                # entries must stay untouched
                continue
            assert os.path.getmtime(os.path.join(cache, e)) == t_
    finally:
        jax.config.update("jax_compilation_cache_dir", None)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _cc.reset_cache()  # later tests must not keep writing into tmp
