"""Label smoothing and global-norm gradient clipping."""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.nn import functional as F
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tpu_dist.train.step import init_sharded_opt_state, make_train_step
from tests.helpers import TinyMLP


def test_label_smoothing_matches_torch():
    import torch

    rng = np.random.default_rng(0)
    logits = rng.normal(size=(16, 10)).astype(np.float32)
    labels = rng.integers(0, 10, 16)
    ref = torch.nn.functional.cross_entropy(
        torch.tensor(logits), torch.tensor(labels), label_smoothing=0.1
    ).item()
    got = float(F.cross_entropy(jnp.array(logits), jnp.array(labels), label_smoothing=0.1))
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def _setup(mesh, **step_kw):
    model = TinyMLP(in_dim=8 * 8 * 3)
    opt = SGD(momentum=0.0, weight_decay=0.0)
    params, bn = model.init(jax.random.PRNGKey(0))
    state = jax.device_put(TrainState.create(params, bn, opt), mesh_lib.replicated(mesh))
    step = make_train_step(model.apply, opt, mesh, sync_bn=False, donate=False, **step_kw)
    return model, opt, state, step


def test_grad_clip_limits_update_norm():
    mesh = mesh_lib.data_parallel_mesh()
    clip = 0.05
    _, _, state, step = _setup(mesh, grad_clip_norm=clip)
    _, _, state_ref, step_ref = _setup(mesh)

    rng = np.random.default_rng(0)
    x = mesh_lib.shard_batch(mesh, (10 * rng.normal(size=(64, 8, 8, 3))).astype(np.float32))
    y = mesh_lib.shard_batch(mesh, rng.integers(0, 10, 64).astype(np.int32))

    lr = 1.0
    s1, _ = step(state, x, y, lr)
    s_ref, _ = step_ref(state_ref, x, y, lr)

    def upd_norm(s):
        return float(
            jnp.sqrt(
                sum(
                    jnp.sum((a - b) ** 2)
                    for a, b in zip(
                        jax.tree_util.tree_leaves(s.params),
                        jax.tree_util.tree_leaves(state.params),
                    )
                )
            )
        )

    # momentum=0, wd=0, lr=1 → update norm == clipped grad norm
    assert upd_norm(s_ref) > clip  # unclipped would exceed
    np.testing.assert_allclose(upd_norm(s1), clip, rtol=1e-4)


def test_grad_clip_consistent_between_plain_and_zero1():
    mesh = mesh_lib.data_parallel_mesh()
    clip = 0.05
    model, opt, state, step = _setup(mesh, grad_clip_norm=clip)
    params, bn = model.init(jax.random.PRNGKey(0))
    z1 = TrainState(
        params=jax.device_put(params, mesh_lib.replicated(mesh)),
        bn_state=jax.device_put(bn, mesh_lib.replicated(mesh)),
        opt_state=init_sharded_opt_state(params, mesh),
        step=jax.device_put(jnp.zeros((), jnp.int32), mesh_lib.replicated(mesh)),
    )
    z1_step = make_train_step(
        model.apply, opt, mesh, sync_bn=False, donate=False,
        grad_clip_norm=clip, shard_weight_update=True,
    )

    rng = np.random.default_rng(1)
    x = mesh_lib.shard_batch(mesh, (10 * rng.normal(size=(64, 8, 8, 3))).astype(np.float32))
    y = mesh_lib.shard_batch(mesh, rng.integers(0, 10, 64).astype(np.int32))
    s_p, _ = step(state, x, y, 0.5)
    s_z, _ = z1_step(z1, x, y, 0.5)
    for a, b in zip(
        jax.tree_util.tree_leaves(s_p.params), jax.tree_util.tree_leaves(s_z.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
