"""Profiler hooks and replica-consistency checks."""

import jax
import jax.numpy as jnp
import pytest

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.metrics.consistency import check_replicated
from tpu_dist.obs.profile import StepTimer, annotate_step, trace


def test_step_timer_skips_warmup():
    t = StepTimer(warmup_steps=2)
    x = jnp.ones(4)
    for _ in range(5):
        x = x * 1.0
        t.tick()
    dt = t.finish(blocker=x)
    assert dt is not None and dt >= 0
    assert t.steps == 3


def test_step_timer_too_few_steps():
    t = StepTimer(warmup_steps=5)
    t.tick()
    assert t.finish() is None


def test_annotate_step_contextmanager():
    with annotate_step(3):
        _ = jnp.ones(2) + 1


@pytest.mark.slow  # >10s e2e: excluded from the timed tier-1 gate; the
# quick slice keeps a fast representative of this subsystem in the gate
def test_trace_writes_profile(tmp_path):
    with trace(str(tmp_path)):
        jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    # a plugins/profile dir with at least one capture should exist
    found = list(tmp_path.rglob("*.xplane.pb"))
    assert found, list(tmp_path.rglob("*"))


def test_check_replicated_passes_on_replicated():
    mesh = mesh_lib.data_parallel_mesh()
    tree = jax.device_put({"w": jnp.ones((4, 4))}, mesh_lib.replicated(mesh))
    check_replicated(tree)


def test_check_replicated_detects_divergence():
    mesh = mesh_lib.data_parallel_mesh()
    # build a deliberately diverged "replicated" array via per-device put
    devs = list(mesh.devices.ravel())
    shards = [jax.device_put(jnp.full((2,), float(i)), d) for i, d in enumerate(devs)]
    from jax.sharding import NamedSharding, PartitionSpec as P

    arr = jax.make_array_from_single_device_arrays(
        (2,), NamedSharding(mesh, P()), shards[:1] * 0 + shards
    )
    with pytest.raises(AssertionError, match="replica divergence"):
        check_replicated({"w": arr}, name="params")


@pytest.mark.slow  # >10s e2e: excluded from the timed tier-1 gate; the
# quick slice keeps a fast representative of this subsystem in the gate
def test_trainer_profile_dir_captures_trace(tmp_path):
    """--profile_dir wraps epoch 0 in the XLA profiler (obs/profile.py):
    a TensorBoard-readable xplane capture must land on disk."""
    from tests.helpers import tiny_resnet
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer, register_model

    register_model("tiny_resnet_obs2", lambda num_classes=10: tiny_resnet(num_classes))
    prof = tmp_path / "prof"
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_obs2", num_classes=10,
        batch_size=64, epochs=1, steps_per_epoch=2, eval_every=0,
        synthetic_n=640, log_every=10, profile_dir=str(prof),
    )
    Trainer(cfg).fit()
    captures = list(prof.rglob("*.xplane.pb"))
    assert captures, f"no xplane capture under {prof}"


@pytest.mark.slow  # ~13 s (two full fits); CI observability step runs
# it without the slow filter (ISSUE 7 tier-1 budget)
def test_loader_num_workers_prefetch_depth():
    """--num_workers maps to the loader's prefetch depth; training is
    unaffected by its value (same batches, same order)."""
    from tests.helpers import tiny_resnet
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer, register_model

    register_model("tiny_resnet_obs3", lambda num_classes=10: tiny_resnet(num_classes))
    import numpy as np

    outs = []
    for nw in (1, 4):
        cfg = TrainConfig(
            dataset="synthetic", model="tiny_resnet_obs3", num_classes=10,
            batch_size=64, epochs=1, steps_per_epoch=3, eval_every=0,
            synthetic_n=640, log_every=10, num_workers=nw, seed=0,
        )
        outs.append(Trainer(cfg).train_epoch(0)["loss"])
    assert np.isclose(outs[0], outs[1]), outs
