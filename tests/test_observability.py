"""Profiler hooks and replica-consistency checks."""

import jax
import jax.numpy as jnp
import pytest

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.metrics.consistency import check_replicated
from tpu_dist.metrics.profiler import StepTimer, annotate_step, trace


def test_step_timer_skips_warmup():
    t = StepTimer(warmup_steps=2)
    x = jnp.ones(4)
    for _ in range(5):
        x = x * 1.0
        t.tick()
    dt = t.finish(blocker=x)
    assert dt is not None and dt >= 0
    assert t.steps == 3


def test_step_timer_too_few_steps():
    t = StepTimer(warmup_steps=5)
    t.tick()
    assert t.finish() is None


def test_annotate_step_contextmanager():
    with annotate_step(3):
        _ = jnp.ones(2) + 1


def test_trace_writes_profile(tmp_path):
    with trace(str(tmp_path)):
        jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    # a plugins/profile dir with at least one capture should exist
    found = list(tmp_path.rglob("*.xplane.pb"))
    assert found, list(tmp_path.rglob("*"))


def test_check_replicated_passes_on_replicated():
    mesh = mesh_lib.data_parallel_mesh()
    tree = jax.device_put({"w": jnp.ones((4, 4))}, mesh_lib.replicated(mesh))
    check_replicated(tree)


def test_check_replicated_detects_divergence():
    mesh = mesh_lib.data_parallel_mesh()
    # build a deliberately diverged "replicated" array via per-device put
    devs = list(mesh.devices.ravel())
    shards = [jax.device_put(jnp.full((2,), float(i)), d) for i, d in enumerate(devs)]
    from jax.sharding import NamedSharding, PartitionSpec as P

    arr = jax.make_array_from_single_device_arrays(
        (2,), NamedSharding(mesh, P()), shards[:1] * 0 + shards
    )
    with pytest.raises(AssertionError, match="replica divergence"):
        check_replicated({"w": arr}, name="params")
