"""Golden-run regression: a fixed-seed 10-step training trajectory must
reproduce across refactors (guards against silent numeric drift in the
step/optimizer/BN/loss stack). Regenerate GOLDEN only for INTENTIONAL
numeric changes, and say so in the commit message.

Tolerance is loose enough for cross-platform (CPU emulation vs TPU)
float reassociation, tight enough to catch real semantic changes.
"""

import jax
import numpy as np

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tpu_dist.train.step import make_train_step
from tests.helpers import TinyConvNet

# Re-pinned on the jax 0.4.37 / jaxlib CPU stack (the prior values came
# from a newer-JAX stack whose init RNG/conv numerics differ by ~1.5%;
# determinism re-verified: two fresh processes reproduce bit-identically).
GOLDEN = [
    2.376438, 2.367249, 2.350771, 2.329373, 2.305475,
    2.28122, 2.258286, 2.237824, 2.220451, 2.206369,
]


def test_fixed_seed_trajectory_reproduces():
    mesh = mesh_lib.data_parallel_mesh()
    model = TinyConvNet(num_classes=10, width=8)
    opt = SGD()
    params, bn = model.init(jax.random.PRNGKey(42))
    state = jax.device_put(
        TrainState.create(params, bn, opt), mesh_lib.replicated(mesh)
    )
    step = make_train_step(model.apply, opt, mesh)
    rng = np.random.default_rng(7)
    x = mesh_lib.shard_batch(mesh, rng.normal(size=(64, 8, 8, 3)).astype(np.float32))
    y = mesh_lib.shard_batch(mesh, rng.integers(0, 10, 64).astype(np.int32))
    losses = []
    for _ in range(10):
        state, m = step(state, x, y, 0.1)
        losses.append(float(m["loss"]))
    np.testing.assert_allclose(losses, GOLDEN, rtol=2e-3)


GOLDEN_ADAMW = [  # re-pinned with GOLDEN above (same stack note)
    2.376438, 2.373347, 2.370287, 2.367262, 2.364261,
    2.361292, 2.358356, 2.355456, 2.352595, 2.349766,
]


def test_fixed_seed_adamw_trajectory_reproduces():
    """Same guard for the AdamW stack (moments, bias correction, decoupled
    decay + auto mask) — the SGD golden run covers none of it."""
    from tpu_dist.train.optim import AdamW

    mesh = mesh_lib.data_parallel_mesh()
    model = TinyConvNet(num_classes=10, width=8)
    opt = AdamW()
    params, bn = model.init(jax.random.PRNGKey(42))
    state = jax.device_put(
        TrainState.create(params, bn, opt), mesh_lib.replicated(mesh)
    )
    step = make_train_step(model.apply, opt, mesh)
    rng = np.random.default_rng(7)
    x = mesh_lib.shard_batch(mesh, rng.normal(size=(64, 8, 8, 3)).astype(np.float32))
    y = mesh_lib.shard_batch(mesh, rng.integers(0, 10, 64).astype(np.int32))
    losses = []
    for _ in range(10):
        state, m = step(state, x, y, 0.001)
        losses.append(float(m["loss"]))
    np.testing.assert_allclose(losses, GOLDEN_ADAMW, rtol=2e-3)
