"""Golden-run regression: a fixed-seed 10-step training trajectory must
reproduce across refactors (guards against silent numeric drift in the
step/optimizer/BN/loss stack). Regenerate GOLDEN only for INTENTIONAL
numeric changes, and say so in the commit message.

Tolerance is loose enough for cross-platform (CPU emulation vs TPU)
float reassociation, tight enough to catch real semantic changes.
"""

import jax
import numpy as np

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tpu_dist.train.step import make_train_step
from tests.helpers import TinyConvNet

GOLDEN = [
    2.412941, 2.402351, 2.383222, 2.358099, 2.329593,
    2.30015, 2.271854, 2.246292, 2.224517, 2.207107,
]


def test_fixed_seed_trajectory_reproduces():
    mesh = mesh_lib.data_parallel_mesh()
    model = TinyConvNet(num_classes=10, width=8)
    opt = SGD()
    params, bn = model.init(jax.random.PRNGKey(42))
    state = jax.device_put(
        TrainState.create(params, bn, opt), mesh_lib.replicated(mesh)
    )
    step = make_train_step(model.apply, opt, mesh)
    rng = np.random.default_rng(7)
    x = mesh_lib.shard_batch(mesh, rng.normal(size=(64, 8, 8, 3)).astype(np.float32))
    y = mesh_lib.shard_batch(mesh, rng.integers(0, 10, 64).astype(np.int32))
    losses = []
    for _ in range(10):
        state, m = step(state, x, y, 0.1)
        losses.append(float(m["loss"]))
    np.testing.assert_allclose(losses, GOLDEN, rtol=2e-3)


GOLDEN_ADAMW = [
    2.412941, 2.409781, 2.406655, 2.403563, 2.400502,
    2.397464, 2.394458, 2.391484, 2.388544, 2.385641,
]


def test_fixed_seed_adamw_trajectory_reproduces():
    """Same guard for the AdamW stack (moments, bias correction, decoupled
    decay + auto mask) — the SGD golden run covers none of it."""
    from tpu_dist.train.optim import AdamW

    mesh = mesh_lib.data_parallel_mesh()
    model = TinyConvNet(num_classes=10, width=8)
    opt = AdamW()
    params, bn = model.init(jax.random.PRNGKey(42))
    state = jax.device_put(
        TrainState.create(params, bn, opt), mesh_lib.replicated(mesh)
    )
    step = make_train_step(model.apply, opt, mesh)
    rng = np.random.default_rng(7)
    x = mesh_lib.shard_batch(mesh, rng.normal(size=(64, 8, 8, 3)).astype(np.float32))
    y = mesh_lib.shard_batch(mesh, rng.integers(0, 10, 64).astype(np.int32))
    losses = []
    for _ in range(10):
        state, m = step(state, x, y, 0.001)
        losses.append(float(m["loss"]))
    np.testing.assert_allclose(losses, GOLDEN_ADAMW, rtol=2e-3)
