"""Crash forensics (ISSUE 12): the SIGKILL-surviving flight recorder,
on-demand stack capture, the postmortem assembler, the hang fault site,
the elastic stale-rank sweep, TD113, and the watchdog capture chain."""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from tpu_dist.obs import flight
from tpu_dist.obs import postmortem as postmortem_lib


# -- ring: round trip, wraparound, shedding ----------------------------------


def test_ring_round_trip_and_wraparound(tmp_path):
    """Records come back in seq order; once the ring wraps, exactly the
    last n_slots survive — the 'last N events of the run' contract."""
    ring = str(tmp_path / "flight.ring")
    rec = flight.FlightRecorder(
        ring, run_id="run-1", rank=3, n_slots=8, slot_size=256
    )
    rec.record("open", world=4)
    for i in range(20):
        rec.step(0, i)
    rec.close("exit", clean=True)  # stamps the terminal record
    dec = flight.decode(ring)
    assert dec["header"]["run_id"] == "run-1"
    assert dec["header"]["rank"] == 3
    assert dec["torn_slots"] == 0
    assert len(dec["records"]) == 8  # the ring's capacity, newest 8
    seqs = [r["seq"] for r in dec["records"]]
    assert seqs == sorted(seqs) and seqs[-1] == 22  # open + 20 + exit
    assert dec["last"]["kind"] == "exit" and dec["last"]["clean"] is True
    assert flight.last_step(dec)["step"] == 19


def test_ring_step_records_carry_counter_deltas(tmp_path):
    from tpu_dist.obs import counters

    # fresh registry: with hundreds of residual counters from earlier
    # tests the FIRST step's delta (vs nothing) would overflow its slot
    # and legitimately shed the dict — this test wants the carried case
    counters.reset()
    ring = str(tmp_path / "flight.ring")
    rec = flight.FlightRecorder(ring, n_slots=8)
    counters.inc("forensic.test_counter", 2)
    rec.step(1, 0)
    counters.inc("forensic.test_counter", 5)
    rec.step(1, 1)
    rec.close()
    dec = flight.decode(ring)
    steps = [r for r in dec["records"] if r["kind"] == "step"]
    assert steps[0]["counters"]["forensic.test_counter"] == 2
    assert steps[1]["counters"]["forensic.test_counter"] == 5  # the DELTA


def test_ring_oversized_record_sheds_bulk_never_fails(tmp_path):
    """A record that cannot fit its slot sheds the counters dict, then
    trims strings — a slot always lands, flagged 'overflow' when cut."""
    ring = str(tmp_path / "flight.ring")
    rec = flight.FlightRecorder(ring, n_slots=4, slot_size=128)
    assert rec.record(
        "step", epoch=0, step=1, counters={f"k{i}": i for i in range(200)}
    )
    assert rec.record("fatal", error="E" * 400, message="m" * 400,
                      frames=["f" * 90] * 12)
    dec = flight.decode(ring)
    assert dec["torn_slots"] == 0
    kinds = {r["kind"] for r in dec["records"]}
    assert kinds == {"step", "fatal"}
    step = next(r for r in dec["records"] if r["kind"] == "step")
    assert "counters" not in step  # shed, not torn


def test_ring_reopen_starts_empty_never_mixes_runs(tmp_path):
    """An elastic relaunch reuses the same --crash_dir path: the new
    recorder must ZERO the previous process's slots — stale slots carry
    valid CRCs, and a hard-killed round 2 must not decode as round 1's
    clean 'preempt' tail."""
    ring = str(tmp_path / "flight.ring")
    r1 = flight.FlightRecorder(ring, run_id="round-1", n_slots=16)
    for i in range(10):
        r1.step(0, i)
    r1.close("preempt", epoch=0)
    r2 = flight.FlightRecorder(ring, run_id="round-2", n_slots=16)
    r2.record("open", world=1)
    r2.step(1, 0)
    # round 2 SIGKILLed here: no terminal record
    dec = flight.decode(ring)
    assert dec["header"]["run_id"] == "round-2"
    assert [r["seq"] for r in dec["records"]] == [1, 2]
    assert dec["last"]["kind"] == "step"  # NOT round 1's 'preempt'
    assert flight.last_step(dec)["epoch"] == 1


def test_ring_torn_slot_flagged_never_raises(tmp_path):
    ring = str(tmp_path / "flight.ring")
    rec = flight.FlightRecorder(ring, n_slots=8, slot_size=128)
    for i in range(6):
        rec.record("step", epoch=0, step=i)
    rec.close()
    with open(ring, "r+b") as f:  # flip a payload byte in slot 2
        f.seek(flight.HEADER_SIZE + 2 * 128 + 30)
        f.write(b"\xff")
    dec = flight.decode(ring)
    assert dec["torn_slots"] == 1
    assert len(dec["records"]) == 6  # 7 written (+exit), 1 torn
    # garbage header: decode still walks the slots with default geometry
    with open(ring, "r+b") as f:
        f.write(b"\xde\xad\xbe\xef")
    dec2 = flight.decode(ring)
    assert dec2["header"] is None and dec2["torn_header"]


@pytest.mark.slow  # tier-1 budget (ISSUE 17): gates in analysis.yml
def test_sigkill_mid_ring_write_recovers_complete_slots(tmp_path):
    """The satellite acceptance: a writer SIGKILLed mid-stream leaves a
    ring whose COMPLETE slots all decode and whose torn tail is at most
    the single in-flight slot — the decoder never raises."""
    ring = str(tmp_path / "flight.ring")
    child = textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(flight.__file__)))!r})
        from tpu_dist.obs import flight
        rec = flight.FlightRecorder({ring!r}, n_slots=32, slot_size=256)
        rec.record("open", world=1)
        i = 0
        while True:  # hammer the ring until the parent kills us
            rec.step(0, i)
            i += 1
    """)
    env = {k: v for k, v in os.environ.items()}
    env["PYTHONPATH"] = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(flight.__file__)))
    )
    pr = subprocess.Popen([sys.executable, "-c", child], env=env)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:  # wait until it is mid-hammer
        try:
            if os.path.getsize(ring) >= flight.HEADER_SIZE + 32 * 256:
                dec = flight.decode(ring)
                if len(dec["records"]) > 40:  # wrapped at least once
                    break
        except OSError:
            pass
        time.sleep(0.02)
    pr.send_signal(signal.SIGKILL)
    pr.wait()
    dec = flight.decode(ring)  # must not raise
    assert dec["torn_slots"] <= 1  # at most the one in-flight pwrite
    recs = dec["records"]
    assert len(recs) >= 31
    seqs = [r["seq"] for r in recs]
    # complete slots are contiguous except for (at most) the torn one
    assert seqs == sorted(seqs)
    gaps = sum(b - a - 1 for a, b in zip(seqs, seqs[1:]))
    assert gaps <= 1
    # the terminal record is absent: the hard-kill signature postmortem
    # classifies as no-clean-exit
    assert dec["last"]["kind"] == "step"
    rep = postmortem_lib._verdict(
        {"last": dec["last"], "n_records": len(recs), "fatal": None},
        None, None,
    )
    assert rep == "no-clean-exit"


# -- fatal slots via the excepthook wrappers ---------------------------------


def test_thread_excepthook_stamps_fatal_slot_and_chains(tmp_path):
    ring = str(tmp_path / "flight.ring")
    rec = flight.FlightRecorder(ring, n_slots=8)
    seen = []
    prev = threading.excepthook
    threading.excepthook = lambda a: seen.append(a.exc_type)
    try:
        rec.install_excepthooks()

        def boom():
            raise RuntimeError("producer died mid-epoch")

        t = threading.Thread(target=boom, name="loader-producer")
        t.start()
        t.join()
    finally:
        rec.uninstall_excepthooks()
        threading.excepthook = prev
    rec.close()
    dec = flight.decode(ring)
    fatals = flight.fatal_records(dec)
    assert len(fatals) == 1
    f = fatals[0]
    assert f["error"] == "RuntimeError"
    assert "producer died" in f["message"]
    assert f["thread"] == "loader-producer"
    assert any("boom" in fr for fr in f["frames"])
    assert seen == [RuntimeError]  # the previous hook still ran


def test_sys_excepthook_stamps_fatal_slot(tmp_path):
    ring = str(tmp_path / "flight.ring")
    rec = flight.FlightRecorder(ring, n_slots=8)
    called = []
    prev = sys.excepthook
    sys.excepthook = lambda *a: called.append(a[0])
    try:
        rec.install_excepthooks()
        try:
            raise ValueError("uncaught")
        except ValueError:
            sys.excepthook(*sys.exc_info())
    finally:
        rec.uninstall_excepthooks()
        sys.excepthook = prev
    dec = flight.decode(ring)
    assert flight.fatal_records(dec)[0]["error"] == "ValueError"
    assert called == [ValueError]


# -- faulthandler arming + stack dumps ---------------------------------------


def test_arm_disarm_restores_prior_faulthandler_state(tmp_path):
    import faulthandler

    before = faulthandler.is_enabled()
    handle = flight.arm_faulthandler(str(tmp_path / "stacks.txt"))
    assert handle is not None and faulthandler.is_enabled()
    flight.disarm_faulthandler(handle)
    assert faulthandler.is_enabled() == before


def test_sigusr1_dump_includes_loader_producer_thread(tmp_path):
    """The satellite acceptance: an on-demand dump taken while the REAL
    DataLoader's producer thread is alive names it — frames inside
    loader.py's producer()."""
    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.data import DataLoader, DistributedSampler

    mesh = mesh_lib.data_parallel_mesh()
    n = 128
    images = np.zeros((n, 4, 4, 3), np.float32)
    labels = np.zeros(n, np.int32)
    sampler = DistributedSampler(n, 1, 0, shuffle=False)
    loader = DataLoader(images, labels, 16, sampler, mesh, prefetch=1)
    stacks = str(tmp_path / "stacks.txt")
    handle = flight.arm_faulthandler(stacks)
    assert handle is not None and handle.registered
    it = iter(loader)
    next(it)  # producer running; with prefetch=1 it blocks on a full queue
    try:
        time.sleep(0.2)
        os.kill(os.getpid(), signal.SIGUSR1)
        deadline = time.monotonic() + 5
        parsed = None
        while time.monotonic() < deadline:
            parsed = flight.read_stack_dump(stacks)
            if parsed and parsed["threads"]:
                break
            time.sleep(0.05)
    finally:
        for _ in it:  # drain so the producer exits cleanly
            pass
        flight.disarm_faulthandler(handle)
    assert parsed is not None
    assert parsed["current"] is not None  # this (main) thread dumped
    producer_frames = [
        fr
        for t in parsed["threads"]
        for fr in t["frames"]
        if fr[0].endswith("loader.py") and fr[2] == "producer"
    ]
    assert producer_frames, parsed["threads"]


def test_parse_stack_dump_last_dump_wins_and_stuck_frame():
    sample = (
        'Thread 0x00007f01 (producer):\n'
        '  File "/x/loader.py", line 118 in get\n'
        '  File "/x/loader.py", line 40 in run\n'
        'Current thread 0x00007f02 (most recent call first):\n'
        '  File "/x/faults.py", line 399 in _hang\n'
        '  File "/x/faults.py", line 330 in on_step\n'
    )
    one = flight.parse_stack_dump(sample)
    assert one["n_dumps"] == 1 and len(one["threads"]) == 2
    assert flight.stuck_frame(one) == "_hang (/x/faults.py:399)"
    two = flight.parse_stack_dump(sample + sample)  # SIGUSR1 appends
    assert two["n_dumps"] == 2
    assert flight.stuck_frame(two) == "_hang (/x/faults.py:399)"
    assert flight.parse_stack_dump("")["current"] is None
    assert flight.stuck_frame(flight.parse_stack_dump("garbage")) is None


# -- the hang fault site -----------------------------------------------------


def test_hang_clause_parses_fires_and_blocks_bounded():
    from tpu_dist.resilience import faults

    faults.install("hang@step=2:seconds=0.6")
    try:
        assert faults.on_step(0, 1) == frozenset()
        t0 = time.monotonic()
        acts = faults.on_step(0, 2)
        took = time.monotonic() - t0
        assert faults.HANG in acts
        assert took >= 0.5  # really blocked for ~seconds
        assert faults.on_step(0, 2) == frozenset()  # disarmed after times=1
    finally:
        faults.clear()


def test_hang_rank_pinned_never_fires_without_rank():
    from tpu_dist.resilience import faults

    faults.install("hang@step=1:rank=1:seconds=0.2")
    try:
        assert faults.on_step(0, 1, rank=None) == frozenset()
        assert faults.on_step(0, 1, rank=0) == frozenset()
        t0 = time.monotonic()
        assert faults.HANG in faults.on_step(0, 1, rank=1)
        assert time.monotonic() - t0 >= 0.15
    finally:
        faults.clear()


def test_hang_parse_errors_and_fused_refusal(tmp_path):
    from tests.helpers import tiny_resnet
    from tpu_dist.config import TrainConfig
    from tpu_dist.resilience import faults
    from tpu_dist.train.trainer import Trainer, register_model

    with pytest.raises(faults.FaultPlanError):
        faults.FaultPlan.parse("hang@epoch=1")  # step is required
    with pytest.raises(faults.FaultPlanError):
        faults.FaultPlan.parse("hang@step=1:call=3")  # not a hang key
    assert "hang" in faults.STEPWISE_SITES
    register_model("tiny_hang_cfg", lambda num_classes=10: tiny_resnet(num_classes))
    with pytest.raises(ValueError, match="hang"):
        Trainer(TrainConfig(
            dataset="synthetic", model="tiny_hang_cfg", num_classes=10,
            batch_size=64, synthetic_n=128, seed=0, fused_epoch=True,
            fault_plan="hang@step=1",
        ))
    faults.clear()


# -- elastic stale-rank sweep ------------------------------------------------


def test_sweep_stale_ranks_unit(tmp_path):
    from tpu_dist.obs.heartbeat import sweep_stale_ranks

    base = str(tmp_path / "hb.json")
    keep = [base, base + ".h1", base + ".h3"]
    stale = [base + ".h4", base + ".h7", base + ".h4.tmp"]
    for p in keep + stale:
        open(p, "w").write("{}")
    # an unrelated file that merely shares the prefix shape is untouched
    other = str(tmp_path / "hb.json.hx")
    open(other, "w").write("{}")
    removed = sweep_stale_ranks(base, 4)
    assert removed == 3
    assert all(os.path.exists(p) for p in keep + [other])
    assert not any(os.path.exists(p) for p in stale)
    assert sweep_stale_ranks(str(tmp_path / "absent" / "x"), 4) == 0


def test_launcher_sweeps_departed_rank_files_at_spawn(tmp_path):
    """After a shrink, the relaunched round must sweep heartbeats/
    metrics/forensics of ranks outside the new world — the watchdog and
    `obs pod` must never report a departed rank as dead."""
    from tpu_dist.cli.launch import main as launch_main

    hb_dir = tmp_path / "hb"
    m_dir = tmp_path / "m"
    c_dir = tmp_path / "c"
    for d in (hb_dir, m_dir, c_dir):
        d.mkdir()
    # leftovers from a defunct 8-wide world
    stale = [
        hb_dir / "hb.json.h5", m_dir / "metrics.prom.h6",
        c_dir / "flight.ring.h4", c_dir / "stacks.txt.h7",
    ]
    live = [hb_dir / "hb.json.h1", c_dir / "flight.ring.h1"]
    for p in stale + live:
        p.write_text("{}")
    rc = launch_main([
        "--nproc", "2",
        "--heartbeat_dir", str(hb_dir), "--metrics_dir", str(m_dir),
        "--crash_dir", str(c_dir), "--",
        sys.executable, "-c", "pass",
    ])
    assert rc == 0
    assert not any(p.exists() for p in stale)
    assert all(p.exists() for p in live)  # ranks inside the world stay


# -- postmortem assembly + CLI -----------------------------------------------


def _make_scene(d, *, rank1_fatal=True):
    """A two-rank crash scene: rank 0 hard-killed mid-step (ring stops,
    heartbeat left behind), rank 1 died on an exception (fatal slot +
    terminal record)."""
    os.makedirs(d, exist_ok=True)
    r0 = flight.FlightRecorder(
        os.path.join(d, flight.RING_NAME), run_id="run-x", rank=0, n_slots=16
    )
    r0.record("open", world=2)
    for i in range(4):
        r0.step(2, i)
    # no terminal record: SIGKILLed
    r1 = flight.FlightRecorder(
        os.path.join(d, flight.RING_NAME + ".h1"), run_id="run-x", rank=1,
        n_slots=16,
    )
    r1.record("open", world=2)
    r1.step(2, 0)
    if rank1_fatal:
        try:
            raise RuntimeError("boom on rank 1")
        except RuntimeError:
            r1.fatal(*sys.exc_info())
        r1.close("exit", clean=False)
    else:
        r1.close("exit", clean=True)
    with open(os.path.join(d, flight.STACKS_NAME), "w") as f:
        f.write(
            'Current thread 0x01 (most recent call first):\n'
            '  File "/x/loader.py", line 118 in get\n'
        )
    with open(os.path.join(d, "hb.json"), "w") as f:
        json.dump({"counter": 9, "epoch": 2, "step": 3, "phase": "train",
                   "ts": time.time()}, f)
    from tpu_dist.obs import export as export_lib

    with open(os.path.join(d, "metrics.prom"), "w") as f:
        f.write(export_lib.render(
            {"train.epoch": 2, "train.data_stall_frac": 0.4},
            {"alert_active": {"stall_high": 1}},
        ))
    with open(os.path.join(d, "run.jsonl"), "w") as f:
        for rec in (
            {"kind": "train_epoch", "epoch": 0, "run_id": "run-x",
             "schema_version": 9, "ts": 1.0, "rel_s": 1.0,
             "images_per_sec": 100.0, "loss": 2.0, "epoch_time": 1.0},
            {"kind": "train_epoch", "epoch": 1, "run_id": "run-x",
             "schema_version": 9, "ts": 2.0, "rel_s": 2.0,
             "images_per_sec": 101.0, "loss": 1.9, "epoch_time": 1.0},
        ):
            f.write(json.dumps(rec) + "\n")


def test_postmortem_assemble_discovers_and_classifies(tmp_path):
    d = str(tmp_path / "scene")
    _make_scene(d)
    report, bundle = postmortem_lib.run_postmortem([d])
    assert bundle == os.path.join(d, "postmortem.json")
    assert os.path.exists(bundle)
    assert report["n_ranks"] == 2
    by_rank = {r["rank"]: r for r in report["ranks"]}
    assert by_rank[0]["verdict"] == "no-clean-exit"
    ls = by_rank[0]["flight"]["last_step"]
    assert (ls["epoch"], ls["step"]) == (2, 3)
    assert by_rank[0]["stack"]["stuck_frame"] == "get (/x/loader.py:118)"
    assert by_rank[0]["heartbeat"]["counter"] == 9
    assert by_rank[0]["exposition"]["gauges"]["stall"] == "40.0%"
    assert by_rank[0]["exposition"]["active_alerts"] == ["stall_high"]
    assert by_rank[1]["verdict"] == "fatal"
    assert "boom on rank 1" in by_rank[1]["flight"]["fatal"]["message"]
    hist = report["histories"][0]
    assert hist["run_id"] == "run-x" and hist["n_records"] == 2
    text = postmortem_lib.format_text(report)
    assert "rank 0: NO-CLEAN-EXIT" in text
    assert "stuck in get (/x/loader.py:118)" in text
    assert "RuntimeError" in text


def test_postmortem_annotate_appends_v9_record(tmp_path):
    d = str(tmp_path / "scene")
    _make_scene(d)
    report, bundle = postmortem_lib.run_postmortem([d], annotate=True)
    lines = [json.loads(l) for l in open(os.path.join(d, "run.jsonl"))]
    pm = [r for r in lines if r["kind"] == "postmortem"]
    assert len(pm) == 1
    rec = pm[0]
    assert rec["schema_version"] == postmortem_lib.POSTMORTEM_SCHEMA_VERSION
    assert rec["bundle"] == bundle
    assert rec["verdicts"] == {"0": "no-clean-exit", "1": "fatal"}
    assert rec["stuck_frames"]["0"] == "get (/x/loader.py:118)"
    assert rec["last_steps"]["0"] == {"epoch": 2, "step": 3}
    assert "boom on rank 1" in rec["fatal"]["1"]


def test_postmortem_schema_literal_pinned_to_history():
    """The jax-free literal (the FLEET_SCHEMA_VERSION discipline) must
    track the real schema — this pin is the drift alarm."""
    from tpu_dist.metrics.history import SCHEMA_VERSION

    assert postmortem_lib.POSTMORTEM_SCHEMA_VERSION == SCHEMA_VERSION == 15


def test_rank_summary_shared_and_numeric_sort():
    """summarize/tail/pod all render per-rank lines through ONE
    formatter; the JSON string rank keys must order numerically (a
    16-rank pod is 0..15, not 0,1,10,11,...)."""
    ranks = {str(r): "clean" for r in range(16)}
    assert postmortem_lib.sorted_ranks(ranks) == [str(r) for r in range(16)]
    rec = _pm_record()
    assert postmortem_lib.rank_summary(rec, "0") == (
        "no-clean-exit, stuck in get (/x/loader.py:118), "
        "flight ring ends at epoch 2 step 3"
    )
    assert postmortem_lib.rank_summary(rec, "1") == "fatal, fatal RuntimeError: boom"


def test_uninstall_excepthooks_leaves_later_wrapper_in_place(tmp_path):
    """A hook installed AFTER ours must survive our uninstall — we only
    unwind our own layer when it is still on top."""
    rec = flight.FlightRecorder(str(tmp_path / "r.ring"), n_slots=4)
    prev = sys.excepthook
    try:
        rec.install_excepthooks()
        later = lambda *a: None  # noqa: E731 — someone wraps after us
        sys.excepthook = later
        rec.uninstall_excepthooks()
        assert sys.excepthook is later  # NOT blindly restored over it
    finally:
        rec.close()
        sys.excepthook = prev


def test_postmortem_cli_exit_codes(tmp_path, capsys):
    from tpu_dist.obs.__main__ import main as obs_main

    d = str(tmp_path / "scene")
    _make_scene(d)
    assert obs_main(["postmortem", d]) == 0
    out = capsys.readouterr().out
    assert "postmortem — 2 rank(s)" in out and "bundle written to" in out
    empty = tmp_path / "empty"
    empty.mkdir()
    assert obs_main(["postmortem", str(empty)]) == 1
    assert obs_main(["postmortem", str(tmp_path / "missing")]) == 2


# -- schema v9 rendering: summarize / tail / pod -----------------------------


def _pm_record(**over):
    rec = {
        "kind": "postmortem", "ts": 9.0, "rel_s": 9.0, "schema_version": 9,
        "run_id": "run-x", "n_ranks": 2, "bundle": "/w/postmortem.json",
        "verdicts": {"0": "no-clean-exit", "1": "fatal"},
        "stuck_frames": {"0": "get (/x/loader.py:118)"},
        "fatal": {"1": "RuntimeError: boom"},
        "last_steps": {"0": {"epoch": 2, "step": 3}},
    }
    rec.update(over)
    return rec


def test_summarize_folds_and_renders_postmortem():
    from tpu_dist.obs.summarize import format_text, summarize

    records = [
        {"kind": "train_epoch", "epoch": 0, "run_id": "run-x",
         "schema_version": 9, "ts": 1.0, "rel_s": 1.0,
         "images_per_sec": 100.0, "loss": 2.0, "epoch_time": 1.0},
        _pm_record(),
    ]
    report = summarize(records)
    assert report["skipped_kinds"] == {}  # postmortem is a KNOWN kind now
    assert len(report["postmortems"]) == 1
    text = format_text(report)
    assert "POSTMORTEM: crash bundle over 2 rank(s) — /w/postmortem.json" in text
    assert "rank 0: no-clean-exit, stuck in get (/x/loader.py:118)" in text
    assert "flight ring ends at epoch 2 step 3" in text
    assert "rank 1: fatal, fatal RuntimeError: boom" in text


def test_tail_renders_crash_events_and_exit_line():
    from tpu_dist.obs.tail import TailState

    state = TailState()
    state.add([
        {"kind": "train_epoch", "epoch": 0, "run_id": "run-x",
         "schema_version": 9, "images_per_sec": 100.0, "loss": 2.0},
        _pm_record(),
    ])
    assert state.finished and state.crashed
    frame = state.render()
    assert "POSTMORTEM: crash bundle over 2 rank(s)" in frame
    assert "rank 0 wedged — stuck in get (/x/loader.py:118)" in frame
    assert "fatal on rank 1: RuntimeError: boom" in frame
    assert "run: CRASHED — postmortem bundle left behind (/w/postmortem.json)" in frame
    assert "clean exit" not in frame
    # the clean run keeps its clean exit line
    clean = TailState()
    clean.add([
        {"kind": "goodput", "final": True, "run_id": "r2",
         "schema_version": 9, "goodput_frac": 0.9, "elapsed_s": 10.0},
    ])
    cframe = clean.render()
    assert clean.finished and not clean.crashed
    assert "run: clean exit" in cframe and "CRASHED" not in cframe


def test_tail_exits_on_postmortem_record(tmp_path, capsys):
    """`obs tail` must stop following a crashed run: no goodput-final
    record is ever coming from a dead writer."""
    from tpu_dist.obs.tail import run_tail

    log = str(tmp_path / "run.jsonl")
    with open(log, "w") as f:
        f.write(json.dumps(_pm_record()) + "\n")
    rc = run_tail(log, interval=0.05)
    assert rc == 0
    assert "CRASHED" in capsys.readouterr().out


def test_pod_report_surfaces_postmortems():
    from tpu_dist.obs.aggregate import format_text, pod_report

    records = [
        {"kind": "train_epoch", "epoch": 0, "run_id": "run-x",
         "schema_version": 9, "ts": 1.0, "rel_s": 1.0,
         "images_per_sec": 100.0, "loss": 2.0, "epoch_time": 1.0},
        _pm_record(),
    ]
    report = pod_report([("h0", records)])
    assert report["hosts"][0]["postmortems"]
    text = format_text(report)
    assert "POSTMORTEM on h0: crash bundle over 2 rank(s)" in text
    assert "rank 0: no-clean-exit, stuck in get (/x/loader.py:118)" in text


# -- spans open-listener tap -------------------------------------------------


def test_span_open_listener_fires_with_recorder_disabled():
    from tpu_dist.obs import spans

    assert not spans.enabled()
    seen = []
    spans.set_open_listener(lambda name, args: seen.append(name))
    try:
        with spans.span("ckpt/write", file="x"):
            pass
        assert seen == ["ckpt/write"]
        assert spans.events() == []  # disabled: the tap buffers nothing
    finally:
        spans.clear_open_listener()
    with spans.span("ckpt/write"):
        pass
    assert seen == ["ckpt/write"]  # cleared listener no longer fires


# -- trainer integration -----------------------------------------------------


@pytest.mark.slow  # full trainer fits (compile): CI crash-forensics step
# runs this module without the slow filter (ISSUE 12)
def test_trainer_crash_dir_rings_clean_and_fatal(tmp_path):
    """fit() with --crash_dir arms the whole kit: a clean run's ring ends
    with `exit` (clean), a diverging run's ring carries the fatal slot
    for TrainingDivergedError even though fit re-raised it."""
    from tests.helpers import tiny_resnet
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer, TrainingDivergedError, register_model

    register_model("tiny_flight_e2e",
                   lambda num_classes=10: tiny_resnet(num_classes))
    crash = str(tmp_path / "crash")
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_flight_e2e", num_classes=10,
        batch_size=64, epochs=1, steps_per_epoch=3, synthetic_n=192,
        seed=0, eval_every=0, log_every=1, crash_dir=crash,
        log_file=str(tmp_path / "run.jsonl"),
    )
    Trainer(cfg).fit()
    dec = flight.decode(os.path.join(crash, flight.RING_NAME))
    kinds = [r["kind"] for r in dec["records"]]
    assert dec["last"]["kind"] == "exit" and dec["last"]["clean"] is True
    assert "open" in kinds and "step" in kinds and "span" in kinds
    assert flight.last_step(dec)["step"] == 2
    assert os.path.exists(os.path.join(crash, flight.STACKS_NAME))
    import faulthandler

    assert not faulthandler.is_enabled() or True  # disarm restored prior state

    crash2 = str(tmp_path / "crash2")
    cfg2 = cfg.replace(crash_dir=crash2, fault_plan="nan_loss@step=1")
    with pytest.raises(TrainingDivergedError):
        Trainer(cfg2).fit()
    dec2 = flight.decode(os.path.join(crash2, flight.RING_NAME))
    fatals = flight.fatal_records(dec2)
    assert fatals and fatals[0]["error"] == "TrainingDivergedError"
    assert dec2["last"]["kind"] == "exit" and dec2["last"]["clean"] is False
    report = postmortem_lib.assemble([crash2])
    assert report["ranks"][0]["verdict"] == "fatal"
    from tpu_dist.obs import spans
    from tpu_dist.resilience import faults

    assert spans._OPEN_LISTENER is None  # teardown cleared the tap
    faults.clear()


# -- launcher watchdog stack capture e2e -------------------------------------


@pytest.mark.slow  # real multi-second watchdog waits; CI crash-forensics
# step runs this module without the slow filter (ISSUE 12)
def test_watchdog_sigusr1_dump_names_stuck_frame_then_kills(tmp_path, capsys):
    """A live-but-frozen child with forensics armed: the watchdog must
    request the SIGUSR1 dump, name the stuck frame, escalate, and
    auto-assemble the postmortem bundle."""
    from tpu_dist.cli.launch import main as launch_main

    work = str(tmp_path)
    child = textwrap.dedent(f"""
        import json, os, sys, time
        sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(flight.__file__)))!r})
        from tpu_dist.obs import flight
        argv = sys.argv
        hb = argv[argv.index('--heartbeat_file') + 1]
        crash = argv[argv.index('--crash_dir') + 1]
        rec = flight.FlightRecorder(os.path.join(crash, flight.RING_NAME))
        rec.record('open', world=1)
        rec.step(1, 7)
        handle = flight.arm_faulthandler(
            os.path.join(crash, flight.STACKS_NAME))
        json.dump({{'counter': 1, 'epoch': 1, 'step': 7, 'phase': 'train',
                   'ts': time.time()}}, open(hb, 'w'))
        def stuck_in_collective():
            while True:
                time.sleep(0.2)
        stuck_in_collective()
    """)
    t0 = time.monotonic()
    rc = launch_main([
        "--nproc", "1", "--heartbeat_dir", work, "--crash_dir", work,
        "--watchdog_timeout", "2", "--watchdog_dump_grace", "6",
        "--watchdog_grace", "2", "--",
        sys.executable, "-c", child,
    ])
    took = time.monotonic() - t0
    assert rc != 0 and rc != 75
    assert took < 60
    err = capsys.readouterr().err
    assert "WATCHDOG: worker 0 wedged" in err
    assert "requesting all-threads stack dump" in err
    assert "stack dump: stuck in" in err and "stuck_in_collective" in err
    assert "postmortem bundle written to" in err
    bundle = json.load(open(os.path.join(work, "postmortem.json")))
    rank0 = bundle["ranks"][0]
    assert rank0["verdict"] == "no-clean-exit"
    assert "stuck_in_collective" in rank0["stack"]["stuck_frame"]
    assert rank0["flight"]["last_step"]["step"] == 7


@pytest.mark.slow  # ~40s subprocess chain; CI crash-forensics step runs
# this module without the slow filter (ISSUE 12)
def test_postmortem_drill_end_to_end(tmp_path):
    """`make postmortem-drill`: a real hung trainer detected, dumped,
    killed, and bundled — the acceptance chain in one invocation."""
    from tpu_dist.obs.drill import main as drill_main

    assert drill_main(["--workdir", str(tmp_path / "drill")]) == 0


# -- TD113 -------------------------------------------------------------------


@pytest.mark.slow  # traces the full dp step twice (compile-heavy); CI
# crash-forensics step runs this module without the slow filter
def test_td113_gate_and_registry():
    from tpu_dist.analysis.jaxpr_audit import flight_recorder_noop_violations
    from tpu_dist.analysis.rules import RULES

    assert "TD113" in RULES
    assert RULES["TD113"].name == "flight-recorder-not-noop"
    assert flight_recorder_noop_violations() == []
