"""The longitudinal run archive (ISSUE 20, docs/observability.md
"Longitudinal archive & trend gating"): ingest idempotence by capture
fingerprint with stale re-emissions archived-but-excluded, torn-tail
healing, forward-compat newer-schema skip-with-count, MAD-band
arithmetic against hand math, the ``compare --against-archive`` exit
contract (0 in-band / 1 regressed / 2 when the gate compared nothing),
CUSUM changepoint localization + ``--blame``, hub snapshot records,
``bench.py --archive`` never-dies self-ingest, the seeded
``tools/bench_archive.jsonl`` golden, and the TD124 noop gate with its
vacuity guard. Everything here is host-side file arithmetic except the
TD124 jaxpr gate, which gates in the analysis.yml archive step too.
"""

import inspect
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

THROUGHPUT = "resnet18_cifar100_train_throughput"


def _bench_rec(value, i, *, metric=THROUGHPUT, **extra):
    rec = {
        "metric": metric,
        "value": value,
        "unit": "images/sec",
        "capture": {
            "host": "testhost",
            "bench_run_id": f"run{i:02d}",
            "mono_s": float(i),
        },
    }
    rec.update(extra)
    return rec


def _write_jsonl(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    return str(path)


def _seed_archive(tmp_path, values, name="archive.jsonl"):
    """Ingest one fresh bench record per value and return the archive."""
    from tpu_dist.obs import archive as archive_lib

    arch = str(tmp_path / name)
    src = _write_jsonl(
        tmp_path / "seed_bench.jsonl",
        [_bench_rec(v, i) for i, v in enumerate(values)],
    )
    archive_lib.ingest_paths([src], arch)
    return arch


# -- ingest: idempotence, staleness, torn tails, forward compat --------------


def test_ingest_idempotent_by_capture_fingerprint(tmp_path):
    from tpu_dist.obs import archive as archive_lib

    arch = str(tmp_path / "archive.jsonl")
    src = _write_jsonl(
        tmp_path / "bench.jsonl",
        [_bench_rec(100.0 + i, i) for i in range(4)],
    )
    rep1 = archive_lib.ingest_paths([src], arch)
    assert rep1["appended"] == 4 and rep1["deduped"] == 0
    rep2 = archive_lib.ingest_paths([src], arch)
    assert rep2["appended"] == 0 and rep2["deduped"] == 4
    records, counts = archive_lib.load_archive(arch)
    assert len(records) == 4 and counts["bad_lines"] == 0
    # seq is monotone from 1 in archive order
    assert [r["seq"] for r in records] == [1, 2, 3, 4]
    assert all(r["schema"] == archive_lib.SCHEMA for r in records)


def test_stale_reemission_archived_flagged_and_excluded(tmp_path):
    """A re-emitted capture (bench's stale-stamped last-good fallback,
    the BENCH_r05 shape) archives as its OWN record — flagged STALE,
    fingerprint suffixed so it does not dedupe-collide with the fresh
    original — and the band is built from the fresh records only."""
    from tpu_dist.obs import archive as archive_lib

    arch = str(tmp_path / "archive.jsonl")
    fresh = [_bench_rec(100.0, 0), _bench_rec(102.0, 1)]
    reemit = dict(fresh[1], stale=True, note="re-emitted last good")
    src = _write_jsonl(tmp_path / "bench.jsonl", fresh + [reemit])
    rep = archive_lib.ingest_paths([src], arch)
    assert rep["appended"] == 3 and rep["stale_appended"] == 1
    records, _ = archive_lib.load_archive(arch)
    stale = [r for r in records if r["stale"]]
    assert len(stale) == 1
    assert ":stale:" in stale[0]["fingerprint"]
    assert stale[0]["meta"].get("reemitted_capture") is True
    band = archive_lib.band_for(records, THROUGHPUT, "value")
    assert band is not None and band["n"] == 2  # stale point excluded
    assert band["median"] == pytest.approx(101.0)
    # re-ingesting the same stream appends nothing: the fresh records
    # dedupe on their capture fingerprint and the stale copy on its
    # content-suffixed one
    rep2 = archive_lib.ingest_paths([src], arch)
    assert rep2["appended"] == 0 and rep2["deduped"] == 3


def test_byte_identical_duplicate_dedupes_not_stale(tmp_path):
    """A byte-equivalent duplicate of an archived FRESH record (same
    label, metrics, provenance) is a re-ingest — deduped, never minted
    as a spurious STALE copy. Only a re-emission that DIFFERS (the
    stale stamp, a driver round's meta) archives as a stale record."""
    from tpu_dist.obs import archive as archive_lib

    arch = str(tmp_path / "archive.jsonl")
    rec = _bench_rec(100.0, 0)
    src = _write_jsonl(tmp_path / "bench.jsonl", [rec, dict(rec)])
    rep = archive_lib.ingest_paths([src], arch)
    assert rep["appended"] == 1 and rep["deduped"] == 1
    assert rep["stale_appended"] == 0


def test_torn_tail_healed_on_append_and_counted_on_load(tmp_path):
    """A writer killed mid-line leaves a torn fragment; the next append
    isolates it on its own line and the loader counts (never crashes)."""
    from tpu_dist.obs import archive as archive_lib

    arch = _seed_archive(tmp_path, [100.0, 101.0])
    with open(arch, "a") as f:
        f.write('{"schema": "archive_record_v1", "label": "to')  # torn
    src = _write_jsonl(tmp_path / "more.jsonl", [_bench_rec(102.0, 9)])
    rep = archive_lib.ingest_paths([src], arch)
    assert rep["appended"] == 1
    records, counts = archive_lib.load_archive(arch)
    assert counts["bad_lines"] == 1
    assert len(records) == 3  # the record appended AFTER the tear is intact
    assert records[-1]["metrics"]["value"] == 102.0


def test_forward_compat_newer_schema_read_with_count(tmp_path):
    """archive_record_v2+ lines are read by their known fields and
    counted; non-archive lines are skipped with a count — the house
    additive-bump contract, never a crash."""
    from tpu_dist.obs import archive as archive_lib

    arch = _seed_archive(tmp_path, [100.0])
    with open(arch, "a") as f:
        f.write(json.dumps({
            "schema": "archive_record_v2", "label": THROUGHPUT,
            "fingerprint": "capture:future:run99:9.0", "stale": False,
            "metrics": {"value": 101.0}, "seq": 2,
            "from_the_future": {"shiny": True},
        }) + "\n")
        f.write(json.dumps({"kind": "train_epoch", "epoch": 0}) + "\n")
    records, counts = archive_lib.load_archive(arch)
    assert counts["newer_schema"] == 1 and counts["skipped_schema"] == 1
    assert len(records) == 2
    band = archive_lib.band_for(records, THROUGHPUT, "value")
    assert band["n"] == 2  # the v2 record's known fields participate


def test_ingest_unrecognized_input_is_exit_2(tmp_path, capsys):
    from tpu_dist.obs.__main__ import main as obs_main

    bad = tmp_path / "mystery.json"
    bad.write_text(json.dumps({"weird": "shape"}))
    arch = str(tmp_path / "archive.jsonl")
    assert obs_main(["archive", "ingest", str(bad), "-a", arch]) == 2
    assert "failed" in capsys.readouterr().err
    assert not os.path.exists(arch)  # nothing half-appended


# -- the MAD band -------------------------------------------------------------


def test_band_math_matches_hand_arithmetic(tmp_path):
    """median/MAD and the allowance against hand-computed values:
    vals = [100, 101, 102, 103, 120] -> median 102, MAD 1;
    allowed = max(k*MAD, rel_floor*|median|) + slack."""
    from tpu_dist.obs import archive as archive_lib
    from tpu_dist.obs import compare as compare_lib

    arch = _seed_archive(tmp_path, [100.0, 101.0, 102.0, 103.0, 120.0])
    records, _ = archive_lib.load_archive(arch)
    band = archive_lib.band_for(records, THROUGHPUT, "value")
    assert band["n"] == 5
    assert band["median"] == pytest.approx(102.0)
    # |v - 102| = [2, 1, 0, 1, 18] -> median 1
    assert band["mad"] == pytest.approx(1.0)
    _direction, slack = compare_lib.direction_of("value")
    row = archive_lib._gate_row(
        "value", THROUGHPUT, "value", 96.0, records,
        k=3.0, window=20, rel_floor=0.05,
    )
    # max(3*1.0, 0.05*102) = 5.1 (+ slack); 102 - 96 = 6 > 5.1 -> REGRESSED
    assert row["allowed"] == pytest.approx(max(3.0, 5.1) + slack)
    assert row["verdict"] == "REGRESSED"
    ok = archive_lib._gate_row(
        "value", THROUGHPUT, "value", 97.0, records,
        k=3.0, window=20, rel_floor=0.05,
    )
    assert ok["verdict"] == ("ok" if slack >= 0.0 else "REGRESSED")
    assert ok["verdict"] == "ok"  # 102 - 97 = 5 < 5.1


def test_band_window_keeps_trailing_records(tmp_path):
    from tpu_dist.obs import archive as archive_lib

    arch = _seed_archive(tmp_path, [50.0] * 10 + [100.0] * 5)
    records, _ = archive_lib.load_archive(arch)
    band = archive_lib.band_for(records, THROUGHPUT, "value", window=5)
    assert band["n"] == 5 and band["median"] == pytest.approx(100.0)


# -- the gate exit contract ---------------------------------------------------


def test_gate_exit_contract_0_in_band_1_regressed(tmp_path, capsys):
    from tpu_dist.obs.__main__ import main as obs_main

    arch = _seed_archive(tmp_path, [100.0, 100.5, 99.5, 100.2, 99.8])
    same = _write_jsonl(tmp_path / "same.jsonl", [_bench_rec(100.1, 50)])
    worse = _write_jsonl(tmp_path / "worse.jsonl", [_bench_rec(90.0, 51)])
    better = _write_jsonl(
        tmp_path / "better.jsonl", [_bench_rec(120.0, 52)]
    )
    assert obs_main(
        ["compare", same, "--against-archive", arch, "--bench"]
    ) == 0
    assert obs_main(
        ["compare", worse, "--against-archive", arch, "--bench"]
    ) == 1
    # better than the band is NEVER flagged (direction-aware)
    assert obs_main(
        ["compare", better, "--against-archive", arch, "--bench"]
    ) == 0
    out = capsys.readouterr().out
    assert "REGRESSED" in out and "archive gate" in out


def test_gate_all_stale_compares_nothing_exits_2(tmp_path, capsys):
    """When every archived point for the candidate's metrics is a stale
    re-emission there is no band; the gate compared nothing and must
    exit 2, never silently pass — the exact r03-r05 wound."""
    from tpu_dist.obs import archive as archive_lib
    from tpu_dist.obs.__main__ import main as obs_main

    arch = str(tmp_path / "archive.jsonl")
    src = _write_jsonl(
        tmp_path / "stale.jsonl", [_bench_rec(100.0, 0, stale=True)]
    )
    rep = archive_lib.ingest_paths([src], arch)
    assert rep["stale_appended"] == 1
    cand = _write_jsonl(tmp_path / "cand.jsonl", [_bench_rec(100.0, 9)])
    assert obs_main(
        ["compare", cand, "--against-archive", arch, "--bench"]
    ) == 2
    assert "compared nothing" in capsys.readouterr().err


def test_gate_stale_candidate_is_flagged_not_compared(tmp_path, capsys):
    """A candidate that re-emits an ARCHIVED capture fingerprint is a
    stale copy: its row reads STALE and contributes nothing."""
    from tpu_dist.obs.__main__ import main as obs_main

    arch = _seed_archive(tmp_path, [100.0, 100.5, 99.5])
    # re-emit archived capture 1 (bench_run_id run01 / mono_s 1.0)
    cand = _write_jsonl(tmp_path / "cand.jsonl", [_bench_rec(100.5, 1)])
    assert obs_main(
        ["compare", cand, "--against-archive", arch, "--bench",
         "--format", "json"]
    ) == 2
    out = capsys.readouterr().out
    result = json.loads(out[out.index("{"):])
    assert result["stale"] == 1 and result["compared"] == 0


def test_gate_bad_invocations_exit_2(tmp_path, capsys):
    from tpu_dist.obs.__main__ import main as obs_main

    arch = _seed_archive(tmp_path, [100.0])
    cand = _write_jsonl(tmp_path / "c.jsonl", [_bench_rec(100.0, 9)])
    # two positionals with --against-archive: the archive IS the baseline
    assert obs_main(
        ["compare", cand, cand, "--against-archive", arch, "--bench"]
    ) == 2
    # empty archive: a gate with no history is broken, not passing
    empty = str(tmp_path / "empty.jsonl")
    open(empty, "w").close()
    assert obs_main(
        ["compare", cand, "--against-archive", empty, "--bench"]
    ) == 2
    # --band-k without --against-archive is a contract violation
    assert obs_main(["compare", cand, cand, "--band-k", "2.0"]) == 2
    capsys.readouterr()


def test_gate_band_k_widens_the_band(tmp_path):
    from tpu_dist.obs.__main__ import main as obs_main

    arch = _seed_archive(tmp_path, [100.0, 101.0, 102.0, 103.0, 104.0])
    cand = _write_jsonl(tmp_path / "c.jsonl", [_bench_rec(93.0, 9)])
    args = ["compare", cand, "--against-archive", arch, "--bench"]
    assert obs_main(args + ["--band-k", "3.0"]) == 1
    assert obs_main(args + ["--band-k", "12.0"]) == 0


# -- trend + changepoint blame ------------------------------------------------


def test_changepoint_localizes_injected_step(tmp_path):
    from tpu_dist.obs import archive as archive_lib

    values = [100.0, 100.2, 99.8, 100.1, 99.9, 100.0,
              90.0, 90.2, 89.8, 90.1]
    arch = _seed_archive(tmp_path, values)
    records, _ = archive_lib.load_archive(arch)
    report = archive_lib.trend_report(records, metric="value")
    (series,) = [s for s in report["series"] if s["metric"] == "value"]
    cp = series["changepoint"]
    assert cp is not None and cp["index"] == 6
    assert cp["kind"] == "regressed"  # throughput stepped DOWN
    assert cp["blame"]["fingerprint"] == "capture:testhost:run06:6.0"
    assert cp["before_mean"] == pytest.approx(100.0, abs=0.1)
    assert cp["after_mean"] == pytest.approx(90.0, abs=0.2)


def test_changepoint_flat_series_never_flags(tmp_path):
    """Float dust on a flat series must not flag (the rel_min floor)."""
    from tpu_dist.obs import archive as archive_lib

    vals = [100.0 + 0.001 * ((-1) ** i) for i in range(12)]
    arch = _seed_archive(tmp_path, vals)
    records, _ = archive_lib.load_archive(arch)
    report = archive_lib.trend_report(records, metric="value")
    (series,) = [s for s in report["series"] if s["metric"] == "value"]
    assert series["changepoint"] is None


def test_trend_cli_blame_names_the_record(tmp_path, capsys):
    from tpu_dist.obs.__main__ import main as obs_main

    values = [100.0, 100.2, 99.8, 100.1, 99.9, 100.0,
              90.0, 90.2, 89.8, 90.1]
    arch = _seed_archive(tmp_path, values)
    assert obs_main(["trend", arch, "--blame"]) == 0
    out = capsys.readouterr().out
    assert "changepoint [regressed]" in out
    assert "blame: first shifted record is fingerprint " \
        "capture:testhost:run06:6.0" in out
    # empty archive: nothing to trend -> exit 1
    empty = str(tmp_path / "none.jsonl")
    open(empty, "w").close()
    assert obs_main(["trend", empty]) == 1
    capsys.readouterr()


def test_trend_stale_only_metric_renders_counted_not_empty(tmp_path):
    from tpu_dist.obs import archive as archive_lib

    arch = str(tmp_path / "archive.jsonl")
    src = _write_jsonl(
        tmp_path / "stale.jsonl", [_bench_rec(100.0, 0, stale=True)]
    )
    archive_lib.ingest_paths([src], arch)
    records, _ = archive_lib.load_archive(arch)
    report = archive_lib.trend_report(records)
    (series,) = [s for s in report["series"] if s["metric"] == "value"]
    assert series["n"] == 0 and series["n_stale"] == 1
    text = archive_lib.format_trend_text(report)
    assert "+1 STALE excluded" in text


# -- the TD124 injected-fault probe -------------------------------------------


def test_inject_regression_probe_catches_and_localizes(tmp_path, capsys):
    from tpu_dist.obs.__main__ import main as obs_main

    arch = _seed_archive(tmp_path, [100.0, 100.5, 99.5, 100.2, 99.8])
    assert obs_main(
        ["trend", arch, "--inject-regression", "--format", "json"]
    ) == 0
    out = capsys.readouterr().out
    probe = json.loads(out[out.index("{"):])
    assert probe["gate_probe"] == "caught"
    assert probe["improvements_clean"] is True
    assert probe["changepoint_probe"] == "localized"
    assert probe["bands_probed"] >= 1
    assert all(g["caught"] for g in probe["gate_results"])


def test_dead_detector_exits_2(tmp_path, capsys, monkeypatch):
    """Gut the band gate so the injected regression comes back unflagged:
    the probe must report DEAD and the CLI must exit 2 (TD124)."""
    from tpu_dist.obs import archive as archive_lib
    from tpu_dist.obs.__main__ import main as obs_main

    arch = _seed_archive(tmp_path, [100.0, 100.5, 99.5, 100.2, 99.8])
    real_row = archive_lib._gate_row

    def lobotomized(*args, **kw):
        row = real_row(*args, **kw)
        if row.get("verdict") == "REGRESSED":
            row["verdict"] = "ok"
        return row

    monkeypatch.setattr(archive_lib, "_gate_row", lobotomized)
    assert obs_main(["trend", arch, "--inject-regression"]) == 2
    assert "dead" in capsys.readouterr().err
    # the library-level verdict agrees
    records, _ = archive_lib.load_archive(arch)
    assert archive_lib.probe_is_dead(archive_lib.inject_probe(records))


# -- TD124: registered, gated, vacuity-guarded --------------------------------


def test_td124_registered_and_audit_all_wired():
    from tpu_dist.analysis import jaxpr_audit
    from tpu_dist.analysis.rules import RULES

    assert "TD124" in RULES
    assert RULES["TD124"].name == "archive-gate-not-vacuous"
    assert "archive_gate_noop_violations" in inspect.getsource(
        jaxpr_audit.audit_all
    )


def test_td124_gate_archive_kit_is_noop():
    from tpu_dist.analysis.jaxpr_audit import archive_gate_noop_violations

    assert archive_gate_noop_violations() == []


def test_td124_probe_is_vacuity_guarded(monkeypatch):
    """A probe whose detector went dead must REPORT, not pass — gut
    probe_is_dead's input by making the gate miss everything."""
    from tpu_dist.analysis.jaxpr_audit import archive_gate_noop_violations
    from tpu_dist.obs import archive as archive_lib

    monkeypatch.setattr(
        archive_lib, "probe_is_dead", lambda probe: True
    )
    vs = archive_gate_noop_violations()
    assert len(vs) == 1 and vs[0].rule == "TD124"
    assert "VACUOUS" in vs[0].message or "dead" in vs[0].message


# -- satellites: seeded archive, hub records, bench self-ingest, stamp --------


def test_seeded_archive_golden_matches_committed_artifacts(monkeypatch):
    """tools/bench_archive.jsonl is exactly what `obs archive ingest`
    produces from the committed r01-r05 + last-good artifacts: 4 empty
    STALE bench_probe rounds, 1 stale re-emission, 5 multichip points,
    1 fresh last-good capture — rebuildable byte-for-record."""
    from tpu_dist.obs import archive as archive_lib

    monkeypatch.chdir(REPO)
    committed, counts = archive_lib.load_archive(
        os.path.join(REPO, "tools", "bench_archive.jsonl")
    )
    assert counts["bad_lines"] == 0 and counts["newer_schema"] == 0
    assert len(committed) == 11
    assert sum(1 for r in committed if r["stale"]) == 5
    probes = [r for r in committed if r["label"] == "bench_probe"]
    assert len(probes) == 4 and all(r["stale"] for r in probes)
    fresh_bench = [
        r for r in committed
        if r["label"] == THROUGHPUT and not r["stale"]
    ]
    assert len(fresh_bench) == 1
    assert fresh_bench[0]["metrics"]["value"] == pytest.approx(36438.2)
    multi = [r for r in committed if r["label"] == "multichip_dryrun"]
    assert len(multi) == 5
    assert sum(r["metrics"]["multichip_ok"] for r in multi) == 4.0
    # rebuild from the same inputs -> identical records (ignoring none)
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        arch = os.path.join(td, "rebuilt.jsonl")
        inputs = (
            [f"BENCH_r0{i}.json" for i in range(1, 6)]
            + [f"MULTICHIP_r0{i}.json" for i in range(1, 6)]
            + ["LAST_GOOD_BENCH.json"]
        )
        archive_lib.ingest_paths(inputs, arch)
        rebuilt, _ = archive_lib.load_archive(arch)
    assert rebuilt == committed


def test_seeded_archive_self_gate_and_probe_pass(monkeypatch, capsys):
    """The `make trend-report` contract: the last-good capture gates
    in-band against the seeded archive (exit 0) and the TD124
    inject-regression probe is alive (exit 0, not 2)."""
    from tpu_dist.obs.__main__ import main as obs_main

    monkeypatch.chdir(REPO)
    arch = os.path.join("tools", "bench_archive.jsonl")
    assert obs_main(
        ["compare", "LAST_GOOD_BENCH.json", "--against-archive", arch,
         "--bench"]
    ) == 0
    assert obs_main(["trend", arch, "--inject-regression"]) == 0
    capsys.readouterr()


def test_hub_snapshot_record_and_append(tmp_path):
    from tpu_dist.obs import archive as archive_lib

    snapshot = {
        "scrapes": 3,
        "drops": 1,
        "rollup": {
            "runs_aggregated": 2, "runs_dead": 1, "breach_count": 2,
            "total_chips": 8, "worst_stall_frac": 0.25,
            "goodput_by_kind": {"train": 0.9, "serve": 0.97},
        },
    }
    arch = str(tmp_path / "hub_archive.jsonl")
    rec = archive_lib.append_hub_snapshot(arch, snapshot, now=123.0)
    assert rec["label"] == "pod" and rec["source"] == "hub"
    assert rec["metrics"] == {
        "pod_runs_dead": 1, "pod_breach_count": 2, "pod_total_chips": 8,
        "pod_worst_stall_frac": 0.25, "pod_goodput_frac_train": 0.9,
        "pod_goodput_frac_serve": 0.97,
    }
    assert rec["fingerprint"].startswith("hub:")
    assert rec["meta"]["runs_aggregated"] == 2
    # a second interval appends (distinct fingerprint), never collides
    snapshot["scrapes"] = 4
    archive_lib.append_hub_snapshot(arch, snapshot, now=124.0)
    records, _ = archive_lib.load_archive(arch)
    assert len(records) == 2 and records[1]["seq"] == 2
    # every hub metric has a registered direction (gateable)
    from tpu_dist.obs import compare as compare_lib

    for name in rec["metrics"]:
        assert compare_lib.direction_of(name)


def test_bench_self_ingest_never_dies(tmp_path, capsys):
    """bench.py --archive: records emitted through _stamped self-ingest
    at exit; an unwritable archive warns and NEVER raises (a perf probe
    must not die on its bookkeeping)."""
    import bench

    rec = {"metric": "synthetic", "value": 1.0}
    arch = str(tmp_path / "bench_archive.jsonl")
    bench._self_ingest(arch, [_bench_rec(100.0, 0)])
    from tpu_dist.obs import archive as archive_lib

    records, _ = archive_lib.load_archive(arch)
    assert len(records) == 1 and records[0]["source_path"] == "bench.py"
    # a directory path cannot be appended to: warn, don't raise
    bench._self_ingest(str(tmp_path), [rec])
    err = capsys.readouterr().err
    assert "archive" in err
    # _stamped feeds the module-level emission list the atexit hook reads
    before = len(bench._EMITTED)
    bench._stamped(dict(rec))
    assert len(bench._EMITTED) == before + 1
    bench._EMITTED.pop()


def test_summarize_json_stamps_capture_fingerprint(tmp_path, capsys):
    """`obs summarize --format json` stamps the content-based capture
    identity + source log path that archive ingest dedupes by."""
    from tpu_dist.obs import summarize as summ
    from tpu_dist.obs.__main__ import main as obs_main

    log = _write_jsonl(tmp_path / "run.jsonl", [{
        "kind": "train_epoch", "epoch": 0, "run_id": "r1", "loss": 2.0,
        "epoch_time": 2.0, "images_per_sec": 1000.0,
        "step_time_p50": 0.01, "step_time_p95": 0.02,
        "step_time_p99": 0.03, "data_stall_frac": 0.05,
    }])
    assert obs_main(["summarize", log, "--format", "json"]) == 0
    out = capsys.readouterr().out
    report = json.loads(out)
    assert report["capture"]["fingerprint"] == \
        summ.capture_stamp(log)["fingerprint"]
    assert report["capture"]["run_id"] == "r1"
    assert report["source_log"] == os.path.abspath(log)
    # content-based: a byte-identical copy fingerprints identically
    copy = str(tmp_path / "copy.jsonl")
    with open(log) as src, open(copy, "w") as dst:
        dst.write(src.read())
    assert summ.capture_stamp(copy)["fingerprint"] == \
        report["capture"]["fingerprint"]


def test_history_log_ingests_and_gates(tmp_path):
    """A --log_file history archives one record over its summarize
    scalars (label `history`) and a worse candidate history regresses
    against the band."""
    from tpu_dist.obs import archive as archive_lib
    from tpu_dist.obs.__main__ import main as obs_main

    def _hist(path, ips):
        return _write_jsonl(path, [{
            "kind": "train_epoch", "epoch": e, "run_id": "r", "loss": 2.0,
            "epoch_time": 2.0, "images_per_sec": ips,
            "step_time_p50": 0.01, "step_time_p95": 0.02,
            "step_time_p99": 0.03, "data_stall_frac": 0.05,
        } for e in range(2)])

    arch = str(tmp_path / "archive.jsonl")
    for i, ips in enumerate([1000.0, 1010.0, 990.0]):
        src = _hist(tmp_path / f"h{i}.jsonl", ips)
        rep = archive_lib.ingest_paths([src], arch)
        assert rep["appended"] == 1
    records, _ = archive_lib.load_archive(arch)
    assert all(r["label"] == "history" for r in records)
    assert records[0]["fingerprint"].startswith("history:")
    worse = _hist(tmp_path / "worse.jsonl", 600.0)
    assert obs_main(["compare", worse, "--against-archive", arch]) == 1
    same = _hist(tmp_path / "same.jsonl", 1000.0)
    assert obs_main(["compare", same, "--against-archive", arch]) == 0
