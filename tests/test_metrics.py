"""Meter math and accuracy parity with the reference kit (``utils/util.py``)."""

import numpy as np
import jax.numpy as jnp

from tpu_dist.metrics.meters import AverageMeter, ProgressMeter
from tpu_dist.nn import functional as F


def test_average_meter():
    m = AverageMeter("loss", ":.2f")
    m.update(2.0, n=2)
    m.update(4.0, n=2)
    assert m.val == 4.0
    assert m.sum == 12.0
    assert m.count == 4
    assert m.avg == 3.0
    assert "loss" in str(m)
    m.reset()
    assert m.count == 0


def test_progress_meter_format():
    m = AverageMeter("Loss", ":.1f")
    m.update(1.5)
    p = ProgressMeter(196, m, prefix="Epoch: ")
    line = p.display(12)
    assert "[ 12/196]" in line and "Loss" in line


def test_accuracy_matches_torch_reference():
    """accuracy(output, target, topk) parity with utils/util.py:50-64."""
    import torch

    rng = np.random.default_rng(0)
    logits = rng.normal(size=(32, 100)).astype(np.float32)
    labels = rng.integers(0, 100, 32)

    # reference implementation, transcribed semantics: topk -> eq -> ratio
    tl = torch.tensor(logits)
    tt = torch.tensor(labels)
    _, pred = tl.topk(5, 1, True, True)
    correct = pred.t().eq(tt.view(1, -1).expand_as(pred.t()))
    ref1 = correct[:1].reshape(-1).float().sum(0) * 100.0 / 32
    ref5 = correct[:5].reshape(-1).float().sum(0) * 100.0 / 32

    a1, a5 = F.accuracy(jnp.array(logits), jnp.array(labels), topk=(1, 5))
    np.testing.assert_allclose(float(a1), float(ref1), rtol=1e-5)
    np.testing.assert_allclose(float(a5), float(ref5), rtol=1e-5)


def test_cross_entropy_matches_torch():
    import torch

    rng = np.random.default_rng(1)
    logits = rng.normal(size=(16, 10)).astype(np.float32)
    labels = rng.integers(0, 10, 16)
    ref = torch.nn.functional.cross_entropy(
        torch.tensor(logits), torch.tensor(labels)
    ).item()
    got = float(F.cross_entropy(jnp.array(logits), jnp.array(labels)))
    np.testing.assert_allclose(got, ref, rtol=1e-5)
