"""End-to-end tensor-parallel training (DP×TP, Megatron ViT) through
make_train_step and the Trainer."""

import jax
import numpy as np
import pytest

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.config import TrainConfig
from tpu_dist.nn.vit import ViTDef
from tpu_dist.train.optim import SGD
from tpu_dist.train.state import TrainState
from tpu_dist.train.step import make_train_step
from tpu_dist.train.trainer import Trainer


def _model():
    return ViTDef(image_size=32, patch_size=4, dim=32, depth=2, heads=4, num_classes=5)


def test_dp_tp_training_matches_single_device():
    from jax.sharding import NamedSharding

    model = _model()
    opt = SGD()
    mesh2d = mesh_lib.device_mesh([2, 4], ["data", "model"])
    mesh1 = mesh_lib.device_mesh([1], ["data"], jax.devices()[:1])
    specs = model.tp_param_specs("model")

    params, s = model.init(jax.random.PRNGKey(0))
    st = TrainState.create(params, s, opt)
    place = lambda tree: jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh2d, spec)), tree, specs
    )
    s_tp = TrainState(
        params=place(st.params),
        bn_state=jax.device_put(st.bn_state, mesh_lib.replicated(mesh2d)),
        opt_state=place(st.opt_state),
        step=jax.device_put(st.step, mesh_lib.replicated(mesh2d)),
    )
    s_1 = jax.device_put(st, mesh_lib.replicated(mesh1))

    step_tp = make_train_step(
        model.apply, opt, mesh2d, sync_bn=False, donate=False,
        tp_axis="model", param_specs=specs,
    )
    step_1 = make_train_step(model.apply, opt, mesh1, sync_bn=False, donate=False)

    rng = np.random.default_rng(0)
    for _ in range(3):
        x = rng.normal(size=(8, 32, 32, 3)).astype(np.float32)
        y = rng.integers(0, 5, 8).astype(np.int32)
        s_tp, m_tp = step_tp(
            s_tp, mesh_lib.shard_batch(mesh2d, x), mesh_lib.shard_batch(mesh2d, y), 0.05
        )
        s_1, m_1 = step_1(
            s_1, mesh_lib.shard_batch(mesh1, x), mesh_lib.shard_batch(mesh1, y), 0.05
        )

    np.testing.assert_allclose(float(m_tp["loss"]), float(m_1["loss"]), rtol=1e-4)
    # compare full (gathered) TP params with the single-device run
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(s_tp.params)),
        jax.tree_util.tree_leaves(jax.device_get(s_1.params)),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5)


def test_tp_forward_parity():
    """TP-sharded forward ≡ dense forward (eval-path insurance)."""
    import jax.numpy as jnp
    from tpu_dist.comm.compat import shard_map
    from jax.sharding import PartitionSpec as P

    model = _model()
    params, s = model.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3), jnp.float32)
    ref, _ = model.apply(params, s, x)

    mesh = mesh_lib.device_mesh([4], ["model"], jax.devices()[:4])
    specs = model.tp_param_specs("model")
    out = shard_map(
        lambda p, xl: model.apply(p, {}, xl, tp_axis="model")[0],
        mesh=mesh, in_specs=(specs, P()), out_specs=P(), check_vma=False,
    )(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


@pytest.mark.slow  # tier-1 budget (ISSUE 17): gates in analysis.yml
def test_trainer_tp_e2e_with_eval_and_resume(tmp_path):
    cfg = TrainConfig(
        dataset="synthetic", model="vit_tiny", num_classes=10, batch_size=16,
        epochs=1, steps_per_epoch=2, log_every=1, lr=0.05, eval_every=1,
        tp=4, sync_bn=False, synthetic_n=160, ckpt_dir=str(tmp_path), save_every=1,
    )
    t = Trainer(cfg)
    assert t.n_data == 2 and t.n_devices == 8
    out = t.fit()
    assert np.isfinite(out["loss"]) and "val_top1" in out

    t2 = Trainer(cfg.replace(resume=True, epochs=2))
    assert t2.start_epoch == 1
    # TP params restored SHARDED (each qkv leaf split over the model axis)
    qkv = t2.state.params["blocks"][0]["qkv"]["w"]
    assert len(qkv.sharding.device_set) == 8
    out2 = t2.fit()
    assert np.isfinite(out2["loss"])


def test_trainer_tp_rejects_bad_configs():
    import pytest

    with pytest.raises(ValueError, match="tensor parallelism"):
        Trainer(TrainConfig(dataset="synthetic", model="resnet18", tp=4, synthetic_n=512))
    with pytest.raises(ValueError, match="sp\\+tp"):  # sp+ep is NOT a valid combo
        Trainer(TrainConfig(dataset="synthetic", model="vit_tiny", sp=2, ep=2, synthetic_n=512))
    with pytest.raises(ValueError, match="incompatible"):
        # grad_clip_norm now composes with tp; ZeRO-1 remains structural
        Trainer(TrainConfig(
            dataset="synthetic", model="vit_tiny", tp=4, shard_weight_update=True,
            synthetic_n=512, batch_size=16,
        ))
