"""Multi-tenant pod: SLO-aware train+serve co-scheduling
(docs/resilience.md "Multi-tenant pod").

The asymmetric policy units (a sustained serve SLO breach preempts
training chips within the bounded tick window; a sustained-healthy
serve run releases its surplus back off-peak; floors, liveness and the
non-SLO alert veto hold on both paths; hysteresis streaks kill thrash),
the preemption-latency contract on a manual clock, the serve-gauge
scrape through ``read_signals`` (including the garbage-heartbeat
fail-closed), vacate-window load shedding vs a queue explosion, the
chip-second conservation audit, the TD122 traced-noop gate (with its
vacuity guard), and the ``tenancy_drill`` policy phase.

The jax-subprocess phases (the real-trainer diurnal cycle and the
SIGKILL'd supervised replica) are slow-marked; ``make tenancy-drill``
runs all three.
"""

import inspect
import json
import os

import numpy as np
import pytest

from tpu_dist.fleet.scheduler import (
    FLEET_SCHEMA_VERSION,
    FleetPolicy,
    FleetScheduler,
    RunSignals,
    RunSpec,
    audit_chip_seconds,
    read_signals,
)
from tpu_dist.obs import counters as counters_lib
from tpu_dist.resilience import faults, preemption


@pytest.fixture(autouse=True)
def _clean_state():
    faults.clear()
    preemption.clear()
    counters_lib.reset()
    yield
    faults.clear()
    preemption.clear()
    counters_lib.reset()


def _train_sig(run, stall=0.02, alive=True, alerts=()):
    return RunSignals(
        run=run, data_stall_frac=stall, goodput_frac=0.9, mfu=0.4,
        active_alerts=tuple(alerts), alive=alive,
    )


def _serve_sig(run, queue, avail=1.0, alerts=(), alive=True, p99=5.0):
    return RunSignals(
        run=run, active_alerts=tuple(alerts), alive=alive,
        queue_depth=float(queue), availability=avail, latency_p99_ms=p99,
    )


def _pod(**kw):
    args = dict(
        runs=[
            RunSpec("tr", 8, min_procs=2),
            RunSpec("sv", 4, min_procs=1, kind="serve"),
        ],
        allocations={"tr": 8, "sv": 2},
        total_chips=11,  # 1 chip free: not enough for sv's 2->4 alone
    )
    args.update(kw)
    return FleetScheduler(**args)


# -- asymmetric policy: the breach path --------------------------------------


def test_run_kind_validated():
    assert RunSpec("s", 4, kind="serve").kind == "serve"
    with pytest.raises(ValueError, match="kind"):
        RunSpec("s", 4, kind="batch")


def test_sustained_breach_preempts_training_within_bound():
    """The preemption-latency contract: the FIRST breach reading starts
    the streak; the donate fires the tick the streak crosses
    ``serve_breach_ticks`` (spike_tick + serve_breach_ticks - 1); the
    chips land one tick later. The trainer is preempted even though it
    is compute-bound — the SLO outranks goodput."""
    s = _pod()
    tr = _train_sig("tr")
    # tick 1: off-peak — establishes the queue baseline
    assert s.step(1, {"tr": tr, "sv": _serve_sig("sv", 0)}) == []
    # tick 2 (the spike): queue jumps 0->6 (growth >= 1.0 is a breach
    # reading) — the streak arms but one reading never moves chips
    spike_tick = 2
    assert s.step(spike_tick, {
        "tr": tr, "sv": _serve_sig("sv", 6, avail=0.8),
    }) == []
    # tick 3: still exploding + an slo_* alert — streak hits the bar
    [d] = s.step(spike_tick + 1, {
        "tr": tr,
        "sv": _serve_sig("sv", 9, avail=0.8, alerts=("slo_availability_low",)),
    })
    assert d["action"] == "donate" and d["preempt"] is True
    assert d["donor"] == "tr" and d["for_run"] == "sv"
    assert d["alloc_after"] == {"tr": 4, "sv": 2}  # sv NOT grown yet
    assert spike_tick + 1 == spike_tick + s.policy.serve_breach_ticks - 1
    assert "SLO breach" in d["reason"]
    # tick 4: the freed chips matured — the grant lands, bound proven
    [g] = s.step(spike_tick + 2, {
        "tr": tr,
        "sv": _serve_sig("sv", 12, avail=0.8, alerts=("slo_p99_high",)),
    })
    assert g["action"] == "grant" and g["preempt"] is True
    assert g["recipient"] == "sv"
    assert s.alloc == {"sv": 4, "tr": 4}
    assert s.preemptions == 2  # the donate and the grant legs
    assert "tpu_dist_fleet_preemptions 2" in s.exposition()


def test_preemption_ignores_donor_cooldown_but_honors_floor():
    # cooldown: tr just moved — the goodput market would sit out, the
    # SLO path must not (a cooldown inside the latency bound is a lie)
    breach = lambda: _serve_sig("sv", 9, avail=0.8, alerts=("slo_p99_high",))
    s = _pod()
    s._last_move_tick["tr"] = 2  # cooldown covers ticks 3 and 4
    s.step(2, {"tr": _train_sig("tr"), "sv": breach()})
    [d] = s.step(3, {"tr": _train_sig("tr"), "sv": breach()})
    assert d["action"] == "donate" and d["preempt"] is True
    # floor: a trainer AT min_procs is never preempted below it
    s2 = _pod(
        runs=[
            RunSpec("tr", 8, min_procs=8),
            RunSpec("sv", 4, min_procs=1, kind="serve"),
        ],
    )
    s2.step(1, {"tr": _train_sig("tr"), "sv": breach()})
    assert s2.step(2, {"tr": _train_sig("tr"), "sv": breach()}) == []


def test_breach_vetoes_dead_heartbeat_and_non_slo_alert():
    # a dead serve heartbeat never attracts chips (they can't help)
    s = _pod()
    dead = lambda: _serve_sig(
        "sv", 9, avail=0.8, alerts=("slo_p99_high",), alive=False,
    )
    s.step(1, {"tr": _train_sig("tr"), "sv": dead()})
    assert s.step(2, {"tr": _train_sig("tr"), "sv": dead()}) == []
    # a non-SLO alert (sick replica) vetoes the grow even mid-breach
    s2 = _pod()
    sick = lambda: _serve_sig(
        "sv", 9, avail=0.8, alerts=("slo_p99_high", "serve_retrace"),
    )
    s2.step(1, {"tr": _train_sig("tr"), "sv": sick()})
    assert s2.step(2, {"tr": _train_sig("tr"), "sv": sick()}) == []
    # a dead TRAINER can't be the preemption donor either
    s3 = _pod()
    breach = lambda: _serve_sig("sv", 9, avail=0.8, alerts=("slo_p99_high",))
    s3.step(1, {"tr": _train_sig("tr", alive=False), "sv": breach()})
    assert s3.step(2, {
        "tr": _train_sig("tr", alive=False), "sv": breach(),
    }) == []


def test_hysteresis_streaks_prevent_thrash():
    """Alternating breach/clean readings never cross either streak bar:
    no donate, no grant, no release — the pod does not thrash."""
    s = _pod(allocations={"tr": 4, "sv": 4})
    tr = _train_sig("tr")
    for tick in range(1, 9):
        if tick % 2:
            sv = _serve_sig("sv", 6 + tick, avail=0.9)  # growing queue
        else:
            sv = _serve_sig("sv", 0, avail=1.0)  # clean and idle
        assert s.step(tick, {"tr": tr, "sv": sv}) == []
    assert s.preemptions == 0


# -- asymmetric policy: the off-peak release path ----------------------------


def test_offpeak_release_returns_chips_to_compute_bound_trainer():
    s = _pod(allocations={"tr": 4, "sv": 4}, total_chips=11)
    tr = _train_sig("tr", stall=0.02)  # compute-bound: wants chips
    idle = lambda: _serve_sig("sv", 0, avail=1.0)
    # healthy streak must reach serve_release_ticks (3) first
    assert s.step(1, {"tr": tr, "sv": idle()}) == []
    assert s.step(2, {"tr": tr, "sv": idle()}) == []
    [d] = s.step(3, {"tr": tr, "sv": idle()})
    assert d["action"] == "donate" and not d.get("preempt")
    assert d["donor"] == "sv" and d["for_run"] == "tr"
    assert "healthy" in d["reason"]
    assert s.alloc == {"sv": 2, "tr": 4}
    [g] = s.step(4, {"tr": tr, "sv": idle()})
    assert g["action"] == "grant" and g["recipient"] == "tr"
    assert s.alloc == {"sv": 2, "tr": 8}
    assert s.preemptions == 0  # off-peak reclaim is NOT a preemption


def test_release_needs_idle_queue_availability_and_floor():
    tr = _train_sig("tr", stall=0.02)
    # busy-but-within-SLO (queue above idle bar): holds its chips
    s = _pod(allocations={"tr": 4, "sv": 4})
    for tick in range(1, 6):
        assert s.step(tick, {
            "tr": tr, "sv": _serve_sig("sv", 3, avail=1.0),
        }) == []
    # availability under the bar: holds its chips
    s2 = _pod(allocations={"tr": 4, "sv": 4})
    for tick in range(1, 6):
        assert s2.step(tick, {
            "tr": tr, "sv": _serve_sig("sv", 0, avail=0.95),
        }) == []
    # at its floor: nothing to release no matter how idle
    s3 = _pod(
        runs=[
            RunSpec("tr", 8, min_procs=2),
            RunSpec("sv", 4, min_procs=4, kind="serve"),
        ],
        allocations={"tr": 4, "sv": 4},
    )
    for tick in range(1, 6):
        assert s3.step(tick, {
            "tr": tr, "sv": _serve_sig("sv", 0, avail=1.0),
        }) == []


# -- the serve-gauge scrape (read_signals) -----------------------------------


def _write(path, text):
    with open(path, "w") as f:
        f.write(text)


def _serve_prom(tmp_path, **gauges):
    from tpu_dist.obs import export as export_lib

    prom = str(tmp_path / "metrics.prom")
    alerts = gauges.pop("alerts", {})
    _write(prom, export_lib.render(gauges, {"alert_active": alerts}))
    return prom


def test_read_signals_scrapes_serve_gauges(tmp_path):
    prom = _serve_prom(
        tmp_path,
        **{
            "serve.queue_depth": 7.0,
            "serve.availability": 0.875,
            "serve.latency_p99_ms": 612.5,
            "alerts": {"slo_p99_high": 1.0, "grad_norm_high": 0.0},
        },
    )
    sig = read_signals("sv", prom)
    assert sig.queue_depth == 7.0
    assert sig.availability == 0.875
    assert sig.latency_p99_ms == 612.5
    assert sig.active_alerts == ("slo_p99_high",)  # only the FIRING one


def test_read_signals_garbage_heartbeat_fails_closed(tmp_path):
    """A heartbeat that is unreadable, missing, or carries no usable
    timestamp is indistinguishable from a dead run — it must scrape as
    alive=False (fail closed), never as unknown: ``alive=None`` would
    keep the run grant-eligible on evidence that says nothing."""
    import time as time_lib

    prom = _serve_prom(tmp_path, **{"serve.queue_depth": 1.0})
    hb = str(tmp_path / "hb.json")
    _write(hb, "{not json")
    assert read_signals("sv", prom, heartbeat_file=hb).alive is False
    _write(hb, json.dumps({"ts": "soon", "phase": "serve"}))  # garbage ts
    assert read_signals("sv", prom, heartbeat_file=hb).alive is False
    assert read_signals(
        "sv", prom, heartbeat_file=str(tmp_path / "absent.json"),
    ).alive is False
    # a fresh, well-formed beat reads alive
    _write(hb, json.dumps({"ts": time_lib.time(), "phase": "serve"}))
    sig = read_signals("sv", prom, heartbeat_file=hb)
    assert sig.alive is True and sig.heartbeat_age_s is not None
    # no heartbeat contracted at all: liveness stays unknown
    assert read_signals("sv", prom).alive is None


def test_export_key_gauges_include_serving_rows():
    from tpu_dist.obs.export import KEY_GAUGES

    names = [raw for raw, _, _ in KEY_GAUGES]
    for want in ("serve.queue_depth", "serve.availability",
                 "serve.latency_p99_ms"):
        assert want in names


# -- vacate-window shedding vs a queue explosion -----------------------------


class _NoopModel:
    classes = 10

    def init(self, key):
        return {}, {}

    def apply(self, params, state, x, **kw):
        return x, state


def test_shed_refuses_at_admission_and_stays_off_the_histograms():
    from tpu_dist.serve.engine import ServingEngine

    eng = ServingEngine(_NoopModel(), {}, {}, max_batch=4, max_queue=2)
    one = np.zeros((4,), np.float32)
    a, b = eng.submit(one), eng.submit(one)
    assert a.ok is False and b.ok is False  # queued, not yet completed
    # the cap: request 3 bounces instead of exploding the queue
    refused = eng.submit(one)
    assert refused.ok is False and refused.result is None
    assert eng.queue_depth() == 2
    # vacate-window shedding refuses EVERYTHING at admission
    eng.set_shedding(True)
    assert eng.shedding is True
    shed = eng.submit(one)
    assert shed.ok is False and eng.queue_depth() == 2
    sc = eng.stats.scalars()
    assert sc["serve.requests"] == 2  # admitted work only
    assert sc["serve.shed"] == 2
    assert counters_lib.get("serve.shed") == 2
    # shed requests never reach the latency histograms
    assert all(
        fam["count"] == 0 for fam in eng.stats.histogram_families().values()
    )
    eng.stats.check_invariants()


def test_pump_beats_heartbeat_even_idle(tmp_path):
    from tpu_dist.obs import heartbeat as hb_lib
    from tpu_dist.serve.engine import ServingEngine

    hb = str(tmp_path / "hb.json")
    eng = ServingEngine(
        _NoopModel(), {}, {}, max_batch=2, heartbeat_file=hb,
    )
    assert eng.pump() == []  # empty queue: no batch...
    rec = hb_lib.read(hb)
    assert rec is not None and rec["phase"] == "serve"  # ...but a beat


# -- chip-second conservation ------------------------------------------------


def test_chip_second_conservation_exact_and_tamper_detected(tmp_path):
    s = _pod(fleet_dir=str(tmp_path))
    tr = _train_sig("tr")
    ticks = [
        _serve_sig("sv", 0), _serve_sig("sv", 6), _serve_sig("sv", 9),
        _serve_sig("sv", 12, alerts=("slo_p99_high",)),
        _serve_sig("sv", 2), _serve_sig("sv", 0),
    ]
    for tick, sv in enumerate(ticks, start=1):
        s.step(tick, {"tr": tr, "sv": sv}, ts=float(tick))
    recs = [json.loads(l) for l in open(s.history_path())]
    tenancy = [r for r in recs if r.get("kind") == "tenancy"]
    assert len(tenancy) == len(ticks)  # exactly one ledger row per tick
    assert all(r["schema_version"] == FLEET_SCHEMA_VERSION for r in tenancy)
    audit = audit_chip_seconds(tenancy, tick_s=2.0)
    assert audit["conserved"] is True and audit["violations"] == []
    assert audit["accounted_chip_s"] == audit["pod_chip_s"]
    assert audit["pod_chip_s"] == 11 * len(ticks) * 2.0
    assert audit["n_ticks"] == len(ticks)
    # the identity is an equality, not a bound: losing OR inventing a
    # chip for one tick is a violation that names the tick
    for delta in (-1, 1):
        bad = [dict(r) for r in tenancy]
        bad[3] = dict(bad[3], free=bad[3]["free"] + delta)
        tampered = audit_chip_seconds(bad)
        assert tampered["conserved"] is False
        assert [v["tick"] for v in tampered["violations"]] == [bad[3]["tick"]]


def test_audit_rejects_records_from_a_different_pod():
    """Mixing snapshots from two schedulers (different pod sizes) can
    never balance — the identity is per-pod, not best-effort."""
    s = _pod()
    tr = _train_sig("tr")
    for tick in range(1, 4):
        s.step(tick, {"tr": tr, "sv": _serve_sig("sv", 0)})
    rows = [s.tenancy_record(t) for t in (1, 2, 3)]
    rows[1] = dict(rows[1], total_chips=12)  # a 12-chip pod's row
    audit = audit_chip_seconds(rows)
    assert audit["conserved"] is False
    # non-tenancy kinds are ignored, not miscounted
    ok = audit_chip_seconds(
        [{"kind": "fleet", "action": "grant"}] + [
            s.tenancy_record(t) for t in (1, 2, 3)
        ]
    )
    assert ok["conserved"] is True and ok["n_ticks"] == 3


# -- TD122: tenancy arbitration is control-plane only ------------------------


def test_td122_registered_and_audit_all_wired():
    from tpu_dist.analysis import jaxpr_audit
    from tpu_dist.analysis.rules import RULES

    assert "TD122" in RULES
    assert RULES["TD122"].name == "tenancy-arbitration-control-plane-only"
    assert "tenancy_arbitration_noop_violations" in inspect.getsource(
        jaxpr_audit.audit_all
    )


def test_td122_gate_tenancy_arbitration_is_noop():
    from tpu_dist.analysis.jaxpr_audit import (
        tenancy_arbitration_noop_violations,
    )

    assert tenancy_arbitration_noop_violations() == []


def test_td122_probe_is_vacuity_guarded(monkeypatch):
    """A kit that cannot fire proves nothing: gut the scheduler so the
    preemption never happens and the probe must REPORT, not pass — the
    dead-detector contract behind ``analysis.__main__``'s exit 2."""
    from tpu_dist.analysis.jaxpr_audit import (
        tenancy_arbitration_noop_violations,
    )
    from tpu_dist.fleet import scheduler as fleet_lib

    monkeypatch.setattr(
        fleet_lib.FleetScheduler, "decide", lambda self, tick, sig: []
    )
    vs = tenancy_arbitration_noop_violations()
    assert len(vs) == 1 and vs[0].rule == "TD122"
    assert "vacuous" in vs[0].message


# -- the drill ---------------------------------------------------------------


def test_tenancy_drill_policy_phase(tmp_path):
    from tpu_dist.fleet.tenancy_drill import main as drill_main

    assert drill_main(
        ["--workdir", str(tmp_path), "--phase", "policy"]
    ) == 0


@pytest.mark.slow
def test_tenancy_drill_replica_phase(tmp_path):
    """SIGKILL a supervised serving replica: crash detected, postmortem
    bundled, relaunch restores bit-exact weights and resumes serving
    with zero post-warmup retraces (jax subprocesses)."""
    from tpu_dist.fleet.tenancy_drill import main as drill_main

    assert drill_main(
        ["--workdir", str(tmp_path), "--phase", "replica"]
    ) == 0


@pytest.mark.slow
def test_tenancy_drill_cycle_phase(tmp_path):
    """The full diurnal day against a REAL trainer: spike -> bounded
    preemption -> lossless shrink -> recovery -> off-peak reclaim ->
    golden-rtol losses and exact chip-second conservation."""
    from tpu_dist.fleet.tenancy_drill import main as drill_main

    assert drill_main(
        ["--workdir", str(tmp_path), "--phase", "cycle"]
    ) == 0
