"""Ring attention ≡ full attention over a sequence-parallel mesh axis."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from tpu_dist.comm.compat import shard_map
from jax.sharding import PartitionSpec as P

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.nn import attention as A


def _qkv(b=2, s=32, h=4, d=8, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, s, h, d), jnp.float32) for k in ks)


def test_full_attention_matches_manual_softmax():
    q, k, v = _qkv(s=8)
    out = A.full_attention(q, k, v)
    scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(8.0)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bkhd->bqhd", p, v)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_ring_equals_full_8way():
    mesh = mesh_lib.device_mesh([8], ["seq"])
    q, k, v = _qkv(s=64)

    ring = jax.jit(
        shard_map(
            lambda q, k, v: A.ring_attention(q, k, v, "seq"),
            mesh=mesh,
            in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
            out_specs=P(None, "seq"),
            check_vma=False,
        )
    )
    out = np.asarray(ring(q, k, v))
    ref = np.asarray(A.full_attention(q, k, v))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_ring_causal_equals_full_causal():
    mesh = mesh_lib.device_mesh([4], ["seq"], jax.devices()[:4])
    q, k, v = _qkv(s=32, seed=3)

    ring = jax.jit(
        shard_map(
            lambda q, k, v: A.ring_attention(q, k, v, "seq", causal=True),
            mesh=mesh,
            in_specs=(P(None, "seq"),) * 3,
            out_specs=P(None, "seq"),
            check_vma=False,
        )
    )
    out = np.asarray(ring(q, k, v))
    ref = np.asarray(A.full_attention(q, k, v, causal=True))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_ring_attention_grads_flow():
    mesh = mesh_lib.device_mesh([4], ["seq"], jax.devices()[:4])
    q, k, v = _qkv(s=16, seed=1)

    def loss_sharded(q, k, v):
        def f(q, k, v):
            o = A.ring_attention(q, k, v, "seq")
            return jax.lax.psum(jnp.sum(o ** 2), "seq")

        return shard_map(
            f, mesh=mesh, in_specs=(P(None, "seq"),) * 3, out_specs=P(),
            check_vma=False,
        )(q, k, v)

    def loss_full(q, k, v):
        return jnp.sum(A.full_attention(q, k, v) ** 2)

    g_ring = jax.grad(loss_sharded)(q, k, v)
    g_full = jax.grad(loss_full)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_full), rtol=1e-3, atol=1e-4)


def test_ulysses_equals_full_4way():
    mesh = mesh_lib.device_mesh([4], ["seq"], jax.devices()[:4])
    q, k, v = _qkv(s=32, h=4, seed=5)

    uly = jax.jit(
        shard_map(
            lambda q, k, v: A.ulysses_attention(q, k, v, "seq"),
            mesh=mesh,
            in_specs=(P(None, "seq"),) * 3,
            out_specs=P(None, "seq"),
            check_vma=False,
        )
    )
    out = np.asarray(uly(q, k, v))
    ref = np.asarray(A.full_attention(q, k, v))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_ulysses_causal_and_grads_match_full():
    mesh = mesh_lib.device_mesh([4], ["seq"], jax.devices()[:4])
    q, k, v = _qkv(s=16, h=4, seed=6)

    def loss_sharded(q, k, v):
        def f(q, k, v):
            o = A.ulysses_attention(q, k, v, "seq", causal=True)
            return jax.lax.psum(jnp.sum(o ** 2), "seq")

        return shard_map(
            f, mesh=mesh, in_specs=(P(None, "seq"),) * 3, out_specs=P(),
            check_vma=False,
        )(q, k, v)

    def loss_full(q, k, v):
        return jnp.sum(A.full_attention(q, k, v, causal=True) ** 2)

    np.testing.assert_allclose(float(loss_sharded(q, k, v)), float(loss_full(q, k, v)), rtol=1e-5)
    g_u = jax.grad(loss_sharded)(q, k, v)
    g_f = jax.grad(loss_full)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_u), np.asarray(g_f), rtol=1e-3, atol=1e-4)


def test_ulysses_with_flash_impl():
    """flash × SP: the ulysses local call runs the Pallas kernel."""
    mesh = mesh_lib.device_mesh([4], ["seq"], jax.devices()[:4])
    q, k, v = _qkv(s=32, h=4, seed=7)

    uly_flash = jax.jit(
        shard_map(
            lambda q, k, v: A.ulysses_attention(q, k, v, "seq", impl="flash"),
            mesh=mesh,
            in_specs=(P(None, "seq"),) * 3,
            out_specs=P(None, "seq"),
            check_vma=False,
        )
    )
    out = np.asarray(uly_flash(q, k, v))
    ref = np.asarray(A.full_attention(q, k, v))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=2e-5)


def test_ulysses_rejects_indivisible_heads():
    import pytest

    mesh = mesh_lib.device_mesh([4], ["seq"], jax.devices()[:4])
    q, k, v = _qkv(s=32, h=3, seed=8)
    with pytest.raises(ValueError, match="heads"):
        jax.jit(
            shard_map(
                lambda q, k, v: A.ulysses_attention(q, k, v, "seq"),
                mesh=mesh,
                in_specs=(P(None, "seq"),) * 3,
                out_specs=P(None, "seq"),
                check_vma=False,
            )
        )(q, k, v)


def test_explicit_impl_overrides_process_default(monkeypatch):
    """ADVICE r2: the step closure pins attn_impl at build time; an explicit
    impl= must win over the process-global default at trace time."""
    from tpu_dist.nn.vit import vit_tiny

    model = vit_tiny(num_classes=4, image_size=16)
    params, state = model.init(jax.random.PRNGKey(0))
    x = jnp.zeros((2, 16, 16, 3), jnp.float32)

    calls = []
    import tpu_dist.ops.flash_attention as fa

    real = fa.flash_attention
    monkeypatch.setattr(
        fa, "flash_attention",
        lambda *a, **k: (calls.append(1), real(*a, **k))[1],
    )

    # global default says flash; explicit xla must NOT hit the kernel
    A.set_default_attention_impl("flash")
    try:
        model.apply(params, state, x, attn_impl="xla")
        assert not calls
        # and explicit flash hits it even when the global says xla
        A.set_default_attention_impl("xla")
        model.apply(params, state, x, attn_impl="flash")
        assert calls
    finally:
        A.set_default_attention_impl("xla")


def test_trainer_snapshots_attn_impl():
    """Two Trainers with different flash settings: each step closure keeps
    its own impl (the global default no longer leaks across builds)."""
    from tests.helpers import tiny_resnet
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer, register_model

    register_model("tiny_resnet", lambda num_classes=10: tiny_resnet(num_classes))
    common = dict(
        dataset="synthetic", model="vit_tiny", num_classes=10, batch_size=64,
        epochs=1, steps_per_epoch=2, synthetic_n=128, sync_bn=False,
    )
    t_xla = Trainer(TrainConfig(**common))
    t_flash = Trainer(TrainConfig(**common, flash_attention=True))
    assert t_xla._attn_model_kwargs() == {"attn_impl": "xla"}
    assert t_flash._attn_model_kwargs() == {"attn_impl": "flash"}
    # conv models don't take the kwarg at all
    t_conv = Trainer(TrainConfig(dataset="synthetic", model="tiny_resnet",
                                 num_classes=10, batch_size=64, epochs=1,
                                 steps_per_epoch=2, synthetic_n=128))
    assert t_conv._attn_model_kwargs() == {}


def _ring_flash_fn(mesh, causal, block=16):
    from tpu_dist.ops.flash_attention import ring_flash_attention

    return jax.jit(
        shard_map(
            lambda q, k, v: ring_flash_attention(
                q, k, v, "seq", causal=causal, block_q=block, block_k=block
            ),
            mesh=mesh,
            in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
            out_specs=P(None, "seq"),
            check_vma=False,
        )
    )


def _run_or_skip_submesh(fn, *args):
    """Some jaxlibs cannot lower pallas-interpret inside shard_map on a
    SUB-mesh (4 of 8 devices): XLA emits a PartitionId instruction it then
    refuses under SPMD. Full-mesh ring-flash tests cover the numerics; the
    sub-mesh variants skip on that exact signature instead of failing."""
    try:
        return fn(*args)
    except Exception as e:  # jaxlib.xla_extension.XlaRuntimeError
        if "PartitionId instruction is not supported" in str(e):
            pytest.skip("jaxlib cannot lower pallas-interpret on a sub-mesh")
        raise


def test_ring_flash_equals_full_4way():
    mesh = mesh_lib.device_mesh([4], ["seq"], jax.devices()[:4])
    q, k, v = _qkv(s=64, seed=5)
    out = np.asarray(_run_or_skip_submesh(_ring_flash_fn(mesh, causal=False), q, k, v))
    ref = np.asarray(A.full_attention(q, k, v))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_ring_flash_bf16_causal_8way_grads():
    """8-way ring, bf16, causal: seven of eight rotations per device hit a
    non-diagonal lax.switch branch (the masked branch dominates), the
    configuration the round-5 TPU capture session runs at S=16k. Forward
    and grads must match the single-device flash kernel within bf16
    rounding."""
    from tpu_dist.ops.flash_attention import flash_attention

    mesh = mesh_lib.device_mesh([8], ["seq"], jax.devices()[:8])
    q, k, v = (t.astype(jnp.bfloat16) for t in _qkv(s=128, seed=12))
    fn = _ring_flash_fn(mesh, causal=True)
    out = np.asarray(fn(q, k, v), dtype=np.float32)
    ref = np.asarray(
        flash_attention(q, k, v, causal=True, block_q=16, block_k=16),
        dtype=np.float32,
    )
    np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-2)

    ct = jax.random.normal(jax.random.PRNGKey(13), q.shape, jnp.bfloat16)

    def g(f):
        return jax.grad(
            lambda q, k, v: jnp.vdot(
                f(q, k, v).astype(jnp.float32), ct.astype(jnp.float32)
            ),
            argnums=(0, 1, 2),
        )(q, k, v)

    g_ring = g(fn)
    g_ref = g(lambda q, k, v: flash_attention(
        q, k, v, causal=True, block_q=16, block_k=16))
    for got, want, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got, dtype=np.float32), np.asarray(want, dtype=np.float32),
            rtol=4e-2, atol=4e-2, err_msg=f"d{name} bf16 causal 8-way",
        )


def test_ring_flash_causal_equals_full_causal():
    mesh = mesh_lib.device_mesh([4], ["seq"], jax.devices()[:4])
    q, k, v = _qkv(s=64, seed=6)
    out = np.asarray(_ring_flash_fn(mesh, causal=True)(q, k, v))
    ref = np.asarray(A.full_attention(q, k, v, causal=True))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_ring_flash_grads_match_full():
    """The custom ring backward (rotating dK/dV accumulators + global
    (m,l) statistics through the Pallas kernels) must match autodiff
    through the gathered reference, causal and not."""
    mesh = mesh_lib.device_mesh([4], ["seq"], jax.devices()[:4])
    q, k, v = _qkv(s=64, seed=7)
    ct = jax.random.normal(jax.random.PRNGKey(9), q.shape, jnp.float32)

    for causal in (False, True):
        fn = _ring_flash_fn(mesh, causal=causal)

        def ring_loss(q, k, v):
            return jnp.vdot(fn(q, k, v), ct)

        def ref_loss(q, k, v):
            return jnp.vdot(A.full_attention(q, k, v, causal=causal), ct)

        g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for got, want, name in zip(g_ring, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5,
                err_msg=f"d{name} (causal={causal})",
            )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_bf16_matches_single_device_flash(causal):
    """bf16 inputs (the TPU training dtype): per-rotation partials merge
    in f32 — the ring result must stay within ONE bf16 rounding of the
    single-device flash kernel, not accumulate a fresh quantization per
    rotation.  causal=True is the advertised long-context training combo;
    its backward hits the masked lax.switch branch, whose zero-grads must
    carry the same f32 dtype as the kernel branches (advisor r4 finding)."""
    from tpu_dist.ops.flash_attention import flash_attention

    mesh = mesh_lib.device_mesh([4], ["seq"], jax.devices()[:4])
    q, k, v = (t.astype(jnp.bfloat16) for t in _qkv(s=64, seed=8))
    fn = _ring_flash_fn(mesh, causal=causal)
    out = np.asarray(_run_or_skip_submesh(fn, q, k, v), dtype=np.float32)
    ref = np.asarray(
        flash_attention(q, k, v, causal=causal, block_q=16, block_k=16),
        dtype=np.float32,
    )
    # bf16 has ~2^-8 relative precision; one rounding of each is ~1.6e-2
    np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-2)

    # backward too: per-rotation grad partials accumulate in f32, so ring
    # grads also stay within one bf16 rounding of the single-device kernel
    ct = jax.random.normal(jax.random.PRNGKey(11), q.shape, jnp.bfloat16)

    def g(f):
        return jax.grad(
            lambda q, k, v: jnp.vdot(
                f(q, k, v).astype(jnp.float32), ct.astype(jnp.float32)
            ),
            argnums=(0, 1, 2),
        )(q, k, v)

    g_ring = g(fn)
    g_ref = g(lambda q, k, v: flash_attention(
        q, k, v, causal=causal, block_q=16, block_k=16))
    for got, want, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got, dtype=np.float32), np.asarray(want, dtype=np.float32),
            rtol=4e-2, atol=4e-2, err_msg=f"d{name} bf16 causal={causal}",
        )
