"""Model-zoo parity tests against the reference ``utils/model.py``.

Golden numbers computed once from the reference implementation (torch):
parameter counts for resnet18/34/50 with num_classes=100, and BN buffer
counts minus the ``num_batches_tracked`` scalars torch adds per BN layer.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist.nn import resnet18, resnet34, resnet50
from tests.helpers import tiny_resnet

# (factory, n_params, n_bn_stats): from reference utils/model.py via torch —
# params exactly equal; torch "buffers" additionally count one
# num_batches_tracked scalar per BN layer (20/36/53 layers respectively).
GOLDEN = [
    (resnet18, 11_220_132, 9_620 - 20),
    (resnet34, 21_328_292, 17_060 - 36),
    (resnet50, 23_705_252, 53_173 - 53),
]


@pytest.mark.parametrize("factory,n_params,n_stats", GOLDEN)
def test_param_count_parity(factory, n_params, n_stats):
    params, state = factory().init(jax.random.PRNGKey(0))
    assert sum(x.size for x in jax.tree_util.tree_leaves(params)) == n_params
    assert sum(x.size for x in jax.tree_util.tree_leaves(state)) == n_stats


def test_resnet50_imagenet_canonical_params():
    from tpu_dist.nn.resnet import resnet50_imagenet

    params, _ = resnet50_imagenet(num_classes=1000).init(jax.random.PRNGKey(0))
    n = sum(x.size for x in jax.tree_util.tree_leaves(params))
    assert n == 25_557_032  # torchvision resnet50 exactly


def test_forward_shapes_and_finiteness():
    m = tiny_resnet(num_classes=7)
    params, state = m.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
    logits, new_state = m.apply(params, state, x, train=True)
    assert logits.shape == (4, 7)
    assert bool(jnp.all(jnp.isfinite(logits)))
    # BN running stats must have moved off their init under train=True
    assert not jnp.allclose(new_state["stem_bn"]["mean"], 0.0)
    # eval mode must not mutate state
    logits2, state2 = m.apply(params, new_state, x, train=False)
    assert jax.tree_util.tree_all(
        jax.tree_util.tree_map(lambda a, b: bool(jnp.all(a == b)), state2, new_state)
    )


def test_eval_uses_running_stats():
    m = tiny_resnet()
    params, state = m.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
    e1, _ = m.apply(params, state, x, train=False)
    # different batch statistics shouldn't matter in eval mode
    e2, _ = m.apply(params, state, x * 3.0 + 1.0, train=False)
    assert e1.shape == e2.shape
    t1, _ = m.apply(params, state, x, train=True)
    assert not jnp.allclose(e1, t1)  # train normalizes by batch stats


def test_s2d_stem_matches_plain_stem():
    """The space-to-depth stem is the SAME function as the 7x7/2 conv
    (MXU-utilization rewrite, nn/resnet.py::_stem_s2d) — same params, same
    logits up to f32 summation order. A narrow bottleneck net keeps the
    check fast; the stem kernel is full-size 7x7 either way."""
    import dataclasses

    from tpu_dist.nn.resnet import ResNetDef

    plain = ResNetDef(
        "bottleneck", (1, 1, 1, 1), num_classes=11,
        widths=(8, 8, 16, 16), imagenet_stem=True,
    )
    s2d = dataclasses.replace(plain, s2d_stem=True)
    params, state = plain.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 64, 3))

    ref, _ = plain.apply(params, state, x, train=False)
    got, _ = s2d.apply(params, state, x, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)

    # odd spatial input is refused, not silently mis-shaped
    with pytest.raises(ValueError, match="even"):
        s2d.apply(params, state, x[:, :63, :, :], train=False)
