"""Bench harness config integrity (no heavy compute — registry drift guard)."""

import json
import subprocess
import sys

import bench


def test_all_configs_have_resolvable_models():
    from tpu_dist.nn import resnet18, resnet34, resnet50
    from tpu_dist.nn.resnet import resnet50_imagenet
    from tpu_dist.nn.vit import vit_b16

    known = {"resnet18", "resnet34", "resnet50", "resnet50_imagenet", "vit_b16"}
    for name, cfg in bench.CONFIGS.items():
        assert cfg.model in known, (name, cfg.model)
        assert cfg.global_batch % cfg.grad_accum == 0
        assert cfg.epoch_images > 0


def test_config_names_match_keys():
    for name, cfg in bench.CONFIGS.items():
        assert cfg.name == name


def test_bench_help_runs():
    out = subprocess.run(
        [sys.executable, "bench.py", "--help"],
        capture_output=True, text=True, timeout=120,
        env={"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
             "PATH": "/usr/bin:/bin:/usr/local/bin", "PYTHONPATH": "."},
        cwd=".",
    )
    assert out.returncode == 0
    assert "--scaling" in out.stdout and "--all" in out.stdout


def test_attn_microbench_smoke():
    """run_attn JSON contract at a tiny length (interpret mode on CPU)."""
    out = bench.run_attn(64, steps=1, warmup=0, batch=1)
    assert out["seq_len"] == 64
    assert out["unit"] == "tokens/sec"
    assert out["heads"] == 8 and out["head_dim"] == 128
    # flash ran (value present) — xla too on these tiny shapes
    assert out["flash_ms"] and out["xla_ms"]
    assert out["value"] and out["vs_baseline"]
