"""Cosine/warmup schedule and the NaN-guard failure detection."""

import numpy as np
import pytest

from tpu_dist.config import TrainConfig
from tpu_dist.train.optim import cosine_lr
from tpu_dist.train.trainer import Trainer, TrainingDivergedError, register_model
from tests.helpers import tiny_resnet

register_model("tiny_resnet_g", lambda num_classes=10: tiny_resnet(num_classes))


def test_cosine_schedule_shape():
    s = cosine_lr(1.0, total_epochs=100, warmup_epochs=10)
    assert np.isclose(s(0), 0.1)          # warmup ramp
    assert np.isclose(s(9), 1.0)
    assert np.isclose(s(10), 1.0)         # peak at warmup end
    assert s(55) < s(11)                  # decaying
    assert np.isclose(s(100), 0.0, atol=1e-8)
    s2 = cosine_lr(1.0, 100, warmup_epochs=0, min_lr=0.01)
    assert np.isclose(s2(0), 1.0)
    assert np.isclose(s2(100), 0.01)


def test_trainer_uses_cosine_when_configured():
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_g", num_classes=10,
        batch_size=64, epochs=10, lr=1.0, lr_schedule="cosine", warmup_epochs=2,
        eval_every=0,
    )
    t = Trainer(cfg)
    assert np.isclose(t.lr_schedule(0), 0.5)
    assert np.isclose(t.lr_schedule(1), 1.0)
    assert t.lr_schedule(9) < 0.1


def test_nan_guard_raises():
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_g", num_classes=10,
        batch_size=64, epochs=1, steps_per_epoch=3, log_every=1,
        lr=1e12, eval_every=0,  # guaranteed blow-up
    )
    t = Trainer(cfg)
    with pytest.raises(TrainingDivergedError, match="non-finite"):
        t.train_epoch(0)


def test_nan_guard_disabled_does_not_raise():
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_g", num_classes=10,
        batch_size=64, epochs=1, steps_per_epoch=2, log_every=1,
        lr=1e12, eval_every=0, nan_guard=False,
    )
    out = Trainer(cfg).train_epoch(0)
    assert not np.isfinite(out["loss"])


def test_nan_guard_catches_between_log_steps():
    # divergence after the last logged step must still raise at epoch end,
    # BEFORE fit() would checkpoint the poisoned state
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_g", num_classes=10,
        batch_size=64, epochs=1, steps_per_epoch=3, log_every=100,
        lr=1e12, eval_every=0,
    )
    with pytest.raises(TrainingDivergedError, match="end of epoch"):
        Trainer(cfg).train_epoch(0)


@pytest.mark.slow  # >10s e2e: excluded from the timed tier-1 gate; the
# quick slice keeps a fast representative of this subsystem in the gate
def test_nan_guard_covers_fused_epoch():
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_g", num_classes=10,
        batch_size=512, epochs=1, lr=1e12, eval_every=0, fused_epoch=True,
        synthetic_n=1024,  # 2 fused steps: keep the epoch-compile small
    )
    with pytest.raises(TrainingDivergedError, match="fused epoch"):
        Trainer(cfg).train_epoch(0)


def test_no_nan_guard_cli_flag():
    import argparse

    from tpu_dist.config import add_reference_flags, config_from_args

    p = add_reference_flags(argparse.ArgumentParser())
    cfg = config_from_args(p.parse_args(["--no_nan_guard"]))
    assert cfg.nan_guard is False
    assert config_from_args(p.parse_args([])).nan_guard is True


@pytest.mark.slow  # tier-1 budget (ISSUE 17): gates in analysis.yml
def test_auto_recover_reloads_and_backs_off(tmp_path):
    """--auto_recover: epoch 0 trains and checkpoints at lr=0.1, the
    milestone then multiplies LR by 1e13 and epoch 1 diverges; recovery
    reloads ckpt_0 and rescales the schedule (factor 1e-13 -> back to
    ~0.1), and the run completes with finite loss. The JSONL history
    records the recovery."""
    import json

    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_g", num_classes=10,
        batch_size=64, epochs=3, steps_per_epoch=3, log_every=1,
        lr=0.1, lr_milestones=(1,), lr_gamma=1e13, eval_every=0,
        ckpt_dir=str(tmp_path), save_every=1,
        auto_recover=1, recover_lr_factor=1e-13,
        log_file=str(tmp_path / "h.jsonl"),
    )
    t = Trainer(cfg)
    out = t.fit()
    assert np.isfinite(out["loss"]), out
    assert t._lr_scale == 1e-13
    events = [json.loads(l) for l in open(tmp_path / "h.jsonl")]
    assert any(e.get("kind") == "auto_recover" for e in events), events


@pytest.mark.slow  # tier-1 budget (ISSUE 17): gates in analysis.yml
def test_auto_recover_exhausted_reraises(tmp_path):
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_g", num_classes=10,
        batch_size=64, epochs=3, steps_per_epoch=3, log_every=1,
        lr=0.1, lr_milestones=(1,), lr_gamma=1e13, eval_every=0,
        ckpt_dir=str(tmp_path), save_every=1,
        auto_recover=2, recover_lr_factor=0.5,  # 5e11x is still a blow-up
    )
    with pytest.raises(TrainingDivergedError):
        Trainer(cfg).fit()


@pytest.mark.slow  # tier-1 budget (ISSUE 17): gates in analysis.yml
def test_auto_recover_without_ckpt_reraises(tmp_path):
    # divergence in epoch 0, nothing saved yet: nothing to recover FROM
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_g", num_classes=10,
        batch_size=64, epochs=1, steps_per_epoch=3, log_every=1,
        lr=1e12, eval_every=0, ckpt_dir=str(tmp_path), save_every=1,
        auto_recover=3,
    )
    with pytest.raises(TrainingDivergedError):
        Trainer(cfg).fit()


@pytest.mark.slow  # tier-1 budget (ISSUE 17): gates in analysis.yml
def test_auto_recover_scale_survives_resume(tmp_path):
    """The backoff is stamped into checkpoint meta: a --resume after a
    recovered run continues with the SCALED schedule instead of replaying
    the divergence (code-review r4)."""
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_g", num_classes=10,
        batch_size=64, epochs=3, steps_per_epoch=3, log_every=1,
        lr=0.1, lr_milestones=(1,), lr_gamma=1e13, eval_every=0,
        ckpt_dir=str(tmp_path), save_every=1,
        auto_recover=1, recover_lr_factor=1e-13,
    )
    t = Trainer(cfg)
    t.fit()
    assert t._lr_scale == 1e-13
    t2 = Trainer(cfg.replace(resume=True, epochs=4))
    assert t2._lr_scale == 1e-13  # picked up from ckpt meta, not reset


def test_emergency_save_refuses_poisoned_state(tmp_path):
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_g", num_classes=10,
        batch_size=64, epochs=1, steps_per_epoch=2, log_every=1,
        eval_every=0, ckpt_dir=str(tmp_path), save_every=1,
    )
    t = Trainer(cfg)
    t._last_epoch, t._in_epoch = 1, False
    t._state_poisoned = True  # the divergence-handling window
    t._emergency_save()
    import os

    assert os.listdir(tmp_path) == []  # nothing written
