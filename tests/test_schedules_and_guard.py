"""Cosine/warmup schedule and the NaN-guard failure detection."""

import numpy as np
import pytest

from tpu_dist.config import TrainConfig
from tpu_dist.train.optim import cosine_lr
from tpu_dist.train.trainer import Trainer, TrainingDivergedError, register_model
from tests.helpers import tiny_resnet

register_model("tiny_resnet_g", lambda num_classes=10: tiny_resnet(num_classes))


def test_cosine_schedule_shape():
    s = cosine_lr(1.0, total_epochs=100, warmup_epochs=10)
    assert np.isclose(s(0), 0.1)          # warmup ramp
    assert np.isclose(s(9), 1.0)
    assert np.isclose(s(10), 1.0)         # peak at warmup end
    assert s(55) < s(11)                  # decaying
    assert np.isclose(s(100), 0.0, atol=1e-8)
    s2 = cosine_lr(1.0, 100, warmup_epochs=0, min_lr=0.01)
    assert np.isclose(s2(0), 1.0)
    assert np.isclose(s2(100), 0.01)


def test_trainer_uses_cosine_when_configured():
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_g", num_classes=10,
        batch_size=64, epochs=10, lr=1.0, lr_schedule="cosine", warmup_epochs=2,
        eval_every=0,
    )
    t = Trainer(cfg)
    assert np.isclose(t.lr_schedule(0), 0.5)
    assert np.isclose(t.lr_schedule(1), 1.0)
    assert t.lr_schedule(9) < 0.1


def test_nan_guard_raises():
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_g", num_classes=10,
        batch_size=64, epochs=1, steps_per_epoch=3, log_every=1,
        lr=1e12, eval_every=0,  # guaranteed blow-up
    )
    t = Trainer(cfg)
    with pytest.raises(TrainingDivergedError, match="non-finite"):
        t.train_epoch(0)


def test_nan_guard_disabled_does_not_raise():
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_g", num_classes=10,
        batch_size=64, epochs=1, steps_per_epoch=2, log_every=1,
        lr=1e12, eval_every=0, nan_guard=False,
    )
    out = Trainer(cfg).train_epoch(0)
    assert not np.isfinite(out["loss"])


def test_nan_guard_catches_between_log_steps():
    # divergence after the last logged step must still raise at epoch end,
    # BEFORE fit() would checkpoint the poisoned state
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_g", num_classes=10,
        batch_size=64, epochs=1, steps_per_epoch=3, log_every=100,
        lr=1e12, eval_every=0,
    )
    with pytest.raises(TrainingDivergedError, match="end of epoch"):
        Trainer(cfg).train_epoch(0)


def test_nan_guard_covers_fused_epoch():
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_resnet_g", num_classes=10,
        batch_size=512, epochs=1, lr=1e12, eval_every=0, fused_epoch=True,
        synthetic_n=1024,  # 2 fused steps: keep the epoch-compile small
    )
    with pytest.raises(TrainingDivergedError, match="fused epoch"):
        Trainer(cfg).train_epoch(0)


def test_no_nan_guard_cli_flag():
    import argparse

    from tpu_dist.config import add_reference_flags, config_from_args

    p = add_reference_flags(argparse.ArgumentParser())
    cfg = config_from_args(p.parse_args(["--no_nan_guard"]))
    assert cfg.nan_guard is False
    assert config_from_args(p.parse_args([])).nan_guard is True
