"""Fleet-level observability (ISSUE 6): the goodput ledger's
sum-equals-wall-clock invariant, preemption/restart loss attribution,
triggered on-device profiling (+ the TD108 noop gate), pod-wide
aggregation, the compare --goodput gate, forward-compat record skipping,
and the launcher heartbeat watchdog."""

import json
import os
import signal
import sys
import time

import pytest

from tpu_dist.obs import counters, goodput, spans
from tpu_dist.obs import profile as profile_lib
from tpu_dist.obs.summarize import format_text, load_records, summarize


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Spans/counters are process-global; isolate every test."""
    spans.disable()
    spans.drain()
    counters.reset()
    yield
    spans.disable()
    spans.drain()
    counters.reset()


def _write_jsonl(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    return str(path)


# -- GoodputLedger units -----------------------------------------------------


def test_ledger_windows_partition_wallclock_exactly():
    led = goodput.GoodputLedger(t0=100.0)
    led.add("productive", 6.0)
    led.add("data_stall", 1.0)
    led.add("ckpt", 0.5)
    rec = led.window_record(now=110.0)
    assert rec["window_s"] == 10.0
    assert rec["productive_s"] == 6.0 and rec["data_stall_s"] == 1.0
    # the remainder is derived, never hidden
    assert rec["unattributed_s"] == pytest.approx(2.5)
    assert sum(
        rec[f"{b}_s"] for b in goodput.ALL_BUCKETS
    ) == pytest.approx(rec["window_s"])
    # second window chains from the first's close
    led.add("eval", 2.0)
    rec2 = led.window_record(now=114.0)
    assert rec2["window_s"] == 4.0 and rec2["unattributed_s"] == 2.0
    totals = led.run_totals(now=114.0)
    assert totals["elapsed_s"] == 14.0
    assert totals["productive_s"] == 6.0 and totals["eval_s"] == 2.0
    assert totals["goodput_frac"] == pytest.approx(6.0 / 14.0, abs=1e-4)
    line = goodput.ledger_line(totals)
    assert "42.9%" in line and "14.0s" in line


def test_ledger_rejects_unknown_bucket_and_clamps_negative():
    led = goodput.GoodputLedger(t0=0.0)
    with pytest.raises(ValueError):
        led.add("coffee", 1.0)
    led.add("productive", -5.0)  # clock weirdness must not corrupt books
    assert led.window_value("productive") == 0.0
    # over-attribution clamps the remainder at zero, not negative
    led.add("productive", 50.0)
    rec = led.window_record(now=10.0)
    assert rec["unattributed_s"] == 0.0


def test_ledger_timed_is_exception_safe():
    led = goodput.GoodputLedger(t0=0.0)
    with pytest.raises(RuntimeError):
        with led.timed("ckpt"):
            time.sleep(0.01)
            raise RuntimeError("disk on fire")
    assert led.window_value("ckpt") >= 0.01


# -- offline run_ledger: segments and restart gaps ---------------------------


def _goodput_rec(run_id, ts, rel_s, **fields):
    return {"kind": "goodput", "run_id": run_id, "ts": ts, "rel_s": rel_s,
            "schema_version": 4, **fields}


def test_run_ledger_folds_segments_and_charges_restart_gap():
    records = [
        _goodput_rec("a-1", 1000.0, 10.0, epoch=0, window_s=10.0,
                     productive_s=8.0, compile_s=1.0, unattributed_s=1.0),
        _goodput_rec("a-1", 1002.0, 12.0, final=True, elapsed_s=12.0,
                     productive_s=8.0, compile_s=1.0, ckpt_s=0.5,
                     preempt_s=1.0, unattributed_s=1.5, goodput_frac=0.667),
        # resumed segment: constructed at wall 1010 (ts - rel_s), so the
        # run lost 1010 - 1002 = 8s to the restart
        _goodput_rec("b-2", 1011.0, 1.0, epoch=1, window_s=1.0,
                     productive_s=0.5, unattributed_s=0.5),
        _goodput_rec("b-2", 1015.0, 5.0, final=True, elapsed_s=5.0,
                     productive_s=4.0, unattributed_s=1.0, goodput_frac=0.8),
    ]
    led = goodput.run_ledger(records)
    assert led["n_segments"] == 2
    assert led["restart_gap_s"] == pytest.approx(8.0)
    assert led["preempt_s"] == pytest.approx(1.0 + 8.0)  # in-process + gap
    assert led["elapsed_s"] == pytest.approx(12.0 + 5.0 + 8.0)
    assert led["productive_s"] == pytest.approx(12.0)
    assert led["goodput_frac"] == pytest.approx(12.0 / 25.0, abs=1e-3)


def test_run_ledger_reconstructs_segment_killed_before_final():
    # a crash between the last window record and the final totals: the
    # windows are the books
    records = [
        _goodput_rec("a-1", 1000.0, 10.0, epoch=0, window_s=10.0,
                     productive_s=7.0, unattributed_s=3.0),
        _goodput_rec("a-1", 1005.0, 15.0, epoch=1, window_s=5.0,
                     productive_s=4.0, unattributed_s=1.0),
    ]
    led = goodput.run_ledger(records)
    assert led["elapsed_s"] == pytest.approx(15.0)
    assert led["productive_s"] == pytest.approx(11.0)
    assert goodput.run_ledger([{"kind": "train_epoch", "epoch": 0}]) is None


# -- triggered profiler state machine (fake capture backend) -----------------


@pytest.fixture
def fake_profiler(monkeypatch):
    calls = {"start": [], "stop": 0}
    import jax

    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda d: calls["start"].append(d)
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace",
        lambda: calls.__setitem__("stop", calls["stop"] + 1),
    )
    return calls


def test_profiler_arm_window_cooldown_and_cap(tmp_path, fake_profiler):
    prof = profile_lib.TriggeredProfiler(
        str(tmp_path), window_steps=2, cooldown_steps=5, max_captures=2
    )
    assert prof.on_step(0) is None          # nothing armed: free
    assert prof.arm("anomaly_loss_spike")
    ev = prof.on_step(1)
    assert ev["event"] == "start" and ev["reason"] == "anomaly_loss_spike"
    assert prof.on_step(2) is None          # window open, 1 of 2 steps
    ev = prof.on_step(3)
    assert ev["event"] == "stop" and ev["steps"] == 2
    assert fake_profiler["stop"] == 1
    # cooldown: an arm inside it stays pending until the cooldown expires
    assert prof.arm("retrace")
    assert prof.on_step(4) is None
    assert prof.on_step(7) is None and prof.armed == "retrace"
    ev = prof.on_step(8)                    # 8 - 3 reaches the cooldown 5
    assert ev is not None and ev["event"] == "start"
    prof.close()
    # cap: both captures spent — further arms are refused and counted
    assert not prof.arm("anomaly_again")
    assert counters.get("profile.skipped_capped") == 1
    assert counters.get("profile.captures") == 2
    assert len(fake_profiler["start"]) == 2


def test_profiler_manual_range_fires_once(tmp_path, fake_profiler):
    prof = profile_lib.TriggeredProfiler(
        str(tmp_path), window_steps=8, manual_range=(3, 5), max_captures=0
    )
    assert prof.on_step(0) is None
    ev = prof.on_step(3)
    assert ev["event"] == "start" and ev["reason"] == "manual"
    assert prof.on_step(4) is None
    ev = prof.on_step(5)                    # [3, 5): stops at b
    assert ev["event"] == "stop" and ev["steps"] == 2
    for s in range(6, 12):                  # manual fires ONCE
        assert prof.on_step(s) is None


def test_profiler_manual_range_longer_than_window_runs_full(
    tmp_path, fake_profiler
):
    """--profile_steps a:b owns its FULL range: window_steps bounds
    triggered captures only (a 50-step manual request must not be
    silently truncated to the 8-step default window)."""
    prof = profile_lib.TriggeredProfiler(
        str(tmp_path), window_steps=3, manual_range=(2, 9), max_captures=0
    )
    ev = prof.on_step(2)
    assert ev["event"] == "start" and ev["window_steps"] == 7
    for s in range(3, 9):                   # steps 3..8 all inside [2, 9)
        assert prof.on_step(s) is None
    ev = prof.on_step(9)
    assert ev["event"] == "stop" and ev["steps"] == 7
    assert fake_profiler["stop"] == 1


def test_profiler_close_reports_actual_steps(tmp_path, fake_profiler):
    """close() mid-window (fit exit, error exits) must report the steps
    that actually ran, flagged aborted — not the planned window."""
    prof = profile_lib.TriggeredProfiler(
        str(tmp_path), window_steps=8, cooldown_steps=0, max_captures=2
    )
    prof.arm("anomaly")
    prof.on_step(5)
    prof.on_step(6)
    prof.on_step(7)                         # 3 of the planned 8 ran
    ev = prof.close()
    assert ev["event"] == "stop" and ev["aborted"]
    assert ev["steps"] == 3
    assert fake_profiler["stop"] == 1


def test_profiler_capture_failure_disables_not_raises(tmp_path, monkeypatch):
    import jax

    def boom(d):
        raise RuntimeError("profiler backend unavailable")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    prof = profile_lib.TriggeredProfiler(str(tmp_path), max_captures=3)
    prof.arm("anomaly_x")
    ev = prof.on_step(0)
    assert ev["event"] == "error"
    assert not prof.arm("anomaly_y")        # broken: stands down for good
    assert counters.get("profile.errors") == 1


def test_profile_spec_parsing():
    assert profile_lib.parse_trigger("off") == frozenset()
    assert profile_lib.parse_trigger("auto") == frozenset(
        profile_lib.TRIGGER_KINDS
    )
    assert profile_lib.parse_trigger("anomaly,retrace") == {
        "anomaly", "retrace"
    }
    with pytest.raises(ValueError):
        profile_lib.parse_trigger("anomaly,typo")
    assert profile_lib.parse_steps(None) is None
    assert profile_lib.parse_steps("3:7") == (3, 7)
    for bad in ("7:3", "3", "a:b", "-1:2", "3:3"):
        with pytest.raises(ValueError):
            profile_lib.parse_steps(bad)


def test_trainer_rejects_bad_profile_configs(tmp_path):
    from tests.helpers import tiny_resnet
    from tpu_dist.config import TrainConfig
    from tpu_dist.train.trainer import Trainer, register_model

    register_model("tiny_gp_cfg", lambda num_classes=10: tiny_resnet(num_classes))
    base = dict(
        dataset="synthetic", model="tiny_gp_cfg", num_classes=10,
        batch_size=64, epochs=1, synthetic_n=64, seed=0,
    )
    with pytest.raises(ValueError, match="profile_dir"):
        Trainer(TrainConfig(**base, profile_trigger="auto"))
    with pytest.raises(ValueError, match="a:b"):
        Trainer(TrainConfig(
            **base, profile_steps="oops",
            profile_dir=str(tmp_path / "p"),
        ))
    with pytest.raises(ValueError, match="fused_epoch"):
        Trainer(TrainConfig(
            **base, profile_steps="1:3", fused_epoch=True,
            profile_dir=str(tmp_path / "p"),
        ))


def test_seed_global_step_reanchors_profile_grid():
    """The --profile_steps grid is RUN-global: a resumed process anchors
    it at the restored position (epoch x steps-per-epoch + mid-epoch
    step), so windows already captured before a preemption never
    re-fire at the wrong steps."""
    import types

    from tpu_dist.train.trainer import Trainer

    stub = types.SimpleNamespace(
        train_loader=[None] * 10,
        cfg=types.SimpleNamespace(steps_per_epoch=None),
        start_epoch=3, _resume_step=4,
    )
    Trainer._seed_global_step(stub)
    assert stub._global_step == 3 * 10 + 4
    # --steps_per_epoch caps the per-epoch count, same as train_epoch
    stub.cfg.steps_per_epoch = 6
    Trainer._seed_global_step(stub)
    assert stub._global_step == 3 * 6 + 4


# -- TD108 -------------------------------------------------------------------


@pytest.mark.slow  # ~20 s: opens a REAL jax.profiler capture window
# (the capture-OPEN trace comparison); excluded from the timed tier-1
# gate, runs in the CI goodput step (no slow filter) — ISSUE 7 budget
def test_td108_profile_trigger_noop_gate():
    from tpu_dist.analysis.jaxpr_audit import profile_trigger_noop_violations

    assert profile_trigger_noop_violations() == []


def test_td108_rule_registered():
    from tpu_dist.analysis.rules import RULES

    assert "TD108" in RULES


# -- forward-compat: unknown kinds / future schema ---------------------------


def test_summarize_skips_unknown_kinds_with_count():
    """The mixed v4/v5(/v6) regression: older tooling reading a newer log
    (and vice versa) must skip-with-count, not crash or silently drop."""
    records = [
        {"kind": "train_epoch", "epoch": 0, "run_id": "r", "ts": 1.0,
         "rel_s": 1.0, "schema_version": 3, "epoch_time": 1.0,
         "images_per_sec": 100.0, "loss": 2.0},
        _goodput_rec("r", 2.0, 2.0, epoch=0, window_s=2.0,
                     productive_s=1.5, unattributed_s=0.5),
        # a future schema's record kinds: skipped, counted, noted
        {"kind": "hologram", "epoch": 0, "schema_version": 16, "ts": 3.0},
        {"kind": "hologram", "epoch": 1, "schema_version": 16, "ts": 4.0},
        {"kind": "quantum_foam", "schema_version": 16, "ts": 5.0},
    ]
    report = summarize(records)
    assert report["skipped_kinds"] == {"hologram": 2, "quantum_foam": 1}
    assert report["newer_schema_records"] == 3
    assert report["totals"]["n_epochs"] == 1  # known kinds still parsed
    assert report["goodput"]["productive_s"] == pytest.approx(1.5)
    text = format_text(report)
    assert "skipped 3 record(s) of unknown kind(s)" in text
    assert "hologram×2" in text and "newer than this reader" in text


def test_summarize_renders_goodput_table():
    records = [
        _goodput_rec("r", 1.0, 1.0, epoch=0, window_s=4.0, productive_s=3.0,
                     compile_s=0.5, data_stall_s=0.25, unattributed_s=0.25),
        # run-end teardown window: same epoch number as the row above, but
        # tail-marked so the table can tell them apart
        _goodput_rec("r", 1.5, 1.5, epoch=0, tail=True, window_s=0.5,
                     ckpt_s=0.4, unattributed_s=0.1),
        _goodput_rec("r", 2.0, 2.0, final=True, elapsed_s=4.5,
                     productive_s=3.0, compile_s=0.5, data_stall_s=0.25,
                     ckpt_s=0.4, unattributed_s=0.35, goodput_frac=0.667),
    ]
    report = summarize(records)
    assert len(report["goodput_epochs"]) == 2
    assert report["goodput_epochs"][0].get("tail") is None
    assert report["goodput_epochs"][1]["tail"] is True
    assert report["goodput"]["goodput_frac"] == pytest.approx(3.0 / 4.5, abs=1e-3)
    text = format_text(report)
    assert "goodput (seconds per window):" in text
    assert "   0*" in text                   # the tail row is marked...
    assert "run-end tail window" in text     # ...and the marker explained
    assert "66.7% of 4.5s wall-clock productive" in text


# -- compare --goodput -------------------------------------------------------


def _history_with_goodput(path, frac, stall=0.05):
    productive = round(10.0 * frac, 4)
    return _write_jsonl(path, [
        {"kind": "train_epoch", "epoch": 0, "run_id": "r", "ts": 1.0,
         "rel_s": 1.0, "epoch_time": 10.0, "images_per_sec": 1000.0,
         "loss": 2.0, "data_stall_frac": stall, "step_time_p50": 0.01,
         "step_time_p95": 0.02, "step_time_p99": 0.03},
        _goodput_rec("r", 11.0, 11.0, final=True, elapsed_s=10.0,
                     productive_s=productive, unattributed_s=10.0 - productive,
                     goodput_frac=frac),
    ])


def test_compare_goodput_gate_exit_contract(tmp_path, capsys):
    from tpu_dist.obs.__main__ import main as obs_main

    base = _history_with_goodput(tmp_path / "base.jsonl", 0.85)
    worse = _history_with_goodput(tmp_path / "cand.jsonl", 0.60)
    # injected goodput regression → exit 1 (the CI gate contract)
    assert obs_main(["compare", base, worse, "--goodput"]) == 1
    out = capsys.readouterr().out
    assert "goodput_frac" in out and "REGRESSED" in out
    # self-compare is clean, and the gate compares ONLY goodput metrics
    assert obs_main(["compare", base, base, "--goodput", "--format", "json"]) == 0
    result = json.loads(capsys.readouterr().out)
    assert {r["metric"] for r in result["rows"]} == {
        "goodput_frac", "data_stall_frac", "preempt_for_serve_s"
    }
    # full-metric compare also sees the fraction (additive, skipped when
    # a pre-v4 log lacks it)
    assert obs_main(["compare", base, worse]) == 1
    # two goodput-less pre-v4 logs under --goodput: nothing compared on the
    # headline metric → the stall row still anchors the gate; drop it too
    # and the CLI refuses to pass silently
    a = _write_jsonl(tmp_path / "old_a.jsonl",
                     [{"kind": "train_epoch", "epoch": 0, "epoch_time": 1.0,
                       "images_per_sec": 10.0}])
    capsys.readouterr()
    assert obs_main(["compare", a, a, "--goodput"]) == 2


# -- pod aggregation ---------------------------------------------------------


def _host_log(path, name_seed, *, epoch_time, stall, frac, t0=1000.0):
    recs = [
        {"kind": "train_epoch", "epoch": 0, "run_id": f"r-{name_seed}",
         "ts": t0 + epoch_time, "rel_s": epoch_time,
         "epoch_time": epoch_time, "images_per_sec": 5000.0 / epoch_time,
         "loss": 2.0, "data_stall_frac": stall},
        {"kind": "spans", "run_id": f"r-{name_seed}", "ts": t0 + epoch_time,
         "rel_s": epoch_time,
         "events": [{"name": "train/dispatch", "ph": "X", "ts": 1e5,
                     "dur": 5e4, "pid": 0, "tid": 1}]},
        _goodput_rec(f"r-{name_seed}", t0 + epoch_time + 0.5,
                     epoch_time + 0.5, final=True,
                     elapsed_s=epoch_time + 0.5,
                     productive_s=round(frac * (epoch_time + 0.5), 3),
                     unattributed_s=round(
                         (1 - frac) * (epoch_time + 0.5), 3),
                     goodput_frac=frac),
    ]
    return _write_jsonl(path, recs)


def test_pod_report_side_by_side_and_straggler_attribution(tmp_path):
    from tpu_dist.obs import aggregate

    # host1 is the straggler AND stalls on input — attribution: data_stall
    h0 = _host_log(tmp_path / "h0.jsonl", 0, epoch_time=10.0, stall=0.02,
                   frac=0.9)
    h1 = _host_log(tmp_path / "h1.jsonl", 1, epoch_time=25.0, stall=0.6,
                   frac=0.4, t0=1000.2)
    hosts = [(p, load_records(p)[0]) for p in (h0, h1)]
    report = aggregate.pod_report(hosts)
    assert report["n_hosts"] == 2
    assert report["pod"]["worst_goodput_host"] == h1
    assert report["pod"]["goodput_frac_min"] == pytest.approx(0.4)
    (skew,) = report["epoch_skew"]
    assert skew["worst_host"] == h1 and skew["skew"] > 1.4
    assert skew["attribution"] == "data_stall"
    text = aggregate.format_text(report)
    assert "per-host goodput ledgers:" in text
    assert "attribution: data_stall" in text


def test_pod_trace_one_track_per_host_aligned_on_wall_clock(tmp_path):
    from tpu_dist.obs import aggregate

    h0 = _host_log(tmp_path / "h0.jsonl", 0, epoch_time=10.0, stall=0.0,
                   frac=0.9, t0=1000.0)
    # host 1's clock zero sits 2s later on the wall — its track must shift
    h1 = _host_log(tmp_path / "h1.jsonl", 1, epoch_time=10.0, stall=0.0,
                   frac=0.9, t0=1002.0)
    hosts = [(p, load_records(p)[0]) for p in (h0, h1)]
    trace = aggregate.pod_trace(hosts)
    pids = {e["pid"] for e in trace["traceEvents"]}
    assert pids == {0, 1}
    names = {
        e["args"]["name"] for e in trace["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert names == {h0, h1}
    span0 = next(e for e in trace["traceEvents"]
                 if e["pid"] == 0 and e["name"] == "train/dispatch")
    span1 = next(e for e in trace["traceEvents"]
                 if e["pid"] == 1 and e["name"] == "train/dispatch")
    assert span1["ts"] - span0["ts"] == pytest.approx(2e6, rel=1e-3)
    for e in trace["traceEvents"]:  # structurally Perfetto-loadable
        assert isinstance(e.get("name"), str) and "ph" in e


def test_pod_cli_merges_logs_and_writes_trace(tmp_path, capsys):
    from tpu_dist.obs.__main__ import main as obs_main

    h0 = _host_log(tmp_path / "h0.jsonl", 0, epoch_time=10.0, stall=0.02,
                   frac=0.9)
    h1 = _host_log(tmp_path / "h1.jsonl", 1, epoch_time=12.0, stall=0.04,
                   frac=0.8)
    hb = str(tmp_path / "hb.h0.json")
    with open(hb, "w") as f:
        json.dump({"counter": 7, "epoch": 0, "step": 3, "phase": "train",
                   "ts": time.time()}, f)
    out = str(tmp_path / "pod_trace.json")
    rc = obs_main(["pod", h0, h1, "--heartbeat", hb,
                   "--heartbeat", str(tmp_path / "absent.json"),
                   "--trace-out", out])
    assert rc == 0
    printed = capsys.readouterr().out
    assert "pod report — 2 host(s)" in printed
    assert "beat 7 at epoch 0 step 3" in printed
    assert "absent (clean exit or not started)" in printed
    trace = json.loads(open(out).read())
    assert {e["pid"] for e in trace["traceEvents"]} == {0, 1}
    assert obs_main(["pod", str(tmp_path / "missing.jsonl")]) == 2


# -- launcher heartbeat watchdog ---------------------------------------------


@pytest.mark.slow  # real multi-second watchdog waits; CI goodput step
# runs it without the slow filter (ISSUE 7 tier-1 budget)
def test_launch_watchdog_detects_and_kills_wedged_worker(tmp_path, capsys):
    """A worker that beats once then hangs (no crash, no preemption) must
    be detected, attributed to its position, and terminated — the
    pre-watchdog launcher waited forever."""
    from tpu_dist.cli.launch import main as launch_main

    hb_dir = str(tmp_path / "hb")
    # the child mimics a trainer far enough to take the injected flags,
    # write one heartbeat at a known position, then wedge
    child = (
        "import json, sys, time\n"
        "argv = sys.argv\n"
        "hb = argv[argv.index('--heartbeat_file') + 1]\n"
        "json.dump({'counter': 1, 'epoch': 2, 'step': 7, 'phase': 'train',\n"
        "           'ts': time.time()}, open(hb, 'w'))\n"
        "time.sleep(60)\n"
    )
    t0 = time.monotonic()
    rc = launch_main([
        "--nproc", "1", "--heartbeat_dir", hb_dir,
        "--watchdog_timeout", "2", "--watchdog_grace", "2", "--",
        sys.executable, "-c", child,
    ])
    took = time.monotonic() - t0
    assert rc != 0 and rc != 75  # a wedge is a failure, never requeue-me
    assert took < 30  # detected and killed, not waited out
    err = capsys.readouterr().err
    assert "WATCHDOG: worker 0 wedged" in err
    assert "epoch 2 step 7" in err and "'train'" in err
    assert "goodput loss" in err


def test_per_rank_path_one_scheme_for_all_sites():
    """The trainer (heartbeat + --per_host_log), the launcher watchdog,
    and `obs pod` all share ONE per-rank naming definition."""
    from tpu_dist.obs.heartbeat import per_rank_path

    assert per_rank_path("/d/hb.json", 0) == "/d/hb.json"
    assert per_rank_path("/d/hb.json", 3) == "/d/hb.json.h3"


@pytest.mark.slow  # ~6 s of real emergency-save sleeps; CI goodput
# step runs it without the slow filter (ISSUE 7 tier-1 budget)
def test_launch_watchdog_stands_down_during_preemption(tmp_path, capsys):
    """A preemption shutdown beats once ('preempted') then goes silent in
    the emergency save BY DESIGN — the watchdog must not reclassify that
    as a wedge and turn the requeue-75 exit into a crash. Child 0 exits
    75 immediately (setting the job's preempted state and triggering the
    SIGTERM fan-out); child 1 then stalls well past the watchdog timeout
    before finishing its graceful exit-75."""
    from tpu_dist.cli.launch import main as launch_main

    hb_dir = str(tmp_path / "hb")
    child = (
        "import json, signal, sys, time\n"
        "argv = sys.argv\n"
        "rank = int(argv[argv.index('--process_id') + 1])\n"
        "base = argv[argv.index('--heartbeat_file') + 1]\n"
        "hb = base if rank == 0 else base + '.h%d' % rank\n"
        "if rank == 0:\n"
        "    sys.exit(75)\n"
        "def on_term(s, f):\n"
        "    json.dump({'counter': 2, 'epoch': 0, 'step': 3,\n"
        "               'phase': 'preempted', 'ts': time.time()},\n"
        "              open(hb, 'w'))\n"
        "    time.sleep(6)\n"   # silent emergency save >> watchdog_timeout
        "    sys.exit(75)\n"
        "signal.signal(signal.SIGTERM, on_term)\n"
        "json.dump({'counter': 1, 'epoch': 0, 'step': 3, 'phase': 'train',\n"
        "           'ts': time.time()}, open(hb, 'w'))\n"
        "time.sleep(60)\n"
    )
    rc = launch_main([
        "--nproc", "2", "--heartbeat_dir", hb_dir,
        "--watchdog_timeout", "2", "--watchdog_grace", "1", "--",
        sys.executable, "-c", child,
    ])
    assert rc == 75                          # requeue-me, not a crash
    assert "WATCHDOG" not in capsys.readouterr().err


# -- e2e: the ledger invariant + triggered capture on a real run -------------


@pytest.mark.slow  # >10s e2e (full trainer fit + compiles): excluded from
# the timed tier-1 gate; gates in the CI goodput step, which runs this
# module without the slow filter
def test_e2e_goodput_buckets_sum_to_wallclock(tmp_path, capsys):
    """Acceptance: on a short run, every goodput window's buckets sum to
    its wall-clock exactly, and the run ledger's elapsed matches the
    measured Trainer-construction-to-exit wall time within 2%. The same
    run drives a manual --profile_steps capture end to end."""
    from tests.helpers import tiny_resnet
    from tpu_dist.config import TrainConfig
    from tpu_dist.obs.__main__ import main as obs_main
    from tpu_dist.train.trainer import Trainer, register_model

    register_model("tiny_gp_e2e", lambda num_classes=10: tiny_resnet(num_classes))
    log = str(tmp_path / "run.jsonl")
    prof_dir = str(tmp_path / "prof")
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_gp_e2e", num_classes=10,
        batch_size=64, epochs=2, steps_per_epoch=3, eval_every=1,
        synthetic_n=640, log_every=2, log_file=log,
        ckpt_dir=str(tmp_path / "ckpt"), save_every=1, seed=0,
        profile_dir=prof_dir, profile_steps="1:3",
    )
    t_wall0 = time.monotonic()
    Trainer(cfg).fit()
    wall = time.monotonic() - t_wall0
    records, bad = load_records(log)
    assert bad == 0
    windows = [r for r in records if r["kind"] == "goodput" and not r.get("final")]
    finals = [r for r in records if r["kind"] == "goodput" and r.get("final")]
    assert len(windows) == 3 and len(finals) == 1  # 2 epochs + tail
    for w in windows:
        parts = sum(w[f"{b}_s"] for b in goodput.ALL_BUCKETS)
        assert parts == pytest.approx(w["window_s"], abs=0.02)
    total = finals[0]
    parts = sum(total[f"{b}_s"] for b in goodput.ALL_BUCKETS)
    assert parts == pytest.approx(total["elapsed_s"], abs=0.05)
    # the acceptance tolerance: ledger elapsed vs measured wall within 2%
    # (+0.3s absolute: the __init__ lock preamble and post-fit teardown
    # sit outside the ledger's clock)
    assert total["elapsed_s"] == pytest.approx(wall, rel=0.02, abs=0.3)
    assert total["productive_s"] > 0
    assert total["compile_s"] > 0      # the jax.monitoring listener fed it
    assert total["ckpt_s"] > 0         # save_every=1 wrote checkpoints
    assert total["eval_s"] > 0
    assert 0.0 < total["goodput_frac"] <= 1.0
    # the manual capture ran: start+stop records and on-disk trace output
    profs = [r for r in records if r["kind"] == "profile"]
    events = [p.get("event") for p in profs]
    assert "start" in events and "stop" in events
    stop = next(p for p in profs if p.get("event") == "stop")
    assert stop["reason"] == "manual" and stop["steps"] == 2
    assert os.path.isdir(prof_dir) and os.listdir(prof_dir)
    # the CLI surfaces the ledger + capture in the report
    capsys.readouterr()
    assert obs_main(["summarize", log]) == 0
    text = capsys.readouterr().out
    assert "goodput (seconds per window):" in text
    assert "wall-clock productive" in text
    assert "profile: captured 2 step(s)" in text


@pytest.mark.slow  # two full trainer fits (~2 compiles): excluded from the
# timed tier-1 gate; runs in the CI goodput step and the full suite
def test_e2e_sigterm_resume_attributes_preempt_and_restart_loss(tmp_path):
    """Acceptance: a fault-plan SIGTERM run resumed from its snapshot
    shows nonzero preemption/restart loss in the folded run ledger."""
    from tests.helpers import tiny_resnet
    from tpu_dist.config import TrainConfig
    from tpu_dist.resilience.preemption import PreemptedError
    from tpu_dist.train.trainer import Trainer, register_model

    register_model("tiny_gp_pre", lambda num_classes=10: tiny_resnet(num_classes))
    log = str(tmp_path / "run.jsonl")
    cfg = TrainConfig(
        dataset="synthetic", model="tiny_gp_pre", num_classes=10,
        batch_size=64, epochs=2, steps_per_epoch=3, eval_every=0,
        synthetic_n=640, log_every=2, log_file=log, seed=0,
        ckpt_dir=str(tmp_path / "ckpt"), save_every=1,
        fault_plan="sigterm@epoch=1:step=1",
    )
    with pytest.raises(PreemptedError):
        Trainer(cfg).fit()
    # requeued at identical size: same log_file, fresh run_id segment
    Trainer(cfg.replace(fault_plan=None, resume=True)).fit()
    records, _bad = load_records(log)
    led = goodput.run_ledger(records)
    assert led is not None and led["n_segments"] == 2
    assert led["preempt_s"] > 0           # SIGTERM tail + restart gap
    assert led["restart_gap_s"] > 0       # the second construction is real
    assert led["productive_s"] > 0
    report = summarize(records)
    assert report["goodput"]["n_segments"] == 2
