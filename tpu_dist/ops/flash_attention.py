"""Pallas flash attention: tiled online-softmax attention for TPU.

The XLA path (``tpu_dist.nn.attention.full_attention``) materializes the
[S, S] score matrix in HBM — fine at ViT lengths, ruinous for long
context. This kernel computes attention in (block_q × block_k) VMEM tiles
with the numerically-stable online softmax (running max ``m``, normalizer
``l``), so peak memory is O(block²) per core instead of O(S²), and the
QKᵀ / PV matmuls hit the MXU back to back from VMEM.

This is the single-device building block of the long-context story; the
sequence-PARALLEL dimension is handled one level up by
``tpu_dist.nn.attention.ring_attention`` (K/V rotating over the mesh
axis), whose per-rotation local block can itself be this kernel.

No reference counterpart (the reference has no attention code at all,
SURVEY §2.3); the role model is apex/FlashAttention-style fused kernels
on the CUDA side — built here the TPU way: ``pl.pallas_call`` over a
(batch·heads, S/block_q, S/block_k) grid, f32 accumulation in VMEM
scratch, sequential innermost grid dimension carrying the softmax state.

Backward: a ``jax.custom_vjp`` running the FlashAttention-2 dq/dk/dv
recipe as two tiled Pallas kernels (default ``bwd='pallas'``): a dK/dV
pass gridded over k-blocks accumulating across q-blocks in VMEM scratch,
and a dQ pass gridded the other way — probabilities recomputed blockwise
from the saved (m, l) statistics, O(block²) working set, never
materializing [S, S]. The original XLA-level ``lax.scan`` formulation is
kept behind ``bwd='xla'`` for A/B comparison and as a fallback.

Works on any backend via Pallas interpret mode (auto-selected off-TPU),
which is how the CPU test suite checks it bit-for-bit against the XLA
path (``tests/test_flash_attention.py``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from tpu_dist.comm import compat

try:  # pallas TPU backend is optional at import time (CPU test images)
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

# renamed TPUCompilerParams -> CompilerParams across JAX releases
_CompilerParams = pltpu and (
    getattr(pltpu, "CompilerParams", None) or getattr(pltpu, "TPUCompilerParams")
)

_NEG_INF = -1e30  # large-negative instead of -inf: keeps exp() NaN-free


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                acc_scr, m_scr, l_scr, *, scale, causal, block_q, block_k,
                kv_len, out_dtype):
    i = pl.program_id(1)
    j = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _accumulate():
        q = q_ref[0].astype(jnp.float32)                  # [bq, d]
        k = k_ref[0].astype(jnp.float32)                  # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                                         # [bq, bk]

        k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = k_pos < kv_len                             # kv padding
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[:, :1]                             # [bq, 1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)                       # exact zeros
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32),
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # tiles entirely above the diagonal are all-masked: p would be 0,
        # m/l/acc unchanged — skip their matmuls (same guard as the bwd)
        pl.when(_causal_block_live(i, j, block_q, block_k))(_accumulate)
    else:
        _accumulate()

    @pl.when(j == n_k - 1)
    def _finish():
        l_fin = l_scr[:, :1]
        o_ref[0] = (acc_scr[:] / jnp.maximum(l_fin, 1e-30)).astype(out_dtype)
        m_ref[0] = m_scr[:, 0]
        l_ref[0] = l_scr[:, 0]


def _pad_to(x, mult, axis):
    pad = (-x.shape[axis]) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _fwd(q3, k3, v3, causal, block_q, block_k, interpret, out_dtype=None):
    """[BH, S, D] inputs → (out [BH, S, D], m [BH, S], l [BH, S]).

    ``out_dtype`` overrides the output dtype (default: ``q3.dtype``) — the
    ring composition asks for f32 so per-rotation partials merge without a
    bf16 quantization per rotation."""
    if pltpu is None:  # pragma: no cover
        raise RuntimeError(
            "flash_attention requires jax.experimental.pallas.tpu (even in "
            "interpret mode) — use the XLA path (nn.attention.full_attention)"
        )
    bh, s_q, d = q3.shape
    s_kv = k3.shape[1]
    bq = min(block_q, -(-s_q // 8) * 8)   # block ≤ padded length, 8-row tiles
    bk = min(block_k, -(-s_kv // 8) * 8)
    qp = _pad_to(q3, bq, 1)
    kp = _pad_to(k3, bk, 1)
    vp = _pad_to(v3, bk, 1)
    n_q = qp.shape[1] // bq
    n_k = kp.shape[1] // bk
    # d is a static Python shape int: float() runs at trace time, no sync
    scale = 1.0 / float(d) ** 0.5  # tpu-dist: ignore[TD001]

    odt = out_dtype or q3.dtype
    kern = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        kv_len=s_kv, out_dtype=odt,
    )
    mem = {"memory_space": pltpu.VMEM}
    out, m, l = pl.pallas_call(
        kern,
        grid=(bh, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0), **mem),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0), **mem),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0), **mem),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0), **mem),
            pl.BlockSpec((1, bq), lambda b, i, j: (b, i), **mem),
            pl.BlockSpec((1, bq), lambda b, i, j: (b, i), **mem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qp.shape, odt),
            jax.ShapeDtypeStruct(qp.shape[:2], jnp.float32),
            jax.ShapeDtypeStruct(qp.shape[:2], jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        # only the innermost (k-block) dim carries softmax state between
        # iterations; batch·heads and q-blocks are free for the TPU to
        # parallelize/pipeline (ADVICE r2)
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :s_q], m[:, :s_q], l[:, :s_q]


def _recompute_p_ds(q_ref, k_ref, v_ref, do_ref, m_ref, l_ref, delta_ref,
                    i, j, *, scale, causal, block_q, block_k, q_len, kv_len):
    """Shared backward block math: recompute the probability block ``p``
    and the score-gradient block ``ds`` from the saved (m, l) statistics.
    One definition, used by BOTH backward kernels — the masking and the
    renormalization clamp must never desync between the dq and dk/dv
    passes. Returns f32 ``(q, do, p, ds)`` blocks."""
    q = q_ref[0].astype(jnp.float32)                       # [bq, d]
    do = do_ref[0].astype(jnp.float32)                     # [bq, d]
    k = k_ref[0].astype(jnp.float32)                       # [bk, d]
    v = v_ref[0].astype(jnp.float32)                       # [bk, d]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale                                              # [bq, bk]

    q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    # padded q rows carry zero m/l from _pad_to — mask them out explicitly
    mask = jnp.logical_and(q_pos < q_len, k_pos < kv_len)
    if causal:
        mask = jnp.logical_and(mask, q_pos >= k_pos)

    m_i = m_ref[0][:, None]                                # [bq, 1]
    l_i = jnp.maximum(l_ref[0][:, None], 1e-30)
    p = jnp.where(mask, jnp.exp(s - m_i), 0.0) / l_i       # [bq, bk]
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )                                                      # [bq, bk]
    ds = p * (dp - delta_ref[0][:, None]) * scale
    return q, do, p, ds


def _causal_block_live(i, j, block_q, block_k):
    """False iff the (q-block i, k-block j) tile lies entirely above the
    causal diagonal (max q_pos < min k_pos) — those tiles are all-masked,
    so all three kernels (forward, dK/dV, dQ) skip their matmuls (~2×
    fewer FLOPs at long S; the running state provably doesn't change:
    p would be exactly 0 and m_new == m_prev even at the _NEG_INF init)."""
    return (i + 1) * block_q - 1 >= j * block_k


def _bwd_dkdv_kernel(q_ref, do_ref, m_ref, l_ref, delta_ref, k_ref, v_ref,
                     dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal,
                     block_q, block_k, q_len, kv_len, k_dtype, v_dtype):
    """dK/dV pass (FlashAttention-2): one (batch·head, k-block) per grid
    point, accumulating over q-blocks in VMEM scratch — the innermost grid
    dim is the q loop, declared ``arbitrary`` so only it is sequential."""
    j = pl.program_id(1)
    i = pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _accumulate():
        q, do, p, ds = _recompute_p_ds(
            q_ref, k_ref, v_ref, do_ref, m_ref, l_ref, delta_ref, i, j,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            q_len=q_len, kv_len=kv_len,
        )
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )                                                  # p^T do: [bk, d]
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )                                                  # ds^T q: [bk, d]

    if causal:
        pl.when(_causal_block_live(i, j, block_q, block_k))(_accumulate)
    else:
        _accumulate()

    @pl.when(i == n_q - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(k_dtype)
        dv_ref[0] = dv_scr[:].astype(v_dtype)


def _bwd_dq_kernel(k_ref, v_ref, q_ref, do_ref, m_ref, l_ref, delta_ref,
                   dq_ref, dq_scr, *, scale, causal, block_q, block_k,
                   q_len, kv_len, out_dtype):
    """dQ pass: one (batch·head, q-block) per grid point, accumulating over
    k-blocks (innermost, sequential) in VMEM scratch."""
    i = pl.program_id(1)
    j = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _accumulate():
        _, _, _, ds = _recompute_p_ds(
            q_ref, k_ref, v_ref, do_ref, m_ref, l_ref, delta_ref, i, j,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            q_len=q_len, kv_len=kv_len,
        )
        k = k_ref[0].astype(jnp.float32)
        dq_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        pl.when(_causal_block_live(i, j, block_q, block_k))(_accumulate)
    else:
        _accumulate()

    @pl.when(j == n_k - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(out_dtype)


def _bwd_pallas(q3, k3, v3, o3, m, l, do3, causal, block_q, block_k, interpret,
                delta=None, grad_dtype=None):
    """Pallas FlashAttention-2 backward: two tiled passes (dK/dV then dQ),
    O(block²) VMEM working set, never materializing [S, S] — the TPU-kernel
    sibling of the XLA-level ``_bwd_blocked`` (kept for A/B and as the
    ``bwd='xla'`` escape hatch).

    ``delta`` (rowsum(do·o), [BH, S]) may be passed precomputed — the ring
    backward hoists it out of its rotation scan (it is K/V-independent).
    ``grad_dtype`` overrides the output dtypes (default: each input's own
    dtype) — the ring backward asks for f32 so per-rotation grad partials
    accumulate without a bf16 quantization per rotation (same invariant
    as the forward's ``out_dtype`` override).
    """
    bh, s_q, d = q3.shape
    s_kv = k3.shape[1]
    bq = min(block_q, -(-s_q // 8) * 8)
    bk = min(block_k, -(-s_kv // 8) * 8)
    # d is a static Python shape int: float() runs at trace time, no sync
    scale = 1.0 / float(d) ** 0.5  # tpu-dist: ignore[TD001]
    dq_dtype = grad_dtype or q3.dtype
    dk_dtype = grad_dtype or k3.dtype
    dv_dtype = grad_dtype or v3.dtype

    if delta is None:
        delta = jnp.sum(
            do3.astype(jnp.float32) * o3.astype(jnp.float32), axis=-1
        )                                                  # [BH, S]
    qp = _pad_to(q3, bq, 1)
    dop = _pad_to(do3, bq, 1)
    mp = _pad_to(m, bq, 1)
    lp = _pad_to(l, bq, 1)
    deltap = _pad_to(delta, bq, 1)
    kp = _pad_to(k3, bk, 1)
    vp = _pad_to(v3, bk, 1)
    n_q = qp.shape[1] // bq
    n_k = kp.shape[1] // bk
    mem = {"memory_space": pltpu.VMEM}

    q_specs = [
        pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0), **mem),  # q
        pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0), **mem),  # do
        pl.BlockSpec((1, bq), lambda b, j, i: (b, i), **mem),        # m
        pl.BlockSpec((1, bq), lambda b, j, i: (b, i), **mem),        # l
        pl.BlockSpec((1, bq), lambda b, j, i: (b, i), **mem),        # delta
    ]
    kv_specs = [
        pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0), **mem),  # k
        pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0), **mem),  # v
    ]
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkdv_kernel, scale=scale, causal=causal, block_q=bq,
            block_k=bk, q_len=s_q, kv_len=s_kv,
            k_dtype=dk_dtype, v_dtype=dv_dtype,
        ),
        grid=(bh, n_k, n_q),
        in_specs=q_specs + kv_specs,
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0), **mem),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0), **mem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(kp.shape, dk_dtype),
            jax.ShapeDtypeStruct(vp.shape, dv_dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qp, dop, mp, lp, deltap, kp, vp)

    dq, = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal, block_q=bq,
            block_k=bk, q_len=s_q, kv_len=s_kv, out_dtype=dq_dtype,
        ),
        grid=(bh, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0), **mem),  # k
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0), **mem),  # v
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0), **mem),  # q
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0), **mem),  # do
            pl.BlockSpec((1, bq), lambda b, i, j: (b, i), **mem),        # m
            pl.BlockSpec((1, bq), lambda b, i, j: (b, i), **mem),        # l
            pl.BlockSpec((1, bq), lambda b, i, j: (b, i), **mem),        # delta
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0), **mem),
        ],
        out_shape=[jax.ShapeDtypeStruct(qp.shape, dq_dtype)],
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(kp, vp, qp, dop, mp, lp, deltap)
    return dq[:, :s_q], dk[:, :s_kv], dv[:, :s_kv]


def _bwd_blocked(q3, k3, v3, o3, m, l, do3, causal, block_k):
    """FlashAttention-2 backward at the XLA level: a scan over K/V blocks
    recomputing P from the saved (m, l) — never materializes [S, S]."""
    bh, s_q, d = q3.shape
    s_kv = k3.shape[1]
    # d is a static Python shape int: float() runs at trace time, no sync
    scale = 1.0 / float(d) ** 0.5  # tpu-dist: ignore[TD001]
    bk = min(block_k, s_kv)

    qf = q3.astype(jnp.float32)
    dof = do3.astype(jnp.float32)
    delta = jnp.sum(dof * o3.astype(jnp.float32), axis=-1)          # [BH,S]

    kp = _pad_to(k3, bk, 1).astype(jnp.float32)
    vp = _pad_to(v3, bk, 1).astype(jnp.float32)
    n_k = kp.shape[1] // bk
    kb = kp.reshape(bh, n_k, bk, d).transpose(1, 0, 2, 3)           # [nk,BH,bk,d]
    vb = vp.reshape(bh, n_k, bk, d).transpose(1, 0, 2, 3)

    q_pos = jnp.arange(s_q)[None, :, None]                          # [1,Sq,1]

    def body(carry, blk):
        dq, j = carry
        kj, vj = blk
        s = jnp.einsum("bqd,bkd->bqk", qf, kj) * scale              # [BH,Sq,bk]
        k_pos = j * bk + jnp.arange(bk)[None, None, :]
        mask = k_pos < s_kv
        if causal:
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        p = jnp.where(mask, jnp.exp(s - m[..., None]), 0.0)
        p = p / jnp.maximum(l, 1e-30)[..., None]
        dv_j = jnp.einsum("bqk,bqd->bkd", p, dof)
        dp = jnp.einsum("bqd,bkd->bqk", dof, vj)
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jnp.einsum("bqk,bkd->bqd", ds, kj)
        dk_j = jnp.einsum("bqk,bqd->bkd", ds, qf)
        return (dq, j + 1), (dk_j, dv_j)

    (dq, _), (dk_b, dv_b) = lax.scan(
        body, (jnp.zeros_like(qf), jnp.int32(0)), (kb, vb)
    )
    dk = dk_b.transpose(1, 0, 2, 3).reshape(bh, n_k * bk, d)[:, :s_kv]
    dv = dv_b.transpose(1, 0, 2, 3).reshape(bh, n_k * bk, d)[:, :s_kv]
    return dq.astype(q3.dtype), dk.astype(k3.dtype), dv.astype(v3.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q3, k3, v3, causal, block_q, block_k, interpret, bwd):
    out, _, _ = _fwd(q3, k3, v3, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q3, k3, v3, causal, block_q, block_k, interpret, bwd):
    out, m, l = _fwd(q3, k3, v3, causal, block_q, block_k, interpret)
    return out, (q3, k3, v3, out, m, l)


def _flash_bwd(causal, block_q, block_k, interpret, bwd, res, do3):
    q3, k3, v3, o3, m, l = res
    if bwd == "pallas":
        return _bwd_pallas(
            q3, k3, v3, o3, m, l, do3, causal, block_q, block_k, interpret
        )
    return _bwd_blocked(q3, k3, v3, o3, m, l, do3, causal, block_k)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_supported() -> bool:
    """True when the Pallas TPU backend imported (interpret mode included)."""
    return pltpu is not None


# ---------------------------------------------------------------------------
# Ring flash attention: the Pallas kernels composed with sequence-parallel
# K/V rotation (the ring-attention scheme of nn/attention.py), so BOTH
# memory dimensions are tiled — across devices by the ring, within a device
# by the kernel. The trick that makes the composition cheap: under the ring,
# causal masking at a given rotation is block-structured — the (my, kv_idx)
# pair is either fully unmasked (kv_idx < my), fully masked (kv_idx > my),
# or the diagonal (kv_idx == my), where global offsets cancel and the
# kernel's RELATIVE causal mask is exactly right. A 3-way lax.switch per
# rotation picks the variant; no global-position plumbing enters the
# kernels. Backward follows the ring-flash recipe: dq accumulates at home,
# (dk, dv) accumulators rotate WITH k/v and arrive home after the full
# cycle; each rotation reuses the FlashAttention-2 kernels with the global
# (m, l, delta) statistics, which are valid for any K/V block.
# ---------------------------------------------------------------------------


def _ring_perm(n):
    return [(i, (i + 1) % n) for i in range(n)]


def _fwd_variants(q3, k3, v3, block_q, block_k, interpret):
    """(full, diagonal-causal, masked) rotation forwards, lax.switch-ready.
    Each returns (out_j [BH,S,D] f32, m_j [BH,S], l_j [BH,S]) — partials
    stay f32 so the cross-rotation merge never quantizes to the input
    dtype (one bf16 round-off per rotation would otherwise accumulate)."""
    def full(kk, vv):
        return _fwd(
            q3, kk, vv, False, block_q, block_k, interpret,
            out_dtype=jnp.float32,
        )

    def diag(kk, vv):
        return _fwd(
            q3, kk, vv, True, block_q, block_k, interpret,
            out_dtype=jnp.float32,
        )

    def masked(kk, vv):
        bh, s_q, _ = q3.shape
        return (
            jnp.zeros(q3.shape, jnp.float32),
            jnp.full((bh, s_q), _NEG_INF, jnp.float32),
            jnp.zeros((bh, s_q), jnp.float32),
        )

    return full, diag, masked


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_flash(q3, k3, v3, axis_name, causal, block_q, block_k, interpret):
    out, _, _ = _ring_flash_fwd_impl(
        q3, k3, v3, axis_name, causal, block_q, block_k, interpret
    )
    return out


def _ring_flash_fwd_impl(q3, k3, v3, axis_name, causal, block_q, block_k,
                         interpret):
    n = compat.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    bh, s_q, d = q3.shape
    full, diag, masked = _fwd_variants(q3, k3, v3, block_q, block_k, interpret)

    def rotation(carry, _):
        m, l, acc, kk, vv, kv_idx = carry
        if causal:
            case = jnp.where(kv_idx < my, 0, jnp.where(kv_idx == my, 1, 2))
            out_j, m_j, l_j = lax.switch(case, (full, diag, masked), kk, vv)
        else:
            out_j, m_j, l_j = full(kk, vv)
        # merge the rotation's (normalized) block into the running stats
        m_new = jnp.maximum(m, m_j)
        corr = jnp.exp(m - m_new)          # m starts at _NEG_INF (finite)
        corr_j = jnp.exp(m_j - m_new)
        acc = acc * corr[..., None] + out_j * (l_j * corr_j)[..., None]
        l = l * corr + l_j * corr_j
        perm = _ring_perm(n)
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        return (m_new, l, acc, kk, vv, (kv_idx - 1) % n), None

    m0 = jnp.full((bh, s_q), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bh, s_q), jnp.float32)
    acc0 = jnp.zeros((bh, s_q, d), jnp.float32)
    (m, l, acc, _, _, _), _ = lax.scan(
        rotation, (m0, l0, acc0, k3, v3, my), None, length=n
    )
    out = (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q3.dtype)
    return out, m, l


def _ring_flash_fwd(q3, k3, v3, axis_name, causal, block_q, block_k, interpret):
    out, m, l = _ring_flash_fwd_impl(
        q3, k3, v3, axis_name, causal, block_q, block_k, interpret
    )
    return out, (q3, k3, v3, out, m, l)


def _ring_flash_bwd(axis_name, causal, block_q, block_k, interpret, res, do3):
    q3, k3, v3, o3, m, l = res
    n = compat.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    # delta is K/V-independent: compute ONCE, not per rotation
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32), axis=-1)

    def blk(kk, vv, blk_causal):
        dq_j, dk_j, dv_j = _bwd_pallas(
            q3, kk, vv, o3, m, l, do3, blk_causal, block_q, block_k,
            interpret, delta=delta, grad_dtype=jnp.float32,
        )
        return dk_j, dv_j, dq_j

    def full(kk, vv):
        return blk(kk, vv, False)

    def diag(kk, vv):
        return blk(kk, vv, True)

    def masked(kk, vv):
        # must match full/diag's grad_dtype=f32 exactly — lax.switch
        # requires identical branch output types, and k/v/q may be bf16
        return (jnp.zeros_like(kk, jnp.float32),
                jnp.zeros_like(vv, jnp.float32),
                jnp.zeros_like(q3, jnp.float32))

    def rotation(carry, _):
        kk, vv, dka, dva, dq, kv_idx = carry
        if causal:
            case = jnp.where(kv_idx < my, 0, jnp.where(kv_idx == my, 1, 2))
            dk_j, dv_j, dq_j = lax.switch(case, (full, diag, masked), kk, vv)
        else:
            dk_j, dv_j, dq_j = full(kk, vv)
        dka = dka + dk_j
        dva = dva + dv_j
        dq = dq + dq_j.astype(dq.dtype)
        # the grad accumulators ride the ring WITH their k/v block; after
        # the full cycle they arrive back at the block's home device
        perm = _ring_perm(n)
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        dka = lax.ppermute(dka, axis_name, perm)
        dva = lax.ppermute(dva, axis_name, perm)
        return (kk, vv, dka, dva, dq, (kv_idx - 1) % n), None

    dq0 = jnp.zeros(q3.shape, jnp.float32)
    (kk, vv, dka, dva, dq, _), _ = lax.scan(
        rotation,
        (k3, v3, jnp.zeros_like(k3, jnp.float32),
         jnp.zeros_like(v3, jnp.float32), dq0, my),
        None,
        length=n,
    )
    return dq.astype(q3.dtype), dka.astype(k3.dtype), dva.astype(v3.dtype)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_flash_attention(q, k, v, axis_name: str, *, causal: bool = False,
                         block_q: int = 128, block_k: int = 128,
                         interpret: bool | None = None):
    """Sequence-parallel flash attention on [B, S_local, H, D] shards —
    drop-in for :func:`tpu_dist.nn.attention.ring_attention` with the
    local tile computed by the Pallas kernels instead of an XLA einsum.
    Per-device peak memory drops from O(S_local²) (the ring's per-rotation
    score tile) to O(block²); causal rotations entirely above the diagonal
    are skipped (a 3-way ``lax.switch``). Call inside ``shard_map`` with
    the sequence dim sharded over ``axis_name``."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, s, h, d = q.shape
    to3 = lambda t: t.transpose(0, 2, 1, 3).reshape(b * h, t.shape[1], d)
    out3 = _ring_flash(
        to3(q), to3(k), to3(v), axis_name, causal, block_q, block_k, interpret
    )
    return out3.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def flash_attention(q, k, v, *, causal: bool = False, block_q: int = 128,
                    block_k: int = 128, interpret: bool | None = None,
                    bwd: str = "pallas"):
    """Tiled attention on [B, S, H, D] — drop-in for
    :func:`tpu_dist.nn.attention.full_attention` (same contract: f32
    softmax accumulation, output in ``q.dtype``).

    ``interpret=None`` auto-selects Pallas interpret mode off-TPU. Head
    dim ``D`` should be a multiple of 128 lanes for peak MXU utilization
    (64 works, at some padding cost). Sequence lengths are padded to the
    block size internally and masked exactly.

    ``bwd``: ``'pallas'`` (default) runs the FlashAttention-2 backward as
    two tiled Pallas kernels (dK/dV pass + dQ pass); ``'xla'`` keeps the
    blockwise ``lax.scan`` formulation — same math, for A/B comparison
    and as a numerics cross-check. (Either way the FORWARD needs the
    Pallas module; off-TPU both run in interpret mode.)
    """
    if bwd not in ("pallas", "xla"):
        raise ValueError(f"bwd must be 'pallas' or 'xla', got {bwd!r}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, s, h, d = q.shape
    to3 = lambda t: t.transpose(0, 2, 1, 3).reshape(b * h, t.shape[1], d)
    out3 = _flash(to3(q), to3(k), to3(v), causal, block_q, block_k, interpret, bwd)
    return out3.reshape(b, h, s, d).transpose(0, 2, 1, 3)
