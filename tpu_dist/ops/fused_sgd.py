"""Pallas fused SGD+momentum+weight-decay update kernel.

TPU-native equivalent of apex's fused multi-tensor optimizer kernels
(SURVEY §2.2 N4: ``amp.initialize``'s C++/CUDA fused ops). One pass over
each parameter tensor computes

    g' = g + wd * p
    b' = mu * b + g'
    p' = p - lr * b'

reading p/g/b once from HBM and writing p'/b' once — the whole update is
VPU element-wise work tiled through VMEM in (CHUNK, 128) blocks, with the
learning rate prefetched to SMEM. On non-TPU backends (the CPU test mesh)
the same kernel runs in Pallas interpret mode; callers can also just use
the plain jnp update in :class:`tpu_dist.train.optim.SGD` — both paths are
bit-comparable (see tests/test_fused_sgd.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is optional at import time
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

_LANES = 128
_SUBLANES = 512  # (512, 128) f32 block = 256 KiB/ref; 5 refs ≈ 1.3 MiB VMEM


def pallas_supported() -> bool:
    return pltpu is not None


def _kernel(lr_ref, p_ref, g_ref, b_ref, out_p_ref, out_b_ref, *, momentum, weight_decay):
    g = g_ref[:] + weight_decay * p_ref[:]
    b = momentum * b_ref[:] + g
    out_b_ref[:] = b
    out_p_ref[:] = p_ref[:] - lr_ref[0] * b


def fused_sgd_leaf(p, g, b, lr, *, momentum: float = 0.9, weight_decay: float = 1e-4,
                   interpret: bool | None = None):
    """Update one parameter leaf. Returns ``(new_p, new_b)``.

    Accepts any shape; internally flattened and padded to (rows, 128) tiles.
    ``interpret=None`` auto-selects interpret mode off-TPU.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not pallas_supported():
        raise RuntimeError(
            "fused SGD requires jax.experimental.pallas.tpu, which failed to "
            "import in this environment — use SGD(fused=False) (the plain jnp "
            "update; bit-comparable, see tests/test_fused_sgd.py)"
        )

    orig_shape, orig_dtype = p.shape, p.dtype
    n = p.size
    cols = _LANES
    rows_per_block = min(_SUBLANES, max(8, -(-n // cols)))
    block = rows_per_block * cols
    n_blocks = -(-n // block)
    padded = n_blocks * block

    def prep(x):
        x = x.reshape(-1).astype(jnp.float32)
        if padded != n:
            x = jnp.pad(x, (0, padded - n))
        return x.reshape(n_blocks * rows_per_block, cols)

    pf, gf, bf = prep(p), prep(g), prep(b)
    lr_arr = jnp.asarray([lr], jnp.float32)

    kernel = functools.partial(_kernel, momentum=momentum, weight_decay=weight_decay)
    blockspec = pl.BlockSpec(
        (rows_per_block, cols), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    out_p, out_b = pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # lr, whole (1,) array
            blockspec,
            blockspec,
            blockspec,
        ],
        out_specs=[blockspec, blockspec],
        out_shape=[
            jax.ShapeDtypeStruct(pf.shape, jnp.float32),
            jax.ShapeDtypeStruct(bf.shape, jnp.float32),
        ],
        interpret=interpret,
    )(lr_arr, pf, gf, bf)

    def unprep(x):
        return x.reshape(-1)[:n].reshape(orig_shape).astype(orig_dtype)

    return unprep(out_p), unprep(out_b)
