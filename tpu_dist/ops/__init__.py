from tpu_dist.ops.fused_sgd import fused_sgd_leaf, pallas_supported  # noqa: F401
