"""Serving subsystem — compiled inference with continuous batching and a
latency-instrumented SLO layer (``docs/serving.md``).

Layout:

* ``engine.py`` — the inference path: jit-compiled forward step per model
  family (``tpu_dist.nn`` ResNet/ViT), a request queue with dynamic batch
  assembly into power-of-two pad-to-bucket shapes (zero steady-state
  retraces, proven by ``obs/costmodel.py::CompileWatcher``), checkpoint →
  serving-weights loading through the existing restore ladder (the
  elastic ``Remapper`` makes any training-time mesh shape loadable), and
  optional int8 weight quantization (``comm/quantize.py`` machinery).
* ``slo.py`` — the observability headline: jax-free streaming latency
  histograms (fixed log-spaced buckets, mergeable, O(1) memory),
  per-phase request latency stats, declarative SLO rules riding the
  ``obs/alerts.py`` engine, and the serve report.
* ``drill.py`` — ``make serve-drill``: deterministic request-trace replay
  proving zero post-warmup retraces, histogram invariants, and the
  ``obs compare --slo`` exit contract.
* ``__main__.py`` — ``python -m tpu_dist.serve {report,drill}``.

The jaxpr-audit rule TD114 pins the cost contract: arming every piece of
the serve telemetry/SLO machinery leaves the traced forward step
byte-identical to bare inference.
"""
