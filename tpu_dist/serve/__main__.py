"""CLI: ``python -m tpu_dist.serve`` — serving reports and the drill.

Subcommands::

    report <run.jsonl> [--format text|json]
        Offline serving SLO report from a history JSONL's ``serve``
        records (schema v10): the per-window table (requests/s, latency
        p50/p99 bounds, TTFB, availability, batch occupancy, queue
        depth), the SLO alerts that fired, and the final latency
        histogram. Exit 1 when the log holds no serve records.

    drill [--workdir DIR] [--format text|json]
        The serving proof (``serve/drill.py`` / ``make serve-drill``):
        deterministic request-trace replay — checkpoint → serving
        weights through the elastic Remapper, zero post-warmup retraces,
        histogram invariants, and the ``obs compare --slo`` exit
        contract (injected regression exits 1, an improvement exits 0).

    replica --ckpt C --workdir D [...]
        One supervised serving replica (``serve/replica.py``): restore
        ladder → warmup → paced synthetic serving with the heartbeat /
        flight-ring / exposition kit armed; SIGTERM runs the graceful
        shed→drain→sweep vacate. ``ReplicaSupervisor`` spawns these.

Exit codes: 0 ok, 1 unusable input / failed drill, 2 bad invocation.
The report path is pure file crunching — no device, no backend.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_dist.serve",
        description="serving SLO reports and the deterministic serve drill",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    r = sub.add_parser(
        "report", help="per-window serving SLO report from a --log_file JSONL"
    )
    r.add_argument("log", help="history JSONL holding serve records")
    r.add_argument("--format", choices=("text", "json"), default="text")
    d = sub.add_parser(
        "drill", help="deterministic serving drill (make serve-drill)"
    )
    d.add_argument("--workdir", default="/tmp/serve_drill")
    d.add_argument("--format", choices=("text", "json"), default="text")
    sub.add_parser(
        "replica", add_help=False,
        help="one supervised serving replica (serve/replica.py)",
    )
    args, rest = ap.parse_known_args(argv)

    if args.cmd == "replica":
        from tpu_dist.serve import replica as replica_lib

        return replica_lib.main(rest)
    if rest:
        ap.error(f"unrecognized arguments: {' '.join(rest)}")

    if args.cmd == "drill":
        from tpu_dist.serve import drill as drill_lib

        return drill_lib.main(
            ["--workdir", args.workdir, "--format", args.format]
        )

    from tpu_dist.obs.summarize import load_records
    from tpu_dist.serve import slo as slo_lib

    try:
        records, _bad = load_records(args.log)
    except OSError as e:
        print(f"tpu_dist.serve: cannot read {args.log}: {e}",
              file=sys.stderr)
        return 2
    report = slo_lib.serve_report(records)
    if not report["n_windows"]:
        print(f"tpu_dist.serve: no serve records in {args.log}",
              file=sys.stderr)
        return 1
    if args.format == "json":
        print(json.dumps(report, indent=2, default=str))
    else:
        print(slo_lib.format_report_text(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
