"""A supervised serving replica process (``python -m tpu_dist.serve
replica`` — docs/serving.md "Replica supervision").

The process :class:`~tpu_dist.serve.supervisor.ReplicaSupervisor`
spawns: it loads weights through the CRC-verified restore ladder
(:func:`~tpu_dist.serve.engine.load_serving_state` — newest→oldest,
quarantine, elastic Remapper), warms the bucket ladder, baselines the
compile watcher, and serves a paced synthetic load while arming the
full forensic kit — per-rank heartbeat (the engine pump beats it),
flight ring, OpenMetrics exposition, history JSONL — so a SIGKILL
leaves exactly the evidence ``obs postmortem`` bundles, and a SIGTERM
runs the graceful vacate: **shed → drain admitted work → final window
→ sweep heartbeat → exit 0**.

Every incarnation appends machine-readable lines to a status JSONL
(``--status_file``): a ``ready`` line carries the loaded weights'
CRC32 digest (the relaunch-restores-bit-exact proof pins two
incarnations' digests equal) and a ``serving``/``drained`` line carries
the post-warmup retrace count (the zero-retrace proof). The payloads
are deterministic per sequence number, so two incarnations serve
byte-identical work.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import zlib
from typing import Optional

import numpy as np

#: Defaults shared with serve/drill.py's miniature model so a replica
#: warms its ladder in seconds on CPU.
IMAGE_SHAPE = (16, 16, 3)
MAX_BATCH = 4


def weights_digest(params, bn_state) -> str:
    """CRC32 over every leaf's bytes in deterministic key order — the
    bit-exactness fingerprint two incarnations must share."""
    import jax

    crc = 0
    for tree in (params, bn_state):
        leaves = sorted(
            jax.tree_util.tree_flatten_with_path(tree)[0],
            key=lambda kv: jax.tree_util.keystr(kv[0]),
        )
        for path, leaf in leaves:
            arr = np.ascontiguousarray(np.asarray(leaf))
            crc = zlib.crc32(jax.tree_util.keystr(path).encode(), crc)
            crc = zlib.crc32(arr.tobytes(), crc)
    return f"{crc:08x}"


def _status(path: Optional[str], **fields) -> None:
    if not path:
        return
    fields.setdefault("ts", round(time.time(), 3))
    fields.setdefault("pid", os.getpid())
    # tpu-dist: ignore[TD002] — a replica is a single supervised process
    # writing its OWN status file (the path is per-replica, like the
    # per-rank heartbeat); there is no rank fan-out to guard against
    with open(path, "a") as f:
        f.write(json.dumps(fields) + "\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_dist.serve replica",
        description="one supervised serving replica (drill-sized model)",
    )
    ap.add_argument("--ckpt", required=True,
                    help="checkpoint file or --ckpt_dir (restore ladder)")
    ap.add_argument("--workdir", required=True,
                    help="heartbeat/ring/exposition/history live here")
    ap.add_argument("--status_file", default=None,
                    help="append ready/serving/drained JSONL lines here")
    ap.add_argument("--rank", type=int, default=0)
    ap.add_argument("--max_batch", type=int, default=MAX_BATCH)
    ap.add_argument("--deadline_ms", type=float, default=500.0)
    ap.add_argument("--max_queue", type=int, default=64)
    ap.add_argument("--serve_n", type=int, default=0,
                    help="exit 0 after N completions (0 = until SIGTERM)")
    ap.add_argument("--pace_s", type=float, default=0.0,
                    help="sleep between submits (0 = as fast as possible)")
    ap.add_argument("--window_every", type=int, default=16,
                    help="record_window every N pumps")
    ap.add_argument("--wedge_after", type=int, default=0,
                    help="TEST HOOK: stop pumping (but stay alive) after "
                         "N completions — fakes a wedged pump loop")
    args = ap.parse_args(argv)

    os.makedirs(args.workdir, exist_ok=True)
    status = args.status_file or os.path.join(
        args.workdir, "replica_status.jsonl"
    )

    from tpu_dist.metrics.history import MetricsHistory
    from tpu_dist.obs import counters as counters_lib
    from tpu_dist.obs import export as export_lib
    from tpu_dist.obs import flight as flight_lib
    from tpu_dist.obs import heartbeat as heartbeat_lib
    from tpu_dist.resilience import preemption
    from tpu_dist.serve import slo as slo_lib
    from tpu_dist.serve.drill import _drill_model
    from tpu_dist.serve.engine import ServingEngine, load_serving_state

    counters_lib.reset()
    token = preemption.install()  # SIGTERM → cooperative vacate flag
    ring = flight_lib.FlightRecorder(
        heartbeat_lib.per_rank_path(
            os.path.join(args.workdir, flight_lib.RING_NAME), args.rank
        ),
        rank=args.rank, run_id="serve-replica",
    )
    ring.install_excepthooks()
    history = MetricsHistory(
        os.path.join(args.workdir, "replica.jsonl"),
        run_id="serve-replica",
    )
    exporter = export_lib.MetricsExporter(
        textfile=heartbeat_lib.per_rank_path(
            os.path.join(args.workdir, "metrics.prom"), args.rank
        ),
        rank=args.rank,
    )

    model = _drill_model()
    loaded = load_serving_state(args.ckpt, model)
    digest = weights_digest(loaded["params"], loaded["bn_state"])
    engine = ServingEngine(
        model, loaded["params"], loaded["bn_state"],
        max_batch=args.max_batch,
        deadline_s=args.deadline_ms / 1e3,
        slo_rules=slo_lib.load_slo_rules("default"),
        history=history,
        exporter=exporter,
        heartbeat_file=os.path.join(args.workdir, "hb.json"),
        rank=args.rank,
        max_queue=args.max_queue,
    )
    compiles = engine.warmup(IMAGE_SHAPE)
    retraces_baseline = counters_lib.get("compile.retraces")
    _status(
        status, event="ready", weights_digest=digest,
        ckpt=loaded["path"], warmup_compiles=compiles,
        remapped=bool(loaded["remapped"]),
    )

    rng = np.random.default_rng(1234)
    # one deterministic payload pool reused round-robin: incarnation k
    # and incarnation k+1 serve byte-identical work
    pool = rng.standard_normal((64,) + IMAGE_SHAPE).astype(np.float32)
    served = 0
    pumps = 0
    try:
        while True:
            if preemption.requested():
                # the vacate window: refuse new work, drain what was
                # admitted, close the books, sweep the beat — exit 0
                engine.set_shedding(True, "vacate (SIGTERM)")
                engine.drain()
                scalars = engine.record_window()
                _status(
                    status, event="drained",
                    served=served,
                    retraces=counters_lib.get("compile.retraces")
                    - retraces_baseline,
                    shed=int(scalars.get("serve.shed", 0)),
                )
                return 0
            if args.serve_n and served >= args.serve_n:
                _status(
                    status, event="serving", served=served,
                    retraces=counters_lib.get("compile.retraces")
                    - retraces_baseline,
                )
                if args.wedge_after and served >= args.wedge_after:
                    # fake a wedge: alive, beating nothing, pumping
                    # nothing — the supervisor's staleness detector is
                    # what this hook exists to exercise
                    while not preemption.requested():
                        time.sleep(0.05)
                    return 0
                return 0
            engine.submit(pool[served % len(pool)], id=served)
            done = engine.pump()
            served += len(done)
            pumps += 1
            if args.window_every and pumps % args.window_every == 0:
                engine.record_window()
            if args.pace_s:
                time.sleep(args.pace_s)
    finally:
        engine.record_window()
        engine.sweep_heartbeat()
        history.close()
        ring.close()
        preemption.restore(token)
    return 0


if __name__ == "__main__":
    sys.exit(main())
