"""``make serve-drill`` — the serving proof, locally and deterministically
(``docs/serving.md``).

Replays a fixed request trace (arrival offsets baked into the trace — NO
wall clock anywhere: the engine runs on a :class:`ManualClock` that only
moves when the replay moves it, so queue waits, batch composition, and
every histogram sample are reproducible bit-for-bit) through the real
engine — real checkpoint restore (written at a simulated dp=4 ZeRO-1
training layout, loaded through the elastic ``Remapper`` onto the
1-process serving extent), real jit-compiled forward steps on the bucket
ladder, real histograms/SLO rules/history records — and asserts:

1. **Zero post-warmup retraces** (``CompileWatcher``): every batch the
   replay assembles lands on a warmed bucket shape.
2. **Histogram invariants**: bucket counts sum to ``count``, every
   phase saw exactly as many samples as completed requests, and the
   per-phase latency sums account for at most the total latency; the
   OpenMetrics histogram family round-trips through ``export.parse``.
3. **The compare --slo exit contract**: a second replay with an
   injected latency regression (the manual clock's per-reading step
   scaled up — every phase slows, exactly what a slow device looks
   like) makes ``obs compare --slo`` exit 1 against the baseline, while
   a slightly FASTER replay exits 0 — lower latency is never flagged.

Run it: ``python -m tpu_dist.serve drill --workdir /tmp/serve_drill``
(or ``python -m tpu_dist.serve.drill``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Tuple

import numpy as np

#: Replay geometry: requests arrive every 4 ms with a 10 ms extra delay
#: every 7th (bursty enough to exercise several bucket sizes), grouped
#: into 16 ms assembly ticks; one window record every 3 ticks.
TRACE_SPACING_S = 0.004
TRACE_BURST_EXTRA_S = 0.01
TICK_S = 0.016
WINDOW_TICKS = 3
N_REQUESTS = 48
IMAGE_SHAPE = (16, 16, 3)
MAX_BATCH = 8
#: Manual-clock step per reading: baseline / injected-regression /
#: improvement. The regression scales every measured phase 5× — far past
#: compare's 5% threshold; the improvement is ~20% faster and must
#: produce ZERO flagged rows (lower-latency-never-flagged).
BASE_STEP_S = 0.0005
REGRESSED_STEP_S = 0.0025
IMPROVED_STEP_S = 0.0004


class DrillError(AssertionError):
    """A drill invariant failed."""


class ManualClock:
    """Deterministic monotonic source: every reading advances the clock
    by ``auto_step_s`` (a fixed per-observation cost standing in for
    real host/device time — scale it and every measured phase scales
    with it), and the replay :meth:`advance_to`\\s arrival boundaries."""

    def __init__(self, auto_step_s: float = 0.0):
        self.t = 0.0
        self.auto_step_s = auto_step_s
        self.readings = 0

    def __call__(self) -> float:
        self.t += self.auto_step_s
        self.readings += 1
        return self.t

    def advance_to(self, t: float) -> None:
        self.t = max(self.t, t)


def _drill_model():
    """A narrow ResNet (identical code path to ``resnet18``, miniature
    widths so the CPU drill compiles its bucket ladder in seconds)."""
    from tpu_dist.nn.resnet import ResNetDef

    return ResNetDef("basic", (1, 1, 1, 1), num_classes=10,
                     widths=(8, 8, 16, 16))


def default_trace(n: int = N_REQUESTS) -> List[float]:
    """The deterministic arrival offsets (seconds)."""
    return [
        round(TRACE_SPACING_S * i
              + (TRACE_BURST_EXTRA_S if i % 7 == 0 else 0.0), 6)
        for i in range(n)
    ]


def write_training_ckpt(ckpt_dir: str, model, *, dp: int = 4) -> dict:
    """Write the checkpoint a ZeRO-1 training run at ``dp`` would leave
    behind: params/bn from a deterministic init, ONE flat momentum
    vector padded to ``dp`` shards (nonzero logical prefix, zero pad
    tail — the elastic layout contract), and the ``elastic`` stamp.
    Returns the init'd trees so the drill can assert bit-exactness."""
    import jax

    from tpu_dist import ckpt as ckpt_lib
    from tpu_dist.comm.quantize import padded_len
    from tpu_dist.elastic.remap import elastic_stamp, params_len
    from tpu_dist.train.state import TrainState

    params, bn_state = model.init(jax.random.PRNGKey(7))
    L = params_len(params)
    mom = np.zeros((padded_len(L, dp),), np.float32)
    mom[:L] = np.arange(1, L + 1, dtype=np.float32) % 17 * 0.01
    state = TrainState(
        params=params, bn_state=bn_state, opt_state=mom,
        step=np.asarray(120, np.int32),
    )
    path = ckpt_lib.save(
        ckpt_dir, state, epoch=3,
        extra_meta={"elastic": elastic_stamp(dp, dp, L)},
    )
    return {"params": params, "bn_state": bn_state, "momentum": mom,
            "L": L, "path": path}


def replay(
    workdir: str,
    name: str,
    model,
    weights: dict,
    *,
    auto_step_s: float,
    trace: Optional[List[float]] = None,
) -> dict:
    """One deterministic replay → ``{log, stats, engine scalars}``. The
    counter registry is reset first (each replay is its own run — its
    retrace count must start clean)."""
    from tpu_dist.metrics.history import MetricsHistory
    from tpu_dist.obs import counters as counters_lib
    from tpu_dist.serve import slo as slo_lib
    from tpu_dist.serve.engine import ServingEngine

    counters_lib.reset()
    trace = trace if trace is not None else default_trace()
    rng = np.random.default_rng(42)  # one payload set per replay, fixed
    payloads = rng.standard_normal(
        (len(trace),) + IMAGE_SHAPE
    ).astype(np.float32)
    clock = ManualClock(auto_step_s=auto_step_s)
    log_path = os.path.join(workdir, f"{name}.jsonl")
    history = MetricsHistory(log_path, run_id=f"serve-drill-{name}")
    engine = ServingEngine(
        model, weights["params"], weights["bn_state"],
        max_batch=MAX_BATCH,
        deadline_s=0.25,
        slo_rules=slo_lib.load_slo_rules("default"),
        history=history,
        clock=clock,
    )
    engine.warmup(IMAGE_SHAPE)
    done = []
    n_ticks = int(max(trace) // TICK_S) + 1
    i = 0
    for tick in range(n_ticks):
        window_end = (tick + 1) * TICK_S
        while i < len(trace) and trace[i] < window_end:
            engine.submit(payloads[i], id=i, arrival_s=trace[i])
            i += 1
        clock.advance_to(window_end)
        done.extend(engine.pump())
        if (tick + 1) % WINDOW_TICKS == 0:
            engine.record_window()
    done.extend(engine.drain())
    scalars = engine.record_window()
    history.close()
    for r in done:
        if r.result is None or r.result.shape != (10,) or not np.all(
            np.isfinite(r.result)
        ):
            raise DrillError(f"request {r.id}: bad result {r.result!r}")
    return {
        "log": log_path,
        "engine": engine,
        "stats": engine.stats,
        "scalars": scalars,
        "completed": len(done),
        "retraces": counters_lib.get("compile.retraces"),
    }


def run_drill(workdir: str, fmt: str = "text") -> dict:
    """The whole proof; raises :class:`DrillError` on any broken
    invariant, returns the summary dict."""
    from tpu_dist.obs import __main__ as obs_main
    from tpu_dist.obs import export as export_lib
    from tpu_dist.serve import slo as slo_lib
    from tpu_dist.serve.engine import load_serving_state

    os.makedirs(workdir, exist_ok=True)
    model = _drill_model()

    # -- phase 1: checkpoint → serving weights through the Remapper ---------
    ckpt_dir = os.path.join(workdir, "ckpt")
    saved = write_training_ckpt(ckpt_dir, model, dp=4)
    loaded = load_serving_state(ckpt_dir, model)
    if not any(kind == "zero1_flat" for _, kind in loaded["remapped"]):
        raise DrillError(
            "the dp=4 ZeRO-1 checkpoint restored without engaging the "
            f"elastic Remapper (remapped={loaded['remapped']})"
        )
    import jax

    for key, a, b in zip(
        ("params",), (saved["params"],), (loaded["params"],)
    ):
        for (pa, la) in zip(
            jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
        ):
            if not np.array_equal(np.asarray(pa), np.asarray(la)):
                raise DrillError(f"{key} changed across the restore")

    # -- phase 2: baseline replay + invariants ------------------------------
    base = replay(workdir, "baseline", model, loaded,
                  auto_step_s=BASE_STEP_S)
    if base["retraces"]:
        raise DrillError(
            f"{base['retraces']:g} post-warmup retrace(s) — the bucket "
            "ladder leaked a shape"
        )
    if base["completed"] != N_REQUESTS:
        raise DrillError(
            f"completed {base['completed']}/{N_REQUESTS} requests"
        )
    probs = base["stats"].check_invariants()
    if probs:
        raise DrillError("histogram invariants broken: " + "; ".join(probs))
    # the exposition histogram grammar round-trips
    expo = export_lib.render(
        {}, histograms=base["stats"].histogram_families()
    )
    parsed = export_lib.parse(expo)
    count_key = export_lib.metric_name("serve.latency_seconds") + "_count"
    if parsed.get(count_key) != base["stats"].total.count:
        raise DrillError(
            f"exposition round-trip lost the histogram count "
            f"({parsed.get(count_key)} vs {base['stats'].total.count})"
        )

    # -- phase 3: injected regression / improvement → compare --slo ---------
    reg = replay(workdir, "regressed", model, loaded,
                 auto_step_s=REGRESSED_STEP_S)
    imp = replay(workdir, "improved", model, loaded,
                 auto_step_s=IMPROVED_STEP_S)
    rc_reg = obs_main.main(["compare", base["log"], reg["log"], "--slo"])
    if rc_reg != 1:
        raise DrillError(
            f"obs compare --slo exited {rc_reg} on the injected latency "
            "regression (want 1)"
        )
    rc_imp = obs_main.main(["compare", base["log"], imp["log"], "--slo"])
    if rc_imp != 0:
        raise DrillError(
            f"obs compare --slo exited {rc_imp} on a faster candidate "
            "(want 0 — lower latency is never flagged)"
        )

    # -- report -------------------------------------------------------------
    from tpu_dist.obs.summarize import load_records

    records, _ = load_records(base["log"])
    report = slo_lib.serve_report(records)
    summary = {
        "workdir": workdir,
        "ckpt": loaded["path"],
        "remapped": loaded["remapped"],
        "requests": N_REQUESTS,
        "retraces_post_warmup": base["retraces"],
        "windows": report["n_windows"],
        "baseline": {
            k: base["scalars"].get(k)
            for k in ("serve.requests_per_s", "serve.latency_p50_ms",
                      "serve.latency_p99_ms", "serve.availability",
                      "serve.batch_occupancy")
        },
        "compare_slo": {"regression_rc": rc_reg, "improvement_rc": rc_imp},
    }
    if fmt == "json":
        print(json.dumps(summary, indent=2, default=str))
    else:
        print(slo_lib.format_report_text(report))
        print(
            f"serve-drill OK: {N_REQUESTS} requests, 0 post-warmup "
            f"retraces, histogram invariants hold, compare --slo "
            f"regression→{rc_reg} improvement→{rc_imp}"
        )
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_dist.serve.drill",
        description="deterministic serving drill: trace replay, retrace-"
                    "freedom, histogram invariants, compare --slo gate",
    )
    ap.add_argument("--workdir", default="/tmp/serve_drill")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)
    try:
        run_drill(args.workdir, fmt=args.format)
    except DrillError as e:
        print(f"serve-drill FAILED: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
