"""Serving SLO layer — streaming latency histograms, per-phase request
stats, and declarative SLO rules (``docs/serving.md``).

Everything here is **jax-free** on purpose: the stats are written from
the engine's host-side pump loop and read back by the exporter's HTTP
thread, the history writer, and offline tooling — none of which may
touch a backend. The jaxpr-audit rule TD114 pins the other half of the
contract: arming all of it leaves the traced forward step byte-identical
to bare inference.

Histograms: fixed **log-spaced buckets** (:data:`DEFAULT_EDGES`, 0.1 ms
→ ~3.5 min in powers of two), NOT a sample list — ``observe`` is one
bisect + increment, memory is O(buckets) however many requests flow
through, and two histograms (different ranks, resumed segments) merge by
elementwise addition. Quantiles come back as **upper bounds** (the upper
edge of the bucket holding the q-th sample): a latency SLO wants the
conservative direction, and the bound is at most one bucket (2×) off.
The same bucket layout renders as an OpenMetrics ``histogram`` family
(``_bucket{le=...}`` / ``_sum`` / ``_count`` — ``obs/export.py``), so a
Prometheus scraping the run computes real ``histogram_quantile()``s.

SLO rules are :class:`~tpu_dist.obs.alerts.AlertRule`\\s over the
``serve.*`` metric namespace, evaluated per window by the PR 7
:class:`~tpu_dist.obs.alerts.AlertEngine` (sustain / cooldown / delta
semantics unchanged) — a breached p99 ceiling fires an ``alert`` history
record and an ``alert_active`` exposition gauge exactly like a training
stall does. ``--slo_rules default`` loads :data:`SLO_BUILTINS`; a
``.toml``/``.json`` spec uses the ``[[rule]]`` grammar from
``obs/alerts.py`` with the serve builtins available to ``builtin =``.
"""

from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Sequence, Tuple

from tpu_dist.obs import alerts as alerts_lib
from tpu_dist.obs import counters as counters_lib

#: Fixed log-spaced bucket edges (seconds): 0.1 ms → ~209 s in powers of
#: two. One shared layout so histograms merge across ranks/segments by
#: construction; 22 buckets + overflow keeps a full phase set under 1 KB.
DEFAULT_EDGES: Tuple[float, ...] = tuple(1e-4 * 2 ** i for i in range(22))

#: Request phases, in pipeline order. ``queue_wait`` is per-request
#: (arrival → its batch starts assembling); the rest are measured at
#: batch grain and attributed to every request the batch carried.
PHASES: Tuple[str, ...] = (
    "queue_wait", "batch_assembly", "dispatch", "device", "fetch",
)


class LatencyHistogram:
    """Streaming log-bucketed histogram: O(1) observe, O(buckets) memory,
    mergeable, exact ``sum``/``count``/``min``/``max`` alongside the
    bucketed distribution."""

    __slots__ = ("edges", "counts", "sum", "count", "min", "max")

    def __init__(self, edges: Sequence[float] = DEFAULT_EDGES):
        if list(edges) != sorted(set(edges)):
            raise ValueError("histogram edges must be strictly increasing")
        self.edges: Tuple[float, ...] = tuple(float(e) for e in edges)
        self.counts: List[int] = [0] * (len(self.edges) + 1)  # + overflow
        self.sum = 0.0
        self.count = 0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, seconds: float) -> None:
        v = float(seconds)
        # OpenMetrics bucket semantics: bucket le=edge counts v <= edge
        self.counts[bisect.bisect_left(self.edges, v)] += 1
        self.sum += v
        self.count += 1
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    def merge(self, other: "LatencyHistogram") -> None:
        """Fold ``other`` in (cross-rank / cross-segment aggregation).
        Refuses mismatched bucket layouts — a silent re-bucketing would
        fabricate a distribution."""
        if other.edges != self.edges:
            raise ValueError(
                f"cannot merge histograms with different bucket layouts "
                f"({len(other.edges)} vs {len(self.edges)} edges)"
            )
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.sum += other.sum
        self.count += other.count
        for attr, pick in (("min", min), ("max", max)):
            o = getattr(other, attr)
            if o is not None:
                s = getattr(self, attr)
                setattr(self, attr, o if s is None else pick(s, o))

    def quantile_bound(self, q: float) -> Optional[float]:
        """Upper bound on the q-quantile: the upper edge of the bucket
        holding the ⌈q·count⌉-th sample (the exact ``max`` for the
        overflow bucket). None while empty. Conservative by design —
        an SLO ceiling compared against this can under-alarm by at most
        one bucket width, never over-report a healthy run."""
        if not self.count:
            return None
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        target = max(1, -(-int(self.count * q * 1e9) // int(1e9)))  # ceil
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= target:
                return self.edges[i] if i < len(self.edges) else self.max
        return self.max  # unreachable with consistent counts

    # -- serialization ------------------------------------------------------

    def to_dict(self) -> dict:
        """Compact history-record form (non-zero buckets only — a quiet
        phase costs a few bytes per record, not 23 zeros)."""
        return {
            "edges": len(self.edges),
            "buckets": {
                str(i): c for i, c in enumerate(self.counts) if c
            },
            "sum": round(self.sum, 9),
            "count": self.count,
            "min": self.min,
            "max": self.max,
        }

    @classmethod
    def from_dict(cls, d: dict, edges: Sequence[float] = DEFAULT_EDGES) -> "LatencyHistogram":
        if int(d.get("edges", len(DEFAULT_EDGES))) != len(edges):
            raise ValueError(
                f"serialized histogram has {d.get('edges')} edges, "
                f"reader expects {len(edges)}"
            )
        h = cls(edges)
        for i, c in (d.get("buckets") or {}).items():
            i = int(i)
            if not 0 <= i < len(h.counts):
                # a corrupt/foreign record must not write past the bucket
                # array — or silently into the overflow bucket via a
                # negative index, fabricating a distribution
                raise ValueError(
                    f"serialized histogram bucket index {i} out of range "
                    f"(0..{len(h.counts) - 1})"
                )
            h.counts[i] = int(c)
        h.sum = float(d.get("sum", 0.0))
        h.count = int(d.get("count", 0))
        h.min = d.get("min")
        h.max = d.get("max")
        return h

    def to_openmetrics(self) -> dict:
        """The shape ``export.render(histograms=...)`` consumes:
        cumulative ``(le, count)`` pairs (ending with ``+Inf``) plus
        ``sum``/``count`` — the OpenMetrics ``histogram`` family."""
        buckets = []
        cum = 0
        for edge, c in zip(self.edges, self.counts):
            cum += c
            buckets.append((format(edge, ".6g"), cum))
        buckets.append(("+Inf", self.count))
        return {"buckets": buckets, "sum": self.sum, "count": self.count}


class ServeStats:
    """The engine's per-process serving stats: one total-latency and one
    TTFB histogram, one histogram per phase, queue/batch gauges, and the
    availability ledger. Host arithmetic only — the pump loop writes it,
    :meth:`publish` mirrors the scalars into the counter/gauge registry
    so history records and OpenMetrics expositions carry them for free.

    ``deadline_s`` arms goodput-style availability: a request is GOOD
    when its total latency meets the deadline; ``availability`` is
    good/completed. Without a deadline every completed request is good
    (availability measures completion only).
    """

    def __init__(self, deadline_s: Optional[float] = None,
                 edges: Sequence[float] = DEFAULT_EDGES):
        self.deadline_s = deadline_s
        self.total = LatencyHistogram(edges)
        self.ttfb = LatencyHistogram(edges)
        self.phases: Dict[str, LatencyHistogram] = {
            p: LatencyHistogram(edges) for p in PHASES
        }
        self.submitted = 0
        self.completed = 0
        self.good = 0          # met the deadline (or all, without one)
        self.shed = 0          # refused at admission (load shedding)
        self.batches = 0
        self.padded_slots = 0  # bucket slots carrying padding, summed
        self.occupancy_sum = 0.0  # Σ real/bucket per batch
        self.queue_depth = 0
        self.queue_depth_max = 0

    # -- writes (engine pump loop) ------------------------------------------

    def on_submit(self, depth: int) -> None:
        self.submitted += 1
        self.set_queue_depth(depth)

    def on_shed(self, depth: int) -> None:
        """One request refused at admission (vacate-window shedding or a
        queue-depth cap). Shed requests never enter ``submitted`` — the
        latency histograms and availability describe ADMITTED work only,
        so shedding degrades the ``serve.shed`` counter, not the p99."""
        self.shed += 1
        self.set_queue_depth(depth)

    def set_queue_depth(self, depth: int) -> None:
        self.queue_depth = depth
        self.queue_depth_max = max(self.queue_depth_max, depth)

    def on_batch(self, n_real: int, bucket: int) -> None:
        self.batches += 1
        self.padded_slots += bucket - n_real
        self.occupancy_sum += n_real / bucket

    def on_request_done(
        self, total_s: float, ttfb_s: float, phase_s: Dict[str, float]
    ) -> None:
        self.total.observe(total_s)
        self.ttfb.observe(ttfb_s)
        for p in PHASES:
            self.phases[p].observe(phase_s.get(p, 0.0))
        self.completed += 1
        if self.deadline_s is None or total_s <= self.deadline_s:
            self.good += 1

    # -- reads --------------------------------------------------------------

    def batch_occupancy(self) -> Optional[float]:
        return self.occupancy_sum / self.batches if self.batches else None

    def availability(self) -> Optional[float]:
        return self.good / self.completed if self.completed else None

    def scalars(self, window_s: Optional[float] = None,
                completed_in_window: Optional[int] = None) -> Dict[str, float]:
        """One flat ``serve.*`` metrics window — what the SLO alert
        engine observes and :meth:`publish` mirrors into the registry.
        Quantiles are :meth:`LatencyHistogram.quantile_bound` upper
        bounds in milliseconds."""
        out: Dict[str, float] = {
            "serve.requests": self.submitted,
            "serve.completed": self.completed,
            "serve.shed": self.shed,
            "serve.batches": self.batches,
            "serve.queue_depth": self.queue_depth,
            "serve.queue_depth_max": self.queue_depth_max,
        }

        def put(name, v, scale=1.0, digits=6):
            if isinstance(v, (int, float)):
                out[name] = round(v * scale, digits)

        put("serve.latency_p50_ms", self.total.quantile_bound(0.5), 1e3)
        put("serve.latency_p95_ms", self.total.quantile_bound(0.95), 1e3)
        put("serve.latency_p99_ms", self.total.quantile_bound(0.99), 1e3)
        put("serve.ttfb_p50_ms", self.ttfb.quantile_bound(0.5), 1e3)
        put("serve.ttfb_p99_ms", self.ttfb.quantile_bound(0.99), 1e3)
        put("serve.availability", self.availability())
        put("serve.batch_occupancy", self.batch_occupancy())
        if window_s and window_s > 0 and completed_in_window is not None:
            put("serve.requests_per_s", completed_in_window / window_s, 1.0, 3)
        return out

    def publish(self, scalars: Optional[Dict[str, float]] = None) -> Dict[str, float]:
        """Mirror the scalar view into the process-global registry: every
        later history record and OpenMetrics exposition carries the
        ``serve.*`` gauges with no per-metric plumbing."""
        scalars = scalars if scalars is not None else self.scalars()
        for name, v in scalars.items():
            counters_lib.set_gauge(name, v)
        return scalars

    def histogram_families(self) -> Dict[str, dict]:
        """The exposition histogram families (total + TTFB + per-phase),
        keyed by raw registry-style names — feed straight into
        ``export.render(histograms=...)``."""
        fams = {
            "serve.latency_seconds": self.total.to_openmetrics(),
            "serve.ttfb_seconds": self.ttfb.to_openmetrics(),
        }
        for p, h in self.phases.items():
            fams[f"serve.phase_{p}_seconds"] = h.to_openmetrics()
        return fams

    def check_invariants(self) -> List[str]:
        """The drill/test invariants; returns the violations (empty =
        healthy). (1) sum-to-count: every histogram's bucket counts sum
        to its ``count``, and every phase (and TTFB) saw exactly as many
        samples as the total. (2) phase latencies account for at most
        the total latency (queue→fetch partitions the request's life;
        float addition slack only)."""
        probs: List[str] = []
        for name, h in (
            [("total", self.total), ("ttfb", self.ttfb)]
            + list(self.phases.items())
        ):
            if sum(h.counts) != h.count:
                probs.append(
                    f"{name}: bucket counts sum to {sum(h.counts)}, "
                    f"count says {h.count}"
                )
            if h.count != self.total.count:
                probs.append(
                    f"{name}: {h.count} sample(s) vs {self.total.count} "
                    "completed requests"
                )
        if self.total.count != self.completed:
            probs.append(
                f"total histogram holds {self.total.count} sample(s), "
                f"{self.completed} requests completed"
            )
        phase_sum = sum(h.sum for h in self.phases.values())
        if phase_sum > self.total.sum + 1e-6 * max(1.0, self.total.sum):
            probs.append(
                f"phase latency sum {phase_sum:.6f}s exceeds total "
                f"latency sum {self.total.sum:.6f}s"
            )
        return probs


# -- SLO rules ---------------------------------------------------------------

#: The built-in serving SLO library (``--slo_rules default``): ceilings a
#: production endpoint wants armed. Thresholds are deliberately loose —
#: a real deployment overrides them from a spec; the POINT is that a
#: breach fires through the same alert engine / history / exposition
#: path a training stall does.
SLO_BUILTINS: Dict[str, alerts_lib.AlertRule] = {
    r.name: r
    for r in (
        alerts_lib.AlertRule("slo_p99_high", "serve.latency_p99_ms", ">",
                             500.0, sustain=2, cooldown=3),
        alerts_lib.AlertRule("slo_p50_high", "serve.latency_p50_ms", ">",
                             100.0, sustain=2, cooldown=3),
        alerts_lib.AlertRule("slo_ttfb_high", "serve.ttfb_p99_ms", ">",
                             250.0, sustain=2, cooldown=3),
        alerts_lib.AlertRule("slo_availability_low", "serve.availability",
                             "<", 0.999, sustain=1, cooldown=3),
        alerts_lib.AlertRule("slo_rps_low", "serve.requests_per_s", "<",
                             1.0, sustain=2, cooldown=3),
        alerts_lib.AlertRule("slo_queue_deep", "serve.queue_depth", ">",
                             64.0, sustain=2, cooldown=3),
        # a mid-serve retrace is a full XLA compile stall on the serving
        # path: ANY growth of the watcher's counter is alertable
        alerts_lib.AlertRule("serve_retrace", "compile.retraces", ">",
                             0.0, sustain=1, cooldown=1, delta=True),
    )
}


def load_slo_rules(spec: str) -> List[alerts_lib.AlertRule]:
    """``--slo_rules`` → validated rule list. ``default`` loads
    :data:`SLO_BUILTINS`; otherwise the value is a ``.toml``/``.json``
    path in the ``[[rule]]`` grammar of ``obs/alerts.py``, with both the
    training and serving builtin libraries available to ``builtin =``."""
    if spec in ("default", "builtin"):
        return list(SLO_BUILTINS.values())
    return alerts_lib.load_rules(
        spec, builtins={**alerts_lib.BUILTIN_RULES, **SLO_BUILTINS}
    )


def make_slo_engine(rules: List[alerts_lib.AlertRule]) -> alerts_lib.AlertEngine:
    """The PR 7 alert engine over the serve windows; delta rules seeded
    immediately (a serving process has no fit() start to seed from)."""
    eng = alerts_lib.AlertEngine(rules)
    eng.seed_deltas(counters_lib.snapshot())
    return eng


# -- offline serve report (``python -m tpu_dist.serve report``) --------------


def serve_report(records: List[dict]) -> dict:
    """Fold a history JSONL's ``serve`` records (schema v10) into one
    report: the window table, last-window scalars, and the alerts that
    fired on serve metrics. Jax-free file crunching."""
    windows = [
        r for r in records
        if r.get("kind") == "serve" and not r.get("event")
    ]
    alerts = [
        r for r in records
        if r.get("kind") == "alert"
        and str(r.get("metric", "")).startswith("serve.")
    ]
    last = windows[-1] if windows else {}
    total = LatencyHistogram()
    for w in windows:
        h = w.get("latency_hist")
        if isinstance(h, dict):
            try:
                # windows carry CUMULATIVE histograms: the last parseable
                # one IS the run's distribution (no merge — merging
                # cumulative snapshots would multiply-count)
                total = LatencyHistogram.from_dict(h)
            except (ValueError, TypeError, KeyError):
                continue
    return {
        "n_windows": len(windows),
        "windows": windows,
        "alerts": alerts,
        "last": {
            k: last.get(k)
            for k in ("requests", "completed", "requests_per_s",
                      "latency_p50_ms", "latency_p95_ms", "latency_p99_ms",
                      "ttfb_p50_ms", "ttfb_p99_ms", "availability",
                      "batch_occupancy", "queue_depth_max", "retraces")
            if last.get(k) is not None
        },
        "latency_hist": total.to_dict() if total.count else None,
    }


def window_table_lines(windows: List[dict]) -> List[str]:
    """The serve-window table (header + one row per window + retrace
    warning sublines) — ONE renderer shared by the offline serve report
    (:func:`format_report_text`) and ``obs summarize``, so the two
    views can never drift column by column (the
    ``postmortem.rank_summary`` discipline)."""
    lines = [
        f"{'window':>7} {'req/s':>8} {'p50_ms':>8} {'p99_ms':>8} "
        f"{'ttfb99':>8} {'avail':>7} {'occup':>6} {'queue':>6} {'compl':>6}"
    ]

    def fmt(v, spec, width):
        return (format(v, spec) if isinstance(v, (int, float)) else "-").rjust(width)

    for i, w in enumerate(windows):
        lines.append(
            f"{i:>7} {fmt(w.get('requests_per_s'), '.1f', 8)} "
            f"{fmt(w.get('latency_p50_ms'), '.2f', 8)} "
            f"{fmt(w.get('latency_p99_ms'), '.2f', 8)} "
            f"{fmt(w.get('ttfb_p99_ms'), '.2f', 8)} "
            f"{fmt(w.get('availability'), '.3f', 7)} "
            f"{fmt(w.get('batch_occupancy'), '.2f', 6)} "
            f"{fmt(w.get('queue_depth_max'), 'd', 6)} "
            f"{fmt(w.get('completed'), 'd', 6)}"
        )
        if w.get("retraces"):
            lines.append(
                f"      WARNING: {w['retraces']:g} mid-serve retrace(s) "
                "— a batch escaped the bucket ladder"
            )
    return lines


def format_report_text(report: dict) -> str:
    lines = [
        f"serve report — {report['n_windows']} window(s), "
        f"{len(report['alerts'])} SLO alert(s)"
    ]
    if not report["n_windows"]:
        return lines[0] + " (no serve records — not a serving log?)"
    lines.extend(window_table_lines(report["windows"]))
    for a in report["alerts"]:
        lines.append(
            f"  SLO ALERT {a.get('rule')}: {a.get('metric')} "
            f"{a.get('value')} {a.get('op')} {a.get('threshold')} "
            f"(sustained {a.get('sustained')} window(s))"
        )
    last = report.get("last") or {}
    if last:
        lines.append(
            "final: "
            + ", ".join(f"{k}={v}" for k, v in sorted(last.items()))
        )
    return "\n".join(lines)
