"""Replica supervision for the serving tier (docs/serving.md
"Replica supervision").

A serving replica that dies takes availability with it, and one that
*wedges* — process alive, pump loop stuck — is worse: it looks healthy
to a process-table check while its queue explodes. Training already
solved both problems (the launcher watchdog + ``elastic/supervisor.py``);
this module is the ``serve`` analog, deliberately the same shape:

* **Crash detection** — the supervisor owns the replica process handle
  and polls its exit status. A non-zero exit (a SIGKILL shows as
  ``-9``) is a crash: the evidence directories are postmortem-bundled
  through the existing flight/verdict machinery (``obs/postmortem.py``)
  BEFORE the relaunch overwrites anything, then the replica is
  respawned after the deterministic ``resilience/retry.py`` backoff,
  bounded by ``max_restarts`` — a crash loop burns its budget and
  surfaces instead of cycling forever.
* **Wedge detection** — the replica's pump loop beats the same per-rank
  heartbeat file the trainer does (``ServingEngine(heartbeat_file=...)``
  arms it). A beat older than ``stale_after_s`` on a live process is a
  wedge: the supervisor escalates SIGTERM → (grace) → SIGKILL —
  the launcher-watchdog discipline — bundles, and relaunches. An
  ABSENT beat is a clean-exit signal, never a wedge verdict.
* **Restore, not re-init** — the relaunched replica loads its weights
  through the CRC-verified restore ladder (``load_serving_state``:
  newest→oldest, quarantine on corruption, elastic Remapper), re-warms
  its bucket ladder, and re-baselines the compile watcher — so the
  relaunch serves the SAME bits with zero post-warmup retraces, which
  the tenancy drill proves rather than asserts.
* **Graceful degradation** — the replica entrypoint arms
  ``ServingEngine.set_shedding`` during its vacate window (SIGTERM →
  shed → drain admitted work → sweep heartbeat → exit 0), so a
  supervised shutdown refuses new work instead of queue-exploding.

Stdlib-only (no jax): the supervisor runs wherever the replica's
artifact files are visible, exactly like the fleet scheduler. The spawn
function and every clock are injectable — the unit tests and the drill
drive the whole state machine deterministically.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Optional

from tpu_dist.obs import counters as counters_lib
from tpu_dist.resilience.retry import backoff_delays


@dataclasses.dataclass(frozen=True)
class ReplicaPolicy:
    """Supervision thresholds. ``stale_after_s`` matches the fleet
    scheduler's STALE_AFTER_S default so one number means "dead"
    pod-wide; ``warmup_grace_s`` covers the replica's compile warmup,
    during which no beat has landed yet and a wedge verdict would be
    premature."""

    max_restarts: int = 3
    stale_after_s: float = 60.0
    warmup_grace_s: float = 120.0
    term_grace_s: float = 5.0
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0

    def __post_init__(self):
        if self.max_restarts < 0:
            raise ValueError("max_restarts must be >= 0")
        if min(self.stale_after_s, self.warmup_grace_s,
               self.term_grace_s) < 0:
            raise ValueError("grace windows must be >= 0")


class ReplicaSupervisor:
    """Supervise ONE serving replica process: crash/wedge detection,
    postmortem bundling, bounded auto-relaunch.

    ``spawn`` is ``(incarnation: int) -> handle`` where the handle is
    ``subprocess.Popen``-compatible (``poll() -> Optional[int]``,
    ``terminate()``, ``kill()``, ``pid``) — production passes a real
    Popen factory, the tests a deterministic fake. ``heartbeat_file``
    is the replica's rank-0 beat path (the replica itself derives
    per-rank names); ``postmortem_dirs`` are scanned by the bundle
    assembler on every crash/wedge. ``now``/``sleep`` are injectable
    for deterministic drills (``now`` must be the wall clock the
    heartbeat ``ts`` field is stamped on)."""

    def __init__(
        self,
        spawn: Callable[[int], object],
        *,
        heartbeat_file: Optional[str] = None,
        policy: Optional[ReplicaPolicy] = None,
        postmortem_dirs: Optional[List[str]] = None,
        now: Callable[[], float] = time.time,
        sleep: Callable[[float], None] = time.sleep,
        on_event: Optional[Callable[[dict], None]] = None,
        capacity_file: Optional[str] = None,
    ):
        self._spawn = spawn
        self.heartbeat_file = heartbeat_file
        self.policy = policy or ReplicaPolicy()
        self.postmortem_dirs = list(postmortem_dirs or [])
        # the replica's allocation file (fleet multi-tenancy): when the
        # fleet arbiter granted/grew this run, the file carries the
        # decision metadata tokens — every spawn names the arbitration
        # that shaped its capacity (schema v15 causal tracing)
        self.capacity_file = capacity_file
        self._now = now
        self._sleep = sleep
        self._on_event = on_event
        self._delays = backoff_delays(
            self.policy.max_restarts,
            self.policy.backoff_base_s,
            self.policy.backoff_max_s,
        )
        self.proc = None
        self.incarnation = 0
        self.restarts = 0
        self.done = False           # clean exit observed — supervision over
        self.gave_up = False        # restart budget exhausted
        self.last_rc: Optional[int] = None
        self.events: List[dict] = []
        self._spawned_at: Optional[float] = None
        self._beat_seen = False

    # -- events --------------------------------------------------------------

    def _event(self, kind: str, **extra) -> dict:
        ev = {"event": kind, "incarnation": self.incarnation, **extra}
        self.events.append(ev)
        if self._on_event is not None:
            self._on_event(ev)
        return ev

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """Spawn the first incarnation (idempotent)."""
        if self.proc is None and not self.done and not self.gave_up:
            self._launch()

    def _launch(self) -> None:
        self.incarnation += 1
        self.proc = self._spawn(self.incarnation)
        self._spawned_at = self._now()
        self._beat_seen = False
        counters_lib.inc("serve.replica_spawns")
        ev: dict = {"pid": getattr(self.proc, "pid", None)}
        if self.capacity_file:
            # recipient-side causal tracing: the grant/grow that sized
            # this replica rides the allocation file's metadata tokens —
            # stamp it so the event stream joins the scheduler's chain
            from tpu_dist.elastic.supervisor import read_decision

            meta = read_decision(self.capacity_file)
            if meta.get("decision_id") is not None:
                ev["decision_id"] = meta["decision_id"]
                ev["decision_cause"] = meta.get("cause")
        self._event("spawn", **ev)

    def _bundle(self, verdict_hint: str) -> Optional[str]:
        """Postmortem-bundle the evidence dirs through the existing
        flight/verdict machinery BEFORE a relaunch can overwrite them.
        Best-effort: a failed bundle must never block the relaunch."""
        if not self.postmortem_dirs:
            return None
        try:
            from tpu_dist.obs import postmortem as postmortem_lib

            report, bundle = postmortem_lib.run_postmortem(
                self.postmortem_dirs, annotate=True
            )
        except Exception as e:  # noqa: BLE001 — forensics never kill serving
            self._event("bundle_failed", error=repr(e), hint=verdict_hint)
            return None
        if bundle:
            counters_lib.inc("serve.replica_postmortems")
            self._event(
                "postmortem", bundle=bundle, hint=verdict_hint,
                n_ranks=report.get("n_ranks"),
            )
        return bundle

    def _relaunch_or_give_up(self, why: str) -> None:
        if self.restarts >= self.policy.max_restarts:
            self.gave_up = True
            self.proc = None
            counters_lib.inc("serve.replica_gave_up")
            self._event("gave_up", why=why, restarts=self.restarts)
            return
        delay = self._delays[self.restarts] if self._delays else 0.0
        self.restarts += 1
        counters_lib.inc("serve.replica_restarts")
        self._event("relaunch", why=why, restart=self.restarts,
                    backoff_s=delay)
        if delay:
            self._sleep(delay)
        self._launch()

    def _wedged(self) -> bool:
        """A live process whose beat went stale. Absent beat: only the
        warmup grace applies (the replica may still be compiling); once
        a beat has been SEEN, absence reads as a clean-exit sweep in
        progress, not a wedge."""
        if self.heartbeat_file is None:
            return False
        from tpu_dist.obs import heartbeat as heartbeat_lib

        rec = heartbeat_lib.read(self.heartbeat_file)
        now = self._now()
        if rec is None:
            if self._beat_seen:
                return False
            started = self._spawned_at if self._spawned_at is not None else now
            return now - started > self.policy.warmup_grace_s
        self._beat_seen = True
        ts = rec.get("ts")
        if not isinstance(ts, (int, float)) or isinstance(ts, bool):
            # garbage beat: unreadable == stale (the read_signals rule)
            return True
        return now - float(ts) > self.policy.stale_after_s

    def _escalate(self) -> int:
        """SIGTERM → grace → SIGKILL a wedged replica; returns the exit
        status. The grace loop runs on the injectable clock so a drill
        can escalate instantly."""
        self.proc.terminate()
        deadline = self._now() + self.policy.term_grace_s
        while self._now() < deadline:
            rc = self.proc.poll()
            if rc is not None:
                return rc
            self._sleep(min(0.05, self.policy.term_grace_s or 0.05))
        self.proc.kill()
        while True:
            rc = self.proc.poll()
            if rc is not None:
                return rc
            self._sleep(0.05)

    def poll_once(self) -> Optional[str]:
        """One supervision step. Returns the event kind that fired
        (``"exit"``, ``"crash"``, ``"wedge"``, ``"gave_up"``) or None
        when the replica is simply healthy. Drive it from any loop —
        :meth:`run` is the batteries-included one."""
        if self.done or self.gave_up:
            return None
        if self.proc is None:
            self._launch()
            return None
        rc = self.proc.poll()
        if rc is not None:
            self.last_rc = rc
            if rc == 0:
                self.done = True
                self.proc = None
                self._event("exit", rc=0)
                return "exit"
            counters_lib.inc("serve.replica_crashes")
            self._event("crash", rc=rc)
            self._bundle(f"replica exit {rc}")
            self._relaunch_or_give_up(f"crash rc={rc}")
            return "gave_up" if self.gave_up else "crash"
        if self._wedged():
            counters_lib.inc("serve.replica_wedges")
            self._event("wedge")
            rc = self._escalate()
            self.last_rc = rc
            self._bundle("replica wedge (stale heartbeat)")
            self._relaunch_or_give_up("wedge")
            return "gave_up" if self.gave_up else "wedge"
        return None

    def run(self, poll_interval_s: float = 0.5,
            max_polls: Optional[int] = None) -> int:
        """Supervise until a clean exit or an exhausted budget; returns
        the final exit code (0 for clean, the last rc otherwise).
        ``max_polls`` bounds the loop for tests/drills."""
        self.start()
        polls = 0
        while not self.done and not self.gave_up:
            if max_polls is not None and polls >= max_polls:
                break
            self.poll_once()
            polls += 1
            if not self.done and not self.gave_up:
                self._sleep(poll_interval_s)
        return 0 if self.done else (self.last_rc or 1)
