"""The inference path — compiled forward steps, continuous batching, and
checkpoint → serving-weights loading (``docs/serving.md``).

Design constraints, in order:

1. **Zero steady-state retraces.** Requests arrive in arbitrary counts;
   the batcher pads every assembled batch up to a **power-of-two bucket**
   (``1, 2, 4, …, max_batch``), so the jitted forward only ever sees
   ``log2(max_batch)+1`` distinct shapes — all compiled at
   :meth:`ServingEngine.warmup`. The proof is not a comment: the engine
   wraps its jitted step in the existing
   :class:`~tpu_dist.obs.costmodel.CompileWatcher`; after warmup is
   baselined, ANY executable-cache growth is a mid-serve retrace — a
   counted, warned, alertable event (the ``serve_retrace`` SLO rule).
2. **Latency is attributed, not hidden.** Every request's life is split
   into the ``slo.PHASES`` (queue_wait / batch_assembly / dispatch /
   device / fetch) on the engine's injectable clock, feeding the
   streaming histograms and the span recorder. Batching helps
   throughput by ADDING queue wait — the split is what makes that
   trade-off visible per request.
3. **Same chips, same checkpoints.** Serving weights load through the
   existing restore ladder (newest→oldest, CRC verify, quarantine
   on corruption) with the elastic
   :class:`~tpu_dist.elastic.remap.Remapper` — a checkpoint written at
   ANY training dp extent restores onto the 1-process serving layout
   (ZeRO-1 flat optimizer vectors crop bit-exactly; serving then drops
   the optimizer state anyway). Optional int8 weight quantization
   reuses the per-chunk-scale machinery of ``comm/quantize.py``:
   weights live as int8 + f32 scales (≈4× less HBM) and dequantize
   inside the compiled step.

The jaxpr-audit rule TD114 pins the cost contract: the traced forward
step is byte-identical with the whole telemetry/SLO kit armed vs bare.
"""

from __future__ import annotations

import collections
import re
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from tpu_dist.obs import costmodel as costmodel_lib
from tpu_dist.obs import counters as counters_lib
from tpu_dist.obs import spans as spans_lib
from tpu_dist.serve import slo as slo_lib


def batch_buckets(max_batch: int) -> Tuple[int, ...]:
    """The power-of-two bucket ladder: ``(1, 2, 4, ..., max_batch)``.
    ``max_batch`` must itself be a power of two — a ragged top bucket
    would silently re-introduce a retraceable shape."""
    if max_batch < 1 or max_batch & (max_batch - 1):
        raise ValueError(
            f"max_batch must be a power of two (the bucket ladder), "
            f"got {max_batch}"
        )
    out = []
    b = 1
    while b <= max_batch:
        out.append(b)
        b *= 2
    return tuple(out)


def bucket_for(n: int, buckets: Tuple[int, ...]) -> int:
    """Smallest bucket holding ``n`` requests (callers cap ``n`` at
    ``max_batch`` first)."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"batch of {n} exceeds the top bucket {buckets[-1]}")


class Request:
    """One in-flight inference request. ``arrival_s`` is on the engine's
    clock (injectable — the drill replays recorded offsets); phase
    timestamps are filled in by the pump."""

    __slots__ = (
        "id", "payload", "arrival_s", "result", "ok",
        "total_s", "ttfb_s", "phase_s",
    )

    def __init__(self, id, payload: np.ndarray, arrival_s: float):
        self.id = id
        self.payload = payload
        self.arrival_s = arrival_s
        self.result: Optional[np.ndarray] = None
        self.ok = False
        self.total_s: Optional[float] = None
        self.ttfb_s: Optional[float] = None
        self.phase_s: Dict[str, float] = {}


# -- int8 weight quantization ------------------------------------------------


def quantize_weights(params, chunk: Optional[int] = None):
    """Per-leaf int8 quantization of a parameter pytree: each leaf is
    raveled and quantized per-chunk (``comm/quantize.py`` — one f32
    scale per ``chunk`` int8 elements, deterministic round-to-nearest:
    serving must be reproducible, so no stochastic rounding). Returns
    ``(qtree, shapes)``: a pytree of ``{"q": int8 (m,), "scale": f32
    (k,)}`` leaves — ~1 byte/elem at rest instead of 4 — and a matching
    tree of the original leaf shapes. The shapes stay a HOST-side
    static closure (:func:`dequantize_weights` takes them separately):
    folding them into the traced tree would turn every dimension into a
    traced value and break the reshape inside jit."""
    import jax
    import jax.numpy as jnp

    from tpu_dist.comm import quantize as q_lib

    chunk = chunk or q_lib.DEFAULT_CHUNK
    is_arr = lambda x: not isinstance(x, (dict, list, tuple))  # noqa: E731

    def one(leaf):
        arr = jnp.asarray(leaf, jnp.float32).ravel()
        q, scales = q_lib.quantize_int8(arr, chunk=chunk, key=None)
        return {"q": q, "scale": scales}

    qtree = jax.tree_util.tree_map(one, params, is_leaf=is_arr)
    shapes = jax.tree_util.tree_map(
        lambda leaf: tuple(int(d) for d in np.shape(leaf)),
        params, is_leaf=is_arr,
    )
    return qtree, shapes


def dequantize_weights(qparams, shapes, chunk: Optional[int] = None):
    """Inverse of :func:`quantize_weights` — runs INSIDE the jitted
    forward (the dequantize is compiled into the step; XLA fuses it into
    the consumers, and the at-rest copy stays int8). ``shapes`` is the
    static shape tree from :func:`quantize_weights`."""
    import jax
    import jax.numpy as jnp

    from tpu_dist.comm import quantize as q_lib

    chunk = chunk or q_lib.DEFAULT_CHUNK

    def is_q(x):
        return isinstance(x, dict) and set(x) == {"q", "scale"}

    def one(leaf, shape):
        out = q_lib.dequantize_int8(leaf["q"], leaf["scale"], chunk=chunk)
        return jnp.reshape(out, shape)

    return jax.tree_util.tree_map(one, qparams, shapes, is_leaf=is_q)


# -- checkpoint → serving weights --------------------------------------------

_KEY_SEG = re.compile(r"\['([^']*)'\]|\[(\d+)\]")


def _tree_from_keys(entries: Dict[str, np.ndarray]):
    """Rebuild a nested dict/list pytree from ``jax.tree_util.keystr``
    keys (``['a'][0]['b']``) → template leaves. Returns None when a key
    uses a construct this parser does not cover (attr paths) — the
    caller then skips mirroring that subtree."""
    root: dict = {}
    for key, leaf in entries.items():
        segs = []
        pos = 0
        for m in _KEY_SEG.finditer(key):
            if m.start() != pos:
                return None
            segs.append(m.group(1) if m.group(1) is not None else int(m.group(2)))
            pos = m.end()
        if pos != len(key) or not segs:
            return None
        node = root
        for i, seg in enumerate(segs):
            last = i == len(segs) - 1
            node = node.setdefault(seg, leaf if last else {})

    def listify(node):
        if not isinstance(node, dict):
            return node
        out = {k: listify(v) for k, v in node.items()}
        if out and all(isinstance(k, int) for k in out):
            return [out[i] for i in sorted(out)]
        return out

    return listify(root)


def load_serving_state(
    ckpt: str,
    model,
    *,
    verify: bool = True,
    key_seed: int = 0,
) -> dict:
    """Checkpoint → serving weights, through the existing restore ladder.

    ``ckpt`` is a plain-format checkpoint file or a ``--ckpt_dir``
    (walked newest→oldest with the trainer's quarantine discipline: a
    CRC-failing candidate is moved to ``*.corrupt`` and the next older
    one tried). ``model`` is an ``nn`` model def (``init``/``apply``).

    Mesh-shape portability: the template's optimizer subtree MIRRORS the
    checkpoint's, with ZeRO-1 flat vectors re-laid at the 1-process
    serving extent — so the restore runs through the elastic
    :class:`~tpu_dist.elastic.remap.Remapper` exactly like an elastic
    resume (``docs/resilience.md``), and a checkpoint written at dp=8
    loads bit-exactly. Serving then keeps params/bn/step ONLY; the
    remapped optimizer state is dropped on the floor (it proved the
    layout round-trips; inference has no use for momentum).

    Returns ``{"params", "bn_state", "step", "epoch", "meta", "path",
    "remapped"}`` (host numpy trees — the engine places them).
    Raises when nothing in ``ckpt`` is usable."""
    import os

    import jax

    from tpu_dist import ckpt as ckpt_lib
    from tpu_dist.comm.quantize import padded_len
    from tpu_dist.elastic import remap as remap_lib
    from tpu_dist.train.state import TrainState

    params, bn_state = model.init(jax.random.PRNGKey(key_seed))
    L = remap_lib.params_len(params)

    if os.path.isdir(ckpt):
        candidates = ckpt_lib.all_checkpoints(ckpt)
        if not candidates:
            if ckpt_lib.latest_sharded_checkpoint(ckpt):
                raise ValueError(
                    f"{ckpt} holds sharded-format checkpoints; serving "
                    "loads the plain format — write one with the plain "
                    "saver (--sharded_ckpt off) or convert offline"
                )
            raise FileNotFoundError(f"no checkpoints in {ckpt}")
    else:
        candidates = [(ckpt, -1)]

    last_err: Optional[Exception] = None
    for path, epoch in candidates:
        try:
            meta = ckpt_lib.read_meta(path)
            with np.load(path) as z:
                opt_entries = {
                    k[len("['opt_state']"):]: z[k]
                    for k in z.files
                    if k.startswith("['opt_state']")
                }
        except (ckpt_lib.CheckpointCorruptError,) + ckpt_lib.CKPT_READ_ERRORS as e:
            last_err = e
            if len(candidates) > 1:
                ckpt_lib.quarantine(path)
                continue
            raise
        el = (meta or {}).get("elastic") or {}
        n_old = el.get("dp")
        # mirror the checkpoint's optimizer subtree in the template, with
        # dp-extent-dependent flat vectors RE-LAID at the serving extent
        # (n=1): the restore then runs through the Remapper like any
        # elastic resume, and its zero1_flat crop is the bit-exactness
        # proof the round-trip test pins
        opt_tpl = None
        if opt_entries:
            mirrored = {}
            for k, arr in opt_entries.items():
                if (
                    arr.ndim == 1
                    and isinstance(n_old, int) and n_old > 0
                    and arr.size == padded_len(L, n_old)
                ):
                    mirrored[k] = np.zeros((padded_len(L, 1),), arr.dtype)
                else:
                    mirrored[k] = np.zeros(arr.shape, arr.dtype)
            if "" in mirrored:  # the whole opt_state is ONE flat leaf
                opt_tpl = mirrored[""] if len(mirrored) == 1 else None
            else:
                opt_tpl = _tree_from_keys(mirrored)
        template = TrainState(
            params=params,
            bn_state=bn_state,
            # an unparseable/absent opt subtree degrades to (): restore
            # then ignores the checkpoint's opt entries (zero template
            # leaves to fill) — serving only needs params/bn anyway
            opt_state=opt_tpl if opt_tpl is not None else (),
            step=np.zeros((), np.int32),
        )
        remapper = remap_lib.make_remapper(template, meta, 1)
        try:
            with spans_lib.span("serve/load_weights", file=os.path.basename(path)):
                restored = ckpt_lib.restore(
                    path, template, verify=verify, remap=remapper
                )
        except (ckpt_lib.CheckpointCorruptError,) + ckpt_lib.CKPT_READ_ERRORS as e:
            last_err = e
            if len(candidates) > 1:
                ckpt_lib.quarantine(path)
                continue
            raise
        counters_lib.inc("serve.weights_loaded")
        if remapper.used:
            counters_lib.inc("serve.weights_remapped")
        return {
            "params": restored.params,
            "bn_state": restored.bn_state,
            "step": int(np.asarray(restored.step)),
            "epoch": meta.get("epoch", epoch),
            "meta": meta,
            "path": path,
            "remapped": list(remapper.used),
        }
    raise ValueError(
        f"every checkpoint candidate in {ckpt} was unreadable/corrupt "
        f"(last error: {last_err})"
    )


# -- the engine --------------------------------------------------------------


class ServingEngine:
    """Continuous-batching inference over one jit-compiled forward step.

    Single-threaded by design: callers :meth:`submit` requests (from a
    socket loop, a replayed trace, a bench) and drive :meth:`pump`,
    which assembles the longest-waiting requests into one bucket-padded
    batch, dispatches the compiled step, and completes them with their
    phase-split latencies recorded. :meth:`record_window` closes an
    observation window: scalars → registry gauges + ``serve`` history
    record (schema v10), SLO rules evaluated, exporter exposition
    (histogram families included) refreshed.

    ``clock`` is any ``() -> float`` monotonic source; the drill passes
    a manual clock so the whole replay — queue waits included — is
    deterministic. With a non-default clock the span timestamps live on
    that clock too (only meaningful for offline analysis)."""

    def __init__(
        self,
        model,
        params,
        bn_state,
        *,
        max_batch: int = 8,
        quantize: bool = False,
        deadline_s: Optional[float] = None,
        slo_rules: Optional[list] = None,
        history=None,
        exporter=None,
        clock: Optional[Callable[[], float]] = None,
        heartbeat_file: Optional[str] = None,
        rank: int = 0,
        max_queue: Optional[int] = None,
    ):
        import jax
        import jax.numpy as jnp

        self.model = model
        self.buckets = batch_buckets(max_batch)
        self.max_batch = max_batch
        self._clock = clock or time.perf_counter
        self._queue: collections.deque = collections.deque()
        self.stats = slo_lib.ServeStats(deadline_s=deadline_s)
        # liveness: the pump loop beats the SAME per-rank heartbeat file
        # discipline the trainer uses (obs/heartbeat.py per_rank_path),
        # so the launcher watchdog, obs pod and the fleet scheduler's
        # read_signals cover serving replicas instead of alive=None
        self._heartbeat = None
        if heartbeat_file:
            from tpu_dist.obs import heartbeat as heartbeat_lib

            self._heartbeat = heartbeat_lib.Heartbeat(
                heartbeat_lib.per_rank_path(heartbeat_file, rank)
            )
        self._pumps = 0
        # admission control: while shedding (the chip-vacate window) or
        # past the queue cap, submit() refuses instead of queueing —
        # graceful degradation beats a queue explosion
        self.max_queue = max_queue
        self._shedding = False
        self._shed_reason = ""
        self.history = history
        self.exporter = exporter
        self._slo = (
            slo_lib.make_slo_engine(slo_rules) if slo_rules else None
        )
        self._seq = 0
        self._window_start = self._clock()
        self._window_completed_at = 0  # stats.completed at window open
        self._retraces_at_window = counters_lib.get("compile.retraces")
        self.quantized = bool(quantize)
        if quantize:
            qtree, qshapes = quantize_weights(params)
            self.params = jax.device_put(qtree)
            self._qshapes = qshapes  # static closure, never traced

            def forward(p, s, x):
                logits, _ = model.apply(
                    dequantize_weights(p, qshapes), s, x, train=False
                )
                return logits
        else:
            self.params = jax.device_put(
                jax.tree_util.tree_map(jnp.asarray, params)
            )

            def forward(p, s, x):
                logits, _ = model.apply(p, s, x, train=False)
                return logits

        self.bn_state = jax.device_put(
            jax.tree_util.tree_map(jnp.asarray, bn_state)
        )
        # donate nothing: weights are long-lived serving state reused by
        # every batch (tpu-dist: ignore[TD003] applies to TRAIN steps)
        self._forward = jax.jit(forward)
        self.watcher = costmodel_lib.CompileWatcher(
            self._forward, name="serving forward step"
        )
        counters_lib.set_gauge("serve.max_batch", max_batch)
        counters_lib.set_gauge(
            "serve.quantized", "int8" if quantize else "none"
        )

    # -- lifecycle ----------------------------------------------------------

    def warmup(self, sample_shape: Tuple[int, ...], dtype="float32") -> int:
        """Compile every bucket shape up front (zeros through the jitted
        step, blocked) and BASELINE the compile watcher: these compiles
        are expected; anything after is a mid-serve retrace. Returns the
        number of executables compiled. ``sample_shape`` is ONE
        request's payload shape (H, W, C).

        The warmup batches are HOST numpy, exactly like the pump's
        assembled batches — a committed device array here would warm a
        different jit-cache signature and every first real batch per
        bucket would retrace anyway."""
        t0 = self._clock()
        for b in self.buckets:
            x = np.zeros((b,) + tuple(sample_shape), dtype)
            self._forward(self.params, self.bn_state, x).block_until_ready()
        self.watcher.baseline()
        dur = self._clock() - t0
        spans_lib.add_event("serve/warmup", t0, dur, buckets=len(self.buckets))
        counters_lib.set_gauge("serve.warmup_s", round(dur, 3))
        counters_lib.inc("serve.warmup_compiles", len(self.buckets))
        return len(self.buckets)

    # -- request flow -------------------------------------------------------

    def set_shedding(self, on: bool, reason: str = "") -> None:
        """Toggle load-shedding admission: while on, :meth:`submit`
        refuses new requests (``req.ok`` False, ``serve.shed`` counted)
        and the pump keeps draining what was already admitted. The
        vacate window arms this — a replica set about to lose (or in
        the middle of re-acquiring) chips degrades gracefully instead
        of exploding its queue."""
        self._shedding = bool(on)
        self._shed_reason = reason if on else ""
        counters_lib.set_gauge("serve.shedding", 1 if on else 0)

    @property
    def shedding(self) -> bool:
        return self._shedding

    def submit(self, payload: np.ndarray, *, id=None,
               arrival_s: Optional[float] = None) -> Request:
        """Enqueue one request. ``arrival_s`` overrides the clock reading
        (trace replay); ``payload`` is one sample (no batch dim).

        Admission control: while shedding is on, or the queue sits at
        ``max_queue``, the request is REFUSED — returned immediately
        with ``ok`` False and no result, counted as ``serve.shed``,
        never entering the queue or the latency histograms (the p99
        describes admitted work; refusals are their own ledger)."""
        self._seq += 1
        req = Request(
            id if id is not None else self._seq,
            np.asarray(payload),
            self._clock() if arrival_s is None else arrival_s,
        )
        if self._shedding or (
            self.max_queue is not None and len(self._queue) >= self.max_queue
        ):
            self.stats.on_shed(len(self._queue))
            counters_lib.inc("serve.shed")
            return req
        self._queue.append(req)
        self.stats.on_submit(len(self._queue))
        counters_lib.inc("serve.requests")
        return req

    def queue_depth(self) -> int:
        return len(self._queue)

    def pump(self) -> List[Request]:
        """Assemble and run ONE batch from the queue head (empty queue →
        no-op, but the heartbeat still beats: an idle replica is alive).
        Returns the completed requests with results and phase latencies
        filled in."""
        self._pumps += 1
        if self._heartbeat is not None:
            self._heartbeat.beat(step=self._pumps, phase="serve")
        if not self._queue:
            return []
        t_assemble = self._clock()
        take = min(len(self._queue), self.max_batch)
        reqs = [self._queue.popleft() for _ in range(take)]
        bucket = bucket_for(take, self.buckets)
        batch = np.zeros((bucket,) + reqs[0].payload.shape,
                         reqs[0].payload.dtype)
        for i, r in enumerate(reqs):
            batch[i] = r.payload
        self.stats.on_batch(take, bucket)
        self.stats.set_queue_depth(len(self._queue))
        counters_lib.inc("serve.batches")
        counters_lib.inc("serve.batch_requests", take)

        t_dispatch = self._clock()
        out = self._forward(self.params, self.bn_state, batch)
        t_dispatched = self._clock()
        out.block_until_ready()
        t_device = self._clock()
        logits = np.asarray(out)
        t_fetch = self._clock()

        if self.watcher.observe(context="mid-serve (batch shape drift?)"):
            # the watcher already counted + warned; stamp the serving-
            # local event so the history/drill can pin WHICH batch
            counters_lib.inc("serve.retraces")
            if self.history is not None:
                self.history.log(
                    "serve", event="retrace", bucket=bucket, n_real=take,
                )

        # batch-grain spans (host timeline; Perfetto-ready when armed)
        spans_lib.add_event("serve/batch_assembly", t_assemble,
                            t_dispatch - t_assemble, n=take, bucket=bucket)
        spans_lib.add_event("serve/dispatch", t_dispatch,
                            t_dispatched - t_dispatch)
        spans_lib.add_event("serve/device", t_dispatched,
                            t_device - t_dispatched)
        spans_lib.add_event("serve/fetch", t_device, t_fetch - t_device)

        for i, r in enumerate(reqs):
            r.result = logits[i]
            r.ok = True
            # a future-dated arrival (a replay that did not advance its
            # clock first, or a frontend stamping arrivals from another
            # clock origin) clamps to the assembly instant CONSISTENTLY:
            # clamping only total/queue_wait would leave the positive
            # batch phases summing past the total and break the
            # phase-sums-≤-total invariant on a healthy engine
            arrival = min(r.arrival_s, t_assemble)
            r.phase_s = {
                "queue_wait": t_assemble - arrival,
                "batch_assembly": t_dispatch - t_assemble,
                "dispatch": t_dispatched - t_dispatch,
                "device": t_device - t_dispatched,
                "fetch": t_fetch - t_device,
            }
            r.total_s = t_fetch - arrival
            # TTFB: arrival → the device accepted the work (the dispatch
            # returned and the result future exists) — the serving
            # analogue of first-byte-queued, before the device/fetch tail
            r.ttfb_s = t_dispatched - arrival
            self.stats.on_request_done(r.total_s, r.ttfb_s, r.phase_s)
        counters_lib.inc("serve.completed", take)
        return reqs

    def drain(self, max_pumps: int = 10_000) -> List[Request]:
        """Pump until the queue empties; returns everything completed."""
        done: List[Request] = []
        for _ in range(max_pumps):
            if not self._queue:
                break
            done.extend(self.pump())
        return done

    def sweep_heartbeat(self) -> None:
        """Remove the replica's heartbeat file — the clean-exit signal
        (an ABSENT beat reads as a clean exit; a stale one as a wedge).
        The replica entrypoint calls this on the way out of a graceful
        SIGTERM drain; a SIGKILL leaves the file behind, which is
        exactly what lets the supervisor tell the two apart."""
        if self._heartbeat is not None:
            self._heartbeat.sweep()

    # -- observation windows -------------------------------------------------

    def record_window(self) -> Dict[str, float]:
        """Close one observation window: compute the ``serve.*`` scalars
        (requests/s over THIS window), publish them as registry gauges,
        evaluate the SLO rules, append a ``serve`` history record
        (schema v10), and refresh the exporter's exposition — histogram
        families included. Returns the scalar window."""
        now = self._clock()
        window_s = max(now - self._window_start, 1e-9)
        completed = self.stats.completed - self._window_completed_at
        scalars = self.stats.scalars(
            window_s=window_s, completed_in_window=completed
        )
        self.stats.publish(scalars)
        retraces = counters_lib.get("compile.retraces") - self._retraces_at_window
        fired = []
        if self._slo is not None:
            window = dict(scalars)
            window.update({
                k: v for k, v in counters_lib.snapshot().items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)
            })
            fired = self._slo.observe(window)
            for alert in fired:
                counters_lib.inc("serve.slo_alerts")
                if self.history is not None:
                    self.history.log("alert", **alert)
        if self.history is not None:
            rec = {
                k.split("serve.", 1)[1]: v for k, v in scalars.items()
            }
            rec["window_s"] = round(window_s, 6)
            if retraces:
                rec["retraces"] = retraces
            rec["phase_s"] = {
                p: round(h.sum, 6) for p, h in self.stats.phases.items()
            }
            rec["latency_hist"] = self.stats.total.to_dict()
            self.history.log("serve", **rec)
        if self.exporter is not None:
            labeled = (
                {"alert_active": self._slo.active()}
                if self._slo is not None else None
            )
            self.exporter.update(
                counters_lib.snapshot(), labeled,
                histograms=self.stats.histogram_families(), force=True,
            )
        self._window_start = now
        self._window_completed_at = self.stats.completed
        self._retraces_at_window = counters_lib.get("compile.retraces")
        scalars["_fired"] = len(fired)
        return scalars
